#!/usr/bin/env python
"""Compare our per-config wait.txt scalars against the reference's
shipped ground truth, per Metropolis base.

Usage:
  python replication/compare_waits.py \
      --ours replication/sec11 --ref /root/reference/New_plots/sec11
  python replication/compare_waits.py \
      --ours replication/frank --ref /root/reference/plots/FRANK

Prints a markdown table: per base B, cell count compared, our mean
Σwaits, the reference mean, and the min/max per-cell ratio (ours/ref on
the SAME cell tag). Single 100k-step runs in slow-mixing regimes are
mode-dominated (REPLICATION.md), so per-cell ratios there reflect mode
occupancy, not error.
"""

import argparse
import os
import re
from collections import defaultdict

import numpy as np


def read_waits(d):
    out = {}
    for f in os.listdir(d):
        if f.endswith("wait.txt"):
            with open(os.path.join(d, f)) as fh:
                out[f[:-len("wait.txt")]] = float(fh.read().strip())
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ours", required=True)
    ap.add_argument("--ref", required=True)
    args = ap.parse_args()
    ours = read_waits(args.ours)
    ref = read_waits(args.ref)
    common = sorted(set(ours) & set(ref))
    missing = sorted(set(ref) - set(ours))
    by_base = defaultdict(list)
    for tag in common:
        m = re.match(r"(\d)B(\d+)P(\d+)", tag)
        if m is None:
            print(f"(skipping unrecognized tag {tag!r})")
            continue
        by_base[int(m.group(2))].append((tag, ours[tag], ref[tag]))

    print(f"{len(common)} cells compared "
          f"({len(missing)} reference cells not yet run)")
    print("| B | cells | ours mean | ref mean | ratio min | ratio max |")
    print("|---|---|---|---|---|---|")
    for b in sorted(by_base):
        rows = by_base[b]
        o = np.array([r[1] for r in rows])
        rf = np.array([r[2] for r in rows])
        rat = o / rf
        print(f"| {b} | {len(rows)} | {o.mean():.4g} | {rf.mean():.4g} "
              f"| {rat.min():.3f} | {rat.max():.3f} |")


if __name__ == "__main__":
    main()
