#!/usr/bin/env python
"""Regenerate the plain-vs-tempered mixing comparison (VERDICT r4 weak-4).

REPLICATION.md "Tempering the B333 bimodal regime" claims the scientific
payoff of BASELINE config 4: on the slow-mixing bimodal FRANK B333
alignment-0 P10 cell, a plain chain makes ~0.875 well crossings per chain
in 100k steps (all of them the one-way initial relaxation, zero completed
round trips), while the TEMPER_BETAS replica-exchange ladder's
reconstructed cold-rung trajectories keep crossing (mean 3.5, max 7).
This script regenerates that comparison end-to-end so the claim stays
continuously true; tests/test_tempered.py runs it at a reduced budget
under --runslow.

Usage:
  python replication/compare_tempering.py                # full 100k budget
  python replication/compare_tempering.py --steps 30001 --ladders 8

Writes JSON (per-chain crossings/round trips both arms, swap rates) to
--out and prints a summary table. Wells follow REPLICATION.md: low
|cut| < 40, high |cut| > 60.
"""

import argparse
import json
import os
import sys

import numpy as np

# run as a script: the package lives at the repo root, one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_comparison(steps=100001, plain_chains=16, ladders=8,
                   swap_every=50, seed=0, record_every=1):
    import flipcomplexityempirical_tpu as fce
    from flipcomplexityempirical_tpu.experiments.config import TEMPER_BETAS
    from flipcomplexityempirical_tpu.experiments.driver import (
        build_graph_and_plan, spec_for)
    from flipcomplexityempirical_tpu.experiments.config import (
        ExperimentConfig)
    from flipcomplexityempirical_tpu.sampling import (
        init_tempered, run_tempered, per_rung_history)
    from flipcomplexityempirical_tpu.stats import (
        round_trips, well_crossings)

    lo, hi = 40.0, 60.0
    cfg = ExperimentConfig(family="temper", alignment=0, base=1 / .3,
                           pop_tol=0.1, betas=TEMPER_BETAS,
                           swap_every=swap_every, total_steps=steps,
                           n_chains=ladders, seed=seed,
                           record_every=record_every)
    g, plan, _ = build_graph_and_plan(cfg)
    spec = spec_for(cfg)

    # plain arm: independent chains at beta = 1 (the physical target)
    dg, st, params = fce.init_batch(
        g, plan, n_chains=plain_chains, seed=seed, spec=spec,
        base=cfg.base, pop_tol=cfg.pop_tol)
    res_p = fce.run_chains(dg, spec, params, st, n_steps=steps,
                           record_history=True, record_every=record_every)
    cut_p = np.asarray(res_p.history["cut_count"], np.float64)

    # tempered arm: ladders * len(TEMPER_BETAS) chains, same per-chain
    # step budget; the physical observable is the reconstructed
    # cold-rung (beta = 1) trajectory of each ladder
    h, st_t, params_t = init_tempered(
        g, plan, betas=list(TEMPER_BETAS), n_ladders=ladders, seed=seed,
        spec=spec, base=cfg.base, pop_tol=cfg.pop_tol)
    res_t = run_tempered(h, spec, params_t, st_t, n_steps=steps,
                         betas=list(TEMPER_BETAS), n_ladders=ladders,
                         swap_every=swap_every, swap_seed=seed,
                         record_every=record_every)
    cut_c = per_rung_history(res_t, "cut_count")[0].astype(np.float64)

    return {
        "cell": "FRANK B333 alignment=0 P10",
        "wells": {"low_below": lo, "high_above": hi},
        "steps": steps,
        "swap_every": swap_every,
        "betas": list(map(float, TEMPER_BETAS)),
        "seed": seed,
        "plain": {
            "chains": plain_chains,
            "crossings": well_crossings(cut_p, lo, hi).tolist(),
            "round_trips": round_trips(cut_p, lo, hi).tolist(),
        },
        "tempered_cold_rung": {
            "ladders": ladders,
            "crossings": well_crossings(cut_c, lo, hi).tolist(),
            "round_trips": round_trips(cut_c, lo, hi).tolist(),
            "swap_rates": res_t.swap_rates().tolist(),
        },
    }


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=100001)
    ap.add_argument("--plain-chains", type=int, default=16)
    ap.add_argument("--ladders", type=int, default=8)
    ap.add_argument("--swap-every", type=int, default=50)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--record-every", type=int, default=1)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--out", default=None,
                    help="write the JSON record here (default: "
                         "replication/temper/compare_S<steps>.json)")
    args = ap.parse_args(argv)
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    rec = run_comparison(steps=args.steps, plain_chains=args.plain_chains,
                         ladders=args.ladders, swap_every=args.swap_every,
                         seed=args.seed, record_every=args.record_every)
    out = args.out or os.path.join(
        os.path.dirname(__file__), "temper",
        f"compare_S{args.steps}.json")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(rec, f, indent=1)

    p, t = rec["plain"], rec["tempered_cold_rung"]
    for name, arm in (("plain", p), ("tempered cold rung", t)):
        cr, rt = np.asarray(arm["crossings"]), np.asarray(arm["round_trips"])
        print(f"{name:>18}: crossings mean {cr.mean():.3f} max {cr.max()}"
              f" | completed round trips mean {rt.mean():.3f} "
              f"max {rt.max()} total {rt.sum()}")
    print(f"adjacent swap rates: "
          f"{' '.join(f'{r:.2f}' for r in t['swap_rates'])}")
    print(f"wrote {out}")
    better = (sum(t["round_trips"]) * p["chains"]
              > sum(p["round_trips"]) * t["ladders"])
    print("tempered mixes better (per-chain round trips): "
          + ("YES" if better else "NO"))
    return 0 if better else 1


if __name__ == "__main__":
    sys.exit(main())
