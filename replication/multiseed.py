#!/usr/bin/env python
"""Multi-seed replication of the slow-base cells (VERDICT r4 next-4).

The full-corpus tables (REPLICATION.md) run every reference cell ONCE; at
the slow bases (sec11 B263 = mu, B695 = mu^2, B1000; frank B333 — the
bimodal regime) single runs are mode-dominated and per-cell ratios are
wide, justified qualitatively by the reference's own per-base spread.
This script makes that quantitative: it runs ONE cell per slow base
(alignment 0, P50) at 15 seeds x 8 chains, records every per-chain wait
sum, and rank/KS-tests the seed distribution against the reference's
shipped per-base ``wait.txt`` scalars (15 cells/base sec11, 12 frank).
If the spread is mode occupancy (as claimed) the two samples are
exchangeable; a subtle ordered-phase acceptance bug would shift ours
detectably.

  python replication/multiseed.py run                   # sec11 cells
  python replication/multiseed.py run --family frank    # frank B333
  python replication/multiseed.py run --cells B1000     # one cell, merged
  python replication/multiseed.py analyze [--family ...]

Committed records: replication/seeds/multiseed_sec11.json and
multiseed_frank.json; tests/test_replication.py re-analyzes them (and
the reference corpora) on every --runslow run so the "consistent with
the reference spread" claim stays continuously checked.
"""

import argparse
import glob
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_SEEDS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "seeds")
MU = 2.63815853
SEEDS = list(range(1, 16))

FAMILIES = {
    "sec11": {
        "cells": {"B263": MU, "B400": 4.0, "B695": MU ** 2,
                  "B1000": 10.0},
        "ref_dir": "/root/reference/New_plots/sec11",
        "ref_cells": 15,  # 3 alignments x 5 pops
        "record": os.path.join(_SEEDS_DIR, "multiseed_sec11.json"),
        "gates": {},
    },
    "frank": {
        "cells": {"B333": 1 / 0.3},
        "ref_dir": "/root/reference/plots/FRANK",
        "ref_cells": 12,  # 3 alignments x 4 pops
        "record": os.path.join(_SEEDS_DIR, "multiseed_frank.json"),
        # B333 is BIMODAL (REPLICATION.md tempering section): seeds
        # legitimately land in either mode, so its seed-noise bound is
        # wider and its center bound reflects cross-mode variance of a
        # 15-sample mean
        "gates": {"B333": {"cv": 0.7, "mean": 0.35}},
    },
}

# back-compat aliases (tests and older docs import these)
RECORD = FAMILIES["sec11"]["record"]
CELLS = FAMILIES["sec11"]["cells"]
REF_DIR = FAMILIES["sec11"]["ref_dir"]


def run(record_path=None, seeds=SEEDS, steps=100_000, chains=8,
        scratch=None, family="sec11", cells=None):
    """Run the requested cells and MERGE them into the family record
    (existing cells under other names are preserved, so one cell can be
    added or regenerated without re-running the rest)."""
    from flipcomplexityempirical_tpu.experiments.config import (
        ExperimentConfig)
    from flipcomplexityempirical_tpu.experiments.driver import run_config

    fam = FAMILIES[family]
    record_path = record_path or fam["record"]
    if cells is not None:
        unknown = sorted(set(cells) - set(fam["cells"]))
        if unknown:
            raise SystemExit(
                f"unknown cell(s) {unknown} for family {family!r}; "
                f"known: {sorted(fam['cells'])}")
    todo = {k: v for k, v in fam["cells"].items()
            if cells is None or k in cells}
    scratch = scratch or os.path.join("/tmp", f"multiseed_{family}")
    rec = {"steps": steps, "chains": chains, "alignment": 0,
           "pop_tol": 0.5, "seeds": list(seeds), "cells": {}}
    if os.path.exists(record_path):
        with open(record_path) as f:
            old = json.load(f)
        if (old["steps"], old["chains"], old["seeds"]) == (
                steps, chains, list(seeds)):
            rec["cells"].update(old["cells"])
        elif set(old["cells"]) - set(todo):
            # a partial rerun at different settings would silently erase
            # the other cells' data — refuse; a FULL rerun may move the
            # settings (every cell is regenerated under the new ones)
            raise SystemExit(
                f"{record_path} holds cells {sorted(old['cells'])} at "
                f"(steps={old['steps']}, chains={old['chains']}, "
                f"{len(old['seeds'])} seeds); rerunning only "
                f"{sorted(todo)} at different settings would drop them. "
                "Rerun all cells, match the settings, or use --record.")
    for name, base in todo.items():
        per_seed = []
        for s in seeds:
            cfg = ExperimentConfig(family=family, alignment=0, base=base,
                                   pop_tol=0.5, seed=s, total_steps=steps,
                                   n_chains=chains)
            data = run_config(cfg, os.path.join(scratch, f"s{s}"))
            per_seed.append(np.asarray(data["waits_all"],
                                       np.float64).tolist())
            print(f"[multiseed] {family} {name} seed {s}: chain0 "
                  f"{per_seed[-1][0]:.4g} ({data['seconds']:.1f}s)",
                  flush=True)
        rec["cells"][name] = {"base": base, "waits_all": per_seed}
    os.makedirs(os.path.dirname(record_path), exist_ok=True)
    with open(record_path, "w") as f:
        json.dump(rec, f, indent=1)
    print(f"wrote {record_path}")
    return rec


def _ref_waits(base_tag, ref_dir=REF_DIR):
    vals = []
    for f in sorted(glob.glob(os.path.join(ref_dir,
                                           f"*{base_tag}P*wait.txt"))):
        with open(f) as fh:
            vals.append(float(fh.read().strip()))
    return np.asarray(vals, np.float64)


def ks_2sample(a, b):
    """Two-sample Kolmogorov-Smirnov: statistic + asymptotic p-value
    (scipy-free, Smirnov's formula — fine at these sample sizes)."""
    a, b = np.sort(a), np.sort(b)
    allv = np.concatenate([a, b])
    cdf_a = np.searchsorted(a, allv, side="right") / len(a)
    cdf_b = np.searchsorted(b, allv, side="right") / len(b)
    d = float(np.abs(cdf_a - cdf_b).max())
    en = np.sqrt(len(a) * len(b) / (len(a) + len(b)))
    t = (en + 0.12 + 0.11 / en) * d
    p = 2 * sum((-1) ** (k - 1) * np.exp(-2 * (k * t) ** 2)
                for k in range(1, 101))
    return d, float(min(max(p, 0.0), 1.0))


def analyze(record_path=None, ref_dir=None, family="sec11"):
    record_path = record_path or FAMILIES[family]["record"]
    with open(record_path) as f:
        rec = json.load(f)
    ref_dir = ref_dir or FAMILIES[family]["ref_dir"]
    results = {}
    for name, cell in rec["cells"].items():
        ref = _ref_waits(name, ref_dir)
        waits = np.asarray(cell["waits_all"], np.float64)  # (S, C)
        chain0 = waits[:, 0]
        d0, p0 = ks_2sample(chain0, ref)
        dall, pall = ks_2sample(waits.ravel(), ref)
        # rank of the reference median inside our seed distribution:
        # mode-occupancy exchangeability puts it well inside the body
        rank = float((chain0 < np.median(ref)).mean())
        results[name] = {
            "ref_cells": len(ref),
            "ref_mean": float(ref.mean()), "ref_min": float(ref.min()),
            "ref_max": float(ref.max()),
            "seed_chain0_mean": float(chain0.mean()),
            "seed_chain0_min": float(chain0.min()),
            "seed_chain0_max": float(chain0.max()),
            # mean agreement and seed noise: at B695 the seeds are TIGHT
            # (the reference's wide per-base spread there is config-
            # driven — pop tolerance — not run-to-run noise), so the
            # center is the sharper consistency statement than KS shape
            "mean_ratio": float(chain0.mean() / ref.mean()),
            "seed_cv": float(chain0.std(ddof=1) / chain0.mean()),
            "ks_chain0": {"D": d0, "p": p0},
            "ks_all_chains": {"D": dall, "p": pall},
            "ref_median_quantile_in_seeds": rank,
        }
    return results


def cell_consistent(c: dict, gate: dict | None = None) -> bool:
    """The single consistency gate (CLI and test share it): the KS test
    does not REJECT at 1%, the seed distribution is centered on the
    reference per-base mean, seed noise is bounded, and the reference
    median sits inside the body of the seed distribution. The committed
    records measure KS p = 0.31 (B263) / 0.0515 (B695) / 0.59 (B1000) /
    0.021 (B333); the shape differences at the ordered-phase bases are
    the tight-seeds-vs-config-spread effect described in analyze(), so
    the binding constraint is the center. ``gate`` widens the noise and
    center bounds for cells declared in FAMILIES[...]["gates"]
    (e.g. frank's bimodal B333)."""
    gate = gate or {}
    return (c["ks_chain0"]["p"] > 0.01
            and abs(c["mean_ratio"] - 1) < gate.get("mean", 0.15)
            and c["seed_cv"] < gate.get("cv", 0.25)
            and 0.05 < c["ref_median_quantile_in_seeds"] < 0.95)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("cmd", choices=["run", "analyze"])
    ap.add_argument("--family", choices=sorted(FAMILIES), default="sec11")
    ap.add_argument("--record", default=None)
    ap.add_argument("--cells", nargs="+", default=None,
                    help="subset of the family's cells to (re)run; "
                         "others are preserved in the record")
    ap.add_argument("--steps", type=int, default=100_000)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    record = args.record or FAMILIES[args.family]["record"]
    if args.cmd == "run":
        run(record, steps=args.steps, family=args.family,
            cells=args.cells)
    res = analyze(record, family=args.family)
    print(json.dumps(res, indent=1))
    gates = FAMILIES[args.family]["gates"]
    ok = all(cell_consistent(c, gates.get(name))
             for name, c in res.items())
    print("seed spread consistent with reference per-base spread "
          f"(KS p > 0.01, centered): {'YES' if ok else 'NO'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
