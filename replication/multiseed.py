#!/usr/bin/env python
"""Multi-seed replication of the slow-base sec11 cells (VERDICT r4 next-4).

The full-corpus table (REPLICATION.md) runs every reference cell ONCE; at
the slow bases (B263 = mu, B695 = mu^2) single runs are mode-dominated
and per-cell ratios span 0.58-1.27, justified qualitatively by the
reference's own 15-cell spread. This script makes that quantitative: it
runs ONE cell per slow base (alignment 0, P50) at 15 seeds x 8 chains,
records every per-chain wait sum, and rank/KS-tests the seed distribution
against the reference's 15 shipped per-base ``wait.txt`` scalars. If the
spread is mode occupancy (as claimed) the two samples are exchangeable;
a subtle ordered-phase acceptance bug would shift ours detectably.

  python replication/multiseed.py run       # ~6 min CPU; writes the JSON
  python replication/multiseed.py analyze   # KS/rank vs the reference

The committed record is replication/seeds/multiseed_sec11.json;
tests/test_replication.py re-analyzes it (and the reference corpus) on
every --runslow run so the "consistent with the reference spread" claim
stays continuously checked.
"""

import argparse
import glob
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RECORD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "seeds", "multiseed_sec11.json")
MU = 2.63815853
CELLS = {"B263": MU, "B695": MU ** 2}
SEEDS = list(range(1, 16))
REF_DIR = "/root/reference/New_plots/sec11"


def run(record_path=RECORD, seeds=SEEDS, steps=100_000, chains=8,
        scratch=None):
    from flipcomplexityempirical_tpu.experiments.config import (
        ExperimentConfig)
    from flipcomplexityempirical_tpu.experiments.driver import run_config

    scratch = scratch or os.path.join("/tmp", "multiseed_artifacts")
    rec = {"steps": steps, "chains": chains, "alignment": 0,
           "pop_tol": 0.5, "seeds": list(seeds), "cells": {}}
    for name, base in CELLS.items():
        per_seed = []
        for s in seeds:
            cfg = ExperimentConfig(family="sec11", alignment=0, base=base,
                                   pop_tol=0.5, seed=s, total_steps=steps,
                                   n_chains=chains)
            data = run_config(cfg, os.path.join(scratch, f"s{s}"))
            per_seed.append(np.asarray(data["waits_all"],
                                       np.float64).tolist())
            print(f"[multiseed] {name} seed {s}: chain0 "
                  f"{per_seed[-1][0]:.4g} ({data['seconds']:.1f}s)",
                  flush=True)
        rec["cells"][name] = {"base": base, "waits_all": per_seed}
    os.makedirs(os.path.dirname(record_path), exist_ok=True)
    with open(record_path, "w") as f:
        json.dump(rec, f, indent=1)
    print(f"wrote {record_path}")
    return rec


def _ref_waits(base_tag, ref_dir=REF_DIR):
    vals = []
    for f in sorted(glob.glob(os.path.join(ref_dir,
                                           f"*{base_tag}P*wait.txt"))):
        with open(f) as fh:
            vals.append(float(fh.read().strip()))
    return np.asarray(vals, np.float64)


def ks_2sample(a, b):
    """Two-sample Kolmogorov-Smirnov: statistic + asymptotic p-value
    (scipy-free, Smirnov's formula — fine at these sample sizes)."""
    a, b = np.sort(a), np.sort(b)
    allv = np.concatenate([a, b])
    cdf_a = np.searchsorted(a, allv, side="right") / len(a)
    cdf_b = np.searchsorted(b, allv, side="right") / len(b)
    d = float(np.abs(cdf_a - cdf_b).max())
    en = np.sqrt(len(a) * len(b) / (len(a) + len(b)))
    t = (en + 0.12 + 0.11 / en) * d
    p = 2 * sum((-1) ** (k - 1) * np.exp(-2 * (k * t) ** 2)
                for k in range(1, 101))
    return d, float(min(max(p, 0.0), 1.0))


def analyze(record_path=RECORD, ref_dir=None):
    with open(record_path) as f:
        rec = json.load(f)
    ref_dir = ref_dir or REF_DIR
    results = {}
    for name, cell in rec["cells"].items():
        ref = _ref_waits(name, ref_dir)
        waits = np.asarray(cell["waits_all"], np.float64)  # (S, C)
        chain0 = waits[:, 0]
        d0, p0 = ks_2sample(chain0, ref)
        dall, pall = ks_2sample(waits.ravel(), ref)
        # rank of the reference median inside our seed distribution:
        # mode-occupancy exchangeability puts it well inside the body
        rank = float((chain0 < np.median(ref)).mean())
        results[name] = {
            "ref_cells": len(ref),
            "ref_mean": float(ref.mean()), "ref_min": float(ref.min()),
            "ref_max": float(ref.max()),
            "seed_chain0_mean": float(chain0.mean()),
            "seed_chain0_min": float(chain0.min()),
            "seed_chain0_max": float(chain0.max()),
            # mean agreement and seed noise: at B695 the seeds are TIGHT
            # (the reference's wide per-base spread there is config-
            # driven — pop tolerance — not run-to-run noise), so the
            # center is the sharper consistency statement than KS shape
            "mean_ratio": float(chain0.mean() / ref.mean()),
            "seed_cv": float(chain0.std(ddof=1) / chain0.mean()),
            "ks_chain0": {"D": d0, "p": p0},
            "ks_all_chains": {"D": dall, "p": pall},
            "ref_median_quantile_in_seeds": rank,
        }
    return results


def cell_consistent(c: dict) -> bool:
    """The single consistency gate (CLI and test share it): the KS test
    does not REJECT at 1%, the seed distribution is centered on the
    reference per-base mean, seed noise is bounded, and the reference
    median sits inside the body of the seed distribution. The committed
    record measures KS p = 0.31 (B263) / 0.0515 (B695); the B695 shape
    difference is the tight-seeds-vs-config-spread effect described in
    analyze(), so the binding constraint is the center."""
    return (c["ks_chain0"]["p"] > 0.01
            and abs(c["mean_ratio"] - 1) < 0.15
            and c["seed_cv"] < 0.25
            and 0.05 < c["ref_median_quantile_in_seeds"] < 0.95)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("cmd", choices=["run", "analyze"])
    ap.add_argument("--record", default=RECORD)
    ap.add_argument("--steps", type=int, default=100_000)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    if args.cmd == "run":
        run(args.record, steps=args.steps)
    res = analyze(args.record)
    print(json.dumps(res, indent=1))
    ok = all(map(cell_consistent, res.values()))
    print("seed spread consistent with reference per-base spread "
          f"(KS p > 0.01, mean within 15%): {'YES' if ok else 'NO'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
