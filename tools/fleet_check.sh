#!/usr/bin/env bash
# Fleet CI gate (`make fleet-check`): one front-door server, two worker
# processes, eight tenants' catalog jobs, and a worker.sigkill chaos
# fault (ISSUE 17). The SIGKILLed worker's leased job must be reclaimed
# by the survivor (lease_expired -> lease_acquired reclaim) and every
# job must end DONE with an artifact; the fleet journal and all per-job
# run journals must replay with zero corruption; no job may execute
# under two simultaneous leases. Fairness gates on Jain's index over
# per-tenant completed service share (>= 0.8): in an 8-job one-shot
# burst, allocation is what weighted-fair admission controls — the
# wait-time fairness axis needs statistics and is gated at 500 tenants
# by tools/loadtest.py. ISSUE 18 adds the observability legs: a mid-run
# /v1/metrics + /v1/fleet scrape while the chaos is armed, the
# trace_export --fleet end-to-end trace-parenting gate over the shared
# $ROOT/events/ streams, and the SLO section of the merged report. The
# full matrix — claim races, lease aging, bit-identical SIGKILL resume
# — lives in tests/test_preemption.py and tests/test_fleet.py; this is
# the cross-process smoke.
#
#   tools/fleet_check.sh
#
# Exercised by tests/test_fleet.py, so tier-1 fails when the gate rots.
set -euo pipefail

cd "$(dirname "$0")/.."
PY="${PYTHON:-python}"
export JAX_PLATFORMS=cpu
# stable XLA cache shared with the other gate scripts: the server and
# both workers each pay the jax import either way, but repeat gate
# runs skip the cold XLA compile of the frank kernel
export JAX_COMPILATION_CACHE_DIR="${GRAFT_GATE_JAX_CACHE:-${TMPDIR:-/tmp}/graft-gate-jax-cache}"
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=1
mkdir -p "$JAX_COMPILATION_CACHE_DIR"
TD="$(mktemp -d)"
ROOT="$TD/fleet"
SERVER_PID=""
W1_PID=""
W2_PID=""
cleanup() {
    for pid in "$SERVER_PID" "$W1_PID" "$W2_PID"; do
        [ -n "$pid" ] && kill -9 "$pid" 2>/dev/null || true
    done
    rm -rf "$TD"
}
trap cleanup EXIT

# -- 1. server up (events default to the canonical $ROOT/events/) ------
"$PY" -m flipcomplexityempirical_tpu.service serve "$ROOT" \
    --ready-file "$ROOT/server.json" \
    --ttl 2 &
SERVER_PID=$!
for _ in $(seq 1 120); do
    [ -f "$ROOT/server.json" ] && break
    kill -0 "$SERVER_PID" 2>/dev/null || {
        echo "fleet-check: server died before binding" >&2; exit 1; }
    sleep 0.25
done
[ -f "$ROOT/server.json" ] || {
    echo "fleet-check: server never wrote its ready file" >&2; exit 1; }
URL="$("$PY" - "$ROOT/server.json" <<'PYEOF'
import json, sys
print(json.load(open(sys.argv[1]))["url"])
PYEOF
)"

# -- 2. eight tenants submit one cheap catalog job each ----------------
# tenant t0 goes through the real CLI; the rest batch through the client
"$PY" -m flipcomplexityempirical_tpu.service submit "$URL" \
    --workload frank --set total_steps=60 --set n_chains=2 \
    --set checkpoint_every=20 --set seed=3 --tenant t0 >/dev/null
"$PY" - "$URL" <<'PYEOF'
import sys
from flipcomplexityempirical_tpu.service import ServiceClient
url = sys.argv[1]
for i in range(1, 8):
    client = ServiceClient(url, tenant=f"t{i}")
    doc = client.submit(workload="frank",
                        overrides={"total_steps": 60, "n_chains": 2,
                                   "checkpoint_every": 20,
                                   "seed": 3 + 13 * i})
    assert doc["job_id"] == f"j{i:04d}", doc
PYEOF

# -- 3. two workers; w2 is armed to SIGKILL itself mid-run -------------
"$PY" -m flipcomplexityempirical_tpu.service worker "$ROOT" \
    --name w1 --ttl 2 --idle-timeout 8 --compile-cache "$ROOT/cc" &
W1_PID=$!
# plan sites below are pinned to resilience.faults.FAULT_SITES by
# graftlint G013 — a renamed site fails `make lint`, not silently here
"$PY" -m flipcomplexityempirical_tpu.service worker "$ROOT" \
    --name w2 --ttl 2 --idle-timeout 8 --compile-cache "$ROOT/cc" \
    --faults worker.sigkill:once@3 &
W2_PID=$!

# -- 3b. mid-run scrape: /v1/metrics + /v1/fleet serve LIVE collector
# state while both workers run and the sigkill chaos is armed (the
# read path is host-side file tailing only — G009 keeps device work
# off handler threads)
"$PY" - "$URL" <<'PYEOF'
import json
import sys
import urllib.request

url = sys.argv[1]
with urllib.request.urlopen(url + "/v1/metrics", timeout=10) as resp:
    assert resp.status == 200, resp.status
    ctype = resp.headers.get("Content-Type", "")
    assert ctype.startswith("text/plain"), ctype
    body = resp.read().decode("utf-8")
assert "# TYPE graft_fleet_jobs gauge" in body, body[:400]
with urllib.request.urlopen(url + "/v1/fleet", timeout=10) as resp:
    doc = json.loads(resp.read())
assert "workers" in doc and "stages" in doc and "queue_depth" in doc
print(f"fleet-check: mid-run scrape ok "
      f"({len(body.splitlines())} metric lines, "
      f"stages={doc['stages']})")
PYEOF

RC_W2=0
wait "$W2_PID" || RC_W2=$?
W2_PID=""
[ "$RC_W2" -eq 137 ] || {
    echo "fleet-check: w2 exited $RC_W2, expected SIGKILL (137)" >&2
    exit 1; }
RC_W1=0
wait "$W1_PID" || RC_W1=$?
W1_PID=""
[ "$RC_W1" -eq 0 ] || {
    echo "fleet-check: surviving worker exited $RC_W1" >&2; exit 1; }

# -- 4. the CLI status view agrees, then drain (serving ends with 3) ---
"$PY" -m flipcomplexityempirical_tpu.service status "$URL" \
    > "$TD/fleet-status.json"
"$PY" - "$TD/fleet-status.json" <<'PYEOF'
import json, sys
doc = json.load(open(sys.argv[1]))
assert doc["counts"] == {"done": 8}, doc["counts"]
assert not doc["draining"], doc
PYEOF
"$PY" - "$URL" <<'PYEOF'
import sys
from flipcomplexityempirical_tpu.service import ServiceClient
print(ServiceClient(sys.argv[1]).drain())
PYEOF
RC_SRV=0
wait "$SERVER_PID" || RC_SRV=$?
SERVER_PID=""
[ "$RC_SRV" -eq 3 ] || {
    echo "fleet-check: server exited $RC_SRV, expected 3" >&2; exit 1; }

# -- 5. assertions over the shared root + event streams ----------------
"$PY" - "$ROOT" "$TD" <<'PYEOF'
import json
import os
import sys
from collections import Counter

from flipcomplexityempirical_tpu.service import journal as jnl

root, td = sys.argv[1], sys.argv[2]
N = 8

# every job DONE, with its artifact and queue-to-start anchor
statuses = {}
for name in os.listdir(os.path.join(root, "status")):
    doc = json.load(open(os.path.join(root, "status", name)))
    statuses[doc["job_id"]] = doc
assert len(statuses) == N, sorted(statuses)
bad = {j: d["status"] for j, d in statuses.items()
       if d["status"] != "done"}
assert not bad, bad
arts = {}
for jid in statuses:
    art = json.load(open(os.path.join(root, "artifacts",
                                      f"{jid}.json")))
    assert art.get("result_sha256") or art.get("recovered"), art
    arts[jid] = art
assert len(os.listdir(os.path.join(root, "started"))) == N

# zero journal corruption: the fleet WAL and every run journal replay
records, truncated = jnl.Journal.read(
    os.path.join(root, "journal.jsonl"))
assert not truncated, "fleet journal torn"
kinds = Counter(r["kind"] for r in records)
assert kinds["job_submitted"] == N, dict(kinds)
assert kinds["job_admitted"] == N, dict(kinds)
assert kinds["service_draining"] == 1, dict(kinds)
for jid in statuses:
    rj, torn = jnl.Journal.read(
        os.path.join(root, "run", jid, "journal.jsonl"))
    assert not torn, f"run journal torn for {jid}"
    state = jnl.replay(rj)
    assert len(state) == 1, (jid, sorted(state))
    (st,) = state.values()
    assert st["status"] == "done", (jid, st)

# the chaos story in the event streams: w2's lease went stale, the
# survivor broke it (lease_expired) and reclaimed; and no job was ever
# freshly claimed twice (double execution)
events = []
for name in ("server.jsonl", "w1.jsonl", "w2.jsonl"):
    for line in open(os.path.join(root, "events", name)):
        line = line.strip()
        if not line:
            continue
        try:
            events.append(json.loads(line))
        except ValueError:
            pass    # w2's SIGKILL may tear its final line mid-write
expired = [e for e in events if e["event"] == "lease_expired"]
assert expired, "no lease_expired: the SIGKILL chaos leg never fired"
assert all(e["by"] == "w1" for e in expired), expired
fresh = Counter(e["job_id"] for e in events
                if e["event"] == "lease_acquired"
                and not e.get("reclaim"))
assert all(v == 1 for v in fresh.values()), dict(fresh)
reclaims = [e for e in events if e["event"] == "lease_acquired"
            and e.get("reclaim")]
assert reclaims, "stale lease was never reclaimed"
exits = {e["worker"]: e for e in events
         if e["event"] == "worker_exited"}
assert "w1" in exits and "w2" not in exits, sorted(exits)

# fairness: Jain over per-tenant completed service share
per_tenant = Counter(d["tenant"] for d in statuses.values())
assert len(per_tenant) == N, dict(per_tenant)
xs = list(per_tenant.values())
jain = sum(xs) ** 2 / (len(xs) * sum(x * x for x in xs))
assert jain >= 0.8, (jain, dict(per_tenant))
waits = sorted(d["started_ts"] - d["submitted_ts"]
               for d in statuses.values())
print(f"fleet-check: {N} jobs done, {len(expired)} lease "
      f"expiration(s), {len(reclaims)} reclaim(s), jain={jain:.3f}, "
      f"queue-to-start p50={waits[len(waits) // 2]:.2f}s "
      f"max={waits[-1]:.2f}s")
PYEOF

# -- 6. telemetry gates: schema-valid streams + the Fleet/SLO report ---
"$PY" tools/obs_report.py "$ROOT/events/server.jsonl" --check
"$PY" tools/obs_report.py "$ROOT/events/w1.jsonl" --check
cat "$ROOT/events/server.jsonl" "$ROOT/events/w1.jsonl" \
    > "$TD/merged-events.jsonl"
"$PY" tools/obs_report.py "$TD/merged-events.jsonl" > "$TD/report.md"
grep -q "Fleet" "$TD/report.md"
grep -q "SLO" "$TD/report.md"

# -- 7. the fleet trace gate: every terminal job's worker-side spans
# parent (via ctx_parent_id links) under its HTTP submit span — across
# the sigkill chaos (w2's torn stream is crash-tolerated) — and the
# merged Perfetto export carries the flow links
"$PY" tools/trace_export.py --fleet "$ROOT" --validate
"$PY" tools/trace_export.py --fleet "$ROOT" -o "$TD/fleet.trace.json" \
    | grep -q "trace link"
echo "fleet-check: OK"
