#!/usr/bin/env bash
# Observability CI gate (`make obs-check`): the three static checks that
# everything emitting telemetry must pass, run against the committed
# fixture stream so the gate itself needs no jax and no device.
#
#   1. graftlint over the package + tools (G004 emit conformance, G005
#      NullRecorder purity, ..., plus the whole-program stage: G011
#      lock discipline, G012 durability protocol, G013 fault-site
#      conformance — including the fault plans in this script's
#      sibling gate scripts; must be clean against the committed
#      empty baseline)
#   2. obs_report --check: schema + span pairing/nesting gate
#   3. trace_export --validate: the same stream must convert to a
#      Chrome trace (Perfetto) without violations
#
#   tools/ci_obs.sh [EVENTS.jsonl]     # default: the smoke fixture
#
# Exercised by tests/test_tools.py, so tier-1 fails when any gate rots.
set -euo pipefail

cd "$(dirname "$0")/.."
STREAM="${1:-tests/fixtures/obs/events_smoke.jsonl}"
PY="${PYTHON:-python}"

"$PY" -m tools.graftlint flipcomplexityempirical_tpu tools
"$PY" tools/obs_report.py --check "$STREAM"
"$PY" tools/trace_export.py --validate "$STREAM"
echo "obs-check: OK ($STREAM)"
