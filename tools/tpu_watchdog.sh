#!/bin/bash
# TPU-tunnel watchdog (VERDICT r4 next-1): poll the flaky axon tunnel all
# round; on the first window, run tools/tpu_capture.sh (which commits each
# record as it lands). Stops once a full set is captured (CAPTURED_*
# sentinel). Probe is a subprocess with a hard timeout because a down
# tunnel HANGS jax.devices() instead of erroring (memory: tpu-tunnel-flaky).
set -u
cd "$(dirname "$0")/.."
mkdir -p bench_runs
LOG=bench_runs/watchdog.log
while true; do
  if ls bench_runs/CAPTURED_* >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) full set already captured; watchdog exiting" >>"$LOG"
    exit 0
  fi
  if timeout 150 python -c \
      "import jax,sys; sys.exit(0 if jax.devices()[0].platform!='cpu' else 1)" \
      >>"$LOG" 2>&1; then
    echo "$(date -u +%FT%TZ) tunnel UP - starting capture" >>"$LOG"
    bash tools/tpu_capture.sh >>"$LOG" 2>&1
    echo "$(date -u +%FT%TZ) capture attempt finished" >>"$LOG"
  else
    echo "$(date -u +%FT%TZ) tunnel down" >>"$LOG"
  fi
  sleep 420
done
