#!/usr/bin/env python
"""Fleet loadtest: queue-to-start latency + tenant fairness, measured.

    python tools/loadtest.py --tenants 500 --simulate
    python tools/loadtest.py --url http://127.0.0.1:8777 --tenants 8

``--simulate`` runs the ROADMAP's 500-concurrent-tenant scenario with
NO network, NO processes, and NO device work: the server's real
admission classes (``service.server.TokenBucket`` / ``FairAdmission`` —
imported, not reimplemented) are driven on a **virtual clock** through
a deterministic discrete-event loop with K simulated workers of
constant per-job service time. Deterministic in ``--seed``, finishes in
milliseconds, and measures the only thing the simulation can honestly
measure: the QUEUEING behavior of the admission design — who waits,
for how long, and how evenly. Device throughput is ``bench.py``'s job;
this record prices scheduling.

``--url`` drives a LIVE front door instead (``service.client``):
submits ``--jobs`` jobs per tenant, waits for all of them, and computes
the same statistics from the server's per-job ``queue_to_start_s``
(submission -> first lease claim, crash-resume keeps the first anchor).

Headline metric: **Jain's fairness index** over per-tenant mean
TURNAROUND (queue-to-start + service), ``(Σx)² / (n·Σx²)`` — 1.0 when
every tenant is served alike, → 1/n when one tenant absorbs all the
delay. Turnaround, not raw wait: waits on an uncontended fleet sit at
the admission floor (~ms), where Jain degenerates into a ratio of
noise; turnaround anchors the index at the service time tenants
actually experience and converges to wait-fairness exactly when
backlog makes waits dominate — the regime where fairness is at stake.
Higher is better, which lets ``tools/bench_compare.py`` gate it like
any throughput metric (qualified ``[tenants=N,workers=K]`` so it never
cross-gates kernel numbers). The record also carries p50/p99
queue-to-start and their ratio; the ROADMAP target (p99 ≤ 2×p50 at 500
tenants) is enforceable inline via ``--require-p99-ratio 2
--require-fairness 0.8`` (exit 1 on violation — the fleet-check gate
uses this). Defaults model the target SLO regime: 16 workers at ~25%
utilization (spread 4× the fleet's total service time), where queueing
theory says waits stay at the floor — shrink ``--spread-s`` or
``--workers`` to study backlog instead.

The simulated record is tagged ``device: cpu`` + ``cpu_fallback`` so
no comparison ever mistakes a scheduling simulation for silicon.

``--collector-bench`` (ISSUE 18, simulate-only) prices the fleet
observability plane itself: the simulated scenario is materialized as
a real fleet event layout (one server stream + K worker streams,
written through the real ``obs.Recorder``), then ``FleetCollector`` is
timed over it — a cold full tail, a warm incremental poll after an
append (the restart path, through the on-disk offset checkpoint), and
one ``/v1/metrics`` text render. The headline metric becomes
``fleet_collector_events_per_s`` (higher is better, same
``[tenants=N,workers=K]`` qualification) and the record carries
``collector_overhead``: collector host-seconds per simulated
fleet-second at this scenario's event volume —
``--require-collector-overhead 0.02`` is the ≤2%% gate.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
from heapq import heapify, heappop, heappush

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, os.pardir))


def jain_index(values) -> float:
    """(Σx)² / (n·Σx²); 1.0 for a uniform vector, 1/n for one-hot."""
    vals = list(values)
    if not vals:
        return 1.0
    total = sum(vals)
    sq = sum(v * v for v in vals)
    if sq == 0.0:
        return 1.0
    return (total * total) / (len(vals) * sq)


def _pctl(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1,
            int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[i]


class _VirtualClock:
    """The injected clock: ``now`` advances only when the event loop
    says so. TokenBucket refills against THIS, so quota behavior in
    simulation is exactly the served behavior, faster."""

    def __init__(self):
        self.now = 0.0

    def __call__(self) -> float:
        return self.now


def simulate(tenants: int, jobs: int, workers: int, service_s: float,
             spread_s: float, admit_s: float, seed: int,
             quota_rate=None, quota_burst: float = 10.0,
             weights=None) -> dict:
    """Discrete-event simulation over the REAL admission classes.

    Submissions: each tenant submits ``jobs`` jobs at seeded-uniform
    times in [0, spread_s). Admission: TokenBucket per tenant (when
    ``quota_rate``), then FairAdmission — the server's own weighted
    deficit round-robin. Service: K workers, constant ``service_s``
    per job, earliest-free-first (a heap of free times — the idle-
    worker poll loop's limit behavior). ``admit_s`` is the constant
    pump/claim overhead floor every job pays even on an idle fleet.
    """
    from flipcomplexityempirical_tpu.service.server import (
        FairAdmission, TokenBucket)

    rng = random.Random(seed)
    subs = sorted(
        (rng.uniform(0.0, spread_s), f"t{t:03d}", j)
        for t in range(tenants) for j in range(jobs))
    clock = _VirtualClock()
    buckets: dict = {}

    def admit(t_sub, tenant, idx) -> bool:
        clock.now = max(clock.now, t_sub)
        if quota_rate is not None:
            bucket = buckets.get(tenant)
            if bucket is None:
                bucket = TokenBucket(quota_rate, quota_burst,
                                     clock=clock)
                buckets[tenant] = bucket
            if not bucket.take():
                return False
        adm.enqueue(tenant, (t_sub, idx))
        return True

    adm = FairAdmission(weights=weights)
    free = [0.0] * workers
    heapify(free)
    waits: dict = {}
    turnarounds: dict = {}
    rejected: dict = {}
    i = 0
    while i < len(subs) or len(adm):
        # feed the queue: everything submitted by the time the next
        # worker frees, plus at least one submission when it is empty
        # (an idle fleet waits for work, not the reverse)
        while i < len(subs) and (subs[i][0] <= free[0]
                                 or len(adm) == 0):
            t_sub, tenant, idx = subs[i]
            i += 1
            if not admit(t_sub, tenant, idx):
                rejected[tenant] = rejected.get(tenant, 0) + 1
        if len(adm) == 0:
            continue        # everything pending was quota-rejected
        w_free = heappop(free)
        tenant, (t_sub, _) = adm.pop()
        start = max(w_free, t_sub) + admit_s
        waits.setdefault(tenant, []).append(start - t_sub)
        turnarounds.setdefault(tenant, []).append(
            start - t_sub + service_s)
        heappush(free, start + service_s)
    return {"waits": waits, "turnarounds": turnarounds,
            "rejected": rejected, "makespan_s": max(free)}


def live(url: str, tenants: int, jobs: int, workload: str,
         overrides: dict, timeout_s: float) -> dict:
    """Drive a served front door: submit jobs×tenants, wait, read the
    server's queue_to_start_s per job."""
    from flipcomplexityempirical_tpu.service.client import (
        ClientError, ServiceClient)

    submitted: dict = {}          # job_id -> tenant
    rejected: dict = {}
    clients = {f"t{t:03d}": ServiceClient(url, tenant=f"t{t:03d}")
               for t in range(tenants)}
    for j in range(jobs):
        for tenant, client in clients.items():
            try:
                out = client.submit(workload=workload,
                                    overrides=overrides)
                submitted[out["job_id"]] = tenant
            except ClientError as e:
                if e.status != 429:
                    raise
                rejected[tenant] = rejected.get(tenant, 0) + 1
    any_client = next(iter(clients.values()))
    done = any_client.wait_all(list(submitted), timeout_s=timeout_s)
    waits: dict = {}
    turnarounds: dict = {}
    for job_id, doc in done.items():
        tenant = submitted[job_id]
        q2s = doc.get("queue_to_start_s")
        if q2s is not None:
            waits.setdefault(tenant, []).append(q2s)
        if (doc.get("finished_ts") is not None
                and doc.get("submitted_ts") is not None):
            turnarounds.setdefault(tenant, []).append(
                doc["finished_ts"] - doc["submitted_ts"])
    return {"waits": waits, "turnarounds": turnarounds,
            "rejected": rejected, "statuses": done}


def collector_bench(tenants: int, jobs: int, workers: int,
                    seed: int, makespan_s: float) -> dict:
    """Materialize the simulated scenario's telemetry and time the
    FleetCollector over it. The layout is the served one exactly: per
    job one ``http_request`` + one ``job_submitted`` (trace context
    attached, server stream) and one ``lease_acquired`` (its worker's
    stream), plus a ``metrics_snapshot`` per worker — written through
    the real Recorder so framing/fsync behavior is the production one.

    Three timed legs: a COLD poll (full tail of every stream), a WARM
    poll through a fresh collector instance after an append (the
    restart path: offsets come back from the on-disk checkpoint), and
    one Prometheus text render (what a /v1/metrics scrape pays).
    ``collector_overhead`` divides the total by the scenario's
    simulated makespan: host-seconds of collection per fleet-second,
    at this scenario's event volume."""
    import tempfile
    import time as _time

    from flipcomplexityempirical_tpu import obs
    from flipcomplexityempirical_tpu.obs.aggregate import FleetCollector

    root = tempfile.mkdtemp(prefix="graft-collector-bench-")
    events_dir = os.path.join(root, "events")
    os.makedirs(events_dir, exist_ok=True)

    def _recorders():
        server = obs.Recorder(
            os.path.join(events_dir, "server.jsonl"),
            ident={"pid": 1, "worker_name": "server"})
        wrecs = [obs.Recorder(
            os.path.join(events_dir, f"w{k}.jsonl"),
            ident={"pid": 100 + k, "worker_name": f"w{k}"})
            for k in range(workers)]
        return server, wrecs

    def _emit_jobs(server, wrecs, n, offset=0):
        for i in range(n):
            job_id = f"j{offset + i:05d}"
            tenant = f"t{i % max(1, tenants):03d}"
            trace_id = f"job:{job_id}"
            server.emit("http_request", method="POST", path="/v1/jobs",
                        status=200, dur_s=0.001, trace_id=trace_id)
            server.emit("job_submitted", job_id=job_id, tag="bench",
                        tenant=tenant, trace_id=trace_id)
            wrecs[i % workers].emit(
                "lease_acquired", job_id=job_id,
                worker=f"w{i % workers}", reclaim=False,
                trace_id=trace_id)

    n_jobs = tenants * jobs
    server, wrecs = _recorders()
    _emit_jobs(server, wrecs, n_jobs)
    for k, w in enumerate(wrecs):
        w.emit("metrics_snapshot", counters={"flips": 1000 * (k + 1)},
               gauges={}, histograms={
                   "segment_wall_s": {"count": 8, "sum": 4.0,
                                      "p50": 0.5, "p95": 0.9,
                                      "p99": 1.0}})
    server.close()
    for w in wrecs:
        w.close()

    t0 = _time.perf_counter()
    cold = FleetCollector(root).poll()
    cold_s = _time.perf_counter() - t0

    # warm leg: append a trickle, collect through a FRESH instance so
    # the offsets round-trip the on-disk checkpoint (the restart path)
    server, wrecs = _recorders()
    _emit_jobs(server, wrecs, workers, offset=n_jobs)
    server.close()
    for w in wrecs:
        w.close()
    t0 = _time.perf_counter()
    warm_collector = FleetCollector(root)
    warm = warm_collector.poll()
    warm_s = _time.perf_counter() - t0

    t0 = _time.perf_counter()
    text = warm_collector.prometheus_text()
    render_s = _time.perf_counter() - t0

    n_events = cold["events"] + warm["events"]
    total_s = cold_s + warm_s + render_s
    return {
        "collector_events": n_events,
        "collector_streams": cold["streams"],
        "collector_cold_poll_s": round(cold_s, 6),
        "collector_warm_poll_s": round(warm_s, 6),
        "collector_render_s": round(render_s, 6),
        "collector_poll_wall_s": round(total_s, 6),
        "collector_events_per_s": round(n_events / max(total_s, 1e-9)),
        "collector_overhead": round(total_s / max(makespan_s, 1e-9), 6),
        "collector_metrics_lines": len(text.splitlines()),
    }


def build_record(waits: dict, turnarounds: dict, rejected: dict,
                 tenants: int, workers: int, jobs: int, mode: str,
                 extra=None) -> dict:
    all_waits = sorted(w for ws in waits.values() for w in ws)
    per_tenant_mean = [sum(ts) / len(ts)
                       for ts in turnarounds.values() if ts]
    p50 = _pctl(all_waits, 0.5)
    p99 = _pctl(all_waits, 0.99)
    record = {
        "metric": "fleet_fairness_jain",
        "value": round(jain_index(per_tenant_mean), 4),
        "unit": "ratio",
        "mode": mode,
        "tenants": tenants,
        "workers": workers,
        "jobs_per_tenant": jobs,
        "jobs_measured": len(all_waits),
        "p50_queue_to_start_s": round(p50, 4),
        "p99_queue_to_start_s": round(p99, 4),
        "p99_over_p50": round(p99 / p50, 3) if p50 > 0 else None,
        "max_queue_to_start_s": round(all_waits[-1], 4)
                                if all_waits else None,
        "quota_rejected": sum(rejected.values()),
        # a scheduling measurement, never silicon:
        "device": "cpu",
        "cpu_fallback": True,
    }
    if extra:
        record.update(extra)
    return record


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="fleet loadtest: queue-to-start + Jain fairness")
    mode = ap.add_mutually_exclusive_group(required=True)
    mode.add_argument("--simulate", action="store_true",
                      help="virtual-clock discrete-event run over the "
                           "server's own admission classes")
    mode.add_argument("--url", default=None,
                      help="drive a live front door instead")
    ap.add_argument("--tenants", type=int, default=500)
    ap.add_argument("--jobs", type=int, default=2,
                    help="jobs per tenant")
    ap.add_argument("--workers", type=int, default=16)
    ap.add_argument("--service-s", type=float, default=1.0,
                    help="simulate: constant per-job service time")
    ap.add_argument("--spread-s", type=float, default=None,
                    help="simulate: submissions arrive uniformly over "
                         "this window (default: 4x the fleet's total "
                         "service time — the ~25%%-utilization SLO "
                         "regime; shrink it to study backlog)")
    ap.add_argument("--admit-s", type=float, default=0.002,
                    help="simulate: constant admission+claim overhead "
                         "floor per job")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--quota-rate", type=float, default=None)
    ap.add_argument("--quota-burst", type=float, default=10.0)
    ap.add_argument("--workload", default="frank",
                    help="live: catalog workload to submit")
    ap.add_argument("--set", dest="overrides", action="append",
                    metavar="K=V", help="live: workload override")
    ap.add_argument("--timeout", type=float, default=600.0)
    ap.add_argument("--collector-bench", action="store_true",
                    help="simulate-only: also materialize the scenario "
                         "as real fleet event streams and time the "
                         "FleetCollector over them; the headline "
                         "metric becomes fleet_collector_events_per_s")
    ap.add_argument("--require-collector-overhead", type=float,
                    default=None, metavar="F",
                    help="exit 1 unless collector_overhead (collector "
                         "host-seconds per simulated fleet-second) "
                         "<= F")
    ap.add_argument("--require-p99-ratio", type=float, default=None,
                    metavar="R", help="exit 1 unless p99 <= R x p50")
    ap.add_argument("--require-fairness", type=float, default=None,
                    metavar="J", help="exit 1 unless Jain >= J")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="also write the record JSON here")
    args = ap.parse_args(argv)

    if args.simulate:
        spread = args.spread_s
        if spread is None:
            spread = (4.0 * args.tenants * args.jobs * args.service_s
                      / max(1, args.workers))
        sim = simulate(args.tenants, args.jobs, args.workers,
                       args.service_s, spread, args.admit_s,
                       args.seed, quota_rate=args.quota_rate,
                       quota_burst=args.quota_burst)
        record = build_record(
            sim["waits"], sim["turnarounds"], sim["rejected"],
            args.tenants, args.workers, args.jobs, "simulate",
            extra={"service_s": args.service_s,
                   "spread_s": round(spread, 3),
                   "admit_s": args.admit_s, "seed": args.seed,
                   "makespan_s": round(sim["makespan_s"], 3)})
        if args.collector_bench:
            cb = collector_bench(args.tenants, args.jobs,
                                 args.workers, args.seed,
                                 sim["makespan_s"])
            # re-headline: the fairness index stays in the record as a
            # plain field, the gated metric is collector throughput
            # (higher is better, same tenants/workers qualification)
            record["fleet_fairness_jain"] = record["value"]
            record.update(cb)
            record["metric"] = "fleet_collector_events_per_s"
            record["value"] = cb["collector_events_per_s"]
            record["unit"] = "events/s"
    else:
        if args.collector_bench:
            ap.error("--collector-bench requires --simulate")
        overrides = {}
        for pair in args.overrides or ():
            k, v = pair.split("=", 1)
            try:
                overrides[k] = json.loads(v)
            except ValueError:
                overrides[k] = v
        res = live(args.url, args.tenants, args.jobs, args.workload,
                   overrides, args.timeout)
        record = build_record(res["waits"], res["turnarounds"],
                              res["rejected"], args.tenants,
                              args.workers, args.jobs, "live",
                              extra={"url": args.url})

    print(json.dumps(record))
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(record, f, indent=1, sort_keys=True)
            f.write("\n")
    rc = 0
    ratio = record["p99_over_p50"]
    if (args.require_p99_ratio is not None and ratio is not None
            and ratio > args.require_p99_ratio):
        print(f"loadtest: p99/p50 {ratio} exceeds "
              f"{args.require_p99_ratio}", file=sys.stderr)
        rc = 1
    jain = record.get("fleet_fairness_jain", record["value"])
    if (args.require_fairness is not None
            and jain < args.require_fairness):
        print(f"loadtest: Jain {jain} below "
              f"{args.require_fairness}", file=sys.stderr)
        rc = 1
    if args.require_collector_overhead is not None:
        ov = record.get("collector_overhead")
        if ov is None or ov > args.require_collector_overhead:
            print(f"loadtest: collector overhead {ov} exceeds "
                  f"{args.require_collector_overhead}", file=sys.stderr)
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
