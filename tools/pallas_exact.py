#!/usr/bin/env python
"""On-silicon Pallas bit-exactness check (VERDICT r4 item 2).

Runs the Pallas board kernel COMPILED on the default backend (TPU via the
axon tunnel when up) and in interpret mode, feeding both the SAME
host-supplied random bits (``_host_bits``), and compares the full end
state: board, district populations, histories, wait sums. Interpret mode
executes the identical per-step jnp semantics that
tests/test_pallas_board.py proves bit-exact against its transparent numpy
simulator on CPU — so compiled-vs-interpret equality on the chip closes
the chain: silicon kernel == simulator semantics.

Prints one JSON line: {"exact": bool, "device": ..., mismatch detail}.
Exit 0 on exact match, 1 on mismatch, 2 on error/unsupported.
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp
    import flipcomplexityempirical_tpu as fce

    dev = jax.devices()[0]
    # chains = block_chains = 128: the bench-proven Mosaic block shape
    # (a tiny block can violate TPU sublane tiling); n = 8*16 = 128 lanes
    h, w, chains, steps = 8, 16, 128, 41
    g = fce.graphs.square_grid(h, w)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(contiguity="patch")
    bg, st, params = fce.sampling.init_board(
        g, plan, n_chains=chains, seed=0, spec=spec, base=1.4, pop_tol=0.3)

    rng = np.random.default_rng(7)
    bank = {}

    def host_bits(chunk_idx, t, c, n):
        if chunk_idx not in bank:
            bank[chunk_idx] = (
                rng.integers(0, 2**32, size=(t, c, n), dtype=np.uint32),
                rng.integers(0, 2**32, size=(t, 2, c), dtype=np.uint32))
        return bank[chunk_idx]

    results = {}
    for name, interp in (("compiled", False), ("interpret", True)):
        res = fce.sampling.run_board_pallas(
            bg, spec, params, st, n_steps=steps, chunk=10,
            block_chains=chains, interpret=interp, _host_bits=host_bits)
        s = res.host_state()
        results[name] = {
            "board": np.asarray(s.board),
            "dist_pop": np.asarray(s.dist_pop),
            "waits_sum": np.asarray(s.waits_sum),
            "cut_count": np.asarray(res.history["cut_count"]),
            "accepts": np.asarray(res.history["accepts"]),
        }

    a, b = results["compiled"], results["interpret"]
    mism = {k: int(np.sum(a[k] != b[k])) for k in a}
    exact = not any(mism.values())
    print(json.dumps({"check": "pallas_compiled_vs_interpret",
                      "exact": exact, "device": str(dev),
                      "chains": chains, "steps": steps,
                      "mismatches": mism}))
    return 0 if exact else 1


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception as e:  # noqa: BLE001 - watchdog consumes the rc
        print(json.dumps({"check": "pallas_compiled_vs_interpret",
                          "error": repr(e)}))
        sys.exit(2)
