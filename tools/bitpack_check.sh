#!/usr/bin/env bash
# Bit-identity CI gate (`make bitpack-check`): the packed lowered_bits
# body must produce bit-identical trajectories to the int8 lowered body
# (ISSUE 8). The full parity matrix — sec11, queen, Frankengraph, both
# record_interface settings — lives in tests/test_bitboard_lowered.py;
# this is the fast tier-1 smoke on a small surgical grid so the
# contract gates every commit, not just slow-marked runs.
#
#   tools/bitpack_check.sh
#
# Exercised by tests/test_tools.py, so tier-1 fails when the gate rots.
set -euo pipefail

cd "$(dirname "$0")/.."
PY="${PYTHON:-python}"

JAX_PLATFORMS=cpu "$PY" - <<'PYEOF'
import numpy as np

import flipcomplexityempirical_tpu as fce
from flipcomplexityempirical_tpu.kernel import bitboard
from flipcomplexityempirical_tpu.kernel import board as kboard

# a surgical grid (holes + extra diagonal edges) small enough that the
# whole check compiles and runs in seconds on CPU, but wide enough
# (w=7 > 4) that b2_disp is unambiguous and the packed body engages
g = fce.graphs.square_grid(
    5, 7, remove_nodes=[(0, 0), (2, 3)],
    extra_edges=[((0, 1), (1, 0)), ((3, 4), (4, 5))])
plan = fce.graphs.stripes_plan(g, 2)
spec = fce.Spec(n_districts=2, proposal="bi", contiguity="patch",
                invalid="repropose", accept="cut",
                parity_metrics=True, geom_waits=True)
bg, st, params = fce.sampling.init_board(
    g, plan, n_chains=6, seed=3, spec=spec, base=1.3, pop_tol=0.3)
assert bitboard.supported_lowered(bg, spec), "gate rejects the fixture"
assert kboard.body_for(bg, spec) == "lowered_bits", \
    f"dispatch fell off the packed rung: {kboard.body_for(bg, spec)}"

got_state, got_outs = kboard.run_board_chunk(bg, spec, params, st, 60)
want_state, want_outs = kboard.run_board_chunk(bg, spec, params, st, 60,
                                               bits=False)
assert set(got_outs) == set(want_outs), (set(got_outs), set(want_outs))
for k in want_outs:
    np.testing.assert_array_equal(np.asarray(got_outs[k]),
                                  np.asarray(want_outs[k]), err_msg=k)
for f in want_state.__dataclass_fields__:
    a, b = getattr(got_state, f), getattr(want_state, f)
    if b is None:
        assert a is None, f
        continue
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                  err_msg=f)
print("bitpack-check: lowered_bits == lowered (60 steps, 6 chains, "
      "5x7 surgical grid)")
PYEOF
echo "bitpack-check: OK"
