#!/usr/bin/env python
"""Fold an obs telemetry stream (JSONL) into a PROFILE.md-style summary.

    python tools/obs_report.py EVENTS.jsonl            # summary tables
    python tools/obs_report.py --check EVENTS.jsonl    # schema gate

Report mode prints, per run (run_start → run_end), the headline numbers
the round-3/5 profiling sessions extracted by hand: chains, chunks,
wall, aggregate flips/s, accept rate, host-transfer and HBM-resident
history bytes, and compile (jit cache miss) counts — plus a per-chunk
throughput spread so a single degraded chunk (the round-5 "history
readback dwarfs sampling" class of finding) is visible without a
profiler. Runs whose stream ends without a run_end (crash / still in
flight) get partial totals synthesized from their chunk events, marked
with a trailing ``*``. A Health section renders the in-flight monitor's
output: anomaly events, the kernel reject-reason breakdown per path,
and each run's R-hat trajectory from its ``diag`` stream. A Timing
section renders the tracing subsystem's output (obs.trace spans +
obs.metrics snapshots): per-phase wall-clock breakdown, the slowest
individual spans, and each run's p50/p95/p99 chunk-latency and flips/s
histograms. A Fleet section summarizes worker-fleet streams (PR 17):
per-worker lease claims/reclaims, worker start/exit pairing (a SIGKILL
leaves a start with no exit), lease expirations, quota rejections by
tenant, http request status mix, and p50/p99 queue-to-start measured
job_submitted -> first lease_acquired; ``--strict`` also fails on a
lease-expiry STORM (more than 2 expirations for one job — lease churn,
not crash recovery). An SLO section (ISSUE 18) renders whenever the
stream carries fleet events: the declarative objectives in
``obs/slo.py`` (queue-to-start tail ratio, lease-expiry rate over the
worst window, per-path throughput floor, compile-cache hit ratio)
evaluated as burn rates — ``--strict`` fails on any violated
objective. A trailing sweep section summarizes driver progress
events.

``--check`` validates every line against the event schema
(obs.events.EVENT_FIELDS envelope + per-type core fields) AND the span
pairing/nesting contract (obs.events.validate_spans: every begin
closed, no orphan parents, no id reuse), and exits nonzero listing each
violation — the CI gate on anything that emits telemetry. It also
prints the grandfathered-finding count from the committed
``graftlint_baseline.json`` so static-analysis debt is visible in the
same report (target: 0). ``--strict`` additionally exits nonzero (after
printing the report) when the stream carries any ``anomaly``,
``config_quarantined``, ``kernel_path_degraded``, or
``dispatch_stalled`` events — the CI gate on chain and sweep HEALTH
rather than stream shape — or when ``--heartbeat PATH`` names a sweep
heartbeat whose mtime is staler than 2x ``--heartbeat-interval``
without a complete status (service heartbeats report WHICH namespaced
per-job/per-batch file went stale and by how much). ``--heartbeat``
pointed at a DIRECTORY (a fleet root, or its ``workers/`` subdir)
probes every per-worker heartbeat doc instead: a worker whose doc went
stale past 2x its own beat cadence is named with how far behind it is;
cleanly "exited" workers are exempt. A Resilience
section summarizes retries by error class, quarantines, kernel-path
degradations, hung dispatches, mesh degradations, corrupt checkpoint
generations, and heartbeat write failures whenever the stream carries
any. Stdlib-only: the
schema module is loaded by file path, so neither gate needs jax (or any
package import) at all. ``.jsonl.gz`` streams (obs.Recorder gzip sinks)
are read transparently.
"""

from __future__ import annotations

import argparse
import gzip
import importlib.util
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_EVENTS_PY = os.path.join(_HERE, os.pardir, "flipcomplexityempirical_tpu",
                          "obs", "events.py")


def _load_schema():
    """Load obs.events directly by path: stdlib-only, no package import
    (the package __init__ pulls jax, which a JSONL check never needs)."""
    spec = importlib.util.spec_from_file_location("_obs_events", _EVENTS_PY)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_GRAFTLINT_BASELINE = os.path.join(_HERE, os.pardir,
                                   "graftlint_baseline.json")


def graftlint_baseline_count(path: str = _GRAFTLINT_BASELINE):
    """Number of grandfathered findings in the committed graftlint
    baseline, or None when no baseline exists. Surfaced by ``--check``
    so static-analysis debt is visible next to the schema gate (the
    target is 0: violations get fixed or pragma'd, not baselined)."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    findings = doc.get("findings")
    return len(findings) if isinstance(findings, list) else None


def _open_text(path: str):
    """Open an event stream for reading, gunzipping transparently when
    the path carries the Recorder's ``.gz`` sink suffix."""
    if path.endswith(".gz"):
        return gzip.open(path, "rt", encoding="utf-8")
    return open(path, encoding="utf-8")


def check(path: str, schema) -> int:
    """Validate every line against the schema, then the parsed stream
    against the span pairing/nesting contract; print one diagnostic per
    violation; return the violation count (the exit code driver)."""
    bad = n = 0
    parsed = []
    with _open_text(path) as f:
        for lineno, line in enumerate(f, 1):
            if not line.strip():
                continue
            n += 1
            err = schema.validate_line(line)
            if err is not None:
                bad += 1
                print(f"{path}:{lineno}: {err}", file=sys.stderr)
            else:
                parsed.append(json.loads(line))
    span_errors = schema.validate_spans(parsed)
    for err in span_errors:
        print(f"{path}: span contract: {err}", file=sys.stderr)
    n_spans = sum(1 for e in parsed if e["event"] == "span_begin")
    if bad:
        print(f"{path}: {bad}/{n} events failed schema "
              f"v{schema.SCHEMA_VERSION}", file=sys.stderr)
    if span_errors:
        print(f"{path}: {len(span_errors)} span nesting violation(s)",
              file=sys.stderr)
    if not bad and not span_errors:
        print(f"{path}: ok ({n} events, {n_spans} spans, "
              f"schema v{schema.SCHEMA_VERSION})")
    grandfathered = graftlint_baseline_count()
    if grandfathered is not None:
        print(f"graftlint baseline: {grandfathered} grandfathered "
              "finding(s)")
    return bad + len(span_errors)


def load_events(path: str, schema):
    """Parse the stream, tolerating (and counting) malformed lines —
    a report over a crashed run's partial stream must still render."""
    events, bad = [], 0
    with _open_text(path) as f:
        for line in f:
            if not line.strip():
                continue
            if schema.validate_line(line) is None:
                events.append(json.loads(line))
            else:
                bad += 1
    return events, bad


def _mb(b):
    return f"{b / 1e6:.1f}" if b else "0"


def fold_runs(events) -> list[dict]:
    """Group the flat stream into runs: a run_start opens a run, every
    chunk/compile/transfer joins the currently open run, run_end closes
    it. Runs never nest within one process (the runners are
    synchronous), so a second run_start before a run_end means the
    previous run died — it is kept, flagged unfinished."""
    runs, open_run = [], None
    for e in events:
        kind = e["event"]
        if kind == "run_start":
            open_run = {"start": e, "chunks": [], "compiles": 0,
                        "transfers": 0, "diags": [], "anomalies": [],
                        "metrics": None, "end": None}
            runs.append(open_run)
        elif open_run is not None:
            if kind == "chunk":
                open_run["chunks"].append(e)
            elif kind == "compile":
                open_run["compiles"] += 1
            elif kind == "transfer":
                open_run["transfers"] += e.get("bytes", 0)
            elif kind == "diag":
                open_run["diags"].append(e)
            elif kind == "anomaly":
                open_run["anomalies"].append(e)
            elif kind == "metrics_snapshot":
                open_run["metrics"] = e
            elif kind == "run_end":
                open_run["end"] = e
                open_run = None
    return runs


def synthesize_totals(run) -> dict | None:
    """run_end-shaped partial totals for a run that never closed,
    rebuilt from its chunk events: wall and flips are sums, the accept
    rate is flips-weighted, and the byte totals come from the running
    per-chunk fields (hbm is cumulative on chunk events; transfers are
    per-chunk). None when not even one chunk landed."""
    chunks = run["chunks"]
    if not chunks:
        return None
    flips = sum(c.get("flips", 0) for c in chunks)
    wall = sum(c.get("wall_s", 0.0) for c in chunks)
    acc = sum(c.get("accept_rate", 0.0) * c.get("flips", 0)
              for c in chunks if c.get("accept_rate") is not None)
    return {
        "flips": flips,
        "wall_s": wall,
        "flips_per_s": flips / max(wall, 1e-12),
        "accept_rate": (acc / flips) if flips else None,
        "transfer_bytes": sum(c.get("transfer_bytes", 0) for c in chunks),
        "hbm_history_bytes": chunks[-1].get("hbm_history_bytes", 0),
        "done": chunks[-1].get("done", 0),
    }


def report_runs(runs, out):
    cols = ("runner path chains steps chunks wall_s Mflips/s accept "
            "xfer_MB hbm_MB compiles").split()
    print("## Runs", file=out)
    print("| " + " | ".join(cols) + " |", file=out)
    print("|" + "---|" * len(cols), file=out)
    partials = 0
    for r in runs:
        s, e = r["start"], r["end"]
        mark = ""
        if e is None:
            e = synthesize_totals(r)
            if e is None:
                print(f"| {s['runner']} | {s.get('path', '-')} "
                      f"| {s['chains']} | {s['n_steps']} "
                      f"| 0 | UNFINISHED@0 | - | - "
                      f"| - | - | {r['compiles']} |", file=out)
                continue
            mark = "*"
            partials += 1
        rate = e.get("accept_rate")
        print(f"| {s['runner']}{mark} | {s.get('path', '-')} "
              f"| {s['chains']} "
              f"| {s['n_steps']} | {len(r['chunks'])} "
              f"| {e['wall_s']:.3f} | {e['flips_per_s'] / 1e6:.3f} "
              f"| {'-' if rate is None else format(rate, '.3f')} "
              f"| {_mb(e.get('transfer_bytes', 0) + r['transfers'])} "
              f"| {_mb(e.get('hbm_history_bytes', 0))} "
              f"| {r['compiles']} |", file=out)
    if partials:
        print("\n\\* no run_end in stream (crash or in flight): totals "
              "synthesized from its chunk events", file=out)

    report_paths(runs, out)

    spreads = [(i, r) for i, r in enumerate(runs) if len(r["chunks"]) > 1]
    if spreads:
        print("\n## Per-chunk throughput spread (flips/s)", file=out)
        print("| run | runner | chunks | min | median | max |", file=out)
        print("|---|---|---|---|---|---|", file=out)
        for i, r in spreads:
            f = sorted(c["flips_per_s"] for c in r["chunks"])
            print(f"| {i} | {r['start']['runner']} | {len(f)} "
                  f"| {f[0] / 1e6:.3f}M | {f[len(f) // 2] / 1e6:.3f}M "
                  f"| {f[-1] / 1e6:.3f}M |", file=out)


def report_paths(runs, out):
    """Aggregate throughput per kernel path (lowered_bits / lowered /
    bitboard / board / general_dense / general / pallas). The dispatch
    in kernel/board.py is silent —
    this table is where a workload that regressed off its fast path
    shows up (e.g. a sec11 run reporting 'general' instead of
    'lowered', or a hex run reporting 'general' instead of
    'general_dense')."""
    by_path: dict = {}
    for r in runs:
        e = r["end"] or synthesize_totals(r)
        if e is None:
            continue
        path = r["start"].get("path", e.get("path", "-"))
        agg = by_path.setdefault(path, {"runs": 0, "flips": 0,
                                        "wall": 0.0})
        agg["runs"] += 1
        agg["flips"] += e.get("flips", 0)
        agg["wall"] += e.get("wall_s", 0.0)
    if not by_path:
        return
    print("\n## Throughput by kernel path", file=out)
    print("| path | runs | flips | wall_s | Mflips/s |", file=out)
    print("|---|---|---|---|---|", file=out)
    for path in sorted(by_path):
        a = by_path[path]
        rate = a["flips"] / max(a["wall"], 1e-12)
        print(f"| {path} | {a['runs']} | {a['flips']} "
              f"| {a['wall']:.3f} | {rate / 1e6:.3f} |", file=out)


def report_readback(runs, out):
    """Device->host traffic per kernel path, from the optional
    ``readback_bytes`` chunk/run_end fields (runners that predate the
    accounting render nothing — the section appears only when at least
    one run carries it). Splits by ``readback_mode``: the summary plane
    (device-resident analytics, one small pytree per chunk) vs the
    flagged history oracle path — the per-step readback ratio between
    them is the devstats gate's acceptance number."""
    by_key: dict = {}
    for r in runs:
        e = r["end"] or {}
        chunks = [c for c in r["chunks"] if "readback_bytes" in c]
        if "readback_bytes" not in e and not chunks:
            continue
        path = r["start"].get("path", e.get("path", "-"))
        mode = e.get("readback_mode",
                     "summary" if r["start"].get("analytics") else
                     "history")
        agg = by_key.setdefault((path, mode), {
            "runs": 0, "bytes": 0, "chunks": 0, "steps": 0})
        agg["runs"] += 1
        agg["chunks"] += len(chunks)
        agg["steps"] += sum(c.get("steps", 0) for c in chunks)
        agg["bytes"] += e.get("readback_bytes",
                              sum(c["readback_bytes"] for c in chunks))
    if not by_key:
        return
    print("\n## Readback (device->host bytes)", file=out)
    print("| path | mode | runs | chunks | bytes | B/chunk | B/step |",
          file=out)
    print("|---|---|---|---|---|---|---|", file=out)
    for path, mode in sorted(by_key):
        a = by_key[(path, mode)]
        per_chunk = a["bytes"] / a["chunks"] if a["chunks"] else 0.0
        per_step = a["bytes"] / a["steps"] if a["steps"] else 0.0
        print(f"| {path} | {mode} | {a['runs']} | {a['chunks']} "
              f"| {a['bytes']} | {per_chunk:.1f} | {per_step:.2f} |",
              file=out)


def _fmt_rhat(x):
    return "-" if x is None else f"{x:.3f}"


def report_health(events, runs, out):
    """The in-flight monitor's section: anomaly events, the kernel
    reject-reason breakdown per path (from the chunk events' ``reject``
    dicts), and each run's R-hat trajectory from its ``diag`` stream.
    Rendered only when the stream carries health data at all (older
    streams without diag/anomaly/reject stay byte-identical)."""
    anomalies = [e for e in events if e["event"] == "anomaly"]
    by_path: dict = {}
    for e in events:
        r = e.get("reject") if e["event"] == "chunk" else None
        if not r:
            continue
        agg = by_path.setdefault(e.get("path", "-"), {})
        for k, v in r.items():
            if isinstance(v, (int, float)):
                agg[k] = agg.get(k, 0) + v
    trajectories = [(i, r) for i, r in enumerate(runs) if r["diags"]]
    if not (anomalies or by_path or trajectories):
        return

    print("\n## Health", file=out)
    if anomalies:
        t0 = events[0]["ts"]
        print(f"{len(anomalies)} anomaly event(s):", file=out)
        print("| t+s | kind | runner | path | detail |", file=out)
        print("|---|---|---|---|---|", file=out)
        for a in anomalies:
            detail = ", ".join(f"{k}={v}" for k, v in
                               sorted((a.get("detail") or {}).items()))
            print(f"| {a['ts'] - t0:.1f} | {a['kind']} "
                  f"| {a.get('runner', '-')} | {a.get('path', '-')} "
                  f"| {detail} |", file=out)
    else:
        print("no anomalies.", file=out)

    if by_path:
        print("\n### Reject reasons by kernel path", file=out)
        print("| path | proposals | accepted | nonboundary | pop "
              "| disconnect | metropolis |", file=out)
        print("|---|---|---|---|---|---|---|", file=out)
        for path in sorted(by_path):
            a = by_path[path]
            prop = a.get("proposals", 0)

            def cell(k, a=a, prop=prop):
                v = a.get(k, 0)
                return (f"{v} ({v / prop:.1%})" if prop else str(v))

            print(f"| {path} | {prop} | {cell('accepted')} "
                  f"| {cell('nonboundary')} | {cell('pop')} "
                  f"| {cell('disconnect')} | {cell('metropolis')} |",
                  file=out)

    if trajectories:
        print("\n### R-hat trajectory (diag stream)", file=out)
        print("| run | runner | observable | rhat trajectory "
              "| final ESS | ESS/s |", file=out)
        print("|---|---|---|---|---|---|", file=out)
        for i, r in trajectories:
            ds = r["diags"]
            # first / quartile-ish / last keeps the row width bounded
            # while showing whether the run was converging
            k = max(1, (len(ds) - 1 + 3) // 4)
            picked = ds[:-1:k] + [ds[-1]] if len(ds) > 1 else ds
            traj = " → ".join(_fmt_rhat(d.get("rhat")) for d in picked)
            last = ds[-1]
            ess = last.get("ess")
            ess_s = last.get("ess_per_s")
            print(f"| {i} | {r['start']['runner']} "
                  f"| {last.get('observable', '-')} | {traj} "
                  f"| {'-' if ess is None else format(ess, '.0f')} "
                  f"| {'-' if ess_s is None else format(ess_s, '.1f')} |",
                  file=out)


_SPAN_ENVELOPE = {"v", "ts", "event", "name", "span_id", "trace_id",
                  "parent_id", "tid", "dur_s"}


def _pair_spans(events):
    """Match span_begin/span_end by span_id, stream order. Returns
    (begin, end) pairs; unclosed spans (crash / in flight) are dropped
    — ``--check`` is where they get reported, not the timing tables."""
    pairs, open_spans = [], {}
    for e in events:
        kind = e["event"]
        if kind == "span_begin":
            open_spans[e.get("span_id")] = e
        elif kind == "span_end":
            b = open_spans.pop(e.get("span_id"), None)
            if b is not None:
                pairs.append((b, e))
    return pairs


def report_timing(events, runs, out):
    """The tracing subsystem's section: per-phase wall-clock breakdown
    (spans grouped by name), the slowest individual spans with their
    tags, and each run's chunk-latency / flips-per-second histogram
    percentiles from its metrics snapshot. Rendered only when the
    stream carries spans or metrics at all (older streams stay
    byte-identical)."""
    pairs = _pair_spans(events)
    metric_runs = []
    for i, r in enumerate(runs):
        hists = None
        end = r["end"]
        if end is not None and isinstance(end.get("metrics"), dict):
            hists = end["metrics"].get("histograms")
        if not hists and r["metrics"] is not None:
            hists = r["metrics"].get("histograms")
        if hists:
            metric_runs.append((i, r, hists))
    if not pairs and not metric_runs:
        return

    print("\n## Timing", file=out)
    if pairs:
        per: dict = {}
        for b, e in pairs:
            agg = per.setdefault(b.get("name", "?"), [0, 0.0, 0.0])
            dur = e.get("dur_s") or 0.0
            agg[0] += 1
            agg[1] += dur
            agg[2] = max(agg[2], dur)
        print("### Per-phase breakdown", file=out)
        print("| span | count | total_s | mean_s | max_s |", file=out)
        print("|---|---|---|---|---|", file=out)
        for name, (count, total, mx) in sorted(
                per.items(), key=lambda kv: -kv[1][1]):
            print(f"| {name} | {count} | {total:.3f} "
                  f"| {total / count:.4f} | {mx:.3f} |", file=out)

        t0 = events[0]["ts"]
        top = sorted(pairs, key=lambda p: -(p[1].get("dur_s") or 0.0))[:8]
        print("\n### Slowest spans", file=out)
        print("| span | dur_s | t+s | args |", file=out)
        print("|---|---|---|---|", file=out)
        for b, e in top:
            args = ", ".join(f"{k}={v}" for k, v in sorted(b.items())
                             if k not in _SPAN_ENVELOPE)
            print(f"| {b.get('name', '?')} "
                  f"| {e.get('dur_s', 0.0):.3f} | {b['ts'] - t0:.1f} "
                  f"| {args or '-'} |", file=out)

    if metric_runs:
        print("\n### Histogram percentiles", file=out)
        print("| run | runner | metric | count | p50 | p95 | p99 |",
              file=out)
        print("|---|---|---|---|---|---|---|", file=out)
        for i, r, hists in metric_runs:
            for mname in sorted(hists):
                h = hists[mname]
                cells = " | ".join(
                    "-" if h.get(q) is None else format(h[q], ".4g")
                    for q in ("p50", "p95", "p99"))
                print(f"| {i} | {r['start']['runner']} | {mname} "
                      f"| {h.get('count', 0)} | {cells} |", file=out)


def report_resilience(events, out):
    """The fault-tolerance section: retries grouped by error class,
    quarantined / failed configs, kernel-path degradations, corrupt
    checkpoint generations, and heartbeat write failures. Rendered only
    when the stream carries any of it (fault-free streams stay
    byte-identical). ``--strict`` turns quarantines and degradations
    into a nonzero exit — the health gate on sweep resilience."""
    retries = [e for e in events if e["event"] == "retry"]
    quarantined = [e for e in events if e["event"] == "config_quarantined"]
    failed = [e for e in events if e["event"] == "config_failed"]
    degraded = [e for e in events if e["event"] == "kernel_path_degraded"]
    corrupt = [e for e in events if e["event"] == "checkpoint_corrupt"]
    hb_err = [e for e in events if e["event"] == "heartbeat_error"]
    stalled = [e for e in events if e["event"] == "dispatch_stalled"]
    meshdeg = [e for e in events if e["event"] == "mesh_degraded"]
    summary = [e for e in events if e["event"] == "sweep_summary"]
    if not (retries or quarantined or failed or degraded or corrupt
            or hb_err or stalled or meshdeg or summary):
        return

    print("\n## Resilience", file=out)
    if summary:
        s = summary[-1]
        print(f"sweep summary: {s['completed']} completed, "
              f"{s['retried']} retried, {s['quarantined']} quarantined, "
              f"{s['failed']} failed", file=out)
    if retries:
        by_class: dict = {}
        for e in retries:
            by_class.setdefault(e.get("error_class", "?"), []).append(e)
        print("\n### Retries by error class", file=out)
        print("| error_class | retries | configs | backoff_s total |",
              file=out)
        print("|---|---|---|---|", file=out)
        for cls in sorted(by_class):
            es = by_class[cls]
            tags = sorted({e.get("tag", "?") for e in es})
            backoff = sum(e.get("backoff_s", 0.0) for e in es)
            print(f"| {cls} | {len(es)} | {', '.join(tags)} "
                  f"| {backoff:.2f} |", file=out)
    for label, es, keys in (
            ("quarantined", quarantined, ("failures",)),
            ("failed", failed, ("error_class", "message"))):
        for e in es:
            detail = ", ".join(f"{k}={e.get(k)}" for k in keys)
            print(f"- {label.upper()} [{e.get('tag', '?')}]: {detail}",
                  file=out)
    for e in degraded:
        print(f"- DEGRADED {e['from_path']} -> {e['to_path']}: "
              f"{e.get('reason', '?')}", file=out)
    for e in corrupt:
        print(f"- CORRUPT CHECKPOINT [{e.get('tag', '?')}] "
              f"{e.get('path', '?')}: {e.get('reason', '?')}", file=out)
    for e in stalled:
        print(f"- DISPATCH STALLED [{e.get('batch_id', '?')}]: no "
              f"progress for {e.get('waited_s', 0):.0f}s (timeout "
              f"{e.get('timeout_s', 0):.0f}s); jobs journaled "
              f"poison-suspect, restart retries them solo", file=out)
    for e in meshdeg:
        print(f"- MESH DEGRADED {e.get('from_devices', '?')} -> "
              f"{e.get('to_devices', '?')} devices: "
              f"{e.get('reason', '?')}", file=out)
    if hb_err:
        print(f"- heartbeat write failures: {len(hb_err)} "
              f"(non-fatal; last: {hb_err[-1].get('message', '?')})",
              file=out)


def report_control(events, out):
    """The adaptive-control section: every control_action the sweep's
    ControlLoop emitted (stop / retune / reshape_ladder / reallocate),
    in stream order, with the decision detail inline. Rendered only
    when the stream carries control actions — a fixed-schedule sweep's
    report stays byte-identical."""
    actions = [e for e in events if e["event"] == "control_action"]
    if not actions:
        return
    print("\n## Control", file=out)
    by_kind: dict = {}
    for e in actions:
        by_kind[e.get("kind", "?")] = by_kind.get(e.get("kind", "?"), 0) + 1
    print(", ".join(f"{k}={v}" for k, v in sorted(by_kind.items())),
          file=out)
    print("\n| kind | tag | step | policy | detail |", file=out)
    print("|---|---|---|---|---|", file=out)
    for e in actions:
        detail = e.get("detail") or {}
        shown = ", ".join(
            f"{k}={detail[k]}" for k in sorted(detail)
            if not isinstance(detail[k], (list, dict)))
        print(f"| {e.get('kind', '?')} | {e.get('tag', '?')} "
              f"| {e.get('step', '?')} | {e.get('policy', '?')} "
              f"| {shown or '-'} |", file=out)


def _pctl(sorted_vals, q):
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[i]


def lease_storms(events, threshold: int = 2) -> dict:
    """``{job_id: n_expirations}`` for jobs whose lease expired MORE
    than ``threshold`` times. One expiration per job is the designed
    crash story (a SIGKILLed worker's lease reclaimed once); two can
    happen when the reclaimer itself dies; more means the TTL is
    shorter than the heartbeat can sustain (or a reclaim livelock) —
    the fleet is churning leases instead of running jobs. ``--strict``
    fails on any storm."""
    per_job: dict = {}
    for e in events:
        if e["event"] == "lease_expired" and e.get("job_id"):
            per_job[e["job_id"]] = per_job.get(e["job_id"], 0) + 1
    return {j: n for j, n in per_job.items() if n > threshold}


def report_fleet(events, out):
    """The worker-fleet section (PR 17): per-worker job counts from
    lease_acquired, lease expirations (the crash-reclaim story), quota
    rejections by tenant, worker start/exit pairing (a SIGKILL leaves a
    start with no exit), and p50/p99 queue-to-start measured
    job_submitted -> first lease_acquired per job. Rendered only when
    the stream carries fleet events — single-process sweeps stay
    byte-identical."""
    acquired = [e for e in events if e["event"] == "lease_acquired"]
    expired = [e for e in events if e["event"] == "lease_expired"]
    quota = [e for e in events if e["event"] == "quota_rejected"]
    started = [e for e in events if e["event"] == "worker_started"]
    exited = [e for e in events if e["event"] == "worker_exited"]
    requests = [e for e in events if e["event"] == "http_request"]
    if not (acquired or expired or quota or started or exited
            or requests):
        return

    print("\n## Fleet", file=out)
    if requests:
        by_status: dict = {}
        for e in requests:
            by_status[e["status"]] = by_status.get(e["status"], 0) + 1
        durs = sorted(e.get("dur_s", 0.0) for e in requests)
        print(f"{len(requests)} http request(s): "
              + ", ".join(f"{n}x {s}"
                          for s, n in sorted(by_status.items()))
              + f"; p50 {_pctl(durs, 0.5):.4f}s "
              f"p99 {_pctl(durs, 0.99):.4f}s", file=out)

    if acquired or started or exited:
        by_worker: dict = {}
        for e in started:
            by_worker.setdefault(e.get("worker", "?"),
                                 {"claims": 0, "reclaims": 0,
                                  "started": 0, "exit": None})
        for e in acquired:
            w = by_worker.setdefault(e.get("worker", "?"),
                                     {"claims": 0, "reclaims": 0,
                                      "started": 0, "exit": None})
            w["claims"] += 1
            if e.get("reclaim"):
                w["reclaims"] += 1
        for e in started:
            by_worker[e.get("worker", "?")]["started"] += 1
        for e in exited:
            w = by_worker.setdefault(e.get("worker", "?"),
                                     {"claims": 0, "reclaims": 0,
                                      "started": 0, "exit": None})
            w["exit"] = (f"{e.get('reason', '?')}"
                         f"/{e.get('n_executed', '?')} job(s)")
        print("\n| worker | claims | reclaims | exit |", file=out)
        print("|---|---|---|---|", file=out)
        for name in sorted(by_worker):
            w = by_worker[name]
            exit_cell = w["exit"] or (
                "NO EXIT (SIGKILL?)" if w["started"] else "-")
            print(f"| {name} | {w['claims']} | {w['reclaims']} "
                  f"| {exit_cell} |", file=out)

    # queue-to-start: submission to FIRST claim (reclaims after a crash
    # keep the original anchor, matching the started/ marker on disk)
    submitted_ts = {}
    for e in events:
        if e["event"] == "job_submitted" and e.get("job_id"):
            submitted_ts.setdefault(e["job_id"], e["ts"])
    first_claim = {}
    for e in acquired:
        if e.get("job_id") in submitted_ts:
            first_claim.setdefault(e["job_id"], e["ts"])
    waits = sorted(first_claim[j] - submitted_ts[j]
                   for j in first_claim)
    if waits:
        print(f"\nqueue-to-start over {len(waits)} job(s): "
              f"p50 {_pctl(waits, 0.5):.3f}s "
              f"p99 {_pctl(waits, 0.99):.3f}s "
              f"max {waits[-1]:.3f}s", file=out)

    if expired:
        by_job: dict = {}
        for e in expired:
            by_job.setdefault(e.get("job_id", "?"), []).append(e)
        print(f"\n{len(expired)} lease expiration(s):", file=out)
        for job_id in sorted(by_job):
            es = by_job[job_id]
            detail = "; ".join(
                f"{e.get('worker', '?')} -> {e.get('by', '?')} "
                f"(age {e.get('age_s', '?')}s)" for e in es)
            storm = "  ← STORM" if len(es) > 2 else ""
            print(f"- {job_id}: {detail}{storm}", file=out)

    if quota:
        by_tenant: dict = {}
        for e in quota:
            by_tenant[e.get("tenant", "?")] = (
                by_tenant.get(e.get("tenant", "?"), 0) + 1)
        print("\nquota rejections: "
              + ", ".join(f"{t}={n}"
                          for t, n in sorted(by_tenant.items())),
              file=out)


_SLO_PY = os.path.join(_HERE, os.pardir, "flipcomplexityempirical_tpu",
                       "obs", "slo.py")

_FLEET_EVENTS = ("job_submitted", "lease_acquired", "lease_expired",
                 "worker_started", "http_request")


def _load_slo():
    """Load obs.slo by file path, same stdlib-only discipline as the
    schema module (no package import, no jax)."""
    spec = importlib.util.spec_from_file_location("_obs_slo", _SLO_PY)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def report_slo(events, out):
    """The SLO section (ISSUE 18): obs/slo.py's declarative objectives
    evaluated as burn rates over the stream. Rendered only when the
    stream carries fleet events (single-process sweeps have no serving
    objectives and stay byte-identical). Returns the evaluated rows so
    ``--strict`` can gate on them, or None when not rendered."""
    if not any(e["event"] in _FLEET_EVENTS for e in events):
        return None
    rows = _load_slo().evaluate(events)
    print("\n## SLO", file=out)
    print("| objective | target | value | burn | n | status |", file=out)
    print("|---|---|---|---|---|---|", file=out)
    for r in rows:
        value = "-" if r["value"] is None else format(r["value"], ".3f")
        print(f"| {r['name']} | {r['target']:g} | {value} "
              f"| {r['burn']:.2f} | {r['count']} "
              f"| {'ok' if r['ok'] else 'VIOLATED'} |", file=out)
    for r in rows:
        print(f"- {r['name']}: {r['detail']}", file=out)
    return rows


def check_fleet_heartbeats(dirpath: str, interval_s: float):
    """Per-worker heartbeat probe over a fleet root (or its ``workers/``
    subdir): a worker doc whose mtime is staler than 2x its own beat
    cadence (the doc's ``hb_s``, falling back to ``interval_s``) is
    named together with how far behind it is. Workers whose doc says
    ``exited`` stopped beating by design and are exempt. Returns an
    error string, or None when every worker is fresh (no docs at all is
    an error — a fleet with no workers has no liveness story)."""
    import time as _time

    d = os.path.join(dirpath, "workers")
    if not os.path.isdir(d):
        d = dirpath
    try:
        names = sorted(n for n in os.listdir(d) if n.endswith(".json"))
    except OSError as e:
        return f"fleet heartbeats {dirpath}: unreadable ({e})"
    if not names:
        return f"fleet heartbeats {d}: no worker heartbeat docs"
    stale = []
    for name in names:
        path = os.path.join(d, name)
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
            mtime = os.path.getmtime(path)
        except (OSError, json.JSONDecodeError):
            # torn mid-replace or vanished: the next beat rewrites it;
            # staleness (not parseability) is the liveness signal here
            continue
        if str(doc.get("status", "")) == "exited":
            continue
        hb_s = doc.get("hb_s")
        cadence = float(hb_s) if isinstance(hb_s, (int, float)) \
            and hb_s > 0 else interval_s
        age = _time.time() - mtime
        if age > 2 * cadence:
            worker = doc.get("worker") or name[:-len(".json")]
            stale.append(
                f"worker {worker}: stale — last beat {age:.0f}s ago "
                f"(> 2x the {cadence:.0f}s cadence; status="
                f"{doc.get('status', '?')}, job={doc.get('job_id')})")
    return "; ".join(stale) if stale else None


def _namespaced_heartbeat_path(path: str, tag: str) -> str:
    # mirror of experiments.driver.heartbeat_path_for (this tool must
    # stay importable without jax): heartbeat.json + 2B30P10 ->
    # heartbeat.2B30P10.json
    root, ext = os.path.splitext(path)
    return f"{root}.{tag}{ext or '.json'}"


def check_heartbeat(path: str, interval_s: float,
                    stopped_tags=frozenset()):
    """Stale-heartbeat probe: returns an error string when the heartbeat
    file is missing, unparsable, or its mtime is older than 2x the
    expected refresh interval — unless its payload says the sweep
    finished (a completed sweep stops refreshing by design).

    A sweep-SERVICE heartbeat (payload carries a ``jobs`` map — see
    service.scheduler) is a merged summary refreshed at job
    transitions, not on the segment cadence; real liveness lives in the
    per-job/per-batch files next to it (``heartbeat.<tag>.json`` /
    ``heartbeat.<batch>.json``). For each non-terminal job the probe
    follows the namespaced sibling — preferring the batch file the job
    is running in — and applies the same staleness rule there.

    ``stopped_tags`` names configs the control loop early-stopped
    (``control_action`` ``kind=stop`` in the event stream): their
    refresh loops stop at the stop boundary BY DESIGN, exactly like a
    finished job's, so they are exempt from the staleness rule even if
    a summary refresh has not yet flipped their status off
    "running"."""
    import time as _time

    try:
        with open(path, encoding="utf-8") as f:
            payload = json.load(f)
        mtime = os.path.getmtime(path)
    except (OSError, json.JSONDecodeError) as e:
        return f"heartbeat {path}: unreadable ({e})"
    status = str(payload.get("status", ""))
    if status.startswith("complete"):
        return None
    jobs = payload.get("jobs")
    if isinstance(jobs, dict):
        errors = []
        running = False
        for tag, entry in sorted(jobs.items()):
            if not isinstance(entry, dict):
                continue
            if str(entry.get("status", "")) != "running":
                # queued/retrying jobs have no refresh loop of their
                # own; their liveness is the summary's (checked below)
                continue
            if tag in stopped_tags:
                # early-stopped by the control loop: refreshes ended at
                # the stop boundary by design (treated like job_done)
                continue
            running = True
            # the batch file carries the segment-cadence refreshes; the
            # per-job file exists from dispatch (fallback when the
            # batch has not produced a live-hook refresh yet)
            names = [str(entry["batch"])] if entry.get("batch") else []
            names.append(tag)
            errs = [check_heartbeat(
                        _namespaced_heartbeat_path(path, n), interval_s)
                    for n in names]
            if all(errs):
                # every probed file failed: name each namespaced file
                # and how stale it is, so the operator sees WHICH job's
                # refresh loop died (not just that something did)
                errors.append(f"job {tag}: " + "; ".join(errs))
        if errors:
            return "; ".join(errors)
        if running:
            return None
    age = _time.time() - mtime
    if age > 2 * interval_s:
        return (f"heartbeat {path}: stale — last refreshed {age:.0f}s "
                f"ago (> 2x the {interval_s:.0f}s interval); status="
                f"{status or '?'}")
    return None


def report_sweep(events, out):
    sweep = [e for e in events if e["event"] == "sweep_config"]
    errors = [e for e in events if e["event"] == "error"]
    if not sweep and not errors:
        return
    print("\n## Sweep", file=out)
    by_status = {}
    for e in sweep:
        by_status.setdefault(e["status"], []).append(e)
    for status in ("done", "skip", "start"):
        tags = by_status.get(status, [])
        if not tags:
            continue
        extra = ""
        if status == "done":
            secs = sum(e.get("seconds", 0) for e in tags)
            extra = f" ({secs:.1f}s total)"
        print(f"- {status}: {len(tags)}{extra} — "
              + ", ".join(e["tag"] for e in tags), file=out)
    # a start with no matching done/skip is the config a crash was in
    finished = {e["tag"] for e in by_status.get("done", [])}
    hanging = [e["tag"] for e in by_status.get("start", [])
               if e["tag"] not in finished]
    if hanging:
        print(f"- in flight (started, never finished): "
              + ", ".join(hanging), file=out)
    for e in errors:
        print(f"- ERROR [{e.get('tag', '?')}]: {e['message']}", file=out)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Summarize / validate an obs telemetry JSONL stream")
    ap.add_argument("path", help="JSONL event stream (obs.Recorder output)")
    ap.add_argument("--check", action="store_true",
                    help="validate only: exit nonzero on any "
                         "unknown/malformed event (CI gate)")
    ap.add_argument("--strict", action="store_true",
                    help="after the report, exit nonzero if the stream "
                         "carries any anomaly, config_quarantined, or "
                         "kernel_path_degraded events (health gate)")
    ap.add_argument("--heartbeat", metavar="PATH", default=None,
                    help="also probe this sweep heartbeat file for "
                         "staleness (mtime > 2x --heartbeat-interval "
                         "with a non-complete status); a DIRECTORY "
                         "probes every per-worker fleet heartbeat doc "
                         "instead; fails --strict")
    ap.add_argument("--heartbeat-interval", type=float, default=300.0,
                    metavar="S",
                    help="expected heartbeat refresh cadence for the "
                         "staleness probe (default: 300)")
    args = ap.parse_args(argv)
    schema = _load_schema()

    if args.check:
        return 1 if check(args.path, schema) else 0

    events, bad = load_events(args.path, schema)
    out = sys.stdout
    counts = {}
    for e in events:
        counts[e["event"]] = counts.get(e["event"], 0) + 1
    span = (events[-1]["ts"] - events[0]["ts"]) if len(events) > 1 else 0.0
    print(f"# obs report: {os.path.basename(args.path)}", file=out)
    print(f"{len(events)} events over {span:.1f}s — "
          + ", ".join(f"{k}={v}" for k, v in sorted(counts.items()))
          + (f" — {bad} MALFORMED (see --check)" if bad else ""),
          file=out)
    print(file=out)
    runs = fold_runs(events)
    if runs:
        report_runs(runs, out)
        report_readback(runs, out)
    report_health(events, runs, out)
    report_timing(events, runs, out)
    report_resilience(events, out)
    report_control(events, out)
    report_fleet(events, out)
    slo_rows = report_slo(events, out)
    report_sweep(events, out)
    hb_error = None
    if args.heartbeat:
        if os.path.isdir(args.heartbeat):
            hb_error = check_fleet_heartbeats(args.heartbeat,
                                              args.heartbeat_interval)
        else:
            stopped = frozenset(
                e.get("tag") for e in events
                if e["event"] == "control_action"
                and e.get("kind") == "stop" and e.get("tag"))
            hb_error = check_heartbeat(args.heartbeat,
                                       args.heartbeat_interval,
                                       stopped_tags=stopped)
        if hb_error:
            print(f"\n{hb_error}", file=out)
    if args.strict:
        gated = {"anomaly": 0, "config_quarantined": 0,
                 "kernel_path_degraded": 0, "dispatch_stalled": 0}
        for e in events:
            if e["event"] in gated:
                gated[e["event"]] += 1
        bad_kinds = [f"{n} {k}" for k, n in sorted(gated.items()) if n]
        if bad_kinds:
            print("--strict: " + ", ".join(bad_kinds)
                  + " event(s) in stream", file=sys.stderr)
            return 2
        storms = lease_storms(events)
        if storms:
            print("--strict: lease-expiry storm — "
                  + ", ".join(f"{j} expired {n}x"
                              for j, n in sorted(storms.items()))
                  + " (> 2 expirations for one job: the fleet is "
                  "churning leases, not running jobs)",
                  file=sys.stderr)
            return 2
        if hb_error:
            print(f"--strict: {hb_error}", file=sys.stderr)
            return 2
        violated = [r for r in (slo_rows or ()) if not r["ok"]]
        if violated:
            print("--strict: SLO violated — "
                  + "; ".join(f"{r['name']} burn {r['burn']:.2f} "
                              f"({r['detail']})" for r in violated),
                  file=sys.stderr)
            return 2
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # report | head is a normal way to skim a long stream summary
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
