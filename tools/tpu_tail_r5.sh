#!/bin/bash
# Round-5 tail watchdog. The first two windows (round start) landed the
# full record set; the second window's tail showed the tunnel DEGRADING
# before it dropped (default 17.4M vs the standing 20.2M, pallas 0.90M
# vs its 4.78M record — PROFILE.md "round-5 refresh" section). So from
# here on: every time the tunnel reopens, capture a fresh quiet-host
# default record (latest-wins evidence of the chip's current state, and
# insurance that a near-round-end record exists), and re-time the pallas
# path ONCE on a healthy window to resolve its anomalous 0.90M reading.
# Runs until the driver kills it at round end; caps the default stream
# at 8 captures to bound commit clutter.
set -u
cd "$(dirname "$0")/.."
. tools/bench_lib.sh
while true; do
  if [ "$(ls bench_runs/*_tail_default.json 2>/dev/null | wc -l)" -ge 8 ]; then
    exit 0
  fi
  if timeout 150 python -c \
      "import jax,sys; sys.exit(0 if jax.devices()[0].platform!='cpu' else 1)" \
      >/dev/null 2>&1; then
    TS=$(date -u +%Y%m%dT%H%M%SZ)
    run_bench tail_default 900 || true
    # pallas re-time only until one post-anomaly number exists; gate on
    # the default capture having measured healthy (>=15x) so we time the
    # kernel, not a dying tunnel
    if ! ls bench_runs/*_tail_pallas.json >/dev/null 2>&1 \
        && [ -s "bench_runs/${TS}_tail_default.json" ] \
        && python - "bench_runs/${TS}_tail_default.json" <<'EOF'
import json, sys
rec = json.load(open(sys.argv[1]))
sys.exit(0 if (rec.get("vs_baseline") or 0) >= 15.0 else 1)
EOF
    then
      run_bench tail_pallas 900 --pallas || true
    fi
    sleep 2700
  else
    sleep 420
  fi
done
