#!/bin/bash
# Round-5 tail watchdog. The first two windows (round start) landed the
# full record set; the second window's tail showed the tunnel DEGRADING
# before it dropped (default 17.4M vs the standing 20.2M, pallas 0.90M
# vs its 4.78M record — PROFILE.md "round-5 refresh" section). So from
# here on, every time the tunnel reopens:
#   1. capture a fresh quiet-host default record (latest-wins evidence
#      of the chip's current state, and insurance that a near-round-end
#      record exists; the committed stream is capped at 8 — past the
#      cap the default run still happens as an uncommitted tmpfile
#      health probe, because the one-shot gate needs a fresh reading);
#   2. if the window is HEALTHY (default read >=15x), run each missing
#      one-shot: a pallas re-timing (resolves the anomalous 0.90M), a
#      device-side ESS capture at the C=8192 throughput peak (the
#      standing 1.93M ESS/s record is C=4096 — ESS scales ~linearly in
#      chains, so the peak config should roughly double it), and a
#      k=4 pair-walk record at C=8192 (its standing record is C=4096).
#      Each one-shot carries its own vs_baseline acceptance floor (well
#      below the expected healthy reading, well above the anomaly), so
#      a window that degrades MID-SET quarantines the low reading
#      (*.suspect) and the one-shot is retried on a later window
#      instead of locking in another anomalous record.
# Failed/fallback/suspect/uncommitted captures are quarantined by
# run_bench (see bench_lib.sh), so only real committed records satisfy
# the have()/count gates. Runs until the driver kills it at round end.
set -u
cd "$(dirname "$0")/.."
. tools/bench_lib.sh

have() { # a non-empty committed-shape record exists for this one-shot
  for f in bench_runs/*"_$1.json"; do
    [ -s "$f" ] && grep -q '"value"' "$f" && return 0
  done
  return 1
}

# window health = this window's default capture read >=15x (shared
# vs_baseline gate lives in bench_lib.sh next to run_bench's floor)
healthy() { vsb_at_least "$1" 15.0; }

while true; do
  n_def=$(find bench_runs -maxdepth 1 -name '*_tail_default.json' -size +1c | wc -l)
  if [ "$n_def" -ge 8 ] && have tail_pallas && have tail_ess8192 \
      && have tail_pair_k4_c8192 && have tail_ess_general; then
    exit 0
  fi
  if timeout 150 python -c \
      "import jax,sys; sys.exit(0 if jax.devices()[0].platform!='cpu' else 1)" \
      >/dev/null 2>&1; then
    TS=$(date -u +%Y%m%dT%H%M%SZ)
    if [ "$n_def" -lt 8 ]; then
      run_bench tail_default 900 || true
      # committed record or the .uncommitted quarantine (a lost git race
      # is still a true reading); empty on the .failed/.fallback shapes,
      # which the gate below treats as unhealthy outright
      health=$(pick_health_record \
                 "bench_runs/${TS}_tail_default.json") || health=""
    else
      # cap reached: measure health without growing the committed stream
      health=$(mktemp /tmp/tail_health.XXXXXX)
      timeout 900 python bench.py >"$health" 2>/dev/null || true
    fi
    if [ -n "$health" ] && healthy "$health"; then
      have tail_pallas || run_bench_min 2.0 tail_pallas 900 --pallas || true
      have tail_ess8192 \
        || run_bench_min 12.0 tail_ess8192 1200 --ess --chains 8192 || true
      have tail_pair_k4_c8192 \
        || run_bench_min 6.0 tail_pair_k4_c8192 900 --k 4 --chains 8192 || true
      # exercises the round-5 general-path device-resident history on
      # silicon (flips floor well under the path's stable 0.30x record)
      have tail_ess_general \
        || run_bench_min 0.2 tail_ess_general 1200 --general --ess || true
    fi
    case "$health" in /tmp/*) rm -f "$health";; esac
    sleep 2700
  else
    sleep 420
  fi
done
