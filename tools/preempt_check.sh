#!/usr/bin/env bash
# Preemption CI gate (`make preempt-check`, ISSUE 11): SIGTERM (the
# injected `sigterm` fault site) lands mid-batch, the service drains —
# checkpointing tenants, requeueing running jobs, journaling ONE
# service_draining event — and exits with the distinct drain code 3.
# A fresh process then `SweepService.recover`s from the journal and the
# resumed per-tenant artifacts must be BYTE-IDENTICAL to uninterrupted
# solo runs, on the board fast path (frank -> lowered_bits) AND the
# general gather path (hex). A torn-tail leg truncates the journal
# mid-record and recovery must detect it (SHA-256 mismatch), repair
# from the previous record, and still converge to the same artifacts.
# The full matrix (crash points x tail states, watchdog, elastic mesh)
# lives in tests/test_preemption.py; this is the fast tier-1 smoke.
#
#   tools/preempt_check.sh                      # both families
#   PREEMPT_FAMILIES=frank tools/preempt_check.sh
#
# PREEMPT_FAMILIES narrows the family loop; the tier-1 test runs the
# frank-only subset (one cold XLA compile instead of two) so the gate
# cannot rot, while `make preempt-check` always runs the full matrix.
set -euo pipefail

cd "$(dirname "$0")/.."
PY="${PYTHON:-python}"
TD="$(mktemp -d)"
trap 'rm -rf "$TD"' EXIT

# one persistent XLA cache across every leg: a recovered process must
# not re-pay the drained process's compiles (the PR 9 on-disk cache is
# exactly the restart story this gate exercises), and it keeps the
# 5-process gate inside the tier-1 time budget. The cache lives at a
# STABLE path (not in $TD) so repeat gate runs — tier-1 wraps this
# script — skip the cold compiles too; nothing here asserts on XLA's
# cache behavior, only on bit-identity of the results.
export JAX_COMPILATION_CACHE_DIR="${GRAFT_GATE_JAX_CACHE:-${TMPDIR:-/tmp}/graft-gate-jax-cache}"
mkdir -p "$JAX_COMPILATION_CACHE_DIR"
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=1

FAMILIES="${PREEMPT_FAMILIES:-frank hex}"

for FAMILY in $FAMILIES; do
  OUT="$TD/$FAMILY"
  mkdir -p "$OUT"

  # --- leg 1: drain. The injected SIGTERM fires at the 2nd segment
  # boundary (mid-batch: both tenants are in flight); the process must
  # exit with the drain code, not 0 and not a failure code.
  set +e
  JAX_PLATFORMS=cpu GRAFT_FAULTS="sigterm:once@2" \
      "$PY" - "$OUT" "$FAMILY" <<'PYEOF'
import os
import sys

from flipcomplexityempirical_tpu import obs
from flipcomplexityempirical_tpu.experiments.config import ExperimentConfig
from flipcomplexityempirical_tpu.resilience import faults as rfaults
from flipcomplexityempirical_tpu.service import SweepService

out, family = sys.argv[1], sys.argv[2]
rfaults.install_from_env()
extra = {} if family == "frank" else dict(lattice_m=4, lattice_n=6)
als = (2, 1) if family == "frank" else (0, 1)
cfgs = [ExperimentConfig(family=family, alignment=al, base=0.3,
                         pop_tol=0.1, total_steps=120, n_chains=2,
                         backend="jax", seed=3 + al,
                         checkpoint_every=40, **extra)
        for al in als]
with obs.Recorder(os.path.join(out, "events.drain.jsonl")) as rec:
    svc = SweepService(outdir=out, recorder=rec)
    jobs = [svc.submit(c) for c in cfgs]
    svc.run_until_idle()
assert svc.drained, "injected sigterm did not drain the service"
assert all(j.status == "queued" for j in jobs), \
    [(j.tag, j.status) for j in jobs]
sys.exit(svc.exit_code)
PYEOF
  rc=$?
  set -e
  if [ "$rc" -ne 3 ]; then
    echo "preempt-check: $FAMILY drain leg exited $rc, want 3 (EXIT_DRAINED)"
    exit 1
  fi

  # snapshot the drained state for the torn-tail leg (frank only)
  # before recovery appends to the journal
  if [ "$FAMILY" = frank ]; then
    cp -r "$OUT" "$TD/frank-torn"
  fi

  # --- leg 2: recover from the journal, run to completion, compare the
  # resumed artifacts bit-for-bit against uninterrupted solo runs.
  JAX_PLATFORMS=cpu "$PY" - "$OUT" "$FAMILY" <<'PYEOF'
import json
import os
import sys
from collections import Counter

import numpy as np

from flipcomplexityempirical_tpu import obs
from flipcomplexityempirical_tpu.experiments import driver as drv
from flipcomplexityempirical_tpu.experiments.config import ExperimentConfig
from flipcomplexityempirical_tpu.service import SweepService

out, family = sys.argv[1], sys.argv[2]
with obs.Recorder(os.path.join(out, "events.recover.jsonl")) as rec:
    svc = SweepService.recover(out, recorder=rec)
    svc.run_until_idle()
assert svc.exit_code == 0, [(j.tag, j.status, j.error)
                            for j in svc.queue.jobs()]
done = {j.tag: j for j in svc.queue.jobs()}
assert len(done) == 2 and all(j.status == "done"
                              for j in done.values()), done

extra = {} if family == "frank" else dict(lattice_m=4, lattice_n=6)
als = (2, 1) if family == "frank" else (0, 1)
for al in als:
    cfg = ExperimentConfig(family=family, alignment=al, base=0.3,
                           pop_tol=0.1, total_steps=120, n_chains=2,
                           backend="jax", seed=3 + al,
                           checkpoint_every=40, **extra)
    g, plan, _ = drv.build_graph_and_plan(cfg)
    ref = drv._run_jax(cfg, g, plan, None)
    got = done[cfg.tag].result
    for k in ("end_signed", "cut_times", "num_flips", "waits_all"):
        np.testing.assert_array_equal(np.asarray(got[k]),
                                      np.asarray(ref[k]), err_msg=k)
    for k in ref["history"]:
        np.testing.assert_array_equal(
            np.asarray(got["history"][k]),
            np.asarray(ref["history"][k]), err_msg=f"history[{k}]")
    np.testing.assert_array_equal(np.asarray(got["assignments"]),
                                  np.asarray(ref["assignments"]))

# exactly one drain event in the drained run, one recovery event here
drain_evs = Counter(json.loads(l)["event"]
                    for l in open(os.path.join(out, "events.drain.jsonl")))
rec_evs = Counter(json.loads(l)["event"]
                  for l in open(os.path.join(out, "events.recover.jsonl")))
assert drain_evs["service_draining"] == 1, dict(drain_evs)
assert drain_evs.get("service_recovered", 0) == 0, dict(drain_evs)
assert rec_evs["service_recovered"] == 1, dict(rec_evs)
assert rec_evs.get("service_draining", 0) == 0, dict(rec_evs)

# the journal narrates the whole story in one file: drain-requeues
# from run 1, job_done records appended by the recovered run
kinds = Counter(json.loads(l)["kind"]
                for l in open(os.path.join(out, "journal.jsonl")))
assert kinds["job_requeued"] >= 2 and kinds["job_done"] == 2, dict(kinds)
print(f"preempt-check[{family}]: drained -> recovered bit-identical "
      f"({dict(rec_evs)})")
PYEOF

  "$PY" tools/obs_report.py "$OUT/events.drain.jsonl" --check
  "$PY" tools/obs_report.py "$OUT/events.recover.jsonl" --check
done

# --- leg 3: torn tail. Truncate the drained journal mid-record; the
# recovering service must detect the torn tail (SHA-256 + seq), drop
# it, emit journal_truncated, and still recover to identical artifacts.
# (Needs the frank drain snapshot, so skipped when PREEMPT_FAMILIES
# excludes frank.)
if [ -d "$TD/frank-torn" ]; then
JAX_PLATFORMS=cpu "$PY" - "$TD/frank-torn" <<'PYEOF'
import json
import os
import sys

import numpy as np

from flipcomplexityempirical_tpu import obs
from flipcomplexityempirical_tpu.experiments import driver as drv
from flipcomplexityempirical_tpu.experiments.config import ExperimentConfig
from flipcomplexityempirical_tpu.service import SweepService

out = sys.argv[1]
jp = os.path.join(out, "journal.jsonl")
blob = open(jp, "rb").read()
open(jp, "wb").write(blob[:-17])  # tear the last record mid-line

with obs.Recorder(os.path.join(out, "events.torn.jsonl")) as rec:
    svc = SweepService.recover(out, recorder=rec)
    n_dropped = svc.journal.dropped
    svc.run_until_idle()
assert n_dropped >= 1, "torn tail not detected"
assert svc.exit_code == 0, [(j.tag, j.status, j.error)
                            for j in svc.queue.jobs()]
evs = [json.loads(l)
       for l in open(os.path.join(out, "events.torn.jsonl"))]
assert sum(e["event"] == "journal_truncated" for e in evs) == 1, \
    "journal_truncated not emitted"

done = {j.tag: j for j in svc.queue.jobs()}
for al in (2, 1):
    cfg = ExperimentConfig(family="frank", alignment=al, base=0.3,
                           pop_tol=0.1, total_steps=120, n_chains=2,
                           backend="jax", seed=3 + al,
                           checkpoint_every=40)
    g, plan, _ = drv.build_graph_and_plan(cfg)
    ref = drv._run_jax(cfg, g, plan, None)
    got = done[cfg.tag].result
    for k in ("end_signed", "cut_times", "num_flips", "waits_all"):
        np.testing.assert_array_equal(np.asarray(got[k]),
                                      np.asarray(ref[k]), err_msg=k)
print("preempt-check[torn-tail]: detected, repaired, recovered "
      "bit-identical")
PYEOF
fi

echo "preempt-check: OK"
