#!/usr/bin/env python
"""Export an obs telemetry stream's spans to Chrome trace-event JSON.

    python tools/trace_export.py EVENTS.jsonl                # -> EVENTS.trace.json
    python tools/trace_export.py -o run.trace.json E1 E2 ...  # merge hosts
    python tools/trace_export.py --validate EVENTS.jsonl      # gate only

The output opens directly in Perfetto (ui.perfetto.dev) or
chrome://tracing: one timeline row per (host, thread), sweep → config →
run → chunk nesting visible as stacked slices, compile/anomaly/error
markers as instants, and a flips/s counter track per run. Span pairs
become "X" (complete) events — begin timestamp plus duration, immune to
the B/E ordering pitfalls — with each child's interval clamped into its
parent's so clock jitter between a span's wall-clock stamp and its
monotonic duration never renders an impossible overhang.

Multiple input files merge into one trace: each file becomes a Chrome
``pid``, parsed from the ``events.host<K>.jsonl`` per-host naming that
``distribute.sharded.host_recorder`` writes (falling back to the file's
position on the command line), so a multi-host run's per-host streams
land side by side under named process groups. ``.jsonl.gz`` sinks are
read transparently.

``--validate`` runs the same schema gate as ``obs_report.py --check``
plus the span pairing/nesting contract (every begin closed, no orphan
parents, no id reuse) and exits nonzero listing each violation, without
writing anything — the CI hook for "this stream will render".
Stdlib-only: the schema module is loaded by file path, so neither mode
imports jax (or any package) at all.
"""

from __future__ import annotations

import argparse
import gzip
import importlib.util
import json
import os
import re
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_EVENTS_PY = os.path.join(_HERE, os.pardir, "flipcomplexityempirical_tpu",
                          "obs", "events.py")

_SPAN_ENVELOPE = {"v", "ts", "event", "name", "span_id", "trace_id",
                  "parent_id", "tid", "dur_s"}

# markers worth a vertical line on the timeline even though they are
# not spans; value is the Perfetto slice scope ("p"rocess / "t"hread)
_INSTANTS = {"anomaly": "p", "error": "p", "compile": "t"}

_HOST_RE = re.compile(r"\.host(\d+)\.")


def _load_schema():
    """Load obs.events directly by path: stdlib-only, no package import
    (the package __init__ pulls jax, which an export never needs)."""
    spec = importlib.util.spec_from_file_location("_obs_events", _EVENTS_PY)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _open_text(path: str):
    if path.endswith(".gz"):
        return gzip.open(path, "rt", encoding="utf-8")
    return open(path, encoding="utf-8")


def load_events(path: str, schema):
    """Parse one stream, keeping only schema-valid lines (a crashed
    run's partial stream must still export)."""
    events, bad = [], 0
    with _open_text(path) as f:
        for line in f:
            if not line.strip():
                continue
            if schema.validate_line(line) is None:
                events.append(json.loads(line))
            else:
                bad += 1
    return events, bad


def validate(path: str, schema) -> int:
    """Schema gate + span contract for one stream; prints one line per
    violation; returns the violation count."""
    bad = n = 0
    parsed = []
    with _open_text(path) as f:
        for lineno, line in enumerate(f, 1):
            if not line.strip():
                continue
            n += 1
            err = schema.validate_line(line)
            if err is not None:
                bad += 1
                print(f"{path}:{lineno}: {err}", file=sys.stderr)
            else:
                parsed.append(json.loads(line))
    span_errors = schema.validate_spans(parsed)
    for err in span_errors:
        print(f"{path}: span contract: {err}", file=sys.stderr)
    n_spans = sum(1 for e in parsed if e["event"] == "span_begin")
    if not bad and not span_errors:
        print(f"{path}: ok ({n} events, {n_spans} spans, "
              f"schema v{schema.SCHEMA_VERSION})")
    return bad + len(span_errors)


def host_pid(path: str, index: int) -> int:
    """Chrome pid for one input file: the host id from the
    ``events.host<K>.jsonl`` per-host naming when present, else the
    file's position on the command line."""
    m = _HOST_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else index


def _span_args(begin: dict, end: dict) -> dict:
    """Merge begin tags with end results (wall_s/flips/reject/...) into
    the slice's args dict — what Perfetto shows on click."""
    args = {k: v for k, v in begin.items() if k not in _SPAN_ENVELOPE}
    args.update({k: v for k, v in end.items() if k not in _SPAN_ENVELOPE})
    return args


def file_trace_events(events, pid: int) -> list[dict]:
    """Convert one stream's events to Chrome trace events under ``pid``.

    Span pairs become "X" slices. The begin wall-clock ``ts`` and the
    monotonic ``dur_s`` come from different clocks, so a child stamped
    late can overhang its parent by a few µs; each child's interval is
    clamped into its (transitively clamped) parent's so the nesting the
    validator proved always renders as nesting. Unclosed spans (crash)
    are dropped — ``--validate`` reports them. Deferred spans
    (``emit_span_at``: the board runner's back-stamped chunks) arrive
    begin-then-end adjacent and need no special casing."""
    out = []
    open_spans: dict = {}   # span_id -> begin event
    pairs = []              # (begin, end), stream order of the begins
    for e in events:
        kind = e["event"]
        if kind == "span_begin":
            open_spans[e["span_id"]] = e
        elif kind == "span_end":
            b = open_spans.pop(e.get("span_id"), None)
            if b is not None:
                pairs.append((b, e))
        elif kind == "chunk":
            # chunk events double as samples on a per-path flips/s
            # counter track (the per-chunk throughput spread, on the
            # timeline instead of in a table)
            rate = e.get("flips_per_s")
            if isinstance(rate, (int, float)):
                out.append({
                    "name": f"flips/s [{e.get('path', '?')}]",
                    "ph": "C",
                    "ts": e["ts"] * 1e6,
                    "pid": pid,
                    "args": {"flips_per_s": rate},
                })
        elif kind in _INSTANTS:
            label = {"anomaly": e.get("kind"),
                     "error": e.get("message"),
                     "compile": e.get("fn")}.get(kind) or kind
            out.append({
                "name": f"{kind}: {label}",
                "ph": "i",
                "ts": e["ts"] * 1e6,
                "pid": pid,
                "tid": e.get("tid", 0),
                "s": _INSTANTS[kind],
                "args": {k: v for k, v in e.items()
                         if k not in ("v", "ts", "event")},
            })
    # clamp top-down: a parent's begin precedes its children's begins in
    # the stream, so sorting pairs by begin order lets each child clamp
    # against its parent's already-clamped interval
    pairs.sort(key=lambda p: p[0]["ts"])
    bounds: dict = {}       # span_id -> clamped (t0, t1)
    for b, e in pairs:
        t0 = b["ts"]
        t1 = t0 + max(e.get("dur_s") or 0.0, 0.0)
        pb = bounds.get(b.get("parent_id"))
        if pb is not None:
            t0 = min(max(t0, pb[0]), pb[1])
            t1 = min(max(t1, t0), pb[1])
        bounds[b["span_id"]] = (t0, t1)
        out.append({
            "name": b.get("name", "?"),
            "ph": "X",
            "ts": t0 * 1e6,
            "dur": (t1 - t0) * 1e6,
            "pid": pid,
            "tid": b.get("tid", 0),
            "args": _span_args(b, e),
        })
    return out


def export(paths: list[str], schema) -> dict:
    """Merge one or more streams into a single Chrome trace document."""
    trace = []
    t_min = None
    per_file = []
    for i, path in enumerate(paths):
        events, bad = load_events(path, schema)
        if bad:
            print(f"{path}: skipped {bad} malformed line(s)",
                  file=sys.stderr)
        pid = host_pid(path, i)
        per_file.append((path, pid, events))
        for e in events:
            if t_min is None or e["ts"] < t_min:
                t_min = e["ts"]
    for path, pid, events in per_file:
        trace.append({"name": "process_name", "ph": "M", "pid": pid,
                      "args": {"name": f"host{pid} "
                                       f"({os.path.basename(path)})"}})
        trace.extend(file_trace_events(events, pid))
    # rebase to t=0 so Perfetto's time axis starts at the run, not the
    # unix epoch
    if t_min is not None:
        for ev in trace:
            if "ts" in ev:
                ev["ts"] -= t_min * 1e6
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


def default_output(path: str) -> str:
    base = path
    for suffix in (".gz", ".jsonl", ".json"):
        if base.endswith(suffix):
            base = base[:-len(suffix)]
    return base + ".trace.json"


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Export obs spans to Chrome trace-event JSON "
                    "(Perfetto / chrome://tracing)")
    ap.add_argument("paths", nargs="+",
                    help="JSONL event stream(s); multiple files (e.g. "
                         "per-host events.host<K>.jsonl) merge into one "
                         "trace, one pid per file")
    ap.add_argument("-o", "--output",
                    help="output path (default: first input with a "
                         ".trace.json suffix)")
    ap.add_argument("--validate", action="store_true",
                    help="validate only (schema + span nesting), write "
                         "nothing, exit nonzero on any violation")
    args = ap.parse_args(argv)
    schema = _load_schema()

    if args.validate:
        return 1 if sum(validate(p, schema) for p in args.paths) else 0

    doc = export(args.paths, schema)
    out_path = args.output or default_output(args.paths[0])
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    n_slices = sum(1 for e in doc["traceEvents"] if e.get("ph") == "X")
    print(f"{out_path}: {len(doc['traceEvents'])} trace events "
          f"({n_slices} spans) from {len(args.paths)} stream(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
