#!/usr/bin/env python
"""Export an obs telemetry stream's spans to Chrome trace-event JSON.

    python tools/trace_export.py EVENTS.jsonl                # -> EVENTS.trace.json
    python tools/trace_export.py -o run.trace.json E1 E2 ...  # merge hosts
    python tools/trace_export.py --validate EVENTS.jsonl      # gate only
    python tools/trace_export.py --fleet ROOT [--validate]    # whole fleet

The output opens directly in Perfetto (ui.perfetto.dev) or
chrome://tracing: one timeline row per (host, thread), sweep → config →
run → chunk nesting visible as stacked slices, compile/anomaly/error
markers as instants, and a flips/s counter track per run. Span pairs
become "X" (complete) events — begin timestamp plus duration, immune to
the B/E ordering pitfalls — with each child's interval clamped into its
parent's so clock jitter between a span's wall-clock stamp and its
monotonic duration never renders an impossible overhang.

Multiple input files merge into one trace: each file becomes a Chrome
``pid``, parsed from the ``events.host<K>.jsonl`` per-host naming that
``distribute.sharded.host_recorder`` writes (falling back to the file's
position on the command line), so a multi-host run's per-host streams
land side by side under named process groups. ``.jsonl.gz`` sinks are
read transparently.

``--validate`` runs the same schema gate as ``obs_report.py --check``
plus the span pairing/nesting contract (every begin closed, no orphan
parents, no id reuse) and exits nonzero listing each violation, without
writing anything — the CI hook for "this stream will render".
Stdlib-only: the schema module is loaded by file path, so neither mode
imports jax (or any package) at all.

``--fleet ROOT`` (ISSUE 18) exports a whole fleet root at once: every
``ROOT/events/*.jsonl`` stream becomes its own Perfetto process (named
after the stream — server, w1, ...), and the cross-process trace
contexts the front door mints at submit time (``trace_id`` +
``ctx_parent_id``, adopted by workers via ``obs.adopt``) render as
Perfetto flow arrows from each job's HTTP ``submit`` span to its
worker-side spans. ``--fleet --validate`` adds the fleet parenting
gate on top of the per-stream contract: every job with a terminal
status doc must have a submit span, a worker span adopting it, and a
local child under that — while tolerating exactly the damage a
SIGKILLed worker legitimately leaves (spans never closed, one torn
final line).
"""

from __future__ import annotations

import argparse
import gzip
import importlib.util
import json
import os
import re
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_EVENTS_PY = os.path.join(_HERE, os.pardir, "flipcomplexityempirical_tpu",
                          "obs", "events.py")

_SPAN_ENVELOPE = {"v", "ts", "event", "name", "span_id", "trace_id",
                  "parent_id", "tid", "dur_s"}

# markers worth a vertical line on the timeline even though they are
# not spans; value is the Perfetto slice scope ("p"rocess / "t"hread)
_INSTANTS = {"anomaly": "p", "error": "p", "compile": "t"}

_HOST_RE = re.compile(r"\.host(\d+)\.")


def _load_schema():
    """Load obs.events directly by path: stdlib-only, no package import
    (the package __init__ pulls jax, which an export never needs)."""
    spec = importlib.util.spec_from_file_location("_obs_events", _EVENTS_PY)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _open_text(path: str):
    if path.endswith(".gz"):
        return gzip.open(path, "rt", encoding="utf-8")
    return open(path, encoding="utf-8")


def load_events(path: str, schema):
    """Parse one stream, keeping only schema-valid lines (a crashed
    run's partial stream must still export)."""
    events, bad = [], 0
    with _open_text(path) as f:
        for line in f:
            if not line.strip():
                continue
            if schema.validate_line(line) is None:
                events.append(json.loads(line))
            else:
                bad += 1
    return events, bad


def validate(path: str, schema, tolerate_crash: bool = False) -> int:
    """Schema gate + span contract for one stream; prints one line per
    violation; returns the violation count.

    ``tolerate_crash`` (fleet mode) forgives exactly what a SIGKILLed
    writer legitimately leaves behind: spans never closed, and a torn
    (malformed) FINAL line. Interior damage still fails — a crash
    truncates a stream, it does not edit the middle of one."""
    bad = n = 0
    parsed = []
    schema_errs = []        # (lineno, err)
    last_lineno = 0
    with _open_text(path) as f:
        for lineno, line in enumerate(f, 1):
            if not line.strip():
                continue
            n += 1
            last_lineno = lineno
            err = schema.validate_line(line)
            if err is not None:
                schema_errs.append((lineno, err))
            else:
                parsed.append(json.loads(line))
    if tolerate_crash:
        schema_errs = [(ln, err) for ln, err in schema_errs
                       if not (ln == last_lineno
                               and err.startswith("malformed JSON"))]
    for lineno, err in schema_errs:
        bad += 1
        print(f"{path}:{lineno}: {err}", file=sys.stderr)
    span_errors = schema.validate_spans(parsed)
    if tolerate_crash:
        span_errors = [e for e in span_errors
                       if not e.endswith("never closed")]
    for err in span_errors:
        print(f"{path}: span contract: {err}", file=sys.stderr)
    n_spans = sum(1 for e in parsed if e["event"] == "span_begin")
    if not bad and not span_errors:
        print(f"{path}: ok ({n} events, {n_spans} spans, "
              f"schema v{schema.SCHEMA_VERSION})")
    return bad + len(span_errors)


def fleet_streams(root: str) -> list:
    """The fleet root's per-process streams, sorted by name (dotfiles —
    the collector checkpoint — excluded)."""
    d = os.path.join(root, "events")
    try:
        names = sorted(n for n in os.listdir(d)
                       if n.endswith((".jsonl", ".jsonl.gz"))
                       and not n.startswith("."))
    except OSError:
        return []
    return [os.path.join(d, n) for n in names]


def _stream_name(path: str) -> str:
    base = os.path.basename(path)
    for suffix in (".gz", ".jsonl"):
        if base.endswith(suffix):
            base = base[:-len(suffix)]
    return base


def _fleet_flows(per_file) -> list:
    """Perfetto flow arrows for the cross-process trace contexts: one
    s->f pair from each submit span (the front door's, carrying the
    ``job:<id>`` trace_id) to every adopted span that names it via
    ``ctx_parent_id`` in another stream. The arrows are the rendered
    form of the propagation the fleet validator proves."""
    submits = {}            # (trace_id, span_id) -> (pid, begin event)
    for _path, pid, events in per_file:
        for e in events:
            if (e["event"] == "span_begin" and e.get("name") == "submit"
                    and e.get("trace_id")):
                submits[(e["trace_id"], e["span_id"])] = (pid, e)
    flows, fid = [], 0
    for _path, pid, events in per_file:
        for e in events:
            cpid = e.get("ctx_parent_id")
            if e["event"] != "span_begin" or cpid is None:
                continue
            src = submits.get((e.get("trace_id"), cpid))
            if src is None:
                continue
            spid, sb = src
            fid += 1
            name = str(e.get("trace_id"))
            flows.append({"name": name, "cat": "fleet", "ph": "s",
                          "id": fid, "ts": sb["ts"] * 1e6, "pid": spid,
                          "tid": sb.get("tid", 0)})
            flows.append({"name": name, "cat": "fleet", "ph": "f",
                          "bp": "e", "id": fid, "ts": e["ts"] * 1e6,
                          "pid": pid, "tid": e.get("tid", 0)})
    return flows


def validate_fleet(root: str, schema) -> int:
    """The fleet gate: per-stream contracts (crash-tolerant) plus
    end-to-end trace parenting for every job that reached a terminal
    status doc — (1) a ``submit`` span with the job's trace_id exists,
    (2) some worker stream has a span adopting it (same trace_id,
    ``ctx_parent_id`` = the submit span's id), and (3) that span has a
    local child (the job actually ran under it). Jobs without status
    docs (drained mid-flight, never claimed) are exempt: parenting is a
    claim about executed work."""
    paths = fleet_streams(root)
    if not paths:
        print(f"{root}: no event streams under events/", file=sys.stderr)
        return 1
    violations = 0
    per_stream = []
    for path in paths:
        violations += validate(path, schema, tolerate_crash=True)
        events, _bad = load_events(path, schema)
        per_stream.append((path, events))
    # index every begin across the fleet
    submits = {}            # trace_id -> begin event (server stream)
    adopted: dict = {}      # trace_id -> [(stream, begin)]
    children = set()        # (stream, parent_id) with a local child
    for path, events in per_stream:
        for e in events:
            if e["event"] != "span_begin":
                continue
            if e.get("name") == "submit" and e.get("trace_id"):
                submits[e["trace_id"]] = e
            if e.get("ctx_parent_id") is not None:
                adopted.setdefault(e.get("trace_id"), []).append(
                    (path, e))
            if e.get("parent_id") is not None:
                children.add((path, e["parent_id"]))
    status_dir = os.path.join(root, "status")
    try:
        job_ids = sorted(n[:-len(".json")]
                         for n in os.listdir(status_dir)
                         if n.endswith(".json"))
    except OSError:
        job_ids = []
    checked = 0
    for job_id in job_ids:
        trace_id = f"job:{job_id}"
        sub = submits.get(trace_id)
        if sub is None:
            print(f"{root}: {job_id}: no submit span with trace_id "
                  f"{trace_id!r}", file=sys.stderr)
            violations += 1
            continue
        links = [(p, e) for p, e in adopted.get(trace_id, ())
                 if e.get("ctx_parent_id") == sub["span_id"]]
        if not links:
            print(f"{root}: {job_id}: no worker span adopted trace "
                  f"{trace_id!r} (ctx_parent_id {sub['span_id']!r})",
                  file=sys.stderr)
            violations += 1
            continue
        if not any((p, e["span_id"]) in children for p, e in links):
            print(f"{root}: {job_id}: adopted span(s) have no local "
                  f"children — job never ran under its trace",
                  file=sys.stderr)
            violations += 1
            continue
        checked += 1
    if not violations:
        print(f"{root}: fleet ok ({len(paths)} stream(s), "
              f"{checked}/{len(job_ids)} terminal job(s) trace-parented)")
    return violations


def host_pid(path: str, index: int) -> int:
    """Chrome pid for one input file: the host id from the
    ``events.host<K>.jsonl`` per-host naming when present, else the
    file's position on the command line."""
    m = _HOST_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else index


def _span_args(begin: dict, end: dict) -> dict:
    """Merge begin tags with end results (wall_s/flips/reject/...) into
    the slice's args dict — what Perfetto shows on click."""
    args = {k: v for k, v in begin.items() if k not in _SPAN_ENVELOPE}
    args.update({k: v for k, v in end.items() if k not in _SPAN_ENVELOPE})
    return args


def file_trace_events(events, pid: int) -> list[dict]:
    """Convert one stream's events to Chrome trace events under ``pid``.

    Span pairs become "X" slices. The begin wall-clock ``ts`` and the
    monotonic ``dur_s`` come from different clocks, so a child stamped
    late can overhang its parent by a few µs; each child's interval is
    clamped into its (transitively clamped) parent's so the nesting the
    validator proved always renders as nesting. Unclosed spans (crash)
    are dropped — ``--validate`` reports them. Deferred spans
    (``emit_span_at``: the board runner's back-stamped chunks) arrive
    begin-then-end adjacent and need no special casing."""
    out = []
    open_spans: dict = {}   # span_id -> begin event
    pairs = []              # (begin, end), stream order of the begins
    for e in events:
        kind = e["event"]
        if kind == "span_begin":
            open_spans[e["span_id"]] = e
        elif kind == "span_end":
            b = open_spans.pop(e.get("span_id"), None)
            if b is not None:
                pairs.append((b, e))
        elif kind == "chunk":
            # chunk events double as samples on a per-path flips/s
            # counter track (the per-chunk throughput spread, on the
            # timeline instead of in a table)
            rate = e.get("flips_per_s")
            if isinstance(rate, (int, float)):
                out.append({
                    "name": f"flips/s [{e.get('path', '?')}]",
                    "ph": "C",
                    "ts": e["ts"] * 1e6,
                    "pid": pid,
                    "args": {"flips_per_s": rate},
                })
            # a second counter track for the device->host traffic the
            # chunk caused (optional field; summary-mode runs sit ~100x
            # under history-mode ones on the same timeline)
            rb = e.get("readback_bytes")
            if isinstance(rb, (int, float)):
                out.append({
                    "name": f"readback bytes [{e.get('path', '?')}]",
                    "ph": "C",
                    "ts": e["ts"] * 1e6,
                    "pid": pid,
                    "args": {"readback_bytes": rb},
                })
        elif kind in _INSTANTS:
            label = {"anomaly": e.get("kind"),
                     "error": e.get("message"),
                     "compile": e.get("fn")}.get(kind) or kind
            out.append({
                "name": f"{kind}: {label}",
                "ph": "i",
                "ts": e["ts"] * 1e6,
                "pid": pid,
                "tid": e.get("tid", 0),
                "s": _INSTANTS[kind],
                "args": {k: v for k, v in e.items()
                         if k not in ("v", "ts", "event")},
            })
    # clamp top-down: a parent's begin precedes its children's begins in
    # the stream, so sorting pairs by begin order lets each child clamp
    # against its parent's already-clamped interval
    pairs.sort(key=lambda p: p[0]["ts"])
    bounds: dict = {}       # span_id -> clamped (t0, t1)
    for b, e in pairs:
        t0 = b["ts"]
        t1 = t0 + max(e.get("dur_s") or 0.0, 0.0)
        pb = bounds.get(b.get("parent_id"))
        if pb is not None:
            t0 = min(max(t0, pb[0]), pb[1])
            t1 = min(max(t1, t0), pb[1])
        bounds[b["span_id"]] = (t0, t1)
        out.append({
            "name": b.get("name", "?"),
            "ph": "X",
            "ts": t0 * 1e6,
            "dur": (t1 - t0) * 1e6,
            "pid": pid,
            "tid": b.get("tid", 0),
            "args": _span_args(b, e),
        })
    return out


def export(paths: list[str], schema, fleet: bool = False) -> dict:
    """Merge one or more streams into a single Chrome trace document.
    Fleet mode names each process after its stream (server, w1, ...) —
    pids are positional, names carry the identity — and adds the
    cross-process flow arrows."""
    trace = []
    t_min = None
    per_file = []
    for i, path in enumerate(paths):
        events, bad = load_events(path, schema)
        if bad:
            print(f"{path}: skipped {bad} malformed line(s)",
                  file=sys.stderr)
        pid = i if fleet else host_pid(path, i)
        per_file.append((path, pid, events))
        for e in events:
            if t_min is None or e["ts"] < t_min:
                t_min = e["ts"]
    for path, pid, events in per_file:
        name = (_stream_name(path) if fleet
                else f"host{pid} ({os.path.basename(path)})")
        trace.append({"name": "process_name", "ph": "M", "pid": pid,
                      "args": {"name": name}})
        trace.extend(file_trace_events(events, pid))
    if fleet:
        trace.extend(_fleet_flows(per_file))
    # rebase to t=0 so Perfetto's time axis starts at the run, not the
    # unix epoch
    if t_min is not None:
        for ev in trace:
            if "ts" in ev:
                ev["ts"] -= t_min * 1e6
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


def default_output(path: str) -> str:
    base = path
    for suffix in (".gz", ".jsonl", ".json"):
        if base.endswith(suffix):
            base = base[:-len(suffix)]
    return base + ".trace.json"


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Export obs spans to Chrome trace-event JSON "
                    "(Perfetto / chrome://tracing)")
    ap.add_argument("paths", nargs="*",
                    help="JSONL event stream(s); multiple files (e.g. "
                         "per-host events.host<K>.jsonl) merge into one "
                         "trace, one pid per file")
    ap.add_argument("--fleet", metavar="ROOT",
                    help="export a fleet root: every ROOT/events/*.jsonl "
                         "stream becomes a named process, submit->worker "
                         "trace contexts render as flow arrows; with "
                         "--validate, gates end-to-end trace parenting")
    ap.add_argument("-o", "--output",
                    help="output path (default: first input with a "
                         ".trace.json suffix; fleet mode: "
                         "ROOT/fleet.trace.json)")
    ap.add_argument("--validate", action="store_true",
                    help="validate only (schema + span nesting), write "
                         "nothing, exit nonzero on any violation")
    args = ap.parse_args(argv)
    if bool(args.paths) == bool(args.fleet):
        ap.error("pass either event stream paths or --fleet ROOT")
    schema = _load_schema()

    if args.validate:
        if args.fleet:
            return 1 if validate_fleet(args.fleet, schema) else 0
        return 1 if sum(validate(p, schema) for p in args.paths) else 0

    paths = fleet_streams(args.fleet) if args.fleet else args.paths
    if not paths:
        print(f"{args.fleet}: no event streams under events/",
              file=sys.stderr)
        return 1
    doc = export(paths, schema, fleet=bool(args.fleet))
    out_path = args.output or (
        os.path.join(args.fleet, "fleet.trace.json") if args.fleet
        else default_output(paths[0]))
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    n_slices = sum(1 for e in doc["traceEvents"] if e.get("ph") == "X")
    n_flows = sum(1 for e in doc["traceEvents"] if e.get("ph") == "s")
    extra = f", {n_flows} trace link(s)" if args.fleet else ""
    print(f"{out_path}: {len(doc['traceEvents'])} trace events "
          f"({n_slices} spans{extra}) from {len(paths)} stream(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
