#!/usr/bin/env bash
# Fault-tolerance CI gate (`make chaos-check`): the ISSUE 7 acceptance
# scenario end to end, on CPU.
#
#   1. graftlint over the package + tools (G007 retry/timeout hygiene
#      rides the same run as the emit/sync/RNG contracts)
#   2. a seeded chaos sweep through the supervised CLI: one checkpoint
#      write failure + one torn checkpoint part + one segment failure
#      across a 3-config frank sweep — every config must complete and
#      every artifact must be byte-identical to a fault-free reference
#      sweep (retries resume from checkpoints; the torn part forces the
#      checksum fallback to the previous generation)
#   3. the chaos run's event stream passes obs_report --check, carries
#      retry + checkpoint_corrupt events, survives trace_export
#      --validate, and obs_report --strict (with the heartbeat probe)
#      exits 0 — recovered-from faults are not health failures
#   4. a poison config (segment.step:always) is quarantined: the CLI
#      exits nonzero and emits config_quarantined; obs_report --strict
#      then fails on that stream
#
#   tools/chaos_check.sh
#
# Exercised by tests/test_resilience.py, so tier-1 fails when any gate
# rots.
set -euo pipefail

cd "$(dirname "$0")/.."
PY="${PYTHON:-python}"
tmp="$(mktemp -d)"
trap 'rm -rf "$tmp"' EXIT

"$PY" -m tools.graftlint flipcomplexityempirical_tpu tools

SWEEP_ARGS=(--family frank --steps 60 --chains 2 --checkpoint-every 20
            --cpu --only 2B30P50 1B30P50 0B30P50)

# fault-free reference sweep (supervised CLI, no plan installed)
GRAFT_FAULTS= "$PY" -m flipcomplexityempirical_tpu.experiments \
    "${SWEEP_ARGS[@]}" --out "$tmp/clean" \
    --checkpoint-dir "$tmp/ck_clean" \
    --heartbeat "$tmp/heartbeat_clean.json" > /dev/null

# the chaos sweep: fail save 1, tear a part of save 2, fail segment 4 —
# all absorbed by retries + the checksum fallback, same seed, same bits
# (fault-site names here are G013-checked against FAULT_SITES)
"$PY" -m flipcomplexityempirical_tpu.experiments \
    "${SWEEP_ARGS[@]}" --out "$tmp/fault" --checkpoint-dir "$tmp/ck" \
    --faults 'checkpoint.write:once,checkpoint.write:truncate@3,segment.step:once@4,seed=7' \
    --events "$tmp/chaos_events.jsonl" \
    --heartbeat "$tmp/heartbeat.json" > /dev/null

for f in "$tmp"/clean/*; do
    cmp "$f" "$tmp/fault/$(basename "$f")" \
        || { echo "chaos-check: artifact diverged: $(basename "$f")"; exit 1; }
done

"$PY" tools/obs_report.py --check "$tmp/chaos_events.jsonl"
"$PY" tools/trace_export.py --validate "$tmp/chaos_events.jsonl"
"$PY" tools/obs_report.py --strict \
    --heartbeat "$tmp/heartbeat.json" \
    "$tmp/chaos_events.jsonl" > /dev/null
"$PY" - "$tmp/chaos_events.jsonl" <<'PYEOF'
import json
import sys

kinds = {}
with open(sys.argv[1], encoding="utf-8") as f:
    for line in f:
        e = json.loads(line)
        kinds[e["event"]] = kinds.get(e["event"], 0) + 1
assert kinds.get("retry", 0) == 2, kinds
assert kinds.get("checkpoint_corrupt", 0) == 1, kinds
assert kinds.get("config_quarantined", 0) == 0, kinds
summary = [json.loads(l) for l in open(sys.argv[1], encoding="utf-8")
           if '"sweep_summary"' in l][-1]
assert summary["completed"] == 3 and summary["retried"] == 2, summary
print("chaos-check: chaos stream OK "
      f"(retries={kinds['retry']}, corrupt={kinds['checkpoint_corrupt']})")
PYEOF

# poison: a config that fails deterministically every attempt must be
# quarantined with a nonzero exit, not retried forever
set +e
"$PY" -m flipcomplexityempirical_tpu.experiments \
    --family frank --steps 40 --chains 2 --cpu --only 0B30P50 \
    --out "$tmp/poison" --faults 'segment.step:always' \
    --quarantine-after 2 \
    --events "$tmp/poison_events.jsonl" > /dev/null
poison_rc=$?
set -e
[ "$poison_rc" -ne 0 ] \
    || { echo "chaos-check: poison sweep exited 0"; exit 1; }
grep -q '"config_quarantined"' "$tmp/poison_events.jsonl" \
    || { echo "chaos-check: no config_quarantined event"; exit 1; }
set +e
"$PY" tools/obs_report.py --strict "$tmp/poison_events.jsonl" > /dev/null
strict_rc=$?
set -e
[ "$strict_rc" -ne 0 ] \
    || { echo "chaos-check: --strict passed a quarantined stream"; exit 1; }

echo "chaos-check: OK"
