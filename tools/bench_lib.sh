# Shared helpers for the TPU capture scripts (tpu_capture.sh,
# tpu_followup_r5.sh). Source from a script whose cwd is the repo root
# and which has set TS.
#
# commit_retry FILE...   - git add+commit with retries (tunnel scripts
#                          race the session's own commits)
# run_bench NAME TMO ARGS... - run bench.py, validate the record, rename
#                          cpu_fallback output to *.fallback (a host
#                          number must never sit under an on-chip record
#                          name), commit on success. Returns 1 on any
#                          failure so callers can abort or continue.

commit_retry() {
  for _ in 1 2 3 4 5; do
    git add "$@" && git commit -q -m "TPU capture: $(basename "$1")

No-Verification-Needed: benchmark-record artifacts only" && return 0
    sleep 7
  done
  return 1
}

run_bench() { # name timeout args...
  local name=$1 tmo=$2; shift 2
  local out="bench_runs/${TS}_${name}.json" err="bench_runs/${TS}_${name}.err"
  timeout "$tmo" python bench.py "$@" >"$out" 2>"$err"
  local rc=$?
  if [ $rc -ne 0 ] || [ ! -s "$out" ]; then
    echo "capture $name: rc=$rc, no record" >&2
    return 1
  fi
  if grep -q cpu_fallback "$out"; then
    mv "$out" "$out.fallback"
    echo "capture $name: tunnel dropped (cpu_fallback)" >&2
    return 1
  fi
  commit_retry "$out" "$err"
}
