# Shared helpers for the TPU capture scripts (tpu_capture.sh,
# tpu_followup_r5.sh, tpu_refresh_r5.sh, tpu_tail_r5.sh). Source from a
# script whose cwd is the repo root; run_bench sets per-record filenames
# from the caller's TS.
#
# commit_retry FILE...   - git add+commit with retries (tunnel scripts
#                          race the session's own commits)
# run_bench NAME TMO ARGS... - run bench.py, validate the record, commit
#                          on success. Returns 1 on any failure so
#                          callers can abort or continue. Failure modes
#                          are QUARANTINED by rename so *.json globs and
#                          the have()/count gates in tpu_tail_r5.sh only
#                          ever see real committed-shape records:
#                            *.failed      rc!=0 or empty output
#                            *.fallback    cpu_fallback record (a host
#                                          number must never sit under an
#                                          on-chip record name)
#                            *.suspect     vs_baseline below the caller's
#                                          floor (degrading-tunnel reading
#                                          - see the 0.90M pallas refresh
#                                          post-mortem in PROFILE.md)
#                            *.uncommitted record valid but commit_retry
#                                          exhausted (retried next window;
#                                          the driver's end-of-round sweep
#                                          picks up the file either way)
# run_bench_min VSB NAME TMO ARGS... - run_bench with a vs_baseline
#                          acceptance floor VSB.
# pick_health_record BASE - print the record usable as a window-health
#                          reading (committed file, else the .uncommitted
#                          quarantine); nothing (rc 1) when only
#                          not-a-reading quarantine shapes exist.

commit_retry() {
  # pathspec'd commit: never sweeps up unrelated staged work from the
  # racing session, and the final unstage keeps index and disk
  # consistent when the caller quarantine-renames the file afterwards
  for _ in 1 2 3 4 5; do
    git add "$@" && git commit -q -m "TPU capture: $(basename "$1")

No-Verification-Needed: benchmark-record artifacts only" -- "$@" && return 0
    sleep 7
  done
  git restore --staged -- "$@" 2>/dev/null || true
  return 1
}

vsb_at_least() { # file floor: record's vs_baseline >= floor (null/absent=0)
  [ -s "$1" ] && python - "$1" "$2" <<'EOF'
import json, sys
rec = json.load(open(sys.argv[1]))
sys.exit(0 if (rec.get("vs_baseline") or 0) >= float(sys.argv[2]) else 1)
EOF
}

pick_health_record() { # base: committed record, else .uncommitted
  # a validated record whose commit lost the git race is still a TRUE
  # health reading, so the .uncommitted quarantine gates fine; the other
  # quarantine shapes are explicitly NOT readings (.failed: bench died
  # with no record; .fallback: a host number; .suspect: already judged
  # below its floor) — print nothing so the caller treats the window as
  # unhealthy outright instead of leaning on vsb_at_least's missing-file
  # behavior (ADVICE r5)
  local f
  for f in "$1" "$1.uncommitted"; do
    if [ -s "$f" ]; then printf '%s\n' "$f"; return 0; fi
  done
  return 1
}

RB_MIN_VSB=""

run_bench() { # name timeout args...
  local name=$1 tmo=$2; shift 2
  local out="bench_runs/${TS}_${name}.json" err="bench_runs/${TS}_${name}.err"
  timeout "$tmo" python bench.py "$@" >"$out" 2>"$err"
  local rc=$?
  if [ $rc -ne 0 ] || [ ! -s "$out" ]; then
    [ -e "$out" ] && mv "$out" "$out.failed"
    echo "capture $name: rc=$rc, no record" >&2
    return 1
  fi
  if grep -q cpu_fallback "$out"; then
    mv "$out" "$out.fallback"
    echo "capture $name: tunnel dropped (cpu_fallback)" >&2
    return 1
  fi
  if [ -n "$RB_MIN_VSB" ] && ! vsb_at_least "$out" "$RB_MIN_VSB"; then
    mv "$out" "$out.suspect"
    echo "capture $name: vs_baseline under the $RB_MIN_VSB floor" \
         "(degrading window?); quarantined for a retry" >&2
    return 1
  fi
  if ! commit_retry "$out" "$err"; then
    mv "$out" "$out.uncommitted"
    echo "capture $name: record valid but commit failed; quarantined" >&2
    return 1
  fi
}

run_bench_min() { # vs_baseline_floor name timeout args...
  RB_MIN_VSB=$1; shift
  run_bench "$@"
  local rc=$?
  RB_MIN_VSB=""
  return $rc
}
