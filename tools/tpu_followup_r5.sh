#!/bin/bash
# Round-5 follow-up capture set, for the NEXT tunnel window. The primary
# records (default/sweep/ess/general) are already committed; this runs
# what the first window could not finish, in priority order. Each record
# commits as it lands (same policy as tpu_capture.sh).
set -u
cd "$(dirname "$0")/.."
mkdir -p bench_runs
TS=$(date -u +%Y%m%dT%H%M%SZ)

commit_retry() {
  for _ in 1 2 3 4 5; do
    git add "$@" && git commit -q -m "TPU follow-up: $(basename "$1")

No-Verification-Needed: benchmark-record artifacts only" && return 0
    sleep 7
  done
  return 1
}

run_bench() { # name timeout args...
  local name=$1 tmo=$2; shift 2
  local out="bench_runs/${TS}_${name}.json" err="bench_runs/${TS}_${name}.err"
  timeout "$tmo" python bench.py "$@" >"$out" 2>"$err"
  local rc=$?
  if [ $rc -ne 0 ] || [ ! -s "$out" ] || grep -q cpu_fallback "$out"; then
    echo "followup $name: rc=$rc or fallback; keeping evidence uncommitted" >&2
    return 1
  fi
  commit_retry "$out" "$err"
}

# 1. C=16384 at the default chunk=500 - the flip-log slicing fix should
#    now fit 16G HBM; compare against the committed chunk=250 record
run_bench c16384_chunk500 1800 --chains 16384
# 2. int8 (v4) body on-chip record for the v4-vs-v5 comparison row
run_bench body_int8 900 --body int8
# 3. C=8192 epilogue amortization at chunk=1000
run_bench c8192_chunk1000 1200 --chains 8192 --chunk 1000 --warmup 1001
# 4. Mosaic probes the first window could not finish (prng-in-loop)
timeout 600 python /tmp/probe4.py >"bench_runs/${TS}_probe4.txt" 2>&1
# 5. Pallas compile retry + exactness (expected: Mosaic SIGABRT; any
#    change in outcome is news)
timeout 600 python tools/pallas_exact.py \
  >"bench_runs/${TS}_pallas_exact.json" 2>"bench_runs/${TS}_pallas_exact.err"
commit_retry "bench_runs/${TS}_probe4.txt" \
  "bench_runs/${TS}_pallas_exact.json" "bench_runs/${TS}_pallas_exact.err" || true
echo "follow-up set done: ${TS}"
