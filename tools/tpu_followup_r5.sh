#!/bin/bash
# Round-5 follow-up capture set, for the NEXT tunnel window. The primary
# records (default/sweep/ess/general) are already committed; this runs
# what the first window could not finish, in priority order. Helpers
# (record validation, fallback quarantine, commit-per-record) are shared
# with tpu_capture.sh via bench_lib.sh.
set -u
cd "$(dirname "$0")/.."
mkdir -p bench_runs
TS=$(date -u +%Y%m%dT%H%M%SZ)
. tools/bench_lib.sh

# 1. C=16384 at the default chunk=500 - the flip-log slicing fix should
#    now fit 16G HBM; compare against the committed chunk=250 record
run_bench c16384_chunk500 1800 --chains 16384
# 2. int8 (v4) body on-chip record for the v4-vs-v5 comparison row
run_bench body_int8 900 --body int8
# 3. C=8192 epilogue amortization at chunk=1000
run_bench c8192_chunk1000 1200 --chains 8192 --chunk 1000 --warmup 1001
# 4. k-district pair walk on-chip records (BASELINE config 2)
run_bench pair_k4 900 --k 4
run_bench pair_k8 900 --k 8
# 4b. ESS with on-device diagnostics (readback-free recorded pass)
run_bench ess_device 900 --ess
# 5. Mosaic probes the first window could not finish (prng-in-loop)
timeout 600 python tools/mosaic_probes.py >"bench_runs/${TS}_probes.txt" 2>&1
# 6. Pallas compile retry + exactness (expected: Mosaic SIGABRT; any
#    change in outcome is news)
timeout 600 python tools/pallas_exact.py \
  >"bench_runs/${TS}_pallas_exact.json" 2>"bench_runs/${TS}_pallas_exact.err"
commit_retry "bench_runs/${TS}_probes.txt" \
  "bench_runs/${TS}_pallas_exact.json" "bench_runs/${TS}_pallas_exact.err" || true
echo "follow-up set done: ${TS}"
