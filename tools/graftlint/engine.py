"""Rule engine: file discovery, pragma handling, rule dispatch, the
whole-program stage, and the content-hash result cache.

Two kinds of rules live in the registry:

* **per-file rules** (G001–G010, G014): ``check(module, config)`` over one
  ``ParsedModule`` — embarrassingly parallel, cacheable per file.
* **program rules** (G011–G013, ``PROGRAM = True``): ``check_program(
  program, config)`` over the cross-module :class:`~.program.Program`
  index — one pass per lint run, cacheable against the digest of every
  input file (any edit anywhere invalidates it, as an interprocedural
  result must be).

The cache (``.graftlint_cache.json`` at the repo root, git-ignored)
keys per-file results on the file's content hash and the whole-program
result on the sorted digest of all inputs, both salted with a
fingerprint of graftlint's own sources so editing a rule re-lints
everything. ``--jobs N`` farms cache-miss per-file work to a process
pool; the program stage is one index build and stays in-process.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re
from typing import Iterable, Iterator, List, Optional, Tuple

from .findings import Finding

# ``# graftlint: disable=G001(reason),G002`` — reasons are free text in
# balanced-paren-free parens; ``# graftlint: traced`` marks the next (or
# same) line's ``def`` as a traced context; ``# graftlint:
# guarded-by(<lock>: <reason>)`` declares an intentionally lock-free
# attribute for G011 (on the attribute's assignment line, or the
# preceding comment line).
_PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*(disable=([^#]*)|traced(?:\s*\([^)]*\))?"
    r"|guarded-by\s*\(([^)]*)\))\s*$")
_RULE_TOKEN_RE = re.compile(r"(G\d{3}|all)(?:\(([^)]*)\))?")

# Directory names never linted when walking (fixtures are deliberately
# violating sources; lint_file() bypasses this filter).
EXCLUDED_DIRS = frozenset({"__pycache__", ".git", "fixtures", ".venv",
                           "build", "dist"})

CACHE_FILE = ".graftlint_cache.json"
_CACHE_VERSION = 2


@dataclasses.dataclass
class LintConfig:
    root: str = "."                  # repo root; paths reported relative to it
    max_test_steps: int = 5000       # G006: unmarked tests may step <= this
    rules: Optional[frozenset] = None  # restrict to these rule ids (tests)
    jobs: int = 1                    # per-file process parallelism
    cache: bool = True               # content-hash result cache


class Pragmas:
    """Per-file suppression map parsed from ``# graftlint:`` comments.

    A ``disable=`` pragma suppresses the named rules on its own line; on
    a comment-only line it suppresses them on the next non-blank source
    line instead. ``traced`` marks the next/same line for the traced-
    context seeder; ``guarded-by(...)`` annotates the next/same line's
    attribute for G011's intentional-lock-free exemption.
    """

    def __init__(self, source_lines: List[str]):
        self._disabled: dict = {}     # lineno -> set of rule ids / {"all"}
        self.reasons: dict = {}       # (lineno, rule) -> reason text
        self.traced_lines: set = set()
        self.guarded: dict = {}       # lineno -> guarded-by payload text
        pending: List[tuple] = []     # comment-only pragmas awaiting code
        pending_traced = False
        pending_guard: Optional[str] = None
        for i, raw in enumerate(source_lines, start=1):
            stripped = raw.strip()
            m = _PRAGMA_RE.search(raw)
            comment_only = stripped.startswith("#")
            code_line = bool(stripped) and not comment_only
            if code_line:
                for rule, reason in pending:
                    self._disabled.setdefault(i, set()).add(rule)
                    if reason:
                        self.reasons[(i, rule)] = reason
                pending = []
                if pending_traced:
                    self.traced_lines.add(i)
                    pending_traced = False
                if pending_guard is not None:
                    self.guarded[i] = pending_guard
                    pending_guard = None
            if not m:
                continue
            if m.group(1).startswith("guarded-by"):
                payload = (m.group(3) or "").strip()
                if comment_only:
                    pending_guard = payload
                else:
                    self.guarded[i] = payload
                continue
            if m.group(1).startswith("traced"):
                if comment_only:
                    pending_traced = True
                else:
                    self.traced_lines.add(i)
                continue
            for rm in _RULE_TOKEN_RE.finditer(m.group(2) or ""):
                rule, reason = rm.group(1), (rm.group(2) or "").strip()
                if comment_only:
                    pending.append((rule, reason))
                else:
                    self._disabled.setdefault(i, set()).add(rule)
                    if reason:
                        self.reasons[(i, rule)] = reason

    def suppressed(self, rule: str, line: int) -> bool:
        active = self._disabled.get(line, ())
        return rule in active or "all" in active


class ParsedModule:
    """One source file, parsed once and shared by every rule."""

    def __init__(self, abspath: str, relpath: str, source: str):
        self.abspath = abspath
        self.path = relpath                       # posix, repo-relative
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        self.pragmas = Pragmas(self.lines)
        self._traced = None

    @property
    def is_test(self) -> bool:
        base = os.path.basename(self.path)
        return base.startswith("test_") or base == "conftest.py"

    @property
    def traced_functions(self):
        if self._traced is None:
            from .astutil import collect_traced_functions
            self._traced = collect_traced_functions(
                self.tree, frozenset(self.pragmas.traced_lines))
        return self._traced

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(rule=rule, path=self.path, line=line,
                       col=getattr(node, "col_offset", 0), message=message,
                       snippet=self.snippet(line))


class ShellFile:
    """A gate script the program stage scans (G013 fault plans)."""

    def __init__(self, abspath: str, relpath: str, source: str):
        self.abspath = abspath
        self.path = relpath
        self.source = source
        self.lines = source.splitlines()
        self.pragmas = Pragmas(self.lines)

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, line: int, col: int,
                message: str) -> Finding:
        return Finding(rule=rule, path=self.path, line=line, col=col,
                       message=message, snippet=self.snippet(line))


def _relpath(path: str, root: str) -> str:
    try:
        rel = os.path.relpath(os.path.abspath(path), os.path.abspath(root))
    except ValueError:
        rel = path
    return rel.replace(os.sep, "/")


def _iter_files(paths: Iterable[str], suffix: str) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(suffix):
                yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in EXCLUDED_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(suffix):
                    yield os.path.join(dirpath, fn)


def iter_py_files(paths: Iterable[str], root: str) -> Iterator[str]:
    yield from _iter_files(paths, ".py")


def iter_sh_files(paths: Iterable[str], root: str) -> Iterator[str]:
    yield from _iter_files(paths, ".sh")


# -- rule dispatch -----------------------------------------------------


def _split_rules():
    from .rules import RULES
    per_file = [r for r in RULES if not getattr(r, "PROGRAM", False)]
    program = [r for r in RULES if getattr(r, "PROGRAM", False)]
    return per_file, program


def _selected(rule, config: LintConfig, module=None) -> bool:
    if config.rules is not None:
        # explicit rule selection (fixture tests) bypasses the
        # path-scoping in applies()
        return rule.RULE_ID in config.rules
    if module is not None:
        return rule.applies(module)
    return True


def _check_module(module: ParsedModule,
                  config: LintConfig) -> List[Finding]:
    per_file, _ = _split_rules()
    findings: List[Finding] = []
    for rule in per_file:
        if not _selected(rule, config, module):
            continue
        for f in rule.check(module, config):
            if not module.pragmas.suppressed(f.rule, f.line):
                findings.append(f)
    return findings


def _check_program(modules: dict, shell_files: List[ShellFile],
                   config: LintConfig) -> List[Finding]:
    _, program_rules = _split_rules()
    active = [r for r in program_rules if _selected(r, config)]
    if not active:
        return []
    from .program import build_program
    program = build_program(modules, shell_files)
    findings: List[Finding] = []
    by_path = {m.path: m for m in modules.values()}
    by_path.update({sf.path: sf for sf in shell_files})
    for rule in active:
        for f in rule.check_program(program, config):
            owner = by_path.get(f.path)
            if owner is not None and owner.pragmas.suppressed(f.rule,
                                                              f.line):
                continue
            findings.append(f)
    return findings


def _parse_module(path: str, config: LintConfig):
    """Returns (ParsedModule | None, [G000 findings])."""
    relpath = _relpath(path, config.root)
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    try:
        return ParsedModule(os.path.abspath(path), relpath, source), []
    except SyntaxError as exc:
        return None, [Finding(rule="G000", path=relpath,
                              line=exc.lineno or 1,
                              col=(exc.offset or 1) - 1,
                              message=f"syntax error: {exc.msg}")]


def lint_file(path: str, config: Optional[LintConfig] = None
              ) -> List[Finding]:
    """Lint one file, bypassing directory exclusions (used on
    fixtures). Program rules run over a single-file program, so a
    fixture exercises G011–G013 without the rest of the tree."""
    config = config or LintConfig()
    findings: List[Finding] = []
    modules: dict = {}
    shell_files: List[ShellFile] = []
    if path.endswith(".sh"):
        relpath = _relpath(path, config.root)
        with open(path, "r", encoding="utf-8") as fh:
            shell_files.append(ShellFile(os.path.abspath(path), relpath,
                                         fh.read()))
    else:
        module, g000 = _parse_module(path, config)
        if module is None:
            return g000
        modules[module.path] = module
        findings.extend(_check_module(module, config))
    findings.extend(_check_program(modules, shell_files, config))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


# -- cache -------------------------------------------------------------

_PACK_FP: Optional[str] = None


def _pack_fingerprint() -> str:
    """Digest of graftlint's own sources: editing the linter
    invalidates every cached result."""
    global _PACK_FP
    if _PACK_FP is None:
        h = hashlib.sha1()
        pkg = os.path.dirname(os.path.abspath(__file__))
        names = []
        for dirpath, dirnames, filenames in os.walk(pkg):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    names.append(os.path.join(dirpath, fn))
        for name in sorted(names):
            with open(name, "rb") as fh:
                h.update(name.encode() + b"\0" + fh.read() + b"\0")
        _PACK_FP = h.hexdigest()
    return _PACK_FP


def _sha_file(path: str) -> str:
    with open(path, "rb") as fh:
        return hashlib.sha1(fh.read()).hexdigest()


def _load_cache(path: str) -> dict:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
        if (doc.get("v") == _CACHE_VERSION
                and doc.get("pack") == _pack_fingerprint()
                and isinstance(doc.get("files"), dict)):
            return doc
    except (OSError, ValueError):
        pass
    return {"v": _CACHE_VERSION, "pack": _pack_fingerprint(),
            "files": {}, "program": {}}


def _save_cache(path: str, doc: dict) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, sort_keys=True)
        os.replace(tmp, path)
    except OSError:
        try:
            os.remove(tmp)
        except OSError:
            pass


def _finding_to_dict(f: Finding) -> dict:
    return dataclasses.asdict(f)


def _finding_from_dict(d: dict) -> Finding:
    return Finding(rule=d["rule"], path=d["path"], line=d["line"],
                   col=d["col"], message=d["message"],
                   snippet=d.get("snippet", ""))


def _pool_lint_one(args: Tuple[str, str, int]) -> Tuple[str, list]:
    """Process-pool worker: per-file rules for one path."""
    path, root, max_test_steps = args
    config = LintConfig(root=root, max_test_steps=max_test_steps)
    module, g000 = _parse_module(path, config)
    if module is None:
        return path, [_finding_to_dict(f) for f in g000]
    return path, [_finding_to_dict(f)
                  for f in _check_module(module, config)]


# -- whole-run driver --------------------------------------------------


def run_lint(paths: Iterable[str], config: Optional[LintConfig] = None
             ) -> List[Finding]:
    config = config or LintConfig()
    py_files = list(dict.fromkeys(iter_py_files(paths, config.root)))
    sh_files = list(dict.fromkeys(iter_sh_files(paths, config.root)))

    use_cache = config.cache and config.rules is None
    cache_path = os.path.join(config.root, CACHE_FILE)
    cache = _load_cache(cache_path) if use_cache else {
        "v": _CACHE_VERSION, "pack": _pack_fingerprint(), "files": {},
        "program": {}}

    shas = {p: _sha_file(p) for p in py_files + sh_files}
    rel = {p: _relpath(p, config.root) for p in py_files + sh_files}

    findings: List[Finding] = []
    new_files: dict = {}
    misses: List[str] = []
    for p in py_files:
        entry = cache["files"].get(rel[p])
        if use_cache and entry and entry.get("sha") == shas[p]:
            cached = [_finding_from_dict(d) for d in entry["findings"]]
            findings.extend(cached)
            new_files[rel[p]] = entry
        else:
            misses.append(p)

    per_file_results: dict = {}
    if misses and config.jobs > 1:
        import multiprocessing

        with multiprocessing.Pool(config.jobs) as pool:
            for path, dicts in pool.imap_unordered(
                    _pool_lint_one,
                    [(p, config.root, config.max_test_steps)
                     for p in misses]):
                per_file_results[path] = [
                    _finding_from_dict(d) for d in dicts]
    else:
        for p in misses:
            module, g000 = _parse_module(p, config)
            if module is None:
                per_file_results[p] = g000
            else:
                per_file_results[p] = _check_module(module, config)

    for p in misses:
        fs = per_file_results[p]
        findings.extend(fs)
        new_files[rel[p]] = {"sha": shas[p],
                             "findings": [_finding_to_dict(f)
                                          for f in fs]}
    for p in sh_files:
        new_files[rel[p]] = {"sha": shas[p], "findings": []}

    # program stage: keyed on every input's digest
    h = hashlib.sha1()
    for p in sorted(py_files + sh_files, key=lambda q: rel[q]):
        h.update(f"{rel[p]}:{shas[p]}\n".encode())
    program_key = h.hexdigest()

    prog_entry = cache.get("program") or {}
    if use_cache and prog_entry.get("key") == program_key:
        findings.extend(_finding_from_dict(d)
                        for d in prog_entry["findings"])
        new_program = prog_entry
    else:
        modules: dict = {}
        for p in py_files:
            module, _ = _parse_module(p, config)
            if module is not None:
                modules[module.path] = module
        shell_objs: List[ShellFile] = []
        for p in sh_files:
            with open(p, "r", encoding="utf-8") as fh:
                shell_objs.append(ShellFile(os.path.abspath(p), rel[p],
                                            fh.read()))
        prog_findings = _check_program(modules, shell_objs, config)
        findings.extend(prog_findings)
        new_program = {"key": program_key,
                       "findings": [_finding_to_dict(f)
                                    for f in prog_findings]}

    if use_cache:
        _save_cache(cache_path, {"v": _CACHE_VERSION,
                                 "pack": _pack_fingerprint(),
                                 "files": new_files,
                                 "program": new_program})

    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
