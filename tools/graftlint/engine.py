"""Rule engine: file discovery, pragma handling, and rule dispatch."""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Iterable, Iterator, List, Optional

from .findings import Finding

# ``# graftlint: disable=G001(reason),G002`` — reasons are free text in
# balanced-paren-free parens; ``# graftlint: traced`` marks the next (or
# same) line's ``def`` as a traced context.
_PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*(disable=([^#]*)|traced(?:\s*\([^)]*\))?)\s*$")
_RULE_TOKEN_RE = re.compile(r"(G\d{3}|all)(?:\(([^)]*)\))?")

# Directory names never linted when walking (fixtures are deliberately
# violating sources; lint_file() bypasses this filter).
EXCLUDED_DIRS = frozenset({"__pycache__", ".git", "fixtures", ".venv",
                           "build", "dist"})


@dataclasses.dataclass
class LintConfig:
    root: str = "."                  # repo root; paths reported relative to it
    max_test_steps: int = 5000       # G006: unmarked tests may step <= this
    rules: Optional[frozenset] = None  # restrict to these rule ids (tests)


class Pragmas:
    """Per-file suppression map parsed from ``# graftlint:`` comments.

    A ``disable=`` pragma suppresses the named rules on its own line; on
    a comment-only line it suppresses them on the next non-blank source
    line instead. ``traced`` marks the next/same line for the traced-
    context seeder.
    """

    def __init__(self, source_lines: List[str]):
        self._disabled: dict = {}     # lineno -> set of rule ids / {"all"}
        self.reasons: dict = {}       # (lineno, rule) -> reason text
        self.traced_lines: set = set()
        pending: List[tuple] = []     # comment-only pragmas awaiting code
        pending_traced = False
        for i, raw in enumerate(source_lines, start=1):
            stripped = raw.strip()
            m = _PRAGMA_RE.search(raw)
            comment_only = stripped.startswith("#")
            code_line = bool(stripped) and not comment_only
            if code_line:
                for rule, reason in pending:
                    self._disabled.setdefault(i, set()).add(rule)
                    if reason:
                        self.reasons[(i, rule)] = reason
                pending = []
                if pending_traced:
                    self.traced_lines.add(i)
                    pending_traced = False
            if not m:
                continue
            if m.group(1).startswith("traced"):
                if comment_only:
                    pending_traced = True
                else:
                    self.traced_lines.add(i)
                continue
            for rm in _RULE_TOKEN_RE.finditer(m.group(2) or ""):
                rule, reason = rm.group(1), (rm.group(2) or "").strip()
                if comment_only:
                    pending.append((rule, reason))
                else:
                    self._disabled.setdefault(i, set()).add(rule)
                    if reason:
                        self.reasons[(i, rule)] = reason

    def suppressed(self, rule: str, line: int) -> bool:
        active = self._disabled.get(line, ())
        return rule in active or "all" in active


class ParsedModule:
    """One source file, parsed once and shared by every rule."""

    def __init__(self, abspath: str, relpath: str, source: str):
        self.abspath = abspath
        self.path = relpath                       # posix, repo-relative
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        self.pragmas = Pragmas(self.lines)
        self._traced = None

    @property
    def is_test(self) -> bool:
        base = os.path.basename(self.path)
        return base.startswith("test_") or base == "conftest.py"

    @property
    def traced_functions(self):
        if self._traced is None:
            from .astutil import collect_traced_functions
            self._traced = collect_traced_functions(
                self.tree, frozenset(self.pragmas.traced_lines))
        return self._traced

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(rule=rule, path=self.path, line=line,
                       col=getattr(node, "col_offset", 0), message=message,
                       snippet=self.snippet(line))


def _relpath(path: str, root: str) -> str:
    try:
        rel = os.path.relpath(os.path.abspath(path), os.path.abspath(root))
    except ValueError:
        rel = path
    return rel.replace(os.sep, "/")


def iter_py_files(paths: Iterable[str], root: str) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in EXCLUDED_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def lint_file(path: str, config: Optional[LintConfig] = None
              ) -> List[Finding]:
    """Lint one file, bypassing directory exclusions (used on fixtures)."""
    from .rules import RULES
    config = config or LintConfig()
    relpath = _relpath(path, config.root)
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    try:
        module = ParsedModule(os.path.abspath(path), relpath, source)
    except SyntaxError as exc:
        return [Finding(rule="G000", path=relpath,
                        line=exc.lineno or 1, col=(exc.offset or 1) - 1,
                        message=f"syntax error: {exc.msg}")]
    findings: List[Finding] = []
    for rule in RULES:
        if config.rules is not None:
            # explicit rule selection (fixture tests) bypasses the
            # path-scoping in applies()
            if rule.RULE_ID not in config.rules:
                continue
        elif not rule.applies(module):
            continue
        for f in rule.check(module, config):
            if not module.pragmas.suppressed(f.rule, f.line):
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def run_lint(paths: Iterable[str], config: Optional[LintConfig] = None
             ) -> List[Finding]:
    config = config or LintConfig()
    findings: List[Finding] = []
    for path in iter_py_files(paths, config.root):
        findings.extend(lint_file(path, config))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
