"""Baseline file: grandfathered finding fingerprints.

The baseline is a JSON document committed at the repo root. Findings
whose fingerprint appears in it are reported as grandfathered and do not
fail the gate; everything else does. Fingerprints hash the rule, file,
and offending line text — not line numbers — so unrelated edits don't
churn the file.
"""

from __future__ import annotations

import json
from typing import Iterable, List, Tuple

from .findings import Finding

BASELINE_VERSION = 1
DEFAULT_BASELINE = "graftlint_baseline.json"


def load_baseline(path: str) -> set:
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if doc.get("version") != BASELINE_VERSION:
        raise ValueError(f"unsupported baseline version in {path}: "
                         f"{doc.get('version')!r}")
    return {e["fingerprint"] for e in doc.get("findings", [])}


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
    entries = sorted(
        ({"fingerprint": f.fingerprint, "rule": f.rule, "path": f.path,
          "message": f.message} for f in findings),
        key=lambda e: (e["path"], e["rule"], e["fingerprint"]))
    doc = {"version": BASELINE_VERSION, "tool": "graftlint",
           "findings": entries}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")


def partition(findings: Iterable[Finding], baseline: set
              ) -> Tuple[List[Finding], List[Finding]]:
    """Split into (new, grandfathered)."""
    new, old = [], []
    for f in findings:
        (old if f.fingerprint in baseline else new).append(f)
    return new, old
