"""Finding record + content-based fingerprint (baseline identity)."""

from __future__ import annotations

import dataclasses
import hashlib


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str        # "G001"
    path: str        # repo-relative posix path
    line: int        # 1-based
    col: int         # 0-based
    message: str
    snippet: str = ""  # stripped source line, for fingerprint stability

    @property
    def fingerprint(self) -> str:
        """Stable across line-number shifts: hashes the rule, the file,
        and the offending line's stripped text (plus the message, so two
        distinct findings on one line stay distinct)."""
        h = hashlib.sha1()
        h.update(f"{self.rule}|{self.path}|{self.snippet}|{self.message}"
                 .encode("utf-8"))
        return h.hexdigest()[:16]

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "fingerprint": self.fingerprint}

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col + 1}: " \
               f"{self.rule} {self.message}"
