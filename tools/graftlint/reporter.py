"""Text and JSON rendering of lint results."""

from __future__ import annotations

import json
from typing import List

from .findings import Finding


def render_text(new: List[Finding], grandfathered: List[Finding]) -> str:
    out = []
    for f in new:
        out.append(f.render())
    for f in grandfathered:
        out.append(f"{f.render()} [baselined]")
    n_new, n_old = len(new), len(grandfathered)
    if n_new or n_old:
        out.append(f"graftlint: {n_new} finding(s)"
                   + (f", {n_old} baselined" if n_old else ""))
    else:
        out.append("graftlint: clean")
    return "\n".join(out)


def render_json(new: List[Finding], grandfathered: List[Finding]) -> str:
    doc = {
        "tool": "graftlint",
        "new": [f.to_dict() for f in new],
        "baselined": [f.to_dict() for f in grandfathered],
        "counts": {"new": len(new), "baselined": len(grandfathered)},
    }
    return json.dumps(doc, indent=2, sort_keys=True)
