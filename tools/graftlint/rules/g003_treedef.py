"""G003: treedef stability for kernel state dataclasses.

``ChainState`` / ``BoardState`` (and any ``struct.dataclass`` whose name
ends in ``State``) are jit-cache keys and checkpoint payloads: their
pytree structure must not change under existing callers. PR 3's
contract: every field WITH a default must be ``Optional[...] = None``
(so the default treedef — and every compiled graph and checkpoint —
stays identical, and enabling the field is an explicit respecialize),
and no non-defaulted field may follow a defaulted one (new fields go at
the end).

Static config fields declared via ``struct.field(pytree_node=False,
...)`` are not part of the treedef leaves and are exempt from the
Optional requirement.
"""

from __future__ import annotations

import ast

from ..astutil import dotted_name, terminal_name

RULE_ID = "G003"

_STATE_CLASSES = frozenset({"ChainState", "BoardState"})


def applies(module) -> bool:
    return not module.is_test


def _is_struct_dataclass(cls: ast.ClassDef) -> bool:
    for dec in cls.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted_name(target) or ""
        if name.endswith("struct.dataclass") or name == "dataclass":
            return True
    return False


def _is_optional(ann) -> bool:
    if isinstance(ann, ast.Subscript):
        base = dotted_name(ann.value) or ""
        return base.split(".")[-1] == "Optional"
    if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
        return any(isinstance(s, ast.Constant) and s.value is None
                   for s in (ann.left, ann.right))
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return "Optional[" in ann.value or "| None" in ann.value
    return False


def _is_static_field(default) -> bool:
    """``struct.field(pytree_node=False, ...)`` — not a treedef leaf."""
    if not (isinstance(default, ast.Call)
            and terminal_name(default.func) == "field"):
        return False
    for kw in default.keywords:
        if kw.arg == "pytree_node" and isinstance(kw.value, ast.Constant):
            return kw.value.value is False
    return False


def check(module, config):
    findings = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        if not (node.name in _STATE_CLASSES
                or (node.name.endswith("State")
                    and _is_struct_dataclass(node))):
            continue
        seen_default = None  # field name of first defaulted field
        for stmt in node.body:
            if not isinstance(stmt, ast.AnnAssign) \
                    or not isinstance(stmt.target, ast.Name):
                continue
            fname = stmt.target.id
            if stmt.value is None:
                if seen_default is not None:
                    findings.append(module.finding(
                        RULE_ID, stmt,
                        f"{node.name}.{fname}: non-defaulted field after "
                        f"defaulted `{seen_default}` — new fields must "
                        "be trailing"))
                continue
            if _is_static_field(stmt.value):
                if seen_default is None:
                    seen_default = fname
                continue
            if seen_default is None:
                seen_default = fname
            is_none = (isinstance(stmt.value, ast.Constant)
                       and stmt.value.value is None)
            if not is_none:
                findings.append(module.finding(
                    RULE_ID, stmt,
                    f"{node.name}.{fname}: defaulted field must default "
                    "to None (treedef/checkpoint stability)"))
            if not _is_optional(stmt.annotation):
                findings.append(module.finding(
                    RULE_ID, stmt,
                    f"{node.name}.{fname}: defaulted field must be "
                    "annotated Optional[...] (treedef/checkpoint "
                    "stability)"))
    return findings
