"""G012: writes into recovery-critical roots must be crash-atomic.

The fleet recovers from SIGKILL by replaying a small set of on-disk
artifacts: the journal WAL, the job spool, lease docs, status/started
markers, checkpoints, the collector's offset doc. A bare
``open(path, "w")`` on any of them is a torn-write bug waiting for a
power cut — chaos runs (``worker.sigkill``, ``checkpoint.write``
truncation) sample that space; this rule covers it exhaustively.

**DURABLE_ROOTS** below is the declarative registry: a path expression
whose resolvable string fragments mention one of these tokens is
*durable*. Resolution is interprocedural-lite: string literals,
f-strings, ``os.path.join`` pieces, module constants, local variables,
``self.x`` attributes (through the program index's recorded assignment
values), and one level of callee return expressions.

Sanctioned idioms (everything else on a durable path flags):

* **tmp + fsync + os.replace** — the write goes to a scratch name
  (``.tmp``/``.hb.``/``.part`` markers), is fsynced, then renamed over
  the destination. The rename itself is checked: source must be a
  scratch name, and the enclosing function must fsync.
* **O_EXCL create** — ``open(path, "x")`` / ``os.open(..., O_EXCL)``
  single-shot claims (leases).
* **the journal choke point** — ``"a"``-mode appends are legal only in
  a function that fsyncs what it wrote (``Journal.append``).

Helpers are classified too: a function that bare-writes a *parameter*
path is a bare writer, and calling it with a durable argument flags at
the call site; a helper that does tmp+fsync+replace internally (the
``_write_json_atomic`` family) is sanctioned.

Scratch names (``.tmp``, ``.hb.``, ``.expired.``, ``.part``) and the
reconstructible compile cache are deliberately *not* durable — the
registry is the single place that decides.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..astutil import dotted_name
from ..findings import Finding
from ..program import FuncInfo, Program

RULE_ID = "G012"
PROGRAM = True

_SCOPE = ("/service/", "/obs/", "/resilience/")

# token -> what lives under it (documentation is part of the registry:
# adding a root here is a reviewed decision, not a side effect)
DURABLE_ROOTS = {
    "journal": "fleet/run WAL (replayed on every recovery)",
    "wal": "write-ahead logs generally",
    "spool": "job spool docs (the fleet's work queue)",
    "jobs": "job spool dir (this repo's spool name)",
    "workers": "worker heartbeat/registry docs",
    "heartbeat": "driver/worker heartbeat docs",
    ".lease": "worker lease docs (ownership protocol)",
    "lease_": "lease-adjacent docs (heartbeats fold into the lease)",
    "status": "job status docs (DONE/FAILED adjudication)",
    "started": "job started markers (double-execution guard)",
    "checkpoint": "sweep checkpoints (resume state)",
    "ckpt": "sweep checkpoints (short form)",
    ".collector": "collector offset checkpoint (scrape resume)",
    "drain": "drain markers (graceful-shutdown protocol)",
    "profile/": "profile request markers (worker-consumed protocol)",
    "artifacts": "published result docs (served to tenants)",
}

_SCRATCH_MARKERS = (".tmp", ".hb.", ".expired.", ".part")

_W_MODES = ("w", "a")


def applies(module) -> bool:
    p = "/" + module.path
    return any(seg in p for seg in _SCOPE)


def _in_scope(path: str, config) -> bool:
    if config.rules is not None:
        return True
    return any(seg in "/" + path for seg in _SCOPE)


# -- path-string resolution -------------------------------------------


class _Resolver:
    def __init__(self, program: Program, func: FuncInfo):
        self.program = program
        self.func = func
        self.locals: Dict[str, List[ast.AST]] = {}
        for sub in ast.walk(func.node):
            if (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                    and isinstance(sub.targets[0], ast.Name)):
                self.locals.setdefault(sub.targets[0].id,
                                       []).append(sub.value)
            elif (isinstance(sub, ast.AnnAssign)
                    and isinstance(sub.target, ast.Name)
                    and sub.value is not None):
                self.locals.setdefault(sub.target.id,
                                       []).append(sub.value)

    def strings(self, expr: Optional[ast.AST], depth: int = 0
                ) -> Set[str]:
        if expr is None or depth > 6:
            return set()
        out: Set[str] = set()
        if isinstance(expr, ast.Constant):
            if isinstance(expr.value, str):
                out.add(expr.value)
            return out
        if isinstance(expr, ast.JoinedStr):
            for part in expr.values:
                if isinstance(part, ast.FormattedValue):
                    out |= self.strings(part.value, depth + 1)
                else:
                    out |= self.strings(part, depth + 1)
            return out
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
            return (self.strings(expr.left, depth + 1)
                    | self.strings(expr.right, depth + 1))
        if isinstance(expr, ast.Call):
            d = dotted_name(expr.func) or ""
            term = d.split(".")[-1]
            if term in ("join", "format"):
                base = (expr.func.value
                        if isinstance(expr.func, ast.Attribute) else None)
                if term == "format" and base is not None:
                    out |= self.strings(base, depth + 1)
                for a in expr.args:
                    out |= self.strings(a, depth + 1)
                for kw in expr.keywords:
                    out |= self.strings(kw.value, depth + 1)
                return out
            callee = None
            ent = self.program.lookup(self.func.module.path, d) if d \
                else None
            if ent and ent[0] == "func":
                callee = ent[1]
            elif (isinstance(expr.func, ast.Attribute)
                    and isinstance(expr.func.value, ast.Name)
                    and expr.func.value.id == self._selfname()
                    and self.func.cls is not None):
                callee = self.program._find_method(self.func.cls,
                                                   expr.func.attr)
            if callee is not None:
                for sub in ast.walk(callee.node):
                    if isinstance(sub, ast.Return):
                        out |= _Resolver(self.program,
                                         callee).strings(sub.value,
                                                         depth + 2)
            return out
        if isinstance(expr, ast.Subscript):
            # ``self.dirs[STATUS_DIR]``: the key names the fleet subdir
            return (self.strings(expr.value, depth + 1)
                    | self.strings(expr.slice, depth + 1))
        if isinstance(expr, ast.Name):
            ent = self.program.lookup(self.func.module.path, expr.id)
            if ent and ent[0] == "const":
                out.add(ent[1])
            for v in self.locals.get(expr.id, ()):
                out |= self.strings(v, depth + 1)
            return out
        if isinstance(expr, ast.Attribute):
            cls = self.func.cls
            if (cls is not None and isinstance(expr.value, ast.Name)
                    and self._selfname() == expr.value.id):
                for v in cls.attr_values.get(expr.attr, ()):
                    out |= self.strings(v, depth + 1)
                return out
            d = dotted_name(expr)
            if d:
                ent = self.program.lookup(self.func.module.path, d)
                if ent and ent[0] == "const":
                    out.add(ent[1])
            return out
        return out

    def _selfname(self) -> Optional[str]:
        args = self.func.node.args
        return args.args[0].arg if args.args else None

    def param_names(self, expr: ast.AST) -> Set[str]:
        """Parameter names of self.func appearing inside expr."""
        a = self.func.node.args
        params = {x.arg for x in
                  list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)}
        found = set()
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name) and sub.id in params:
                found.add(sub.id)
        return found


def _durable_token(fragments: Set[str]) -> Optional[str]:
    for frag in fragments:
        low = frag.lower()
        for token in DURABLE_ROOTS:
            if token in low:
                return token
    return None


def _is_scratch(fragments: Set[str]) -> bool:
    return any(m in frag for frag in fragments for m in _SCRATCH_MARKERS)


# -- per-function facts -----------------------------------------------


def _has_fsync(func: FuncInfo) -> bool:
    for sub in ast.walk(func.node):
        if isinstance(sub, ast.Call):
            d = dotted_name(sub.func) or ""
            if d.split(".")[-1] == "fsync":
                return True
    return False


def _open_mode(call: ast.Call) -> str:
    for i, a in enumerate(call.args):
        if i == 1 and isinstance(a, ast.Constant) and isinstance(
                a.value, str):
            return a.value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return "r"


def _flag_names(expr: ast.AST) -> Set[str]:
    out = set()
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Attribute):
            out.add(sub.attr)
        elif isinstance(sub, ast.Name):
            out.add(sub.id)
    return out


def _is_sanctioned_writer(func: FuncInfo) -> bool:
    """tmp+fsync+replace helper: fsyncs and renames internally."""
    if not _has_fsync(func):
        return False
    for sub in ast.walk(func.node):
        if isinstance(sub, ast.Call):
            d = dotted_name(sub.func) or ""
            if d.split(".")[-1] in ("replace", "rename"):
                return True
    return False


def _bare_write_params(func: FuncInfo) -> Set[str]:
    """Parameters this function writes non-atomically (bare open with
    a w/a mode on a parameter-derived path, no internal replace)."""
    if _is_sanctioned_writer(func):
        return set()
    out: Set[str] = set()
    for sub in ast.walk(func.node):
        if not (isinstance(sub, ast.Call)
                and dotted_name(sub.func) == "open" and sub.args):
            continue
        mode = _open_mode(sub)
        if not any(m in mode for m in _W_MODES) or "x" in mode:
            continue
        a = func.node.args
        params = {x.arg for x in
                  list(a.posonlyargs) + list(a.args)
                  + list(a.kwonlyargs)}
        for n in ast.walk(sub.args[0]):
            if isinstance(n, ast.Name) and n.id in params:
                out.add(n.id)
    return out


# -- the rule ---------------------------------------------------------


def check_program(program: Program, config) -> List[Finding]:
    findings: List[Finding] = []

    bare_writers: Dict[FuncInfo, Set[str]] = {}
    sanctioned: Set[FuncInfo] = set()
    for func in program.functions:
        if _is_sanctioned_writer(func):
            sanctioned.add(func)
        else:
            p = _bare_write_params(func)
            if p:
                bare_writers[func] = p

    for func in program.functions:
        if not _in_scope(func.module.path, config):
            continue
        if func.module.is_test:
            continue
        findings.extend(_check_function(program, func, bare_writers,
                                        sanctioned))
    return findings


def _check_function(program: Program, func: FuncInfo,
                    bare_writers, sanctioned) -> List[Finding]:
    findings: List[Finding] = []
    mod = func.module
    res = _Resolver(program, func)

    def durable(expr) -> Optional[str]:
        frags = res.strings(expr)
        if _is_scratch(frags):
            return None
        return _durable_token(frags)

    for node in ast.walk(func.node):
        if not isinstance(node, ast.Call):
            continue
        d = dotted_name(node.func) or ""
        term = d.split(".")[-1]

        if d == "open" and node.args:
            mode = _open_mode(node)
            if "x" in mode or not any(m in mode for m in _W_MODES):
                continue
            token = durable(node.args[0])
            if token is None:
                continue
            if "a" in mode and "w" not in mode:
                if not _has_fsync(func):
                    findings.append(mod.finding(
                        RULE_ID, node,
                        f"append to durable path (root '{token}': "
                        f"{DURABLE_ROOTS[token]}) outside a fsyncing "
                        f"choke point — route it through "
                        f"Journal.append or fsync what you wrote"))
                continue
            findings.append(mod.finding(
                RULE_ID, node,
                f"bare open(..., {mode!r}) on durable path (root "
                f"'{token}': {DURABLE_ROOTS[token]}) — a crash here "
                f"tears the doc; write a .tmp name, fsync, then "
                f"os.replace (or create with O_EXCL)"))

        elif term == "open" and d.endswith("os.open") and node.args:
            flags = set()
            if len(node.args) >= 2:
                flags = _flag_names(node.args[1])
            if "O_EXCL" in flags:
                continue
            if not ({"O_WRONLY", "O_RDWR", "O_CREAT", "O_TRUNC",
                     "O_APPEND"} & flags):
                continue
            token = durable(node.args[0])
            if token is None:
                continue
            findings.append(mod.finding(
                RULE_ID, node,
                f"os.open write on durable path (root '{token}') "
                f"without O_EXCL — use an O_EXCL create or "
                f"tmp+fsync+os.replace"))

        elif term in ("replace", "rename") and len(node.args) >= 2 \
                and d.startswith("os"):
            token = durable(node.args[1])
            if token is None:
                continue
            src_frags = res.strings(node.args[0])
            if src_frags and not _is_scratch(src_frags):
                findings.append(mod.finding(
                    RULE_ID, node,
                    f"rename into durable path (root '{token}') from "
                    f"a non-scratch source — stage through a .tmp "
                    f"name so a crash never leaves a half-written "
                    f"doc"))
                continue
            if not _has_fsync(func):
                findings.append(mod.finding(
                    RULE_ID, node,
                    f"os.{term} into durable path (root '{token}') "
                    f"with no fsync in '{func.name}' — the rename can "
                    f"hit disk before the data does"))

        elif term in ("write_text", "write_bytes") and isinstance(
                node.func, ast.Attribute):
            token = durable(node.func.value)
            if token is not None:
                findings.append(mod.finding(
                    RULE_ID, node,
                    f"direct {term} on durable path (root '{token}') "
                    f"— use tmp+fsync+os.replace"))

        else:
            # call into a classified bare-writer helper with a durable
            # argument
            callee = _resolve_callee(program, func, node)
            if callee is None or callee in sanctioned:
                continue
            params = bare_writers.get(callee)
            if not params:
                continue
            for arg in list(node.args) + [k.value for k in
                                          node.keywords]:
                token = durable(arg)
                if token is not None:
                    findings.append(mod.finding(
                        RULE_ID, node,
                        f"durable path (root '{token}') flows into "
                        f"'{callee.name}', which writes it "
                        f"non-atomically — make the helper "
                        f"tmp+fsync+os.replace or write through the "
                        f"journal"))
                    break
    return findings


def _resolve_callee(program: Program, func: FuncInfo,
                    node: ast.Call) -> Optional[FuncInfo]:
    d = dotted_name(node.func)
    if d:
        ent = program.lookup(func.module.path, d)
        if ent and ent[0] == "func":
            return ent[1]
    if isinstance(node.func, ast.Attribute) and func.cls is not None:
        fv = node.func.value
        args = func.node.args
        sname = args.args[0].arg if args.args else None
        if isinstance(fv, ast.Name) and fv.id == sname:
            return program._find_method(func.cls, node.func.attr)
    return None
