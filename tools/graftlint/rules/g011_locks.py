"""G011: thread-shared state must mutate under its dominating lock.

The fleet's correctness story leans on a small set of lock disciplines
(``FrontDoor._cond`` around admission state, ``Journal._lock`` around
the WAL, per-bucket ``TokenBucket._lock``). This rule checks the
discipline *statically*, through the whole-program index:

1. For every class in ``service/``, ``obs/``, and ``resilience/``,
   compute which thread roots (main, spawned threads, concurrent
   ``do_*`` HTTP handlers, signal handlers) reach each method via the
   resolved call graph.
2. An attribute whose accessors are reachable from a combined root
   weight >= 2 (a handler root alone counts as two threads) is
   **multi-thread-reachable**.
3. Every mutation of such an attribute — ``self.x = ...``,
   ``self.x[k] = ...``, ``del``, container mutators like ``.append`` —
   must happen with a common lock held, either lexically (``with
   self._lock:``) or inherited through the call graph (every resolved
   call path into the mutating method holds the lock).

Exemptions, in order:

* construction: ``__init__`` and methods reachable *only* from
  constructors (recovery helpers) — no other thread has the object yet;
* lock-ish attributes themselves (``Lock``/``RLock``/``Condition``/
  ``Event``/``Thread`` values);
* ``# graftlint: guarded-by(<lock>: <reason>)`` on the attribute's
  assignment line (or the preceding comment line) declares an
  intentional lock-free field — Events, monotonic counters read
  without synchronization, fields serialized by an external contract;
* the same pragma on the ``class`` line exempts every attribute of the
  class — for per-operation objects (one ``Span`` per begin/end pair)
  that are never handed across threads despite living in a scoped
  package.
"""

from __future__ import annotations

from typing import List

from ..findings import Finding
from ..program import EVENT, LOCK, THREAD, Program

RULE_ID = "G011"
PROGRAM = True

_SCOPE = ("/service/", "/obs/", "/resilience/")


def applies(module) -> bool:
    p = "/" + module.path
    return any(seg in p for seg in _SCOPE)


def _in_scope(path: str, config) -> bool:
    if config.rules is not None:
        return True
    return any(seg in "/" + path for seg in _SCOPE)


def _lock_name(lock_id: tuple) -> str:
    kind = lock_id[0]
    if kind == "attr":
        return f"self.{lock_id[2]}"
    if kind == "mod":
        return lock_id[2]
    return lock_id[2]


def check_program(program: Program, config) -> List[Finding]:
    findings: List[Finding] = []
    for cls in program.classes:
        if not _in_scope(cls.module.path, config):
            continue
        if cls.module.is_test:
            continue
        findings.extend(_check_class(program, cls))
    return findings


def _check_class(program: Program, cls) -> List[Finding]:
    findings: List[Finding] = []
    guarded_lines = cls.module.pragmas.guarded
    # a guarded-by pragma on the ``class`` line exempts every attribute
    # (per-request / per-thread objects never shared across threads)
    if cls.node.lineno in guarded_lines:
        return findings
    attrs = set(cls.attr_types)
    attrs.update(a for (c, a) in program.accesses if c is cls)

    for attr in sorted(attrs):
        types = cls.attr_types.get(attr, set())
        if types & {LOCK, EVENT, THREAD}:
            continue
        accesses = program.accesses.get((cls, attr), [])
        if not accesses:
            continue

        stores = [a for a in accesses
                  if a.is_store and not program.is_init_context(a.func)]
        if not stores:
            continue

        # guarded-by pragma on any definition or mutation line exempts
        lines = set(cls.attr_lines.get(attr, ()))
        lines.update(a.line for a in accesses if a.is_store)
        if any(ln in guarded_lines for ln in lines):
            continue

        roots = []
        for acc in accesses:
            for r in program.roots_reaching(acc.func):
                if r not in roots:
                    roots.append(r)
        weight = sum(r.weight for r in roots)
        if weight < 2:
            continue

        locksets = [a.lexical_locks | program.held_locks(a.func)
                    for a in stores]
        common = frozenset.intersection(*locksets) if locksets else \
            frozenset()
        if common:
            continue

        # name the likeliest intended lock for the message
        counts: dict = {}
        for ls in locksets:
            for lid in ls:
                counts[lid] = counts.get(lid, 0) + 1
        candidate = max(counts, key=counts.get) if counts else None

        root_labels = ", ".join(r.label for r in roots)
        for acc, ls in zip(stores, locksets):
            if candidate is not None and candidate in ls:
                continue
            if candidate is not None:
                detail = (f"other mutation sites hold "
                          f"'{_lock_name(candidate)}' but this one "
                          f"does not")
            elif ls:
                detail = ("no single lock dominates every mutation "
                          "site")
            else:
                detail = "no lock is held here on any resolved path"
            findings.append(cls.module.finding(
                RULE_ID, acc.node,
                f"unguarded mutation of '{cls.name}.{attr}', which is "
                f"reachable from multiple threads ({root_labels}): "
                f"{detail}; guard it or mark the field "
                f"'# graftlint: guarded-by(<lock>: <reason>)'"))
    return findings
