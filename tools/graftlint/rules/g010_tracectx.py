"""G010: trace-context hygiene in the fleet's request/job paths.

The fleet's observability story (ISSUE 18) hangs off one invariant:
every event a request or job produces can be joined back to the trace
the front door minted at submit time (``trace_id = "job:<id>"``).
Events that break the chain are the ones that hurt — a
``lease_expired`` with no trace context is exactly the crash-reclaim
record an operator needs to find FROM the job's timeline and can't.

Statically, in ``service/server.py`` and ``service/worker.py`` (the
two processes that handle requests and jobs), every ``.emit()`` of a
request/job-scoped event type — ``job_submitted``, ``http_request``,
``quota_rejected``, ``lease_acquired``, ``lease_expired`` — must carry
the context explicitly (a ``trace_id=`` or ``trace=`` keyword, even if
the value is None: the author decided, rather than forgot) OR be
emitted inside a ``with ...adopt(...)`` block, where the recorder
stamps every span with the adopted context.

Fleet-scoped events (``worker_started``/``worker_exited``) belong to
no job and are exempt; span events inherit context from the tracer
itself. Everything else stays out of scope — this is a contract about
the fleet's serving surface, not a global tax on emit sites.
"""

from __future__ import annotations

import ast

from ..astutil import dotted_name

RULE_ID = "G010"

# request/job-scoped event types: each names a job or request whose
# trace the front door minted; emitting one without context orphans it
_SCOPED = frozenset({"job_submitted", "http_request", "quota_rejected",
                     "lease_acquired", "lease_expired"})

_CTX_KWARGS = frozenset({"trace_id", "trace"})


def applies(module) -> bool:
    return ("service/" in module.path
            and module.path.endswith(("server.py", "worker.py"))
            and not module.is_test)


def _adopting_with(node) -> bool:
    """True for a ``with`` statement whose context expression calls an
    ``adopt`` (``obs.adopt(rec, ctx)`` / ``trace.adopt(...)``)."""
    for item in node.items:
        for call in ast.walk(item.context_expr):
            if isinstance(call, ast.Call):
                name = dotted_name(call.func) or ""
                if name.split(".")[-1] == "adopt":
                    return True
    return False


def _scoped_emit_type(node: ast.Call):
    """The event-type literal of a ``.emit("<type>", ...)`` call when
    it is one of the scoped types, else None."""
    if not (isinstance(node.func, ast.Attribute)
            and node.func.attr == "emit" and node.args):
        return None
    first = node.args[0]
    if isinstance(first, ast.Constant) and isinstance(first.value, str) \
            and first.value in _SCOPED:
        return first.value
    return None


def check(module, config):
    findings = []

    def visit(node, adopted):
        if isinstance(node, (ast.With, ast.AsyncWith)) \
                and _adopting_with(node):
            adopted = True
        if isinstance(node, ast.Call) and not adopted:
            etype = _scoped_emit_type(node)
            if etype is not None:
                kwargs = {kw.arg for kw in node.keywords}
                if not (kwargs & _CTX_KWARGS):
                    findings.append(module.finding(
                        RULE_ID, node,
                        f"emit({etype!r}) without trace context — "
                        "pass trace_id=/trace= (None is an explicit "
                        "decision) or emit under `with ...adopt(...)`;"
                        " an uncontexted request/job event cannot be "
                        "joined to its submit trace"))
        for child in ast.iter_child_nodes(node):
            visit(child, adopted)

    visit(module.tree, False)
    return findings
