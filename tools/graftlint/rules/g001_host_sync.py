"""G001: host-sync hazards inside traced (jit/scan/vmap) contexts.

Inside a traced function in ``kernel/`` or ``sampling/``, flag:

- ``float(x)`` / ``int(x)`` / ``bool(x)`` on a value that is not
  trace-static (a ConcretizationError at trace time, or — worse — a
  silent device sync if the value is already concrete on some paths);
- ``x.item()`` and ``np.asarray(x)`` / ``np.array(x)`` /
  ``jax.device_get(x)`` / ``x.block_until_ready()`` on non-static
  values (always a blocking device->host copy);
- ``if`` / ``while`` whose test is not trace-static (python control
  flow on an array expression cannot be traced).

Staticness follows astutil.StaticEnv: constants, annotated python-typed
params, ``static_argnames``, ``is None`` tests, array metadata, and this
repo's ``pytree_node=False`` config attributes are static; everything
else is assumed traced.
"""

from __future__ import annotations

import ast

from ..astutil import (FuncNode, StaticEnv, dotted_name, parents,
                       terminal_name)

RULE_ID = "G001"

_CONVERTERS = frozenset({"float", "int", "bool", "complex"})
_NP_ROOTS = frozenset({"np", "numpy", "onp"})
_NP_COPIES = frozenset({"asarray", "array", "device_get"})
_SYNC_METHODS = frozenset({"item", "block_until_ready", "tolist"})


def applies(module) -> bool:
    if module.is_test:
        return False
    return "kernel/" in module.path or "sampling/" in module.path


def _outermost_traced(module):
    traced = module.traced_functions
    for fn in traced:
        if not any(p in traced for p in parents(fn)):
            yield fn


def _child_env(env, fn):
    child = StaticEnv(fn)
    for name, static in env.known.items():
        if name not in child.known and name not in child._locals:
            child.known[name] = static
    child._locals |= env._locals
    return child


class _Checker:
    def __init__(self, module, findings):
        self.module = module
        self.findings = findings

    def report(self, node, message):
        self.findings.append(self.module.finding(RULE_ID, node, message))

    # -- expression scan (conversions / syncs), skipping nested funcs --

    def scan_expr(self, node, env):
        if isinstance(node, FuncNode):
            self.check_function(node, _child_env(env, node))
            return
        if isinstance(node, ast.Call):
            self._check_call(node, env)
        for child in ast.iter_child_nodes(node):
            self.scan_expr(child, env)

    def _check_call(self, call, env):
        name = terminal_name(call.func)
        args_static = all(env.is_static(a) for a in call.args)
        if (isinstance(call.func, ast.Name) and name in _CONVERTERS
                and call.args and not args_static):
            self.report(call, f"{name}() on a traced value forces a host "
                            "sync inside a traced context")
            return
        if name in _SYNC_METHODS and isinstance(call.func, ast.Attribute):
            if not env.is_static(call.func.value):
                self.report(call, f".{name}() on a traced value forces a "
                                "device sync inside a traced context")
            return
        dn = dotted_name(call.func) or ""
        root = dn.split(".")[0] if dn else None
        if name in _NP_COPIES and call.args and not args_static:
            if root in _NP_ROOTS or dn == "jax.device_get" \
                    or name == "device_get":
                self.report(call, f"{dn}() copies a traced value to host "
                                "inside a traced context")

    # -- statement walk (forward order, folding staticness) ------------

    def check_body(self, stmts, env):
        for stmt in stmts:
            self.check_stmt(stmt, env)
            env.fold_statement(stmt)

    def check_stmt(self, stmt, env):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.check_function(stmt, _child_env(env, stmt))
            return
        if isinstance(stmt, ast.If):
            if not env.is_static(stmt.test):
                self.report(stmt, "`if` on a traced value inside a traced "
                                "context (use lax.cond/jnp.where)")
            self.scan_expr(stmt.test, env)
            self.check_body(stmt.body, env)
            self.check_body(stmt.orelse, env)
            return
        if isinstance(stmt, ast.While):
            if not env.is_static(stmt.test):
                self.report(stmt, "`while` on a traced value inside a "
                                "traced context (use lax.while_loop)")
            self.scan_expr(stmt.test, env)
            self.check_body(stmt.body, env)
            self.check_body(stmt.orelse, env)
            return
        if isinstance(stmt, ast.For):
            self.scan_expr(stmt.iter, env)
            env.bind(stmt.target, env.is_static(stmt.iter))
            self.check_body(stmt.body, env)
            self.check_body(stmt.orelse, env)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self.scan_expr(item.context_expr, env)
            self.check_body(stmt.body, env)
            return
        if isinstance(stmt, ast.Try):
            self.check_body(stmt.body, env)
            for h in stmt.handlers:
                self.check_body(h.body, env)
            self.check_body(stmt.orelse, env)
            self.check_body(stmt.finalbody, env)
            return
        # simple statement: scan all contained expressions
        for child in ast.iter_child_nodes(stmt):
            self.scan_expr(child, env)

    def check_function(self, fn, env):
        if isinstance(fn, ast.Lambda):
            self.scan_expr(fn.body, env)
        else:
            self.check_body(fn.body, env)


def check(module, config):
    findings = []
    checker = _Checker(module, findings)
    for fn in _outermost_traced(module):
        checker.check_function(fn, StaticEnv(fn))
    return findings
