"""G007: retry/timeout hygiene in the fault-tolerance layer.

The resilience package and the experiments driver are the code that
runs UNATTENDED for days: retry loops, backoff waits, checkpoint
rotation, deadline checks. Three classes of bug hide well there and
surface only in production sweeps:

- ``except Exception: pass`` (or bare / BaseException) — a swallowed
  error defeats the supervisor's classifier: the failure neither
  retries nor quarantines, it silently vanishes. Handle a TYPED
  exception, or re-raise / record something.
- ``time.time()`` in duration arithmetic — wall-clock time jumps under
  NTP slew; a backoff or deadline computed from it can go negative or
  stretch unboundedly. Durations and deadlines must use
  ``time.monotonic()`` (or ``perf_counter``); ``time.time()`` stays
  legal for event TIMESTAMPS, which are never subtracted.
- module-level ``random.*`` calls — backoff jitter from the unseeded
  process-global RNG makes retry schedules (and therefore chaos-test
  streams) unreproducible. Jitter must come from a seeded
  ``random.Random(seed)`` instance (RetryPolicy does this).

Statically: in ``resilience/``, ``experiments/``, and ``service/``
modules, flag (a) any ExceptHandler whose type is missing /
``Exception`` / ``BaseException`` and whose body is a single ``pass``;
(b) any ``-`` BinOp where an operand is a ``time.time()`` call or a
name assigned from one; (c) any ``random.<fn>()`` call on the
``random`` MODULE (instantiating ``random.Random``/``SystemRandom`` is
the fix, so those are exempt).

``service/`` additionally flags ANY bare ``time.time()`` call (ISSUE
11): the service layer injects clocks (``JobQueue(clock=...)``,
``Journal(clock=...)``) so drain/recovery tests can replay timestamp
sequences deterministically — a direct wall-clock call bypasses the
injection point. Passing ``time.time`` as a default (a reference, not
a call) is the sanctioned spelling.
"""

from __future__ import annotations

import ast

from ..astutil import dotted_name

RULE_ID = "G007"

_BROAD = frozenset({"Exception", "BaseException"})
_SEEDED_FACTORIES = frozenset({"Random", "SystemRandom"})


def applies(module) -> bool:
    in_scope = ("resilience/" in module.path
                or "experiments/" in module.path
                or "service/" in module.path)
    return in_scope and not module.is_test


def _is_service(module) -> bool:
    return "service/" in module.path


def _is_time_time(node) -> bool:
    return (isinstance(node, ast.Call)
            and dotted_name(node.func) == "time.time")


def _wall_clock_names(tree) -> set:
    """Names bound (anywhere in the module) from a bare ``time.time()``
    call — subtracting one of these is the same bug as subtracting the
    call itself."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and _is_time_time(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
    return names


def _swallows(handler: ast.ExceptHandler) -> bool:
    if not (len(handler.body) == 1
            and isinstance(handler.body[0], ast.Pass)):
        return False
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Tuple):
        return any((dotted_name(el) or "").split(".")[-1] in _BROAD
                   for el in t.elts)
    return (dotted_name(t) or "").split(".")[-1] in _BROAD


def check(module, config):
    findings = []
    tree = module.tree
    wall_names = _wall_clock_names(tree)
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and _swallows(node):
            what = (dotted_name(node.type) if node.type is not None
                    else "bare except")
            findings.append(module.finding(
                RULE_ID, node,
                f"swallowed broad exception ({what}: pass) — a failure "
                "here neither retries nor quarantines; catch a typed "
                "exception or record it"))
        elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub):
            for side in (node.left, node.right):
                if _is_time_time(side) or (isinstance(side, ast.Name)
                                           and side.id in wall_names):
                    findings.append(module.finding(
                        RULE_ID, node,
                        "duration computed from time.time() — wall "
                        "clock jumps under NTP; use time.monotonic() "
                        "for durations/deadlines (time.time() is for "
                        "timestamps only)"))
                    break
        elif isinstance(node, ast.Call):
            if _is_service(module) and _is_time_time(node):
                findings.append(module.finding(
                    RULE_ID, node,
                    "bare time.time() call in service/ — the service "
                    "layer injects clocks (JobQueue/Journal clock= "
                    "params) so recovery tests replay deterministically;"
                    " thread the injected clock through instead"))
                continue
            fn = node.func
            if (isinstance(fn, ast.Attribute)
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id == "random"
                    and fn.attr not in _SEEDED_FACTORIES):
                findings.append(module.finding(
                    RULE_ID, node,
                    f"random.{fn.attr}() uses the unseeded process "
                    "RNG — backoff jitter must come from a seeded "
                    "random.Random(seed) so retry schedules replay"))
    return findings
