"""G004: obs event conformance at emit call sites.

Every ``<recorder>.emit("<type>", field=..., ...)`` call must name an
event type declared in ``obs/events.py`` and cover that type's core
fields with keyword arguments. The registry is read STATICALLY from the
``EVENT_REGISTRY`` literal (falling back to ``EVENT_FIELDS``) in the
events module — the same single source of truth ``Recorder.emit``
validates against at runtime, so the two cannot drift.

A ``**splat`` in the call suppresses the field-coverage check (the
fields are dynamic); the event-name check still applies when the first
argument is a string literal, and a non-literal event name is itself a
finding (a typo'd dynamic name would only fail at runtime).
"""

from __future__ import annotations

import ast
import os

RULE_ID = "G004"

EVENTS_RELPATH = os.path.join("flipcomplexityempirical_tpu", "obs",
                              "events.py")

_registry_cache = {}


def applies(module) -> bool:
    return not module.is_test


def _const_str(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _extract_registry(tree):
    """{event: frozenset(core fields)} from the EVENT_REGISTRY literal,
    else from the legacy EVENT_FIELDS frozenset literals."""
    for name in ("EVENT_REGISTRY", "EVENT_FIELDS"):
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == name
                    and isinstance(node.value, ast.Dict)):
                continue
            out = {}
            for k, v in zip(node.value.keys, node.value.values):
                event = _const_str(k)
                if event is None:
                    continue
                fields = _extract_fields(v)
                if fields is not None:
                    out[event] = fields
            if out:
                return out
    return None


def _extract_fields(value):
    # EVENT_REGISTRY style: {"fields": ("a", "b"), ...}
    if isinstance(value, ast.Dict):
        for k, v in zip(value.keys, value.values):
            if _const_str(k) == "fields":
                return _extract_fields(v)
        return None
    # frozenset({...}) / set / tuple / list of string constants
    if isinstance(value, ast.Call) and value.args:
        return _extract_fields(value.args[0])
    if isinstance(value, (ast.Tuple, ast.List, ast.Set)):
        fields = [_const_str(e) for e in value.elts]
        if all(f is not None for f in fields):
            return frozenset(fields)
    return None


def load_registry(root):
    """Parse the event registry out of obs/events.py under ``root``.
    Returns None (rule disabled) when the file is missing — fixture
    checkouts — rather than erroring."""
    path = os.path.join(root, EVENTS_RELPATH)
    key = os.path.abspath(path)
    if key not in _registry_cache:
        registry = None
        try:
            with open(path, "r", encoding="utf-8") as fh:
                registry = _extract_registry(ast.parse(fh.read()))
        except (OSError, SyntaxError):
            registry = None
        _registry_cache[key] = registry
    return _registry_cache[key]


def check(module, config):
    registry = load_registry(config.root)
    if registry is None:
        return []
    findings = []
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "emit"):
            continue
        if not node.args:
            continue  # emit() with no event arg fails at runtime anyway
        event = _const_str(node.args[0])
        if event is None:
            findings.append(module.finding(
                RULE_ID, node,
                "event type passed to .emit() must be a string literal "
                "so the schema is statically checkable"))
            continue
        if event not in registry:
            findings.append(module.finding(
                RULE_ID, node,
                f"unknown event type {event!r} — not declared in "
                "obs/events.py EVENT_REGISTRY"))
            continue
        has_splat = any(kw.arg is None for kw in node.keywords)
        if has_splat:
            continue
        given = {kw.arg for kw in node.keywords if kw.arg is not None}
        missing = registry[event] - given - {"ts"}
        if missing:
            findings.append(module.finding(
                RULE_ID, node,
                f"emit({event!r}, ...) missing core field(s) "
                f"{sorted(missing)} declared in obs/events.py"))
    return findings
