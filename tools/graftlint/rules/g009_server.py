"""G009: HTTP handler hygiene in the fleet front door.

``service/server.py``'s request handlers run on ``ThreadingHTTPServer``
threads — one per in-flight request, concurrent with the admission pump
and every other request. Three bug classes turn that into a
correctness problem rather than a style one:

- **blocking sweep execution on a request thread**: constructing a
  ``SweepService`` or calling ``run_until_idle`` inside a handler runs
  device work (minutes of XLA compile + sampling) while the client's
  socket — and the server's accept backlog behind it — waits.
  Execution belongs to the worker fleet; the front door only journals,
  spools, and reads.
- **bare ``time.time()``**: the server's quota buckets and journal
  timestamps replay in tests on an injected clock (the G007
  discipline); a handler reading the wall clock directly bypasses it.
  Durations use ``time.monotonic()``, which stays legal.
- **unjournaled state mutation**: handlers share the ``FrontDoor``
  through ``self.server`` — a ``do_*`` method that assigns into or
  mutates ``self.server...`` without any journaling call in sight is a
  state change a server restart silently forgets (the WAL is the
  recovery story; mutations the journal never saw don't survive it).

Statically, inside any class that subclasses ``BaseHTTPRequestHandler``
(or structurally looks like one: defines ``do_*`` methods): flag (a)
calls whose dotted name contains ``SweepService`` or ends with
``run_until_idle``; (b) ``time.time()`` calls; (c) within ``do_*``
methods containing no call whose dotted name mentions ``journal`` or
``submit`` (the FrontDoor's journaling entry points), any assignment to
an attribute chain rooted at ``self.server`` or any mutating method
call (``append``/``add``/``pop``/``update``/``setdefault``/
``insert``/``remove``/``extend``/``clear``) on such a chain.
"""

from __future__ import annotations

import ast
import re

from ..astutil import dotted_name

RULE_ID = "G009"

_MUTATORS = ("append", "add", "pop", "update", "setdefault",
             "insert", "remove", "extend", "clear")

_DO_METHOD = re.compile(r"do_[A-Z]+$")


def applies(module) -> bool:
    return ("service/" in module.path
            and module.path.endswith("server.py")
            and not module.is_test)


def _is_handler_class(cls: ast.ClassDef) -> bool:
    for base in cls.bases:
        name = dotted_name(base) or ""
        if "BaseHTTPRequestHandler" in name:
            return True
    return any(isinstance(node, ast.FunctionDef)
               and _DO_METHOD.match(node.name)
               for node in cls.body)


def _server_chain(node) -> bool:
    """True when ``node`` is an attribute chain rooted at
    ``self.server`` (the handler's shared-state door)."""
    name = dotted_name(node) or ""
    return name == "self.server" or name.startswith("self.server.")


def _journals(fn: ast.FunctionDef) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func) or ""
            if "journal" in name or name.endswith(".submit"):
                return True
    return False


def _check_mutations(fn: ast.FunctionDef, module, findings):
    if _journals(fn):
        return
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets
                       if isinstance(node, ast.Assign)
                       else [node.target])
            for tgt in targets:
                if isinstance(tgt, ast.Attribute) and _server_chain(tgt):
                    findings.append(module.finding(
                        RULE_ID, node,
                        f"{fn.name} assigns into self.server state "
                        "with no journaling call in the handler — a "
                        "restart forgets mutations the WAL never saw; "
                        "route state changes through the FrontDoor's "
                        "journaled entry points"))
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr in _MUTATORS
              and _server_chain(node.func.value)):
            findings.append(module.finding(
                RULE_ID, node,
                f"{fn.name} mutates self.server state "
                f"(.{node.func.attr}) with no journaling call in the "
                "handler — unjournaled mutations don't survive a "
                "server restart"))


def check(module, config):
    findings = []
    for cls in ast.walk(module.tree):
        if not (isinstance(cls, ast.ClassDef)
                and _is_handler_class(cls)):
            continue
        for node in ast.walk(cls):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            if "SweepService" in name or name.endswith("run_until_idle"):
                findings.append(module.finding(
                    RULE_ID, node,
                    f"{name}() runs sweep execution on a request "
                    "thread — the front door only journals, spools, "
                    "and reads; execution belongs to the worker "
                    "fleet (service.worker)"))
            elif name == "time.time":
                findings.append(module.finding(
                    RULE_ID, node,
                    "time.time() inside an HTTP handler bypasses the "
                    "injected clock the server replays on in tests; "
                    "use the FrontDoor's clock for timestamps and "
                    "time.monotonic() for durations"))
        for fn in cls.body:
            if (isinstance(fn, ast.FunctionDef)
                    and _DO_METHOD.match(fn.name)):
                _check_mutations(fn, module, findings)
    return findings
