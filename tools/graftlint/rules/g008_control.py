"""G008: purity of the adaptive control plane.

The control/ package is the one place where observation becomes action
(early stops, segment retunes, ladder reshapes). The whole recovery
story — SweepService.recover() replaying journaled ``control_action``
records bit-identically — rests on control decisions being PURE
functions of the observed history: same snapshots in, same actions out,
on any host, at any wall-clock time, in any process. Three bug classes
silently break that contract:

- any ``time.*()`` clock read — a decision influenced by wall clock
  (or even a monotonic timer) cannot replay; latency enters control
  ONLY through the quantized ``segment_wall_s`` histogram snapshot the
  loop hands to policies (ObservedState.p95_bucket).
- any ``random.*`` / ``np.random.*`` call — there is no legitimate
  randomness in a control decision; a stochastic policy would emit a
  different action sequence on recovery than the journal recorded.
- emission from inside a policy — policies PROPOSE actions and the
  ControlLoop alone emits/journals them (``_emit``). A policy calling
  ``.emit(...)`` or ``journal.append(...)`` bypasses the loop's
  dedup/adopt bookkeeping, so replay double-counts its actions.

Statically, in ``control/`` modules: flag (a) any call whose dotted
name starts with ``time.`` or ``datetime.``; (b) any call on the
``random`` module or dotted through ``np.random``/``numpy.random``;
(c) inside any ClassDef whose name ends with ``Policy``, any
``.emit(...)`` attribute call or any call whose dotted name ends with
``journal.append``.
"""

from __future__ import annotations

import ast

from ..astutil import dotted_name

RULE_ID = "G008"


def applies(module) -> bool:
    return "control/" in module.path and not module.is_test


def _clock_call(name: str) -> bool:
    return name.startswith("time.") or name.startswith("datetime.")


def _rng_call(name: str) -> bool:
    return (name.startswith("random.")
            or name.startswith("np.random.")
            or name.startswith("numpy.random."))


def _check_calls(nodes, module, findings, in_policy: bool):
    for node in nodes:
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func) or ""
        if _clock_call(name):
            findings.append(module.finding(
                RULE_ID, node,
                f"{name}() reads a clock inside control/ — decisions "
                "must be pure in the observed history so recovery "
                "replays them bit-identically; latency reaches "
                "policies only via the quantized p95_bucket snapshot"))
        elif _rng_call(name):
            findings.append(module.finding(
                RULE_ID, node,
                f"{name}() draws randomness inside control/ — a "
                "stochastic decision cannot replay; control actions "
                "must be deterministic in the observed history"))
        elif in_policy and isinstance(node.func, ast.Attribute):
            if node.func.attr == "emit":
                findings.append(module.finding(
                    RULE_ID, node,
                    "emit() from inside a Policy class — policies "
                    "propose ControlActions and the ControlLoop alone "
                    "emits/journals them (its _emit keeps the "
                    "dedup/adopt bookkeeping replay depends on)"))
            elif name.endswith("journal.append"):
                findings.append(module.finding(
                    RULE_ID, node,
                    "journal.append() from inside a Policy class — "
                    "journaling is the ControlLoop's job; a policy "
                    "writing records directly double-counts them on "
                    "replay"))


def check(module, config):
    findings = []
    tree = module.tree
    policy_spans = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.ClassDef)
                and node.name.endswith("Policy")):
            policy_spans.append(node)
    policy_nodes = set()
    for cls in policy_spans:
        for sub in ast.walk(cls):
            policy_nodes.add(id(sub))
    in_policy = [n for n in ast.walk(tree) if id(n) in policy_nodes]
    outside = [n for n in ast.walk(tree) if id(n) not in policy_nodes]
    _check_calls(in_policy, module, findings, in_policy=True)
    _check_calls(outside, module, findings, in_policy=False)
    return findings
