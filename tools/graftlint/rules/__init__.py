"""Rule registry. Each per-file rule module exposes:

- ``RULE_ID``: "Gnnn"
- ``applies(module) -> bool``: path scoping (bypassed when a LintConfig
  selects rules explicitly, so fixtures outside the scoped trees still
  exercise the rule)
- ``check(module, config) -> list[Finding]``

Program rules (``PROGRAM = True``) run once per lint over the
cross-module index instead:

- ``check_program(program, config) -> list[Finding]``
"""

from . import (g001_host_sync, g002_prng, g003_treedef, g004_events,
               g005_recorder, g006_pytest, g007_retry, g008_control,
               g009_server, g010_tracectx, g011_locks, g012_durability,
               g013_faultsites, g014_history_readback)

RULES = (g001_host_sync, g002_prng, g003_treedef, g004_events,
         g005_recorder, g006_pytest, g007_retry, g008_control,
         g009_server, g010_tracectx, g011_locks, g012_durability,
         g013_faultsites, g014_history_readback)

RULE_IDS = tuple(r.RULE_ID for r in RULES)
