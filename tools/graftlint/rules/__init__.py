"""Rule registry. Each rule module exposes:

- ``RULE_ID``: "Gnnn"
- ``applies(module) -> bool``: path scoping (bypassed when a LintConfig
  selects rules explicitly, so fixtures outside the scoped trees still
  exercise the rule)
- ``check(module, config) -> list[Finding]``
"""

from . import (g001_host_sync, g002_prng, g003_treedef, g004_events,
               g005_recorder, g006_pytest, g007_retry, g008_control,
               g009_server, g010_tracectx)

RULES = (g001_host_sync, g002_prng, g003_treedef, g004_events,
         g005_recorder, g006_pytest, g007_retry, g008_control,
         g009_server, g010_tracectx)

RULE_IDS = tuple(r.RULE_ID for r in RULES)
