"""G013: fault-site literals must exist in the FAULT_SITES registry.

Chaos coverage rots silently: rename a site in ``resilience/faults.py``
and every ``fault_point("old.name")`` still runs — it just never arms —
and every ``--faults old.name:once`` plan in a gate script becomes a
no-op that passes green. This rule pins every site literal to the
single registry:

* **registry extraction** — the ``FAULT_SITES`` dict (or legacy
  ``SITES`` tuple) defined at top level of ``resilience/faults.py``
  (any linted module defining one works, which is how fixtures carry
  their own registry);
* **Python injection points** — first-argument string literals of
  ``fault_point`` / ``corrupt_file`` / ``wants_corruption`` /
  ``FaultRule``;
* **plan specs** — string literals handed to ``install_from_spec`` /
  ``FaultPlan.from_spec``, parsed with the plan grammar
  (``SITE:MODE[,...]``, ``seed=N`` entries skipped);
* **gate scripts** — ``--faults``/``GRAFT_FAULTS=`` plan strings in
  the ``.sh`` files of the lint set (shell lines take the same
  ``# graftlint: disable=G013`` pragma).

No registry in the lint set -> the rule is inert (a fixture tree
without faults.py doesn't fabricate findings).
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Set

from ..astutil import dotted_name
from ..findings import Finding
from ..program import Program

RULE_ID = "G013"
PROGRAM = True

_INJECTORS = ("fault_point", "corrupt_file", "wants_corruption",
              "FaultRule")
_SPEC_TAKERS = ("from_spec", "install_from_spec")

# --faults 'spec' | --faults=spec | GRAFT_FAULTS=spec (shell)
_SH_PLAN_RE = re.compile(
    r"(?:--faults[= ]|GRAFT_FAULTS=)['\"]?([A-Za-z0-9_.*@:=,+-]+)")


def applies(module) -> bool:
    return True


def _registry(program: Program) -> Optional[Set[str]]:
    best: Optional[Set[str]] = None
    for relpath, mod in sorted(program.modules.items()):
        sites = _sites_in(mod)
        if sites is None:
            continue
        if relpath.endswith("resilience/faults.py"):
            return sites
        if best is None:
            best = sites
    return best


def _sites_in(mod) -> Optional[Set[str]]:
    for node in mod.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        name = node.targets[0].id
        if name == "FAULT_SITES" and isinstance(node.value, ast.Dict):
            keys = set()
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(
                        k.value, str):
                    keys.add(k.value)
            return keys
        if name == "SITES" and isinstance(node.value,
                                          (ast.Tuple, ast.List)):
            vals = set()
            for e in node.value.elts:
                if isinstance(e, ast.Constant) and isinstance(
                        e.value, str):
                    vals.add(e.value)
            return vals
    return None


def _spec_sites(spec: str) -> List[str]:
    sites = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry or entry.startswith("seed="):
            continue
        sites.append(entry.split(":", 1)[0].strip())
    return sites


def check_program(program: Program, config) -> List[Finding]:
    registry = _registry(program)
    if registry is None:
        return []
    findings: List[Finding] = []

    for mod in program.modules.values():
        if mod.path.endswith("resilience/faults.py"):
            continue  # the registry's own docstrings/defaults
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func) or ""
            term = d.split(".")[-1]
            if term in _INJECTORS and node.args:
                a = node.args[0]
                if isinstance(a, ast.Constant) and isinstance(
                        a.value, str):
                    if a.value not in registry:
                        findings.append(mod.finding(
                            RULE_ID, a,
                            f"unknown fault site {a.value!r} — not in "
                            f"resilience.faults.FAULT_SITES "
                            f"({_nearest(a.value, registry)})"))
            elif term in _SPEC_TAKERS and node.args:
                a = node.args[0]
                if isinstance(a, ast.Constant) and isinstance(
                        a.value, str):
                    for site in _spec_sites(a.value):
                        if site not in registry:
                            findings.append(mod.finding(
                                RULE_ID, a,
                                f"fault plan names unknown site "
                                f"{site!r} — not in FAULT_SITES "
                                f"({_nearest(site, registry)})"))

    for sf in program.shell_files:
        for lineno, line in enumerate(sf.lines, start=1):
            for m in _SH_PLAN_RE.finditer(line):
                spec = m.group(1)
                if "$" in spec:
                    continue  # shell interpolation: not a literal
                for site in _spec_sites(spec):
                    if site and site not in registry:
                        findings.append(sf.finding(
                            RULE_ID, lineno, m.start(),
                            f"fault plan in gate script names unknown "
                            f"site {site!r} — not in "
                            f"resilience.faults.FAULT_SITES "
                            f"({_nearest(site, registry)})"))
    return findings


def _nearest(site: str, registry: Set[str]) -> str:
    import difflib

    close = difflib.get_close_matches(site, sorted(registry), n=1)
    if close:
        return f"did you mean {close[0]!r}?"
    return f"{len(registry)} sites registered"
