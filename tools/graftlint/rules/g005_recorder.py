"""G005: recorder-contract purity in the sampling runners.

The NullRecorder contract (PR 1): with no recorder attached, a runner's
hot loop must execute byte-identically to the un-instrumented code — no
metric readbacks, no host formatting, nothing between a device dispatch
and the runner's own sync point. The enforcement pattern in this repo
is truthiness gating: ``bool(NullRecorder()) is False``, so every piece
of telemetry work hangs under an ``if rec:`` (or ``if rec and ...:``)
guard.

Statically: in ``sampling/`` modules, inside any function that performs
a device dispatch (calls one of DISPATCH_NAMES), every obs call —
``.emit`` / ``.observe_chunk`` / ``.poll``, plus the tracing layer's
``.span`` / ``.begin`` / ``.end`` / ``.emit_span_at`` and the metrics
registry's ``.notify`` — must be lexically nested under an ``if`` whose
test mentions a recorder-ish name (``rec``, ``recorder``, or anything
assigned from ``resolve_recorder``). Span objects are cheap but their
begin/end EMIT, so they fall under the same guard. Functions that never
dispatch (deferred emitters like ``_emit_board_chunks``, which run
after the run-end sync) are exempt.
"""

from __future__ import annotations

import ast

from ..astutil import (FuncNode, enclosing_function, parents,
                       terminal_name, walk_with_parents)

RULE_ID = "G005"

DISPATCH_NAMES = frozenset({
    "_run_chunk", "run_board_chunk", "run_board_chunk_pallas",
    "_record_initial", "record_final", "exchange_step",
})
OBS_METHODS = frozenset({"emit", "observe_chunk", "poll",
                         "span", "begin", "end", "emit_span_at",
                         "notify"})
_RECORDERISH = frozenset({"rec", "recorder"})


def applies(module) -> bool:
    return "sampling/" in module.path and not module.is_test


def _recorderish_names(fn) -> set:
    names = set(_RECORDERISH)
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and terminal_name(node.value.func) == "resolve_recorder"):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
    return names


def _dispatches(fn) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) \
                and terminal_name(node.func) in DISPATCH_NAMES:
            return True
    return False


def _guarded(node, fn, names) -> bool:
    """Some ancestor ``if`` (within ``fn``) tests a recorder-ish name."""
    for p in parents(node):
        if p is fn:
            return False
        if isinstance(p, ast.If):
            for n in ast.walk(p.test):
                if isinstance(n, ast.Name) and n.id in names:
                    return True
    return False


def check(module, config):
    walk_with_parents(module.tree)
    findings = []
    for fn in ast.walk(module.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not _dispatches(fn):
            continue
        names = _recorderish_names(fn)
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in OBS_METHODS):
                continue
            if enclosing_function(node) is not fn:
                continue  # nested function's calls judged in their own fn
            if isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "self":
                continue  # method plumbing, not a runner call site
            if not _guarded(node, fn, names):
                findings.append(module.finding(
                    RULE_ID, node,
                    f".{node.func.attr}() in a dispatching runner "
                    "function must be guarded by `if rec:` so the "
                    "NullRecorder path stays byte-identical"))
    return findings
