"""G014: per-step history tensors materialized on host in ``sampling/``.

Since the device-resident analytics layer (stats/accumulators.py), the
full per-step history is an *oracle path*: runners keep it behind the
``record_history`` / ``analytics='history'`` flags and funnel every
device->host copy of it through the ``maybe_host`` helper, which gates
on ``history_device``. Any other host materialization of a history
tensor in ``sampling/`` silently reintroduces the O(C*T) per-chunk
readback that summary mode exists to eliminate — and it does so off the
books, since it bypasses the honest ``readback_bytes`` accounting.

Statically: in non-test ``sampling/`` modules, flag

- ``np.asarray(h)`` / ``np.array(h)`` / ``jax.device_get(h)``
- ``jax.tree.map(np.asarray, h)`` (and ``jax.tree_map`` /
  ``jax.tree_util.tree_map`` spellings)

whenever the materialized expression mentions a history-shaped name
(``out``/``outs``/``out0``/``out_last``/``hist``/``history``/
``host_outs``/``ys``...). Scalar counter readbacks
(``np.asarray(states.accept_count)`` and friends) are not history
tensors and stay unflagged. Call sites *inside* the ``maybe_host``
helper itself are exempt — that is the flagged oracle path. A runner
that legitimately assembles history on host (e.g. the tempered ladder's
``collect``) declares it with ``# graftlint: disable=G014(reason)``,
which keeps the exception visible at the call site.
"""

from __future__ import annotations

import ast
import re

from ..astutil import dotted_name, parents, terminal_name, \
    walk_with_parents

RULE_ID = "G014"

_NP_ROOTS = frozenset({"np", "numpy", "onp"})
_NP_COPIES = frozenset({"asarray", "array"})
_TREE_MAPS = frozenset({"jax.tree.map", "jax.tree_map",
                        "jax.tree_util.tree_map", "tree.map", "tree_map"})
# Functions that ARE the flagged oracle path: the one helper allowed to
# move history to host (it gates on history_device).
_ORACLE_FUNCS = frozenset({"maybe_host"})

_HISTORY_NAME = re.compile(r"^(out\w*|hist\w*|host_out\w*|ys)$")


def applies(module) -> bool:
    return "sampling/" in module.path and not module.is_test


def _is_np_copy(func) -> bool:
    dn = dotted_name(func) or ""
    root = dn.split(".")[0] if dn else None
    name = terminal_name(func)
    if name in _NP_COPIES and root in _NP_ROOTS:
        return True
    return dn == "jax.device_get" or name == "device_get"


def _history_names(expr) -> list:
    """History-shaped identifiers mentioned anywhere in ``expr``."""
    found = []
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and _HISTORY_NAME.match(node.id):
            found.append(node.id)
        # states.accept_count etc.: the attribute chain's base name is
        # what we walk into; attribute *names* are deliberately ignored
        # so counter fields never match.
    return found


def _materialized_args(call):
    """Args a call copies to host, or None if it is not a materializer."""
    if _is_np_copy(call.func):
        return call.args
    dn = dotted_name(call.func) or ""
    if dn in _TREE_MAPS and call.args and _is_np_copy(call.args[0]):
        return call.args[1:]
    return None


def _in_oracle_helper(node) -> bool:
    for p in parents(node):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and p.name in _ORACLE_FUNCS:
            return True
    return False


def check(module, config):
    walk_with_parents(module.tree)
    findings = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        args = _materialized_args(node)
        if args is None:
            continue
        names = []
        for a in args:
            names.extend(_history_names(a))
        if not names:
            continue
        if _in_oracle_helper(node):
            continue
        findings.append(module.finding(
            RULE_ID, node,
            f"per-step history tensor ({', '.join(sorted(set(names)))}) "
            "materialized on host outside the maybe_host oracle path — "
            "route it through maybe_host/history_device (or account for "
            "it and disable=G014 with a reason)"))
    return findings
