"""G006: pytest hygiene for the tiered suite.

The tier-1 verify command runs ``-m 'not slow'`` under a wall-clock
budget (tests/conftest.py). A test that drives more than
``max_test_steps`` chain steps, or loops over physical devices, belongs
in the slow tier: it must carry ``@pytest.mark.slow`` (or ride a
module-level ``pytestmark`` that includes it).

Detected step loads: an integer literal > N passed as
``n_steps=``/``num_steps=``/``steps=`` to any call inside the test, or
bound to a local of one of those names. Device loops: ``for ... in
jax.devices()`` / ``jax.local_devices()``.
"""

from __future__ import annotations

import ast

from ..astutil import dotted_name, terminal_name

RULE_ID = "G006"

_STEP_KWARGS = frozenset({"n_steps", "num_steps", "steps"})
_DEVICE_ITERS = frozenset({"devices", "local_devices"})


def applies(module) -> bool:
    return module.is_test


def _has_slow_marker(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted_name(target) or ""
        if name.endswith("mark.slow") or name == "slow":
            return True
    return False


def _module_marked_slow(tree) -> bool:
    for node in tree.body:
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "pytestmark"
                        for t in node.targets)):
            for n in ast.walk(node.value):
                name = dotted_name(n) or ""
                if name.endswith("mark.slow"):
                    return True
    return False


def _heavy_reasons(fn, max_steps):
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg in _STEP_KWARGS \
                        and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, int) \
                        and kw.value.value > max_steps:
                    yield node, (f"drives {kw.value.value} chain steps "
                                 f"(> {max_steps})")
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id in _STEP_KWARGS \
                        and isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, int) \
                        and node.value.value > max_steps:
                    yield node, (f"binds {t.id}={node.value.value} "
                                 f"(> {max_steps})")
        elif isinstance(node, ast.For):
            if isinstance(node.iter, ast.Call) \
                    and terminal_name(node.iter.func) in _DEVICE_ITERS:
                yield node, "loops over physical devices"


def check(module, config):
    if _module_marked_slow(module.tree):
        return []
    findings = []
    for fn in ast.walk(module.tree):
        if not isinstance(fn, ast.FunctionDef) \
                or not fn.name.startswith("test_"):
            continue
        if _has_slow_marker(fn):
            continue
        for node, reason in _heavy_reasons(fn, config.max_test_steps):
            findings.append(module.finding(
                RULE_ID, node,
                f"{fn.name} {reason} but lacks @pytest.mark.slow "
                "(tier-1 budget)"))
    return findings
