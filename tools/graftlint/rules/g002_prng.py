"""G002: PRNG key discipline.

A key value may be consumed (passed bare to a call) at most once; the
next use must come from a fresh binding via ``jax.random.split`` /
``fold_in`` / ``PRNGKey`` (or this repo's ``_split4``). Two patterns
are flagged, per function, in forward program order:

- straight-line reuse: ``a = random.uniform(key); b = random.normal(key)``;
- loop reuse: a key consumed inside a ``for``/``while`` body that is not
  rebound from a key-maker before the body repeats (the body is replayed
  once with the first pass's exit state to catch cross-iteration reuse).

Only bare ``Name`` arguments count as consumption — keys riding inside
carry tuples (``lax.scan`` carries) or subscripted key batches
(``keys[i]``) are not consumptions, which keeps the rule quiet on the
repo's carry-threading style.
"""

from __future__ import annotations

import ast

from ..astutil import FuncNode, terminal_name

RULE_ID = "G002"

KEY_MAKERS = frozenset({"split", "fold_in", "PRNGKey", "key",
                        "wrap_key_data", "_split4", "split4"})
# calls a key may be passed to any number of times (making fresh keys,
# or pure metadata)
_NONCONSUMING = frozenset({"len", "isinstance", "print", "repr", "type",
                           "key_data", "unwrap"})


def applies(module) -> bool:
    return not module.is_test


def _keyish_param(arg: ast.arg) -> bool:
    """JAX key params by name ("key", "kprop", "init_key"...). Stateful
    host RNGs (``rng: np.random.Generator``) are mutable and reusable —
    not keys."""
    if "key" not in arg.arg:
        return False
    ann = arg.annotation
    if ann is not None:
        for n in ast.walk(ann):
            if isinstance(n, ast.Attribute) and n.attr == "Generator":
                return False
    return True


def _terminates(body) -> bool:
    """The branch body unconditionally leaves the enclosing suite."""
    return any(isinstance(s, (ast.Return, ast.Raise, ast.Break,
                              ast.Continue)) for s in body)


class _KeyTracker:
    def __init__(self, module, findings):
        self.module = module
        self.findings = findings
        self.reported = set()      # (name, lineno) dedupe across replays

    def run(self, fn):
        state = {}
        args = fn.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            if _keyish_param(a):
                state[a.arg] = "fresh"
        body = fn.body if not isinstance(fn, ast.Lambda) else [
            ast.Expr(value=fn.body)]
        self.walk_body(body, state)

    # -- state ops -----------------------------------------------------

    def _consume(self, name_node, state):
        name = name_node.id
        if name not in state:
            return
        if state[name] == "consumed":
            key = (name, name_node.lineno)
            if key not in self.reported:
                self.reported.add(key)
                self.findings.append(self.module.finding(
                    RULE_ID, name_node,
                    f"PRNG key `{name}` reused after being consumed — "
                    "split/fold_in a fresh key first"))
        else:
            state[name] = "consumed"

    def _bind_fresh(self, target, state):
        if isinstance(target, ast.Name):
            state[target.id] = "fresh"
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind_fresh(elt, state)

    def _bind_unknown(self, target, state):
        if isinstance(target, ast.Name):
            state.pop(target.id, None)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind_unknown(elt, state)

    # -- expression scan: consumption happens at calls -----------------

    def scan_expr(self, node, state):
        if isinstance(node, FuncNode):
            return  # nested functions tracked separately
        if isinstance(node, ast.Call):
            name = terminal_name(node.func)
            for arg in node.args:
                if isinstance(arg, ast.Name) and name not in _NONCONSUMING:
                    self._consume(arg, state)
                else:
                    self.scan_expr(arg, state)
            for kw in node.keywords:
                if isinstance(kw.value, ast.Name) \
                        and name not in _NONCONSUMING:
                    self._consume(kw.value, state)
                else:
                    self.scan_expr(kw.value, state)
            self.scan_expr(node.func, state)
            return
        for child in ast.iter_child_nodes(node):
            self.scan_expr(child, state)

    def _is_key_maker(self, value) -> bool:
        return (isinstance(value, ast.Call)
                and terminal_name(value.func) in KEY_MAKERS)

    # -- statements ----------------------------------------------------

    def walk_body(self, stmts, state):
        for stmt in stmts:
            self.walk_stmt(stmt, state)

    def walk_stmt(self, stmt, state):
        if isinstance(stmt, FuncNode + (ast.ClassDef,)):
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            value = stmt.value
            if value is None:
                return
            self.scan_expr(value, state)
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for t in targets:
                if self._is_key_maker(value):
                    self._bind_fresh(t, state)
                else:
                    self._bind_unknown(t, state)
            return
        if isinstance(stmt, ast.If):
            self.scan_expr(stmt.test, state)
            then_state = dict(state)
            else_state = dict(state)
            self.walk_body(stmt.body, then_state)
            self.walk_body(stmt.orelse, else_state)
            # a branch that returns/raises doesn't flow into the code
            # after the if — early-return guards must not poison keys
            then_ends = _terminates(stmt.body)
            else_ends = bool(stmt.orelse) and _terminates(stmt.orelse)
            if then_ends and not else_ends:
                state.clear()
                state.update(else_state)
                return
            if else_ends and not then_ends:
                state.clear()
                state.update(then_state)
                return
            if then_ends and else_ends:
                return  # code after is unreachable from here; keep entry
            # merge: consumed in either branch -> consumed
            for name in set(then_state) | set(else_state):
                a = then_state.get(name)
                b = else_state.get(name)
                if a is None or b is None:
                    state.pop(name, None)
                else:
                    state[name] = "consumed" if "consumed" in (a, b) \
                        else "fresh"
            return
        if isinstance(stmt, (ast.For, ast.While)):
            if isinstance(stmt, ast.For):
                self.scan_expr(stmt.iter, state)
                self._bind_unknown(stmt.target, state)
            else:
                self.scan_expr(stmt.test, state)
            # two passes: the second replays the body with the first
            # pass's exit state, so a key consumed in iteration N and
            # not re-split before iteration N+1 is caught
            self.walk_body(stmt.body, state)
            self.walk_body(stmt.body, state)
            self.walk_body(stmt.orelse, state)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self.scan_expr(item.context_expr, state)
            self.walk_body(stmt.body, state)
            return
        if isinstance(stmt, ast.Try):
            self.walk_body(stmt.body, state)
            for h in stmt.handlers:
                self.walk_body(h.body, state)
            self.walk_body(stmt.orelse, state)
            self.walk_body(stmt.finalbody, state)
            return
        for child in ast.iter_child_nodes(stmt):
            self.scan_expr(child, state)


def check(module, config):
    findings = []
    tracker = _KeyTracker(module, findings)
    for node in ast.walk(module.tree):
        if isinstance(node, FuncNode):
            tracker.run(node)
    return findings
