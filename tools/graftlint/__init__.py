"""graftlint: JAX-aware static analysis for this repo's hot-path contracts.

The flip-walk throughput story (PROFILE.md) rests on conventions that no
runtime test can cheaply police: runners only sync at chunk boundaries,
kernel state pytrees grow only via trailing ``Optional`` fields, every
telemetry event matches ``obs/events.py``, and the ``NullRecorder`` path
stays byte-identical. graftlint turns those review-enforced conventions
into a stdlib-``ast`` gate that fails before anything compiles:

- G001 host-sync-hazard: ``float()``/``int()``/``bool()``/``.item()``/
  ``np.asarray`` on traced values, and ``if``/``while`` on array
  expressions, inside jit/scan bodies in ``kernel/`` and ``sampling/``.
- G002 prng-reuse: a PRNG key consumed twice (or consumed inside a loop)
  without an intervening ``jax.random.split``/``fold_in``.
- G003 treedef-stability: new ``ChainState``/``BoardState`` fields must
  be trailing ``Optional`` with a ``None`` default (checkpoint/jit-cache
  compatibility, PR 3's contract).
- G004 event-schema: every ``.emit("<type>", ...)`` call site names an
  event type and covers the core fields declared in
  ``obs/events.py::EVENT_REGISTRY`` (one source of truth for the static
  and the runtime validator).
- G005 recorder-purity: recorder/monitor/watch traffic in the sampling
  runners must be guarded on recorder truthiness, so the NullRecorder
  path does no extra host work between device dispatch and the runner's
  existing sync point.
- G006 pytest-hygiene: tests driving > ``max_test_steps`` chain steps or
  looping over devices must carry ``@pytest.mark.slow``.

Usage::

    python -m tools.graftlint [--format text|json]
        [--baseline graftlint_baseline.json] [--write-baseline] paths...

Exit status is nonzero iff any non-baselined finding remains. Intentional
host-side code carries ``# graftlint: disable=G001(<reason>)`` pragmas;
``# graftlint: traced`` marks a function as a traced context when the
jit/scan seeding cannot see it (e.g. kernels entered via cross-module
``vmap``). No dependencies beyond the stdlib.
"""

from .engine import LintConfig, lint_file, run_lint  # noqa: F401
from .findings import Finding  # noqa: F401
from .rules import RULES  # noqa: F401

__version__ = "1.0"
