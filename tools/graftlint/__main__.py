"""CLI entry point: ``python -m tools.graftlint [opts] paths...``"""

from __future__ import annotations

import argparse
import os
import sys

from .baseline import (DEFAULT_BASELINE, load_baseline, partition,
                       write_baseline)
from .engine import LintConfig, run_lint
from .reporter import render_json, render_text


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="JAX-aware static analysis for this repo's tracing, "
                    "sync, RNG, and event-schema contracts.")
    ap.add_argument("paths", nargs="+", help="files or directories to lint")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="baseline JSON of grandfathered fingerprints "
                         f"(default: ./{DEFAULT_BASELINE} if present)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write all current findings to the baseline file "
                         "and exit 0")
    ap.add_argument("--root", default=".",
                    help="repo root for relative paths (default: cwd)")
    ap.add_argument("--jobs", type=int, default=1, metavar="N",
                    help="process-parallel per-file rule dispatch "
                         "(default: 1)")
    ap.add_argument("--no-cache", action="store_true",
                    help="ignore and don't write the content-hash "
                         "result cache (.graftlint_cache.json)")
    args = ap.parse_args(argv)

    config = LintConfig(root=args.root, jobs=max(1, args.jobs),
                        cache=not args.no_cache)
    findings = run_lint(args.paths, config)

    baseline_path = args.baseline
    if baseline_path is None:
        candidate = os.path.join(args.root, DEFAULT_BASELINE)
        if os.path.exists(candidate):
            baseline_path = candidate

    if args.write_baseline:
        path = baseline_path or os.path.join(args.root, DEFAULT_BASELINE)
        write_baseline(path, findings)
        print(f"graftlint: wrote {len(findings)} fingerprint(s) to {path}")
        return 0

    baseline = set()
    if baseline_path is not None:
        try:
            baseline = load_baseline(baseline_path)
        except (OSError, ValueError, KeyError) as exc:
            print(f"graftlint: bad baseline {baseline_path}: {exc}",
                  file=sys.stderr)
            return 2

    new, grandfathered = partition(findings, baseline)
    render = render_json if args.format == "json" else render_text
    print(render(new, grandfathered))
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
