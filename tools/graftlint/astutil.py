"""Shared AST machinery: call-name resolution, traced-context seeding,
and the trace-time staticness evaluator the rules lean on.

Everything here is heuristic in the way a linter must be: the goal is
zero false negatives on the contract patterns this repo actually uses
(documented per rule) with false positives rare enough that a
``# graftlint: disable=...`` pragma per intentional exception is cheap.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional


def walk_with_parents(tree: ast.AST) -> None:
    """Annotate every node with ``._gl_parent`` (None at the root)."""
    tree._gl_parent = None  # type: ignore[attr-defined]
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._gl_parent = node  # type: ignore[attr-defined]


def parents(node: ast.AST) -> Iterator[ast.AST]:
    cur = getattr(node, "_gl_parent", None)
    while cur is not None:
        yield cur
        cur = getattr(cur, "_gl_parent", None)


def terminal_name(func: ast.AST) -> Optional[str]:
    """The rightmost name of a call target: ``jax.lax.scan`` -> "scan",
    ``split`` -> "split". None for computed targets."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def dotted_name(node: ast.AST) -> Optional[str]:
    """Full dotted path when the expression is a plain name chain
    (``jax.lax.while_loop``), else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def root_name(node: ast.AST) -> Optional[str]:
    """Leftmost name of an attribute/subscript chain (``state.key`` ->
    "state")."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


# ---------------------------------------------------------------------------
# Traced-context detection
# ---------------------------------------------------------------------------

# Calling one of these hands the callee to the tracer: any function (or
# lambda) passed by name is a traced context.
TRACING_COMBINATORS = frozenset({
    "scan", "while_loop", "fori_loop", "cond", "switch", "associative_scan",
    "vmap", "pmap", "map", "grad", "value_and_grad", "checkpoint", "remat",
    "pallas_call", "custom_vjp", "custom_jvp", "shard_map",
})

TRACED_MARK = "traced"  # "# graftlint: traced" pragma key


def _jit_decorator(dec: ast.AST) -> bool:
    """True for ``@jax.jit``, ``@jit``, ``@functools.partial(jax.jit,
    ...)``, ``@partial(jit, ...)``."""
    if isinstance(dec, ast.Call):
        if terminal_name(dec.func) == "partial" and dec.args:
            return _jit_decorator(dec.args[0])
        return terminal_name(dec.func) == "jit"
    return terminal_name(dec) == "jit"


def jit_static_argnames(dec: ast.AST) -> frozenset:
    """static_argnames/static_argnums is unavailable positionally here;
    pull the names from a partial(jax.jit, static_argnames=(...))
    decorator so G001 treats those parameters as trace-static."""
    if not (isinstance(dec, ast.Call)
            and terminal_name(dec.func) == "partial"):
        return frozenset()
    for kw in dec.keywords:
        if kw.arg == "static_argnames":
            names = []
            for elt in ast.walk(kw.value):
                if isinstance(elt, ast.Constant) and isinstance(elt.value,
                                                                str):
                    names.append(elt.value)
            return frozenset(names)
    return frozenset()


FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def collect_traced_functions(tree: ast.AST, marked_lines=frozenset()):
    """The set of function/lambda nodes considered traced contexts.

    Seeds: jit-decorated defs, defs/lambdas passed (by name or inline)
    to a tracing combinator, and defs whose ``def`` line carries a
    ``# graftlint: traced`` pragma (``marked_lines``). Propagation:
    lexically nested defs, and same-module call closure (a traced
    function calling module-level ``f`` by bare name makes ``f``
    traced). Cross-module calls are invisible by design — mark the
    entry point with the pragma instead.
    """
    walk_with_parents(tree)
    defs_by_name: dict[str, list[ast.AST]] = {}
    all_funcs = []
    for node in ast.walk(tree):
        if isinstance(node, FuncNode):
            all_funcs.append(node)
            if not isinstance(node, ast.Lambda):
                defs_by_name.setdefault(node.name, []).append(node)

    traced: set[ast.AST] = set()
    for fn in all_funcs:
        if not isinstance(fn, ast.Lambda):
            if any(_jit_decorator(d) for d in fn.decorator_list):
                traced.add(fn)
            if fn.lineno in marked_lines:
                traced.add(fn)

    # names / lambdas handed to combinators anywhere in the module
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if terminal_name(node.func) not in TRACING_COMBINATORS:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Lambda):
                traced.add(arg)
            elif isinstance(arg, ast.Name):
                for d in defs_by_name.get(arg.id, ()):
                    traced.add(d)

    # fixpoint: nesting + same-module bare-name calls
    changed = True
    while changed:
        changed = False
        for fn in all_funcs:
            if fn in traced:
                continue
            if any(p in traced for p in parents(fn)):
                traced.add(fn)
                changed = True
        for fn in list(traced):
            for node in ast.walk(fn):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)):
                    for d in defs_by_name.get(node.func.id, ()):
                        # only module-level helpers: a local def is
                        # already covered by the nesting rule
                        if d not in traced and isinstance(
                                getattr(d, "_gl_parent", None), ast.Module):
                            traced.add(d)
                            changed = True
    return traced


def enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    for p in parents(node):
        if isinstance(p, FuncNode):
            return p
    return None


# ---------------------------------------------------------------------------
# Trace-time staticness evaluation (G001's core)
# ---------------------------------------------------------------------------

# Annotations that make a parameter trace-static (python values baked
# into the compiled graph) vs traced arrays.
STATIC_ANNOTATIONS = frozenset({
    "int", "float", "bool", "str", "tuple", "Spec", "StencilSpec",
})

# Attribute names that are static regardless of their base: array
# metadata, and this repo's struct.field(pytree_node=False) fields on
# BoardGraph / the hashable Spec config (kernel/board.py, kernel/step.py).
STATIC_ATTRS = frozenset({
    "shape", "ndim", "dtype", "size",
    # Spec (frozen dataclass, jit cache key)
    "n_districts", "proposal", "contiguity", "invalid", "accept", "anneal",
    "frame_interface", "weighted_cut", "max_tries", "propose_parallel",
    "record_interface", "parity_metrics", "geom_waits",
    "record_assignment_bits",
    # BoardGraph static fields + derived int properties
    "h", "w", "uniform_pop", "surgical", "real_nodes", "b2_offsets",
    "b2_iters", "patch_exact", "iface_ok", "iface_decode", "center",
    "n", "n_real", "n_nodes", "n_edges",
})

# Call names whose results are trace-static. Split by call shape:
# ``min(a, b)`` (bare builtin over python ints) is static, but
# ``state.min()`` (array method reduction) is traced — the bare set must
# not whitelist attribute calls.
STATIC_CALLS = frozenset({
    "len", "isinstance", "max", "min", "range", "tuple", "zip", "enumerate",
    "getattr", "hasattr", "abs", "int", "float", "bool", "str", "sorted",
    "divmod",
    "supported", "supported_pair", "supported_lowered",
    "geom_denom_finite", "kstep_geom_ok",
    "n_words", "canvas_words", "field",
})
# attribute calls: host predicates over static config + python int methods
STATIC_ATTR_CALLS = frozenset({
    "bit_length", "n_words", "canvas_words",
    "supported", "supported_pair", "supported_lowered",
    "geom_denom_finite", "kstep_geom_ok", "field", "get", "keys", "values",
    "items",
})


def _annotation_name(ann: Optional[ast.AST]) -> Optional[str]:
    if ann is None:
        return None
    if isinstance(ann, ast.Subscript):  # Optional[X] -> look at X? no: name
        return _annotation_name(ann.value)
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.split("[")[0].split(".")[-1]
    name = dotted_name(ann)
    return name.split(".")[-1] if name else None


class StaticEnv:
    """Per-function map of local names to trace-time staticness.

    Built in one forward pass over the function body: parameters are
    classified by annotation (static python types vs traced pytrees) or
    by a jit decorator's ``static_argnames``; single-assignment locals
    inherit the staticness of their right-hand side. Names never
    assigned in the function (globals, builtins, module imports) are
    static. Assign-once is not verified — a rebinding simply overwrites,
    matching forward program order, which is what the rules evaluate
    under.
    """

    def __init__(self, fn: ast.AST):
        self.known: dict[str, bool] = {}
        static_params = frozenset()
        if not isinstance(fn, ast.Lambda):
            for dec in fn.decorator_list:
                static_params |= jit_static_argnames(dec)
        args = fn.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            ann = _annotation_name(getattr(a, "annotation", None))
            self.known[a.arg] = (a.arg in static_params
                                 or ann in STATIC_ANNOTATIONS)
        self._locals = set(self.known)
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and isinstance(node.ctx,
                                                         ast.Store):
                self._locals.add(node.id)

    def bind(self, target: ast.AST, static: bool) -> None:
        if isinstance(target, ast.Name):
            self.known[target.id] = static
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self.bind(elt, static)
        # attribute/subscript stores don't change name staticness

    def is_static(self, node: ast.AST) -> bool:
        """Conservative: True only when the expression is certainly
        trace-static; anything unknown is treated as traced."""
        if isinstance(node, ast.Constant):
            return True
        if isinstance(node, ast.Name):
            if node.id in self.known:
                return self.known[node.id]
            # not a local: module global / import / builtin
            return node.id not in self._locals
        if isinstance(node, ast.Attribute):
            if self.is_static(node.value):
                return True
            return node.attr in STATIC_ATTRS
        if isinstance(node, ast.Call):
            name = terminal_name(node.func)
            if isinstance(node.func, ast.Name):
                return name in STATIC_CALLS
            return name in STATIC_ATTR_CALLS
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None` is a structural (treedef)
            # test — static no matter what x is
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops) \
                    and all(isinstance(c, ast.Constant) and c.value is None
                            for c in node.comparators):
                return True
            return (self.is_static(node.left)
                    and all(self.is_static(c) for c in node.comparators))
        if isinstance(node, ast.BoolOp):
            return all(self.is_static(v) for v in node.values)
        if isinstance(node, ast.BinOp):
            return self.is_static(node.left) and self.is_static(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_static(node.operand)
        if isinstance(node, ast.IfExp):
            return (self.is_static(node.test) and self.is_static(node.body)
                    and self.is_static(node.orelse))
        if isinstance(node, ast.Subscript):
            return self.is_static(node.value) and self.is_static(node.slice)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return all(self.is_static(e) for e in node.elts)
        if isinstance(node, ast.JoinedStr):
            return True
        if isinstance(node, ast.Starred):
            return self.is_static(node.value)
        return False

    def fold_statement(self, stmt: ast.AST) -> None:
        """Update name staticness for one statement (forward order)."""
        if isinstance(stmt, ast.Assign):
            static = self.is_static(stmt.value)
            for t in stmt.targets:
                if (isinstance(t, (ast.Tuple, ast.List))
                        and isinstance(stmt.value, (ast.Tuple, ast.List))
                        and len(t.elts) == len(stmt.value.elts)):
                    for te, ve in zip(t.elts, stmt.value.elts):
                        self.bind(te, self.is_static(ve))
                else:
                    self.bind(t, static)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self.bind(stmt.target, self.is_static(stmt.value))
        elif isinstance(stmt, ast.AugAssign):
            self.bind(stmt.target, self.is_static(stmt.value)
                      and self.is_static(stmt.target))
