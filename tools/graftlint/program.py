"""Whole-program index: symbols, class attribute types, call graph,
thread entry points, and lock-dominance dataflow (ISSUE 19).

Built once per lint run from every ``ParsedModule`` in the invocation,
the :class:`Program` is what turns graftlint from a per-file syntactic
pass into an interprocedural analyzer. The index is deliberately
heuristic in the direction a linter must be — resolution only follows
facts the source states explicitly (constructor calls, parameter / class
-body annotations, ``self`` receivers, intra-repo imports), so an edge
in the call graph is close to certain while a *missing* edge is merely
unknown. Rules built on top (G011/G012) therefore only reason from
resolved edges and stay quiet about the rest; intentional exceptions are
one ``# graftlint:`` pragma away.

Resolution ladder for a call ``expr.m(...)`` / ``f(...)``:

1. ``self.m(...)``      -> method ``m`` of the enclosing class or its
                           indexed bases;
2. typed receivers      -> local vars assigned from an indexed
                           constructor, annotated parameters (incl.
                           ``Optional[X]`` and quoted forwards), class
                           attributes whose ``__init__`` assignment or
                           class-body annotation names an indexed class,
                           and return annotations of resolved callees;
3. module symbols       -> functions/classes defined or imported
                           (``from .x import y``, ``from . import x``)
                           anywhere in the linted set;
4. attribute fallback   -> ``anything.attr`` where exactly ONE indexed
                           class declares ``attr`` with a class type
                           (e.g. ``self.server.front`` via the
                           ``front: FrontDoor`` class-body annotation).

Thread roots recognized: ``threading.Thread`` subclasses (their ``run``
is an entry), ``threading.Thread(target=...)``, ``do_*`` methods of
HTTP handler classes (entered *concurrently* — each counts as two
threads), ``signal.signal(sig, handler)`` callbacks, and callables
passed into a thread-subclass constructor (the ``beat_fn`` pattern:
they run on that thread). Everything with no in-index caller and no
thread reference seeds the implicit **main** root.

Lock facts: an attribute assigned ``threading.Lock/RLock/Condition()``
types as a lock; ``with self._lock:`` (or ``with mod._LOCK:`` /
``with x.attr_lock:`` on typed chains) establishes the lock lexically;
a fixpoint over the call graph then computes, for every function, the
set of locks *guaranteed held on every resolved path from any entry* —
which is exactly the dominance fact G011 needs.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .astutil import dotted_name, parents, walk_with_parents

# Type-lattice tokens for non-class types we care about.
LOCK = "@lock"          # threading.Lock / RLock / Condition
EVENT = "@event"        # threading.Event (atomic by contract)
THREAD = "@thread"      # a threading.Thread instance

_LOCK_CTORS = frozenset({"Lock", "RLock", "Condition"})
_EVENT_CTORS = frozenset({"Event"})
_THREAD_CTORS = frozenset({"Thread"})

# Container-mutating method names (same list G009 uses): a call
# ``self.attr.append(x)`` mutates ``attr`` when attr isn't an indexed
# class (when it is, the call is an edge and the callee is analyzed).
MUTATORS = frozenset({"append", "add", "pop", "update", "setdefault",
                      "insert", "remove", "extend", "clear", "popitem",
                      "discard", "appendleft"})


class FuncInfo:
    """One function/method in the index. Nested defs and lambdas are
    inlined into their enclosing function's body (they execute on the
    same threads unless explicitly handed to a thread, which the
    thread-root seeding handles separately)."""

    __slots__ = ("module", "node", "cls", "name", "qualname")

    def __init__(self, module, node, cls: Optional["ClassInfo"]):
        self.module = module
        self.node = node
        self.cls = cls
        self.name = node.name
        owner = f"{cls.name}." if cls is not None else ""
        self.qualname = f"{module.path}::{owner}{node.name}"

    def __repr__(self):
        return f"<func {self.qualname}>"


class ClassInfo:
    __slots__ = ("module", "node", "name", "bases", "methods",
                 "attr_types", "attr_lines", "attr_values", "is_thread",
                 "is_handler")

    def __init__(self, module, node: ast.ClassDef):
        self.module = module
        self.node = node
        self.name = node.name
        self.bases: List[str] = [d for d in
                                 (dotted_name(b) for b in node.bases)
                                 if d]
        self.methods: Dict[str, FuncInfo] = {}
        # attr -> set of type tokens (ClassInfo objects or @-strings)
        self.attr_types: Dict[str, set] = {}
        # attr -> [lineno, ...] of assignment/annotation sites
        self.attr_lines: Dict[str, List[int]] = {}
        # attr -> [value exprs] (G012 resolves path strings through them)
        self.attr_values: Dict[str, List[ast.AST]] = {}
        self.is_thread = False
        self.is_handler = False

    def attrs(self) -> Set[str]:
        return set(self.attr_types)

    def __repr__(self):
        return f"<class {self.module.path}::{self.name}>"


class Root:
    """A source of control flow. ``weight`` counts how many concurrent
    threads the root contributes (HTTP handlers are entered by a
    threaded server, hence 2)."""

    __slots__ = ("kind", "label", "entries", "weight")

    def __init__(self, kind: str, label: str, weight: int = 1):
        self.kind = kind          # "main" | "thread" | "handler" | "signal"
        self.label = label        # display, e.g. "thread:_Heartbeat.run"
        self.entries: List[FuncInfo] = []
        self.weight = weight

    def __repr__(self):
        return f"<root {self.label} w={self.weight}>"


class Access:
    """One attribute access site."""

    __slots__ = ("func", "node", "line", "is_store", "lexical_locks")

    def __init__(self, func: FuncInfo, node: ast.AST, is_store: bool,
                 lexical_locks: frozenset):
        self.func = func
        self.node = node
        self.line = getattr(node, "lineno", 1)
        self.is_store = is_store
        self.lexical_locks = lexical_locks


def _self_name(func_node) -> Optional[str]:
    args = func_node.args
    if args.args:
        return args.args[0].arg
    return None


def _ann_names(ann: Optional[ast.AST]) -> List[str]:
    """Candidate type names from an annotation: unwraps Optional[...]/
    quoted forwards; returns dotted names."""
    if ann is None:
        return []
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return []
    if isinstance(ann, ast.Subscript):
        head = dotted_name(ann.value) or ""
        if head.split(".")[-1] in ("Optional", "Union"):
            inner = ann.slice
            elts = inner.elts if isinstance(inner, ast.Tuple) else [inner]
            out: List[str] = []
            for e in elts:
                out.extend(_ann_names(e))
            return out
        return []
    d = dotted_name(ann)
    return [d] if d else []


class Program:
    """The cross-module index. ``modules`` maps repo-relative posix
    paths to ParsedModule; ``shell_files`` is the list of
    ``engine.ShellFile`` gate scripts G013 scans."""

    def __init__(self, modules: Dict[str, object],
                 shell_files: Optional[list] = None):
        self.modules = modules
        self.shell_files = shell_files or []

        self.classes: List[ClassInfo] = []
        self.functions: List[FuncInfo] = []
        # relpath -> {local name: ("class", ClassInfo) | ("func", FuncInfo)
        #             | ("module", relpath) | ("const", value)}
        self.symbols: Dict[str, Dict[str, tuple]] = {}
        self._cls_of_node: Dict[int, ClassInfo] = {}
        self._func_of_node: Dict[int, FuncInfo] = {}
        # attr name -> [ClassInfo] declaring it with a class-typed value
        self._attr_owners: Dict[str, List[ClassInfo]] = {}
        # call edges: (caller, callee, frozenset of lock ids at the site)
        self.edges: List[Tuple[FuncInfo, FuncInfo, frozenset]] = []
        self._edges_in: Dict[FuncInfo, List[tuple]] = {}
        self.roots: List[Root] = []
        # attribute accesses: (ClassInfo, attr) -> [Access]
        self.accesses: Dict[Tuple[ClassInfo, str], List[Access]] = {}
        self.held: Dict[FuncInfo, Optional[frozenset]] = {}
        self._reach: Dict[int, Set[FuncInfo]] = {}
        self._init_ctx: Set[FuncInfo] = set()

        self._build()

    # -- construction -------------------------------------------------

    def _build(self) -> None:
        for mod in self.modules.values():
            walk_with_parents(mod.tree)
            self._index_module(mod)
        for cls in self.classes:
            self._resolve_bases(cls)
        # two passes: pass 2 types attrs assigned from attrs of classes
        # indexed later in pass 1 (``self.front = httpd.front``)
        for _ in range(2):
            for mod in self.modules.values():
                self._collect_class_attrs(mod)
        for attr, owners in list(self._attr_owners.items()):
            # dedupe, keep deterministic order
            seen: List[ClassInfo] = []
            for c in owners:
                if c not in seen:
                    seen.append(c)
            self._attr_owners[attr] = seen
        for func in self.functions:
            self._walk_function(func)
        self._seed_roots()
        self._compute_reach()
        self._compute_init_ctx()
        self._compute_held()

    def _index_module(self, mod) -> None:
        table: Dict[str, tuple] = {}
        self.symbols[mod.path] = table
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                cls = ClassInfo(mod, node)
                self.classes.append(cls)
                self._cls_of_node[id(node)] = cls
                table[cls.name] = ("class", cls)
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        fi = FuncInfo(mod, item, cls)
                        cls.methods[item.name] = fi
                        self.functions.append(fi)
                        self._func_of_node[id(item)] = fi
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = FuncInfo(mod, node, None)
                self.functions.append(fi)
                self._func_of_node[id(node)] = fi
                table[node.name] = ("func", fi)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    if (isinstance(node.value, ast.Constant)
                            and isinstance(node.value.value, str)):
                        table[tgt.id] = ("const", node.value.value)
                    else:
                        tok = self._builtin_ctor_token(node.value)
                        if tok:
                            table[tgt.id] = ("token", tok)
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                self._index_import(mod, node, table)
            elif (isinstance(node, ast.If)
                    and (dotted_name(node.test) or "").split(".")[-1]
                    == "TYPE_CHECKING"):
                # typing-only imports back quoted annotations
                for stmt in node.body:
                    if isinstance(stmt, (ast.Import, ast.ImportFrom)):
                        self._index_import(mod, stmt, table)

    def _index_import(self, mod, node, table) -> None:
        if isinstance(node, ast.Import):
            return  # absolute external imports: not resolved
        target = self._resolve_module(mod.path, node.level, node.module)
        for alias in node.names:
            local = alias.asname or alias.name
            # ``from .pkg import mod`` names a submodule, ``from .mod
            # import sym`` names a symbol; try submodule first (the
            # submodule can resolve even when the package __init__
            # isn't part of the lint set, e.g. fixture trees).
            sub = self._resolve_module(mod.path, node.level,
                                       (node.module + "." + alias.name)
                                       if node.module else alias.name)
            if sub is not None:
                table[local] = ("module", sub)
            elif target is not None:
                table[local] = ("import", target, alias.name)

    def _resolve_module(self, relpath: str, level: int,
                        module: Optional[str]) -> Optional[str]:
        """Resolve a relative import to a relpath in the linted set."""
        if level == 0:
            # absolute: try to match a linted top-level package
            parts = (module or "").split(".")
        else:
            base = relpath.split("/")[:-1]
            if relpath.endswith("/__init__.py"):
                base = relpath.split("/")[:-1]
            for _ in range(level - 1):
                if base:
                    base = base[:-1]
            parts = base + ((module or "").split(".") if module else [])
            parts = [p for p in parts if p]
        if not parts:
            return None
        cand = "/".join(parts)
        for suffix in (cand + ".py", cand + "/__init__.py"):
            if suffix in self.modules:
                return suffix
        return None

    def lookup(self, relpath: str, name: str, _depth: int = 0):
        """Resolve a (possibly dotted) name in a module to a
        ("class"|"func"|"const"|"token", payload) entry, following
        import and module links."""
        if _depth > 8:
            return None
        table = self.symbols.get(relpath)
        if table is None:
            return None
        head, _, rest = name.partition(".")
        entry = table.get(head)
        if entry is None:
            return None
        kind = entry[0]
        if kind == "module":
            if not rest:
                return entry
            return self.lookup(entry[1], rest, _depth + 1)
        if kind == "import":
            resolved = self.lookup(entry[1], entry[2], _depth + 1)
            if resolved is None:
                return None
            if rest:
                if resolved[0] == "module":
                    return self.lookup(resolved[1], rest, _depth + 1)
                return None
            return resolved
        if rest:
            return None
        return entry

    def _builtin_ctor_token(self, expr) -> Optional[str]:
        if not isinstance(expr, ast.Call):
            return None
        term = dotted_name(expr.func)
        term = term.split(".")[-1] if term else None
        if term in _LOCK_CTORS:
            return LOCK
        if term in _EVENT_CTORS:
            return EVENT
        if term in _THREAD_CTORS:
            return THREAD
        return None

    def _resolve_bases(self, cls: ClassInfo) -> None:
        seen: Set[int] = set()

        def is_thread(c: ClassInfo) -> bool:
            if id(c) in seen:
                return False
            seen.add(id(c))
            for b in c.bases:
                if b.split(".")[-1] == "Thread":
                    return True
                ent = self.lookup(c.module.path, b)
                if ent and ent[0] == "class" and is_thread(ent[1]):
                    return True
            return False

        cls.is_thread = is_thread(cls)
        cls.is_handler = (
            any(b.split(".")[-1] == "BaseHTTPRequestHandler"
                for b in cls.bases)
            or any(m.startswith("do_") and m[3:].isupper()
                   for m in cls.methods))

    # -- class attribute typing ---------------------------------------

    def _collect_class_attrs(self, mod) -> None:
        for node in mod.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            cls = self._cls_of_node[id(node)]
            for item in node.body:
                # class-body annotations: ``front: FrontDoor``
                if isinstance(item, ast.AnnAssign) and isinstance(
                        item.target, ast.Name):
                    self._note_attr(cls, item.target.id, item.lineno,
                                    self._types_from_ann(mod,
                                                         item.annotation),
                                    item.value)
                elif isinstance(item, ast.Assign):
                    for tgt in item.targets:
                        if isinstance(tgt, ast.Name):
                            self._note_attr(cls, tgt.id, item.lineno,
                                            set(), item.value)
            for meth in cls.methods.values():
                sname = _self_name(meth.node)
                if sname is None:
                    continue
                env = self._param_env(mod, meth.node)
                for sub in ast.walk(meth.node):
                    tgt = None
                    ann = None
                    if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                        tgt = sub.targets[0]
                    elif isinstance(sub, ast.AnnAssign):
                        tgt = sub.target
                        ann = sub.annotation
                    else:
                        continue
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == sname):
                        types: set = set()
                        if ann is not None:
                            types |= self._types_from_ann(mod, ann)
                        if sub.value is not None:
                            types |= self._types_of_expr(
                                mod, sub.value, env, cls, sname)
                        self._note_attr(cls, tgt.attr, sub.lineno, types,
                                        sub.value)

    def _note_attr(self, cls: ClassInfo, attr: str, lineno: int,
                   types: set, value: Optional[ast.AST] = None) -> None:
        cls.attr_types.setdefault(attr, set()).update(types)
        if lineno not in cls.attr_lines.setdefault(attr, []):
            cls.attr_lines[attr].append(lineno)
        if value is not None:
            vals = cls.attr_values.setdefault(attr, [])
            if all(v is not value for v in vals):
                vals.append(value)
        for t in types:
            if isinstance(t, ClassInfo):
                self._attr_owners.setdefault(attr, []).append(cls)

    def _types_from_ann(self, mod, ann) -> set:
        out: set = set()
        for name in _ann_names(ann):
            term = name.split(".")[-1]
            if term in _LOCK_CTORS:
                out.add(LOCK)
            elif term in _EVENT_CTORS:
                out.add(EVENT)
            elif term in _THREAD_CTORS:
                out.add(THREAD)
            ent = self.lookup(mod.path, name) \
                or self.lookup(mod.path, term)
            if ent and ent[0] == "class":
                out.add(ent[1])
        return out

    def _param_env(self, mod, func_node) -> Dict[str, set]:
        env: Dict[str, set] = {}
        a = func_node.args
        for arg in (list(a.posonlyargs) + list(a.args)
                    + list(a.kwonlyargs)):
            types = self._types_from_ann(mod, arg.annotation)
            if types:
                env[arg.arg] = types
        return env

    def _types_of_expr(self, mod, expr, env, cls, sname) -> set:
        """Best-effort type of an expression: a set of ClassInfo /
        @-token candidates (empty when unknown)."""
        tok = self._builtin_ctor_token(expr)
        if tok:
            return {tok}
        if isinstance(expr, ast.Call):
            d = dotted_name(expr.func)
            if d:
                ent = self.lookup(mod.path, d)
                if ent and ent[0] == "class":
                    return {ent[1]}
                if ent and ent[0] == "func":
                    ret = getattr(ent[1].node, "returns", None)
                    return self._types_from_ann(ent[1].module, ret)
            # method call on a typed receiver with a return annotation
            if isinstance(expr.func, ast.Attribute):
                recv = self._types_of_expr(mod, expr.func.value, env,
                                           cls, sname)
                out: set = set()
                for t in recv:
                    if isinstance(t, ClassInfo):
                        m = self._find_method(t, expr.func.attr)
                        if m is not None:
                            ret = getattr(m.node, "returns", None)
                            out |= self._types_from_ann(m.module, ret)
                return out
            return set()
        if isinstance(expr, ast.Name):
            if expr.id in env:
                return env[expr.id]
            ent = self.lookup(mod.path, expr.id)
            if ent and ent[0] == "class":
                return {ent[1]}
            if ent and ent[0] == "token":
                return {ent[1]}
            return set()
        if isinstance(expr, ast.Attribute):
            if (isinstance(expr.value, ast.Name) and cls is not None
                    and sname is not None and expr.value.id == sname):
                return set(cls.attr_types.get(expr.attr, ()))
            base = self._types_of_expr(mod, expr.value, env, cls, sname)
            out = set()
            for t in base:
                if isinstance(t, ClassInfo):
                    out |= set(t.attr_types.get(expr.attr, ()))
            if out:
                return out
            # global fallback: every indexed declarer of this attr
            # agrees on ONE class type -> safe to assume it
            owners = self._attr_owners.get(expr.attr, [])
            cand: set = set()
            for o in owners:
                cand |= {t for t in o.attr_types.get(expr.attr, set())
                         if isinstance(t, ClassInfo)}
            if len(cand) == 1:
                return cand
            return set()
        if isinstance(expr, ast.IfExp):
            return (self._types_of_expr(mod, expr.body, env, cls, sname)
                    | self._types_of_expr(mod, expr.orelse, env, cls,
                                          sname))
        if isinstance(expr, ast.Await):
            return self._types_of_expr(mod, expr.value, env, cls, sname)
        return set()

    def _find_method(self, cls: ClassInfo, name: str,
                     _depth: int = 0) -> Optional[FuncInfo]:
        if _depth > 8:
            return None
        if name in cls.methods:
            return cls.methods[name]
        for b in cls.bases:
            ent = self.lookup(cls.module.path, b)
            if ent and ent[0] == "class":
                m = self._find_method(ent[1], name, _depth + 1)
                if m is not None:
                    return m
        return None

    # -- per-function walk: env, edges, locks, accesses ---------------

    def _local_env(self, func: FuncInfo) -> Dict[str, set]:
        mod, cls = func.module, func.cls
        sname = _self_name(func.node) if cls else None
        env = self._param_env(mod, func.node)
        # two passes so forward-defined locals still type
        for _ in range(2):
            for sub in ast.walk(func.node):
                if (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                        and isinstance(sub.targets[0], ast.Name)):
                    t = self._types_of_expr(mod, sub.value, env, cls,
                                            sname)
                    if t:
                        env.setdefault(sub.targets[0].id, set()).update(t)
                elif (isinstance(sub, ast.AnnAssign)
                        and isinstance(sub.target, ast.Name)):
                    t = self._types_from_ann(mod, sub.annotation)
                    if t:
                        env.setdefault(sub.target.id, set()).update(t)
        return env

    def _lock_id_of(self, expr, func: FuncInfo, env) -> Optional[tuple]:
        """Lock identity of a ``with`` context expression, or None."""
        mod, cls = func.module, func.cls
        sname = _self_name(func.node) if cls else None
        if isinstance(expr, ast.Name):
            ent = self.lookup(mod.path, expr.id)
            if ent and ent[0] == "token" and ent[1] == LOCK:
                return ("mod", mod.path, expr.id)
            local = env.get(expr.id, set())
            if LOCK in local:
                return ("local", func.qualname, expr.id)
            return None
        if isinstance(expr, ast.Attribute):
            if (isinstance(expr.value, ast.Name) and cls is not None
                    and sname is not None and expr.value.id == sname):
                if LOCK in cls.attr_types.get(expr.attr, ()):
                    return ("attr", self._lock_owner(cls, expr.attr),
                            expr.attr)
                return None
            base = self._types_of_expr(mod, expr.value, env, cls, sname)
            for t in base:
                if isinstance(t, ClassInfo) and LOCK in t.attr_types.get(
                        expr.attr, ()):
                    return ("attr", self._lock_owner(t, expr.attr),
                            expr.attr)
        return None

    def _lock_owner(self, cls: ClassInfo, attr: str) -> str:
        """Canonical owner key so ``self._lock`` in a base and a child
        name the same lock."""
        return f"{cls.module.path}::{cls.name}"

    def _lexical_locks(self, node, func: FuncInfo, env) -> frozenset:
        locks = set()
        for p in parents(node):
            if p is func.node:
                break
            if isinstance(p, (ast.With, ast.AsyncWith)):
                for item in p.items:
                    lid = self._lock_id_of(item.context_expr, func, env)
                    if lid is not None:
                        locks.add(lid)
        return frozenset(locks)

    def _self_attr_chain_root(self, node, sname) -> Optional[str]:
        """For a Subscript/Attribute chain rooted at ``self.A``, the
        attr name A; else None."""
        while isinstance(node, (ast.Subscript, ast.Attribute)):
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == sname):
                return node.attr
            node = node.value
        return None

    def _record_access(self, cls: ClassInfo, attr: str, func: FuncInfo,
                       node, is_store: bool, env) -> None:
        acc = Access(func, node, is_store,
                     self._lexical_locks(node, func, env))
        self.accesses.setdefault((cls, attr), []).append(acc)

    def _walk_function(self, func: FuncInfo) -> None:
        mod, cls = func.module, func.cls
        sname = _self_name(func.node) if cls else None
        env = self._local_env(func)

        for node in ast.walk(func.node):
            if isinstance(node, ast.Attribute):
                is_store = isinstance(node.ctx, (ast.Store, ast.Del))
                # self.A loads/stores
                if (isinstance(node.value, ast.Name) and sname is not None
                        and node.value.id == sname):
                    self._record_access(cls, node.attr, func, node,
                                        is_store, env)
                elif is_store:
                    # cross-object store: ``x.front = ...`` on typed x
                    for t in self._types_of_expr(mod, node.value, env,
                                                 cls, sname):
                        if isinstance(t, ClassInfo):
                            self._record_access(t, node.attr, func, node,
                                                True, env)
            elif (isinstance(node, ast.Subscript)
                    and isinstance(node.ctx, (ast.Store, ast.Del))
                    and sname is not None):
                attr = self._self_attr_chain_root(node, sname)
                if attr is not None:
                    self._record_access(cls, attr, func, node, True, env)
            elif isinstance(node, ast.Call):
                self._walk_call(func, node, env, sname)

    def _walk_call(self, func: FuncInfo, node: ast.Call, env,
                   sname) -> None:
        mod, cls = func.module, func.cls
        site_locks = self._lexical_locks(node, func, env)
        callees: List[FuncInfo] = []
        ctor_of: Optional[ClassInfo] = None
        fn = node.func

        if isinstance(fn, ast.Attribute):
            recv_types: set = set()
            if (isinstance(fn.value, ast.Name) and sname is not None
                    and fn.value.id == sname and cls is not None):
                m = self._find_method(cls, fn.attr)
                if m is not None:
                    callees.append(m)
                elif fn.attr in MUTATORS:
                    pass  # self.append? no such attr: ignore
            else:
                recv_types = self._types_of_expr(mod, fn.value, env, cls,
                                                 sname)
                for t in recv_types:
                    if isinstance(t, ClassInfo):
                        m = self._find_method(t, fn.attr)
                        if m is not None:
                            callees.append(m)
                if not callees and fn.attr in MUTATORS and sname is not None:
                    # mutator on a container hanging off self.A
                    attr = self._self_attr_chain_root(fn.value, sname)
                    if attr is not None and not any(
                            isinstance(t, ClassInfo)
                            for t in cls.attr_types.get(attr, ())):
                        self._record_access(cls, attr, func, node, True,
                                            env)
            d = dotted_name(fn)
            if d:
                ent = self.lookup(mod.path, d)
                if ent and ent[0] == "func":
                    callees.append(ent[1])
                elif ent and ent[0] == "class":
                    ctor_of = ent[1]
            # signal handler registration
            if fn.attr == "signal" and len(node.args) >= 2:
                self._seed_callable(node.args[1], func, env, sname,
                                    kind="signal")
            # raw Thread(target=...) on a dotted threading.Thread
            if fn.attr == "Thread":
                self._thread_ctor(node, func, env, sname)
        elif isinstance(fn, ast.Name):
            ent = self.lookup(mod.path, fn.id)
            if ent and ent[0] == "func":
                callees.append(ent[1])
            elif ent and ent[0] == "class":
                ctor_of = ent[1]
            if fn.id == "Thread":
                self._thread_ctor(node, func, env, sname)

        if ctor_of is not None:
            init = self._find_method(ctor_of, "__init__")
            if init is not None:
                callees.append(init)
            if ctor_of.is_thread:
                # callables handed to a thread-subclass constructor run
                # on that thread (the beat_fn pattern)
                root = self._root("thread",
                                  f"thread:{ctor_of.name}", 1)
                runm = self._find_method(ctor_of, "run")
                if runm is not None and runm not in root.entries:
                    root.entries.append(runm)
                for arg in list(node.args) + [k.value for k in
                                              node.keywords]:
                    self._seed_callable(arg, func, env, sname,
                                        kind="thread", root=root)

        for callee in callees:
            self._add_edge(func, callee, site_locks)

    def _thread_ctor(self, node: ast.Call, func, env, sname) -> None:
        for kw in node.keywords:
            if kw.arg == "target":
                self._seed_callable(kw.value, func, env, sname,
                                    kind="thread")

    def _seed_callable(self, expr, func: FuncInfo, env, sname,
                       kind: str, root: Optional[Root] = None) -> None:
        """Register a callable reference (self.m / module func /
        lambda) as an entry of a thread/signal root."""
        mod, cls = func.module, func.cls
        targets: List[FuncInfo] = []
        if isinstance(expr, ast.Attribute):
            recv: set = set()
            if (isinstance(expr.value, ast.Name) and sname is not None
                    and expr.value.id == sname and cls is not None):
                recv = {cls}
            else:
                recv = self._types_of_expr(mod, expr.value, env, cls,
                                           sname)
            for t in recv:
                if isinstance(t, ClassInfo):
                    m = self._find_method(t, expr.attr)
                    if m is not None:
                        targets.append(m)
        elif isinstance(expr, ast.Name):
            ent = self.lookup(mod.path, expr.id)
            if ent and ent[0] == "func":
                targets.append(ent[1])
        elif isinstance(expr, ast.Lambda):
            # the lambda body runs on the new thread; its self.m()
            # calls become entries
            for sub in ast.walk(expr):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and isinstance(sub.func.value, ast.Name)
                        and sname is not None
                        and sub.func.value.id == sname
                        and cls is not None):
                    m = self._find_method(cls, sub.func.attr)
                    if m is not None:
                        targets.append(m)
        if not targets:
            return
        if root is None:
            label = f"{kind}:{targets[0].qualname.rsplit('::', 1)[-1]}"
            root = self._root(kind, label, 1)
        for t in targets:
            if t not in root.entries:
                root.entries.append(t)

    def _root(self, kind: str, label: str, weight: int) -> Root:
        for r in self.roots:
            if r.kind == kind and r.label == label:
                return r
        r = Root(kind, label, weight)
        self.roots.append(r)
        return r

    def _add_edge(self, caller: FuncInfo, callee: FuncInfo,
                  locks: frozenset) -> None:
        self.edges.append((caller, callee, locks))
        self._edges_in.setdefault(callee, []).append((caller, locks))

    # -- roots, reachability, dominance -------------------------------

    def _seed_roots(self) -> None:
        for cls in self.classes:
            if cls.is_thread and "run" in cls.methods:
                root = self._root("thread", f"thread:{cls.name}", 1)
                if cls.methods["run"] not in root.entries:
                    root.entries.append(cls.methods["run"])
            if cls.is_handler:
                for name, m in cls.methods.items():
                    if name.startswith("do_") and name[3:].isupper():
                        r = self._root("handler",
                                       f"handler:{cls.name}.{name}", 2)
                        if m not in r.entries:
                            r.entries.append(m)

        threaded = set()
        for r in self.roots:
            threaded.update(r.entries)
        called = set(self._edges_in)
        main = self._root("main", "main", 1)
        for f in self.functions:
            if f not in called and f not in threaded:
                main.entries.append(f)

    def _compute_reach(self) -> None:
        out: Dict[FuncInfo, List[FuncInfo]] = {}
        for caller, callee, _ in self.edges:
            out.setdefault(caller, []).append(callee)
        for root in self.roots:
            seen: Set[FuncInfo] = set()
            work = list(root.entries)
            while work:
                f = work.pop()
                if f in seen:
                    continue
                seen.add(f)
                work.extend(out.get(f, ()))
            self._reach[id(root)] = seen

    def roots_reaching(self, func: FuncInfo) -> List[Root]:
        return [r for r in self.roots if func in self._reach[id(r)]]

    def _compute_held(self) -> None:
        entries = set()
        for r in self.roots:
            entries.update(r.entries)
        held: Dict[FuncInfo, Optional[frozenset]] = {}
        for f in self.functions:
            held[f] = frozenset() if f in entries else None
        changed = True
        iters = 0
        while changed and iters < 50:
            changed = False
            iters += 1
            for f in self.functions:
                if f in entries:
                    continue
                acc: Optional[frozenset] = None
                unknown = False
                callers = self._edges_in.get(f, ())
                live = [(c, lk) for c, lk in callers
                        if c not in self._init_ctx]
                # construction-time call sites can't race: they don't
                # weaken the lock guarantee of the live callers
                for caller, locks in live or callers:
                    h = held.get(caller)
                    if h is None:
                        unknown = True
                        continue
                    site = h | locks
                    acc = site if acc is None else (acc & site)
                if unknown and acc is None:
                    continue  # stay TOP until a caller resolves
                if acc is None:
                    acc = frozenset()
                if held[f] != acc:
                    held[f] = acc
                    changed = True
        self.held = held

    def held_locks(self, func: FuncInfo) -> frozenset:
        h = self.held.get(func)
        return h if h is not None else frozenset()

    def _compute_init_ctx(self) -> None:
        """Functions reachable ONLY from constructors: their stores are
        construction-time and exempt from lock dominance."""
        init_ctx = {f for f in self.functions
                    if f.cls is not None and f.name == "__init__"}
        entries = set()
        for r in self.roots:
            if r.kind != "main":
                entries.update(r.entries)
        changed = True
        while changed:
            changed = False
            for f in self.functions:
                if f in init_ctx or f in entries:
                    continue
                callers = [c for c, _ in self._edges_in.get(f, ())]
                if callers and all(c in init_ctx for c in callers):
                    init_ctx.add(f)
                    changed = True
        self._init_ctx = init_ctx

    def is_init_context(self, func: FuncInfo) -> bool:
        return func in self._init_ctx


def build_program(modules: Dict[str, object],
                  shell_files: Optional[List[tuple]] = None) -> Program:
    return Program(modules, shell_files)
