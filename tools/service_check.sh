#!/usr/bin/env bash
# Sweep-service CI gate (`make service-check`): three jobs — two
# coalescible tenants and one poison config — must produce exactly one
# coalesced batch (ONE compile_cache_miss for the pair: the second
# tenant rides the first's compile), a quarantined poison job with the
# survivors unharmed, a valid merged event stream (obs_report --check),
# and a probeable namespaced heartbeat set (ISSUE 9). The full matrix —
# bit-identity vs solo runs, retry/backoff taxonomy, simulation-mode
# efficiency — lives in tests/test_service.py; this is the fast tier-1
# smoke (<30s on CPU).
#
#   tools/service_check.sh
#
# Exercised by tests/test_service.py, so tier-1 fails when the gate rots.
set -euo pipefail

cd "$(dirname "$0")/.."
PY="${PYTHON:-python}"
TD="$(mktemp -d)"
trap 'rm -rf "$TD"' EXIT

# stable XLA cache across gate runs (tier-1 wraps this script): the
# compile_cache_miss assertions below count the service's OWN on-disk
# CompileCache index — a different layer — so XLA cache warmth never
# changes them, only the wall clock
export JAX_COMPILATION_CACHE_DIR="${GRAFT_GATE_JAX_CACHE:-${TMPDIR:-/tmp}/graft-gate-jax-cache}"
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=1
mkdir -p "$JAX_COMPILATION_CACHE_DIR"

JAX_PLATFORMS=cpu "$PY" - "$TD" <<'PYEOF'
import json
import os
import sys
from collections import Counter

from flipcomplexityempirical_tpu import obs
from flipcomplexityempirical_tpu.experiments.config import ExperimentConfig
from flipcomplexityempirical_tpu.service import SweepService

td = sys.argv[1]
ev = os.path.join(td, "events.jsonl")
rec = obs.Recorder(ev)
svc = SweepService(outdir=td, recorder=rec,
                   heartbeat=os.path.join(td, "heartbeat.json"))
# two coalescible tenants: same fingerprint (kernel statics), distinct
# tags/plans/seeds; the poison job demands the python backend, which the
# service rejects deterministically -> quarantine after the solo retry
base = dict(family="frank", base=0.3, pop_tol=0.1, total_steps=120,
            n_chains=2, backend="jax")
a = svc.submit(ExperimentConfig(alignment=2, seed=3, **base))
b = svc.submit(ExperimentConfig(alignment=1, seed=7, **base))
p = svc.submit(ExperimentConfig(alignment=0,
                                **{**base, "backend": "python"}))
assert a.fingerprint == b.fingerprint, "pair must coalesce"
assert p.fingerprint != a.fingerprint, "poison must not coalesce"
svc.run_until_idle()
rec.close()

assert a.status == "done" and b.status == "done", (a.error, b.error)
assert a.batch == b.batch, "pair did not share a batch"
assert p.status == "quarantined", (p.status, p.error)
assert svc.exit_code != 0, "quarantine must surface in the exit code"

evs = [json.loads(line) for line in open(ev)]
c = Counter(e["event"] for e in evs)
assert c["job_submitted"] == 3 and c["job_done"] == 3, dict(c)
# the amortization proof: ONE miss covers both tenants, and the poison
# job dies before ever reaching the compile probe
assert c["compile_cache_miss"] == 1, dict(c)
assert c.get("compile_cache_hit", 0) == 0, dict(c)
assert c["config_quarantined"] == 1 and c["retry"] == 1, dict(c)
batched = [e for e in evs if e["event"] == "job_batched"]
assert len(batched) == 1, batched
assert sorted(batched[0]["jobs"]) == sorted([a.job_id, b.job_id]), batched
assert batched[0]["chains"] == 4, batched

hb = json.load(open(os.path.join(td, "heartbeat.json")))
assert hb["status"] == "complete_with_failures", hb["status"]
assert {j["status"] for j in hb["jobs"].values()} == \
    {"done", "quarantined"}, hb["jobs"]
per_job = sorted(f for f in os.listdir(td) if f.startswith("heartbeat."))
assert f"heartbeat.{a.tag}.json" in per_job, per_job
print("service-check: 1 batch, 1 compile_cache_miss, poison "
      f"quarantined ({dict(c)})")
PYEOF

"$PY" tools/obs_report.py "$TD/events.jsonl" --check
"$PY" tools/obs_report.py "$TD/events.jsonl" \
    --heartbeat "$TD/heartbeat.json" >/dev/null
echo "service-check: OK"
