#!/bin/bash
# One full on-chip capture set, priority-ordered (VERDICT r4 next-1/2/3).
# Assumes the probe just succeeded. Each record is written to bench_runs/
# and committed IMMEDIATELY so a tunnel drop mid-set loses nothing.
# A record that comes back "cpu_fallback" is kept on disk (*.fallback)
# but NOT committed and aborts the set — the tunnel dropped again.
set -u
cd "$(dirname "$0")/.."
mkdir -p bench_runs
TS=$(date -u +%Y%m%dT%H%M%SZ)

commit_retry() {
  for _ in 1 2 3 4 5; do
    git add "$@" && git commit -q -m "TPU watchdog: capture $(basename "$1")" && return 0
    sleep 7
  done
  return 1
}

run_bench() { # name timeout args...
  local name=$1 tmo=$2; shift 2
  local out="bench_runs/${TS}_${name}.json" err="bench_runs/${TS}_${name}.err"
  timeout "$tmo" python bench.py "$@" >"$out" 2>"$err"
  local rc=$?
  if [ $rc -ne 0 ] || [ ! -s "$out" ]; then
    echo "capture $name: rc=$rc, aborting set" >&2
    return 1
  fi
  if grep -q cpu_fallback "$out"; then
    mv "$out" "$out.fallback"
    echo "capture $name: tunnel dropped (cpu_fallback), aborting set" >&2
    return 1
  fi
  commit_retry "$out" "$err"
}

# 1. THE scoreboard record: default board bench, both bodies
run_bench default 900 || exit 1
# 2. ESS-per-second axis (BASELINE wall-clock-to-target-ESS)
run_bench ess 900 --ess || exit 1
# 3. Pallas timing
run_bench pallas 900 --pallas
# 4. Pallas bit-exactness on silicon
timeout 600 python tools/pallas_exact.py \
  >"bench_runs/${TS}_pallas_exact.json" 2>"bench_runs/${TS}_pallas_exact.err"
commit_retry "bench_runs/${TS}_pallas_exact.json" "bench_runs/${TS}_pallas_exact.err"
# 5. Chain-count scaling (>=1e4-chain axis)
run_bench c8192 1200 --chains 8192
run_bench c16384 1800 --chains 16384
# 6. General-path record refresh (round-2's 0.30x was this path)
run_bench general 900 --general
# 7. ESS with thinning (record_every ~ IAT)
run_bench ess_thin 900 --ess --record-every 10
touch bench_runs/CAPTURED_${TS}
commit_retry bench_runs/CAPTURED_${TS}
echo "capture set complete: ${TS}"
