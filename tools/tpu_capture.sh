#!/bin/bash
# One full on-chip capture set, priority-ordered (VERDICT r4 next-1/2/3).
# Assumes the probe just succeeded. Each record is written to bench_runs/
# and committed IMMEDIATELY so a tunnel drop mid-set loses nothing.
# A record that comes back "cpu_fallback" is quarantined on disk
# (*.fallback) but NOT committed and aborts the set — the tunnel dropped
# again. Helpers are shared with tpu_followup_r5.sh via bench_lib.sh.
set -u
cd "$(dirname "$0")/.."
mkdir -p bench_runs
TS=$(date -u +%Y%m%dT%H%M%SZ)
. tools/bench_lib.sh

# 1. THE scoreboard record: default board bench, both bodies
run_bench default 900 || exit 1
# 2. ESS-per-second axis (BASELINE wall-clock-to-target-ESS)
run_bench ess 900 --ess || exit 1
# 3. Pallas timing
run_bench pallas 900 --pallas
# 4. Pallas bit-exactness on silicon
timeout 600 python tools/pallas_exact.py \
  >"bench_runs/${TS}_pallas_exact.json" 2>"bench_runs/${TS}_pallas_exact.err"
commit_retry "bench_runs/${TS}_pallas_exact.json" "bench_runs/${TS}_pallas_exact.err"
# 5. Chain-count scaling (>=1e4-chain axis)
run_bench c8192 1200 --chains 8192
run_bench c16384 1800 --chains 16384
# 5b. Lowered-family headlines (round 8): sec11/frank race the packed
#     lowered_bits body against the int8 lowered body ("body" in the
#     record says which won), and the sec11 C=16384 row measures whether
#     bit-packing reclaimed the HBM-bound falloff PROFILE.md pinned on
#     int-plane traffic
run_bench sec11 900 --graph sec11
run_bench frank 900 --graph frank
run_bench sec11_c16384 1800 --graph sec11 --chains 16384
# 6. General-path record refresh (round-2's 0.30x was this path)
run_bench general 900 --general
# 6b. General-dense headlines (round 14): hex races the rejection-free
#     general_dense body against the legacy general kernel (kernel_path
#     in the record says which won; CPU gate is >=2x at 32x32/C=256,
#     >=3x is the silicon aspiration), and the dual-fixture matrix rows
#     price the new path on the real 80-precinct ingestion family —
#     both BENCH trajectories were empty before this round
run_bench hex 900 --graph hex --grid 32
run_bench dual_fixture 900 --workload-matrix \
  --workloads dual-fixture,dual-fixture-k4,dual-fixture-k8
# 7. ESS with thinning (record_every ~ IAT)
run_bench ess_thin 900 --ess --record-every 10
# 8. Sweep-service tenant efficiency (round 9): 4 coalescible tenants
#    drained as one batch vs a solo tenant, compile included — on-chip
#    this prices both the compile amortization AND the device's real
#    batch-occupancy headroom (CPU simulation can only show the former)
run_bench service 900 --service --graph frank --steps 2001
# 9. Workload-catalog matrix (round 13): one per-family record per named
#    workload — flip grids, the dual-graph fixture, ReCom, variants —
#    gated per [workload=...] by bench_compare so families never
#    cross-gate; on-chip this prices the recom scan and the general-path
#    variants against their CPU records
run_bench workloads 1200 --workload-matrix
touch bench_runs/CAPTURED_${TS}
commit_retry bench_runs/CAPTURED_${TS}
echo "capture set complete: ${TS}"
