#!/usr/bin/env bash
# Device-resident analytics CI gate (`make devstats-check`, ISSUE 20):
# moving the telemetry plane on-chip must change NOTHING downstream and
# pay for itself in readback bytes.
#
# - lint:      graftlint over sampling/ + stats/ — G014 enforces that
#              per-step history tensors only reach the host through the
#              flagged maybe_host oracle path (or a reasoned pragma),
#              so summary mode cannot silently regress into O(C*T)
#              per-chunk exfiltration.
# - artifacts: the paper's sec11 config rendered twice from the same
#              seed — analytics='history' (oracle) vs 'summary'
#              (device-resident) — every artifact in the manifest must
#              be byte-identical, and the two runs' fingerprints must
#              differ (summary mode is a distinct compiled kernel).
# - hotpath:   NullRecorder contract: recorder absent, NULL, or
#              recorder+analytics attached — the trajectory itself is
#              bit-identical in all three (telemetry never perturbs the
#              chain).
# - ratio:     the acceptance number: on the board fast path
#              (chunk >= 256) the per-chunk readback drops >= 100x
#              summary vs history, measured from the runs' own honest
#              readback_bytes event fields.
#
#   tools/devstats_check.sh                  # all legs
#   DEVSTATS_LEGS="lint hotpath" tools/devstats_check.sh
set -euo pipefail

cd "$(dirname "$0")/.."
PY="${PYTHON:-python}"
TD="$(mktemp -d)"
trap 'rm -rf "$TD"' EXIT

# shared XLA cache so repeat gate runs (and the history/summary pairs,
# which differ by treedef anyway) skip whatever compiles they can
export JAX_COMPILATION_CACHE_DIR="${GRAFT_GATE_JAX_CACHE:-${TMPDIR:-/tmp}/graft-gate-jax-cache}"
mkdir -p "$JAX_COMPILATION_CACHE_DIR"
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=1

LEGS="${DEVSTATS_LEGS:-lint artifacts hotpath ratio}"

for LEG in $LEGS; do
case "$LEG" in

lint)
  "$PY" -m tools.graftlint flipcomplexityempirical_tpu/sampling \
      flipcomplexityempirical_tpu/stats
  echo "devstats-check[lint]: sampling/ + stats/ are G014-clean"
  ;;

artifacts)
  JAX_PLATFORMS=cpu "$PY" - "$TD" <<'PYEOF'
import filecmp
import os
import sys

from flipcomplexityempirical_tpu import experiments as ex
from flipcomplexityempirical_tpu.experiments.artifacts import artifact_kinds

td = sys.argv[1]
kw = dict(family="sec11", alignment=0, base=1.4, pop_tol=0.3,
          total_steps=240, n_chains=2, backend="jax")
cfg_h = ex.ExperimentConfig(**kw)
cfg_s = ex.ExperimentConfig(analytics="summary", **kw)
assert cfg_h.tag == cfg_s.tag
assert cfg_h.fingerprint() != cfg_s.fingerprint(), \
    "summary mode must fingerprint as a distinct compiled kernel"

out_h, out_s = os.path.join(td, "hist"), os.path.join(td, "summ")
data_h = ex.run_config(cfg_h, out_h)
data_s = ex.run_config(cfg_s, out_s)

kinds = artifact_kinds(cfg_h.family)
diff = [k for k in kinds
        if not filecmp.cmp(os.path.join(out_h, cfg_h.tag + k),
                           os.path.join(out_s, cfg_s.tag + k),
                           shallow=False)]
assert not diff, f"artifacts diverged between analytics modes: {diff}"
assert int(data_s["summary"]["n"]) == kw["total_steps"]
assert data_s["readback_bytes"] > 0
print(f"devstats-check[artifacts]: {len(kinds)} sec11 artifacts "
      "byte-identical, history vs device-resident summary")
PYEOF
  ;;

hotpath)
  JAX_PLATFORMS=cpu "$PY" - <<'PYEOF'
import numpy as np

import flipcomplexityempirical_tpu as fce
from flipcomplexityempirical_tpu import obs, stats

g = fce.graphs.square_grid(8)
plan = fce.graphs.stripes_plan(g, 2)
spec = fce.Spec(contiguity="patch")
bg, st, params = fce.sampling.init_board(g, plan, n_chains=8, seed=2,
                                         spec=spec, base=1.4, pop_tol=0.3)

def run(**kw):
    return fce.sampling.run_board(bg, spec, params, st, n_steps=129,
                                  chunk=32, **kw)

bare = run(record_history=False)
null = run(record_history=False, recorder=obs.NULL)
summ = run(record_history=False, recorder=obs.NULL,
           analytics=stats.DeviceAnalytics(8))
for other, label in ((null, "NullRecorder"), (summ, "analytics")):
    np.testing.assert_array_equal(
        np.asarray(bare.state.board), np.asarray(other.state.board),
        err_msg=label)
    np.testing.assert_array_equal(
        np.asarray(bare.state.accept_count),
        np.asarray(other.state.accept_count), err_msg=label)
print("devstats-check[hotpath]: bare / NullRecorder / analytics "
      "trajectories bit-identical over 129 yields")
PYEOF
  ;;

ratio)
  JAX_PLATFORMS=cpu "$PY" - "$TD" <<'PYEOF'
import json
import os
import sys

import flipcomplexityempirical_tpu as fce
from flipcomplexityempirical_tpu import obs, stats

td = sys.argv[1]
g = fce.graphs.square_grid(16)
plan = fce.graphs.stripes_plan(g, 2)
spec = fce.Spec(contiguity="patch")
bg, st, params = fce.sampling.init_board(g, plan, n_chains=64, seed=0,
                                         spec=spec, base=1.4, pop_tol=0.3)

def leg(analytics, path):
    with obs.Recorder(path=path) as rec:
        fce.sampling.run_board(bg, spec, params, st, n_steps=2049,
                               chunk=256, recorder=rec,
                               record_history=analytics is None,
                               analytics=analytics)
    ev = [json.loads(ln) for ln in open(path, encoding="utf-8")]
    chunks = [e for e in ev if e["event"] == "chunk"]
    steps = sum(e["steps"] for e in chunks)
    rb = sum(e["readback_bytes"] for e in chunks)
    mode = [e for e in ev if e["event"] == "run_end"][0]["readback_mode"]
    return rb / steps, mode

hist, mode_h = leg(None, os.path.join(td, "ratio.hist.jsonl"))
summ, mode_s = leg(stats.DeviceAnalytics(64),
                   os.path.join(td, "ratio.summ.jsonl"))
assert (mode_h, mode_s) == ("history", "summary")
ratio = hist / summ
assert ratio >= 100, (
    f"summary readback only {ratio:.1f}x below history "
    f"({summ:.1f} vs {hist:.1f} B/step) — acceptance needs >= 100x")
print(f"devstats-check[ratio]: {ratio:.1f}x per-chunk readback "
      f"reduction on the board path ({hist:.1f} -> {summ:.2f} B/step)")
PYEOF
  for EV in "$TD"/ratio.*.jsonl; do
    "$PY" tools/obs_report.py "$EV" --check
    "$PY" tools/obs_report.py "$EV" \
        | grep -q "^## Readback" \
        || { echo "devstats-check: report on $EV is missing its" \
                  "Readback section"; exit 1; }
  done
  ;;

*)
  echo "devstats-check: unknown leg '$LEG'"
  exit 1
  ;;
esac
done

echo "devstats-check: OK"
