#!/usr/bin/env bash
# Multi-chip CI gate (`make mesh-check`): the shard_map scale-out path
# end to end, without TPU hardware.
#
#   1. graftlint over the package + tools (the shard_map bodies in
#      distribute/ ride the same emit/sync/RNG contracts as everything
#      else)
#   2. the committed two-host fixture streams each validate standalone
#      AND merge into one Chrome trace with one pid per host — the
#      contract per_host_path/trace_export promise multi-host runs
#   3. a live 2-device forced-host mesh smoke through `bench.py --mesh`:
#      the MULTICHIP record must select a fast-path body (bitboard or
#      the lowered family, not int8/general), carry per-chip flips/s,
#      and emit an event stream that survives trace_export --validate
#   4. the same mesh smoke on the sec11 surgical graph: the sharded
#      step must resolve the packed lowered_bits body (ISSUE 8 — the
#      mesh path picks the new rung up through run_board_chunk)
#
#   tools/mesh_check.sh
#
# Exercised by tests/test_tools.py, so tier-1 fails when any gate rots.
set -euo pipefail

cd "$(dirname "$0")/.."
PY="${PYTHON:-python}"
FIX0=tests/fixtures/obs/events_mesh.host0.jsonl
FIX1=tests/fixtures/obs/events_mesh.host1.jsonl

"$PY" -m tools.graftlint flipcomplexityempirical_tpu tools

"$PY" tools/trace_export.py --validate "$FIX0" "$FIX1"
tmp="$(mktemp -d)"
trap 'rm -rf "$tmp"' EXIT
"$PY" tools/trace_export.py "$FIX0" "$FIX1" -o "$tmp/mesh_trace.json"

"$PY" bench.py --mesh 2 --cpu --grid 32 --chains 4 --steps 41 \
    --warmup 21 --chunk 20 --events "$tmp/mesh_events.jsonl" \
    > "$tmp/mesh_record.json" 2> "$tmp/mesh_detail.json"
"$PY" tools/trace_export.py --validate "$tmp/mesh_events.jsonl"
"$PY" - "$tmp/mesh_record.json" <<'PYEOF'
import json
import sys

with open(sys.argv[1], encoding="utf-8") as f:
    rec = json.load(f)
assert rec["devices"] == 2, rec
assert rec["body"] in ("bitboard", "lowered_bits", "lowered"), \
    f"mesh smoke fell off the fast path: {rec['body']}"
assert rec["flips_per_s_per_chip"] > 0, rec
assert [r["devices"] for r in rec["scaling"]] == [1, 2], rec
print("mesh-check: bench record OK "
      f"(body={rec['body']}, "
      f"per-chip {rec['flips_per_s_per_chip']:,.0f} flips/s)")
PYEOF

"$PY" bench.py --mesh 2 --cpu --graph sec11 --chains 2 --steps 21 \
    --warmup 21 --chunk 20 \
    > "$tmp/mesh_sec11.json" 2> "$tmp/mesh_sec11_detail.json"
"$PY" - "$tmp/mesh_sec11.json" <<'PYEOF'
import json
import sys

with open(sys.argv[1], encoding="utf-8") as f:
    rec = json.load(f)
assert rec["body"] == "lowered_bits", \
    f"sec11 mesh smoke must resolve the packed body: {rec['body']}"
assert rec["flips_per_s_per_chip"] > 0, rec
print("mesh-check: sec11 record OK (body=lowered_bits, "
      f"per-chip {rec['flips_per_s_per_chip']:,.0f} flips/s)")
PYEOF
echo "mesh-check: OK"
