#!/usr/bin/env bash
# General-dense CI gate (`make dense-check`), ISSUE 15: the rejection-free
# general_dense kernel body must (1) keep the tree graftlint-clean,
# (2) sample the exact stationary law (chi2 vs the enumerated state
# space on a small hex graph — the same slow-marked test the full suite
# runs, so gate and test can never disagree), (3) beat the legacy
# general kernel >=2x on the CPU hex microbench (32x32 hex lattice,
# C=256, pop_tol=0.1, base=2.0 — steady-state scan timing, compile
# excluded; the transition-level harness PROFILE.md round 14 used), and
# (4) degrade general_dense -> general under an injected compile fault
# without losing the run.
#
#   tools/dense_check.sh
#
# Exercised by tests/test_dense.py (slow tier), so the gate rots loudly.
set -euo pipefail

cd "$(dirname "$0")/.."
PY="${PYTHON:-python}"

echo "dense-check: [1/4] graftlint"
"$PY" -m tools.graftlint flipcomplexityempirical_tpu tools

echo "dense-check: [2/4] chi2 exactness smoke (enumerated hex, N=10)"
JAX_PLATFORMS=cpu "$PY" -m pytest tests/test_dense.py --runslow -q \
  -k chi2_hex

echo "dense-check: [3/4] CPU microbench (hex 32x32, C=256)"
JAX_PLATFORMS=cpu "$PY" - <<'PYEOF'
import time

import jax

import flipcomplexityempirical_tpu as fce
from flipcomplexityempirical_tpu.kernel import dense as kdense
from flipcomplexityempirical_tpu.kernel import step as kstep
from flipcomplexityempirical_tpu.lower import dispatch

g = fce.graphs.hex_lattice(32, 32)
plan = fce.graphs.stripes_plan(g, 2)
spec = fce.Spec(n_districts=2, proposal="bi", contiguity="patch",
                invalid="repropose", accept="cut")
assert kdense.supported(g, spec), "gate fixture fell off the dense rung"
assert dispatch.kernel_path_for(g, spec) == "general_dense", \
    f"dispatch resolves {dispatch.kernel_path_for(g, spec)}"
dg, states, params = fce.init_batch(g, plan, n_chains=256, seed=0,
                                    spec=spec, base=2.0, pop_tol=0.1)


def steady(trans, states, n=200):
    """Steady-state ms/step: jit a fixed-length transition scan, one
    warmup call (compile + reach steady boundary sizes), best of 3."""
    paxes = kstep.StepParams.vmap_axes()

    @jax.jit
    def run(s):
        s, _ = jax.lax.scan(
            lambda st, _: (jax.vmap(lambda p, x: trans(dg, spec, p, x),
                                    in_axes=(paxes, 0))(params, st), ()),
            s, None, length=n)
        return s

    out = run(states)
    jax.tree.map(lambda x: x.block_until_ready(), out)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = run(states)
        jax.tree.map(lambda x: x.block_until_ready(), out)
        best = min(best, time.perf_counter() - t0)
    return best / n * 1e3


md = steady(kdense.transition, kdense.ensure_conn_bits(dg, spec, states))
ml = steady(kstep.transition, states)
print(f"dense-check: general_dense {md:.3f} ms/step, "
      f"legacy general {ml:.3f} ms/step -> {ml / md:.2f}x")
assert ml / md >= 2.0, (
    f"general_dense is only {ml / md:.2f}x the legacy general kernel "
    f"(gate: >=2.0x at hex 32x32, C=256) — the rejection-free path "
    f"regressed")
PYEOF

echo "dense-check: [4/4] compile-fault degradation general_dense -> general"
JAX_PLATFORMS=cpu "$PY" - <<'PYEOF'
import flipcomplexityempirical_tpu as fce
from flipcomplexityempirical_tpu.resilience import degrade as rdegrade
from flipcomplexityempirical_tpu.resilience import faults as rfaults

g = fce.graphs.hex_lattice(6, 6)
plan = fce.graphs.stripes_plan(g, 2)
spec = fce.Spec(n_districts=2, proposal="bi", contiguity="patch",
                invalid="repropose", accept="cut")
dg, states, params = fce.init_batch(g, plan, n_chains=8, seed=0,
                                    spec=spec, base=2.0, pop_tol=0.2)
mark = rdegrade.snapshot()
rfaults.install_from_spec("compile:once")
try:
    res = fce.run_chains(dg, spec, params, states, n_steps=51, chunk=25,
                         record_history=True)
finally:
    rfaults.install_from_spec(None)
falls = [(d["from_path"], d["to_path"]) for d in rdegrade.since(mark)]
assert falls == [("general_dense", "general")], falls
assert res.n_yields == 51, f"degraded run lost steps: {res.n_yields}/51"
assert res.history["cut_count"].shape == (8, 51), \
    res.history["cut_count"].shape
print("dense-check: compile fault fell through to the legacy kernel, "
      "run completed (51/51 yields)")
PYEOF

echo "dense-check: OK"
