#!/usr/bin/env bash
# Workload-catalog CI gate (`make workload-check`, ISSUE 14): every
# served family must stay NAMED, RESOLVABLE, and GATEABLE.
#
# - lint:    graftlint over workloads/ + sampling/ (the registry and the
#            ReCom chunked runner ride the same purity gates as the
#            rest of the package).
# - resolve: every catalog entry materialises through the driver's own
#            builders, its declared dispatch rung matches what
#            lower.dispatch actually resolves, and the two fingerprint
#            layers (workload declaration, kernel-coalescing config)
#            are stable and distinct across entries.
# - smoke:   the two acceptance workloads run end to end on CPU via the
#            real CLI — the committed dual-graph fixture (partisan
#            artifacts attached) and the ReCom chain family — with
#            schema-valid event streams.
# - bench:   bench.py --workload-matrix emits per-family records that
#            bench_compare qualifies per [workload=...], so a flip-grid
#            regression never gates against ReCom or a dual fixture.
#
#   tools/workload_check.sh                  # all legs
#   WORKLOAD_LEGS="lint resolve" tools/workload_check.sh
set -euo pipefail

cd "$(dirname "$0")/.."
PY="${PYTHON:-python}"
TD="$(mktemp -d)"
trap 'rm -rf "$TD"' EXIT

# one persistent XLA cache across the legs' processes
export JAX_COMPILATION_CACHE_DIR="$TD/jax-cache"
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=1

LEGS="${WORKLOAD_LEGS:-lint resolve smoke bench}"

for LEG in $LEGS; do
case "$LEG" in

lint)
  "$PY" -m tools.graftlint flipcomplexityempirical_tpu/workloads \
      flipcomplexityempirical_tpu/sampling
  echo "workload-check[lint]: workloads/ + sampling/ are graftlint-clean"
  ;;

resolve)
  JAX_PLATFORMS=cpu "$PY" - <<'PYEOF'
from flipcomplexityempirical_tpu import workloads

fps, cfps = {}, {}
for n in workloads.names():
    r = workloads.resolve(n)
    w = r.workload
    assert r.kernel_path == w.kernel_path, (
        f"{n}: declared kernel_path {w.kernel_path!r} but dispatch "
        f"resolves {r.kernel_path!r}")
    assert r.plan.shape == (r.graph.n_nodes,), n
    assert w.fingerprint() == w.fingerprint(), n
    fps[n] = w.fingerprint()
    cfps[n] = r.config.fingerprint()
assert len(set(fps.values())) == len(fps), "workload fingerprint clash"
print(f"workload-check[resolve]: {len(fps)} entries resolve on their "
      "declared dispatch rungs, fingerprints distinct")
PYEOF
  ;;

smoke)
  JAX_PLATFORMS=cpu "$PY" -m flipcomplexityempirical_tpu.experiments \
      --workload dual-fixture --out "$TD/wl-dual" \
      --steps "${WORKLOAD_STEPS:-200}" --chains 2 \
      --events "$TD/events.dual.jsonl" --no-supervise
  test -s "$TD"/wl-dual/*partisan.json \
      || { echo "workload-check: dual fixture run left no partisan.json"; \
           exit 1; }
  JAX_PLATFORMS=cpu "$PY" -m flipcomplexityempirical_tpu.experiments \
      --workload recom-grid --out "$TD/wl-recom" \
      --steps 20 --chains 2 \
      --events "$TD/events.recom.jsonl" --no-supervise
  "$PY" tools/obs_report.py "$TD/events.dual.jsonl" --check
  "$PY" tools/obs_report.py "$TD/events.recom.jsonl" --check
  grep -q '"kernel_path": *"recom"' "$TD/events.recom.jsonl" \
      || { echo "workload-check: recom events not tagged kernel_path=recom"; \
           exit 1; }
  echo "workload-check[smoke]: dual-fixture + recom-grid ran end to end"
  ;;

bench)
  JAX_PLATFORMS=cpu "$PY" bench.py --workload-matrix --cpu \
      --workloads "${WORKLOAD_MATRIX:-grid-k4,recom-grid,dual-fixture}" \
      > "$TD/matrix.json" 2> "$TD/matrix.meta"
  "$PY" - "$TD/matrix.json" <<'PYEOF'
import json
import sys

doc = json.load(open(sys.argv[1]))
assert doc["mode"] == "workload-matrix", doc
recs = doc["results"]
assert recs, "empty workload matrix"
for r in recs:
    assert r["metric"] == "workload_steps_per_s", r
    assert r["value"] > 0, r
    assert "workload" in r and "kernel_path" in r, r
names = [r["workload"] for r in recs]
print(f"workload-check[bench]: {len(recs)} per-family records "
      f"({', '.join(names)})")
PYEOF
  # self-compare: each record must extract under its own
  # [workload=...]-qualified name — families never cross-gate
  "$PY" tools/bench_compare.py "$TD/matrix.json" "$TD/matrix.json" \
      | grep -q 'workload_steps_per_s\[workload=recom-grid\]' \
      || { echo "workload-check: bench_compare did not qualify per workload"; \
           exit 1; }
  ;;

*)
  echo "workload-check: unknown leg '$LEG'"
  exit 1
  ;;
esac
done

echo "workload-check: OK"
