import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BC, N, T = 128, 128, 4


def run(name, kern, out_shape, ins):
    try:
        r = pl.pallas_call(
            kern,
            in_specs=[pl.BlockSpec(a.shape, (lambda sh: (lambda: tuple([0] * len(sh))))(a.shape)) for a in ins],
            out_specs=pl.BlockSpec(out_shape.shape, lambda: tuple([0] * len(out_shape.shape))),
            out_shape=out_shape,
        )(*ins)
        jax.block_until_ready(r)
        print(f"{name}: OK")
    except Exception as e:
        print(f"{name}: FAIL {type(e).__name__} {str(e).splitlines()[0][:100]}")


x = jnp.ones((BC, N), jnp.int32)


# A. prng plane in loop, reduce, carry int
def kA(x_ref, o_ref):
    pltpu.prng_seed(3)
    def step(t, c):
        bits = pltpu.bitcast(pltpu.prng_random_bits((BC, N)), jnp.uint32)
        s32 = pltpu.bitcast(bits ^ jnp.uint32(0x80000000), jnp.int32)
        return c + jnp.max(s32, axis=1)
    out = jax.lax.fori_loop(0, T, step, jnp.zeros((BC,), jnp.int32))
    o_ref[0, :] = out

run("prng-plane-loop-carry", kA, jax.ShapeDtypeStruct((1, BC), jnp.int32), (x,))


# B. same but any_valid bool astype counters (the exact stage-54 shape)
def kB(x_ref, o_ref):
    pltpu.prng_seed(3)
    def step(t, c):
        bits = pltpu.bitcast(pltpu.prng_random_bits((BC, N)), jnp.uint32)
        valid = x_ref[:] > 0
        score = jnp.where(valid, jnp.bitwise_or(bits, jnp.uint32(1)), jnp.uint32(0))
        s32 = pltpu.bitcast(score ^ jnp.uint32(0x80000000), jnp.int32)
        smax = jnp.max(s32, axis=1)
        any_valid = smax > jnp.int32(-(2 ** 31))
        return c + any_valid.astype(jnp.int32)
    out = jax.lax.fori_loop(0, T, step, jnp.zeros((BC,), jnp.int32))
    o_ref[0, :] = out

run("score-anyvalid-loop", kB, jax.ShapeDtypeStruct((1, BC), jnp.int32), (x,))
