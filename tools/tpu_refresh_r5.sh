#!/bin/bash
# One-shot refresh for the round-5 tail: if the tunnel reopens, capture a
# fresh default-config record (the default now resolves to the C=8192
# peak) and exit. The full capture set is already committed; this only
# adds a confirming record at the new default.
set -u
cd "$(dirname "$0")/.."
. tools/bench_lib.sh
fails=0
while true; do
  if timeout 150 python -c \
      "import jax,sys; sys.exit(0 if jax.devices()[0].platform!='cpu' else 1)" \
      >/dev/null 2>&1; then
    TS=$(date -u +%Y%m%dT%H%M%SZ)
    run_bench default_refresh 900 && exit 0
    # chip up but the bench failed (regression, commit failure, tunnel
    # dropped mid-run): cap the burn at 3 attempts, backing off between
    fails=$((fails + 1))
    [ "$fails" -ge 3 ] && exit 1
  fi
  sleep 420
done
