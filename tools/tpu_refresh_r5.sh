#!/bin/bash
# One-shot refresh for the round-5 tail: if the tunnel reopens, capture
# (1) a fresh default-config record (the default now resolves to the
# C=8192 peak), (2) the two frontier probes the first window lost to the
# tunnel drop (8192@chunk600, 10240), and (3) a --pallas record at the
# shipped 32MiB VMEM budget. The primary set is already committed; these
# only confirm/extend it, so the probes' failures do not block exit.
set -u
cd "$(dirname "$0")/.."
. tools/bench_lib.sh
fails=0
while true; do
  if timeout 150 python -c \
      "import jax,sys; sys.exit(0 if jax.devices()[0].platform!='cpu' else 1)" \
      >/dev/null 2>&1; then
    TS=$(date -u +%Y%m%dT%H%M%SZ)
    if run_bench default_refresh 900; then
      run_bench frontier_c8192_chunk600 900 --chains 8192 --chunk 600 --warmup 601 || true
      run_bench frontier_c10240 900 --chains 10240 || true
      run_bench pallas_refresh 900 --pallas || true
      exit 0
    fi
    # chip up but the bench failed (regression, commit failure, tunnel
    # dropped mid-run): cap the burn at 3 attempts, backing off between
    fails=$((fails + 1))
    [ "$fails" -ge 3 ] && exit 1
  fi
  sleep 420
done
