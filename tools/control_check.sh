#!/usr/bin/env bash
# Adaptive-control CI gate (`make control-check`, ISSUE 12): the
# observe->act loop must PAY ITS WAY and survive preemption.
#
# - lint:   graftlint over control/ — G008 enforces policy purity (no
#           clocks, no unseeded RNG, no recorder/journal mutation from
#           inside a policy), which is what makes the control plane
#           journal-replayable at all.
# - bench:  a seeded CPU sweep (two frank configs + one tempered
#           ladder) run adaptive and fixed from the same warm jit
#           cache: the adaptive leg must reach the split-R-hat/ESS
#           targets in strictly less wall clock (value > 1.0x), with at
#           least one journaled early stop; the event stream must
#           validate and the report must render its Control section;
#           bench_compare must qualify the record per (family, policy).
# - replay: SIGTERM-drain a controlled service mid-sweep (exit 3), then
#           recover in a FRESH process whose ControlLoop adopts the
#           journaled decisions — the full journal's control_action
#           sequence must be bit-identical to an uninterrupted
#           reference run's, and so must the per-tenant artifacts.
#
#   tools/control_check.sh                 # all legs
#   CONTROL_LEGS="lint replay" tools/control_check.sh
set -euo pipefail

cd "$(dirname "$0")/.."
PY="${PYTHON:-python}"
TD="$(mktemp -d)"
trap 'rm -rf "$TD"' EXIT

# one persistent XLA cache across the legs' processes: the recovered
# process must not re-pay the drained process's compiles. Stable path
# (not in $TD) so repeat gate runs skip the cold compiles as well; the
# bench leg's fixed-vs-adaptive ratio is measured from its own warmup
# leg either way.
export JAX_COMPILATION_CACHE_DIR="${GRAFT_GATE_JAX_CACHE:-${TMPDIR:-/tmp}/graft-gate-jax-cache}"
mkdir -p "$JAX_COMPILATION_CACHE_DIR"
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=1

LEGS="${CONTROL_LEGS:-lint bench replay}"

for LEG in $LEGS; do
case "$LEG" in

lint)
  "$PY" -m tools.graftlint flipcomplexityempirical_tpu/control
  echo "control-check[lint]: control/ is G008-clean"
  ;;

bench)
  # steps=961 puts the early stops (~160/320) far enough from the full
  # schedule that the adaptive margin is robust, not a timing coin flip
  JAX_PLATFORMS=cpu "$PY" bench.py --adaptive --cpu \
      --steps "${CONTROL_STEPS:-961}" --chains 4 --target-ess 32 \
      --events "$TD/events.bench.jsonl" \
      > "$TD/record.json" 2> "$TD/bench.meta"
  "$PY" - "$TD/record.json" <<'PYEOF'
import json
import sys

rec = json.load(open(sys.argv[1]))
assert rec["metric"] == "wall_clock_to_target_ess", rec
assert rec["value"] > 1.0, \
    f"adaptive did not beat the fixed schedule: {rec['value']}x"
assert rec["stops"], "no early stop fired — the loop did nothing"
print(f"control-check[bench]: {rec['value']}x fixed/adaptive "
      f"(stops at {[s['step'] for s in rec['stops']]}, "
      f"reshapes at {[r['step'] for r in rec['reshapes']]})")
PYEOF
  "$PY" tools/obs_report.py "$TD/events.bench.jsonl" --check
  "$PY" tools/obs_report.py "$TD/events.bench.jsonl" \
      | grep -q "^## Control" \
      || { echo "control-check: report is missing its Control section"; \
           exit 1; }
  # self-compare: the record must extract under its (family, policy)
  # qualified metric name, not collide with other adaptive records
  "$PY" tools/bench_compare.py "$TD/record.json" "$TD/record.json" \
      | grep -q "wall_clock_to_target_ess\[family=frank+temper,policy=early_stop+ladder\]" \
      || { echo "control-check: bench_compare did not qualify the record"; \
           exit 1; }
  ;;

replay)
  OUT="$TD/replay"
  mkdir -p "$OUT/drained" "$OUT/ref"

  # --- drain: job 1's early stop consumes sigterm hit 1 (the stop
  # breaks its segment loop); job 2's first boundary takes hit 2 and
  # the service drains with the distinct exit code 3.
  set +e
  JAX_PLATFORMS=cpu GRAFT_FAULTS="sigterm:once@2" \
      "$PY" - "$OUT/drained" <<'PYEOF'
import os
import sys

from flipcomplexityempirical_tpu import obs
from flipcomplexityempirical_tpu.control import ControlLoop, EarlyStopPolicy
from flipcomplexityempirical_tpu.experiments.config import ExperimentConfig
from flipcomplexityempirical_tpu.resilience import faults as rfaults
from flipcomplexityempirical_tpu.service import SweepService

out = sys.argv[1]
rfaults.install_from_env()
cfgs = [ExperimentConfig(family="frank", alignment=al, base=0.3,
                         pop_tol=0.1, total_steps=60, n_chains=2,
                         backend="jax", checkpoint_every=20, seed=seed)
        for al, seed in ((2, 3), (1, 4))]
loop = ControlLoop(policies=[EarlyStopPolicy(
    rhat_target=5.0, ess_target=4.0, patience=1, min_columns=4)])
with obs.Recorder(os.path.join(out, "events.drain.jsonl")) as rec:
    svc = SweepService(outdir=out, recorder=rec, max_batch_chains=2,
                       control=loop)
    for c in cfgs:
        svc.submit(c)
    svc.run_until_idle()
assert svc.drained, "injected sigterm did not drain the service"
assert any(a.kind == "stop" for a in loop.actions), \
    "the drained run journaled no stop to replay"
sys.exit(svc.exit_code)
PYEOF
  rc=$?
  set -e
  if [ "$rc" -ne 3 ]; then
    echo "control-check: drain leg exited $rc, want 3 (EXIT_DRAINED)"
    exit 1
  fi

  # --- recover + reference: a fresh process adopts the journaled
  # decisions; its FULL control_action sequence (drained prefix +
  # recovery) must equal an uninterrupted run's, byte for byte.
  JAX_PLATFORMS=cpu "$PY" - "$OUT/drained" "$OUT/ref" <<'PYEOF'
import json
import os
import sys

import numpy as np

from flipcomplexityempirical_tpu import obs
from flipcomplexityempirical_tpu.control import ControlLoop, EarlyStopPolicy
from flipcomplexityempirical_tpu.experiments.config import ExperimentConfig
from flipcomplexityempirical_tpu.service import Journal, SweepService
from flipcomplexityempirical_tpu.service import journal as jnl

drained, ref_dir = sys.argv[1], sys.argv[2]
cfgs = [ExperimentConfig(family="frank", alignment=al, base=0.3,
                         pop_tol=0.1, total_steps=60, n_chains=2,
                         backend="jax", checkpoint_every=20, seed=seed)
        for al, seed in ((2, 3), (1, 4))]


def policies():
    return [EarlyStopPolicy(rhat_target=5.0, ess_target=4.0,
                            patience=1, min_columns=4)]


def control_story(outdir):
    records, truncated = Journal.read(jnl.journal_path_for(outdir))
    assert not truncated
    return [(r["action"], r["tag"], r["step"], r["policy"],
             json.dumps(r["detail"], sort_keys=True))
            for r in records if r["kind"] == "control_action"]


with obs.Recorder(os.path.join(drained, "events.recover.jsonl")) as rec:
    svc = SweepService.recover(drained, recorder=rec, max_batch_chains=2,
                               control=ControlLoop(policies=policies()))
    svc.run_until_idle()
assert svc.exit_code == 0, [(j.tag, j.status, j.error)
                            for j in svc.queue.jobs()]
got = {j.tag: j for j in svc.queue.jobs()}

ref_svc = SweepService(outdir=ref_dir, max_batch_chains=2,
                       control=ControlLoop(policies=policies()))
ref_jobs = [ref_svc.submit(c) for c in cfgs]
ref_svc.run_until_idle()
assert ref_svc.exit_code == 0

story, ref_story = control_story(drained), control_story(ref_dir)
assert story == ref_story, (
    "control_action replay diverged:\n"
    f"  drained+recovered: {story}\n  reference:         {ref_story}")
assert [k for (k, *_) in story] == ["stop", "stop"], story

compared = 0
for c, ref_job in zip(cfgs, ref_jobs):
    assert got[c.tag].status == "done", (c.tag, got[c.tag].error)
    a, b = got[c.tag].result, ref_job.result
    if a is None or b is None:
        # a job already done BEFORE the drain recovers as a journal
        # verdict only (results live in artifacts, not the journal)
        continue
    compared += 1
    assert a["early_stopped"] == b["early_stopped"] == 20
    for k in ("end_signed", "cut_times", "num_flips", "waits_all"):
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]),
                                      err_msg=k)
    for k in b["history"]:
        np.testing.assert_array_equal(
            np.asarray(a["history"][k]), np.asarray(b["history"][k]),
            err_msg=f"history[{k}]")
assert compared >= 1, "no re-run job left artifacts to compare"
print(f"control-check[replay]: {len(story)} control actions replayed "
      "bit-identically across the drain "
      f"({compared} re-run job(s) artifact-compared)")
PYEOF

  "$PY" tools/obs_report.py "$OUT/drained/events.drain.jsonl" --check
  "$PY" tools/obs_report.py "$OUT/drained/events.recover.jsonl" --check
  ;;

*)
  echo "control-check: unknown leg '$LEG'"
  exit 1
  ;;
esac
done

echo "control-check: OK"
