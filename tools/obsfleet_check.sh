#!/usr/bin/env bash
# Fleet observability gate (`make obsfleet-check`, ISSUE 18): one HTTP
# front door + two worker processes over the canonical shared-root
# event layout ($ROOT/events/<name>.jsonl), exercising every surface of
# the observability plane end to end:
#
#   - /v1/metrics and /v1/fleet scraped MID-RUN serve live
#     FleetCollector state (Prometheus text exposition + JSON topology);
#   - an on-demand profile marker dropped over HTTP before the run is
#     honored by the owning worker at a segment boundary and published
#     as a fetchable artifact;
#   - per-worker heartbeat docs appear under $ROOT/workers/ and the
#     obs_report --heartbeat DIRECTORY probe passes on the drained
#     fleet;
#   - trace_export --fleet --validate proves every job's worker-side
#     spans parent under its HTTP submit span, and the merged Perfetto
#     export carries the cross-stream flow links;
#   - the merged report renders the SLO section, --strict passes clean
#     and trips (exit 2) on an injected lease-expiry storm;
#   - the collector microbench holds the <= 2% overhead gate at the
#     frozen 500-tenant/16-worker scenario (BENCH_obs_r16.json's
#     shape).
#
#   tools/obsfleet_check.sh
#
# Exercised by tests/test_obsfleet.py, so tier-1 fails when the gate
# rots.
set -euo pipefail

cd "$(dirname "$0")/.."
PY="${PYTHON:-python}"
export JAX_PLATFORMS=cpu
export JAX_COMPILATION_CACHE_DIR="${GRAFT_GATE_JAX_CACHE:-${TMPDIR:-/tmp}/graft-gate-jax-cache}"
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=1
mkdir -p "$JAX_COMPILATION_CACHE_DIR"
TD="$(mktemp -d)"
ROOT="$TD/fleet"
SERVER_PID=""
W1_PID=""
W2_PID=""
cleanup() {
    for pid in "$SERVER_PID" "$W1_PID" "$W2_PID"; do
        [ -n "$pid" ] && kill -9 "$pid" 2>/dev/null || true
    done
    rm -rf "$TD"
}
trap cleanup EXIT

# -- 1. server up ------------------------------------------------------
"$PY" -m flipcomplexityempirical_tpu.service serve "$ROOT" \
    --ready-file "$ROOT/server.json" --ttl 2 &
SERVER_PID=$!
for _ in $(seq 1 120); do
    [ -f "$ROOT/server.json" ] && break
    kill -0 "$SERVER_PID" 2>/dev/null || {
        echo "obsfleet-check: server died before binding" >&2; exit 1; }
    sleep 0.25
done
[ -f "$ROOT/server.json" ] || {
    echo "obsfleet-check: server never wrote its ready file" >&2
    exit 1; }
URL="$("$PY" - "$ROOT/server.json" <<'PYEOF'
import json, sys
print(json.load(open(sys.argv[1]))["url"])
PYEOF
)"

# -- 2. three tenants submit; j0000 gets a profile request BEFORE any
# worker runs, so the capture is honored at the job's first segment
# boundary (the marker is per-job and one-shot)
"$PY" - "$URL" <<'PYEOF'
import json
import sys
import urllib.request

from flipcomplexityempirical_tpu.service import ServiceClient

url = sys.argv[1]
for i in range(3):
    client = ServiceClient(url, tenant=f"t{i}")
    doc = client.submit(workload="frank",
                        overrides={"total_steps": 60, "n_chains": 2,
                                   "checkpoint_every": 20,
                                   "seed": 3 + 13 * i})
    assert doc["job_id"] == f"j{i:04d}", doc
req = urllib.request.Request(url + "/v1/profile/j0000",
                             data=json.dumps({"segments": 1}).encode(),
                             method="POST")
with urllib.request.urlopen(req, timeout=10) as resp:
    out = json.loads(resp.read())
assert out == {"job_id": "j0000", "segments": 1,
               "profiling": "requested"}, out
PYEOF
[ -f "$ROOT/profile/j0000.json" ] || {
    echo "obsfleet-check: profile marker never dropped" >&2; exit 1; }

# -- 3. two workers run the spool to idle-exit; scrape mid-run ---------
"$PY" -m flipcomplexityempirical_tpu.service worker "$ROOT" \
    --name w1 --ttl 2 --idle-timeout 8 --compile-cache "$ROOT/cc" &
W1_PID=$!
"$PY" -m flipcomplexityempirical_tpu.service worker "$ROOT" \
    --name w2 --ttl 2 --idle-timeout 8 --compile-cache "$ROOT/cc" &
W2_PID=$!

"$PY" - "$URL" <<'PYEOF'
import json
import sys
import urllib.request

url = sys.argv[1]
with urllib.request.urlopen(url + "/v1/metrics", timeout=10) as resp:
    assert resp.status == 200, resp.status
    assert resp.headers.get("Content-Type", "").startswith("text/plain")
    body = resp.read().decode("utf-8")
assert "# TYPE graft_events_total counter" in body, body[:400]
assert "graft_fleet_jobs{" in body, body[:400]
with urllib.request.urlopen(url + "/v1/fleet", timeout=10) as resp:
    doc = json.loads(resp.read())
assert "workers" in doc and "streams" in doc
assert doc["queue_depth"] >= 0 and doc["draining"] is False
print(f"obsfleet-check: mid-run scrape ok "
      f"({len(body.splitlines())} metric lines, "
      f"stages={doc['stages']})")
PYEOF

RC_W1=0; RC_W2=0
wait "$W1_PID" || RC_W1=$?
W1_PID=""
wait "$W2_PID" || RC_W2=$?
W2_PID=""
[ "$RC_W1" -eq 0 ] && [ "$RC_W2" -eq 0 ] || {
    echo "obsfleet-check: workers exited $RC_W1/$RC_W2" >&2; exit 1; }

# -- 4. the profile round-trip completed: marker consumed, capture
# published as a fetchable artifact, profile_captured in the stream
"$PY" - "$URL" "$ROOT" <<'PYEOF'
import json
import os
import sys
import urllib.request

url, root = sys.argv[1], sys.argv[2]
cap = json.load(open(os.path.join(root, "artifacts",
                                  "j0000.profile.json")))
assert cap["job_id"] == "j0000" and cap["segments"] >= 1, cap
assert cap["ok"] is True, cap
assert not os.path.exists(os.path.join(root, "profile", "j0000.json"))
with urllib.request.urlopen(url + "/v1/profile/j0000",
                            timeout=10) as resp:
    doc = json.loads(resp.read())
assert doc["requested"] is None and doc["captured"]["ok"] is True, doc
docs = sorted(os.listdir(os.path.join(root, "workers")))
assert docs == ["w1.json", "w2.json"], docs
for name in docs:
    hb = json.load(open(os.path.join(root, "workers", name)))
    assert hb["status"] == "exited", hb
print(f"obsfleet-check: profile captured "
      f"({cap['segments']} segment(s)) by {cap['worker']}")
PYEOF

# -- 5. drain; serving ends with EXIT_DRAINED --------------------------
"$PY" - "$URL" <<'PYEOF'
import sys
from flipcomplexityempirical_tpu.service import ServiceClient
print(ServiceClient(sys.argv[1]).drain())
PYEOF
RC_SRV=0
wait "$SERVER_PID" || RC_SRV=$?
SERVER_PID=""
[ "$RC_SRV" -eq 3 ] || {
    echo "obsfleet-check: server exited $RC_SRV, expected 3" >&2
    exit 1; }

# -- 6. fleet trace gate + Perfetto export with flow links -------------
"$PY" tools/trace_export.py --fleet "$ROOT" --validate
"$PY" tools/trace_export.py --fleet "$ROOT" -o "$TD/fleet.trace.json" \
    | grep -q "trace link"

# -- 7. merged report: SLO section renders, heartbeat-directory probe
# and --strict pass on the clean run
cat "$ROOT"/events/*.jsonl > "$TD/merged-events.jsonl"
"$PY" tools/obs_report.py "$TD/merged-events.jsonl" \
    --heartbeat "$ROOT" --strict > "$TD/report.md"
grep -q "## SLO" "$TD/report.md"
grep -q "queue_to_start_tail" "$TD/report.md"

# -- 8. an injected lease-expiry storm (5 expirations inside one 60s
# window vs the 2/min objective) must trip --strict with exit 2
"$PY" - "$TD" <<'PYEOF'
import json
import shutil
import sys

td = sys.argv[1]
src = f"{td}/merged-events.jsonl"
dst = f"{td}/storm-events.jsonl"
shutil.copy(src, dst)
last_ts = max(json.loads(ln)["ts"] for ln in open(src) if ln.strip())
with open(dst, "a") as f:
    for k in range(5):      # distinct jobs: the per-job storm gate
        f.write(json.dumps({  # stays quiet; the SLO burn rate trips
            "v": 1, "ts": last_ts + 1.0 + 10.0 * k,
            "event": "lease_expired", "job_id": f"j{k:04d}",
            "worker": "w9"}) + "\n")
PYEOF
RC_STORM=0
"$PY" tools/obs_report.py "$TD/storm-events.jsonl" --strict \
    > "$TD/storm-report.md" || RC_STORM=$?
[ "$RC_STORM" -eq 2 ] || {
    echo "obsfleet-check: --strict exited $RC_STORM on the injected" \
         "storm, expected 2" >&2
    exit 1; }
grep -q "VIOLATED" "$TD/storm-report.md"

# -- 9. collector overhead gate at the frozen bench scenario -----------
"$PY" tools/loadtest.py --simulate --tenants 500 --workers 16 \
    --collector-bench --require-collector-overhead 0.02 \
    --require-fairness 0.8 --out "$TD/bench_obs.json"
"$PY" - "$TD/bench_obs.json" <<'PYEOF'
import json, sys
rec = json.load(open(sys.argv[1]))
assert rec["metric"] == "fleet_collector_events_per_s", rec["metric"]
assert rec["collector_overhead"] <= 0.02, rec["collector_overhead"]
assert rec["fleet_fairness_jain"] >= 0.8, rec["fleet_fairness_jain"]
print(f"obsfleet-check: collector {rec['value']:.0f} events/s, "
      f"overhead {rec['collector_overhead']:.5f} "
      f"over {rec['collector_events']} events")
PYEOF

echo "obsfleet-check: OK"
