#!/usr/bin/env python
"""Diff two bench records (BENCH_r*.json): per-metric flips/s deltas.

    python tools/bench_compare.py OLD.json NEW.json [--tolerance 0.05]

Walks both documents for anything metric-shaped — ``parsed`` blocks and
``{"metric": ..., "value": ...}`` JSON lines embedded in the captured
``tail`` — plus per-config throughput derived from bench config lines
(``chains * (steps - 1) / seconds``, named by path/body/grid/chains so
the same configuration matches across records). Prints a delta table
and exits nonzero when any metric present in BOTH records regressed by
more than ``--tolerance`` (a fraction: 0.05 = 5%), so a bench wrapper
can gate on throughput drift between rounds the way obs_report.py
--check gates on stream shape.

Gating only applies when the two records measured the same hardware:
when their device tags differ (``device`` fields anywhere in the
walked blocks, or a truthy ``cpu_fallback`` marker), the delta table
still prints but the tolerance gate is refused — an "incomparable
devices" note and exit 0, because a TPU-vs-CPU-fallback "regression"
is a config problem, not a perf one. Stdlib-only.
"""

from __future__ import annotations

import argparse
import json
import sys


def _config_name(d: dict) -> str:
    """Stable name for a bench config line, from the fields that define
    the workload (not the measurement)."""
    parts = []
    for k in ("path", "body", "grid", "k", "chains", "device"):
        if k in d:
            parts.append(f"{k}={d[k]}")
    return "config[" + ",".join(parts) + "]"


def extract_metrics(doc, out: dict | None = None) -> dict:
    """name -> float over everything metric-shaped in a bench record."""
    if out is None:
        out = {}
    if isinstance(doc, dict):
        if "metric" in doc and isinstance(doc.get("value"), (int, float)):
            out[str(doc["metric"])] = float(doc["value"])
        elif ("seconds" in doc and "chains" in doc and "steps" in doc
              and doc.get("seconds")):
            # a bench config line: derive the throughput it measured
            flips = doc["chains"] * max(doc["steps"] - 1, 1)
            out[_config_name(doc) + ".flips_per_s"] = \
                flips / float(doc["seconds"])
        for key in ("parsed", "results", "metrics"):
            if key in doc:
                extract_metrics(doc[key], out)
        tail = doc.get("tail")
        if isinstance(tail, str):
            for line in tail.splitlines():
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    extract_metrics(json.loads(line), out)
                except ValueError:
                    pass
    elif isinstance(doc, list):
        for item in doc:
            extract_metrics(item, out)
    return out


def device_tags(doc, out: set | None = None) -> set:
    """The set of device identities a bench record claims to have
    measured: every string ``device`` value plus a ``cpu_fallback``
    marker when any truthy ``cpu_fallback`` field appears. Walks the
    same blocks (parsed/results/metrics + embedded tail JSON) as
    extract_metrics, so anything that contributed a metric also
    contributes its device tag."""
    if out is None:
        out = set()
    if isinstance(doc, dict):
        dev = doc.get("device")
        if isinstance(dev, str) and dev:
            out.add(dev)
        if doc.get("cpu_fallback"):
            out.add("cpu_fallback")
        for key in ("parsed", "results", "metrics"):
            if key in doc:
                device_tags(doc[key], out)
        tail = doc.get("tail")
        if isinstance(tail, str):
            for line in tail.splitlines():
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    device_tags(json.loads(line), out)
                except ValueError:
                    pass
    elif isinstance(doc, list):
        for item in doc:
            device_tags(item, out)
    return out


def compare(a: dict, b: dict, tolerance: float, out=sys.stdout):
    """Print the delta table; return the list of regressed metric names.
    Higher is better (every extracted metric is a throughput)."""
    names = sorted(set(a) | set(b))
    regressed = []
    print("| metric | A | B | delta |", file=out)
    print("|---|---|---|---|", file=out)
    for name in names:
        va, vb = a.get(name), b.get(name)
        if va is None or vb is None:
            side = "A" if vb is None else "B"
            print(f"| {name} | {_num(va)} | {_num(vb)} "
                  f"| only in {side} |", file=out)
            continue
        delta = (vb - va) / va if va else 0.0
        flag = ""
        if delta < -tolerance:
            flag = " REGRESSED"
            regressed.append(name)
        print(f"| {name} | {_num(va)} | {_num(vb)} "
              f"| {delta:+.1%}{flag} |", file=out)
    return regressed


def _num(v):
    return "-" if v is None else f"{v:,.1f}"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Diff two BENCH_r*.json records; exit nonzero on "
                    "throughput regression past --tolerance")
    ap.add_argument("old", help="baseline bench record (A)")
    ap.add_argument("new", help="candidate bench record (B)")
    ap.add_argument("--tolerance", type=float, default=0.05,
                    help="allowed fractional regression before the "
                         "nonzero exit (default 0.05 = 5%%)")
    args = ap.parse_args(argv)

    with open(args.old, encoding="utf-8") as f:
        doc_a = json.load(f)
    with open(args.new, encoding="utf-8") as f:
        doc_b = json.load(f)
    a, b = extract_metrics(doc_a), extract_metrics(doc_b)

    common = set(a) & set(b)
    if not common:
        print("bench_compare: no metric appears in both records — "
              "nothing to gate on", file=sys.stderr)
        return 0

    tags_a, tags_b = device_tags(doc_a), device_tags(doc_b)
    if tags_a != tags_b:
        # different hardware (or one fell back to CPU): the deltas are
        # still worth eyeballing, but gating on them would turn a setup
        # difference into a fake perf regression
        compare(a, b, args.tolerance)
        print("bench_compare: incomparable devices "
              f"(A={sorted(tags_a) or ['?']}, "
              f"B={sorted(tags_b) or ['?']}) — refusing --tolerance "
              "gate", file=sys.stderr)
        return 0

    regressed = compare(a, b, args.tolerance)
    if regressed:
        print(f"bench_compare: {len(regressed)} metric(s) regressed "
              f"past {args.tolerance:.0%}: " + ", ".join(regressed),
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
