#!/usr/bin/env python
"""Diff two bench records (BENCH_r*.json): per-metric flips/s deltas.

    python tools/bench_compare.py OLD.json NEW.json [--tolerance 0.05]

Walks both documents for anything metric-shaped — ``parsed`` blocks and
``{"metric": ..., "value": ...}`` JSON lines embedded in the captured
``tail`` — plus per-config throughput derived from bench config lines
(``chains * (steps - 1) / seconds``, named by path/body/grid/chains so
the same configuration matches across records). Prints a delta table
and exits nonzero when any metric present in BOTH records regressed by
more than ``--tolerance`` (a fraction: 0.05 = 5%), so a bench wrapper
can gate on throughput drift between rounds the way obs_report.py
--check gates on stream shape.

Gating only applies when the two records measured the same hardware:
when their device tags differ (``device`` fields anywhere in the
walked blocks, or a truthy ``cpu_fallback`` marker), the delta table
still prints but the tolerance gate is refused — an "incomparable
devices" note and exit 0, because a TPU-vs-CPU-fallback "regression"
is a config problem, not a perf one. Records carrying a truthy
``degraded`` marker (bench.py: the dispatch ladder fell to a slower
kernel body during the timed region) are refused the same way — their
number measures the fallback body, not the intended path.

One exception: multi-chip records tag the device as ``"<dev0> xN"``
(bench.py --mesh), so a 4-chip and an 8-chip run of the same silicon
carry different tags but ARE comparable per chip. When the tags
normalize to the same silicon (``_base_silicon``) and both records
expose per-chip metrics (``flips_per_s_per_chip`` headline fields or
``scaling`` rows), the gate still runs — restricted to the per-chip
metric names, since aggregate flips/s legitimately moves with the
device count. Stdlib-only.
"""

from __future__ import annotations

import argparse
import json
import re
import sys


def _config_name(d: dict) -> str:
    """Stable name for a bench config line, from the fields that define
    the workload (not the measurement). kernel_path and graph keep the
    per-path metrics distinct: a sec11 run on lowered_bits and a square
    run on bitboard both say path=board, and both reuse grid/chains
    defaults — without these keys their throughputs would collide into
    one gated metric."""
    parts = []
    for k in ("path", "kernel_path", "body", "graph", "grid", "k",
              "chains", "device"):
        if k in d:
            parts.append(f"{k}={d[k]}")
    return "config[" + ",".join(parts) + "]"


def extract_metrics(doc, out: dict | None = None) -> dict:
    """name -> float over everything metric-shaped in a bench record."""
    if out is None:
        out = {}
    if isinstance(doc, dict):
        if "metric" in doc and isinstance(doc.get("value"), (int, float)):
            name = str(doc["metric"])
            if "policy" in doc and "family" in doc:
                # adaptive-control records (bench --adaptive): a
                # wall-clock-to-target-ESS ratio is only comparable
                # under the same workload family and policy stack
                name += (f"[family={doc['family']},"
                         f"policy={doc['policy']}]")
            elif "tenants" in doc and "workers" in doc:
                # fleet loadtest records (bench --fleet /
                # tools/loadtest.py): fairness and queue-to-start only
                # compare under the same tenant count AND worker fleet
                # size — qualify on both so a 4-worker and a 16-worker
                # record (or any kernel metric) never cross-gate
                name += (f"[tenants={doc['tenants']},"
                         f"workers={doc['workers']}]")
            elif "tenants" in doc:
                # sweep-service records (bench --service): a 4-tenant
                # and an 8-tenant efficiency measure different
                # coalescing shapes — qualify so they never gate
                # against each other
                name += f"[tenants={doc['tenants']}]"
            elif name.startswith("readback_"):
                # devstats records (bench --devstats): per-step readback
                # is a property of one runner path AND the kernel body
                # it dispatched to (the summary pytree is fixed-size but
                # the history keys differ per body) — qualify on both so
                # a board/lowered_bits record never gates against a
                # general/general_dense one
                name += (f"[path={doc.get('path', '-')},"
                         f"kernel_path={doc.get('kernel_path', '-')}]")
            elif "workload" in doc:
                # workload-matrix records (bench --workload-matrix):
                # every catalog workload is its own family (flip vs
                # ReCom, grid vs dual fixture, proposal variants) —
                # qualify per workload so families never cross-gate
                name += f"[workload={doc['workload']}]"
            out[name] = float(doc["value"])
            if isinstance(doc.get("flips_per_s_per_chip"), (int, float)):
                # multi-chip headline: the per-chip figure is the one
                # that gates across differing device counts
                out[str(doc["metric"]) + ".per_chip"] = \
                    float(doc["flips_per_s_per_chip"])
        elif ("seconds" in doc and "chains" in doc and "steps" in doc
              and doc.get("seconds")):
            # a bench config line: derive the throughput it measured
            flips = doc["chains"] * max(doc["steps"] - 1, 1)
            out[_config_name(doc) + ".flips_per_s"] = \
                flips / float(doc["seconds"])
        scaling = doc.get("scaling")
        if isinstance(scaling, list):
            # bench --mesh ladder rows: one metric per rung, named by
            # device count so the same rung matches across records
            for row in scaling:
                if not (isinstance(row, dict) and "devices" in row):
                    continue
                for field in ("flips_per_s", "flips_per_s_per_chip"):
                    if isinstance(row.get(field), (int, float)):
                        out[f"mesh[devices={row['devices']}].{field}"] = \
                            float(row[field])
        for key in ("parsed", "results", "metrics"):
            if key in doc:
                extract_metrics(doc[key], out)
        tail = doc.get("tail")
        if isinstance(tail, str):
            for line in tail.splitlines():
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    extract_metrics(json.loads(line), out)
                except ValueError:
                    pass
    elif isinstance(doc, list):
        for item in doc:
            extract_metrics(item, out)
    return out


def device_tags(doc, out: set | None = None) -> set:
    """The set of device identities a bench record claims to have
    measured: every string ``device`` value plus a ``cpu_fallback``
    marker when any truthy ``cpu_fallback`` field appears. Walks the
    same blocks (parsed/results/metrics + embedded tail JSON) as
    extract_metrics, so anything that contributed a metric also
    contributes its device tag."""
    if out is None:
        out = set()
    if isinstance(doc, dict):
        dev = doc.get("device")
        if isinstance(dev, str) and dev:
            out.add(dev)
        if doc.get("cpu_fallback"):
            out.add("cpu_fallback")
        for key in ("parsed", "results", "metrics"):
            if key in doc:
                device_tags(doc[key], out)
        tail = doc.get("tail")
        if isinstance(tail, str):
            for line in tail.splitlines():
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    device_tags(json.loads(line), out)
                except ValueError:
                    pass
    elif isinstance(doc, list):
        for item in doc:
            device_tags(item, out)
    return out


def record_degraded(doc) -> bool:
    """Did any block of this record run on a kernel body it DEGRADED to
    (bench.py's ``degraded`` marker, set when resilience.degrade logged
    a dispatch-ladder fall during the timed region)? Walks the same
    blocks as extract_metrics. Such a record's throughput measures the
    fallback body, not the intended path — gating on it would bless a
    broken fast path."""
    if isinstance(doc, dict):
        if doc.get("degraded"):
            return True
        for key in ("parsed", "results", "metrics"):
            if key in doc and record_degraded(doc[key]):
                return True
        tail = doc.get("tail")
        if isinstance(tail, str):
            for line in tail.splitlines():
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    if record_degraded(json.loads(line)):
                        return True
                except ValueError:
                    pass
    elif isinstance(doc, list):
        return any(record_degraded(item) for item in doc)
    return False


def _base_silicon(tag: str) -> str:
    """Collapse a device tag to the silicon it names: lowercase, strip
    parenthesized detail, a trailing ``xN`` device count (bench --mesh
    tags), and a trailing per-device ordinal. ``"TFRT_CPU_0 x8"`` and
    ``"TFRT_CPU_0 x2"`` both normalize to ``"tfrt_cpu"``."""
    t = tag.lower()
    t = re.sub(r"\s*\(.*?\)", "", t)
    t = re.sub(r"\s+x\d+$", "", t)
    t = re.sub(r"[:_]\d+$", "", t)
    return t.strip()


def compare(a: dict, b: dict, tolerance: float, out=sys.stdout,
            gate_names=None):
    """Print the delta table; return the list of regressed metric names.
    Higher is better (every extracted metric is a throughput). When
    ``gate_names`` is given, only those metrics can flag REGRESSED —
    the rest still print for eyeballing (per-chip gating across
    differing device counts)."""
    names = sorted(set(a) | set(b))
    regressed = []
    print("| metric | A | B | delta |", file=out)
    print("|---|---|---|---|", file=out)
    for name in names:
        va, vb = a.get(name), b.get(name)
        if va is None or vb is None:
            side = "A" if vb is None else "B"
            print(f"| {name} | {_num(va)} | {_num(vb)} "
                  f"| only in {side} |", file=out)
            continue
        delta = (vb - va) / va if va else 0.0
        flag = ""
        if delta < -tolerance and (gate_names is None
                                   or name in gate_names):
            flag = " REGRESSED"
            regressed.append(name)
        print(f"| {name} | {_num(va)} | {_num(vb)} "
              f"| {delta:+.1%}{flag} |", file=out)
    return regressed


def _num(v):
    return "-" if v is None else f"{v:,.1f}"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Diff two BENCH_r*.json records; exit nonzero on "
                    "throughput regression past --tolerance")
    ap.add_argument("old", help="baseline bench record (A)")
    ap.add_argument("new", help="candidate bench record (B)")
    ap.add_argument("--tolerance", type=float, default=0.05,
                    help="allowed fractional regression before the "
                         "nonzero exit (default 0.05 = 5%%)")
    args = ap.parse_args(argv)

    with open(args.old, encoding="utf-8") as f:
        doc_a = json.load(f)
    with open(args.new, encoding="utf-8") as f:
        doc_b = json.load(f)
    a, b = extract_metrics(doc_a), extract_metrics(doc_b)

    common = set(a) & set(b)
    if not common:
        print("bench_compare: no metric appears in both records — "
              "nothing to gate on", file=sys.stderr)
        return 0

    deg_a, deg_b = record_degraded(doc_a), record_degraded(doc_b)
    if deg_a or deg_b:
        # a degraded record timed whatever body the dispatch ladder fell
        # to, not the intended path — same shape as the cpu_fallback
        # refusal: print the deltas for eyeballing, refuse the gate
        compare(a, b, args.tolerance)
        which = " and ".join(s for s, d in (("A", deg_a), ("B", deg_b))
                             if d)
        print(f"bench_compare: record {which} ran degraded (kernel-path "
              "fallback during the timed region) — refusing --tolerance "
              "gate", file=sys.stderr)
        return 0

    tags_a, tags_b = device_tags(doc_a), device_tags(doc_b)
    if tags_a != tags_b:
        sil_a = {_base_silicon(t) for t in tags_a if t != "cpu_fallback"}
        sil_b = {_base_silicon(t) for t in tags_b if t != "cpu_fallback"}
        fb_a = "cpu_fallback" in tags_a
        fb_b = "cpu_fallback" in tags_b
        per_chip = {n for n in common if "per_chip" in n}
        if fb_a == fb_b and sil_a and sil_a == sil_b and per_chip:
            # same silicon, different device counts (mesh tags like
            # "TFRT_CPU_0 x2" vs "x8"): aggregate flips/s legitimately
            # moves with the count, but per-chip throughput must hold —
            # gate on the per-chip metrics only
            regressed = compare(a, b, args.tolerance,
                                gate_names=per_chip)
            print("bench_compare: device counts differ but silicon "
                  f"matches ({sorted(sil_a)[0]}) — gating per-chip "
                  f"metrics only ({len(per_chip)})", file=sys.stderr)
            if regressed:
                print(f"bench_compare: {len(regressed)} per-chip "
                      f"metric(s) regressed past {args.tolerance:.0%}: "
                      + ", ".join(regressed), file=sys.stderr)
                return 1
            return 0
        # different hardware (or one fell back to CPU): the deltas are
        # still worth eyeballing, but gating on them would turn a setup
        # difference into a fake perf regression
        compare(a, b, args.tolerance)
        print("bench_compare: incomparable devices "
              f"(A={sorted(tags_a) or ['?']}, "
              f"B={sorted(tags_b) or ['?']}) — refusing --tolerance "
              "gate", file=sys.stderr)
        return 0

    regressed = compare(a, b, args.tolerance)
    if regressed:
        print(f"bench_compare: {len(regressed)} metric(s) regressed "
              f"past {args.tolerance:.0%}: " + ", ".join(regressed),
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
