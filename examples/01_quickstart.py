"""Quickstart: the flagship 2-district flip walk, end to end.

Builds a rook grid, a balanced stripes plan, and runs a batch of
single-node-flip Markov chains through the board (stencil) fast path —
the same code path as the headline benchmark (bench.py) — recording
cut-count / boundary-size trajectories, geometric waiting times, and
accept telemetry. Reference semantics throughout: boundary proposal,
re-propose-on-invalid, patch contiguity, population bounds, Metropolis
accept base^(-d|cut|) (grid_chain_sec11.py's chain, vectorized).

    python examples/01_quickstart.py
    python examples/01_quickstart.py --grid 64 --chains 4096 --steps 20001
"""

import argparse
import os
import sys

# run as a script from anywhere: the package lives at the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--grid", type=int, default=32)
    ap.add_argument("--chains", type=int, default=256)
    ap.add_argument("--steps", type=int, default=5001)
    ap.add_argument("--base", type=float, default=2.63815853)
    ap.add_argument("--pop-tol", type=float, default=0.1)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (default: whatever jax.devices() finds, e.g. the TPU)")
    args = ap.parse_args()
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")


    import flipcomplexityempirical_tpu as fce

    g = fce.graphs.square_grid(args.grid, args.grid)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(contiguity="patch", parity_metrics=True,
                    geom_waits=True)

    bg, states, params = fce.sampling.init_board(
        g, plan, n_chains=args.chains, seed=0, spec=spec,
        base=args.base, pop_tol=args.pop_tol)
    res = fce.sampling.run_board(bg, spec, params, states,
                                 n_steps=args.steps)

    cut = np.asarray(res.history["cut_count"])      # (chains, steps)
    bnd = np.asarray(res.history["b_count"])
    s = res.host_state()
    n_steps = args.steps - 1
    print(f"grid {args.grid}x{args.grid}, {args.chains} chains x "
          f"{n_steps} steps (board fast path)")
    print(f"  cut edges      : start {cut[0, 0]:.0f}, "
          f"final mean {cut[:, -1].mean():.1f} "
          f"+- {cut[:, -1].std():.1f}")
    print(f"  boundary nodes : final mean {bnd[:, -1].mean():.1f}")
    print(f"  accept rate    : "
          f"{np.asarray(s.accept_count).mean() / n_steps:.3f}")
    print(f"  geometric waits: sum {float(np.sum(res.waits_total)):.4g} "
          f"(the reference's wait.txt scalar, per chain x{args.chains})")


if __name__ == "__main__":
    main()
