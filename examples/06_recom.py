"""ReCom: spanning-tree recombination moves, batched over chains.

Where the flip walk moves one node per step, a ReCom move merges the
two districts straddling a random cut edge, draws a random spanning
tree of the merged region (batched Boruvka), and re-splits it at an
edge whose subtree is population-balanced — redistricting's big-step
sampler (the reference wires gerrychain's recom but never sweeps it;
compat.make_recom is the oracle twin of this kernel). Every chain
executes its own move in the same jitted vmap.

This script runs a batch of ReCom chains on a k-district grid and
prints how fast the cut count and population spread move per ACCEPTED
move, next to the flip walk given the same number of node updates.

    python examples/06_recom.py
    python examples/06_recom.py --grid 32 --districts 8 --moves 80
"""

import argparse
import os
import sys

# run as a script from anywhere: the package lives at the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--grid", type=int, default=24)
    ap.add_argument("--districts", type=int, default=4)
    ap.add_argument("--chains", type=int, default=16)
    ap.add_argument("--moves", type=int, default=40)
    ap.add_argument("--epsilon", type=float, default=0.1,
                    help="population balance tolerance per ReCom split")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (default: whatever "
                         "jax.devices() finds, e.g. the TPU)")
    args = ap.parse_args()
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    import flipcomplexityempirical_tpu as fce
    from flipcomplexityempirical_tpu.sampling import recom_move

    k = args.districts
    g = fce.graphs.square_grid(args.grid, args.grid)
    plan = fce.graphs.stripes_plan(g, k)
    # parity clocks off: this example never interleaves flip-kernel
    # records, and recom_move without label_values would leave them stale
    spec = fce.Spec(n_districts=k, proposal="pair", accept="cut",
                    contiguity="patch", parity_metrics=False)
    dg, states, params = fce.init_batch(
        g, plan, n_chains=args.chains, seed=0, spec=spec, base=1.0,
        pop_tol=args.epsilon)

    target = g.n_nodes / k
    move = jax.jit(jax.vmap(
        lambda s: recom_move(dg, spec, s, epsilon=args.epsilon,
                             pop_target=target)))
    s = states
    cut0 = np.asarray(s.cut_count).copy()
    for _ in range(args.moves):
        s = move(s)
    jax.block_until_ready(s.assignment)

    cut = np.asarray(s.cut_count)
    executed = np.asarray(s.accept_count)
    pops = np.stack([np.bincount(a, minlength=k)
                     for a in np.asarray(s.assignment)])
    spread = np.abs(pops - target).max(axis=1) / target
    print(f"{args.grid}x{args.grid} grid, {k} districts, "
          f"{args.chains} chains x {args.moves} ReCom attempts "
          f"(epsilon {args.epsilon})")
    print(f"  executed moves/chain: mean {executed.mean():.1f} "
          f"(a failed tree draw leaves the plan unchanged)")
    print(f"  cut edges: start {cut0.mean():.0f} -> "
          f"final mean {cut.mean():.1f}")
    print(f"  worst district pop deviation per chain: "
          f"mean {spread.mean():.3f} (each split is "
          f"epsilon-balanced against the global ideal, up to "
          f"whole-node granularity)")
    print("  contrast: the flip walk moves ONE boundary node per step; "
          "one ReCom move redraws two whole districts")


if __name__ == "__main__":
    main()
