"""Replica exchange on the bimodal Frankengraph cell.

The FRANK B333 regime (base = 1/0.3, compactness-favoring) is bimodal:
plain chains sit in one cut-count well for a long time
(Frankenstein_chain.py's hardest cell; REPLICATION.md "Tempering the
B333 bimodal regime"). A beta ladder with replica-exchange swaps lets
the hot rungs carry the ladder across the barrier — this script runs
both arms on the same per-chain step budget and counts round trips
between the wells of the RECONSTRUCTED cold-rung (beta = 1) trajectory.

    python examples/02_replica_exchange.py                 # ~1 min CPU
    python examples/02_replica_exchange.py --steps 100001  # full budget

(The committed full-budget comparison lives at
replication/temper/compare_S100001.json — regenerate it with
replication/compare_tempering.py.)
"""

import argparse
import os
import sys

# run as a script from anywhere: the package lives at the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20001)
    ap.add_argument("--ladders", type=int, default=4)
    ap.add_argument("--swap-every", type=int, default=50)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (default: whatever jax.devices() finds, e.g. the TPU)")
    args = ap.parse_args()
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")


    import flipcomplexityempirical_tpu as fce
    from flipcomplexityempirical_tpu.experiments.config import TEMPER_BETAS
    from flipcomplexityempirical_tpu.sampling import (
        init_tempered, per_rung_history, run_tempered)
    from flipcomplexityempirical_tpu.stats import round_trips

    g = fce.graphs.frankengraph()
    plan = fce.graphs.frank_plan(g, alignment=0)
    spec = fce.Spec(contiguity="patch", parity_metrics=True,
                    geom_waits=True)
    base, pop_tol = 1 / 0.3, 0.1
    lo, hi = 40.0, 60.0          # the two cut-count wells

    # plain arm: independent chains at the physical target (beta = 1)
    dg, st, params = fce.init_batch(
        g, plan, n_chains=args.ladders, seed=args.seed, spec=spec,
        base=base, pop_tol=pop_tol)
    res_p = fce.run_chains(dg, spec, params, st, n_steps=args.steps)
    cut_p = np.asarray(res_p.history["cut_count"], np.float64)

    # tempered arm: same number of ladders, 10 rungs each, swaps every
    # swap_every transitions; the observable is the cold rung's
    # trajectory reconstructed through the swap record
    h, st_t, params_t = init_tempered(
        g, plan, betas=list(TEMPER_BETAS), n_ladders=args.ladders,
        seed=args.seed, spec=spec, base=base, pop_tol=pop_tol)
    res_t = run_tempered(h, spec, params_t, st_t, n_steps=args.steps,
                         betas=list(TEMPER_BETAS), n_ladders=args.ladders,
                         swap_every=args.swap_every, swap_seed=args.seed)
    cut_c = per_rung_history(res_t, "cut_count")[0].astype(np.float64)

    rt_p = round_trips(cut_p, lo, hi)
    rt_c = round_trips(cut_c, lo, hi)
    sr = res_t.swap_rates()
    print(f"FRANK B333, {args.steps - 1} steps, {args.ladders} plain "
          f"chains vs {args.ladders} ladders x {len(TEMPER_BETAS)} rungs")
    print(f"  plain    round trips/chain : {rt_p.tolist()} "
          f"(mean {rt_p.mean():.2f})")
    print(f"  tempered round trips/ladder: {rt_c.tolist()} "
          f"(mean {rt_c.mean():.2f}, cold rung)")
    print(f"  swap accept rates (cold->hot adjacent pairs): "
          f"{np.round(sr, 3).tolist()}")


if __name__ == "__main__":
    main()
