"""Scaling out: chains sharded over a device mesh, swaps over ICI.

Chains are the embarrassingly parallel axis, so the mesh is 1-D
("chains") and the train step is ``shard_map``'d: ``inner_steps`` of
purely local stencil yields, then one rank-paired cross-device
replica-exchange round via a scalar ``lax.all_gather`` + replicated
selection. On a pod the collectives ride ICI; here the same compiled
program runs on 8 virtual CPU devices (which is also how the test
suite proves 1-vs-8-device bit-identity — tests/test_sharding.py).

    python examples/05_multi_device.py
    python examples/05_multi_device.py --devices 4 --inner-steps 100
"""

import argparse
import os
import sys

# run as a script from anywhere: the package lives at the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--chains-per-device", type=int, default=2)
    ap.add_argument("--inner-steps", type=int, default=50)
    ap.add_argument("--rounds", type=int, default=4)
    args = ap.parse_args()

    # the virtual-device flag must win the race with backend init, so it
    # is set before the first jax import (on a real pod, delete this
    # block — jax.devices() already spans the slice)
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + f" --xla_force_host_platform_device_count"
                                 f"={args.devices}").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    import flipcomplexityempirical_tpu as fce
    from flipcomplexityempirical_tpu import distribute as dist

    n_dev = args.devices
    c = n_dev * args.chains_per_device
    g = fce.graphs.square_grid(8, 32)   # bit-board body shape (W % 32 == 0)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(contiguity="patch")
    bg, states, params = fce.sampling.init_board(
        g, plan, n_chains=c, seed=0, spec=spec, base=1.5, pop_tol=0.2)

    # one temperature rung per device -> swaps are genuinely cross-device
    betas = np.repeat(np.linspace(0.25, 2.0, n_dev),
                      args.chains_per_device).astype(np.float32)
    params = params.replace(beta=jnp.asarray(betas))

    mesh = dist.make_mesh(n_dev)
    states = dist.shard_chain_batch(mesh, states)
    params = dist.shard_chain_batch(mesh, params)
    step = dist.make_board_train_step(bg, spec, mesh,
                                      inner_steps=args.inner_steps,
                                      exchange=True)
    key = jax.random.PRNGKey(0)
    accepts, swaps = 0, 0
    for r in range(args.rounds):
        key, sub = jax.random.split(key)
        params, states, info = step(sub, params, states)
        # info["accepts"] reads the state's CUMULATIVE accept counter
        # (psum over devices), so keep the latest; swaps are per-round
        accepts = int(info["accepts"])
        swaps += int(info["swaps"])
    jax.block_until_ready(states.board)

    steps_done = args.rounds * args.inner_steps
    print(f"{n_dev} devices x {args.chains_per_device} chains, "
          f"{args.rounds} rounds x {args.inner_steps} local steps")
    print(f"  devices: {[str(d) for d in jax.devices()][:3]} ...")
    print(f"  flip accepts {accepts} "
          f"(of {c * steps_done} proposals), "
          f"cross-device beta swaps {swaps}")
    print("  same code path on a TPU pod: collectives ride ICI; "
          "see README 'Scaling out'")


if __name__ == "__main__":
    main()
