"""Precinct dual graphs: geometry in, compactness-aware chain out.

Generates an irregular Voronoi precinct map (the realistic-topology
stand-in this offline environment ships — point ``from_geojson`` /
``from_shapefile`` at any real precinct file for the identical code
path), builds the rook dual graph with boundary-length edge weights,
and runs a k-district pair walk whose Metropolis target scores boundary
LENGTH (``Spec(weighted_cut=True)``) rather than edge count. Reports
Polsby-Popper compactness of the initial vs final plans.

    python examples/03_dual_geometry.py
    python examples/03_dual_geometry.py --precincts 400 --districts 6
"""

import argparse
import os
import sys

# run as a script from anywhere: the package lives at the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--precincts", type=int, default=144)
    ap.add_argument("--districts", type=int, default=4)
    ap.add_argument("--chains", type=int, default=32)
    ap.add_argument("--steps", type=int, default=4001)
    ap.add_argument("--base", type=float, default=3.0,
                    help="Metropolis base; >1 penalizes boundary length, "
                         "and it needs to be comfortably >1 to beat the "
                         "entropy of long-boundary plans")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (default: whatever jax.devices() finds, e.g. the TPU)")
    args = ap.parse_args()
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    k = args.districts

    import flipcomplexityempirical_tpu as fce
    from flipcomplexityempirical_tpu.stats import polsby_popper

    fc = fce.graphs.voronoi_precincts(args.precincts, seed=args.seed)
    g, geo = fce.graphs.from_geojson(fc, pop_property="POP")
    plan = fce.graphs.stripes_plan(g, k)
    spec = fce.Spec(n_districts=k, proposal="pair" if k > 2 else "bi",
                    accept="cut", weighted_cut=True, contiguity="patch")

    dg, states, params = fce.init_batch(
        g, plan, n_chains=args.chains, seed=args.seed, spec=spec,
        base=args.base, pop_tol=0.25)
    res = fce.run_chains(dg, spec, params, states, n_steps=args.steps)

    pp_kw = dict(edges=g.edges, shared_perim=geo.shared_perim,
                 node_area=geo.area, node_exterior_perim=geo.exterior_perim)
    pp0 = polsby_popper(np.asarray(plan)[None], k, **pp_kw)
    ppf = polsby_popper(np.asarray(res.state.assignment), k, **pp_kw)
    cut = np.asarray(res.history["cut_count"])
    print(f"{args.precincts} Voronoi precincts -> dual graph "
          f"{g.n_nodes} nodes / {len(g.edges)} edges; "
          f"{k} districts, {args.chains} chains x {args.steps - 1} steps")
    print(f"  boundary-length-weighted walk, base {args.base}")
    print(f"  cut edges: start {cut[0, 0]}, final mean "
          f"{cut[:, -1].mean():.1f}")
    print(f"  Polsby-Popper (mean over districts): initial "
          f"{pp0.mean():.3f} -> final {ppf.mean():.3f} "
          f"(higher = more compact; base > 1 favors short boundaries)")


if __name__ == "__main__":
    main()
