"""The batched stats interface: reading a metastable chain's diagnostics.

Runs the Frankengraph's hard bimodal cell (base 1/0.3 — two cut-count
wells near 40 and 60, Frankenstein_chain.py's B333 regime) and feeds
the (chains, T) cut-count histories through the diagnostics the
BASELINE correctness bar names. Each one reads a different symptom of
metastability, and together they tell a coherent story that no single
number does:

- per-chain ESS is HIGH: inside its well each chain decorrelates fast;
- Gelman-Rubin R-hat stays far above 1: the chains disagree about the
  mean because they are stuck in different wells;
- well crossings are rare: the direct count of barrier transits;
- the bottleneck-ratio scan locates WHERE the barrier is: the
  conductance minimum lands between the two wells — the quantity whose
  reference estimate the framework replicates (REPLICATION.md).

(Example 02 shows the cure for this cell: a replica-exchange ladder.)

    python examples/04_diagnostics.py
    python examples/04_diagnostics.py --steps 20001 --chains 64
"""

import argparse
import os
import sys

# run as a script from anywhere: the package lives at the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--chains", type=int, default=16)
    ap.add_argument("--steps", type=int, default=6001)
    ap.add_argument("--burn", type=int, default=1500)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (default: whatever "
                         "jax.devices() finds, e.g. the TPU)")
    args = ap.parse_args()
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import flipcomplexityempirical_tpu as fce
    from flipcomplexityempirical_tpu.stats import (
        bottleneck_ratio, bottleneck_ratio_device, ess_device,
        gelman_rubin, gelman_rubin_device, integer_thresholds,
        integrated_autocorr_time, well_crossings)

    g = fce.graphs.frankengraph()
    plan = fce.graphs.frank_plan(g, alignment=0)
    spec = fce.Spec(contiguity="patch", parity_metrics=True)

    dg, states, params = fce.init_batch(
        g, plan, n_chains=args.chains, seed=0, spec=spec,
        base=1 / 0.3, pop_tol=0.1)
    # history stays DEVICE-resident: ESS / R-hat / bottleneck run on the
    # accelerator (f32 twins of the host f64 estimators — parity is
    # test-pinned) and only scalars come back; on a TPU this skips a
    # (chains, T) x 4-key readback that can dwarf the sampling itself
    res = fce.run_chains(dg, spec, params, states, n_steps=args.steps,
                         history_device=True)
    cut_dev = res.history["cut_count"][:, args.burn:]

    _, ess_total = ess_device(cut_dev)
    rhat = float(gelman_rubin_device(cut_dev))
    thr = integer_thresholds(cut_dev)
    phi, r_star = (float(v)
                   for v in bottleneck_ratio_device(cut_dev, thr))
    # every device scalar is cross-checked by its host f64 estimator;
    # the trajectory-shape helpers (IAT, crossings) read the history
    # once and the host ESS reuses their tau
    cut = np.asarray(cut_dev, np.float64)
    tau = integrated_autocorr_time(cut)
    cross = well_crossings(cut, 40.0, 60.0)
    phi_h, _ = bottleneck_ratio(cut, np.asarray(thr, np.float64))
    print(f"FRANK B333 (bimodal), {args.chains} chains x "
          f"{cut.shape[1]} recorded steps after burn-in "
          f"(diagnostics computed on-device)")
    print(f"  per-chain ESS total {float(ess_total):,.0f} "
          f"(IAT median {np.median(tau):.0f} steps) — fast WITHIN a well"
          f"  [host f64 check: {(cut.shape[1] / tau).sum():,.0f}]")
    print(f"  Gelman-Rubin R-hat {rhat:.3f} "
          f"— far from 1: chains sit in different wells"
          f"  [host: {gelman_rubin(cut):.3f}]")
    print(f"  well crossings (40 <-> 60): {cross.tolist()} "
          f"(mean {cross.mean():.2f} per chain)")
    print(f"  bottleneck ratio {phi:.5f} at cut <= {r_star:.0f} "
          f"— the conductance minimum between the wells at ~40 and ~60"
          f"  [host: {phi_h:.5f}]")


if __name__ == "__main__":
    main()
