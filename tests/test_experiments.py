"""Experiment driver tests: artifact set, filename scheme, resume manifest,
both backends (golden-artifact strategy of SURVEY.md section 4.6)."""

import os

import numpy as np
import pytest

from flipcomplexityempirical_tpu import experiments as ex


def test_config_tags_match_reference_vocabulary():
    tags = {c.tag for c in ex.sec11_sweep()}
    # B vocabulary from the shipped artifact dirs (SURVEY.md section 5):
    for b in (10, 14, 20, 37, 80, 100, 263, 400, 695, 1000):
        assert any(f"B{b}P" in t for t in tags), b
    for p in (1, 5, 10, 50, 90):
        assert any(t.endswith(f"P{p}") for t in tags), p
    assert len(tags) == 150
    ftags = {c.tag for c in ex.frank_sweep()}
    assert len(ftags) == 24
    assert "2B333P90" in ftags  # int(100/0.3) == 333 truncation


@pytest.mark.slow
def test_run_config_artifacts_and_resume(tmp_path):
    out = str(tmp_path / "plots")
    cfg = ex.ExperimentConfig(family="frank", alignment=2, base=1 / .3,
                              pop_tol=0.5, total_steps=300, n_chains=2,
                              backend="jax")
    data = ex.run_config(cfg, out)
    for kind in ex.ARTIFACT_KINDS:
        assert os.path.exists(os.path.join(out, cfg.tag + kind)), kind
    wait = int(open(os.path.join(out, cfg.tag + "wait.txt")).read())
    assert wait >= 0
    assert ex.is_done(cfg, out)
    # resume: sweep over the same config is a no-op
    results = ex.run_sweep([cfg], out, verbose=False)
    assert results == []
    # histories have the full yield count
    assert data["history"]["cut_count"].shape == (2, 300)
    assert len(data["slopes"]) == 300


def test_python_backend_runs(tmp_path):
    out = str(tmp_path / "plots")
    cfg = ex.ExperimentConfig(family="frank", alignment=0, base=0.3,
                              pop_tol=0.5, total_steps=200,
                              backend="python")
    data = ex.run_config(cfg, out)
    assert ex.is_done(cfg, out)
    assert data["history"]["cut_count"].shape == (1, 200)
    # num_flips bounded by yields; part_sum finalized for never-flipped
    assert data["num_flips"].sum() <= 200
    assert np.abs(data["part_sum"]).max() <= 200


@pytest.mark.slow
def test_checkpoint_roundtrip(tmp_path):
    out = str(tmp_path / "plots")
    ck = str(tmp_path / "ckpt")
    cfg = ex.ExperimentConfig(family="frank", alignment=1, base=0.3,
                              pop_tol=0.5, total_steps=150, n_chains=2)
    data = ex.run_config(cfg, out, checkpoint_dir=ck)
    loaded = ex.load_checkpoint(ck, cfg)
    assert loaded is not None
    assert int(loaded["meta_done"]) == 150
    assert (loaded["state_assignment"] ==
            np.asarray(data["state"].assignment)).all()


@pytest.mark.slow
def test_mid_config_resume_is_bit_identical(tmp_path):
    """A crash between checkpoint segments resumes exactly: the
    interrupted-and-resumed run reproduces the uninterrupted run
    bit-for-bit (PRNG keys live in the checkpointed chain state)."""
    from flipcomplexityempirical_tpu.experiments import driver as drv

    kw = dict(family="frank", alignment=0, base=0.3, pop_tol=0.5,
              total_steps=240, n_chains=2)
    # the baseline is a genuinely uninterrupted, unsegmented run
    clean = ex.run_config(ex.ExperimentConfig(**kw), str(tmp_path / "a"))

    # interrupted run: crash after the first 100-step segment...
    cfg = ex.ExperimentConfig(**kw, checkpoint_every=100)
    ck_b = str(tmp_path / "ckb")
    g, plan, _ = drv.build_graph_and_plan(cfg)
    with pytest.raises(drv._SegmentStop):
        drv._run_jax(cfg, g, plan, checkpoint_dir=ck_b,
                     _stop_after_segments=1)
    partial = ex.load_checkpoint(ck_b, cfg)
    assert int(partial["meta_done"]) == 100
    # ...then resume through the public entry point
    out_b = str(tmp_path / "b")
    resumed = ex.run_config(cfg, out_b, checkpoint_dir=ck_b)

    for k in clean["history"]:
        np.testing.assert_array_equal(clean["history"][k],
                                      resumed["history"][k], err_msg=k)
    np.testing.assert_array_equal(np.asarray(clean["state"].assignment),
                                  np.asarray(resumed["state"].assignment))
    np.testing.assert_allclose(clean["waits_all"], resumed["waits_all"])
    np.testing.assert_array_equal(clean["part_sum"], resumed["part_sum"])


@pytest.mark.slow
def test_checkpoint_mismatch_and_stale_formats_ignored(tmp_path):
    """Resume must never crash on, or silently reuse, incompatible
    checkpoints: wrong config identity, old formats, too-long runs."""
    ck = str(tmp_path / "ck")
    cfg = ex.ExperimentConfig(family="frank", alignment=1, base=0.3,
                              pop_tol=0.5, total_steps=120, n_chains=2)
    data = ex.run_config(cfg, str(tmp_path / "o1"), checkpoint_dir=ck)
    assert ex.load_checkpoint(ck, cfg) is not None

    # different seed => identity mismatch => fresh start, not stale chains
    cfg2 = ex.ExperimentConfig(family="frank", alignment=1, base=0.3,
                               pop_tol=0.5, total_steps=120, n_chains=2,
                               seed=9)
    assert ex.load_checkpoint(ck, cfg2) is None
    # shorter rerun than the checkpoint => ignored
    cfg3 = ex.ExperimentConfig(family="frank", alignment=1, base=0.3,
                               pop_tol=0.5, total_steps=60, n_chains=2)
    assert ex.load_checkpoint(ck, cfg3) is None
    # pre-versioned format (bare field names) => ignored, no KeyError
    np.savez(os.path.join(ck, cfg.tag + ".npz"),
             assignment=np.asarray(data["state"].assignment))
    assert ex.load_checkpoint(ck, cfg) is None
    run2 = ex.run_config(cfg, str(tmp_path / "o2"), checkpoint_dir=ck)
    np.testing.assert_array_equal(np.asarray(run2["state"].assignment),
                                  np.asarray(data["state"].assignment))
