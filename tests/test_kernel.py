"""Kernel step tests: derived-field invariants, connectivity preservation,
acceptance math, parity bookkeeping quirks, geometric waits."""

import dataclasses

import numpy as np
import networkx as nx
import jax
import jax.numpy as jnp
import pytest

import flipcomplexityempirical_tpu as fce
from flipcomplexityempirical_tpu.state import derive
from flipcomplexityempirical_tpu.kernel import step as kstep


def run_small(spec, n=8, k=2, steps=400, chains=8, base=0.8, tol=0.3, seed=0):
    g = fce.graphs.square_grid(n, n)
    plan = fce.graphs.stripes_plan(g, k)
    dg, states, params = fce.init_batch(
        g, plan, n_chains=chains, seed=seed, spec=spec, base=base,
        pop_tol=tol)
    res = fce.run_chains(dg, spec, params, states, n_steps=steps)
    return g, dg, res


def assert_districts_connected(g, s, k, lo=None, hi=None):
    """Every chain's districts are alive, connected, and (optionally)
    size-bounded."""
    gx = nx.Graph(list(map(tuple, g.edges)))
    for c in range(s.assignment.shape[0]):
        a = np.asarray(s.assignment[c])
        for d in range(k):
            nodes = np.nonzero(a == d)[0].tolist()
            assert nodes, f"district {d} vanished in chain {c}"
            assert nx.is_connected(gx.subgraph(nodes))
            if lo is not None:
                assert lo <= len(nodes) <= hi, (d, len(nodes))


def check_invariants(dg, s, k, proposal="bi"):
    c = s.assignment.shape[0]
    cut, cdeg, dpop, cc, bc = jax.vmap(
        lambda a: derive(dg, a, k, proposal))(jnp.asarray(s.assignment))
    assert (np.asarray(cut) == np.asarray(s.cut)).all()
    assert (np.asarray(cdeg) == np.asarray(s.cut_deg)).all()
    assert (np.asarray(dpop) == np.asarray(s.dist_pop)).all()
    assert (np.asarray(cc) == np.asarray(s.cut_count)).all()
    assert (np.asarray(bc) == np.asarray(s.b_count)).all()


@pytest.mark.parametrize("contig", ["patch", "exact"])
def test_invariants_bi(contig):
    spec = fce.Spec(contiguity=contig)
    g, dg, res = run_small(spec, steps=300)
    check_invariants(dg, res.host_state(), 2)


@pytest.mark.slow
def test_invariants_pair_k4():
    spec = fce.Spec(n_districts=4, proposal="pair", contiguity="patch")
    g, dg, res = run_small(spec, n=10, k=4, steps=300, tol=0.5)
    s = res.host_state()
    check_invariants(dg, s, 4, proposal="pair")
    assert_districts_connected(g, s, 4)


def test_districts_stay_connected_and_balanced():
    spec = fce.Spec(contiguity="patch")
    tol = 0.1
    g, dg, res = run_small(spec, n=8, steps=600, tol=tol, base=1.0)
    s = res.host_state()
    ideal = g.n_nodes / 2
    assert_districts_connected(g, s, 2, lo=(1 - tol) * ideal,
                               hi=(1 + tol) * ideal)


def test_accept_always_moves_every_step():
    spec = fce.Spec(accept="always", geom_waits=False)
    g, dg, res = run_small(spec, steps=200, base=1.0, tol=0.5)
    s = res.host_state()
    # with accept='always' and repropose, every non-initial yield moves
    assert (np.asarray(s.accept_count) == 199).all()


def test_base_extremes_control_acceptance():
    # base >> 1 rewards compactness: cut count must drop or stay near the
    # minimum; base << 1 grows the interface.
    spec = fce.Spec()
    _, _, res_hi = run_small(spec, steps=800, base=8.0, tol=0.5, seed=1)
    _, _, res_lo = run_small(spec, steps=800, base=0.12, tol=0.5, seed=1)
    hi = res_hi.history["cut_count"][:, -100:].mean()
    lo = res_lo.history["cut_count"][:, -100:].mean()
    assert hi < lo, (hi, lo)


def test_record_parity_bookkeeping_quirk():
    """Reference lines 396-400: on EVERY yield the last-flipped node is
    re-booked — including self-loop yields. Drive record() directly."""
    g = fce.graphs.square_grid(4, 4)
    dg = g.device()
    spec = fce.Spec(parity_metrics=True, geom_waits=False)
    params = kstep.make_params(1.0, 0.0, 100.0, [1, -1])
    from flipcomplexityempirical_tpu.state import init_state
    st = init_state(dg, jnp.asarray(fce.graphs.stripes_plan(g, 2)), 2,
                    jax.random.PRNGKey(0), jnp.asarray([1, -1], jnp.int32))
    # pretend node 5 just flipped to district 1 (label -1) at yield t=3
    st = st.replace(cur_flip_node=jnp.int32(5), t_yield=jnp.int32(3),
                    assignment=st.assignment.at[5].set(1))
    rec = jax.jit(lambda s: kstep.record(dg, spec, params, s))
    st1, _ = rec(st)
    # part_sum[5] -= sign * (t - last_flipped) = -(-1) * (3 - 0) = +3 on top
    # of the init value, which was seeded from the PRE-flip district label
    # (district 0 -> +1), because init_state ran before the manual flip
    base_ps = 1
    assert int(st1.part_sum[5]) == base_ps + 3
    assert int(st1.last_flipped[5]) == 3
    assert int(st1.num_flips[5]) == 1
    # a self-loop yield at t=4 re-books the same node (the reference quirk)
    st2, _ = rec(st1)
    assert int(st2.num_flips[5]) == 2
    assert int(st2.last_flipped[5]) == 4
    assert int(st2.part_sum[5]) == base_ps + 3 + 1
    # initial state (cur_flip_node=-1) books nothing
    st0 = st.replace(cur_flip_node=jnp.int32(-1))
    st0b, _ = rec(st0)
    assert (np.asarray(st0b.num_flips) == 0).all()


def test_geom_wait_distribution():
    # mean of Geometric(p)-1 is (1-p)/p
    key = jax.random.PRNGKey(0)
    n_nodes, k, b = 100, 2, 37
    p = b / (n_nodes ** k - 1)
    keys = jax.random.split(key, 20000)
    w = jax.vmap(lambda kk: kstep.sample_geom_minus1(
        kk, jnp.int32(b), n_nodes, k))(keys)
    w = np.asarray(w)
    expect = (1 - p) / p
    assert abs(w.mean() - expect) / expect < 0.05
    assert (w >= 0).all()


def test_geom_wait_overflow_guard():
    """n**k - 1 past f32 range must raise (silent p=0 => infinite waits
    diverging from the reference's float64 geom_wait), and the board gates
    must route such configs off the geom-sampling bodies."""
    key = jax.random.PRNGKey(0)
    with pytest.raises(ValueError, match="geom_waits"):
        kstep.sample_geom_minus1(key, jnp.int32(5), 4096, 11)
    # k=10 at n=4096 is the last finite config and still samples
    w = kstep.sample_geom_minus1(key, jnp.int32(5), 4096, 10)
    assert np.isfinite(float(w))

    from flipcomplexityempirical_tpu.kernel import bitboard, board
    g = fce.graphs.square_grid(64, 64)
    for k, ok in [(8, True), (11, False)]:
        spec = fce.Spec(n_districts=k, proposal="pair", contiguity="patch",
                        geom_waits=True, parity_metrics=False)
        assert board.supports(g, spec) == ok
        bg = board.make_board_graph(g)
        assert bitboard.supported_pair(bg, spec) == ok
        nogeom = dataclasses.replace(spec, geom_waits=False)
        assert board.supports(g, nogeom)
        assert bitboard.supported_pair(bg, nogeom)


def test_interface_metrics_vertical_split():
    g = fce.graphs.grid_sec11()
    dg = g.device()
    plan = fce.graphs.sec11_plan(g, 0)  # vertical split at x>19
    cut, *_ = derive(dg, jnp.asarray(plan), 2)
    slope, angle = jax.jit(
        lambda c: kstep.interface_metrics(dg, c))(cut)
    # interface crosses walls y==0 and y==39 at x=19.5: dx=0 -> slope inf
    assert np.isinf(float(slope))
    # angle between (19.5,0)-(20,20) and (19.5,39)-(20,20), ref formula
    enda, endb = np.array([19.5, 0.0]), np.array([19.5, 39.0])
    c = np.array([20.0, 20.0])
    va, vb = enda - c, endb - c
    want = np.arccos(np.clip(np.dot(va / np.linalg.norm(va),
                                    vb / np.linalg.norm(vb)), -1, 1))
    assert abs(float(angle) - want) < 1e-5


def test_selfloop_policy_runs():
    spec = fce.Spec(invalid="selfloop")
    g, dg, res = run_small(spec, steps=300)
    s = res.host_state()
    check_invariants(dg, s, 2)
    # selfloop mode: exactly one try per step
    assert (np.asarray(s.tries_sum) == 299).all()


def test_waits_match_history_sum():
    spec = fce.Spec()
    _, _, res = run_small(spec, steps=500, seed=4)
    np.testing.assert_allclose(
        res.waits_total, res.history["wait"].sum(axis=1, dtype=np.float64),
        rtol=1e-6)


def test_anneal_linear_beta_zero_accepts_all_valid():
    # With t0 beyond the run, the annealed beta is 0 => the Metropolis bound
    # is base**0 = 1 and every valid proposal is accepted.
    spec = fce.Spec(anneal="linear")
    g = fce.graphs.square_grid(8, 8)
    plan = fce.graphs.stripes_plan(g, 2)
    dg, states, params = fce.init_batch(
        g, plan, n_chains=4, seed=1, spec=spec, base=0.01, pop_tol=0.5)
    params = params.replace(anneal_t0=jnp.float32(10**9))
    res = fce.run_chains(dg, spec, params, states, n_steps=200)
    s = res.host_state()
    # 200 yields = initial state + 199 transitions (reference semantics)
    assert (np.asarray(s.accept_count) == 199).all()


@pytest.mark.slow
def test_anneal_linear_beta_ramps_to_max():
    # t0=0, ramp=1 => beta saturates at beta_max immediately: the annealed
    # chain must match a constant-beta chain distributionally (strongly
    # suppressive base, so cut counts stay near the minimum).
    base = 10.0
    g = fce.graphs.square_grid(8, 8)
    plan = fce.graphs.stripes_plan(g, 2)

    def final_cuts(spec, params_fix):
        dg, states, params = fce.init_batch(
            g, plan, n_chains=16, seed=2, spec=spec, base=base, pop_tol=0.5)
        params = params_fix(params)
        res = fce.run_chains(dg, spec, params, states, n_steps=400)
        return np.asarray(res.host_state().cut_count, dtype=np.float64)

    ann = final_cuts(
        fce.Spec(anneal="linear"),
        lambda p: p.replace(anneal_t0=jnp.float32(0.0),
                            anneal_ramp=jnp.float32(1.0),
                            anneal_beta_max=jnp.float32(2.0)))
    const = final_cuts(fce.Spec(), lambda p: p.replace(
        beta=jnp.full_like(p.beta, 2.0)))
    # both collapse to (near-)minimal interfaces; means within 2 edges
    assert abs(ann.mean() - const.mean()) < 2.0


def test_frame_interface_constraint_holds():
    # boundary_condition as a kernel constraint: the outer frame keeps
    # touching both districts for the whole run even at high base (which
    # otherwise shrinks the minority district away from the frame).
    spec = fce.Spec(frame_interface=True)
    g, dg, res = run_small(spec, n=8, steps=500, base=4.0, tol=0.9, seed=4)
    s = res.host_state()
    frame = np.asarray(g.frame_mask)
    for c in range(s.assignment.shape[0]):
        vals = np.unique(np.asarray(s.assignment)[c][frame])
        assert len(vals) == 2


@pytest.mark.slow
def test_invariants_pair_k8():
    """BASELINE config 2 at k=8: districts all alive, connected, balanced."""
    spec = fce.Spec(n_districts=8, proposal="pair", contiguity="patch")
    g, dg, res = run_small(spec, n=12, k=8, steps=300, tol=0.5, base=1.0)
    s = res.host_state()
    check_invariants(dg, s, 8, proposal="pair")
    ideal = g.n_nodes / 8
    assert_districts_connected(g, s, 8, lo=0.5 * ideal, hi=1.5 * ideal)


@pytest.mark.parametrize("make", [
    lambda: fce.graphs.triangular_lattice(5, 8),
    lambda: fce.graphs.hex_lattice(3, 3),
])
def test_chain_runs_on_non_grid_lattices(make):
    """BASELINE config 3: flip walks on triangular/hex adjacency keep the
    districts connected (hex uses patch radius 3)."""
    g = make()
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(contiguity="patch")
    dg, st, params = fce.init_batch(g, plan, n_chains=8, seed=4, spec=spec,
                                    base=1.0, pop_tol=0.5)
    res = fce.run_chains(dg, spec, params, st, n_steps=300)
    s = res.host_state()
    check_invariants(dg, s, 2)
    assert_districts_connected(g, s, 2)
    assert int(np.asarray(s.accept_count).sum()) > 0
