"""Fault-tolerant sweep execution (ISSUE 7): the fault-injection
harness, error classification, the sweep supervisor's retry/quarantine
machinery, checkpoint integrity (manifest, generations, ``.corrupt/``
fallback), graceful kernel degradation, and the non-fatal heartbeat.

The chaos tests drive REAL sweeps on CPU with injected faults and
assert the recovered run is byte-identical to a fault-free one — the
acceptance bar for every recovery path being exercised in tier-1.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import flipcomplexityempirical_tpu.experiments as ex
from flipcomplexityempirical_tpu import obs
from flipcomplexityempirical_tpu import resilience as rz
from flipcomplexityempirical_tpu.experiments import driver as drv
from flipcomplexityempirical_tpu.resilience import faults as rfaults
from flipcomplexityempirical_tpu.resilience import supervisor as rsup

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    """Every test starts and ends with no fault plan installed."""
    rfaults.install_plan(None)
    yield
    rfaults.install_plan(None)


def _events(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# ---- fault plan --------------------------------------------------------

def test_fault_plan_parse_and_describe():
    spec = ("checkpoint.write:once,segment.step:fail*2@4,"
            "compile:p=0.5,checkpoint.load:truncate@2,"
            "recorder.emit:always,seed=7")
    plan = rfaults.FaultPlan.from_spec(spec)
    assert plan.seed == 7
    assert [r.describe() for r in plan.rules] == [
        "checkpoint.write:once", "segment.step:fail*2@4",
        "compile:p=0.5", "checkpoint.load:truncate@2",
        "recorder.emit:always"]
    # describe() round-trips through from_spec
    again = rfaults.FaultPlan.from_spec(plan.describe())
    assert again.describe() == plan.describe()


@pytest.mark.parametrize("bad", ["nosuchsite:once", "compile:meh",
                                 "compile", "segment.step:once@0"])
def test_fault_plan_rejects_bad_specs(bad):
    with pytest.raises(ValueError):
        rfaults.FaultPlan.from_spec(bad)


def test_fault_plan_hit_ordinal_and_budget():
    plan = rfaults.FaultPlan.from_spec("segment.step:fail*2@3")
    fired = []
    for hit in range(1, 7):
        try:
            plan.check("segment.step")
        except rfaults.InjectedFault as e:
            fired.append((hit, e.hit))
    # arms at hit 3, budget 2 -> fires exactly on hits 3 and 4
    assert fired == [(3, 3), (4, 4)]
    assert plan.log == [("segment.step", "fail", 3),
                        ("segment.step", "fail", 4)]


def test_fault_plan_sites_count_independently():
    plan = rfaults.FaultPlan.from_spec(
        "checkpoint.write:once@2,segment.step:once")
    with pytest.raises(rfaults.InjectedFault):
        plan.check("segment.step")         # its own hit 1
    plan.check("checkpoint.write")         # hit 1 < @2: passes
    with pytest.raises(rfaults.InjectedFault):
        plan.check("checkpoint.write")     # hit 2


def test_fault_plan_p_mode_is_seeded():
    def firing_pattern(seed):
        plan = rfaults.FaultPlan.from_spec(f"compile:p=0.5,seed={seed}")
        out = []
        for _ in range(20):
            try:
                plan.check("compile")
                out.append(0)
            except rfaults.InjectedFault:
                out.append(1)
        return out

    a, b = firing_pattern(3), firing_pattern(3)
    assert a == b                       # reproducible
    assert 0 < sum(a) < 20              # actually probabilistic
    assert firing_pattern(4) != a       # and seed-dependent


def test_poison_mode_marks_injected_fault():
    plan = rfaults.FaultPlan.from_spec("segment.step:always")
    with pytest.raises(rfaults.InjectedFault) as ei:
        plan.check("segment.step")
    assert ei.value.poison
    plan2 = rfaults.FaultPlan.from_spec("segment.step:once")
    with pytest.raises(rfaults.InjectedFault) as ei:
        plan2.check("segment.step")
    assert not ei.value.poison


def test_truncate_and_corrupt_file(tmp_path):
    p = tmp_path / "blob.npz"
    p.write_bytes(b"x" * 1000)
    rfaults.truncate_file(str(p))
    assert p.stat().st_size == 500
    # corrupt_file: independent hit stream, truncate rules only
    plan = rfaults.FaultPlan.from_spec("checkpoint.write:truncate@2")
    rfaults.install_plan(plan)
    q = tmp_path / "part.npz"
    q.write_bytes(b"y" * 100)
    assert not rfaults.corrupt_file("checkpoint.write", str(q))  # hit 1
    assert rfaults.corrupt_file("checkpoint.write", str(q))      # hit 2
    assert q.stat().st_size == 50
    # missing files never count a hit
    assert not rfaults.corrupt_file("checkpoint.write",
                                    str(tmp_path / "nope.npz"))


def test_install_from_env_and_fault_point():
    assert rfaults.install_from_env({}) is None
    assert rfaults.active_plan() is None
    rfaults.fault_point("segment.step")   # no plan: no-op
    plan = rfaults.install_from_env(
        {rfaults.ENV_VAR: "segment.step:once,seed=5"})
    assert plan is rfaults.active_plan()
    with pytest.raises(rfaults.InjectedFault):
        rfaults.fault_point("segment.step")
    rfaults.fault_point("segment.step")   # budget spent


def test_recorder_emit_fault_site(tmp_path):
    rfaults.install_from_spec("recorder.emit:once@2")
    rec = obs.from_spec(str(tmp_path / "ev.jsonl"))
    rec.emit("sweep_summary", completed=0, retried=0, quarantined=0,
             failed=0)
    with pytest.raises(rfaults.InjectedFault):
        rec.emit("sweep_summary", completed=0, retried=0, quarantined=0,
                 failed=0)
    rec.close()


# ---- classification / policy / deadline --------------------------------

def test_classify_error_taxonomy():
    c = rsup.classify_error
    assert c(OSError("disk hiccup")) == rsup.TRANSIENT
    assert c(TimeoutError()) == rsup.TRANSIENT
    assert c(RuntimeError("mystery")) == rsup.TRANSIENT
    assert c(MemoryError()) == rsup.RESOURCE
    assert c(RuntimeError("RESOURCE_EXHAUSTED: hbm")) == rsup.RESOURCE
    assert c(rz.ConfigDeadlineExceeded("t", 1.0)) == rsup.RESOURCE
    assert c(ValueError("bad shape")) == rsup.DETERMINISTIC
    assert c(rz.CheckpointIdentityError("t", ["a"], [])) \
        == rsup.DETERMINISTIC
    inj = rfaults.InjectedFault("segment.step", "fail", 1)
    assert c(inj) == rsup.TRANSIENT
    poison = rfaults.InjectedFault("segment.step", "always", 1)
    assert c(poison) == rsup.DETERMINISTIC
    # PR 3 anomaly taxonomy: a sick walk makes the failure deterministic
    assert c(RuntimeError("x"), anomalies={"frozen_chain": 2}) \
        == rsup.DETERMINISTIC
    assert c(RuntimeError("x"), anomalies={"throughput_regression": 1}) \
        == rsup.TRANSIENT


def test_backoff_grows_caps_and_jitters():
    import random
    pol = rsup.RetryPolicy(backoff_base_s=0.1, backoff_factor=2.0,
                           backoff_max_s=0.5, jitter=0.25)
    rng = random.Random(0)
    waits = [pol.backoff(a, rng) for a in range(1, 6)]
    for w, base in zip(waits, (0.1, 0.2, 0.4, 0.5, 0.5)):
        assert base <= w <= base * 1.25
    # seeded: the schedule replays
    rng2 = random.Random(0)
    assert waits == [pol.backoff(a, rng2) for a in range(1, 6)]


def test_cooperative_deadline():
    rsup.clear_deadline()
    rsup.check_deadline()                  # unarmed: no-op
    rsup.set_deadline(1e-9, tag="T")
    try:
        with pytest.raises(rz.ConfigDeadlineExceeded) as ei:
            import time
            time.sleep(0.01)
            rsup.check_deadline()
        assert "T" in str(ei.value)
    finally:
        rsup.clear_deadline()
    rsup.check_deadline()


def test_checkpoint_identity_error_names_both_sides():
    e = rz.CheckpointIdentityError(
        "2B30P10", expected_fields=["state_key", "state_assignment"],
        found_fields=["state_assignment"], identity="frank/seed0")
    msg = str(e)
    assert "2B30P10" in msg
    assert "state_key" in msg and "state_assignment" in msg
    assert "delete the checkpoint" in msg


def test_dispatch_ladder_and_board_fallback():
    from flipcomplexityempirical_tpu.lower import dispatch
    assert dispatch.DISPATCH_LADDER == ("lowered_bits", "lowered",
                                        "bitboard", "board",
                                        "general_dense", "general")
    assert dispatch.next_path("lowered_bits") == "lowered"
    assert dispatch.next_path("lowered") == "bitboard"
    assert dispatch.next_path("general_dense") == "general"
    assert dispatch.next_path("general") is None
    assert dispatch.next_path("pallas") is None
    # only the state-compatible lowered_bits -> lowered,
    # bitboard -> board and general_dense -> general hops stay
    # in-segment
    assert rz.next_board_body("lowered_bits") == "lowered"
    assert rz.next_board_body("bitboard") == "board"
    assert rz.next_board_body("lowered") is None
    assert rz.next_board_body("board") is None
    assert rz.next_general_path("general_dense") == "general"
    assert rz.next_general_path("general") is None
    assert rz.next_general_path("board") is None


# ---- supervisor over a stubbed driver ----------------------------------

class _Flaky:
    """A run_config stand-in failing ``fails`` times before succeeding."""

    def __init__(self, fails, exc=OSError("flaky")):
        self.fails = fails
        self.exc = exc
        self.calls = 0

    def __call__(self, cfg, outdir, checkpoint_dir=None, recorder=None):
        self.calls += 1
        if self.calls <= self.fails:
            raise self.exc
        return {"waits_sum": 1.0}


_FAST = dict(backoff_base_s=0.001, backoff_max_s=0.002)


def _cfg():
    return ex.ExperimentConfig(family="frank", alignment=0, base=0.3,
                               pop_tol=0.5, total_steps=10, n_chains=1)


def test_supervisor_retries_transient_then_succeeds(tmp_path,
                                                    monkeypatch):
    flaky = _Flaky(fails=2)
    monkeypatch.setattr(drv, "run_config", flaky)
    rep = rsup.run_supervised_sweep(
        [_cfg()], str(tmp_path), verbose=False,
        policy=rsup.RetryPolicy(max_retries=3, **_FAST))
    assert flaky.calls == 3
    assert rep.completed == [_cfg().tag] and rep.retried == 2
    assert rep.attempts[_cfg().tag] == 3 and rep.exit_code == 0


def test_supervisor_exhausts_retries_and_fails(tmp_path, monkeypatch):
    flaky = _Flaky(fails=99)
    monkeypatch.setattr(drv, "run_config", flaky)
    ev = str(tmp_path / "ev.jsonl")
    rec = obs.from_spec(ev)
    rep = rsup.run_supervised_sweep(
        [_cfg()], str(tmp_path), verbose=False, recorder=rec,
        policy=rsup.RetryPolicy(max_retries=2, **_FAST))
    rec.close()
    assert flaky.calls == 3 and rep.failed == [_cfg().tag]
    assert rep.exit_code == 2
    kinds = [e["event"] for e in _events(ev)]
    assert kinds.count("retry") == 2
    assert "config_failed" in kinds and "sweep_summary" in kinds


def test_supervisor_quarantines_deterministic_failures(tmp_path,
                                                       monkeypatch):
    flaky = _Flaky(fails=99, exc=ValueError("bad shape"))
    monkeypatch.setattr(drv, "run_config", flaky)
    ev = str(tmp_path / "ev.jsonl")
    rec = obs.from_spec(ev)
    rep = rsup.run_supervised_sweep(
        [_cfg()], str(tmp_path), verbose=False, recorder=rec,
        policy=rsup.RetryPolicy(max_retries=10, quarantine_after=2,
                                **_FAST))
    rec.close()
    # 2 deterministic failures -> quarantined, NOT 11 attempts
    assert flaky.calls == 2 and rep.quarantined == [_cfg().tag]
    assert rep.exit_code == 2
    events = _events(ev)
    q = [e for e in events if e["event"] == "config_quarantined"]
    assert q and q[0]["tag"] == _cfg().tag and q[0]["failures"] == 2
    summary = [e for e in events if e["event"] == "sweep_summary"][-1]
    assert summary["quarantined"] == 1


def test_supervisor_isolates_failures_between_configs(tmp_path,
                                                      monkeypatch):
    cfg_bad = _cfg()
    cfg_ok = ex.ExperimentConfig(family="frank", alignment=1, base=0.3,
                                 pop_tol=0.5, total_steps=10, n_chains=1)

    def run_config(cfg, outdir, checkpoint_dir=None, recorder=None):
        if cfg.tag == cfg_bad.tag:
            raise ValueError("poison config")
        return {"waits_sum": 2.0}

    monkeypatch.setattr(drv, "run_config", run_config)
    rep = rsup.run_supervised_sweep(
        [cfg_bad, cfg_ok], str(tmp_path), verbose=False,
        policy=rsup.RetryPolicy(quarantine_after=1, **_FAST))
    assert rep.quarantined == [cfg_bad.tag]
    assert rep.completed == [cfg_ok.tag]      # the sweep went on
    assert rep.exit_code == 2


def test_supervisor_sweep_stream_validates(tmp_path, monkeypatch):
    """The supervised sweep's full event stream (retry + backoff spans +
    sweep/config spans) passes the schema AND span-nesting gates."""
    flaky = _Flaky(fails=1)
    monkeypatch.setattr(drv, "run_config", flaky)
    ev = str(tmp_path / "ev.jsonl")
    rec = obs.from_spec(ev)
    rsup.run_supervised_sweep(
        [_cfg()], str(tmp_path), verbose=False, recorder=rec,
        heartbeat=str(tmp_path / "hb.json"),
        policy=rsup.RetryPolicy(**_FAST))
    rec.close()
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obs_report.py"),
         "--check", ev], capture_output=True, text=True)
    assert res.returncode == 0, res.stderr
    hb = json.load(open(tmp_path / "hb.json"))
    assert hb["status"] == "complete"


# ---- heartbeat is non-fatal --------------------------------------------

def test_heartbeat_write_failure_is_nonfatal(tmp_path, capsys):
    rfaults.install_from_spec("heartbeat.write:once")
    ev = str(tmp_path / "ev.jsonl")
    rec = obs.from_spec(ev)
    hb = str(tmp_path / "hb.json")
    drv.write_heartbeat(hb, recorder=rec, status="running")  # absorbed
    drv.write_heartbeat(hb, recorder=rec, status="running")  # lands
    rec.close()
    assert json.load(open(hb))["status"] == "running"
    errs = [e for e in _events(ev) if e["event"] == "heartbeat_error"]
    assert len(errs) == 1 and "InjectedFault" in errs[0]["message"]
    assert "continuing" in capsys.readouterr().err


def test_heartbeat_oserror_is_nonfatal(tmp_path, monkeypatch):
    monkeypatch.setattr(drv.os, "replace",
                        lambda *a: (_ for _ in ()).throw(OSError("full")))
    drv.write_heartbeat(str(tmp_path / "hb.json"), status="running")


# ---- checkpoint integrity ----------------------------------------------

def _ckpt_cfg(**over):
    kw = dict(family="frank", alignment=0, base=0.3, pop_tol=0.5,
              total_steps=60, n_chains=2, checkpoint_every=20)
    kw.update(over)
    return ex.ExperimentConfig(**kw)


@pytest.mark.slow
def test_checkpoint_manifest_and_rotation(tmp_path):
    ck = str(tmp_path / "ck")
    cfg = _ckpt_cfg()
    ex.run_config(cfg, str(tmp_path / "o"), checkpoint_dir=ck)
    man = json.load(open(os.path.join(ck, cfg.tag + ".manifest.json")))
    assert man["version"] == 1
    assert man["current"]["file"] == cfg.tag + ".npz"
    assert man["previous"]["file"] == cfg.tag + ".prev.npz"
    assert man["current"]["gen"] == man["previous"]["gen"] + 1
    # every manifest digest matches the bytes on disk
    for name, digest in [(man["current"]["file"],
                          man["current"]["sha256"]),
                         (man["previous"]["file"],
                          man["previous"]["sha256"])] + \
            sorted(man["parts"].items()):
        assert drv._sha256_file(os.path.join(ck, name)) == digest, name
    # keep-last-2: exactly the current + previous generations on disk
    mains = [f for f in os.listdir(ck) if f.endswith(".npz")
             and ".h" not in f]
    assert sorted(mains) == [cfg.tag + ".npz", cfg.tag + ".prev.npz"]


def test_corrupt_main_falls_back_to_previous_generation(tmp_path):
    ck = str(tmp_path / "ck")
    cfg = _ckpt_cfg()
    ex.run_config(cfg, str(tmp_path / "o"), checkpoint_dir=ck)
    main = os.path.join(ck, cfg.tag + ".npz")
    rfaults.truncate_file(main)
    loaded = ex.load_checkpoint(ck, cfg)
    # fell back to the previous generation (one 20-step segment earlier)
    assert loaded is not None and int(loaded["meta_done"]) == 40
    assert os.path.exists(os.path.join(ck, ".corrupt"))
    assert not os.path.exists(main)       # quarantined, not left behind
    # the fallback is now current; a second load needs no repair
    assert int(ex.load_checkpoint(ck, cfg)["meta_done"]) == 40


def test_corrupt_part_quarantines_generation(tmp_path):
    ck = str(tmp_path / "ck")
    cfg = _ckpt_cfg()
    ex.run_config(cfg, str(tmp_path / "o"), checkpoint_dir=ck)
    man = json.load(open(os.path.join(ck, cfg.tag + ".manifest.json")))
    # tear the newest history part (exclusive to the last generation)
    newest = sorted(man["parts"])[-1]
    rfaults.truncate_file(os.path.join(ck, newest))
    ev = str(tmp_path / "ev.jsonl")
    rec = obs.from_spec(ev)
    loaded = drv.load_checkpoint(ck, cfg, recorder=rec)
    rec.close()
    assert loaded is not None and int(loaded["meta_done"]) == 40
    corrupt = [e for e in _events(ev)
               if e["event"] == "checkpoint_corrupt"]
    assert corrupt and corrupt[0]["tag"] == cfg.tag
    assert "checksum" in corrupt[0]["reason"]
    quarantined = os.listdir(os.path.join(ck, ".corrupt"))
    assert any(newest in q for q in quarantined)


def test_both_generations_corrupt_means_fresh_start(tmp_path):
    ck = str(tmp_path / "ck")
    cfg = _ckpt_cfg()
    ex.run_config(cfg, str(tmp_path / "o"), checkpoint_dir=ck)
    rfaults.truncate_file(os.path.join(ck, cfg.tag + ".npz"))
    rfaults.truncate_file(os.path.join(ck, cfg.tag + ".prev.npz"))
    assert ex.load_checkpoint(ck, cfg) is None


def test_checkpoint_identity_error_on_foreign_state(tmp_path):
    """A checkpoint whose state fields do not match the template raises
    the typed error (naming both sides), no longer a bare KeyError."""
    ck = str(tmp_path / "ck")
    cfg = _ckpt_cfg(total_steps=40)
    ex.run_config(cfg, str(tmp_path / "o"), checkpoint_dir=ck)
    loaded = ex.load_checkpoint(ck, cfg)
    import dataclasses

    @dataclasses.dataclass
    class Fake:
        here: int = 0
        missing_field: int = 0
    with pytest.raises(rz.CheckpointIdentityError) as ei:
        drv._state_from_arrays(Fake(), loaded, tag=cfg.tag)
    assert "missing_field" in str(ei.value)
    assert cfg.tag in str(ei.value)


# ---- chaos: injected faults leave bit-identical sweeps -----------------

_CHAOS_SPEC = ("checkpoint.write:once,checkpoint.write:truncate@3,"
               "segment.step:once@4,seed=7")


def _history_equal(a, b):
    for k in a["history"]:
        np.testing.assert_array_equal(a["history"][k], b["history"][k],
                                      err_msg=k)


@pytest.mark.slow
def test_chaos_sweep_recovers_bit_identically_lowered(tmp_path):
    """The acceptance scenario on the lowered fast path: one checkpoint
    write failure, one torn checkpoint part, one segment failure — the
    supervised sweep completes and every artifact is byte-identical to
    the fault-free run (checksum fallback replays from generation 1)."""
    cfg = _ckpt_cfg()
    clean = ex.run_config(cfg, str(tmp_path / "clean"))

    rfaults.install_from_spec(_CHAOS_SPEC)
    ev = str(tmp_path / "ev.jsonl")
    rec = obs.from_spec(ev)
    rep = rsup.run_supervised_sweep(
        [cfg], str(tmp_path / "fault"),
        checkpoint_dir=str(tmp_path / "ck"), verbose=False,
        recorder=rec, policy=rsup.RetryPolicy(seed=7, **_FAST))
    rec.close()
    plan = rfaults.active_plan()
    assert [f[:2] for f in plan.log] == [
        ("checkpoint.write", "fail"), ("checkpoint.write", "truncate"),
        ("segment.step", "fail")]
    assert rep.completed == [cfg.tag] and rep.retried == 2
    assert rep.exit_code == 0

    _history_equal(clean, rep.results[0][1])
    for kind in ex.ARTIFACT_KINDS:
        a = open(os.path.join(tmp_path, "clean", cfg.tag + kind),
                 "rb").read()
        b = open(os.path.join(tmp_path, "fault", cfg.tag + kind),
                 "rb").read()
        assert a == b, kind
    kinds = [e["event"] for e in _events(ev)]
    assert kinds.count("retry") == 2
    assert "checkpoint_corrupt" in kinds


def test_chaos_sweep_recovers_bit_identically_general(tmp_path):
    """The same fault set on the general gather path (hex lattice is
    rejected by the board family), exercising the general runner's
    segment resume under injected faults."""
    cfg = ex.ExperimentConfig(family="hex", alignment=1, base=0.3,
                              pop_tol=0.1, lattice_m=6, lattice_n=10,
                              total_steps=60, n_chains=2,
                              checkpoint_every=20)
    clean = ex.run_config(cfg, str(tmp_path / "clean"))

    rfaults.install_from_spec(_CHAOS_SPEC)
    rep = rsup.run_supervised_sweep(
        [cfg], str(tmp_path / "fault"),
        checkpoint_dir=str(tmp_path / "ck"), verbose=False,
        policy=rsup.RetryPolicy(seed=7, **_FAST))
    assert rep.completed == [cfg.tag] and rep.exit_code == 0
    _history_equal(clean, rep.results[0][1])
    from flipcomplexityempirical_tpu.experiments.artifacts import (
        artifact_kinds)
    for kind in artifact_kinds("hex"):
        a = open(os.path.join(tmp_path, "clean", cfg.tag + kind),
                 "rb").read()
        b = open(os.path.join(tmp_path, "fault", cfg.tag + kind),
                 "rb").read()
        assert a == b, kind


def test_poison_config_quarantined_with_nonzero_exit(tmp_path):
    """segment.step:always is deterministic poison: quarantine after
    quarantine_after attempts, exit code 2, sweep keeps going."""
    cfg = _ckpt_cfg()
    rfaults.install_from_spec("segment.step:always")
    ev = str(tmp_path / "ev.jsonl")
    rec = obs.from_spec(ev)
    rep = rsup.run_supervised_sweep(
        [cfg], str(tmp_path), verbose=False, recorder=rec,
        policy=rsup.RetryPolicy(quarantine_after=2, **_FAST))
    rec.close()
    assert rep.quarantined == [cfg.tag] and rep.exit_code == 2
    kinds = [e["event"] for e in _events(ev)]
    assert "config_quarantined" in kinds


# ---- graceful kernel degradation ---------------------------------------

def test_compile_fault_degrades_to_general(tmp_path):
    """A persistent kernel failure walks the WHOLE ladder: the packed
    lowered_bits body falls in-segment to the int8 lowered body, which
    then hands the config to the general rerun; there the dense rung
    faults once more and falls in-segment to the legacy general kernel
    — the ladder's fault-free terminal floor — completing with three
    kernel_path_degraded events instead of crashing."""
    cfg = _ckpt_cfg(total_steps=40, checkpoint_every=0)
    rfaults.install_from_spec("compile:always")
    ev = str(tmp_path / "ev.jsonl")
    rec = obs.from_spec(ev)
    mark = len(rz.DEGRADATIONS)
    data = ex.run_config(cfg, str(tmp_path / "o"), recorder=rec)
    rec.close()
    assert data["history"]["cut_count"].shape == (2, 40)
    deg = [e for e in _events(ev) if e["event"] == "kernel_path_degraded"]
    assert [(d["from_path"], d["to_path"]) for d in deg] == [
        ("lowered_bits", "lowered"), ("lowered", "general"),
        ("general_dense", "general")]
    assert len(rz.DEGRADATIONS) > mark   # audit trail for bench records


def test_compile_fault_once_degrades_in_segment(tmp_path):
    """A transient kernel failure on the packed lowered body retries
    the SAME segment on the int8 lowered body (shared BoardState — no
    general rerun, no state conversion) and the run completes with
    exactly one kernel_path_degraded event."""
    cfg = _ckpt_cfg(total_steps=40, checkpoint_every=0)
    rfaults.install_from_spec("compile:once")
    ev = str(tmp_path / "ev.jsonl")
    rec = obs.from_spec(ev)
    data = ex.run_config(cfg, str(tmp_path / "o"), recorder=rec)
    rec.close()
    assert data["history"]["cut_count"].shape == (2, 40)
    deg = [e for e in _events(ev) if e["event"] == "kernel_path_degraded"]
    assert [(d["from_path"], d["to_path"]) for d in deg] == [
        ("lowered_bits", "lowered")]


def test_bench_compare_refuses_degraded_records(tmp_path):
    from tools import bench_compare
    rec_ok = {"metrics": {"flips_per_sec": 100.0}, "device": "cpu"}
    rec_deg = {"metrics": {"flips_per_sec": 50.0}, "device": "cpu",
               "degraded": True,
               "degradations": [{"from_path": "bitboard",
                                 "to_path": "board", "reason": "x"}]}
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(rec_ok))
    b.write_text(json.dumps(rec_deg))
    assert bench_compare.record_degraded(rec_deg)
    assert not bench_compare.record_degraded(rec_ok)
    # a 50% drop would gate... but the degraded record is refused
    assert bench_compare.main([str(a), str(b),
                               "--tolerance", "0.05"]) == 0


# ---- the CI chaos gate --------------------------------------------------

@pytest.mark.slow
def test_chaos_check_gate_passes():
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHON=sys.executable)
    res = subprocess.run(
        ["bash", os.path.join(REPO, "tools", "chaos_check.sh")],
        capture_output=True, text=True, env=env, timeout=900)
    assert res.returncode == 0, (res.stdout + "\n" + res.stderr)
