"""Tracing + metrics subsystem (obs/trace.py, obs/metrics.py) and its
tooling (tools/trace_export.py, obs_report Timing section, the
obs-check CI gate): span pairing/nesting over real runner streams,
Chrome trace-event export structure, histogram percentiles, gzip
sinks, and the NullRecorder zero-span contract."""

import gzip
import json
import os
import subprocess
import sys

import pytest

import flipcomplexityempirical_tpu as fce
from flipcomplexityempirical_tpu import obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPORT = os.path.join(REPO, "tools", "obs_report.py")
EXPORT = os.path.join(REPO, "tools", "trace_export.py")
SMOKE = os.path.join(REPO, "tests", "fixtures", "obs",
                     "events_smoke.jsonl")


def read_events(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt", encoding="utf-8") as f:
        return [json.loads(line) for line in f if line.strip()]


class _Cap:
    """Truthy in-memory recorder capturing emitted events."""

    diag_hook = anomaly_hook = metrics_hook = None

    def __init__(self):
        self.events = []

    def emit(self, event, ts=None, **fields):
        e = {"event": event, "ts": 0.0 if ts is None else ts, **fields}
        self.events.append(e)
        return e


# ----------------------------------------------------------- span basics


def test_span_context_manager_pairs_and_nests():
    rec = _Cap()
    with obs.span(rec, "outer", tag="t1"):
        with obs.span(rec, "inner"):
            pass
    kinds = [(e["event"], e["name"]) for e in rec.events]
    assert kinds == [("span_begin", "outer"), ("span_begin", "inner"),
                     ("span_end", "inner"), ("span_end", "outer")]
    outer_b, inner_b, inner_e, outer_e = rec.events
    assert outer_b["parent_id"] is None
    assert inner_b["parent_id"] == outer_b["span_id"]
    assert inner_b["trace_id"] == outer_b["trace_id"]
    assert outer_b["tag"] == "t1"
    assert inner_e["dur_s"] >= 0.0 and outer_e["dur_s"] >= inner_e["dur_s"]
    assert obs.validate_spans(rec.events) == []


def test_span_explicit_begin_end_args():
    rec = _Cap()
    sp = obs.span(rec, "run:x", kernel_path="board").begin()
    sp.end(flips=100, wall_s=0.5)
    b, e = rec.events
    assert b["kernel_path"] == "board"
    assert e["flips"] == 100 and e["wall_s"] == 0.5
    # single-use: a second end is a no-op, not a duplicate emission
    sp.end()
    assert len(rec.events) == 2


def test_span_error_exit_tags_end():
    rec = _Cap()
    with pytest.raises(RuntimeError):
        with obs.span(rec, "boom"):
            raise RuntimeError("x")
    assert rec.events[-1]["event"] == "span_end"
    assert rec.events[-1]["error"] == "RuntimeError"
    assert obs.validate_spans(rec.events) == []


def test_null_recorder_emits_zero_spans():
    """The hot-path contract: with no recorder, span() hands back a
    falsy shared no-op and nothing is emitted anywhere."""
    sp = obs.span(None, "anything")
    assert not sp
    assert sp is obs.span(obs.NULL, "other")  # shared singleton
    with sp:
        pass
    sp.begin().end()
    sp.set_args(x=1)


def test_traced_decorator():
    rec = _Cap()

    @obs.traced("work", flavor="unit")
    def f(x):
        return x + 1

    assert f(5) == 6  # default recorder is NULL: pure passthrough
    assert rec.events == []
    prev = obs.set_default_recorder(rec)
    try:
        assert f(1) == 2  # resolved at call time, not decoration time
    finally:
        obs.set_default_recorder(prev)
    names = [(e["event"], e["name"]) for e in rec.events]
    assert names == [("span_begin", "work"), ("span_end", "work")]
    assert rec.events[0]["flavor"] == "unit"

    @obs.traced
    def bare():
        return 7

    assert bare() == 7  # bare form: qualname label, passthrough on NULL


def test_emit_span_at_backstamps_and_parents():
    rec = _Cap()
    with obs.span(rec, "run"):
        obs.emit_span_at(rec, "chunk", 100.0, 0.25, kernel_path="board",
                         end_args={"reject": {"proposals": 10}})
    run_b = rec.events[0]
    chunk_b = next(e for e in rec.events
                   if e["event"] == "span_begin" and e["name"] == "chunk")
    chunk_e = next(e for e in rec.events
                   if e["event"] == "span_end" and e["name"] == "chunk")
    assert chunk_b["ts"] == 100.0 and chunk_e["ts"] == 100.25
    assert chunk_e["dur_s"] == 0.25
    assert chunk_b["parent_id"] == run_b["span_id"]  # stack top = run
    assert chunk_b["kernel_path"] == "board"
    assert chunk_e["reject"] == {"proposals": 10}
    assert obs.validate_spans(rec.events) == []


# ---------------------------------------------------- validate_spans gate


def _sb(sid, name, parent=None):
    return {"event": "span_begin", "span_id": sid, "name": name,
            "parent_id": parent, "trace_id": "t", "ts": 0.0}


def _se(sid, name):
    return {"event": "span_end", "span_id": sid, "name": name,
            "trace_id": "t", "ts": 1.0, "dur_s": 1.0}


def test_validate_spans_failure_modes():
    assert obs.validate_spans([_sb(1, "a"), _se(1, "a")]) == []
    # never closed
    assert any("never closed" in m
               for m in obs.validate_spans([_sb(1, "a")]))
    # end without begin
    assert any("no open begin" in m
               for m in obs.validate_spans([_se(9, "a")]))
    # id reuse
    errs = obs.validate_spans(
        [_sb(1, "a"), _se(1, "a"), _sb(1, "b"), _se(1, "b")])
    assert any("reuses" in m for m in errs)
    # orphan parent
    assert any("not open" in m
               for m in obs.validate_spans(
                   [_sb(2, "kid", parent=7), _se(2, "kid")]))
    # name mismatch
    assert any("!=" in m
               for m in obs.validate_spans([_sb(1, "a"), _se(1, "b")]))
    # parent closes while child open
    errs = obs.validate_spans(
        [_sb(1, "p"), _sb(2, "c", parent=1), _se(1, "p"), _se(2, "c")])
    assert any("still open" in m for m in errs)


# ------------------------------------------------------- metrics registry


def test_histogram_percentiles():
    h = obs.Histogram()
    for v in [1.0] * 50 + [10.0] * 45 + [100.0] * 5:
        h.observe(v)
    s = h.snapshot()
    assert s["count"] == 100 and s["min"] == 1.0 and s["max"] == 100.0
    assert s["p50"] <= s["p95"] <= s["p99"] <= s["max"]
    assert s["p50"] < 10.0 * 1.5   # the p50 lands in the low buckets
    assert s["p99"] > 10.0         # the p99 sees the tail
    assert abs(s["mean"] - (50 + 450 + 500) / 100) < 1e-9


def test_histogram_clamps_and_empty():
    h = obs.Histogram()
    assert h.snapshot()["count"] == 0
    assert h.percentile(0.5) is None
    h.observe(5.0)
    s = h.snapshot()
    assert s["p50"] == 5.0 == s["p99"]  # clamped into [min, max]


def test_metrics_registry_snapshot_and_emit():
    met = obs.MetricsRegistry()
    met.inc("chunks")
    met.inc("flips", 100)
    met.set("done", 50)
    met.observe("chunk_wall_s", 0.1)
    met.observe("chunk_wall_s", 0.3)
    snap = met.snapshot()
    assert snap["counters"] == {"chunks": 1, "flips": 100}
    assert snap["gauges"] == {"done": 50}
    assert snap["histograms"]["chunk_wall_s"]["count"] == 2
    rec = _Cap()
    met.emit_snapshot(rec, runner="general")
    e = rec.events[-1]
    assert e["event"] == "metrics_snapshot" and e["runner"] == "general"
    assert e["histograms"]["chunk_wall_s"]["count"] == 2
    # notify drives the metrics_hook (heartbeat wiring), tolerantly
    seen = []
    rec.metrics_hook = lambda s: seen.append(s)
    met.notify(rec)
    assert seen and seen[0]["counters"]["chunks"] == 1
    rec.metrics_hook = lambda s: 1 / 0
    met.notify(rec)  # hook failure must not propagate


# -------------------------------------------- real runner span streams


def _grid_setup(n=8):
    g = fce.graphs.square_grid(n, n)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(contiguity="patch")
    return g, plan, spec


def test_run_chains_span_stream(tmp_path):
    """Acceptance: a real general-path run emits a span stream with
    matched begin/end, correct parent nesting, chunk spans tagged with
    kernel_path, and a metrics_snapshot embedded in run_end."""
    g, plan, spec = _grid_setup()
    dg, st, params = fce.init_batch(g, plan, n_chains=4, seed=0,
                                    spec=spec, base=1.3, pop_tol=0.4)
    path = str(tmp_path / "run.jsonl")
    with obs.Recorder(path=path) as rec:
        fce.run_chains(dg, spec, params, st, n_steps=101, chunk=25,
                       recorder=rec)
    events = read_events(path)
    assert obs.validate_spans(events) == []
    begins = [e for e in events if e["event"] == "span_begin"]
    ends = [e for e in events if e["event"] == "span_end"]
    assert len(begins) == len(ends) > 0
    # an 8x8 rook grid off the board path auto-resolves the rejection-
    # free dense rung — the span stream must carry the REAL path tag
    run_b = next(b for b in begins if b["name"] == "run:general_dense")
    assert run_b["kernel_path"] == "general_dense" and run_b["chains"] == 4
    chunk_bs = [b for b in begins if b["name"] == "chunk"]
    assert len(chunk_bs) == 4  # one per executed chunk
    for b in chunk_bs:
        assert b["kernel_path"] == "general_dense"
        assert b["parent_id"] == run_b["span_id"]
    run_e = next(e for e in ends if e["name"] == "run:general_dense")
    assert run_e["flips"] > 0 and run_e["wall_s"] > 0
    chunk_es = [e for e in ends if e["name"] == "chunk"]
    assert all("reject" in e and e["wall_s"] > 0 for e in chunk_es)
    snaps = [e for e in events if e["event"] == "metrics_snapshot"]
    assert len(snaps) == 1
    hists = snaps[0]["histograms"]
    assert hists["chunk_wall_s"]["count"] == 4
    assert hists["flips_per_s"]["p50"] is not None
    end = next(e for e in events if e["event"] == "run_end")
    assert end["metrics"]["counters"]["chunks"] == 4


def test_run_board_span_stream_backstamped(tmp_path):
    """Board fast path: chunk spans are deferred (emitted at the run-end
    flush, back-stamped over the dispatch interval) yet still pair, nest
    under the run span, and carry the kernel path tag."""
    g, plan, spec = _grid_setup()
    bg, st, params = fce.sampling.init_board(
        g, plan, n_chains=4, seed=0, spec=spec, base=1.3, pop_tol=0.4)
    path = str(tmp_path / "board.jsonl")
    with obs.Recorder(path=path) as rec:
        fce.sampling.run_board(bg, spec, params, st, n_steps=101,
                               chunk=25, recorder=rec)
    events = read_events(path)
    assert obs.validate_spans(events) == []
    begins = [e for e in events if e["event"] == "span_begin"]
    run_b = next(b for b in begins if b["name"] == "run:board")
    chunk_bs = [b for b in begins if b["name"] == "chunk"]
    assert len(chunk_bs) == 4
    for b in chunk_bs:
        assert b["parent_id"] == run_b["span_id"]
        assert b["kernel_path"] == run_b["kernel_path"]
    # back-stamped: chunk begins carry timestamps before their emission
    # point (the run_end flush), i.e. before the run span's end ts
    run_e_ts = next(e["ts"] for e in events
                    if e["event"] == "span_end"
                    and e["name"] == "run:board")
    assert all(b["ts"] <= run_e_ts for b in chunk_bs)
    assert any(b["name"] == "finalize" for b in begins)


def test_run_tempered_span_stream(tmp_path):
    g, plan, spec = _grid_setup(6)
    handle, st, params = fce.sampling.init_tempered(
        g, plan, betas=(1.0, 0.5), n_ladders=2, seed=0, spec=spec,
        base=1.3, pop_tol=0.4)
    path = str(tmp_path / "t.jsonl")
    with obs.Recorder(path=path) as rec:
        fce.sampling.run_tempered(handle, spec, params, st, n_steps=41,
                                  betas=(1.0, 0.5), n_ladders=2,
                                  swap_every=10, recorder=rec)
    events = read_events(path)
    assert obs.validate_spans(events) == []
    begins = [e for e in events if e["event"] == "span_begin"]
    run_b = next(b for b in begins if b["name"] == "run:tempered")
    assert [b["round"] for b in begins if b["name"] == "chunk"] \
        == [0, 1, 2, 3]
    swaps = [b for b in begins if b["name"] == "swap_round"]
    assert len(swaps) == 3  # no swap follows the final round
    assert all(b["parent_id"] == run_b["span_id"] for b in swaps)


def test_null_recorder_run_emits_nothing(tmp_path):
    """recorder=None through a full run: zero events anywhere (the
    existing parity test proves the walk is identical; this one proves
    the tracing layer adds no stream side channel)."""
    g, plan, spec = _grid_setup(6)
    dg, st, params = fce.init_batch(g, plan, n_chains=2, seed=0,
                                    spec=spec, base=1.3, pop_tol=0.4)
    prev = obs.set_default_recorder(obs.NULL)
    try:
        fce.run_chains(dg, spec, params, st, n_steps=26, chunk=25,
                       recorder=None)
    finally:
        obs.set_default_recorder(prev)
    assert obs.NULL.n_emitted == 0


# --------------------------------------------------- gzip + per-host I/O


def test_recorder_gzip_sink_roundtrip(tmp_path):
    path = str(tmp_path / "ev.jsonl.gz")
    with obs.Recorder(path=path) as rec:
        with obs.span(rec, "outer"):
            rec.emit("error", message="inside")
    events = read_events(path)
    assert [e["event"] for e in events] == ["span_begin", "error",
                                            "span_end"]
    assert obs.validate_spans(events) == []
    # both tools read the gzip sink directly
    r = subprocess.run([sys.executable, REPORT, "--check", path],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    r = subprocess.run([sys.executable, EXPORT, "--validate", path],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr


def test_per_host_path_rewriting():
    assert obs.per_host_path("ev.jsonl", index=3) == "ev.host3.jsonl"
    assert obs.per_host_path("ev.jsonl.gz", index=1) == \
        "ev.host1.jsonl.gz"
    assert obs.per_host_path("/a/b/events", index=0) == \
        "/a/b/events.host0"


# ------------------------------------------------------ tools: export


def test_trace_export_smoke_fixture(tmp_path):
    """Acceptance: the fixture stream converts to structurally valid
    Chrome trace-event JSON — matched X slices, children contained in
    their parents, chunk slices tagged with kernel_path."""
    out = str(tmp_path / "t.trace.json")
    r = subprocess.run([sys.executable, EXPORT, SMOKE, "-o", out],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    with open(out) as f:
        doc = json.load(f)
    assert set(doc) == {"traceEvents", "displayTimeUnit"}
    evs = doc["traceEvents"]
    xs = [e for e in evs if e["ph"] == "X"]
    assert len(xs) == 10  # one slice per span pair in the fixture
    for e in xs:
        assert e["dur"] >= 0 and e["ts"] >= 0
        assert {"name", "pid", "tid", "args"} <= set(e)
    by_name = {e["name"]: e for e in xs if e["name"] != "chunk"}
    sweep = by_name["sweep"]
    run = by_name["run:board"]
    s0, s1 = sweep["ts"], sweep["ts"] + sweep["dur"]
    assert s0 <= run["ts"] and run["ts"] + run["dur"] <= s1
    for c in (e for e in xs if e["name"] == "chunk"):
        assert c["args"]["kernel_path"] == "lowered"
        assert run["ts"] <= c["ts"]
        assert c["ts"] + c["dur"] <= run["ts"] + run["dur"] + 1e-6
    # markers and counters came through
    assert any(e["ph"] == "i" and "anomaly" in e["name"] for e in evs)
    assert any(e["ph"] == "C" for e in evs)


def test_trace_export_real_run_roundtrip(tmp_path):
    """sec11-style acceptance path: record a real run with --events,
    export, and get a valid nested trace."""
    g, plan, spec = _grid_setup(6)
    dg, st, params = fce.init_batch(g, plan, n_chains=2, seed=0,
                                    spec=spec, base=1.3, pop_tol=0.4)
    path = str(tmp_path / "real.jsonl")
    with obs.Recorder(path=path) as rec:
        fce.run_chains(dg, spec, params, st, n_steps=51, chunk=25,
                       recorder=rec)
    r = subprocess.run([sys.executable, EXPORT, "--validate", path],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    out = str(tmp_path / "real.trace.json")
    r = subprocess.run([sys.executable, EXPORT, path, "-o", out],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    with open(out) as f:
        doc = json.load(f)
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in xs} >= {"run:general_dense", "chunk"}


def test_trace_export_validate_rejects_broken_spans(tmp_path):
    path = str(tmp_path / "bad.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"v": 1, "ts": 1.0, "event": "span_begin",
                            "name": "a", "span_id": 1, "trace_id": "t",
                            "parent_id": None}) + "\n")
    r = subprocess.run([sys.executable, EXPORT, "--validate", path],
                       capture_output=True, text=True)
    assert r.returncode == 1
    assert "never closed" in r.stderr


def test_trace_export_merges_hosts(tmp_path):
    """Per-host files land under distinct pids parsed from the host<K>
    filename convention."""
    for k in (0, 1):
        p = str(tmp_path / f"ev.host{k}.jsonl")
        with obs.Recorder(path=p) as rec:
            with obs.span(rec, "run:board", kernel_path="board"):
                pass
    out = str(tmp_path / "merged.trace.json")
    r = subprocess.run(
        [sys.executable, EXPORT, str(tmp_path / "ev.host0.jsonl"),
         str(tmp_path / "ev.host1.jsonl"), "-o", out],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    with open(out) as f:
        doc = json.load(f)
    pids = {e["pid"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert pids == {0, 1}
    names = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert {n["pid"] for n in names} == {0, 1}


# ------------------------------------------------- tools: report + gate


def test_obs_report_timing_section(tmp_path):
    """Acceptance: the report over a span-bearing stream prints the
    Timing section with per-phase totals and p50/p95/p99 chunk
    latency."""
    r = subprocess.run([sys.executable, REPORT, SMOKE],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "## Timing" in r.stdout
    assert "Per-phase breakdown" in r.stdout
    assert "Slowest spans" in r.stdout
    assert "Histogram percentiles" in r.stdout
    assert "chunk_wall_s" in r.stdout
    assert "| run | runner | metric | count | p50 | p95 | p99 |" \
        in r.stdout


def test_obs_report_check_gates_span_nesting(tmp_path):
    path = str(tmp_path / "orphan.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"v": 1, "ts": 1.0, "event": "span_begin",
                            "name": "kid", "span_id": 2, "trace_id": "t",
                            "parent_id": 99}) + "\n")
        f.write(json.dumps({"v": 1, "ts": 2.0, "event": "span_end",
                            "name": "kid", "span_id": 2, "trace_id": "t",
                            "dur_s": 1.0}) + "\n")
    r = subprocess.run([sys.executable, REPORT, "--check", path],
                       capture_output=True, text=True)
    assert r.returncode == 1
    assert "span" in r.stderr and "not open" in r.stderr


def test_ci_obs_gate_passes():
    """make obs-check: graftlint + schema/span gate + export validation
    over the committed fixture stream, as one script."""
    r = subprocess.run(["bash", os.path.join(REPO, "tools", "ci_obs.sh")],
                       capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "obs-check: OK" in r.stdout


@pytest.mark.slow
def test_mesh_check_gate_passes():
    """make mesh-check: graftlint + the committed two-host fixture
    streams merging through trace_export + a live 2-device forced-host
    bench --mesh smoke, as one script. Slow tier: the smoke pays a
    fresh JAX import + compile in a subprocess."""
    r = subprocess.run(["bash",
                        os.path.join(REPO, "tools", "mesh_check.sh")],
                       capture_output=True, text=True, cwd=REPO,
                       timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "mesh-check: OK" in r.stdout
    assert "bench record OK" in r.stdout
