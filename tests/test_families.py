"""End-to-end tests for the widened experiment families (BASELINE.json
configs 2-5): each family must run from ExperimentConfig through
run_config to its full artifact manifest, with family-specific invariants
checked on the outputs — and the driver must dispatch the board fast path
exactly when kernel.board.supports holds."""

import json
import os

import networkx as nx
import numpy as np
import pytest

import flipcomplexityempirical_tpu.experiments as ex
from flipcomplexityempirical_tpu.experiments import driver as drv
from flipcomplexityempirical_tpu.experiments.artifacts import artifact_kinds


def _assert_artifacts(cfg, outdir):
    for kind in artifact_kinds(cfg.family):
        assert os.path.exists(os.path.join(outdir, cfg.tag + kind)), kind
    assert ex.is_done(cfg, outdir)


def _districts_connected(g, assignment, k):
    gx = nx.Graph(list(map(tuple, np.asarray(g.edges))))
    for d in range(k):
        nodes = np.nonzero(np.asarray(assignment) == d)[0].tolist()
        assert nodes, f"district {d} empty"
        assert nx.is_connected(gx.subgraph(nodes))


@pytest.mark.slow
def test_kpair_family_end_to_end(tmp_path):
    """k-district pair walk on the plain grid: board fast path, k=4."""
    cfg = ex.ExperimentConfig(family="kpair", alignment=0, base=0.8,
                              pop_tol=0.5, n_districts=4, grid=12,
                              total_steps=300, n_chains=3)
    out = str(tmp_path)
    data = ex.run_config(cfg, out)
    _assert_artifacts(cfg, out)
    g, plan, _ = drv.build_graph_and_plan(cfg)
    assert sorted(set(data["end_signed"].tolist())) <= [0, 1, 2, 3]
    for c in range(cfg.n_chains):
        _districts_connected(g, data["assignments"][c], 4)
    # wait.txt carries the literal n**k - 1 denominator's scale
    with open(os.path.join(out, cfg.tag + "wait.txt")) as f:
        assert int(f.read()) > 0


@pytest.mark.parametrize(
    "family", [pytest.param("tri", marks=pytest.mark.slow), "hex"])
def test_lattice_families_end_to_end(tmp_path, family):
    cfg = ex.ExperimentConfig(family=family, alignment=1, base=0.3,
                              pop_tol=0.1, lattice_m=6, lattice_n=10,
                              total_steps=300, n_chains=3)
    out = str(tmp_path)
    data = ex.run_config(cfg, out)
    _assert_artifacts(cfg, out)
    g, plan, _ = drv.build_graph_and_plan(cfg)
    for c in range(cfg.n_chains):
        _districts_connected(g, data["assignments"][c], 2)
    assert np.isfinite(data["waits_sum"])
    assert "partisan" in data


def test_dual_family_end_to_end(tmp_path):
    """Synthetic-precinct dual graph: k-district pair walk, boundary-
    length Metropolis, Polsby-Popper in the summary."""
    cfg = ex.ExperimentConfig(family="dual", alignment=0, base=2.6,
                              pop_tol=0.25, n_districts=4, dual_nx=8,
                              dual_ny=8, total_steps=300, n_chains=3)
    out = str(tmp_path)
    data = ex.run_config(cfg, out)
    _assert_artifacts(cfg, out)
    g, plan, geo = drv.build_graph_and_plan(cfg)
    for c in range(cfg.n_chains):
        _districts_connected(g, data["assignments"][c], 4)
    pp = data["polsby_popper"]
    assert pp.shape == (cfg.n_chains, 4)
    assert np.isfinite(pp).all() and (pp > 0).all() and (pp <= 1).all()
    with open(os.path.join(out, cfg.tag + "compactness.json")) as f:
        js = json.load(f)
    assert len(js["polsby_popper_per_chain_mean"]) == cfg.n_chains
    # population bounds hold at the end (weighted-cut chain stays valid)
    ideal = g.pop.sum() / 4
    for c in range(cfg.n_chains):
        a = data["assignments"][c]
        for d in range(4):
            pd = g.pop[a == d].sum()
            assert (1 - 0.25) * ideal - 1e-6 <= pd \
                <= (1 + 0.25) * ideal + 1e-6


@pytest.mark.slow
def test_temper_family_end_to_end(tmp_path):
    cfg = ex.ExperimentConfig(family="temper", alignment=0, base=1 / .3,
                              pop_tol=0.1, betas=(1.0, 0.6, 0.3),
                              swap_every=50, total_steps=400, n_chains=4)
    out = str(tmp_path)
    data = ex.run_config(cfg, out)
    _assert_artifacts(cfg, out)
    st = data["swapstats"]
    assert st["attempts"][0] > 0
    assert data["rung_cut"].shape == (3, 400)
    # the batch is n_chains ladders x 3 rungs; the reported plans are the
    # one PHYSICAL (cold) chain per ladder
    assert data["state"].assignment.shape[0] == 4 * 3
    assert data["assignments"].shape[0] == 4
    with open(os.path.join(out, cfg.tag + "swapstats.json")) as f:
        assert json.load(f)["betas"] == [1.0, 0.6, 0.3]


def test_driver_dispatches_board_fast_path(monkeypatch):
    """_run_jax must route through init_board exactly when
    board.supports holds — since the stencil-lowering rework that
    includes frank's surgical seam grid (lowered body), not just kpair's
    plain grid. Both init spies abort after recording, so this is a pure
    ROUTING test — no chain runs, no artifacts render (the families'
    end-to-end behavior is covered by the other tests in this file,
    which is what kept this one pinned at the fast-tier budget when it
    ran two full configs)."""
    class _Routed(Exception):
        pass

    monkeypatch.setattr(
        drv, "init_board",
        lambda *a, **kw: (_ for _ in ()).throw(_Routed("board")))
    monkeypatch.setattr(
        drv, "init_batch",
        lambda *a, **kw: (_ for _ in ()).throw(_Routed("general")))

    def route_of(cfg):
        try:
            g, plan, _ = drv.build_graph_and_plan(cfg)
            drv._run_jax(cfg, g, plan)
        except _Routed as e:
            return str(e)
        raise AssertionError("neither init path was reached")

    cfg = ex.ExperimentConfig(family="kpair", alignment=0, base=0.8,
                              pop_tol=0.5, n_districts=2, grid=8,
                              total_steps=120, n_chains=2)
    assert route_of(cfg) == "board", \
        "kpair config did not take the board fast path"

    cfg2 = ex.ExperimentConfig(family="frank", alignment=0, base=0.3,
                               pop_tol=0.5, total_steps=120, n_chains=2)
    assert route_of(cfg2) == "board", \
        "frank's surgical seam grid must lower onto the board fast path"


def test_temper_family_checkpoint_resume_bit_identical(tmp_path):
    """The temper family checkpoints whole swap rounds and resumes
    bit-exactly: ladder betas, swap key/parity, pair statistics, and the
    per-round beta assignment all survive the crash."""
    kw = dict(family="temper", alignment=0, base=1 / .3, pop_tol=0.1,
              betas=(1.0, 0.6, 0.3), swap_every=40, total_steps=241,
              n_chains=2)
    clean = ex.run_config(ex.ExperimentConfig(**kw), str(tmp_path / "a"))

    cfg = ex.ExperimentConfig(**kw, checkpoint_every=80)
    ck = str(tmp_path / "ck")
    g, plan, _ = drv.build_graph_and_plan(cfg)
    with pytest.raises(drv._SegmentStop):
        drv._run_temper(cfg, g, plan, checkpoint_dir=ck,
                        _stop_after_segments=1)
    assert int(ex.load_checkpoint(ck, cfg)["meta_done"]) == 80
    resumed = ex.run_config(cfg, str(tmp_path / "b"), checkpoint_dir=ck)

    for k in clean["history"]:
        np.testing.assert_array_equal(clean["history"][k],
                                      resumed["history"][k], err_msg=k)
    np.testing.assert_array_equal(clean["assignments"],
                                  resumed["assignments"])
    np.testing.assert_array_equal(clean["rung_cut"], resumed["rung_cut"])
    assert clean["swapstats"] == resumed["swapstats"]
    np.testing.assert_allclose(clean["waits_all"], resumed["waits_all"],
                               rtol=2e-6)
    np.testing.assert_array_equal(clean["part_sum"], resumed["part_sum"])


def test_board_family_checkpoint_resume_bit_identical(tmp_path):
    """The board-path driver route checkpoints and resumes bit-exactly,
    like the general path (test_experiments.py's mid-config test)."""
    kw = dict(family="kpair", alignment=0, base=0.8, pop_tol=0.5,
              n_districts=4, grid=10, total_steps=241, n_chains=2)
    clean = ex.run_config(ex.ExperimentConfig(**kw), str(tmp_path / "a"))

    cfg = ex.ExperimentConfig(**kw, checkpoint_every=80)
    ck = str(tmp_path / "ck")
    g, plan, _ = drv.build_graph_and_plan(cfg)
    with pytest.raises(drv._SegmentStop):
        drv._run_jax(cfg, g, plan, checkpoint_dir=ck,
                     _stop_after_segments=1)
    assert int(ex.load_checkpoint(ck, cfg)["meta_done"]) == 80
    resumed = ex.run_config(cfg, str(tmp_path / "b"), checkpoint_dir=ck)

    for k in clean["history"]:
        np.testing.assert_array_equal(clean["history"][k],
                                      resumed["history"][k], err_msg=k)
    np.testing.assert_array_equal(clean["assignments"],
                                  resumed["assignments"])
    # waits accumulate on device in f32 per chunk (drained to f64 on
    # host), so different segment boundaries legitimately regroup the
    # f32 partial sums; the per-step "wait" HISTORY above is bit-equal
    np.testing.assert_allclose(clean["waits_all"], resumed["waits_all"],
                               rtol=2e-6)
    np.testing.assert_array_equal(clean["part_sum"], resumed["part_sum"])
    np.testing.assert_array_equal(clean["cut_times"],
                                  resumed["cut_times"])


def test_dual_voronoi_family_end_to_end(tmp_path):
    """The dual family on the irregular Voronoi geometry
    (--dual-source voronoi): distinct tag namespace, irregular degrees,
    same artifact manifest + compactness scoring + contiguity/population
    invariants as the quad state."""
    cfg = ex.ExperimentConfig(family="dual", alignment=0, base=2.6,
                              pop_tol=0.3, n_districts=4, dual_nx=7,
                              dual_ny=7, dual_source="voronoi",
                              total_steps=300, n_chains=3)
    assert cfg.tag.startswith("dual-VOR-K4-")
    out = str(tmp_path)
    data = ex.run_config(cfg, out)
    _assert_artifacts(cfg, out)
    g, plan, geo = drv.build_graph_and_plan(cfg)
    assert g.n_nodes == 49
    assert g.deg.max() > 4  # genuinely irregular topology
    for c in range(cfg.n_chains):
        _districts_connected(g, data["assignments"][c], 4)
    pp = data["polsby_popper"]
    assert np.isfinite(pp).all() and (pp > 0).all() and (pp <= 1).all()
