"""k-district pair-proposal board path (kernel/board._planes_pair /
_transition_pair): BASELINE config 2 ("k-district (k=4,8) flip walk on
n x n grid with population-balance eps") on the stencil fast path.

Checks: the pair move-set against a brute-force numpy enumeration of
distinct (boundary node, adjacent district) pairs; run invariants
(derived fields pure in the board, every district connected, bounds
respected); and distributional equivalence against the general
gather-path kernel running the same spec.
"""

import numpy as np

import flipcomplexityempirical_tpu as fce

from conftest import assert_grid_districts_connected
from flipcomplexityempirical_tpu.kernel import board as kb

from test_parity import ks_stat
import pytest


def _spec(k, **kw):
    base = dict(n_districts=k, proposal="pair", contiguity="patch",
                invalid="repropose", accept="cut", parity_metrics=True,
                geom_waits=True, record_interface=False)
    base.update(kw)
    return fce.Spec(**base)


def _run_pair(grid=(8, 8), k=4, chains=16, steps=601, base=1.3, tol=0.5,
              seed=2, **kw):
    g = fce.graphs.square_grid(*grid)
    plan = fce.graphs.stripes_plan(g, k)
    spec = _spec(k, **kw)
    assert kb.supports(g, spec)
    bg, st, params = fce.sampling.init_board(
        g, plan, n_chains=chains, seed=seed, spec=spec, base=base,
        pop_tol=tol)
    res = fce.sampling.run_board(bg, spec, params, st, n_steps=steps)
    return g, spec, res


def _brute_pair_set(b2, dist_pop, lo, hi, unit=1):
    """All valid (flat node, target district) pairs of one chain's board:
    distinct adjacent districts != own, ring contiguity of the origin
    district, population bounds."""
    h, w = b2.shape
    pairs = set()
    pad = np.pad(b2, 1, constant_values=-1)

    def at(x, y):
        return pad[x + 1, y + 1]

    ring_off = [(0, 1), (1, 1), (1, 0), (1, -1), (0, -1), (-1, -1),
                (-1, 0), (-1, 1)]
    for x in range(h):
        for y in range(w):
            a = b2[x, y]
            same = [at(x + dx, y + dy) == a for dx, dy in ring_off]
            seeds = sum(same[i] for i in (0, 2, 4, 6))
            runs = sum(same[i] & ~(same[i - 1] & same[i - 2])
                       for i in (0, 2, 4, 6))
            contig = (seeds <= 1) | (runs <= 1)
            if not contig:
                continue
            if dist_pop[a] - unit < lo:
                continue
            for dx, dy in ((0, 1), (1, 0), (0, -1), (-1, 0)):
                d = at(x + dx, y + dy)
                if d < 0 or d == a:
                    continue
                if dist_pop[d] + unit > hi:
                    continue
                pairs.add((x * w + y, int(d)))
    return pairs


def test_pair_move_set_matches_brute_force():
    g = fce.graphs.square_grid(6, 7)
    k = 4
    plan = fce.graphs.stripes_plan(g, k)
    spec = _spec(k)
    bg, st, params = fce.sampling.init_board(
        g, plan, n_chains=6, seed=9, spec=spec, base=1.0, pop_tol=0.6)
    # evolve away from the initial stripes first
    res = fce.sampling.run_board(bg, spec, params, st, n_steps=120,
                                 record_history=False)
    st = res.state
    planes = kb._planes_pair(bg, spec, params, st)
    valid = np.asarray(planes["valid"]).reshape(6, g.n_nodes, 4)
    lo = float(np.asarray(params.pop_lo)[0])
    hi = float(np.asarray(params.pop_hi)[0])
    offs = [1, bg.w, -1, -bg.w]
    for c in range(6):
        b2 = np.asarray(st.board[c]).reshape(bg.h, bg.w)
        want = _brute_pair_set(b2, np.asarray(st.dist_pop[c]), lo, hi)
        got = set()
        flat = b2.reshape(-1)
        for v in range(g.n_nodes):
            for j in range(4):
                if valid[c, v, j]:
                    got.add((v, int(flat[v + offs[j]])))
        assert got == want, f"chain {c}"
        # dedup: one slot per distinct target district
        for v in range(g.n_nodes):
            ds = [int(flat[v + offs[j]]) for j in range(4) if valid[c, v, j]]
            assert len(ds) == len(set(ds)), f"chain {c} node {v} dup"
        # b_count is the DISTINCT-PAIR count before validity gates (the
        # reference's pair b_nodes updater feeding geom_wait)
        raw = set()
        for x in range(bg.h):
            for y in range(bg.w):
                for dx, dy in ((0, 1), (1, 0), (0, -1), (-1, 0)):
                    if 0 <= x + dx < bg.h and 0 <= y + dy < bg.w:
                        d = b2[x + dx, y + dy]
                        if d != b2[x, y]:
                            raw.add((x * bg.w + y, int(d)))
        assert int(np.asarray(planes["b_count"])[c]) == len(raw), c


def test_pair_run_invariants():
    k = 4
    g, spec, res = _run_pair(k=k, tol=0.6)
    s = res.host_state()
    b = np.asarray(s.board).reshape(-1, 8, 8)

    for d in range(k):
        pops = (b == d).sum(axis=(1, 2))
        np.testing.assert_array_equal(np.asarray(s.dist_pop)[:, d], pops)
    cut = ((b[:, :, :-1] != b[:, :, 1:]).sum(axis=(1, 2))
           + (b[:, :-1, :] != b[:, 1:, :]).sum(axis=(1, 2)))
    np.testing.assert_array_equal(np.asarray(s.cut_count), cut)

    assert_grid_districts_connected(b, k)

    ideal = 64 / k
    dp = np.asarray(s.dist_pop)
    assert (dp >= (1 - 0.6) * ideal - 1e-6).all()
    assert (dp <= (1 + 0.6) * ideal + 1e-6).all()

    cut_t = kb.edge_cut_times(g, res.state)
    np.testing.assert_array_equal(cut_t.sum(axis=1),
                                  res.history["cut_count"].sum(axis=1))


@pytest.mark.slow
def test_pair_board_matches_general_path():
    # burn must cover the k=4 mode-mixing transient: at burn 600 the
    # per-run mean-cut spread is ~1.3% seed-to-seed (both backends);
    # at burn 2000 an 8-seed calibration gives 38.000+-0.109 (general)
    # vs 38.001+-0.102 (board) — identical distributions
    k, chains, steps, burn = 4, 24, 6001, 2000
    g = fce.graphs.square_grid(8, 8)
    plan = fce.graphs.stripes_plan(g, k)
    spec = _spec(k)

    dg, st_g, par_g = fce.init_batch(g, plan, n_chains=chains, seed=3,
                                    spec=spec, base=1.3, pop_tol=0.5)
    res_g = fce.run_chains(dg, spec, par_g, st_g, n_steps=steps)

    bg, st_b, par_b = fce.sampling.init_board(
        g, plan, n_chains=chains, seed=8, spec=spec, base=1.3, pop_tol=0.5)
    res_b = fce.sampling.run_board(bg, spec, par_b, st_b, n_steps=steps)

    # stride-25 samples of a k=4 chain stay autocorrelated, so the pooled
    # KS noise floor sits near 0.07 even between two same-backend seeds.
    # Calibration (4 seeds/backend, chains=32): cut 38.000+-0.109 vs
    # 38.001+-0.102, b 47.265+-0.071 vs 47.283+-0.126 — identical
    # distributions; single same-backend runs wander up to ~1% in mean,
    # so 2.5% is the regression tripwire (a wrong move set shifts these
    # by far more)
    sub = slice(burn, None, 25)
    for key in ("cut_count", "b_count"):
        a = res_g.history[key][:, sub].ravel()
        c = res_b.history[key][:, sub].ravel()
        ks = ks_stat(a, c)
        assert ks < 0.09, f"{key} KS {ks:.4f}"
        ma, mc = a.mean(), c.mean()
        assert abs(ma - mc) / ma < 0.025, f"{key} means {ma:.2f} vs {mc:.2f}"
    ra = res_g.history["accepts"][:, -1].mean()
    rb = res_b.history["accepts"][:, -1].mean()
    assert abs(ra - rb) / ra < 0.06, (ra, rb)


def test_pair_contiguity_none_smoke():
    """No-contiguity pair walk (districts may fragment); derived fields
    stay pure functions of the board."""
    _, _, res = _run_pair(k=3, steps=201, tol=0.9, contiguity="none")
    s = res.host_state()
    b = np.asarray(s.board).reshape(-1, 8, 8)
    for d in range(3):
        np.testing.assert_array_equal(np.asarray(s.dist_pop)[:, d],
                                      (b == d).sum((1, 2)))
    cut = ((b[:, :, :-1] != b[:, :, 1:]).sum((1, 2))
           + (b[:, :-1] != b[:, 1:]).sum((1, 2)))
    np.testing.assert_array_equal(np.asarray(s.cut_count), cut)


def test_pair_k8_smoke():
    _, _, res = _run_pair(grid=(8, 16), k=8, chains=8, steps=301, tol=0.9)
    s = res.host_state()
    assert (np.asarray(s.tries_sum) == 300).all()
    b = np.asarray(s.board).reshape(-1, 8, 16)
    assert_grid_districts_connected(b, 8)
