"""Bit-board backend verification (kernel/bitboard.py).

The backend promises BIT-IDENTICAL trajectories to the int8 board body —
same PRNG stream, same m-th-valid selection, same acceptance arithmetic —
so the primary test runs the same chunk through both bodies and asserts
every state field, history row, and bookkeeping plane equal. Plus unit
tests of the packing/shifting/counter primitives against numpy.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import flipcomplexityempirical_tpu as fce
from flipcomplexityempirical_tpu.kernel import bitboard as bb
from flipcomplexityempirical_tpu.kernel import board as kb


def test_pack_unpack_roundtrip(rng):
    for n in (5, 32, 64, 100, 256):
        plane = rng.integers(0, 2, size=(3, n)).astype(np.int8)
        words = bb.pack_bits(jnp.asarray(plane))
        assert words.shape == (3, bb.n_words(n))
        back = bb.unpack_bits(words, n)
        np.testing.assert_array_equal(np.asarray(back), plane)


def test_shifts_match_numpy(rng):
    n = 200
    plane = rng.integers(0, 2, size=(2, n)).astype(np.int8)
    words = bb.pack_bits(jnp.asarray(plane))
    nw = bb.n_words(n)
    padded = np.pad(plane, ((0, 0), (0, nw * 32 - n)))
    for k in (1, 31, 32, 33, 63, 64, 65):
        down = np.zeros_like(padded)
        down[:, :padded.shape[1] - k] = padded[:, k:]
        got = bb.unpack_bits(bb.shift_down(words, k), nw * 32)
        np.testing.assert_array_equal(np.asarray(got), down, err_msg=f"down {k}")
        up = np.zeros_like(padded)
        up[:, k:] = padded[:, :padded.shape[1] - k]
        got = bb.unpack_bits(bb.shift_up(words, k), nw * 32)
        np.testing.assert_array_equal(np.asarray(got), up, err_msg=f"up {k}")


def test_bit_sliced_counters(rng):
    c, n, t = 3, 70, 37
    planes = rng.integers(0, 2, size=(t, c, n)).astype(np.int8)
    slices = bb.counter_init(c, bb.n_words(n), t.bit_length())
    for r in range(t):
        slices = bb.counter_add(slices, bb.pack_bits(jnp.asarray(planes[r])))
    got = bb.counter_fold(slices, n)
    np.testing.assert_array_equal(np.asarray(got), planes.sum(0))


@pytest.mark.slow
def test_select_flat_picks_mth_valid(rng):
    g = fce.graphs.square_grid(6, 32)
    bg = kb.make_board_graph(g)
    c, n = 16, 192
    valid = rng.integers(0, 2, size=(c, n)).astype(bool)
    valid[0] = False                                   # exhausted chain
    u = rng.random(c).astype(np.float32)
    flat, any_valid = bb.select_flat(bg, bb.pack_bits(jnp.asarray(valid)),
                                     jnp.asarray(u))
    flat = np.asarray(flat)
    for ci in range(c):
        idxs = np.flatnonzero(valid[ci])
        if len(idxs) == 0:
            assert not bool(np.asarray(any_valid)[ci])
            continue
        m = min(int(np.float32(u[ci]) * np.float32(len(idxs))),
                len(idxs) - 1)
        assert flat[ci] == idxs[m], ci


def assert_run_equal(st, got, want):
    """Field-for-field equality of two (state, outs) chunk results."""
    got_state, got_outs = got
    want_state, want_outs = want
    for f in st.__dataclass_fields__:
        np.testing.assert_array_equal(
            np.asarray(getattr(got_state, f)),
            np.asarray(getattr(want_state, f)), err_msg=f)
    for key in want_outs:
        np.testing.assert_array_equal(np.asarray(got_outs[key]),
                                      np.asarray(want_outs[key]),
                                      err_msg=key)


@pytest.mark.parametrize("hw,spec_kw", [
    ((6, 32), {}),
    ((4, 64), {}),
    ((6, 32), dict(accept="always")),
    ((6, 32), dict(contiguity="none")),
    ((6, 32), dict(geom_waits=False, parity_metrics=False)),
])
@pytest.mark.slow
def test_bit_identity_vs_int8_body(rng, hw, spec_kw):
    """The dispatch and the promise: on a supported workload the
    auto-dispatched chunk (bit body) equals the int8 body forced via
    bits=False — field for field, including histories and bookkeeping
    planes."""
    h, w = hw
    g = fce.graphs.square_grid(h, w)
    plan = fce.graphs.stripes_plan(g, 2)
    kw = dict(n_districts=2, proposal="bi", contiguity="patch",
              invalid="repropose", accept="cut", parity_metrics=True,
              geom_waits=True, record_interface=False)
    kw.update(spec_kw)
    spec = fce.Spec(**kw)
    bg, st, params = fce.sampling.init_board(
        g, plan, n_chains=8, seed=11, spec=spec, base=1.7, pop_tol=0.3)
    assert bb.supported(bg, spec)

    # bits=False forces the int8 body first-class (same jit, distinct
    # cache entry)
    assert_run_equal(st, kb.run_board_chunk(bg, spec, params, st, 75),
                     kb.run_board_chunk(bg, spec, params, st, 75,
                                        bits=False))


@pytest.mark.parametrize("hw,k,spec_kw", [
    ((6, 32), 3, {}),
    ((4, 64), 4, {}),
    ((6, 32), 8, {}),
    ((6, 32), 4, dict(accept="always")),
    ((6, 32), 3, dict(contiguity="none")),
    ((6, 32), 5, dict(geom_waits=False, parity_metrics=False)),
])
@pytest.mark.slow
def test_pair_bit_identity_vs_int8_body(hw, k, spec_kw):
    """The k-district pair bit body (district ids as bit-sliced planes)
    equals the int8 pair body forced via bits=False — field for field."""
    h, w = hw
    g = fce.graphs.square_grid(h, w)
    plan = fce.graphs.stripes_plan(g, k)
    kw = dict(n_districts=k, proposal="pair", contiguity="patch",
              invalid="repropose", accept="cut", parity_metrics=True,
              geom_waits=True, record_interface=False)
    kw.update(spec_kw)
    spec = fce.Spec(**kw)
    bg, st, params = fce.sampling.init_board(
        g, plan, n_chains=8, seed=7, spec=spec, base=1.4, pop_tol=0.5)
    assert bb.supported_pair(bg, spec)

    assert_run_equal(st, kb.run_board_chunk(bg, spec, params, st, 60),
                     kb.run_board_chunk(bg, spec, params, st, 60,
                                        bits=False))


def test_pack_board_planes_roundtrip(rng):
    for k in (2, 3, 5, 8):
        board = rng.integers(0, k, size=(3, 100)).astype(np.int8)
        planes = bb.pack_board_planes(jnp.asarray(board), k)
        assert len(planes) == bb.bits_per_district(k)
        back = bb.unpack_board_planes(planes, 100)
        np.testing.assert_array_equal(np.asarray(back), board)


def test_dispatch_gates():
    g = fce.graphs.square_grid(6, 32)
    bg = kb.make_board_graph(g)
    assert bg.uniform_pop
    assert not bb.supported(bg, fce.Spec(accept="corrected"))
    assert not bb.supported(bg, fce.Spec(record_assignment_bits=True))
    g2 = fce.graphs.square_grid(8, 8)          # w % 32 != 0
    assert not bb.supported(kb.make_board_graph(g2), fce.Spec())
    # non-uniform population defeats the scalar pop gate
    import dataclasses
    g3 = dataclasses.replace(
        g, pop=np.arange(g.n_nodes, dtype=np.int64) % 3 + 1)
    bg3 = kb.make_board_graph(g3)
    assert not bg3.uniform_pop
    assert not bb.supported(bg3, fce.Spec())
