"""Board (stencil) fast-path verification.

Four layers, per the test strategy of SURVEY.md section 4:

1. Exhaustive local equivalence: the ring contiguity criterion equals
   ``contiguity.patch_connected`` for EVERY membership pattern of the
   radius-2 patch (up to 2^12 patterns) at interior, edge, corner, and
   near-corner positions — the proof obligation for collapsing the patch
   BFS into elementwise stencil ops.
2. Exact replay of the deferred flip bookkeeping: ``apply_flip_log``'s
   scatter algebra against a sequential Python replay of the reference's
   per-yield updates (grid_chain_sec11.py:396-400), including chunked
   application.
3. Exact per-run invariants: derived fields never drift from the board;
   accumulators tie out against histories (sum cut_times == sum cut_count
   over yields; waits_total == sum of wait history); chunking invisible.
4. Cross-path distributional parity: run_board vs run_chains (same spec,
   independent RNG streams) agree on cut/b/wait trajectory statistics and
   accumulator profiles.
"""

import itertools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import flipcomplexityempirical_tpu as fce

from conftest import assert_grid_districts_connected
from flipcomplexityempirical_tpu.kernel import board as kb
from flipcomplexityempirical_tpu.kernel import contiguity

from test_parity import ks_stat


# ---------------------------------------------------------------------------
# 1. ring criterion == patch_connected, exhaustively
# ---------------------------------------------------------------------------

def _patch_cells(h, w, x, y):
    """Radius-2 rook ball around (x, y), clipped to the grid (== the patch
    of graphs.lattice.build_lattice for a plain grid)."""
    cells = []
    for dx in range(-2, 3):
        for dy in range(-2, 3):
            if 0 < abs(dx) + abs(dy) <= 2:
                cx, cy = x + dx, y + dy
                if 0 <= cx < h and 0 <= cy < w:
                    cells.append((cx, cy))
    return cells


@pytest.mark.parametrize("pos", [(2, 2), (0, 2), (2, 0), (0, 0), (1, 1),
                                 (1, 2), (4, 4), (4, 2), (0, 4)])
def test_ring_equals_patch_exhaustive(pos):
    h = w = 5
    g = fce.graphs.square_grid(h, w)
    dg = g.device()
    bg = kb.make_board_graph(g)
    x, y = pos
    v = x * w + y
    cells = _patch_cells(h, w, x, y)
    assert len(cells) <= 12

    boards = []
    for bits in itertools.product((0, 1), repeat=len(cells)):
        b = np.ones((h, w), np.int8)       # everything else: other district
        b[x, y] = 0                        # v's own district is 0
        for (cx, cy), m in zip(cells, bits):
            b[cx, cy] = 0 if m else 1
        boards.append(b.reshape(-1))
    boards = np.stack(boards)              # (2^k, N)

    # ring criterion, batched over patterns (patterns act as the C axis)
    same = kb.same_planes(bg, jnp.asarray(boards))
    ring = np.asarray(kb.ring_contig_ok(same))[:, v]

    patch = np.asarray(jax.vmap(
        lambda a: contiguity.patch_connected(dg, a, v, jnp.int32(0)))(
            jnp.asarray(boards)))

    mism = np.nonzero(ring != patch)[0]
    assert mism.size == 0, (
        f"ring vs patch disagree at pos {pos} for {mism.size} patterns, "
        f"first board:\n{boards[mism[0]].reshape(h, w)}")


def test_ring_equals_patch_random_boards(rng):
    """Whole-board comparison on random assignments: every node's ring
    verdict equals its patch verdict (both origin districts arise since
    membership is relative to each node's own label)."""
    h = w = 7
    g = fce.graphs.square_grid(h, w)
    dg = g.device()
    bg = kb.make_board_graph(g)

    boards = (rng.random((64, h * w)) < 0.5).astype(np.int8)
    same = kb.same_planes(bg, jnp.asarray(boards))
    ring = np.asarray(kb.ring_contig_ok(same))

    nodes = jnp.arange(h * w)

    def one(a):
        return jax.vmap(
            lambda vv: contiguity.patch_connected(
                dg, a, vv, a[vv].astype(jnp.int32)))(nodes)

    patch = np.asarray(jax.vmap(one)(jnp.asarray(boards)))
    assert (ring == patch).all()


# ---------------------------------------------------------------------------
# 2. deferred flip bookkeeping == sequential replay
# ---------------------------------------------------------------------------

def _replay_sequential(part_sum, last_flipped, num_flips, log_f, log_s, t0):
    """The reference's per-yield updates (grid_chain_sec11.py:396-400),
    literally."""
    ps, lf, nf = part_sum.copy(), last_flipped.copy(), num_flips.copy()
    tlen, c = log_f.shape
    for r in range(tlen):
        for ci in range(c):
            f = log_f[r, ci]
            if f < 0:
                continue
            t = t0[ci] + r
            s = log_s[r, ci]
            ps[ci, f] += -s * (t - lf[ci, f])
            lf[ci, f] = t
            nf[ci, f] += 1
    return ps, lf, nf


def _random_log(rng, tlen, c, n, p_accept=0.4, p_none=0.1):
    """A log with the structure the chain produces: the pointer holds
    between accepts, each accept moves it to a fresh node and flips that
    node's sign; chains may start with no pointer."""
    log_f = np.full((tlen, c), -1, np.int32)
    log_s = np.ones((tlen, c), np.int32)
    node_sign = {}
    for ci in range(c):
        f = -1 if rng.random() < p_none else int(rng.integers(n))
        if f >= 0:
            node_sign[(ci, f)] = rng.choice([-1, 1])
        for r in range(tlen):
            if rng.random() < p_accept:
                f = int(rng.integers(n))
                node_sign[(ci, f)] = -node_sign.get((ci, f), -1)
            if f >= 0:
                log_f[r, ci] = f
                log_s[r, ci] = node_sign[(ci, f)]
    return log_f, log_s


def test_apply_flip_log_matches_sequential(rng):
    tlen, c, n = 60, 5, 12
    log_f, log_s = _random_log(rng, tlen, c, n)
    t0 = rng.integers(0, 50, size=c).astype(np.int32)
    ps0 = rng.integers(-5, 5, size=(c, n)).astype(np.int32)
    lf0 = rng.integers(0, 3, size=(c, n)).astype(np.int32)
    nf0 = rng.integers(0, 3, size=(c, n)).astype(np.int32)
    # last_flipped carry-in must precede the log (reference invariant)
    lf0 = np.minimum(lf0, t0[:, None])

    want = _replay_sequential(ps0, lf0, nf0, log_f, log_s, t0)
    got = kb.apply_flip_log(jnp.asarray(ps0), jnp.asarray(lf0),
                            jnp.asarray(nf0), jnp.asarray(log_f),
                            jnp.asarray(log_s), jnp.asarray(t0))
    for w_arr, g_arr, name in zip(want, got,
                                  ("part_sum", "last_flipped", "num_flips")):
        np.testing.assert_array_equal(np.asarray(g_arr), w_arr, err_msg=name)


def test_apply_flip_log_benchmark_scale(rng):
    """Exactness at the headline shapes: N > 128 exercises the two-level
    node factorization (n = x*128 + y), tlen > 256 exercises weight
    magnitudes past bf16's exact-integer range, and big t0 exercises the
    chunk-relative carry correction (absolute yields ~1e5)."""
    tlen, c, n = 300, 3, 4096
    log_f, log_s = _random_log(rng, tlen, c, n)
    t0 = np.full(c, 100_000, np.int32)
    ps0 = rng.integers(-10 ** 5, 10 ** 5, size=(c, n)).astype(np.int32)
    lf0 = rng.integers(0, 100_000, size=(c, n)).astype(np.int32)
    nf0 = rng.integers(0, 1000, size=(c, n)).astype(np.int32)

    want = _replay_sequential(ps0, lf0, nf0, log_f, log_s, t0)
    got = kb.apply_flip_log(jnp.asarray(ps0), jnp.asarray(lf0),
                            jnp.asarray(nf0), jnp.asarray(log_f),
                            jnp.asarray(log_s), jnp.asarray(t0))
    for w_arr, g_arr, name in zip(want, got,
                                  ("part_sum", "last_flipped", "num_flips")):
        np.testing.assert_array_equal(np.asarray(g_arr), w_arr, err_msg=name)


def test_apply_flip_log_key_overflow_guard():
    n = 70_000
    with pytest.raises(ValueError, match="overflows int32"):
        kb.apply_flip_log(jnp.zeros((1, n), jnp.int32),
                          jnp.zeros((1, n), jnp.int32),
                          jnp.zeros((1, n), jnp.int32),
                          jnp.zeros((32_000, 1), jnp.int32),
                          jnp.zeros((32_000, 1), jnp.int32),
                          jnp.zeros(1, jnp.int32))


@pytest.mark.slow
def test_apply_flip_log_chunked_composition(rng):
    """Splitting a log at an arbitrary boundary (including mid-run) and
    applying the pieces sequentially gives the same result as one piece."""
    tlen, c, n = 50, 4, 10
    log_f, log_s = _random_log(rng, tlen, c, n)
    t0 = np.zeros(c, np.int32)
    ps0 = np.zeros((c, n), np.int32)
    lf0 = np.zeros((c, n), np.int32)
    nf0 = np.zeros((c, n), np.int32)

    whole = kb.apply_flip_log(jnp.asarray(ps0), jnp.asarray(lf0),
                              jnp.asarray(nf0), jnp.asarray(log_f),
                              jnp.asarray(log_s), jnp.asarray(t0))
    for cut in (1, 17, 23, 49):
        a = kb.apply_flip_log(jnp.asarray(ps0), jnp.asarray(lf0),
                              jnp.asarray(nf0), jnp.asarray(log_f[:cut]),
                              jnp.asarray(log_s[:cut]), jnp.asarray(t0))
        b = kb.apply_flip_log(*a, jnp.asarray(log_f[cut:]),
                              jnp.asarray(log_s[cut:]),
                              jnp.asarray(t0 + cut))
        for w_arr, g_arr in zip(whole, b):
            np.testing.assert_array_equal(np.asarray(g_arr),
                                          np.asarray(w_arr))


def test_apply_flip_log_auto_slicing(rng):
    """The HBM-bounded internal T-slicing (slice_bytes) is the identity:
    a budget that forces the minimum 16-row slices reproduces the
    single-einsum replay exactly (board.py round-5 C=16384 OOM fix)."""
    tlen, c, n = 50, 4, 10
    log_f, log_s = _random_log(rng, tlen, c, n)
    t0 = np.asarray([0, 3, 7, 100], np.int32)
    ps0 = np.zeros((c, n), np.int32)
    lf0 = np.zeros((c, n), np.int32)
    nf0 = np.zeros((c, n), np.int32)
    args = (jnp.asarray(ps0), jnp.asarray(lf0), jnp.asarray(nf0),
            jnp.asarray(log_f), jnp.asarray(log_s), jnp.asarray(t0))
    whole = kb.apply_flip_log(*args)
    sliced = kb.apply_flip_log(*args, slice_bytes=1)
    for w_arr, g_arr in zip(whole, sliced):
        np.testing.assert_array_equal(np.asarray(g_arr), np.asarray(w_arr))


# ---------------------------------------------------------------------------
# 3. exact invariants of a run
# ---------------------------------------------------------------------------

def _run(grid=8, chains=32, steps=601, base=1.4, tol=0.3, seed=3, **kw):
    g = fce.graphs.square_grid(grid, grid)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(n_districts=2, proposal="bi", contiguity="patch",
                    invalid="repropose", accept="cut",
                    parity_metrics=True, geom_waits=True, **kw)
    bg, st, params = fce.sampling.init_board(
        g, plan, n_chains=chains, seed=seed, spec=spec, base=base,
        pop_tol=tol)
    res = fce.sampling.run_board(bg, spec, params, st, n_steps=steps,
                                 chunk=100)
    return g, res


@pytest.mark.slow
def test_board_invariants():
    g, res = _run()
    s = res.host_state()
    h = w = 8
    b = s.board.reshape(-1, h, w)

    # derived fields are pure functions of the board
    pop0 = (b == 0).sum(axis=(1, 2))
    assert (s.dist_pop[:, 0] == pop0).all()
    assert (s.dist_pop[:, 1] == h * w - pop0).all()
    cut = ((b[:, :, :-1] != b[:, :, 1:]).sum(axis=(1, 2))
           + (b[:, :-1, :] != b[:, 1:, :]).sum(axis=(1, 2)))
    assert (s.cut_count == cut).all()

    # every chain still satisfies contiguity (district connected) — the
    # single masked draw must never commit a disconnecting flip
    assert_grid_districts_connected(b, 2)

    # accumulators tie out against histories
    cut_t = kb.edge_cut_times(g, res.state)
    np.testing.assert_array_equal(cut_t.sum(axis=1),
                                  res.history["cut_count"].sum(axis=1))
    np.testing.assert_allclose(res.waits_total,
                               res.history["wait"].sum(axis=1, dtype=float),
                               rtol=1e-6)
    # num_flips counts every yield whose state carries a flip pointer
    # (reference re-application quirk): equals yields after first accept
    first = (res.history["accepts"] > 0).argmax(axis=1)
    expect = np.where(res.history["accepts"][:, -1] > 0,
                      res.history["accepts"].shape[1] - first, 0)
    np.testing.assert_array_equal(s.num_flips.sum(axis=1), expect)


def test_board_population_bounds_respected():
    g, res = _run(tol=0.05, steps=801)
    s = res.host_state()
    ideal = g.n_nodes / 2
    assert (s.dist_pop >= (1 - 0.05) * ideal - 1e-6).all()
    assert (s.dist_pop <= (1 + 0.05) * ideal + 1e-6).all()


@pytest.mark.slow
def test_board_chunking_is_invisible():
    """Same seed, different chunking => bit-identical state and history."""
    g = fce.graphs.square_grid(6, 6)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(contiguity="patch")
    outs = []
    for chunk in (7, 50):
        bg, st, params = fce.sampling.init_board(
            g, plan, n_chains=8, seed=5, spec=spec, base=1.2, pop_tol=0.3)
        res = fce.sampling.run_board(bg, spec, params, st, n_steps=201,
                                     chunk=chunk)
        outs.append(res)
    a, b = outs
    for k in a.history:
        np.testing.assert_array_equal(a.history[k], b.history[k])
    for fld in ("board", "part_sum", "last_flipped", "num_flips",
                "cut_times_e", "cut_times_s"):
        np.testing.assert_array_equal(np.asarray(getattr(a.state, fld)),
                                      np.asarray(getattr(b.state, fld)),
                                      err_msg=fld)
    np.testing.assert_allclose(a.waits_total, b.waits_total)


@pytest.mark.parametrize("path", ["general", "board"])
@pytest.mark.parametrize("every", [4, 7])
@pytest.mark.slow
def test_record_every_is_a_stride(path, every):
    """Thinned recording (record_every=k) must be EXACTLY the full
    history's columns 0, k, 2k, ... — same seed, same final state, same
    accumulators — because thinning only strides the readback; every
    metric accumulator still advances per step."""
    g = fce.graphs.square_grid(6, 6)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(contiguity="patch")
    n_steps = 201

    def go(record_every):
        if path == "board":
            bg, st, params = fce.sampling.init_board(
                g, plan, n_chains=8, seed=5, spec=spec, base=1.2,
                pop_tol=0.3)
            return fce.sampling.run_board(bg, spec, params, st,
                                          n_steps=n_steps, chunk=40,
                                          record_every=record_every)
        dg, st, params = fce.init_batch(
            g, plan, n_chains=8, seed=5, spec=spec, base=1.2, pop_tol=0.3)
        return fce.run_chains(dg, spec, params, st, n_steps=n_steps,
                              chunk=40, record_every=record_every)

    full, thin = go(1), go(every)
    grid = np.arange(0, n_steps, every)
    assert set(full.history) == set(thin.history)
    for k in full.history:
        np.testing.assert_array_equal(thin.history[k],
                                      full.history[k][:, grid],
                                      err_msg=k)
    sf, st_ = full.host_state(), thin.host_state()
    for fld in sf.__dataclass_fields__:
        np.testing.assert_array_equal(np.asarray(getattr(sf, fld)),
                                      np.asarray(getattr(st_, fld)),
                                      err_msg=fld)
    np.testing.assert_allclose(full.waits_total, thin.waits_total)
    # the thinned history still feeds the stats layer
    from flipcomplexityempirical_tpu.stats import ess as ess_fn
    _, total = ess_fn(np.asarray(thin.history["cut_count"], np.float64))
    assert np.isfinite(total) and total > 0


def test_supports_gates():
    spec = fce.Spec(contiguity="patch")
    assert kb.supports(fce.graphs.square_grid(6, 6), spec)
    # the paper's near-grid graphs lower onto the stencil fast path
    # (lower.lower_to_stencil); hex falls back — its radius-3 patch
    # tables don't match the lowering's radius-2 B2 windows
    assert kb.supports(fce.graphs.grid_sec11(), spec)
    assert kb.supports(fce.graphs.frankengraph(), spec)
    assert not kb.supports(fce.graphs.hex_lattice(4, 4), spec)
    g = fce.graphs.square_grid(6, 6)
    assert not kb.supports(g, fce.Spec(contiguity="exact"))
    # the k-district pair walk has its own body (uniform pop, no
    # corrected accept) — tests/test_board_pair.py
    assert kb.supports(g, fce.Spec(proposal="pair", n_districts=4))
    assert not kb.supports(g, fce.Spec(proposal="pair", n_districts=4,
                                       accept="corrected"))
    assert not kb.supports(g, fce.Spec(proposal="pair", n_districts=40))
    assert not kb.supports(g, fce.Spec(invalid="selfloop"))
    assert not kb.supports(g, fce.Spec(record_interface=True))
    assert kb.supports(g, fce.Spec(accept="corrected"))
    assert kb.supports(g, fce.Spec(anneal="linear"))
    # packed-assignment recording only fits graphs with <= 32 nodes
    small = fce.graphs.square_grid(4, 8)
    assert kb.supports(small, fce.Spec(record_assignment_bits=True))
    assert not kb.supports(fce.graphs.square_grid(8, 8),
                           fce.Spec(record_assignment_bits=True))


# ---------------------------------------------------------------------------
# 4. board path vs general path: same distribution
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_board_matches_general_path():
    grid, chains, steps, burn = 8, 24, 4001, 800
    base, tol = 1.4, 0.2
    g = fce.graphs.square_grid(grid, grid)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(n_districts=2, proposal="bi", contiguity="patch",
                    invalid="repropose", accept="cut",
                    parity_metrics=True, geom_waits=True)

    dg, st_g, par_g = fce.init_batch(g, plan, n_chains=chains, seed=11,
                                     spec=spec, base=base, pop_tol=tol)
    res_g = fce.run_chains(dg, spec, par_g, st_g, n_steps=steps)

    bg, st_b, par_b = fce.sampling.init_board(
        g, plan, n_chains=chains, seed=17, spec=spec, base=base, pop_tol=tol)
    res_b = fce.sampling.run_board(bg, spec, par_b, st_b, n_steps=steps)

    sub = slice(burn, None, 25)
    for key, tol_ks in (("cut_count", 0.06), ("b_count", 0.06)):
        a = res_g.history[key][:, sub].ravel()
        b = res_b.history[key][:, sub].ravel()
        ks = ks_stat(a, b)
        assert ks < tol_ks, f"{key} KS {ks:.4f}"
        ma, mb = a.mean(), b.mean()
        assert abs(ma - mb) / ma < 0.02, f"{key} means {ma:.2f} vs {mb:.2f}"

    # waits are heavy-tailed; compare means loosely and accept rates tightly
    wa = res_g.history["wait"][:, burn:].mean()
    wb = res_b.history["wait"][:, burn:].mean()
    assert abs(wa - wb) / wa < 0.1, f"wait means {wa:.2f} vs {wb:.2f}"
    aa = np.asarray(res_g.state.accept_count).mean()
    ab = np.asarray(res_b.state.accept_count).mean()
    assert abs(aa - ab) / aa < 0.05, f"accepts {aa:.1f} vs {ab:.1f}"

    # parity accumulators: per-node flip-count fields drawn from the same
    # distribution => chain-averaged profiles correlate strongly
    nf_g = np.asarray(res_g.state.num_flips).mean(axis=0)
    nf_b = np.asarray(res_b.state.num_flips).mean(axis=0)
    corr = np.corrcoef(nf_g, nf_b)[0, 1]
    assert corr > 0.97, f"num_flips profile corr {corr:.3f}"

    # cut-edge heat profiles likewise (exercises edge_cut_times mapping)
    ct_g = np.asarray(res_g.state.cut_times).mean(axis=0)
    ct_b = kb.edge_cut_times(g, res_b.state).mean(axis=0)
    corr_ct = np.corrcoef(ct_g, ct_b)[0, 1]
    assert corr_ct > 0.97, f"cut_times profile corr {corr_ct:.3f}"

    # part_sum profiles: same time-integral structure across the board
    psg = np.asarray(res_g.state.part_sum).mean(axis=0)
    psb = np.asarray(res_b.state.part_sum).mean(axis=0)
    corr_ps = np.corrcoef(psg, psb)[0, 1]
    assert corr_ps > 0.9, f"part_sum profile corr {corr_ps:.3f}"


def test_recount_cuts_matches_recompute(rng):
    g = fce.graphs.square_grid(7, 5)
    bg = kb.make_board_graph(g)
    boards = (rng.random((16, 35)) < 0.5).astype(np.int8)
    got = np.asarray(kb.recount_cuts(bg, jnp.asarray(boards)))
    b2 = boards.reshape(16, 7, 5)
    want = ((b2[:, :, :-1] != b2[:, :, 1:]).sum((1, 2))
            + (b2[:, :-1, :] != b2[:, 1:, :]).sum((1, 2)))
    np.testing.assert_array_equal(got, want)


def test_empty_valid_set_self_loops_forever():
    """pop_tol=0 with an exactly balanced plan makes every flip invalid:
    the single masked draw must self-loop (exhausted), never commit."""
    g = fce.graphs.square_grid(6, 6)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(contiguity="patch")
    bg, st, params = fce.sampling.init_board(
        g, plan, n_chains=4, seed=2, spec=spec, base=1.0, pop_tol=0.0)
    res = fce.sampling.run_board(bg, spec, params, st, n_steps=51)
    s = res.host_state()
    np.testing.assert_array_equal(np.asarray(s.board),
                                  np.broadcast_to(plan, (4, 36)))
    assert (np.asarray(s.accept_count) == 0).all()
    assert (np.asarray(s.exhausted_count) == 50).all()
    # histories are constant at the initial values
    assert (res.history["cut_count"] == res.history["cut_count"][:, :1]).all()


@pytest.mark.parametrize(
    "mode", [pytest.param("corrected", marks=pytest.mark.slow), "anneal"])
def test_board_matches_general_path_extended_modes(mode):
    """Corrected (reversibility-ratio) acceptance and the reference's
    linear annealing schedule agree across paths."""
    grid, chains, steps = 8, 48, 2501
    g = fce.graphs.square_grid(grid, grid)
    plan = fce.graphs.stripes_plan(g, 2)
    if mode == "corrected":
        spec = fce.Spec(contiguity="patch", accept="corrected")
        kw = dict(base=1.4, pop_tol=0.2)
        mk = dict()
    else:
        spec = fce.Spec(contiguity="patch", anneal="linear")
        kw = dict(base=2.0, pop_tol=0.3)
        mk = dict()

    def params_for(p):
        if mode == "anneal":
            # schedule ramps within the run so the annealing is active
            return p.replace(anneal_t0=jnp.float32(200.0),
                             anneal_ramp=jnp.float32(400.0),
                             anneal_beta_max=jnp.float32(2.0))
        return p

    dg, st_g, par_g = fce.init_batch(g, plan, n_chains=chains, seed=21,
                                     spec=spec, **kw)
    res_g = fce.run_chains(dg, spec, params_for(par_g), st_g,
                           n_steps=steps)
    bg, st_b, par_b = fce.sampling.init_board(
        g, plan, n_chains=chains, seed=31, spec=spec, **kw)
    res_b = fce.sampling.run_board(bg, spec, params_for(par_b), st_b,
                                   n_steps=steps)

    sub = slice(800, None, 25)
    for key in ("cut_count", "b_count"):
        a = res_g.history[key][:, sub].ravel().astype(float)
        b = res_b.history[key][:, sub].ravel().astype(float)
        ks = ks_stat(a, b)
        assert ks < 0.08, f"{mode}/{key} KS {ks:.4f}"
        assert abs(a.mean() - b.mean()) / a.mean() < 0.04, (
            mode, key, a.mean(), b.mean())
    aa = np.asarray(res_g.state.accept_count).mean()
    ab = np.asarray(res_b.state.accept_count).mean()
    assert abs(aa - ab) / aa < 0.06, (mode, aa, ab)
