"""Dual-graph importer tests: GeoJSON parsing, rook/queen adjacency,
geometry attributes, compactness scores, and a k-district chain on a real
(synthetic) precinct geometry with the boundary-length-weighted target."""

import numpy as np
import networkx as nx
import pytest

import flipcomplexityempirical_tpu as fce
from flipcomplexityempirical_tpu import graphs, stats


def test_synthetic_precincts_rook_is_grid():
    gj = graphs.synthetic_precincts(5, 4, seed=1)
    assert len(gj["features"]) == 20
    g, geo = graphs.from_geojson(gj, pop_property="POP",
                                 name_property="NAME")
    # rook adjacency of a jittered quad grid == the 5x4 grid graph
    assert g.n_nodes == 20
    assert g.n_edges == 5 * 3 + 4 * 4  # (nx*(ny-1) + (nx-1)*ny)
    gx = nx.Graph(list(map(tuple, g.edges)))
    ref = nx.grid_2d_graph(5, 4)
    assert nx.is_isomorphic(gx, ref)
    # populations forwarded
    assert g.pop.min() >= 80 and g.pop.max() <= 120
    # labels preserved
    assert "p0_0" in g.labels


def test_geometry_attributes_consistency():
    gj = graphs.synthetic_precincts(4, 4, seed=2, jitter=0.0)
    g, geo = graphs.from_geojson(gj)
    # unit squares: area 1, perimeter 4
    assert np.allclose(geo.area, 1.0)
    assert np.allclose(geo.perimeter, 4.0)
    # every interior edge shares a unit segment
    assert np.allclose(geo.shared_perim, 1.0)
    # exterior perimeter: corners 2, edges 1, interior 0
    n_corner = (np.isclose(geo.exterior_perim, 2.0)).sum()
    n_side = (np.isclose(geo.exterior_perim, 1.0)).sum()
    n_int = (np.isclose(geo.exterior_perim, 0.0)).sum()
    assert (n_corner, n_side, n_int) == (4, 8, 4)
    # total exterior == bounding square perimeter
    assert np.isclose(geo.exterior_perim.sum(), 16.0)
    # edge_len attached to the graph for weighted-cut chains
    assert np.allclose(g.edge_len, 1.0)


def test_queen_adjacency_supersets_rook():
    gj = graphs.synthetic_precincts(4, 3, seed=3, jitter=0.0)
    g_rook, _ = graphs.from_geojson(gj, adjacency="rook")
    g_queen, _ = graphs.from_geojson(gj, adjacency="queen")
    rook_edges = {tuple(e) for e in g_rook.edges.tolist()}
    queen_edges = {tuple(e) for e in g_queen.edges.tolist()}
    assert rook_edges < queen_edges
    # queen adds the diagonal contacts: 2 per interior vertex
    assert len(queen_edges) == len(rook_edges) + 2 * 3 * 2


def test_polsby_popper_on_synthetic_state():
    gj = graphs.synthetic_precincts(6, 6, seed=4, jitter=0.0)
    g, geo = graphs.from_geojson(gj, pop_property="POP")
    # vertical split into 2 districts of 3 columns each: each district is
    # a 3x6 rectangle => PP = 4*pi*18 / 18^2
    plan = graphs.stripes_plan(g, 2)
    pp = stats.polsby_popper(
        plan, 2, edges=g.edges, shared_perim=geo.shared_perim,
        node_area=geo.area, node_exterior_perim=geo.exterior_perim)
    expect = 4 * np.pi * 18.0 / (18.0 ** 2)
    assert np.allclose(pp, expect, rtol=1e-6)


def test_weighted_cut_chain_on_precinct_graph():
    # full pipeline: jittered geometry -> dual graph -> weighted-cut chain
    gj = graphs.synthetic_precincts(8, 8, seed=5, jitter=0.2)
    g, geo = graphs.from_geojson(gj, pop_property="POP")
    assert not np.allclose(g.edge_len, g.edge_len[0])  # lengths vary
    plan = graphs.stripes_plan(g, 2)
    spec = fce.Spec(weighted_cut=True)
    dg, states, params = fce.init_batch(
        g, plan, n_chains=8, seed=0, spec=spec, base=8.0, pop_tol=0.4)
    res = fce.run_chains(dg, spec, params, states, n_steps=400)
    s = res.host_state()
    # strongly compactness-favoring base: boundary length must not blow up
    def blen(a):
        cut = a[g.edges[:, 0]] != a[g.edges[:, 1]]
        return (geo.shared_perim * cut).sum()
    init_len = blen(np.asarray(plan))
    final = np.array([blen(np.asarray(s.assignment)[c]) for c in range(8)])
    assert (final <= init_len * 1.5 + 1e-6).all()
    # chains stayed valid: connected districts, pop within bounds
    gx = nx.Graph(list(map(tuple, g.edges)))
    ideal = g.pop.sum() / 2
    for c in range(8):
        a = np.asarray(s.assignment)[c]
        for d in (0, 1):
            assert nx.is_connected(gx.subgraph(np.nonzero(a == d)[0].tolist()))
            pd = g.pop[a == d].sum()
            assert (1 - 0.4) * ideal - 1e-6 <= pd <= (1 + 0.4) * ideal + 1e-6


def test_from_geojson_accepts_string_and_multipolygon():
    gj = graphs.synthetic_precincts(3, 3, seed=6)
    # wrap one feature as a MultiPolygon; parse from a JSON string
    f0 = gj["features"][0]
    f0["geometry"] = {
        "type": "MultiPolygon",
        "coordinates": [f0["geometry"]["coordinates"]],
    }
    import json
    g, geo = graphs.from_geojson(json.dumps(gj))
    assert g.n_nodes == 9
    assert g.n_edges == 12


def test_duplicate_labels_raise():
    gj = graphs.synthetic_precincts(3, 3, seed=7)
    gj["features"][1]["properties"]["NAME"] = "p0_0"  # collide with f0
    with pytest.raises(ValueError, match="not unique"):
        graphs.from_geojson(gj, name_property="NAME")


def test_recom_rejects_unknown_pop_col():
    from flipcomplexityempirical_tpu import compat
    with pytest.raises(ValueError, match="pop_col"):
        compat.make_recom(np.random.default_rng(0), pop_col="VAP")


def test_voronoi_precincts_geometry_and_topology():
    """The irregular-topology generator: cells tile the bounding box
    exactly (areas sum to width*height, no overlaps by construction),
    rook adjacency is connected with varied degrees, and the scipy
    Delaunay dual is a superset sanity check: every rook edge joins
    cells whose generators are Delaunay neighbors of the mirrored
    tessellation."""
    n = 40
    fc = graphs.voronoi_precincts(n, seed=5)
    g, geo = graphs.from_geojson(fc, pop_property="POP",
                                 name_property="NAME")
    assert g.n_nodes == n
    nx_side = int(np.ceil(np.sqrt(n)))
    ny_side = int(np.ceil(n / nx_side))
    assert np.isclose(geo.area.sum(), nx_side * ny_side)
    assert nx.is_connected(nx.Graph(list(map(tuple, g.edges))))
    assert g.deg.max() > 4 > g.deg.min()  # irregular, unlike quad grids
    # every shared boundary has positive length; exterior cells carry
    # exterior perimeter, interior cells none
    assert (g.edge_len > 0).all()
    assert np.isclose(geo.exterior_perim.sum(),
                      2 * (nx_side + ny_side), atol=1e-6)


def test_shapefile_roundtrip_matches_geojson():
    """write_shapefile -> from_shapefile reproduces from_geojson exactly
    on the same FeatureCollection: dual graph, geometry attributes, and
    attribute table (N/C dBase fields) all survive the binary format."""
    import tempfile, os
    fc = graphs.voronoi_precincts(30, seed=11)
    # exercise a float column and a hole-free multipart feature too
    for i, f in enumerate(fc["features"]):
        f["properties"]["WEIGHT"] = 0.25 + i / 16.0
    g1, geo1 = graphs.from_geojson(fc, pop_property="POP",
                                   name_property="NAME")
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "state")
        graphs.write_shapefile(path, fc)
        assert sorted(os.listdir(d)) == ["state.dbf", "state.shp",
                                         "state.shx"]
        g2, geo2 = graphs.from_shapefile(path, pop_property="POP",
                                         name_property="NAME")
        fc2 = graphs.read_shapefile(path)
    assert g2.labels == g1.labels
    assert np.array_equal(g2.edges, g1.edges)
    assert np.array_equal(np.asarray(g2.pop), np.asarray(g1.pop))
    np.testing.assert_allclose(geo2.area, geo1.area, rtol=1e-12)
    np.testing.assert_allclose(geo2.shared_perim, geo1.shared_perim,
                               rtol=1e-12)
    # dBase numeric columns survive with their declared types
    p0 = fc2["features"][0]["properties"]
    assert isinstance(p0["POP"], int)
    assert isinstance(p0["WEIGHT"], float)
    assert p0["NAME"] == "v0"


def test_shapefile_reader_rejects_non_polygon_and_bad_magic():
    import struct, tempfile, os
    with tempfile.TemporaryDirectory() as d:
        bad = os.path.join(d, "bad.shp")
        with open(bad, "wb") as f:
            f.write(struct.pack(">i", 1234) + b"\x00" * 96)
        with pytest.raises(ValueError, match="file code"):
            graphs.read_shapefile(bad)
        # a valid header with point type (1) must be refused up front
        pt = os.path.join(d, "pt.shp")
        hdr = struct.pack(">i5ii", 9994, 0, 0, 0, 0, 0, 50)
        hdr += struct.pack("<ii", 1000, 1) + struct.pack("<8d", *([0.0] * 8))
        with open(pt, "wb") as f:
            f.write(hdr)
        with pytest.raises(ValueError, match="polygon"):
            graphs.read_shapefile(pt)


@pytest.mark.slow
def test_weighted_cut_chain_on_voronoi_state():
    """BASELINE config 5 on the realistic-topology stand-in: a k=4
    boundary-length-weighted chain on the Voronoi state runs end to end
    under the general kernel, preserving contiguity and population
    bounds (the same path a real shapefile's dual graph takes)."""
    fc = graphs.voronoi_precincts(48, seed=2)
    g, geo = graphs.from_geojson(fc, pop_property="POP",
                                 name_property="NAME")
    k = 4
    plan = graphs.stripes_plan(g, k)
    spec = fce.Spec(n_districts=k, proposal="pair", accept="cut",
                    contiguity="exact", weighted_cut=True,
                    invalid="repropose", parity_metrics=False,
                    geom_waits=False)
    dg, st, params = fce.init_batch(g, plan, n_chains=8, seed=0,
                                    spec=spec, base=1.5, pop_tol=0.5)
    res = fce.run_chains(dg, spec, params, st, n_steps=201,
                         record_history=True)
    s = res.host_state()
    a = np.asarray(s.assignment)
    gx = nx.Graph(list(map(tuple, g.edges)))
    pops = np.asarray(g.pop)
    for c in range(a.shape[0]):
        for d_ in range(k):
            members = np.flatnonzero(a[c] == d_)
            assert members.size, f"chain {c} district {d_} vanished"
            assert nx.is_connected(gx.subgraph(members.tolist()))
        tal = np.bincount(a[c], weights=pops, minlength=k)
        ideal = pops.sum() / k
        assert (np.abs(tal - ideal) <= 0.5 * ideal + 1e-9).all()


def test_shapefile_bool_and_deleted_rows():
    """Review findings: booleans must round-trip as dBase L fields (not
    the unparseable text 'True' in an N column), and rows soft-deleted
    by dBase tools (flag '*') must stay in the table so the mandatory
    1:1 shp/dbf row alignment survives."""
    import tempfile, os
    fc = graphs.voronoi_precincts(9, seed=4)
    for i, f in enumerate(fc["features"]):
        f["properties"]["URBAN"] = bool(i % 2)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "s")
        graphs.write_shapefile(path, fc)
        fc2 = graphs.read_shapefile(path)
        assert [f["properties"]["URBAN"] for f in fc2["features"]] \
            == [bool(i % 2) for i in range(9)]
        # soft-delete row 3 the way a dBase tool would: flip its flag
        import struct
        with open(path + ".dbf", "r+b") as fh:
            buf = fh.read()
            hs, rs = struct.unpack_from("<HH", buf, 8)
            fh.seek(hs + 3 * rs)
            fh.write(b"*")
        fc3 = graphs.read_shapefile(path)
        assert len(fc3["features"]) == 9          # alignment preserved
        g3, _ = graphs.from_geojson(fc3, pop_property="POP",
                                    name_property="NAME")
        assert g3.n_nodes == 9


def test_shapefile_truncated_files_fail_loudly():
    """Truncated .shp/.dbf must raise a clear ValueError naming the file,
    not a cryptic struct/index error from parser internals."""
    import tempfile, os
    fc = graphs.voronoi_precincts(12, seed=1)
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "s")
        graphs.write_shapefile(p, fc)
        shp = open(p + ".shp", "rb").read()
        dbf = open(p + ".dbf", "rb").read()
        for ext, full, cut in ((".shp", shp, 50), (".shp", shp, 150),
                               (".dbf", dbf, 20), (".dbf", dbf, 40)):
            with open(p + ext, "wb") as f:
                f.write(full[:cut])
            with pytest.raises(ValueError, match="truncated|inconsistent"):
                graphs.read_shapefile(p)
            with open(p + ext, "wb") as f:
                f.write(full)
        # cut exactly at a record boundary: the header's declared
        # length must catch it (review finding: the per-record guard
        # alone lets this silently return a prefix of the features)
        import struct
        pos, cuts = 100, []
        while pos + 8 <= len(shp):
            _, cw = struct.unpack_from(">ii", shp, pos)
            pos += 8 + 2 * cw
            cuts.append(pos)
        with open(p + ".shp", "wb") as f:
            f.write(shp[:cuts[2]])
        with pytest.raises(ValueError, match="truncated"):
            graphs.read_shapefile(p)
        with open(p + ".shp", "wb") as f:
            f.write(shp)
        # corrupt dbf header fabricating records: rec_size=0 must be
        # refused, not loop n_rec times over an unmoving cursor
        bad = bytearray(dbf)
        struct.pack_into("<I", bad, 4, 10**6)
        struct.pack_into("<H", bad, 10, 0)
        with open(p + ".dbf", "wb") as f:
            f.write(bytes(bad))
        with pytest.raises(ValueError, match="corrupt"):
            graphs.read_shapefile(p)
        with open(p + ".dbf", "wb") as f:
            f.write(dbf)
        # intact again after restores
        assert len(graphs.read_shapefile(p)["features"]) == 12
