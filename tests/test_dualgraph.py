"""Dual-graph importer tests: GeoJSON parsing, rook/queen adjacency,
geometry attributes, compactness scores, and a k-district chain on a real
(synthetic) precinct geometry with the boundary-length-weighted target."""

import numpy as np
import networkx as nx
import pytest

import flipcomplexityempirical_tpu as fce
from flipcomplexityempirical_tpu import graphs, stats


def test_synthetic_precincts_rook_is_grid():
    gj = graphs.synthetic_precincts(5, 4, seed=1)
    assert len(gj["features"]) == 20
    g, geo = graphs.from_geojson(gj, pop_property="POP",
                                 name_property="NAME")
    # rook adjacency of a jittered quad grid == the 5x4 grid graph
    assert g.n_nodes == 20
    assert g.n_edges == 5 * 3 + 4 * 4  # (nx*(ny-1) + (nx-1)*ny)
    gx = nx.Graph(list(map(tuple, g.edges)))
    ref = nx.grid_2d_graph(5, 4)
    assert nx.is_isomorphic(gx, ref)
    # populations forwarded
    assert g.pop.min() >= 80 and g.pop.max() <= 120
    # labels preserved
    assert "p0_0" in g.labels


def test_geometry_attributes_consistency():
    gj = graphs.synthetic_precincts(4, 4, seed=2, jitter=0.0)
    g, geo = graphs.from_geojson(gj)
    # unit squares: area 1, perimeter 4
    assert np.allclose(geo.area, 1.0)
    assert np.allclose(geo.perimeter, 4.0)
    # every interior edge shares a unit segment
    assert np.allclose(geo.shared_perim, 1.0)
    # exterior perimeter: corners 2, edges 1, interior 0
    n_corner = (np.isclose(geo.exterior_perim, 2.0)).sum()
    n_side = (np.isclose(geo.exterior_perim, 1.0)).sum()
    n_int = (np.isclose(geo.exterior_perim, 0.0)).sum()
    assert (n_corner, n_side, n_int) == (4, 8, 4)
    # total exterior == bounding square perimeter
    assert np.isclose(geo.exterior_perim.sum(), 16.0)
    # edge_len attached to the graph for weighted-cut chains
    assert np.allclose(g.edge_len, 1.0)


def test_queen_adjacency_supersets_rook():
    gj = graphs.synthetic_precincts(4, 3, seed=3, jitter=0.0)
    g_rook, _ = graphs.from_geojson(gj, adjacency="rook")
    g_queen, _ = graphs.from_geojson(gj, adjacency="queen")
    rook_edges = {tuple(e) for e in g_rook.edges.tolist()}
    queen_edges = {tuple(e) for e in g_queen.edges.tolist()}
    assert rook_edges < queen_edges
    # queen adds the diagonal contacts: 2 per interior vertex
    assert len(queen_edges) == len(rook_edges) + 2 * 3 * 2


def test_polsby_popper_on_synthetic_state():
    gj = graphs.synthetic_precincts(6, 6, seed=4, jitter=0.0)
    g, geo = graphs.from_geojson(gj, pop_property="POP")
    # vertical split into 2 districts of 3 columns each: each district is
    # a 3x6 rectangle => PP = 4*pi*18 / 18^2
    plan = graphs.stripes_plan(g, 2)
    pp = stats.polsby_popper(
        plan, 2, edges=g.edges, shared_perim=geo.shared_perim,
        node_area=geo.area, node_exterior_perim=geo.exterior_perim)
    expect = 4 * np.pi * 18.0 / (18.0 ** 2)
    assert np.allclose(pp, expect, rtol=1e-6)


def test_weighted_cut_chain_on_precinct_graph():
    # full pipeline: jittered geometry -> dual graph -> weighted-cut chain
    gj = graphs.synthetic_precincts(8, 8, seed=5, jitter=0.2)
    g, geo = graphs.from_geojson(gj, pop_property="POP")
    assert not np.allclose(g.edge_len, g.edge_len[0])  # lengths vary
    plan = graphs.stripes_plan(g, 2)
    spec = fce.Spec(weighted_cut=True)
    dg, states, params = fce.init_batch(
        g, plan, n_chains=8, seed=0, spec=spec, base=8.0, pop_tol=0.4)
    res = fce.run_chains(dg, spec, params, states, n_steps=400)
    s = res.host_state()
    # strongly compactness-favoring base: boundary length must not blow up
    def blen(a):
        cut = a[g.edges[:, 0]] != a[g.edges[:, 1]]
        return (geo.shared_perim * cut).sum()
    init_len = blen(np.asarray(plan))
    final = np.array([blen(np.asarray(s.assignment)[c]) for c in range(8)])
    assert (final <= init_len * 1.5 + 1e-6).all()
    # chains stayed valid: connected districts, pop within bounds
    gx = nx.Graph(list(map(tuple, g.edges)))
    ideal = g.pop.sum() / 2
    for c in range(8):
        a = np.asarray(s.assignment)[c]
        for d in (0, 1):
            assert nx.is_connected(gx.subgraph(np.nonzero(a == d)[0].tolist()))
            pd = g.pop[a == d].sum()
            assert (1 - 0.4) * ideal - 1e-6 <= pd <= (1 + 0.4) * ideal + 1e-6


def test_from_geojson_accepts_string_and_multipolygon():
    gj = graphs.synthetic_precincts(3, 3, seed=6)
    # wrap one feature as a MultiPolygon; parse from a JSON string
    f0 = gj["features"][0]
    f0["geometry"] = {
        "type": "MultiPolygon",
        "coordinates": [f0["geometry"]["coordinates"]],
    }
    import json
    g, geo = graphs.from_geojson(json.dumps(gj))
    assert g.n_nodes == 9
    assert g.n_edges == 12


def test_duplicate_labels_raise():
    gj = graphs.synthetic_precincts(3, 3, seed=7)
    gj["features"][1]["properties"]["NAME"] = "p0_0"  # collide with f0
    with pytest.raises(ValueError, match="not unique"):
        graphs.from_geojson(gj, name_property="NAME")


def test_recom_rejects_unknown_pop_col():
    from flipcomplexityempirical_tpu import compat
    with pytest.raises(ValueError, match="pop_col"):
        compat.make_recom(np.random.default_rng(0), pop_col="VAP")
