"""general_dense kernel tests (ISSUE 15): bit-packed selection unit
tests, the patch-ball symmetry the incremental conn plane relies on,
incremental-vs-full conn recompute, the reject-accounting invariant on
the rejection-free path, and the exact-enumeration chi2 bars proving
general_dense matches the legacy oracle's law on a small hex graph and
a <=12-node dual-graph slice (both slow-marked, like the lowered-path
chi2 bars in test_lower.py)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import flipcomplexityempirical_tpu as fce
from flipcomplexityempirical_tpu import lower
from flipcomplexityempirical_tpu.graphs import dualgraph
from flipcomplexityempirical_tpu.kernel import dense as kdense
from flipcomplexityempirical_tpu.kernel import step as kstep


def _dense_spec(**kw):
    kw.setdefault("n_districts", 2)
    kw.setdefault("proposal", "bi")
    kw.setdefault("contiguity", "patch")
    kw.setdefault("geom_waits", False)
    kw.setdefault("parity_metrics", False)
    return fce.Spec(**kw)


def _dual_slice(n=12, seed=3):
    """A <=12-node precinct dual-graph slice through the production
    from_geojson ingestion path (unit populations, like the reference's
    unit weights)."""
    g, _geo = dualgraph.from_geojson(
        dualgraph.voronoi_precincts(n, seed=seed), name=f"vor{n}")
    assert g.n_nodes == n
    return g


# --- packed node-set primitives -------------------------------------------

def test_pack_unpack_roundtrip():
    rng = np.random.default_rng(0)
    for n in (1, 31, 32, 33, 80, 100):
        mask = rng.random(n) < 0.4
        words = kdense.pack_mask(jnp.asarray(mask))
        assert words.shape == (kdense.n_words(n),)
        assert words.dtype == jnp.uint32
        back = np.asarray(kdense.unpack_mask(words, n))
        np.testing.assert_array_equal(back, mask)
        # pad bits are zero: the packed plane AND-composes safely
        total = int(np.asarray(
            jax.lax.population_count(words).astype(jnp.int32)).sum())
        assert total == int(mask.sum())


def test_select_nth_set_matches_numpy():
    rng = np.random.default_rng(1)
    for n in (5, 32, 33, 95):
        mask = rng.random(n) < 0.3
        if not mask.any():
            mask[n // 2] = True
        words = kdense.pack_mask(jnp.asarray(mask))
        set_idx = np.nonzero(mask)[0]
        for m in range(len(set_idx)):
            got = int(kdense.select_nth_set(words, jnp.int32(m)))
            assert got == int(set_idx[m]), (n, m)


def test_select_nth_set_word_boundary():
    # bit 31 exercises the (2 << lane) - 1 uint32 wrap in the in-word
    # prefix popcount
    mask = np.zeros(64, bool)
    mask[[31, 32, 63]] = True
    words = kdense.pack_mask(jnp.asarray(mask))
    assert [int(kdense.select_nth_set(words, jnp.int32(m)))
            for m in range(3)] == [31, 32, 63]


# --- the incremental conn plane -------------------------------------------

def test_patch_ball_symmetry():
    """u in patch(v) iff v in patch(u) — what makes {v} | patch(v) the
    complete refresh set after a flip at v (dense.py's refresh
    invariant), on both the hex lattice and the dual fixture slice."""
    for g in (fce.graphs.hex_lattice(4, 4), _dual_slice()):
        dg = g.device()
        pn = np.asarray(dg.patch_nodes)
        n = dg.n_nodes
        members = [set(pn[v]) - {v} for v in range(n)]
        for v in range(n):
            for u in members[v]:
                assert v in members[u], (g.name, v, u)


def test_refresh_matches_full_recompute():
    """After a real dense run, the incrementally-maintained conn_bits
    equal a from-scratch conn_plane recompute of the final assignment."""
    for g in (fce.graphs.hex_lattice(4, 4), _dual_slice()):
        spec = _dense_spec()
        plan = fce.graphs.stripes_plan(g, 2)
        dg, st, params = fce.init_batch(g, plan, n_chains=8, seed=5,
                                        spec=spec, base=1.5, pop_tol=0.4)
        st = kdense.ensure_conn_bits(dg, spec, st)
        res = fce.run_chains(dg, spec, params, st, n_steps=301,
                             record_history=False,
                             kernel_path="general_dense")
        # run_chains strips conn_bits on exit only when it attached them;
        # we attached them ourselves, so they ride out for inspection
        final = res.state
        assert final.conn_bits is not None
        full = jax.jit(jax.vmap(
            lambda a: kdense.init_conn_bits(dg, spec, a)))(final.assignment)
        np.testing.assert_array_equal(np.asarray(final.conn_bits),
                                      np.asarray(full), err_msg=g.name)


def test_conn_bits_stripped_on_exit():
    g = fce.graphs.hex_lattice(4, 4)
    spec = _dense_spec()
    plan = fce.graphs.stripes_plan(g, 2)
    dg, st, params = fce.init_batch(g, plan, n_chains=4, seed=0,
                                    spec=spec, base=1.5, pop_tol=0.4)
    assert lower.kernel_path_for(g, spec) == "general_dense"
    res = fce.run_chains(dg, spec, params, st, n_steps=50,
                         record_history=False)
    assert res.state.conn_bits is None


# --- supported() gating ----------------------------------------------------

def test_supported_gates():
    g = fce.graphs.hex_lattice(4, 4)
    assert kdense.supported(g, _dense_spec())
    assert kdense.supported(g, _dense_spec(contiguity="none"))
    assert kdense.supported(
        g, _dense_spec(n_districts=4, proposal="pair"))
    # out: one-draw selfloop walk, global frame plane, exact contiguity,
    # nobacktrack on the pair walk
    assert not kdense.supported(g, _dense_spec(invalid="selfloop"))
    assert not kdense.supported(g, _dense_spec(contiguity="exact"))
    assert not kdense.supported(
        g, _dense_spec(n_districts=4, proposal="pair", nobacktrack=True))


# --- reject accounting on the rejection-free path --------------------------

def test_reject_accounting_invariant():
    """rejects + accepts == proposals: with tries == 1 per dense step,
    every draw is either accepted or attributed exactly one reject
    taxon (nonboundary/pop/disconnect for a zero-valid self-loop,
    metropolis for a coin reject)."""
    for g in (fce.graphs.hex_lattice(4, 4), _dual_slice()):
        spec = _dense_spec()
        plan = fce.graphs.stripes_plan(g, 2)
        # tight pop bounds so zero-valid self-loops actually happen
        dg, st, params = fce.init_batch(g, plan, n_chains=16, seed=11,
                                        spec=spec, base=2.0, pop_tol=0.1)
        n_chains = 16
        st = st.replace(reject_count=jnp.zeros((n_chains, 4), jnp.int32))
        steps = 400
        res = fce.run_chains(dg, spec, params, st, n_steps=steps,
                             record_history=False,
                             kernel_path="general_dense")
        s = res.state
        rej = np.asarray(s.reject_count, np.int64)
        acc = np.asarray(s.accept_count, np.int64)
        tries = np.asarray(s.tries_sum, np.int64)
        # per chain, not just in aggregate
        np.testing.assert_array_equal(rej.sum(axis=1) + acc, tries)
        # the dense path consumes exactly one draw per transition
        # (n_steps yields include the initial state: steps - 1 draws)
        np.testing.assert_array_equal(tries, np.full(n_chains, steps - 1))
        exh = np.asarray(s.exhausted_count, np.int64)
        np.testing.assert_array_equal(rej[:, :3].sum(axis=1), exh)


# --- exact-enumeration chi2 vs the legacy oracle ---------------------------

def _valid_plane_fn(dg, spec, params):
    """bool[N] valid-move plane for the bi walk under the repo's OWN
    patch-contiguity semantics — the law both general bodies implement."""
    pop_lo = float(np.asarray(params.pop_lo)[0])
    pop_hi = float(np.asarray(params.pop_hi)[0])
    pops = np.asarray(dg.pop, np.float64)
    nbr = np.asarray(dg.nbr)
    nbm = np.asarray(dg.nbr_mask)

    conn_jit = jax.jit(lambda a: kdense.conn_plane(dg, spec, a))

    def plane(a):
        a = np.asarray(a, np.int8)
        boundary = ((a[nbr] != a[:, None]) & nbm).any(axis=1)
        dist_pop = np.array([pops[a == 0].sum(), pops[a == 1].sum()])
        pop_ok = ((dist_pop[a] - pops) >= pop_lo) \
            & ((dist_pop[1 - a] + pops) <= pop_hi)
        conn = np.asarray(conn_jit(jnp.asarray(a)))
        return boundary & pop_ok & conn

    return plane


def _closure_and_matrix(g, dg, spec, params, a0, base):
    """BFS the state closure from a0 under the patch-law valid moves and
    build the literal transition matrix of 'uniform over the valid set,
    Metropolis cut accept' — the exact law of BOTH general bodies."""
    n = g.n_nodes
    plane = _valid_plane_fn(dg, spec, params)
    edges = np.asarray(g.edges)

    def mask_of(a):
        return int((a.astype(np.uint64) << np.arange(n, dtype=np.uint64))
                   .sum())

    def arr_of(m):
        return np.array([(m >> v) & 1 for v in range(n)], np.int8)

    seen = {}
    order = []
    frontier = [mask_of(np.asarray(a0, np.int8))]
    seen[frontier[0]] = 0
    order.append(frontier[0])
    moves_of = {}
    while frontier:
        m = frontier.pop()
        a = arr_of(m)
        valid = np.nonzero(plane(a))[0]
        moves_of[m] = valid
        for v in valid:
            m2 = m ^ (1 << int(v))
            if m2 not in seen:
                seen[m2] = len(order)
                order.append(m2)
                frontier.append(m2)
    cuts = np.array([
        int((arr_of(m)[edges[:, 0]] != arr_of(m)[edges[:, 1]]).sum())
        for m in order])
    P = np.zeros((len(order), len(order)))
    for i, m in enumerate(order):
        valid = moves_of[m]
        V = len(valid)
        assert V > 0, "absorbing state in the enumeration closure"
        stay = 0.0
        for v in valid:
            j = seen[m ^ (1 << int(v))]
            acc = min(1.0, base ** float(cuts[i] - cuts[j]))
            P[i, j] += acc / V
            stay += (1 - acc) / V
        P[i, i] += stay
    assert np.allclose(P.sum(axis=1), 1.0)
    pi = np.full(len(order), 1.0 / len(order))
    for _ in range(50000):
        nxt = pi @ P
        if np.abs(nxt - pi).max() < 1e-13:
            break
        pi = nxt
    return seen, pi / pi.sum()


def _chi2_both_paths(g, base=1.4, pop_tol=0.5, chains=48, steps=12000,
                     burn=2000, stride=25, seed=23):
    spec = _dense_spec(record_assignment_bits=True)
    plan = fce.graphs.stripes_plan(g, 2)
    dg, st, params = fce.init_batch(g, plan, n_chains=chains, seed=seed,
                                    spec=spec, base=base, pop_tol=pop_tol)
    index, pi = _closure_and_matrix(g, dg, spec, params, plan, base)
    assert len(index) > 20, f"state space too small ({len(index)})"
    for path in ("general_dense", "general"):
        res = fce.run_chains(dg, spec, params, st, n_steps=steps,
                             kernel_path=path)
        abits = np.asarray(res.history["abits"])[:, burn::stride].ravel()
        # KeyError here = the kernel left the enumerated closure
        idx = np.array([index[int(m)] for m in abits])
        emp = np.bincount(idx, minlength=len(pi)).astype(float)
        tot = emp.sum()
        exp = pi * tot
        chi2 = float((((emp - exp) ** 2) / exp).sum())
        df = len(pi) - 1
        assert chi2 < df + 6.0 * np.sqrt(2.0 * df), \
            f"{g.name}/{path}: chi2 {chi2:.1f} vs df {df} (|S|={len(pi)})"


@pytest.mark.slow
def test_dense_matches_exact_stationary_chi2_hex():
    """The exact-enumeration bar on a small hex graph: general_dense and
    the legacy oracle both match the power-iterated stationary law of
    the literal uniform-over-valid + Metropolis transition matrix."""
    g = fce.graphs.hex_lattice(1, 2)
    assert g.n_nodes == 10
    spec = _dense_spec(record_assignment_bits=True)
    assert lower.kernel_path_for(g, spec) == "general_dense"
    _chi2_both_paths(g)


@pytest.mark.slow
def test_dense_matches_exact_stationary_chi2_dual_slice():
    """The same bar on a 12-node precinct dual-graph slice ingested
    through from_geojson (irregular degrees, real dual topology)."""
    g = _dual_slice()
    spec = _dense_spec(record_assignment_bits=True)
    assert lower.kernel_path_for(g, spec) == "general_dense"
    _chi2_both_paths(g)


# --- the CI gate wrapper --------------------------------------------------

@pytest.mark.slow
def test_dense_check_gate_passes():
    """make dense-check: graftlint + chi2 smoke + the >=2x CPU hex
    microbench + the compile-fault degradation leg as one script. Slow
    tier (the microbench alone is ~25s of steady-state timing); running
    it here keeps the gate from rotting silently."""
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHON=sys.executable)
    r = subprocess.run(
        ["bash", os.path.join(repo, "tools", "dense_check.sh")],
        capture_output=True, text=True, env=env, timeout=900)
    assert r.returncode == 0, r.stdout + "\n" + r.stderr
    assert "dense-check: OK" in r.stdout
