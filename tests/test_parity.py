"""Cross-backend distributional parity (SURVEY.md section 4.3): the
vectorized kernel and the pure-Python oracle implement the same chain, so
their trajectory statistics must agree — compared via subsampled KS
statistics and moment ratios (RNG parity is impossible; SURVEY section 7.3
item 4)."""

import numpy as np
import pytest

import flipcomplexityempirical_tpu as fce
from flipcomplexityempirical_tpu import compat


BASE, EPS, STEPS = 0.7, 0.3, 6000
BURN = 1000


def oracle_trajectory(lat, seed):
    rng = np.random.default_rng(seed)
    plan = fce.graphs.stripes_plan(lat, 2)
    signed = {lab: 1 - 2 * int(plan[i]) for i, lab in enumerate(lat.labels)}
    updaters = {"population": compat.Tally("population"),
                "cut_edges": compat.cut_edges,
                "b_nodes": compat.b_nodes_bi,
                "base": lambda p: BASE,
                "geom": compat.make_geom_wait(rng)}
    part = compat.Partition(lat, signed, updaters)
    popbound = compat.within_percent_of_ideal_population(part, EPS)
    chain = compat.MarkovChain(
        compat.make_reversible_propose_bi(rng),
        compat.Validator([compat.single_flip_contiguous, popbound]),
        compat.make_cut_accept(rng), part, STEPS)
    cuts, bs, waits = [], [], []
    for p in chain:
        cuts.append(len(p["cut_edges"]))
        bs.append(len(p["b_nodes"]))
        waits.append(p["geom"])
    return (np.array(cuts[BURN:]), np.array(bs[BURN:]),
            np.array(waits[BURN:], dtype=float))


def kernel_trajectories(lat, seed, chains=8):
    plan = fce.graphs.stripes_plan(lat, 2)
    spec = fce.Spec(contiguity="exact")  # gerrychain-exact semantics
    dg, st, params = fce.init_batch(lat, plan, n_chains=chains, seed=seed,
                                    spec=spec, base=BASE, pop_tol=EPS)
    res = fce.run_chains(dg, spec, params, st, n_steps=STEPS)
    return (res.history["cut_count"][:, BURN:],
            res.history["b_count"][:, BURN:],
            res.history["wait"][:, BURN:])


def ks_stat(x, y):
    xs = np.sort(x)
    ys = np.sort(y)
    grid = np.concatenate([xs, ys])
    fx = np.searchsorted(xs, grid, side="right") / len(xs)
    fy = np.searchsorted(ys, grid, side="right") / len(ys)
    return np.abs(fx - fy).max()


def oracle_pair_trajectory(lat, k, seed):
    """The k-district pair walk on the compat oracle: b_nodes is the PAIR
    set (grid_chain_sec11.py:151-153) feeding both the proposal and
    geom_wait's p = |b_nodes| / (n**k - 1)."""
    rng = np.random.default_rng(seed)
    plan = fce.graphs.stripes_plan(lat, k)
    assign = {lab: int(plan[i]) for i, lab in enumerate(lat.labels)}
    updaters = {"population": compat.Tally("population"),
                "cut_edges": compat.cut_edges,
                "b_nodes": compat.b_nodes_pairs,
                "base": lambda p: BASE,
                "geom": compat.make_geom_wait(rng)}
    part = compat.Partition(lat, assign, updaters)
    popbound = compat.within_percent_of_ideal_population(part, EPS)
    chain = compat.MarkovChain(
        compat.make_reversible_propose_pairs(rng),
        compat.Validator([compat.single_flip_contiguous, popbound]),
        compat.make_cut_accept(rng), part, STEPS)
    cuts, bs, waits = [], [], []
    for p in chain:
        cuts.append(len(p["cut_edges"]))
        bs.append(len(p["b_nodes"]))
        waits.append(p["geom"])
    return (np.array(cuts[BURN:]), np.array(bs[BURN:]),
            np.array(waits[BURN:], dtype=float))


def kernel_pair_trajectories(lat, k, seed, chains=8):
    plan = fce.graphs.stripes_plan(lat, k)
    spec = fce.Spec(n_districts=k, proposal="pair", contiguity="exact")
    dg, st, params = fce.init_batch(lat, plan, n_chains=chains, seed=seed,
                                    spec=spec, base=BASE, pop_tol=EPS)
    res = fce.run_chains(dg, spec, params, st, n_steps=STEPS)
    return (res.history["cut_count"][:, BURN:],
            res.history["b_count"][:, BURN:],
            res.history["wait"][:, BURN:])


@pytest.mark.slow
def test_kernel_matches_oracle_distributions():
    lat = fce.graphs.square_grid(6, 6)
    o_cut, o_b, o_w = oracle_trajectory(lat, seed=1)
    k_cut, k_b, k_w = kernel_trajectories(lat, seed=2)

    # subsample to decorrelate before a KS comparison
    sub = slice(None, None, 40)
    ks_cut = ks_stat(o_cut[sub], k_cut[:, ::40].ravel())
    ks_b = ks_stat(o_b[sub], k_b[:, ::40].ravel())
    assert ks_cut < 0.12, f"cut-count KS {ks_cut:.3f}"
    assert ks_b < 0.12, f"b-count KS {ks_b:.3f}"

    # means within 3% (tighter than KS on autocorrelated series)
    assert abs(o_cut.mean() - k_cut.mean()) / o_cut.mean() < 0.03
    assert abs(o_b.mean() - k_b.mean()) / o_b.mean() < 0.03
    # waits: mean ratio within 10% (heavy-tailed)
    assert abs(o_w.mean() - k_w.mean()) / o_w.mean() < 0.10


@pytest.mark.slow
def test_pair_kernel_matches_oracle_distributions():
    """The k-district pair walk agrees with the gerrychain-semantics
    oracle, including the distinct-PAIR |b_nodes| feeding geom_wait."""
    lat = fce.graphs.square_grid(6, 6)
    k = 3
    o_cut, o_b, o_w = oracle_pair_trajectory(lat, k, seed=4)
    k_cut, k_b, k_w = kernel_pair_trajectories(lat, k, seed=5)

    sub = slice(None, None, 40)
    ks_cut = ks_stat(o_cut[sub], k_cut[:, ::40].ravel())
    ks_b = ks_stat(o_b[sub], k_b[:, ::40].ravel())
    assert ks_cut < 0.12, f"cut-count KS {ks_cut:.3f}"
    assert ks_b < 0.12, f"b-count KS {ks_b:.3f}"
    assert abs(o_cut.mean() - k_cut.mean()) / o_cut.mean() < 0.03
    assert abs(o_b.mean() - k_b.mean()) / o_b.mean() < 0.03
    assert abs(o_w.mean() - k_w.mean()) / o_w.mean() < 0.10
