"""Pallas board kernel verification (interpret mode on CPU).

The kernel's host_rng mode reads its random bits from input refs, making a
chunk a deterministic function of known bits — so the primary test is
BIT-EXACT equality against a transparent simulator that replays the same
per-step logic (numpy control flow; jnp float32 for the transcendental
bits so the numerics match XLA's). On top: chain invariants (contiguity,
population, derived-field consistency) and log replay through
kernel.board.apply_flip_log.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import flipcomplexityempirical_tpu as fce

from conftest import assert_grid_districts_connected
from flipcomplexityempirical_tpu.kernel import board as kb
from flipcomplexityempirical_tpu.kernel import pallas_board as pb


H, W = 8, 16
N = H * W


def _setup(chains=8, base=1.4, tol=0.3, seed=0):
    g = fce.graphs.square_grid(H, W)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(contiguity="patch")
    bg, st, params = fce.sampling.init_board(
        g, plan, n_chains=chains, seed=seed, spec=spec, base=base,
        pop_tol=tol)
    return g, spec, bg, st, params


def _bits(rng, t, c, n):
    plane = rng.integers(0, 2**32, size=(t, c, n), dtype=np.uint32)
    scal = rng.integers(0, 2**32, size=(t, 2, c), dtype=np.uint32)
    return plane, scal


def _u01(bits):
    return np.asarray(
        (jnp.right_shift(jnp.asarray(bits), jnp.uint32(8))
         .astype(jnp.float32) + 1.0) * jnp.float32(1.0 / 16777218.0))


def _f32(x):
    return jnp.asarray(x, jnp.float32)


def simulate(bg, spec, board0, dist_pop0, params, st, bits_plane,
             bits_scal):
    """Transparent replay of the kernel's per-step semantics."""
    t_len, c, n = bits_plane.shape
    h, w = bg.h, bg.w
    board = np.asarray(board0, np.int8).copy()
    dp = np.asarray(dist_pop0, np.int64).copy()        # (C, 2)
    deg = np.asarray(bg.deg)
    pop = np.asarray(bg.pop)
    log_base = np.asarray(params.log_base, np.float32)
    beta = np.asarray(params.beta, np.float32)
    pop_lo = np.asarray(params.pop_lo, np.float32)
    pop_hi = np.asarray(params.pop_hi, np.float32)
    cur_wait = np.asarray(st.cur_wait, np.float32).copy()
    pending = np.asarray(st.wait_pending).copy()
    cur_flip = np.asarray(st.cur_flip).copy()
    cur_sign = np.asarray(st.cur_sign, np.int64).copy()
    acc_cnt = np.asarray(st.accept_count).copy()
    denom = np.float32(float(n) ** 2 - 1.0)

    hist = {k: np.zeros((t_len, c), np.int64)
            for k in ("cut", "b", "accepts")}
    hist["wait"] = np.zeros((t_len, c), np.float32)
    log_f = np.zeros((t_len, c), np.int64)
    log_s = np.zeros((t_len, c), np.int64)
    cut_e16 = np.zeros((c, n), np.int64)
    cut_s16 = np.zeros((c, n), np.int64)
    waits_sum = np.zeros(c, np.float32)

    b2 = lambda a: a.reshape(c, h, w)
    for t in range(t_len):
        bb = b2(board)
        same = {}
        pad = np.pad(bb, ((0, 0), (1, 1), (1, 1)), constant_values=-1)
        for name, (dx, dy) in dict(
                e=(0, 1), w=(0, -1), s=(1, 0), n=(-1, 0),
                se=(1, 1), sw=(1, -1), ne=(-1, 1), nw=(-1, -1)).items():
            same[name] = (pad[:, 1 + dx:1 + dx + h, 1 + dy:1 + dy + w]
                          == bb).reshape(c, n)
        same_deg = sum(same[k].astype(np.int64) for k in "eswn")
        diff_deg = deg[None] - same_deg
        b_mask = diff_deg > 0
        ys = np.arange(n) % w
        cut_e = (ys < w - 1)[None] & ~same["e"]
        cut_s = (np.arange(n) < (h - 1) * w)[None] & ~same["s"]
        runs = ((same["e"] & ~(same["ne"] & same["n"])).astype(np.int64)
                + (same["s"] & ~(same["se"] & same["e"]))
                + (same["w"] & ~(same["sw"] & same["s"]))
                + (same["n"] & ~(same["nw"] & same["w"])))
        contig = (same_deg <= 1) | (runs <= 1)
        pop_of = np.where(board == 1, dp[:, 1, None], dp[:, 0, None])
        pop_to = np.where(board == 1, dp[:, 0, None], dp[:, 1, None])
        pop_ok = ((pop_of - pop[None] >= pop_lo[:, None])
                  & (pop_to + pop[None] <= pop_hi[:, None]))
        valid = b_mask & contig & pop_ok
        b_count = b_mask.sum(1)
        cut_count = cut_e.sum(1) + cut_s.sum(1)

        u_wait = _u01(bits_scal[t, 0])
        p = np.asarray(_f32(b_count) / denom)
        wnew = np.asarray(jnp.maximum(jnp.floor(
            jnp.log(jnp.maximum(_f32(u_wait), 1e-12))
            / jnp.log1p(-_f32(p))), 0.0))
        cur_wait = np.where(pending, wnew, cur_wait).astype(np.float32)

        hist["cut"][t] = cut_count
        hist["b"][t] = b_count
        hist["wait"][t] = cur_wait
        hist["accepts"][t] = acc_cnt
        log_f[t] = cur_flip
        log_s[t] = cur_sign
        cut_e16 += cut_e
        cut_s16 += cut_s
        waits_sum = np.asarray(_f32(waits_sum) + _f32(cur_wait))

        score = np.where(valid, bits_plane[t] | np.uint32(1), 0)
        idx = score.argmax(axis=1)
        any_valid = score.max(axis=1) > 0
        d_from = board[np.arange(c), idx].astype(np.int64)
        dcut = deg[idx] - 2 * diff_deg[np.arange(c), idx]
        u_acc = _u01(bits_scal[t, 1])
        log_bound = np.asarray(
            -_f32(beta) * _f32(dcut) * _f32(log_base))
        logu = np.asarray(jnp.log(jnp.maximum(_f32(u_acc), 1e-12)))
        accept = any_valid & (logu < log_bound)

        d_to = 1 - d_from
        sel = accept
        board[np.arange(c)[sel], idx[sel]] = d_to[sel].astype(np.int8)
        popv = np.where(sel, pop[idx], 0)
        sgn = np.where(d_from == 0, 1, -1)
        dp[:, 0] -= popv * sgn
        dp[:, 1] += popv * sgn
        cur_flip = np.where(sel, idx, cur_flip)
        cur_sign = np.where(sel, 1 - 2 * d_to, cur_sign)
        pending = sel.copy()
        acc_cnt = acc_cnt + sel

    return dict(board=board, dist_pop=dp, hist=hist, log_f=log_f,
                log_s=log_s, cut_e16=cut_e16, cut_s16=cut_s16,
                cur_wait=cur_wait, pending=pending, cur_flip=cur_flip,
                acc_cnt=acc_cnt, waits_sum=waits_sum)


def _run_kernel(spec, bg, st, params, bits_plane, bits_scal, bc=8):
    t_len, c, n = bits_plane.shape
    pop_plane, deg_plane, masks8 = pb.make_static_inputs(bg)
    dist_pop, scal, ints = pb.pack_state(st, params)
    seeds = jnp.zeros(c // bc, jnp.int32)
    return pb.run_pallas_chunk(
        spec, bg.h, bg.w, t_len, bc, seeds, st.board, pop_plane,
        deg_plane, masks8, dist_pop, scal, ints, jnp.asarray(bits_plane),
        jnp.asarray(bits_scal), host_rng=True, interpret=True)


def test_kernel_bit_exact_vs_simulator(rng):
    g, spec, bg, st, params = _setup(chains=16)
    bits_plane, bits_scal = _bits(rng, 40, 16, N)
    outs = _run_kernel(spec, bg, st, params, bits_plane, bits_scal, bc=8)
    sim = simulate(bg, spec, st.board, st.dist_pop, params, st,
                   bits_plane, bits_scal)

    (board, dist_pop, scal, ints, log_f, log_s, h_cut, h_b, h_wait, h_acc,
     cut_e16, cut_s16) = outs
    np.testing.assert_array_equal(np.asarray(board), sim["board"])
    np.testing.assert_array_equal(np.asarray(dist_pop).T, sim["dist_pop"])
    np.testing.assert_array_equal(np.asarray(log_f), sim["log_f"])
    np.testing.assert_array_equal(np.asarray(log_s), sim["log_s"])
    np.testing.assert_array_equal(np.asarray(h_cut), sim["hist"]["cut"])
    np.testing.assert_array_equal(np.asarray(h_b), sim["hist"]["b"])
    np.testing.assert_array_equal(np.asarray(h_acc),
                                  sim["hist"]["accepts"])
    np.testing.assert_allclose(np.asarray(h_wait), sim["hist"]["wait"],
                               rtol=0, atol=0)
    np.testing.assert_array_equal(np.asarray(cut_e16), sim["cut_e16"])
    np.testing.assert_array_equal(np.asarray(cut_s16), sim["cut_s16"])
    np.testing.assert_array_equal(np.asarray(ints[1]), sim["cur_flip"])
    np.testing.assert_array_equal(np.asarray(ints[5]), sim["acc_cnt"])
    np.testing.assert_allclose(np.asarray(scal[1]), sim["waits_sum"])


def test_kernel_invariants_and_log_replay(rng):
    g, spec, bg, st, params = _setup(chains=8, tol=0.1)
    bits_plane, bits_scal = _bits(rng, 60, 8, N)
    outs = _run_kernel(spec, bg, st, params, bits_plane, bits_scal)
    st2 = pb.unpack_state(st, bg, outs, 60)
    b = np.asarray(st2.board).reshape(-1, H, W)

    assert_grid_districts_connected(b, 2)
    ideal = N / 2
    dp = np.asarray(st2.dist_pop)
    assert (dp >= 0.9 * ideal - 1e-6).all() and (dp <= 1.1 * ideal).all()
    assert (dp.sum(axis=1) == N).all()
    accepts_hist = np.asarray(outs[9])
    assert (np.asarray(st2.accept_count) >= accepts_hist[-1]).all()

    # flip log replays through the shared apply_flip_log
    log_f, log_s = outs[4], outs[5]
    ps, lf, nf = kb.apply_flip_log(
        st.part_sum, st.last_flipped, st.num_flips, log_f, log_s,
        st.t_yield)
    nf = np.asarray(nf)
    first = (np.asarray(log_f) >= 0).argmax(axis=0)
    active = (np.asarray(log_f) >= 0).any(axis=0)
    expect = np.where(active, 60 - first, 0)
    np.testing.assert_array_equal(nf.sum(axis=1), expect)


def test_multi_block_grid_matches_single_block(rng):
    """Blocking over chains is invisible: bc=4 (4 blocks) == bc=16."""
    g, spec, bg, st, params = _setup(chains=16)
    bits_plane, bits_scal = _bits(rng, 25, 16, N)
    a = _run_kernel(spec, bg, st, params, bits_plane, bits_scal, bc=4)
    b = _run_kernel(spec, bg, st, params, bits_plane, bits_scal, bc=16)
    for xa, xb in zip(a, b):
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))


def test_selection_is_uniform_over_valid():
    """argmax of iid masked bits == uniform over the valid set (the
    re-propose-until-valid equivalence this kernel relies on)."""
    rng = np.random.default_rng(3)
    n, draws = 24, 40000
    valid = np.zeros(n, bool)
    valid[[2, 5, 6, 11, 17, 23]] = True
    bits = rng.integers(0, 2**32, size=(draws, n), dtype=np.uint32)
    score = np.where(valid[None], bits | np.uint32(1), 0)
    idx = score.argmax(axis=1)
    counts = np.bincount(idx, minlength=n)
    assert (counts[~valid] == 0).all()
    expect = draws / valid.sum()
    assert np.abs(counts[valid] - expect).max() < 5 * np.sqrt(expect)


@pytest.mark.slow
def test_simulator_matches_xla_board_distribution():
    """Transitive distribution check: the kernel is bit-exact to the
    simulator (above), and the simulator's trajectory statistics match
    the XLA board path — so kernel == board path in distribution.

    Uses a local fixed rng (not the shared session fixture) so its
    draws — and therefore the KS statistic — do not shift when other
    tests are added or reordered."""
    from test_parity import ks_stat

    rng = np.random.default_rng(42)
    chains, steps, burn = 32, 2500, 400
    g, spec, bg, st, params = _setup(chains=chains, base=1.3, tol=0.3)
    bits_plane, bits_scal = _bits(rng, steps, chains, N)
    sim = simulate(bg, spec, st.board, st.dist_pop, params, st,
                   bits_plane, bits_scal)

    bg2, st2, par2 = fce.sampling.init_board(
        fce.graphs.square_grid(H, W), fce.graphs.stripes_plan(
            fce.graphs.square_grid(H, W), 2),
        n_chains=chains, seed=9, spec=spec, base=1.3, pop_tol=0.3)
    res = fce.sampling.run_board(bg2, spec, par2, st2, n_steps=steps)

    sub = slice(burn, None, 20)
    for sim_key, xla_key, tol in (("cut", "cut_count", 0.08),
                                  ("b", "b_count", 0.08)):
        a = sim["hist"][sim_key][sub].ravel().astype(float)
        b = res.history[xla_key][:, sub].ravel().astype(float)
        ks = ks_stat(a, b)
        assert ks < tol, f"{sim_key} KS {ks:.4f}"
        assert abs(a.mean() - b.mean()) / b.mean() < 0.03, (
            sim_key, a.mean(), b.mean())
    # accept rates agree
    aa = sim["acc_cnt"].mean() / steps
    ab = np.asarray(res.state.accept_count).mean() / steps
    assert abs(aa - ab) < 0.03, (aa, ab)


def test_pallas_runner_end_to_end_interpret(rng):
    """run_board_pallas's chunk stitching, t0-offset log replay, pending
    wait handoff across chunks, waits draining, and record_final merge —
    exercised with host-supplied bits in interpret mode, checked via the
    same exact invariants the XLA board runner satisfies."""
    chains, steps = 8, 121
    g, spec, bg, st, params = _setup(chains=chains, tol=0.2)

    def host_bits(chunk_idx, t, c, n):
        r = np.random.default_rng(1000 + chunk_idx)
        return (jnp.asarray(r.integers(0, 2**32, (t, c, n),
                                       dtype=np.uint32)),
                jnp.asarray(r.integers(0, 2**32, (t, 2, c),
                                       dtype=np.uint32)))

    res = fce.sampling.run_board_pallas(
        bg, spec, params, st, n_steps=steps, chunk=40, block_chains=8,
        interpret=True, _host_bits=host_bits)
    s = jax.tree.map(np.asarray, res.state)

    # history shapes and exact accumulator tie-outs
    assert res.history["cut_count"].shape == (chains, steps)
    cut_t = kb.edge_cut_times(g, res.state)
    np.testing.assert_array_equal(cut_t.sum(axis=1),
                                  res.history["cut_count"].sum(axis=1))
    np.testing.assert_allclose(
        res.waits_total, res.history["wait"].sum(axis=1, dtype=float),
        rtol=1e-6)
    first = (res.history["accepts"] > 0).argmax(axis=1)
    expect = np.where(res.history["accepts"][:, -1] > 0, steps - first, 0)
    np.testing.assert_array_equal(s.num_flips.sum(axis=1), expect)

    # derived fields consistent; contiguity preserved through chunks
    b = s.board.reshape(chains, H, W)
    pop0 = (b == 0).sum(axis=(1, 2))
    np.testing.assert_array_equal(s.dist_pop[:, 0], pop0)
    assert_grid_districts_connected(b, 2)
    assert (s.t_yield == steps).all()


def test_pallas_runner_validates_config():
    g, spec, bg, st, params = _setup(chains=8)
    with pytest.raises(ValueError):
        fce.sampling.run_board_pallas(bg, spec, params, st, n_steps=10,
                                      block_chains=3)
    spec_bad = fce.Spec(contiguity="patch", accept="always")
    with pytest.raises(ValueError):
        fce.sampling.run_board_pallas(bg, spec_bad, params, st, n_steps=10,
                                      block_chains=8)


def test_pallas_empty_valid_set_self_loops():
    """pop_tol=0 with a balanced plan: every draw invalid, board frozen."""
    g = fce.graphs.square_grid(H, W)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(contiguity="patch")
    bg, st, params = fce.sampling.init_board(
        g, plan, n_chains=8, seed=1, spec=spec, base=1.0, pop_tol=0.0)

    def host_bits(chunk_idx, t, c, n):
        r = np.random.default_rng(chunk_idx)
        return (jnp.asarray(r.integers(0, 2**32, (t, c, n),
                                       dtype=np.uint32)),
                jnp.asarray(r.integers(0, 2**32, (t, 2, c),
                                       dtype=np.uint32)))

    res = fce.sampling.run_board_pallas(
        bg, spec, params, st, n_steps=31, chunk=10, block_chains=8,
        interpret=True, _host_bits=host_bits)
    s = jax.tree.map(np.asarray, res.state)
    np.testing.assert_array_equal(s.board, np.broadcast_to(plan, (8, N)))
    assert (s.accept_count == 0).all()
    assert (s.exhausted_count == 30).all()
