"""Network front door + worker fleet (ISSUE 17).

The load-bearing claims, each tested here:
- per-tenant token buckets and weighted deficit round-robin shape WHO
  enters the spool (quota refusal is a typed 429, weights change the
  interleave, one tenant's burst never reorders a neighbor's queue);
- the front door journals every submission write-ahead and a restarted
  server recovers losslessly — pending submissions re-enter admission,
  spooled ones don't double;
- the HTTP surface round-trips submit / status / artifact / drain with
  typed refusals (400 unknown workload, 404 missing artifact, 429
  quota, 503 draining or armed ``http.accept`` fault);
- a worker executes a spooled job and publishes the verdict under the
  FLEET job id (per-job services number internally from j0000 — the
  regression that once spun the fleet forever);
- ``tools/loadtest.py`` simulate mode is seed-deterministic and its
  p99/p50 + Jain gates hold at the frozen bench scenario's shape;
- ``bench_compare`` qualifies fleet records per [tenants=N,workers=K]
  so they never cross-gate kernel metrics, and ``obs_report`` renders
  the Fleet section and fails --strict on lease-expiry storms.

The cross-process story (SIGKILL mid-batch, lease reclaim between real
worker processes) lives in tools/fleet_check.sh (`make fleet-check`,
wrapped here as a slow-tier test) and the lease-protocol matrix in
tests/test_preemption.py.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from flipcomplexityempirical_tpu import obs
from flipcomplexityempirical_tpu.resilience import faults as rfaults
from flipcomplexityempirical_tpu.service import (
    EXIT_DRAINED, FairAdmission, FleetServer, FrontDoor, ServiceClient,
    ClientError, TokenBucket, Worker, clear_drain, drain_marked)
from flipcomplexityempirical_tpu.service import journal as jnl
from flipcomplexityempirical_tpu.service.server import (
    BadRequest, QuotaExceeded, Unavailable)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# cheap catalog job: same 60/20/2 jit specialization every service-layer
# test suite uses, so the compile is paid once per pytest process
OVERRIDES = {"total_steps": 60, "n_chains": 2, "checkpoint_every": 20}


@pytest.fixture(autouse=True)
def _clean_process_state():
    rfaults.install_plan(None)
    clear_drain()
    yield
    rfaults.install_plan(None)
    clear_drain()


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _tools(name):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


# ---------------------------------------------------------------------------
# admission primitives
# ---------------------------------------------------------------------------

def test_token_bucket_burst_then_refill():
    clk = FakeClock()
    b = TokenBucket(rate=2.0, burst=3.0, clock=clk)
    assert [b.take() for _ in range(4)] == [True, True, True, False]
    clk.t += 1.0          # 2 tokens back
    assert b.take() and b.take() and not b.take()
    clk.t += 100.0        # refill caps at burst
    assert [b.take() for _ in range(4)] == [True, True, True, False]


def test_fair_admission_round_robin_interleaves_bursts():
    fa = FairAdmission()
    for i in range(3):
        fa.enqueue("a", f"a{i}")
    fa.enqueue("b", "b0")
    fa.enqueue("c", "c0")
    order = [fa.pop()[1] for _ in range(len(fa))]
    # a's burst waits behind every other tenant's head-of-line job
    assert order == ["a0", "b0", "c0", "a1", "a2"]
    assert fa.pop() is None


def test_fair_admission_weights_set_the_share():
    fa = FairAdmission(weights={"heavy": 2})
    for i in range(4):
        fa.enqueue("heavy", f"h{i}")
        fa.enqueue("light", f"l{i}")
    order = [fa.pop()[0] for _ in range(len(fa))]
    # per full cycle: two heavy admissions to one light
    assert order[:3] == ["heavy", "heavy", "light"]
    assert order.count("heavy") == 4 and order.count("light") == 4


# ---------------------------------------------------------------------------
# front door: journal, spool, recovery, quota, drain
# ---------------------------------------------------------------------------

def _submit_workload(front, tenant, seed=3):
    return front.submit({"workload": "frank",
                         "overrides": {**OVERRIDES, "seed": seed}},
                        tenant)


def test_front_door_spools_in_admission_order(tmp_path):
    front = FrontDoor(str(tmp_path))
    ids = [_submit_workload(front, t, seed=3 + i)["job_id"]
           for i, t in enumerate(["a", "a", "b"])]
    assert ids == ["j0000", "j0001", "j0002"]
    front.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        docs = [d for d in
                (os.path.join(tmp_path, "jobs", f"{j}.json")
                 for j in ids) if os.path.exists(d)]
        if len(docs) == 3:
            break
        time.sleep(0.02)
    front.stop()
    spooled = {j: json.load(open(
        os.path.join(tmp_path, "jobs", f"{j}.json"))) for j in ids}
    # fair admission: a's second job admitted AFTER b's first
    assert spooled["j0002"]["admit_seq"] < spooled["j0001"]["admit_seq"]
    records, truncated = jnl.Journal.read(
        jnl.journal_path_for(str(tmp_path)))
    assert not truncated
    kinds = [r["kind"] for r in records]
    assert kinds.count("job_submitted") == 3
    assert kinds.count("job_admitted") == 3
    # WAL ordering: every submission journaled before it's admitted
    assert kinds.index("job_submitted") < kinds.index("job_admitted")
    sub = next(r for r in records if r["kind"] == "job_submitted")
    assert sub["config"]["total_steps"] == 60   # full config doc rides


def test_front_door_restart_recovers_pending(tmp_path):
    front = FrontDoor(str(tmp_path))     # pump never started
    _submit_workload(front, "a", seed=3)
    _submit_workload(front, "b", seed=4)
    # crash before admission: journal has the submissions, spool empty
    assert os.listdir(tmp_path / "jobs") == []
    front2 = FrontDoor(str(tmp_path))
    assert front2.job_status("j0000")["status"] == "pending"
    front2.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not (
            front2.pump_idle()
            and len(os.listdir(tmp_path / "jobs")) == 2):
        time.sleep(0.02)
    front2.stop()
    assert sorted(os.listdir(tmp_path / "jobs")) == ["j0000.json",
                                                     "j0001.json"]
    assert front2.job_status("j0001")["status"] == "queued"
    # a third restart does NOT double-spool admitted jobs
    front3 = FrontDoor(str(tmp_path))
    front3.start()
    time.sleep(0.3)
    front3.stop()
    records, _ = jnl.Journal.read(jnl.journal_path_for(str(tmp_path)))
    assert sum(r["kind"] == "job_admitted" for r in records) == 2


def test_front_door_quota_refuses_with_429(tmp_path):
    clk = FakeClock()
    ev = tmp_path / "events.jsonl"
    rec = obs.Recorder(str(ev))
    front = FrontDoor(str(tmp_path), recorder=rec, quota_rate=1.0,
                      quota_burst=2.0, clock=clk)
    _submit_workload(front, "greedy", seed=3)
    _submit_workload(front, "greedy", seed=4)
    with pytest.raises(QuotaExceeded) as ei:
        _submit_workload(front, "greedy", seed=5)
    assert ei.value.status == 429
    # quotas are per tenant: a neighbor is unaffected
    _submit_workload(front, "polite", seed=6)
    clk.t += 1.0
    _submit_workload(front, "greedy", seed=7)
    rec.close()
    events = [json.loads(l) for l in ev.read_text().splitlines()]
    rejected = [e for e in events if e["event"] == "quota_rejected"]
    assert len(rejected) == 1 and rejected[0]["tenant"] == "greedy"


def test_front_door_drain_refuses_and_marks(tmp_path):
    front = FrontDoor(str(tmp_path))
    _submit_workload(front, "a")
    out = front.drain("test")
    assert out == {"draining": "test"}
    assert drain_marked(str(tmp_path)) == "test"
    with pytest.raises(Unavailable):
        _submit_workload(front, "a", seed=9)
    records, _ = jnl.Journal.read(jnl.journal_path_for(str(tmp_path)))
    assert records[-1]["kind"] == "service_draining"


def test_front_door_rejects_bad_bodies(tmp_path):
    front = FrontDoor(str(tmp_path))
    for body in ({}, {"workload": "no-such-workload"},
                 {"workload": "frank", "overrides": ["not", "a", "dict"]},
                 {"workload": "frank",
                  "overrides": {"no_such_field": 1}},
                 {"config": {"family": "frank", "bogus": True}}):
        with pytest.raises(BadRequest):
            front.submit(body, "a")
    # refusals journal nothing (write-ahead means no take-backs needed)
    records, _ = jnl.Journal.read(jnl.journal_path_for(str(tmp_path)))
    assert records == []


# ---------------------------------------------------------------------------
# HTTP surface
# ---------------------------------------------------------------------------

def test_http_round_trip_and_typed_refusals(tmp_path):
    with FleetServer(str(tmp_path)) as srv:
        client = ServiceClient(srv.url, tenant="alice")
        assert client.healthz()["ok"] is True
        assert "frank" in client.workloads()
        with pytest.raises(ClientError) as ei:
            client.submit(workload="no-such-workload")
        assert ei.value.status == 400
        doc = client.submit(workload="frank", overrides=OVERRIDES)
        assert doc["job_id"] == "j0000" and doc["tenant"] == "alice"
        st = client.status("j0000")
        assert st["status"] in ("pending", "queued")
        with pytest.raises(ClientError) as ei:
            client.artifact("j0000")      # not run yet
        assert ei.value.status == 404
        with pytest.raises(ClientError) as ei:
            client.status("j9999")
        assert ei.value.status == 404
        # an armed http.accept fault is a 503 refusal, never torn state
        rfaults.install_from_spec("http.accept:once")
        with pytest.raises(ClientError) as ei:
            client.healthz()
        assert ei.value.status == 503
        assert client.healthz()["ok"] is True   # once means once
        n = client.jobs()
        assert n["counts"] == {"queued": 1} or n["counts"] == \
            {"pending": 1}


@pytest.mark.slow
def test_http_submit_worker_executes_artifact_served(tmp_path):
    """The full tenant story over real HTTP: submit a catalog workload,
    a worker claims it from the spool, the artifact (with its
    bit-identity digest) comes back through the door — verdicts keyed
    by the FLEET id, not the per-job service's internal j0000."""
    with FleetServer(str(tmp_path)) as srv:
        client = ServiceClient(srv.url, tenant="alice")
        a = client.submit(workload="frank", overrides=OVERRIDES)
        b = client.submit(workload="frank",
                          overrides={**OVERRIDES, "seed": 11})
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not os.path.exists(
                tmp_path / "jobs" / "j0001.json"):
            time.sleep(0.02)
        w = Worker(str(tmp_path), worker="wtest", ttl_s=30.0)
        assert w.run_once() == 2
        for job_id in (a["job_id"], b["job_id"]):
            st = client.status(job_id)
            assert st["status"] == "done", st
            assert st["worker"] == "wtest"
            assert st["queue_to_start_s"] >= 0
            art = client.artifact(job_id)
            assert art["job_id"] == job_id
            assert art["result_sha256"]
        # distinct seeds -> distinct result digests (real payloads)
        assert client.artifact(a["job_id"])["result_sha256"] != \
            client.artifact(b["job_id"])["result_sha256"]
        assert client.jobs()["counts"] == {"done": 2}


def test_drain_endpoint_stops_workers_and_refuses(tmp_path):
    with FleetServer(str(tmp_path)) as srv:
        client = ServiceClient(srv.url)
        client.submit(workload="frank", overrides=OVERRIDES)
        assert client.drain() == {"draining": "http"}
        assert client.healthz()["draining"] is True
        with pytest.raises(ClientError) as ei:
            client.submit(workload="frank", overrides=OVERRIDES)
        assert ei.value.status == 503
        # a worker landing on the drained root exits 3 without claiming
        w = Worker(str(tmp_path), worker="wd", idle_timeout_s=0.1,
                   poll_s=0.05)
        assert w.run() == EXIT_DRAINED
        assert w.executed == []


# ---------------------------------------------------------------------------
# loadtest + bench_compare + obs_report
# ---------------------------------------------------------------------------

def test_jain_index():
    loadtest = _tools("loadtest")
    assert loadtest.jain_index([1.0, 1.0, 1.0, 1.0]) == pytest.approx(1.0)
    assert loadtest.jain_index([1.0, 0.0, 0.0, 0.0]) == pytest.approx(0.25)
    # degenerate inputs lean fair (empty / all-zero -> 1.0): the gate
    # is value >= threshold, so the conservative direction is not to
    # fabricate unfairness where there is no signal
    assert loadtest.jain_index([]) == 1.0
    assert loadtest.jain_index([0.0, 0.0]) == 1.0


def test_loadtest_simulate_deterministic_and_gated():
    loadtest = _tools("loadtest")
    kw = dict(tenants=40, jobs=2, workers=8, service_s=0.5,
              spread_s=20.0, admit_s=0.002, seed=7)
    sim = loadtest.simulate(**kw)
    again = loadtest.simulate(**kw)
    assert sim["waits"] == again["waits"]       # seeded, replayable
    rec = loadtest.build_record(sim["waits"], sim["turnarounds"],
                                sim["rejected"], tenants=40, workers=8,
                                jobs=2, mode="simulate")
    assert rec["metric"] == "fleet_fairness_jain"
    assert rec["cpu_fallback"] is True and rec["device"] == "cpu"
    assert rec["jobs_measured"] == 80
    # the acceptance gates at the SLO-regime shape
    assert rec["p99_over_p50"] <= 2.0, rec
    assert rec["value"] >= 0.8, rec


def test_loadtest_quota_rejections_counted():
    loadtest = _tools("loadtest")
    sim = loadtest.simulate(tenants=4, jobs=50, workers=4,
                            service_s=0.01, spread_s=1.0, admit_s=0.0,
                            seed=7, quota_rate=1.0, quota_burst=2.0)
    assert sum(sim["rejected"].values()) > 0
    done = sum(len(w) for w in sim["waits"].values())
    assert done + sum(sim["rejected"].values()) == 200


def test_bench_compare_qualifies_fleet_records():
    bench_compare = _tools("bench_compare")
    fleet = {"metric": "fleet_fairness_jain", "value": 0.97,
             "tenants": 500, "workers": 16}
    assert bench_compare.extract_metrics(fleet) == \
        {"fleet_fairness_jain[tenants=500,workers=16]": 0.97}
    # the service record's qualifier is untouched (no workers key)
    svc = {"metric": "tenant_efficiency", "value": 2.4, "tenants": 4}
    assert bench_compare.extract_metrics(svc) == \
        {"tenant_efficiency[tenants=4]": 2.4}


def _fleet_events(n_expired):
    evs = [{"event": "job_submitted", "ts": 100.0, "job_id": "j0000",
            "tenant": "a"},
           {"event": "worker_started", "ts": 100.5, "worker": "w1"},
           {"event": "lease_acquired", "ts": 101.0, "job_id": "j0000",
            "worker": "w1", "reclaim": False},
           {"event": "http_request", "ts": 101.2, "method": "POST",
            "path": "/v1/jobs", "status": 200, "tenant": "a",
            "dur_s": 0.004},
           {"event": "quota_rejected", "ts": 101.3, "tenant": "b"}]
    for i in range(n_expired):
        evs.append({"event": "lease_expired", "ts": 102.0 + i,
                    "job_id": "j0000", "worker": "w1", "by": "w2",
                    "age_s": 9.0})
    evs.append({"event": "worker_exited", "ts": 110.0, "worker": "w1",
                "reason": "done", "n_executed": 1, "n_failures": 0})
    return evs


def test_obs_report_lease_storms_threshold():
    obs_report = _tools("obs_report")
    assert obs_report.lease_storms(_fleet_events(2)) == {}
    storms = obs_report.lease_storms(_fleet_events(3))
    assert storms == {"j0000": 3}


def test_obs_report_fleet_section_renders():
    import io

    obs_report = _tools("obs_report")
    out = io.StringIO()
    obs_report.report_fleet(_fleet_events(1), out)
    text = out.getvalue()
    assert "Fleet" in text
    assert "w1" in text and "quota" in text.lower()
    # no fleet events -> no section
    out2 = io.StringIO()
    obs_report.report_fleet([{"event": "run_start", "ts": 1.0}], out2)
    assert out2.getvalue() == ""


def test_obs_report_strict_fails_on_lease_storm(tmp_path):
    p = tmp_path / "events.jsonl"
    p.write_text("".join(
        json.dumps({"v": 1, **e}) + "\n" for e in _fleet_events(3)))
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obs_report.py"),
         str(p), "--strict"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert r.returncode == 2, r.stdout + r.stderr
    assert "storm" in (r.stdout + r.stderr).lower()


# ---------------------------------------------------------------------------
# CLI + CI gate
# ---------------------------------------------------------------------------

def test_cli_submit_refusal_exits_4(tmp_path):
    """Client-side refusals (here: no server at all) are exit code 4 —
    distinct from job failures (2) and drains (3)."""
    r = subprocess.run(
        [sys.executable, "-m", "flipcomplexityempirical_tpu.service",
         "submit", "http://127.0.0.1:9", "--workload", "frank"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 4, r.stdout + r.stderr
    assert "error" in r.stderr


@pytest.mark.slow
def test_fleet_check_gate_passes():
    """make fleet-check: 1 server + 2 workers + 8 tenants + SIGKILL
    chaos as one script. Slow-tier like the mesh gate — CI runs it
    both here (--runslow) and as the make target."""
    r = subprocess.run(
        ["bash", os.path.join(REPO, "tools", "fleet_check.sh")],
        capture_output=True, text=True, cwd=REPO, timeout=420)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "fleet-check: OK" in r.stdout
