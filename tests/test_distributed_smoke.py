"""2-process localhost smoke test for the DCN bring-up path
(distribute.initialize_distributed — VERDICT r4 next-9: it had no test,
"if multi-host ever matters it will fail on first contact").

Two subprocesses form a real jax.distributed cluster over a localhost
coordinator (DCN stand-in), each contributing one virtual CPU device;
they build the global 2-device chains mesh, run a psum over it, and
process 0 asserts the collective saw both processes' contributions.
This exercises coordinator handshake, cross-process device visibility,
and a multi-process collective — everything the single-process virtual
mesh tests cannot."""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
# one virtual CPU device per process BEFORE jax import; the cluster mesh
# then has 2 global devices, 1 local to each process
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
coord, pid = sys.argv[1], int(sys.argv[2])

sys.path.insert(0, %(repo)r)
from flipcomplexityempirical_tpu.distribute import initialize_distributed
initialize_distributed(coordinator=coord, num_processes=2, process_id=pid)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 2, jax.devices()

import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from flipcomplexityempirical_tpu.distribute import make_mesh

mesh = make_mesh(2)
sharding = NamedSharding(mesh, P("chains"))

@jax.jit
def total(x):
    return jnp.sum(x)

# each process owns one shard of the global (2,) array, value = pid + 1
local = np.asarray([float(pid + 1)])
garr = jax.make_array_from_single_device_arrays(
    (2,), sharding, [jax.device_put(local, jax.local_devices()[0])])
out = total(garr)
# the jitted global sum must see both shards: 1 + 2
assert float(out) == 3.0, float(out)
print(f"proc{pid} OK", flush=True)
jax.distributed.shutdown()
"""


def _attempt(script, env):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    procs = [subprocess.Popen([sys.executable, "-c", script, coord,
                               str(pid)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, env=env, text=True)
             for pid in (0, 1)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    return procs, outs


@pytest.mark.slow
def test_two_process_dcn_bringup_and_collective():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = _WORKER % {"repo": repo}
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs, outs = _attempt(script, env)
    if any(p.returncode for p in procs):
        # the bind-probe-then-release port pick has a TOCTOU window:
        # another process can grab the port before the coordinator
        # binds it; one retry on a fresh port removes the flake
        procs, outs = _attempt(script, env)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc{pid} failed:\n{out}"
        assert f"proc{pid} OK" in out
