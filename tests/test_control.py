"""control/: monitor-driven adaptive sweep control.

Covers the purity/determinism contract of the built-in policies, the
ControlLoop's emit/journal/adopt round trip, the driver's early-stop
path (bit-identical to a truncated fixed schedule), the tempered
ladder reshape, the service's batch reallocate, and the tiny-history
ESS guards (host <-> device parity below the autocorrelation window).
"""

import dataclasses
import json
import os
import subprocess

import numpy as np
import pytest

from flipcomplexityempirical_tpu import obs
from flipcomplexityempirical_tpu.control import (AutotunePolicy,
                                                ControlLoop,
                                                EarlyStopPolicy,
                                                LadderPolicy)
from flipcomplexityempirical_tpu.control.policy import (ObservedState,
                                                        quantize_latency)
from flipcomplexityempirical_tpu.experiments import driver as drv
from flipcomplexityempirical_tpu.experiments.config import ExperimentConfig
from flipcomplexityempirical_tpu.obs.metrics import (DEFAULT_EDGES,
                                                     MetricsRegistry)
from flipcomplexityempirical_tpu.service.journal import Journal
from flipcomplexityempirical_tpu.service.scheduler import SweepService
from flipcomplexityempirical_tpu.stats.diagnostics import ess
from flipcomplexityempirical_tpu.stats.device import ess_device

# same segmenting as tests/test_preemption.py (60 steps in 20-step
# segments, 2 chains) so the jit specializations are shared across the
# suite's modules
FRANK = dict(family="frank", base=0.3, pop_tol=0.1, total_steps=60,
             n_chains=2, checkpoint_every=20)

# targets the 60-step histories comfortably meet at the first boundary
# (split R-hat ~1.8-2.1, total ESS ~14-15 at T=21)
LOOSE = dict(rhat_target=5.0, ess_target=4.0, patience=1, min_columns=4)


def _solo(cfg, control=None, built=None):
    """build + raw segmented driver run (no rendering: keeps the
    equality tests inside the fast-tier budget)."""
    g, plan = built if built is not None else \
        drv.build_graph_and_plan(cfg)[:2]
    return drv._run_jax(cfg, g, plan, None, control=control)


def _view(**kw):
    base = dict(tag="t", family="frank", done=40, total=100, every=20)
    base.update(kw)
    return ObservedState(**base)


def _mixed_history(seed=0, c=4, t=64):
    return np.random.default_rng(seed).normal(size=(c, t))


# ---------------------------------------------------------------------------
# policies: pure observed-history -> actions
# ---------------------------------------------------------------------------

def test_early_stop_policy_is_deterministic():
    pol = EarlyStopPolicy(rhat_target=2.0, ess_target=1.0, patience=1,
                          min_columns=4)
    view = _view(history=_mixed_history())
    first = pol.propose(view)
    assert [a.kind for a in first] == ["stop"]
    # pure: the identical view yields the identical action, detail and
    # all (replay equality is judged on the JSON of the detail)
    for _ in range(3):
        again = pol.propose(view)
        assert [json.dumps(a.doc(), sort_keys=True) for a in again] == \
            [json.dumps(a.doc(), sort_keys=True) for a in first]


def test_early_stop_policy_respects_gates():
    pol = EarlyStopPolicy(rhat_target=2.0, ess_target=1.0, patience=1,
                          min_steps=50, min_columns=4)
    hist = _mixed_history()
    assert pol.propose(_view(history=hist, done=40)) == []  # < min_steps
    assert pol.propose(_view(history=hist, done=60)) != []
    assert pol.propose(_view(history=hist, done=60,
                             family="temper")) == []        # temper exempt
    assert pol.propose(_view(history=None, done=60)) == []
    assert pol.propose(_view(history=hist, done=100)) == []  # already done
    assert pol.propose(_view(history=hist, done=60,
                             taken={"stop": 1})) == []       # once only
    whitelisted = EarlyStopPolicy(rhat_target=2.0, ess_target=1.0,
                                  patience=1, min_columns=4,
                                  tags=("other",))
    assert whitelisted.propose(_view(history=hist, done=60)) == []


def test_early_stop_patience_needs_enough_boundaries():
    pol = EarlyStopPolicy(rhat_target=10.0, ess_target=1.0, patience=3,
                          min_columns=4)
    hist = _mixed_history(t=60)
    # only 2 grid points (20, 40) exist at done=40: patience=3 unmet
    assert pol.propose(_view(history=hist, done=40, every=20)) == []
    assert pol.propose(_view(history=hist, done=60, every=20,
                             total=200)) != []


def test_ladder_policy_contracts_widens_and_pins_cold_rung():
    pol = LadderPolicy(low=0.15, high=0.60)
    betas = (1.0, 0.5, 0.25)
    att = np.full(2, 20)

    def propose(accepts, **kw):
        return pol.propose(_view(
            family="temper", swap_attempts=att,
            swap_accepts=np.asarray(accepts), betas=betas, **kw))

    low = propose([1, 1])
    assert low[0].detail["direction"] == "contract"
    high = propose([15, 15])
    assert high[0].detail["direction"] == "widen"
    mid = propose([8, 8])
    assert mid == []
    for acts in (low, high):
        new = acts[0].detail["betas"]
        assert new[0] == 1.0                       # cold rung exact
        assert all(a > b for a, b in zip(new, new[1:]))
    # anomaly pulls a mid-band rate into a contraction
    anom = pol.propose(_view(
        family="temper", swap_attempts=att,
        swap_accepts=np.asarray([8, 8]), betas=betas,
        anomalies=("acceptance_collapse",)))
    assert anom[0].detail["direction"] == "contract"
    # starved statistics: no decision yet
    assert pol.propose(_view(
        family="temper", swap_attempts=np.asarray([1, 1]),
        swap_accepts=np.asarray([0, 0]), betas=betas)) == []
    # bounded: a taken reshape blocks further ones
    assert pol.propose(_view(
        family="temper", swap_attempts=att,
        swap_accepts=np.asarray([0, 0]), betas=betas,
        taken={"reshape_ladder": 1})) == []


def test_autotune_policy_reads_quantized_buckets_once():
    pol = AutotunePolicy(target_wall_s=1.0)
    slow = _view(every=64, p95_bucket={"segment_wall_s": (5.0, 4)})
    acts = pol.propose(slow)
    assert acts[0].kind == "retune"
    assert acts[0].detail["advisory"] is True
    assert acts[0].detail["segment_steps"] < 64
    fast = _view(every=64, total=1000,
                 p95_bucket={"segment_wall_s": (0.1, 4)})
    assert pol.propose(fast)[0].detail["segment_steps"] == 128
    in_band = _view(every=64, p95_bucket={"segment_wall_s": (1.0, 4)})
    assert pol.propose(in_band) == []
    assert pol.propose(_view(every=64)) == []                # no reading
    assert pol.propose(dataclasses.replace(
        slow, taken={"retune": 1})) == []                    # once only
    assert pol.propose(dataclasses.replace(
        slow, p95_bucket={"segment_wall_s": (5.0, 1)})) == []  # count < 2


def test_quantize_latency_snaps_to_histogram_edges():
    for v, want in ((0.0011, 0.002), (0.7, 1.0), (1.0, 1.0), (3.0, 5.0)):
        assert quantize_latency(v) == want
    assert quantize_latency(1e13) == DEFAULT_EDGES[-1]


# ---------------------------------------------------------------------------
# the loop: emit / journal / adopt
# ---------------------------------------------------------------------------

def test_loop_emits_event_and_journal_record(tmp_path):
    ev = str(tmp_path / "events.jsonl")
    j = Journal(str(tmp_path / "journal.jsonl"))
    with obs.Recorder(ev) as rec:
        loop = ControlLoop(
            policies=[EarlyStopPolicy(rhat_target=2.0, ess_target=1.0,
                                      patience=1, min_columns=4)],
            recorder=rec, journal=j)
        acts = loop.consult("t0", family="frank", done=40, total=100,
                            every=20, history=_mixed_history())
    assert [a.kind for a in acts] == ["stop"]
    assert loop.stopped("t0") and loop.stop_step("t0") == 40
    events = [json.loads(l) for l in open(ev)]
    ctl = [e for e in events if e["event"] == "control_action"]
    assert [(e["kind"], e["tag"], e["step"], e["policy"])
            for e in ctl] == [("stop", "t0", 40, "early_stop")]
    records, _ = Journal.read(j.path)
    ctl_r = [r for r in records if r["kind"] == "control_action"]
    assert [(r["action"], r["tag"], r["step"]) for r in ctl_r] == \
        [("stop", "t0", 40)]
    assert ctl_r[0]["detail"] == ctl[0]["detail"]


def test_loop_adopt_replays_instead_of_rederiving(tmp_path):
    j = Journal(str(tmp_path / "journal.jsonl"))
    pol = EarlyStopPolicy(rhat_target=2.0, ess_target=1.0, patience=1,
                          min_columns=4)
    loop = ControlLoop(policies=[pol], journal=j)
    hist = _mixed_history()
    loop.consult("t0", family="frank", done=40, total=100, every=20,
                 history=hist)
    records, _ = Journal.read(j.path)

    j2 = Journal(str(tmp_path / "journal2.jsonl"))
    loop2 = ControlLoop(policies=[pol], journal=j2)
    assert loop2.adopt(records) == 1
    assert loop2.stopped("t0") and loop2.stop_step("t0") == 40
    # the adopted stop replays at/after its boundary without re-emission
    assert not loop2.consult_stop("t0", family="frank", done=20,
                                  total=100, every=20, history=hist)
    assert loop2.consult_stop("t0", family="frank", done=40, total=100,
                              every=20, history=hist)
    assert loop2.actions == []
    assert Journal.read(j2.path)[0] == []


def test_loop_dedups_stops_and_collects_anomalies():
    pols = [EarlyStopPolicy(rhat_target=2.0, ess_target=1.0, patience=1,
                            min_columns=4),
            EarlyStopPolicy(rhat_target=3.0, ess_target=1.0, patience=1,
                            min_columns=4, name="early_stop_b")]
    loop = ControlLoop(policies=pols)
    acts = loop.consult("t0", family="frank", done=40, total=100,
                        every=20, history=_mixed_history())
    assert [a.kind for a in acts] == ["stop"]       # second proposer deduped
    assert loop.consult("t0", family="frank", done=60, total=100,
                        every=20, history=_mixed_history()) == []
    loop.observe_anomaly("tm", "acceptance_collapse")
    loop.observe_anomaly("tm", "acceptance_collapse")
    assert loop._anomalies["tm"] == ["acceptance_collapse"]


def test_loop_quantizes_segment_histogram_for_policies():
    metrics = MetricsRegistry()
    for v in (0.9, 1.1, 4.0):
        metrics.observe("segment_wall_s", v)
    seen = {}

    class Probe:
        name = "probe"

        def propose(self, view):
            seen.update(view.p95_bucket)
            return []

    ControlLoop(policies=[Probe()], metrics=metrics).consult(
        "t", family="frank", done=20, total=100, every=20)
    bucket, count = seen["segment_wall_s"]
    assert count == 3
    assert bucket in DEFAULT_EDGES


# ---------------------------------------------------------------------------
# driver integration: the early-stopped run IS the truncated schedule
# ---------------------------------------------------------------------------

def test_driver_early_stop_matches_truncated_schedule():
    cfg = ExperimentConfig(alignment=2, seed=3, **FRANK)
    built = tuple(drv.build_graph_and_plan(cfg)[:2])
    loop = ControlLoop(policies=[EarlyStopPolicy(**LOOSE)])
    data = _solo(cfg, control=loop, built=built)
    stop = data.get("early_stopped")
    assert stop == 20
    assert [(a.kind, a.tag, a.step) for a in loop.actions] == \
        [("stop", cfg.tag, 20)]
    # board family: the stop closes the run at boundary+1 yields, which
    # must be bit-identical to a fresh FIXED schedule of that length
    ref_cfg = dataclasses.replace(cfg, total_steps=stop + 1,
                                  checkpoint_every=0)
    ref = _solo(ref_cfg, built=built)
    for k in data["history"]:
        np.testing.assert_array_equal(
            np.asarray(data["history"][k]),
            np.asarray(ref["history"][k]), err_msg=f"history[{k}]")
    np.testing.assert_array_equal(np.asarray(data["waits_all"]),
                                  np.asarray(ref["waits_all"]))


@pytest.mark.slow
def test_driver_without_control_is_unchanged():
    cfg = ExperimentConfig(alignment=2, seed=3, **FRANK)
    built = tuple(drv.build_graph_and_plan(cfg)[:2])
    a = _solo(cfg, built=built)
    b = _solo(cfg, control=None, built=built)
    assert "early_stopped" not in a
    for k in a["history"]:
        np.testing.assert_array_equal(np.asarray(a["history"][k]),
                                      np.asarray(b["history"][k]))


@pytest.mark.slow
def test_temper_reshape_applies_and_checkpoints(tmp_path):
    cfg = ExperimentConfig(family="temper", alignment=0, base=1 / 0.3,
                           pop_tol=0.1, total_steps=60, n_chains=2,
                           betas=(1.0, 0.9, 0.8, 0.7), swap_every=10,
                           checkpoint_every=20, seed=29)
    loop = ControlLoop(policies=[LadderPolicy(low=0.99, high=0.999,
                                              min_attempts_per_pair=1)])
    data = drv.run_config(cfg, str(tmp_path / "out"), control=loop)
    reshapes = [a for a in loop.actions if a.kind == "reshape_ladder"]
    assert len(reshapes) == 1          # max_reshapes bound holds
    new = reshapes[0].detail["betas"]
    assert new[0] == 1.0
    assert all(a > b for a, b in zip(new, new[1:]))
    assert "rung_cut" in data          # run completed its full schedule


# ---------------------------------------------------------------------------
# service integration: batch early stop frees chains to stragglers
# ---------------------------------------------------------------------------

def test_service_batch_reallocates_stopped_tenant(tmp_path):
    cfgs = [ExperimentConfig(alignment=al, seed=seed, **FRANK)
            for al, seed in ((2, 3), (1, 4))]
    loop = ControlLoop(policies=[EarlyStopPolicy(
        tags=(cfgs[0].tag,), **LOOSE)])
    svc = SweepService(outdir=str(tmp_path), control=loop, verbose=False)
    jobs = [svc.submit(c) for c in cfgs]
    svc.run_until_idle()
    assert [j.status for j in jobs] == ["done", "done"]

    stops = [a for a in loop.actions if a.kind == "stop"]
    reallocs = [a for a in loop.actions if a.kind == "reallocate"]
    assert [(a.tag, a.step) for a in stops] == [(cfgs[0].tag, 20)]
    assert len(reallocs) == 1
    assert reallocs[0].detail["from"] == cfgs[0].tag
    assert reallocs[0].detail["to"] == [cfgs[1].tag]
    assert reallocs[0].detail["freed_chains"] == cfgs[0].n_chains

    # the stopped tenant's artifacts are the truncated fixed schedule;
    # the straggler's are its full solo run — both bit-identical
    stop = stops[0].step
    ref0 = _solo(dataclasses.replace(cfgs[0], total_steps=stop + 1,
                                     checkpoint_every=0))
    assert jobs[0].result["early_stopped"] == stop
    for k in ref0["history"]:
        np.testing.assert_array_equal(
            np.asarray(jobs[0].result["history"][k]),
            np.asarray(ref0["history"][k]), err_msg=f"stopped[{k}]")
    ref1 = _solo(dataclasses.replace(cfgs[1], checkpoint_every=0))
    for k in ref1["history"]:
        np.testing.assert_array_equal(
            np.asarray(jobs[1].result["history"][k]),
            np.asarray(ref1["history"][k]), err_msg=f"straggler[{k}]")

    # the decisions rode the service journal
    records, _ = Journal.read(svc.journal.path)
    kinds = [(r["action"], r["tag"]) for r in records
             if r["kind"] == "control_action"]
    assert kinds == [("stop", cfgs[0].tag), ("reallocate", "b0000")]


@pytest.mark.slow
def test_service_without_control_keeps_batch_path(tmp_path):
    cfgs = [ExperimentConfig(alignment=al, seed=seed, **FRANK)
            for al, seed in ((2, 3), (1, 4))]
    svc = SweepService(outdir=str(tmp_path), verbose=False)
    jobs = [svc.submit(c) for c in cfgs]
    svc.run_until_idle()
    assert all(j.status == "done" for j in jobs)
    assert all("early_stopped" not in j.result for j in jobs)


# ---------------------------------------------------------------------------
# tiny-history diagnostics guards (satellite)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("t", [1, 2, 3])
def test_tiny_history_ess_host_device_parity(t):
    x = np.arange(2 * t, dtype=np.float64).reshape(2, t)
    per_h, tot_h = ess(x)
    np.testing.assert_allclose(per_h, np.full(2, float(t)))
    assert tot_h == 2.0 * t
    per_d, tot_d = ess_device(x)
    np.testing.assert_allclose(np.asarray(per_d), per_h)
    assert float(tot_d) == tot_h


def test_tiny_history_ess_single_chain():
    per, tot = ess(np.asarray([0.5, 1.5]))
    np.testing.assert_allclose(per, [2.0])
    assert tot == 2.0


# ---------------------------------------------------------------------------
# the tier-1 gate itself
# ---------------------------------------------------------------------------

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_control_check_gate():
    """The bench leg (adaptive must beat fixed to the ESS target) plus
    the lint leg; the drain->replay story already runs in-process above
    and in tests/test_preemption.py, so the gate's replay leg is left
    to `make control-check`."""
    proc = subprocess.run(
        [os.path.join(REPO, "tools", "control_check.sh")],
        cwd=REPO, capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "CONTROL_LEGS": "lint bench"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "control-check: OK" in proc.stdout
    assert "control-check[bench]:" in proc.stdout
