"""run_tempered: single-device replica exchange (BASELINE config 4).

Three bars: (1) a 1-rung ladder is bit-identical to the plain runners —
the orchestration adds nothing when there is nothing to swap; (2) with
base=1 every valid swap accepts, so the beta assignment is a
deterministic permutation and per_rung_history must invert it exactly;
(3) on the exhaustively-enumerated small grid, the reconstructed cold
AND hot rung occupancies each match the exact stationary distribution of
their own temperature's transition matrix — the standard parallel-
tempering invariant, which breaks if the swap acceptance ratio is wrong.
"""

import numpy as np
import pytest

import flipcomplexityempirical_tpu as fce
from flipcomplexityempirical_tpu.sampling import (
    init_tempered, run_tempered, per_rung_history)

from test_enumeration import (build_masks, enumerate_states,
                              build_transition, stationary,
                              assert_matches_stationary, EPS)


@pytest.mark.parametrize("path", ["general", "board"])
@pytest.mark.slow
def test_single_rung_matches_plain_runner(path):
    g = fce.graphs.square_grid(6, 6)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(contiguity="patch")
    use_board = path == "board"
    if use_board:
        h, st, params = fce.sampling.init_board(
            g, plan, n_chains=6, seed=3, spec=spec, base=1.3, pop_tol=0.3)
        plain = fce.sampling.run_board(h, spec, params, st, n_steps=161,
                                       chunk=40)
        h2, st2, params2 = init_tempered(
            g, plan, betas=[1.0], n_ladders=6, seed=3, spec=spec,
            base=1.3, pop_tol=0.3)
    else:
        spec = fce.Spec(contiguity="patch", record_interface=True)
        h, st, params = fce.init_batch(
            g, plan, n_chains=6, seed=3, spec=spec, base=1.3, pop_tol=0.3)
        plain = fce.run_chains(h, spec, params, st, n_steps=161, chunk=40)
        h2, st2, params2 = init_tempered(
            g, plan, betas=[1.0], n_ladders=6, seed=3, spec=spec,
            base=1.3, pop_tol=0.3)
    res = run_tempered(h2, spec, params2, st2, n_steps=161,
                       betas=[1.0], n_ladders=6, swap_every=40)
    assert set(res.history) == set(plain.history)
    for k in plain.history:
        np.testing.assert_array_equal(res.history[k], plain.history[k],
                                      err_msg=k)
    sp, st_ = plain.host_state(), res.host_state()
    for fld in sp.__dataclass_fields__:
        np.testing.assert_array_equal(np.asarray(getattr(sp, fld)),
                                      np.asarray(getattr(st_, fld)),
                                      err_msg=fld)
    np.testing.assert_allclose(res.waits_total, plain.waits_total)
    assert res.swap_attempts.sum() == 0


@pytest.mark.slow
def test_base1_deterministic_swaps_and_rung_reconstruction():
    """At base=1 the swap log-ratio is 0 > log(u), so every valid pair
    exchanges every round: beta_hist follows the deterministic even-odd
    brickwork, and per_rung_history must invert it column-exactly."""
    g = fce.graphs.square_grid(5, 5)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(contiguity="patch")
    betas = [1.0, 0.75, 0.5, 0.25]
    h, st, params = init_tempered(g, plan, betas=betas, n_ladders=3,
                                  seed=7, spec=spec, base=1.0, pop_tol=0.5)
    res = run_tempered(h, spec, params, st, n_steps=121, betas=betas,
                       n_ladders=3, swap_every=20)
    n_rounds = res.beta_hist.shape[0]
    assert n_rounds == 6
    assert res.swap_rates().min() == 1.0

    # expected assignment: swaps pair adjacent RANKS (rank follows the
    # temperature, not the batch position): parity-0 rounds exchange rank
    # pairs (0,1) and (2,3), parity-1 rounds (1,2). Track which position
    # holds each rank; every valid pair accepts at base=1.
    b32 = np.asarray(betas, np.float32)
    pos_of_rank = np.arange(4)
    rows = []
    for rnd in range(n_rounds):
        row = np.empty(4, np.float32)
        row[pos_of_rank] = b32
        rows.append(row.copy())
        for r in range(3):
            if r % 2 == rnd % 2:
                pos_of_rank[[r, r + 1]] = pos_of_rank[[r + 1, r]]
    expect = np.stack(rows)                        # (rounds, 4)
    np.testing.assert_array_equal(res.beta_hist,
                                  np.tile(expect, (1, 3)))

    # reconstruction: rung r's trajectory equals the per-chain history
    # read through the inverse permutation
    rung = per_rung_history(res, "cut_count")      # (4, 3, T)
    h_all = np.asarray(res.history["cut_count"])   # (12, T)
    t_rec = h_all.shape[1]
    for t in range(t_rec):
        rnd = min(t // 20, n_rounds - 1)
        for r, b in enumerate(b32):
            for l in range(3):
                j = int(np.argmax(expect[rnd] == b))
                assert rung[r, l, t] == h_all[l * 4 + j, t]


def _joint_tempered_stationary(P1, P2, cuts, lb, b1, b2):
    """Exact time-averaged distribution of the 2-rung tempered chain with
    swap_every=1 and the implementation's alternating parity (parity-1
    rounds have no valid pair at 2 rungs, so they are identity): the
    recorded-yield distribution obeys v_{t+1} = S_{t%2}(P(v_t)) over the
    joint (cold state, hot state) space, independent numpy throughout."""
    n = P1.shape[0]
    d = cuts[:, None] - cuts[None, :]
    a_ij = np.minimum(1.0, np.exp(lb * (b1 - b2) * d))      # a(i, j)

    def step_p(v):
        return P1.T @ v @ P2

    def swap(v):
        return v * (1 - a_ij) + v.T * a_ij.T

    v = np.full((n, n), 1.0 / (n * n))
    for _ in range(4000):
        nxt = step_p(swap(step_p(v)))                       # M_even
        if np.abs(nxt - v).max() < 1e-13:
            break
        v = nxt
    v /= v.sum()
    avg = (v + swap(step_p(v))) / 2                         # both phases
    return avg / avg.sum()


@pytest.mark.slow
def test_rungs_match_exact_joint_stationary():
    """Cold (beta=1) and hot (beta=0.5) rung occupancies, reconstructed
    through the swap record, vs the EXACT marginals of the tempered
    chain's joint stationary distribution — this fails if the swap
    acceptance ratio, cadence, or rung bookkeeping is wrong."""
    base = 3.0
    b1, b2 = 1.0, 0.5
    g, nbrmask = build_masks()
    states = enumerate_states(nbrmask)
    P1, cuts = build_transition(states, g, base ** b1)
    P2, _ = build_transition(states, g, base ** b2)
    avg = _joint_tempered_stationary(P1, P2, cuts.astype(np.float64),
                                     np.log(base), b1, b2)
    pi_cold = avg.sum(axis=1)
    pi_hot = avg.sum(axis=0)

    spec = fce.Spec(contiguity="patch", record_assignment_bits=True,
                    geom_waits=False, parity_metrics=False)
    plan = fce.graphs.stripes_plan(g, 2)
    n_ladders, steps, burn = 48, 12001, 3000
    h, st, params = init_tempered(g, plan, betas=[b1, b2],
                                  n_ladders=n_ladders, seed=11, spec=spec,
                                  base=base, pop_tol=EPS)
    res = run_tempered(h, spec, params, st, n_steps=steps,
                       betas=[b1, b2], n_ladders=n_ladders, swap_every=1)
    assert res.swap_rates().min() > 0.05
    rung = per_rung_history(res, "abits")          # (2, L, T)
    for r, pi in ((0, pi_cold), (1, pi_hot)):
        assert_matches_stationary(rung[r][:, burn:].ravel(),
                                  states, pi, cuts)


def test_host_rungs_pinned_to_device_chain_rungs():
    """tempered._host_rungs is a numpy mirror of tempering.chain_rungs;
    the swap bookkeeping silently depends on the two staying in lockstep
    (ADVICE r4). Pin them on ladders WITH duplicate betas — the case
    where a stable-sort divergence would first show — across random
    permutations of rung-to-position assignments."""
    import jax.numpy as jnp
    from flipcomplexityempirical_tpu.sampling.tempered import _host_rungs
    from flipcomplexityempirical_tpu.sampling.tempering import chain_rungs

    rng = np.random.default_rng(3)
    for ladder in ([2.0, 1.0, 1.0, 0.5],
                   [1.0, 1.0, 1.0],
                   [4.0, 2.0, 1.0, 0.5, 0.25]):
        n_rungs = len(ladder)
        for _ in range(20):
            perm = np.stack([rng.permutation(ladder) for _ in range(6)])
            beta = np.asarray(perm, np.float32).reshape(-1)
            dev, _ = chain_rungs(jnp.asarray(beta), n_rungs)
            np.testing.assert_array_equal(
                _host_rungs(beta, n_rungs), np.asarray(dev))


@pytest.mark.slow
def test_tempered_mixes_bimodal_better_than_plain():
    """The scientific payoff of BASELINE config 4, kept continuously
    true (VERDICT r4): on the bimodal FRANK B333 cell, the TEMPER_BETAS
    ladder's reconstructed cold-rung trajectories complete strictly more
    well round trips per chain than plain beta=1 chains on the same
    per-chain budget. Reduced-budget calibration (20k steps, seed 0):
    tempered 13 completed round trips over 6 ladders vs plain 1 over 8
    chains — asserted with a wide margin below."""
    import importlib.util
    import pathlib

    path = (pathlib.Path(__file__).resolve().parent.parent
            / "replication" / "compare_tempering.py")
    mspec = importlib.util.spec_from_file_location("compare_tempering",
                                                   path)
    mod = importlib.util.module_from_spec(mspec)
    mspec.loader.exec_module(mod)

    rec = mod.run_comparison(steps=20001, plain_chains=8, ladders=6,
                             swap_every=50, seed=0, record_every=5)
    plain_rt = np.asarray(rec["plain"]["round_trips"])
    cold_rt = np.asarray(rec["tempered_cold_rung"]["round_trips"])
    swap_rates = np.asarray(rec["tempered_cold_rung"]["swap_rates"])
    # the ladder itself must be healthy end to end, or the cold rung is
    # just a plain chain with extra steps
    assert swap_rates.min() > 0.2
    # strictly better mode mixing per chain, with margin: the calibrated
    # ratio is ~17x, the assertion only demands 3x
    assert cold_rt.mean() > 3 * max(plain_rt.mean(), 1 / len(plain_rt))
    # and the plain arm reproduces its REPLICATION.md signature: chains
    # relax one-way and (almost) never complete a round trip
    assert plain_rt.mean() < 0.5
