"""Oracle backend tests: chain invariants, contiguity vs networkx, updater
incrementality. The oracle must be trustworthy before it can validate the
vectorized kernel."""

import numpy as np
import networkx as nx
import pytest

from flipcomplexityempirical_tpu import graphs
from flipcomplexityempirical_tpu import compat


def small_chain(n=6, base=1.0, eps=0.5, steps=500, seed=0, accept="literal"):
    rng = np.random.default_rng(seed)
    lat = graphs.square_grid(n, n)
    plan = graphs.stripes_plan(lat, 2)
    # reference labels are +1/-1 (grid_chain_sec11.py:195): map 0->+1, 1->-1
    signed = {lab: 1 - 2 * int(plan[i]) for i, lab in enumerate(lat.labels)}
    updaters = {
        "population": compat.Tally("population"),
        "cut_edges": compat.cut_edges,
        "b_nodes": compat.b_nodes_bi,
        "base": lambda p: base,
        "geom": compat.make_geom_wait(rng),
        "step_num": compat.step_num,
    }
    part = compat.Partition(lat, signed, updaters)
    popbound = compat.within_percent_of_ideal_population(part, eps)
    make = (compat.make_cut_accept if accept == "literal"
            else compat.make_corrected_cut_accept)
    chain = compat.MarkovChain(
        compat.make_reversible_propose_bi(rng),
        compat.Validator([compat.single_flip_contiguous, popbound]),
        make(rng), part, steps)
    return lat, chain, popbound


def test_chain_yield_semantics():
    lat, chain, _ = small_chain(steps=50)
    states = list(chain)
    assert len(states) == 50
    assert states[0].flips is None  # initial state yielded first
    # each subsequent yielded state is either the same object (self-loop) or
    # a child created by a single flip
    for prev, cur in zip(states, states[1:]):
        assert cur is prev or (cur.flips is not None and len(cur.flips) == 1)


def test_chain_invariants_maintained():
    lat, chain, popbound = small_chain(steps=400, base=0.7, eps=0.1, seed=3)
    g = nx.Graph(list(map(tuple, lat.edges)))
    ideal = lat.n_nodes / 2
    for t, part in enumerate(chain):
        pops = part["population"]
        assert min(pops.values()) >= (1 - 0.1) * ideal - 1e-9
        assert max(pops.values()) <= (1 + 0.1) * ideal + 1e-9
        if t % 50 == 0:  # full connectivity check is slow; sample it
            a = part.assignment_array
            for dist in (1, -1):
                sub = g.subgraph(np.nonzero(a == dist)[0].tolist())
                assert sub.number_of_nodes() > 0
                assert nx.is_connected(sub)


def test_cut_edges_incremental_matches_bruteforce():
    lat, chain, _ = small_chain(steps=200, base=1.3, seed=5)
    for t, part in enumerate(chain):
        if t % 25 == 0:
            a = part.assignment_array
            brute = {(lat.labels[e[0]], lat.labels[e[1]])
                     for e in lat.edges if a[e[0]] != a[e[1]]}
            assert part["cut_edges"] == brute
            tal = part["population"]
            for d in tal:
                assert tal[d] == int((a == d).sum())


def test_single_flip_contiguous_vs_networkx():
    rng = np.random.default_rng(7)
    lat = graphs.square_grid(5, 5)
    g = nx.grid_2d_graph(5, 5)
    plan = graphs.stripes_plan(lat, 2)
    signed = {lab: 1 - 2 * int(plan[i]) for i, lab in enumerate(lat.labels)}
    part = compat.Partition(lat, signed, {"cut_edges": compat.cut_edges})
    agree = 0
    for _ in range(300):
        # random boundary flip (may or may not disconnect)
        bn = sorted({u for e in part["cut_edges"] for u in e})
        lab = bn[rng.integers(len(bn))]
        child = part.flip({lab: -part.assignment[lab]})
        got = compat.single_flip_contiguous(child)
        # networkx oracle: all districts of the child connected
        a = child.assignment_array
        want = all(
            nx.is_connected(g.subgraph(
                [lat.labels[i] for i in np.nonzero(a == d)[0]]))
            for d in (1, -1) if (a == d).any())
        assert got == want
        agree += 1
        if got:
            part = child  # walk only through valid states
    assert agree == 300


def test_corrected_accept_runs():
    lat, chain, _ = small_chain(steps=100, base=2.0, accept="corrected")
    states = list(chain)
    assert len(states) == 100


def test_pairs_proposal_k_districts():
    rng = np.random.default_rng(11)
    lat = graphs.square_grid(8, 8)
    plan = graphs.stripes_plan(lat, 4)
    d = {lab: int(plan[i]) for i, lab in enumerate(lat.labels)}
    updaters = {
        "population": compat.Tally("population"),
        "cut_edges": compat.cut_edges,
        "b_nodes": compat.b_nodes_pairs,
    }
    part = compat.Partition(lat, d, updaters)
    popbound = compat.within_percent_of_ideal_population(part, 0.5)
    chain = compat.MarkovChain(
        compat.make_reversible_propose_pairs(rng),
        compat.Validator([compat.single_flip_contiguous, popbound]),
        compat.always_accept, part, 300)
    last = None
    for p in chain:
        last = p
    assert len(last.parts) == 4  # no district vanished
    g = nx.Graph(list(map(tuple, lat.edges)))
    a = last.assignment_array
    for dist in range(4):
        sub = g.subgraph(np.nonzero(a == dist)[0].tolist())
        assert nx.is_connected(sub)


def _frame_chain_parts(n=6, seed=0):
    rng = np.random.default_rng(seed)
    lat = graphs.square_grid(n, n)
    plan = graphs.stripes_plan(lat, 2)
    signed = {lab: 1 - 2 * int(plan[i]) for i, lab in enumerate(lat.labels)}
    updaters = {
        "population": compat.Tally("population"),
        "cut_edges": compat.cut_edges,
        "b_nodes": compat.b_nodes_bi,
        "boundary": compat.bnodes_p,
        "step_num": compat.step_num,
    }
    part = compat.Partition(lat, signed, updaters)
    return rng, lat, part


def test_boundary_condition_and_bnodes_p():
    rng, lat, part = _frame_chain_parts()
    # stripes plan: the frame touches both districts
    assert compat.boundary_condition(part)
    assert set(part["boundary"]) == {
        lat.labels[i] for i in np.nonzero(lat.frame_mask)[0]}
    # all-one-district partition: frame touches one district only
    mono = compat.Partition(
        lat, {lab: 1 for lab in lat.labels},
        {"boundary": compat.bnodes_p, "cut_edges": compat.cut_edges})
    assert not compat.boundary_condition(mono)


def test_fixed_endpoints_predicate():
    _, lat, part = _frame_chain_parts()
    # vertical stripes on 6x6: (2,y) and (3,y) straddle the boundary
    pred = compat.make_fixed_endpoints(
        pairs=(((2, 0), (3, 0)), ((2, 5), (3, 5))))
    assert pred(part)
    bad = compat.make_fixed_endpoints(pairs=(((0, 0), (0, 1)),))
    assert not bad(part)


def test_uniform_accept_requires_frame_interface():
    rng, lat, part = _frame_chain_parts()
    popbound = compat.within_percent_of_ideal_population(part, 0.9)
    acc = compat.make_uniform_accept(rng, popbound)
    # initial state: parent is None so single_flip_contiguous falls back to
    # full contiguity; stripes are contiguous and touch the frame => accept
    assert acc(part)


def test_linear_beta_schedule_matches_commented_reference():
    beta = compat.linear_beta_schedule(t0=100000, ramp=100000, beta_max=3)
    assert beta(0) == 0.0
    assert beta(100000) == 0.0
    assert beta(250000) == pytest.approx(1.5)
    assert beta(400000) == pytest.approx(3.0)
    assert beta(10**7) == pytest.approx(3.0)


def test_annealing_accept_matches_analytic_bound():
    # Fixed parent and fixed (cut-increasing) child: the acceptance
    # frequency must match base**(beta*delta) * |b(child)|/|b(parent)|.
    rng, lat, part = _frame_chain_parts(seed=5)
    popbound = compat.within_percent_of_ideal_population(part, 0.9)
    base, beta = 10.0, 1.0
    acc = compat.make_annealing_cut_accept_backwards(
        rng, popbound, base=base, beta=beta)
    child = part.flip({(2, 0): -part.assignment[(2, 0)]})
    delta = -len(child["cut_edges"]) + len(part["cut_edges"])
    assert delta == -1  # flipping a stripe-edge corner node adds one cut
    b1 = {x for e in child["cut_edges"] for x in e}
    b2 = {x for e in part["cut_edges"] for x in e}
    expected = (base ** (beta * delta)) * (len(b1) / len(b2))
    assert 0.01 < expected < 0.99
    n = 4000
    freq = sum(acc(child) for _ in range(n)) / n
    assert abs(freq - expected) < 4 * np.sqrt(expected * (1 - expected) / n)
