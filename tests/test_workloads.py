"""Workload catalog tests: registry round-trip + fingerprint stability,
kernel-path expectations against the live dispatch ladder, the committed
dual-graph fixture end-to-end through run_config (partisan artifacts
included), the ReCom chunked runner's obs contract and reject taxonomy,
proposal-variant Spec mapping, and (slow tier) k=4 flip stationarity
against the exact uniform target."""

import json
import os

import numpy as np
import pytest

import flipcomplexityempirical_tpu as fce
from flipcomplexityempirical_tpu import obs, workloads
from flipcomplexityempirical_tpu.experiments import driver as drv
from flipcomplexityempirical_tpu.workloads.data import load_fixture


# ---------------------------------------------------------------------------
# registry round-trip + fingerprints
# ---------------------------------------------------------------------------

def test_catalog_roundtrip():
    names = workloads.names()
    assert len(names) >= 10
    for must in ("sec11", "frank", "grid-k2", "grid-k4", "grid-k8",
                 "dual-fixture", "recom-grid", "sec11-nobacktrack",
                 "frank-lazy"):
        assert must in names
    for n in names:
        w = workloads.get(n)
        assert w.name == n
        cfg = w.to_config()
        assert cfg.family == w.family
        assert cfg.chain == w.chain
        assert cfg.variant == w.variant
        # CLI-style extras win over the tuned shape but never identity
        cfg2 = w.to_config(total_steps=7, n_chains=2)
        assert (cfg2.total_steps, cfg2.n_chains) == (7, 2)
        assert cfg2.family == w.family


def test_workload_fingerprints_stable_and_distinct():
    fps = {n: workloads.get(n).fingerprint() for n in workloads.names()}
    # stable across calls
    for n, fp in fps.items():
        assert workloads.get(n).fingerprint() == fp
        assert len(fp) == 16
    # distinct across entries
    assert len(set(fps.values())) == len(fps)


def test_config_fingerprint_untouched_by_default_chain():
    """Pre-existing configs must keep their exact fingerprints (journal
    and compile-cache compatibility): the chain/variant payload keys
    only appear when non-default."""
    from flipcomplexityempirical_tpu.experiments.config import \
        ExperimentConfig
    base = dict(family="kpair", alignment=0, base=0.8, pop_tol=0.5)
    a = ExperimentConfig(**base)
    b = ExperimentConfig(**base, chain="flip", variant="none")
    assert a.fingerprint() == b.fingerprint()
    assert ExperimentConfig(**base, chain="recom").fingerprint() \
        != a.fingerprint()
    assert ExperimentConfig(**base, variant="lazy").fingerprint() \
        != a.fingerprint()
    # tags segregate artifacts/checkpoints per chain family and variant
    assert ExperimentConfig(**base, chain="recom").tag.startswith("recom-")
    assert ExperimentConfig(**base, variant="lazy").tag.endswith("-LAZY")


def test_resolve_matches_declared_kernel_paths():
    """Every catalog entry materialises through the driver's own
    builders, and the dispatch ladder resolves the rung the entry
    declares — a workload silently falling off its fast path fails."""
    for n in workloads.names():
        r = workloads.resolve(n)
        assert r.kernel_path == r.workload.kernel_path, \
            f"{n}: declared {r.workload.kernel_path}, got {r.kernel_path}"
        assert r.plan.shape == (r.graph.n_nodes,)
        k = r.config.n_districts
        assert set(np.unique(np.asarray(r.plan))) == set(range(k))


def test_stencil_rejected_families_resolve_to_general_dense():
    """The dual-fixture family and the proposal variants are exactly the
    workloads the stencil pass rejects; since ISSUE 15 they must land on
    the rejection-free general_dense rung, not the legacy general kernel
    — pinned here explicitly (the declaration-vs-resolution test above
    would still pass if both quietly reverted together)."""
    for n in ("dual-fixture", "dual-fixture-k4", "dual-fixture-k8",
              "sec11-nobacktrack", "frank-lazy"):
        assert workloads.resolve(n).kernel_path == "general_dense", n


# ---------------------------------------------------------------------------
# dual-graph fixture: ingestion + end-to-end sweep
# ---------------------------------------------------------------------------

def test_fixture_loads_through_real_ingestion():
    fc = load_fixture()
    assert fc["type"] == "FeatureCollection"
    assert len(fc["features"]) == 80
    g, geo = fce.graphs.from_geojson(fc, pop_property="POP")
    assert g.n_nodes == 80
    assert np.asarray(g.pop).shape == (80,)
    assert (np.asarray(g.pop) > 0).all()
    assert geo.area.shape == (80,)
    # deterministic: same committed bytes -> same graph every session
    g2, _ = fce.graphs.from_geojson(load_fixture(), pop_property="POP")
    np.testing.assert_array_equal(g.edges, g2.edges)


def test_dual_fixture_workload_end_to_end(tmp_path):
    """--workload dual-fixture equivalent: run_config on the committed
    fixture emits the full dual manifest, partisan.json included."""
    cfg = workloads.get("dual-fixture").to_config(total_steps=120,
                                                  n_chains=2)
    drv.run_config(cfg, str(tmp_path))
    from flipcomplexityempirical_tpu.experiments.artifacts import \
        artifact_kinds
    for kind in artifact_kinds("dual"):
        assert os.path.exists(str(tmp_path / (cfg.tag + kind))), kind
    with open(str(tmp_path / (cfg.tag + "partisan.json"))) as f:
        partisan = json.load(f)
    assert set(partisan) == {"mean_median", "efficiency_gap",
                             "seats_pink"}
    assert len(partisan["efficiency_gap"]) == cfg.n_chains


def test_validate_votes_rejects_misalignment():
    from flipcomplexityempirical_tpu.graphs import (VoteAlignmentError,
                                                    validate_votes)
    g, _ = fce.graphs.from_geojson(load_fixture(), pop_property="POP")
    votes = fce.graphs.seed_votes(g, 0)
    out = validate_votes(g, votes)
    assert out.shape == (g.n_nodes, 2)
    with pytest.raises(VoteAlignmentError):
        validate_votes(g, votes[:-1])
    with pytest.raises(VoteAlignmentError):
        validate_votes(g, votes[:, :1])
    bad = np.array(votes, dtype=float)
    bad[0, 0] = np.nan
    with pytest.raises(VoteAlignmentError):
        validate_votes(g, bad)


# ---------------------------------------------------------------------------
# ReCom as a served chain family
# ---------------------------------------------------------------------------

def test_run_recom_events_and_reject_taxonomy(tmp_path):
    """The chunked ReCom runner mirrors run_chains' obs contract — every
    event tagged runner/path 'recom' — and its reject taxonomy accounts
    for every proposal: reject.sum() + accepted == proposals."""
    g = fce.graphs.square_grid(6, 6)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(n_districts=2, proposal="bi")
    dg, states, params = fce.init_batch(g, plan, n_chains=2, seed=5,
                                        spec=spec, base=1.0, pop_tol=0.5)
    path = str(tmp_path / "recom_events.jsonl")
    with obs.Recorder(path=path) as rec:
        res = fce.sampling.run_recom(dg, spec, params, states,
                                     n_steps=12, epsilon=0.4,
                                     recorder=rec)
    events = [json.loads(l) for l in open(path)]
    kinds = [e["event"] for e in events]
    assert "run_start" in kinds and "run_end" in kinds
    chunks = [e for e in events if e["event"] == "chunk"]
    assert chunks
    for e in events:
        if "runner" in e:
            assert e["runner"] == "recom"
        if "kernel_path" in e:
            assert e["kernel_path"] == "recom"
    total = {"accepted": 0, "proposals": 0, "rej": 0}
    for c in chunks:
        rej = c["reject"]
        assert (rej["nonboundary"] + rej["pop"] + rej["disconnect"]
                + rej["metropolis"] + rej["accepted"]
                == rej["proposals"])
        total["accepted"] += rej["accepted"]
        total["proposals"] += rej["proposals"]
    # chains x (steps - 1): the first yield records the initial state
    assert total["proposals"] == 2 * (12 - 1)
    # final states stay valid partitions
    a = np.asarray(res.state.assignment)
    for c in range(a.shape[0]):
        assert set(np.unique(a[c])) == {0, 1}


def test_recom_workload_routes_through_driver(tmp_path):
    """cfg.chain='recom' takes the driver's recom segment branch and
    lands the standard kpair artifact manifest under the recom- tag."""
    cfg = workloads.get("recom-grid").to_config(total_steps=10,
                                                n_chains=2)
    assert cfg.tag.startswith("recom-")
    drv.run_config(cfg, str(tmp_path))
    from flipcomplexityempirical_tpu.experiments.artifacts import \
        artifact_kinds
    for kind in artifact_kinds("kpair"):
        assert os.path.exists(str(tmp_path / (cfg.tag + kind))), kind


# ---------------------------------------------------------------------------
# proposal variants
# ---------------------------------------------------------------------------

def test_variant_spec_mapping():
    import dataclasses
    from flipcomplexityempirical_tpu.experiments.config import \
        ExperimentConfig
    base = dict(family="sec11", alignment=2, base=1.0, pop_tol=0.1)
    s0 = drv.spec_for(ExperimentConfig(**base))
    assert not s0.nobacktrack and not s0.lazy_uniform
    s1 = drv.spec_for(ExperimentConfig(**base, variant="nobacktrack"))
    # a variant config differs from its base by exactly that flag
    assert s1 == dataclasses.replace(s0, nobacktrack=True)
    s2 = drv.spec_for(ExperimentConfig(**base, variant="lazy"))
    assert s2 == dataclasses.replace(s0, lazy_uniform=True)
    # nobacktrack is a bi-walk variant: the pair walk has no single
    # last-flipped node to exclude
    with pytest.raises(ValueError):
        drv.spec_for(ExperimentConfig(family="kpair", alignment=0,
                                      base=1.0, pop_tol=0.5,
                                      n_districts=4,
                                      variant="nobacktrack"))
    # variants fall off the board fast path (kernel/board.py supports)
    g = fce.graphs.grid_sec11()
    from flipcomplexityempirical_tpu.kernel import board as kboard
    assert not kboard.supports(g, s1)
    assert not kboard.supports(g, s2)


def test_nobacktrack_never_reflips_after_accept():
    """Non-backtracking flip (arxiv 1204.4140): the last-accepted node
    is excluded from the proposal draw, so two consecutive accepted
    moves never touch the same node. Verified by decoding the flip
    sequence from the packed per-step assignments."""
    g = fce.graphs.square_grid(4, 6)       # 24 nodes: abits fits uint32
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(n_districts=2, proposal="bi", nobacktrack=True,
                    record_assignment_bits=True, geom_waits=False,
                    parity_metrics=False)
    dg, states, params = fce.init_batch(g, plan, n_chains=4, seed=9,
                                        spec=spec, base=1.0, pop_tol=0.5)
    res = fce.run_chains(dg, spec, params, states, n_steps=400)
    ab = np.asarray(res.history["abits"])         # (C, T) uint32
    for c in range(ab.shape[0]):
        d = ab[c, 1:] ^ ab[c, :-1]
        flips = d[d != 0]
        # single-node moves only...
        assert (np.bitwise_and(flips, flips - 1) == 0).all()
        nodes = np.array([int(x).bit_length() - 1 for x in flips])
        assert nodes.size > 10
        # ...and never the same node twice in a row
        assert (nodes[1:] != nodes[:-1]).all()


def test_lazy_uniform_weights_ride_waits():
    """Lazy-uniform reweighting: recorded per-sample weight is
    1 + the geometric wait the sample would repeat for."""
    g = fce.graphs.square_grid(6, 6)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(n_districts=2, proposal="bi", lazy_uniform=True)
    dg, states, params = fce.init_batch(g, plan, n_chains=2, seed=11,
                                        spec=spec, base=2.0, pop_tol=0.5)
    res = fce.run_chains(dg, spec, params, states, n_steps=200)
    w = np.asarray(res.history["weight"])
    waits = np.asarray(res.history["wait"])
    assert (w >= 1.0).all()
    np.testing.assert_allclose(w, 1.0 + waits)


# ---------------------------------------------------------------------------
# k=4 stationarity (slow tier): corrected/selfloop chain at base=1 is
# reversible w.r.t. the UNIFORM distribution on valid states
# ---------------------------------------------------------------------------

def _enumerate_k4(g, lo, hi):
    """All 4-labelings of the 2x4 grid with every district nonempty,
    connected, and sized in [lo, hi]; encoded 2 bits/node to match
    record_assignment_bits' k=4 packing."""
    import networkx as nx
    n = g.n_nodes
    gx = nx.Graph(list(map(tuple, g.edges)))
    states = []
    for m in range(4 ** n):
        digs, t = [], m
        for _ in range(n):
            digs.append(t % 4)
            t //= 4
        sizes = np.bincount(digs, minlength=4)
        if not ((sizes >= lo) & (sizes <= hi)).all():
            continue
        ok = True
        for d in range(4):
            members = [v for v in range(n) if digs[v] == d]
            if not nx.is_connected(gx.subgraph(members)):
                ok = False
                break
        if ok:
            states.append(sum(d << (2 * v) for v, d in enumerate(digs)))
    return states


@pytest.mark.slow
def test_flip_k4_stationarity_chi2():
    """k=4 pair walk, accept='corrected' + invalid='selfloop' at base=1:
    the chain is reversible w.r.t. the uniform distribution on the valid
    states, so thinned occupancy counts face a chi-squared bar (generous
    threshold: samples are thinned but still weakly correlated)."""
    g = fce.graphs.square_grid(2, 4)
    lo, hi = 1, 3                      # ideal 2, pop_tol 0.5 -> [1, 3]
    states = _enumerate_k4(g, lo, hi)
    assert len(states) > 20
    index = {m: i for i, m in enumerate(states)}

    spec = fce.Spec(n_districts=4, proposal="pair", accept="corrected",
                    invalid="selfloop", contiguity="patch",
                    record_assignment_bits=True, geom_waits=False,
                    parity_metrics=False)
    plan = fce.graphs.stripes_plan(g, 4, axis=1)
    # thin=30 decorrelates enough for the chi-squared approximation;
    # 64 x 900 samples put ~31 expected counts in each of the 1848 cells
    chains, steps, burn, thin = 64, 30000, 3000, 30
    dg, st, params = fce.init_batch(g, plan, n_chains=chains, seed=17,
                                    spec=spec, base=1.0, pop_tol=0.5)
    res = fce.run_chains(dg, spec, params, st, n_steps=steps)
    abits = np.asarray(res.history["abits"])[:, burn::thin].ravel()
    # KeyError here = the chain visited a state outside the valid set
    idx = np.array([index[int(m)] for m in abits])
    counts = np.bincount(idx, minlength=len(states)).astype(float)
    expected = counts.sum() / len(states)
    stat = float(((counts - expected) ** 2 / expected).sum())
    df = len(states) - 1
    assert stat < df + 6.0 * np.sqrt(2.0 * df), \
        f"chi2 {stat:.1f} vs df {df} (|S|={len(states)})"
