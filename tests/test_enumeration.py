"""Exact-distribution validation (SURVEY.md section 4.1): on a small grid,
enumerate every valid state, build the EXACT transition matrix of the chain
as specified (re-propose-on-invalid, uniform-over-valid proposals, literal
cut_accept), power-iterate to its stationary distribution, and compare the
vectorized kernel's empirical occupancy against it.

This is strictly stronger than testing against pi ∝ base^(-|cut|): the
literal reference chain is NOT exactly reversible (missing |b_nodes|
correction + validity conditioning), so the honest target is the actual
stationary distribution of the specified transition kernel — which this
test computes independently of the JAX implementation.
"""

import numpy as np
import pytest

import flipcomplexityempirical_tpu as fce


NX, NY = 3, 4          # 12 nodes -> 4096 assignments, exhaustive
EPS = 0.5              # pops within [3, 9]
N = NX * NY


def build_masks(nx=NX, ny=NY):
    g = fce.graphs.square_grid(nx, ny)
    n = nx * ny
    nbrmask = [0] * n  # python ints: arbitrary-precision bit ops
    for i in range(n):
        for j in g.nbr[i][g.nbr_mask[i]]:
            nbrmask[i] |= 1 << int(j)
    return g, nbrmask


def connected_bitmask(mask, nbrmask):
    if mask == 0:
        return False
    start = mask & (-mask)
    reach = start
    while True:
        grow = reach
        m = reach
        while m:
            b = m & (-m)
            grow |= nbrmask[b.bit_length() - 1]
            m ^= b
        grow &= mask
        if grow == reach:
            return reach == mask
        reach = grow


def enumerate_states(nbrmask):
    """All 2-labelings (district-1 bitmask) with both districts connected
    and pops within bounds."""
    full = (1 << N) - 1
    ideal = N / 2
    lo, hi = (1 - EPS) * ideal, (1 + EPS) * ideal
    states = []
    for m in range(1, full):
        p1 = bin(m).count("1")
        if not (lo <= p1 <= hi and lo <= N - p1 <= hi):
            continue
        if connected_bitmask(m, nbrmask) and \
                connected_bitmask(full ^ m, nbrmask):
            states.append(m)
    return states


def cut_count_of(m, edges):
    a = np.array([(m >> i) & 1 for i in range(N)])
    return int((a[edges[:, 0]] != a[edges[:, 1]]).sum())


def build_transition(states, g, base):
    """Row-stochastic matrix of the re-propose chain with literal accept."""
    index = {m: i for i, m in enumerate(states)}
    edges = g.edges
    cuts = np.array([cut_count_of(m, edges) for m in states])
    n = len(states)
    P = np.zeros((n, n))
    for i, m in enumerate(states):
        a = np.array([(m >> v) & 1 for v in range(N)])
        cut = a[edges[:, 0]] != a[edges[:, 1]]
        bnodes = np.unique(edges[cut].ravel())
        # valid moves: flips landing in the enumerated state set
        moves = []
        for v in bnodes:
            m2 = m ^ (1 << int(v))
            j = index.get(m2)
            if j is not None:
                moves.append(j)
        V = len(moves)
        assert V > 0
        stay = 0.0
        for j in moves:
            acc = min(1.0, base ** (cuts[i] - cuts[j]))
            P[i, j] += acc / V
            stay += (1 - acc) / V
        P[i, i] += stay
    assert np.allclose(P.sum(axis=1), 1.0)
    return P, cuts


def stationary(P):
    pi = np.full(P.shape[0], 1.0 / P.shape[0])
    for _ in range(20000):
        nxt = pi @ P
        if np.abs(nxt - pi).max() < 1e-13:
            break
        pi = nxt
    return pi / pi.sum()


def assert_matches_stationary(abits, states, pi, cuts,
                              tv_tol=0.06, cut_tol=0.02):
    """Empirical occupancy vs the exact stationary distribution: decode
    the packed assignments (KeyError => the chain visited an invalid
    state), bound the total-variation distance and the E[|cut|] error."""
    index = {m: i for i, m in enumerate(states)}
    idx = np.array([index[int(m)] for m in abits])
    emp = np.bincount(idx, minlength=len(states)).astype(float)
    emp /= emp.sum()
    tv = 0.5 * np.abs(emp - pi).sum()
    assert tv < tv_tol, f"TV distance {tv:.4f} (|S|={len(states)})"
    e_cut_exact = float((pi * cuts).sum())
    e_cut_emp = float((emp * cuts).sum())
    assert abs(e_cut_emp - e_cut_exact) / e_cut_exact < cut_tol, \
        (e_cut_emp, e_cut_exact)


@pytest.mark.parametrize("base", [0.5, 1.0, 2.0])
@pytest.mark.slow
def test_kernel_matches_exact_stationary(base):
    g, nbrmask = build_masks()
    states = enumerate_states(nbrmask)
    P, cuts = build_transition(states, g, base)
    pi = stationary(P)

    spec = fce.Spec(contiguity="patch", record_assignment_bits=True,
                    geom_waits=False, parity_metrics=False)
    plan = fce.graphs.stripes_plan(g, 2)
    chains, steps, burn = 48, 12000, 2000
    dg, st, params = fce.init_batch(g, plan, n_chains=chains, seed=42,
                                    spec=spec, base=base, pop_tol=EPS)
    res = fce.run_chains(dg, spec, params, st, n_steps=steps)
    assert_matches_stationary(res.history["abits"][:, burn:].ravel(),
                              states, pi, cuts)


@pytest.mark.slow
def test_corrected_accept_matches_reversible_target():
    """With the |b_nodes| correction AND selfloop invalid policy, the chain
    IS reversible w.r.t. pi ∝ base^(-|cut|) on the valid-state space: the
    proposal is uniform over b_nodes (invalid moves become rejections), and
    the acceptance carries the b-count ratio."""
    base = 1.6
    g, nbrmask = build_masks()
    states = enumerate_states(nbrmask)
    edges = g.edges
    cuts = np.array([cut_count_of(m, edges) for m in states])
    target = np.asarray([base ** (-c) for c in cuts], dtype=float)
    target /= target.sum()

    spec = fce.Spec(contiguity="patch", record_assignment_bits=True,
                    geom_waits=False, parity_metrics=False,
                    accept="corrected", invalid="selfloop")
    plan = fce.graphs.stripes_plan(g, 2)
    chains, steps, burn = 48, 12000, 2000
    dg, st, params = fce.init_batch(g, plan, n_chains=chains, seed=7,
                                    spec=spec, base=base, pop_tol=EPS)
    res = fce.run_chains(dg, spec, params, st, n_steps=steps)
    assert_matches_stationary(res.history["abits"][:, burn:].ravel(),
                              states, target, cuts, cut_tol=np.inf)


# ---------------------------------------------------------------------------
# k=3 pair walk: exact stationary distribution on a 3x3 grid
# ---------------------------------------------------------------------------

K3_NX = K3_NY = 3
K3_N = 9
K3_EPS = 0.5           # ideal 3 -> district sizes in {2, 3, 4}


def k3_enumerate(nbrmask):
    """All 3-labelings of the 3x3 grid with every district connected and
    sized within bounds. Encoded base-4 (2 bits/node) to match
    record_assignment_bits' packing for k=3."""
    lo, hi = (1 - K3_EPS) * 3, (1 + K3_EPS) * 3
    states = []
    for m in range(3 ** K3_N):
        digs, t = [], m
        for _ in range(K3_N):
            digs.append(t % 3)
            t //= 3
        masks = [0, 0, 0]
        for v, d in enumerate(digs):
            masks[d] |= 1 << v
        if not all(lo <= bin(mk).count("1") <= hi for mk in masks):
            continue
        if all(connected_bitmask(mk, nbrmask) for mk in masks):
            states.append(sum(d << (2 * v) for v, d in enumerate(digs)))
    return states


def k3_digits(code):
    return [(code >> (2 * v)) & 3 for v in range(K3_N)]


def k3_build_transition(states, g, base):
    """Row-stochastic matrix of the re-propose PAIR chain: uniform over
    distinct (node, adjacent-district) pairs whose landing state is
    valid, literal cut_accept."""
    index = {m: i for i, m in enumerate(states)}
    edges = g.edges
    cuts = []
    for m in states:
        a = np.array(k3_digits(m))
        cuts.append(int((a[edges[:, 0]] != a[edges[:, 1]]).sum()))
    cuts = np.array(cuts)
    n = len(states)
    P = np.zeros((n, n))
    nbrs = [g.nbr[i][g.nbr_mask[i]].tolist() for i in range(K3_N)]
    for i, m in enumerate(states):
        a = k3_digits(m)
        moves = []
        for v in range(K3_N):
            for d in {a[u] for u in nbrs[v]} - {a[v]}:
                m2 = m + ((d - a[v]) << (2 * v))
                j = index.get(m2)
                if j is not None:
                    moves.append(j)
        V = len(moves)
        assert V > 0
        stay = 0.0
        for j in moves:
            acc = min(1.0, base ** (cuts[i] - cuts[j]))
            P[i, j] += acc / V
            stay += (1 - acc) / V
        P[i, i] += stay
    assert np.allclose(P.sum(axis=1), 1.0)
    return P, cuts


@pytest.mark.parametrize("path", ["general", "board"])
@pytest.mark.slow
def test_pair_walk_matches_exact_stationary(path):
    """The k=3 pair walk (both backends) against the power-iterated
    stationary distribution of its exact transition matrix."""
    base = 1.5
    g, nbrmask = build_masks(K3_NX, K3_NY)
    states = k3_enumerate(nbrmask)
    P, cuts = k3_build_transition(states, g, base)
    pi = stationary(P)

    spec = fce.Spec(n_districts=3, proposal="pair", contiguity="patch",
                    record_assignment_bits=True, geom_waits=False,
                    parity_metrics=False)
    plan = fce.graphs.stripes_plan(g, 3)
    chains, steps, burn = 48, 12000, 2000
    if path == "general":
        dg, st, params = fce.init_batch(g, plan, n_chains=chains, seed=21,
                                        spec=spec, base=base,
                                        pop_tol=K3_EPS)
        res = fce.run_chains(dg, spec, params, st, n_steps=steps)
    else:
        bg, st, params = fce.sampling.init_board(
            g, plan, n_chains=chains, seed=22, spec=spec, base=base,
            pop_tol=K3_EPS)
        res = fce.sampling.run_board(bg, spec, params, st, n_steps=steps)
    assert_matches_stationary(res.history["abits"][:, burn:].ravel(),
                              states, pi, cuts)


@pytest.mark.parametrize("path", ["general", "board"])
@pytest.mark.slow
def test_pair_walk_k2_equals_bi_walk(path):
    """At k=2 the pair move set — distinct (node, adjacent-other-district)
    pairs (grid_chain_sec11.py:117-130) — is in bijection with the bi move
    set (boundary nodes, grid_chain_sec11.py:132-145): each boundary node
    has exactly one other district to move to. So the k=2 pair chain must
    match the exact stationary distribution of the BI transition matrix,
    and its |b_nodes| (distinct-pair count) must equal the boundary-node
    count at every recorded state."""
    base = 2.0
    g, nbrmask = build_masks()
    states = enumerate_states(nbrmask)
    P, cuts = build_transition(states, g, base)   # the BI chain's matrix
    pi = stationary(P)

    spec = fce.Spec(n_districts=2, proposal="pair", contiguity="patch",
                    record_assignment_bits=True, geom_waits=False,
                    parity_metrics=False)
    plan = fce.graphs.stripes_plan(g, 2)
    chains, steps, burn = 48, 12000, 2000
    if path == "general":
        dg, st, params = fce.init_batch(g, plan, n_chains=chains, seed=31,
                                        spec=spec, base=base, pop_tol=EPS)
        res = fce.run_chains(dg, spec, params, st, n_steps=steps)
    else:
        bg, st, params = fce.sampling.init_board(
            g, plan, n_chains=chains, seed=32, spec=spec, base=base,
            pop_tol=EPS)
        res = fce.sampling.run_board(bg, spec, params, st, n_steps=steps)
    abits = np.asarray(res.history["abits"])
    assert_matches_stationary(abits[:, burn:].ravel(), states, pi, cuts)

    # |b_nodes|(pair, k=2) == boundary-node count, recomputed from the
    # recorded assignments with independent numpy
    sub = abits[:4]                                    # (4, T)
    a = (sub[..., None] >> np.arange(N)) & 1           # (4, T, N)
    e = g.edges
    cut = a[..., e[:, 0]] != a[..., e[:, 1]]           # (4, T, E)
    is_b = np.zeros(a.shape, bool)
    ci, ti, ei = np.nonzero(cut)
    is_b[ci, ti, e[ei, 0]] = True
    is_b[ci, ti, e[ei, 1]] = True
    np.testing.assert_array_equal(
        np.asarray(res.history["b_count"])[:4], is_b.sum(-1))


@pytest.mark.parametrize("base", [0.5, 2.0])
@pytest.mark.slow
def test_board_path_matches_exact_stationary(base):
    """The board (stencil) fast path faces the same exact-enumeration bar
    as the general kernel: empirical occupancy vs the power-iterated
    stationary distribution of the specified transition matrix."""
    g, nbrmask = build_masks()
    states = enumerate_states(nbrmask)
    P, cuts = build_transition(states, g, base)
    pi = stationary(P)

    spec = fce.Spec(contiguity="patch", record_assignment_bits=True,
                    geom_waits=False, parity_metrics=False)
    plan = fce.graphs.stripes_plan(g, 2)
    chains, steps, burn = 48, 12000, 2000
    bg, st, params = fce.sampling.init_board(
        g, plan, n_chains=chains, seed=13, spec=spec, base=base,
        pop_tol=EPS)
    res = fce.sampling.run_board(bg, spec, params, st, n_steps=steps)
    assert_matches_stationary(res.history["abits"][:, burn:].ravel(),
                              states, pi, cuts)
