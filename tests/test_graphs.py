"""Graph substrate tests: counts, adjacency integrity, patch tables, plans.

Node/edge counts and plan sizes are the verified reference facts from
SURVEY.md section 2.2 rows 14-17 (grid_chain_sec11.py:186-260,
Frankenstein_chain.py:186-246).
"""

import numpy as np
import networkx as nx
import pytest

from flipcomplexityempirical_tpu import graphs


def nx_sec11():
    g = nx.grid_2d_graph(40, 40)
    g.add_edges_from([((0, 1), (1, 0)), ((0, 38), (1, 39)),
                      ((38, 0), (39, 1)), ((38, 39), (39, 38))])
    g.remove_nodes_from([(0, 0), (0, 39), (39, 0), (39, 39)])
    return g


def nx_frank(m=20):
    g = nx.grid_graph([m, m])
    h = nx.triangular_lattice_graph(m, 2 * m - 2)
    g = nx.relabel_nodes(g, {x: (x[0], x[1] - m + 1) for x in g.nodes()})
    return nx.compose(g, h)


def check_adjacency(lat, g):
    assert lat.n_nodes == g.number_of_nodes()
    assert lat.n_edges == g.number_of_edges()
    idx = lat.index
    for u, v in g.edges():
        iu, iv = idx[u], idx[v]
        assert iv in set(lat.nbr[iu][lat.nbr_mask[iu]])
        assert iu in set(lat.nbr[iv][lat.nbr_mask[iv]])
    # degree and padding conventions
    assert (lat.deg == np.array([g.degree[lab] for lab in lat.labels])).all()
    pad = ~lat.nbr_mask
    rows = np.tile(np.arange(lat.n_nodes)[:, None], (1, lat.max_deg))
    assert (lat.nbr[pad] == rows[pad]).all()
    # every edge appears exactly twice in nbr_edge (once per endpoint)
    counts = np.bincount(lat.nbr_edge[lat.nbr_mask], minlength=lat.n_edges)
    assert (counts == 2).all()


def test_grid_sec11_counts():
    lat = graphs.grid_sec11()
    assert lat.n_nodes == 1596
    assert lat.n_edges == 3116
    check_adjacency(lat, nx_sec11())
    # frame mask parity: 0 in n or 39 in n (minus removed corners) -> 152
    assert int(lat.frame_mask.sum()) == 152
    # wall ids: 4 corner diagonals
    assert int((lat.wall_id == 4).sum()) == 4
    # each wall has 37 edges along it (39 gridline edges minus 2 at corners)
    for w in range(4):
        assert int((lat.wall_id == w).sum()) == 37


def test_frankengraph_counts():
    lat = graphs.frankengraph()
    assert lat.n_nodes == 800
    assert lat.n_edges == 1920
    check_adjacency(lat, nx_frank())
    assert int(lat.frame_mask.sum()) == 116


def test_patch_tables_grid():
    lat = graphs.square_grid(6, 6)
    assert lat.patch_ok
    n = lat.n_nodes
    for i in range(0, n, 7):
        size = int(lat.patch_size[i])
        pl = list(lat.patch_nodes[i][:size])
        # neighbors come first, in nbr-slot order
        deg = int(lat.deg[i])
        assert pl[:deg] == list(lat.nbr[i][:deg])
        # patch = radius-2 ball minus self
        g = nx.grid_2d_graph(6, 6)
        lab = lat.labels[i]
        ball = set(nx.single_source_shortest_path_length(g, lab, 2)) - {lab}
        assert {lat.labels[j] for j in pl} == ball
        # bitset adjacency matches induced subgraph
        for s in range(size):
            for t in range(size):
                bit = (int(lat.patch_adj[i][s]) >> t) & 1
                expect = g.has_edge(lat.labels[pl[s]], lat.labels[pl[t]])
                assert bit == int(expect)


def test_plans_sec11():
    lat = graphs.grid_sec11()
    for al in (0, 1, 2):
        plan = graphs.sec11_plan(lat, al)
        c0, c1 = int((plan == 0).sum()), int((plan == 1).sum())
        assert (c0, c1) == (798, 798)


def test_plans_frank():
    lat = graphs.frankengraph()
    sizes = {}
    for al in (0, 1, 2):
        plan = graphs.frank_plan(lat, al)
        sizes[al] = int((plan == 0).sum())
    # diagonal=380, vertical=400, horizontal=380 (Frankenstein_chain.py:207-230)
    assert sizes == {0: 380, 1: 400, 2: 380}


def test_triangular_and_hex_build():
    tri = graphs.triangular_lattice(6, 10)
    assert tri.patch_ok and tri.n_nodes > 0
    hexg = graphs.hex_lattice(4, 4)
    assert hexg.patch_ok
    assert int(hexg.deg.max()) <= 3


def test_stripes_plan_balanced():
    lat = graphs.square_grid(12, 12)
    for k in (2, 4, 8):
        plan = graphs.stripes_plan(lat, k)
        counts = np.bincount(plan, minlength=k)
        assert counts.min() >= 144 // k - k and counts.max() <= 144 // k + k
        assert len(np.unique(plan)) == k


def test_assignment_roundtrip():
    lat = graphs.square_grid(5, 5)
    plan = graphs.stripes_plan(lat, 2)
    d = lat.assignment_to_dict(plan)
    back = lat.assignment_from_dict(d)
    assert (back == plan).all()
