"""G004 negative fixture: schema-conforming emit sites."""


def run(rec):
    rec.emit("run_start", runner="general", chains=4, n_steps=10, chunk=5)
    rec.emit("error", message="boom", extra="extras are fine")
    fields = {"what": "final_record", "bytes": 96}
    rec.emit("transfer", **fields)    # splat: field coverage is dynamic
    rec.emit("run_end", ts=0.0, runner="general", n_yields=10,
             wall_s=0.1, flips_per_s=100.0)
