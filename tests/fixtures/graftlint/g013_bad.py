"""G013 positive fixture: fault-site literals that miss the registry.
The fixture carries its own FAULT_SITES so the rule has a registry even
when linted standalone."""

FAULT_SITES = {
    "checkpoint.write": "raise in the fsync window",
    "journal.append": "raise before the WAL append",
    "lease.write": "raise before the O_EXCL create",
}


def fault_point(site, **ctx):
    return site


def install_from_spec(spec):
    return spec


def run():
    fault_point("checkpoint.wrte")               # typo: missing 'i'
    fault_point("journal.append")                # registered: fine
    install_from_spec("journal.append:once,worker.sigkill:always")
