"""G012 negative fixture: durable writes through the sanctioned
idioms — tmp+fsync+replace, O_EXCL create, fsync'd append."""

import json
import os


def save_status(run_dir, doc):
    path = os.path.join(run_dir, "status", "job.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def append_journal(run_dir, line):
    path = os.path.join(run_dir, "journal.wal")
    with open(path, "a", encoding="utf-8") as f:
        f.write(line)
        f.flush()
        os.fsync(f.fileno())


def claim_lease(run_dir, worker, payload):
    path = os.path.join(run_dir, "leases", worker + ".lease")
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        return False
    with os.fdopen(fd, "w", encoding="utf-8") as f:
        json.dump(payload, f)
        f.flush()
        os.fsync(f.fileno())
    return True


def _write_json_atomic(path, doc):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def publish_checkpoint(root, doc):
    _write_json_atomic(os.path.join(root, "checkpoint", "latest.json"),
                       doc)
