"""G009 negative fixture: the hygienic handler shape — delegate to the
FrontDoor (which journals), measure durations monotonically, mutate
nothing the journal doesn't see."""

import time


class GoodHandler:
    def do_POST(self):
        # delegation: the FrontDoor's submit journals write-ahead
        out = self.server.front.submit({"workload": "frank"}, "t0")
        return out

    def do_GET(self):
        t0 = time.monotonic()          # durations: monotonic is legal
        doc = self.server.front.job_status("j0000")  # read-only
        doc["dur_s"] = time.monotonic() - t0
        return doc


class NotAHandler:
    """Outside handler classes none of this is G009's business."""

    def helper(self):
        self.server = object()         # plain attribute, not a handler
        return time.time()
