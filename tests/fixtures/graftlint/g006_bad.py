"""G006 positive fixture: heavy tests without the slow marker."""
import jax


def test_long_walk(dg, spec, params, states):
    res = run_chains(dg, spec, params, states, n_steps=50000)
    assert res is not None


def test_device_sweep():
    for dev in jax.devices():
        assert dev is not None


def test_bound_steps(dg, spec, params, states):
    n_steps = 99999
    res = run_chains(dg, spec, params, states, n_steps=n_steps)
    assert res is not None
