"""G014 positive fixture: history tensors pulled to host off the books."""
import jax
import numpy as np


def run_chunks(chunk_fn, states, n_steps):
    hist_parts = []
    for _ in range(n_steps // 64):
        states, outs = chunk_fn(states, 64)
        hist_parts.append(np.asarray(outs))          # direct copy
    history = jax.tree.map(np.asarray, hist_parts)   # tree-map copy
    return states, history


def finalize(states, out_last, history):
    tail = np.array(out_last)                        # np.array spelling
    full = jax.device_get(history)                   # device_get spelling
    return tail, full
