"""G004 negative fixture: schema-conforming span/metrics emit sites
(the tracing layer's event types, as obs.trace.Span emits them)."""


def run(rec):
    rec.emit("span_begin", name="chunk", span_id=7, trace_id="ab12",
             parent_id=3, tid=0, kernel_path="board")
    rec.emit("span_end", name="chunk", span_id=7, trace_id="ab12",
             dur_s=0.25, wall_s=0.25, reject={"proposals": 10})
    rec.emit("metrics_snapshot", counters={"chunks": 4}, gauges={},
             histograms={"chunk_wall_s": {"count": 4, "p50": 0.2}},
             runner="board")
    fields = {"name": "diag", "span_id": 9}
    rec.emit("span_begin", **fields)    # splat: field coverage is dynamic
