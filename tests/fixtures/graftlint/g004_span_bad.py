"""G004 positive fixture: span/metrics emit sites off the registry."""


def run(rec):
    rec.emit("span_instant", name="chunk", span_id=7)  # unknown event type
    rec.emit("span_begin", name="chunk")               # missing core fields
    rec.emit("span_end", name="chunk", span_id=7,
             trace_id="ab12")                          # missing dur_s
    rec.emit("metrics_snapshot", counters={})          # missing core fields
