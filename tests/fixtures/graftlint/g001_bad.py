"""G001 positive fixture: host syncs inside a traced context."""
import jax
import numpy as np


@jax.jit
def step(state):
    if state.energy > 0:          # python `if` on a traced value
        state = state + 1
    while state.min() < 0:        # python `while` on a traced value
        state = state + 1
    x = float(state)              # host conversion of a traced value
    y = state.item()              # blocking device->host sync
    z = np.asarray(state)         # host copy of a traced value
    return x + y + z
