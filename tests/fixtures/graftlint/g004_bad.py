"""G004 positive fixture: emit sites off the event registry."""


def run(rec):
    rec.emit("not_an_event", runner="general")        # unknown event type
    rec.emit("run_start", runner="general")           # missing core fields
    etype = "chunk"
    rec.emit(etype, runner="general")                 # non-literal name
