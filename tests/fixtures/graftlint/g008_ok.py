"""G008 negative fixture: a pure control policy — deterministic in the
observed history, no clocks, no RNG, no emission; plain list.append and
numpy statistics stay legal."""

import numpy as np


class PureStopPolicy:
    name = "pure_stop"

    def __init__(self, target=1.05):
        self.target = target

    def propose(self, view):
        actions = []
        hist = view.history
        if hist is None:
            return actions
        spread = float(np.asarray(hist).std())
        if spread < self.target:
            # proposing is fine — the ControlLoop emits/journals
            actions.append(("stop", view.tag, view.done))
        return actions


def summarize(loop_actions):
    parts = []
    for act in loop_actions:
        parts.append(str(act))  # plain list.append is not journaling
    return ", ".join(parts)
