"""G010 positive fixture: request/job-scoped emits with no trace
context — each is an event an operator cannot join to its submit
trace."""


def submit(rec):
    rec.emit("http_request", method="POST", status=200)
    rec.emit("job_submitted", job_id="j0000", tenant="t0")
    rec.emit("quota_rejected", tenant="t0", tokens=0.0)


def claim(rec):
    rec.emit("lease_acquired", job_id="j0000", worker="w1")


def reclaim(rec):
    # a `with` that is NOT an adopt() does not supply context
    with open("/dev/null") as fh:  # noqa: F841
        rec.emit("lease_expired", job_id="j0000", holder="w9")
