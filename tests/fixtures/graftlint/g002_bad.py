"""G002 positive fixture: PRNG key reuse."""
import jax


def sample(key):
    a = jax.random.uniform(key)
    b = jax.random.normal(key)    # straight-line reuse of a consumed key
    return a + b


def walk(key, n: int):
    total = 0.0
    for _ in range(n):
        total = total + jax.random.uniform(key)   # cross-iteration reuse
    return total
