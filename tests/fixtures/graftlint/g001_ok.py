"""G001 negative fixture: static control flow and traced-safe ops."""
import jax
import jax.numpy as jnp


@jax.jit
def step(state, n: int):
    if n > 2:                         # static: annotated python int
        state = state + 1
    if state.ndim == 2:               # static: array metadata
        state = state.sum(axis=-1)
    if state is None:                 # static: structural None test
        return jnp.zeros(())
    clipped = jnp.where(state > 0, state, 0.0)   # traced select, no sync
    flag = bool(n)                    # bool() on a static value
    return clipped if flag else -clipped


def host_summary(res):
    # not a traced context: host conversions are fine here
    return float(res.mean())
