"""G003 positive fixture: treedef-unstable state fields."""
from typing import Optional

import jax.numpy as jnp
from flax import struct


@struct.dataclass
class DemoState:
    key: jnp.ndarray
    board: jnp.ndarray
    count: jnp.ndarray = None                     # default but not Optional
    extra: Optional[jnp.ndarray] = 0              # Optional but non-None
    tail: jnp.ndarray                             # non-default after default
