"""G007 negative fixture: the hygienic forms of every hazard."""

import random
import time


def typed_and_recorded(op, log):
    try:
        op()
    except OSError as e:  # typed, recorded
        log.append(e)
    try:
        op()
    except Exception as e:
        raise RuntimeError("wrapped") from e


def monotonic_deadline(budget_s):
    start = time.monotonic()
    while time.monotonic() - start < budget_s:
        break


def timestamp_is_fine():
    # time.time() as a TIMESTAMP (never subtracted) stays legal
    return {"ts": time.time()}


def seeded_jitter(base, seed):
    rng = random.Random(seed)
    return base * (1.0 + rng.uniform(0.0, 0.25))
