"""G002 negative fixture: disciplined key splitting."""
import jax


def sample(key):
    key, k1 = jax.random.split(key)
    a = jax.random.uniform(k1)
    key, k2 = jax.random.split(key)
    b = jax.random.normal(k2)
    return a + b


def walk(key, n: int):
    total = 0.0
    for i in range(n):
        key, sub = jax.random.split(key)    # re-split before each use
        total = total + jax.random.uniform(sub)
    return total


def guarded(key, flag: bool):
    if flag:
        return jax.random.uniform(key)      # early return: no reuse below
    return jax.random.normal(key)
