"""G005 positive fixture: unguarded obs traffic in a dispatching runner."""


def run_segment(bg, spec, params, state, rec, mon):
    state, outs = run_board_chunk(bg, spec, params, state, 100)
    rec.emit("transfer", what="chunk", bytes=128)    # unguarded: runs on
    mon.observe_chunk(outs=outs)                     # the NullRecorder path
    return state
