"""G009 positive fixture: every HTTP-handler hygiene hazard."""

import time


class SweepService:
    def run_until_idle(self):
        pass


class BadHandler:  # structurally a handler: defines do_* methods
    def do_POST(self):
        # blocking sweep execution on the request thread
        svc = SweepService()
        svc.run_until_idle()
        # unjournaled shared-state mutation (no journal call anywhere
        # in this method)
        self.server.jobs.append("j0000")
        self.server.n_jobs = 1

    def do_GET(self):
        # wall clock inside a handler bypasses the injected clock
        started = time.time()
        return started
