"""G014 negative fixture: counters, the oracle helper, and a declared
host-assembly site."""
import jax
import numpy as np


def maybe_host(outs, history_device):
    # the flagged oracle path: the one helper allowed to move history
    if history_device:
        return outs
    return jax.tree.map(np.asarray, outs)


def run_chunks(chunk_fn, states, n_steps, history_device):
    hist_parts = []
    for _ in range(n_steps // 64):
        states, outs = chunk_fn(states, 64)
        hist_parts.append(maybe_host(outs, history_device))
    # scalar counter readbacks are not per-step history tensors
    accepted = int(np.asarray(states.accept_count, np.int64).sum())
    waits = np.asarray(states.waits_sum, np.float64)
    return states, hist_parts, accepted, waits


def legacy_collect(outs):
    # declared exception: host assembly accounted for by the caller
    host = jax.tree.map(np.asarray, outs)  # graftlint: disable=G014(ladder history is host-assembled by design; bytes counted in rb_total)
    return host
