"""G008 positive fixture: every impurity class the rule must flag in a
control/ policy module."""

import random
import time

import numpy as np


def decide_with_clock(history):
    started = time.time()          # BAD: wall-clock read in control/
    elapsed = time.monotonic()     # BAD: even monotonic timers
    return started, elapsed, history


def decide_with_rng(history):
    if random.random() < 0.5:      # BAD: unseeded process RNG
        return "stop"
    jitter = np.random.uniform()   # BAD: numpy global RNG
    return jitter


class LeakyStopPolicy:
    """A policy that emits and journals directly instead of proposing."""

    def propose(self, view, recorder, journal):
        actions = []
        recorder.emit("control_action", kind="stop",  # BAD: policy emits
                      tag=view.tag, step=view.done, policy="leaky")
        journal.append("control_action",              # BAD: policy journals
                       action="stop", tag=view.tag)
        return actions
