"""G007 positive fixture: every retry/timeout hygiene hazard."""

import random
import time


def swallow_everything(op):
    try:
        op()
    except Exception:  # swallowed: neither retries nor quarantines
        pass


def swallow_bare(op):
    try:
        op()
    except:  # noqa: E722
        pass


def wall_clock_deadline(budget_s):
    start = time.time()
    while time.time() - start < budget_s:  # NTP slew breaks this
        pass


def wall_clock_duration(t0):
    return time.time() - t0


def unseeded_jitter(base):
    return base * (1.0 + random.uniform(0.0, 0.25))
