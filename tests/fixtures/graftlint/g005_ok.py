"""G005 negative fixture: guarded obs traffic; deferred emitters exempt."""


def run_segment(bg, spec, params, state, rec, mon):
    state, outs = run_board_chunk(bg, spec, params, state, 100)
    if rec:
        rec.emit("transfer", what="chunk", bytes=128)
        mon.observe_chunk(outs=outs)
    if rec and mon is not None:
        watch.poll(rec, chunk=100)
    return state


def _emit_chunks_after_sync(rec, metas):
    # no device dispatch in this function: it runs after the run-end
    # sync, so unguarded emits are fine (the caller already gated on rec)
    for meta in metas:
        rec.emit("transfer", what="history", bytes=meta)
