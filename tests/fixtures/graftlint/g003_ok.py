"""G003 negative fixture: trailing Optional-with-None fields only."""
from typing import Optional

import jax.numpy as jnp
from flax import struct


@struct.dataclass
class DemoState:
    key: jnp.ndarray
    board: jnp.ndarray
    h: int = struct.field(pytree_node=False, default=0)   # static, exempt
    cut_times_se: Optional[jnp.ndarray] = None
    reject_count: Optional[jnp.ndarray] = None


class NotAState:
    limit: int = 7          # unrelated class: out of scope
