"""G013 negative fixture: every fault-site literal and plan spec names
a registered site."""

FAULT_SITES = {
    "checkpoint.write": "raise in the fsync window",
    "journal.append": "raise before the WAL append",
    "lease.write": "raise before the O_EXCL create",
}


def fault_point(site, **ctx):
    return site


def install_from_spec(spec):
    return spec


def run():
    fault_point("checkpoint.write")
    fault_point("lease.write", path="/tmp/x.lease")
    install_from_spec("journal.append:once,lease.write:always,seed=7")
