"""G006 negative fixture: short tests, or marked slow."""
import jax
import pytest


def test_short_walk(dg, spec, params, states):
    res = run_chains(dg, spec, params, states, n_steps=200)
    assert res is not None


@pytest.mark.slow
def test_long_walk(dg, spec, params, states):
    res = run_chains(dg, spec, params, states, n_steps=50000)
    assert res is not None


@pytest.mark.slow
def test_device_sweep():
    for dev in jax.devices():
        assert dev is not None
