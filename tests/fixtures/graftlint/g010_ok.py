"""G010 negative fixture: request/job-scoped emits that carry trace
context — explicitly via kwargs, or ambiently under adopt()."""


class obs:  # stand-in for flipcomplexityempirical_tpu.obs
    @staticmethod
    def adopt(rec, ctx):
        return ctx


def submit(rec, trace_id):
    # explicit context: trace_id kwarg (even None is a decision)
    rec.emit("http_request", method="POST", status=200, trace_id=trace_id)
    rec.emit("job_submitted", job_id="j0000", trace_id=trace_id)
    rec.emit("quota_rejected", tenant="t0", trace_id=None)


def claim(rec, trace):
    # the whole trace dict works too
    rec.emit("lease_acquired", job_id="j0000", trace=trace)


def execute(rec, ctx):
    with obs.adopt(rec, ctx):
        # ambient context: the recorder stamps the adopted trace
        rec.emit("lease_expired", job_id="j0000", holder="w9")


def lifecycle(rec):
    # fleet-scoped events belong to no job: exempt
    rec.emit("worker_started", worker="w1")
    rec.emit("worker_exited", worker="w1", code=0)
