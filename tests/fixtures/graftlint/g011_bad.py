"""G011 positive fixture: a worker thread mutates shared state without
the lock the other mutation site holds."""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0
        self.events = []
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def _run(self):
        while True:
            self.total += 1          # unguarded on the worker thread
            self.events.append(self.total)

    def bump(self, n):
        with self._lock:
            self.total += n          # the lock the other site should hold


def main():
    c = Counter()
    c.bump(3)
    return c.total
