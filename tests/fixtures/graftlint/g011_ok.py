"""G011 negative fixture: every shared mutation happens under the one
dominating lock; intentional lock-free fields carry guarded-by pragmas."""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0
        self.hint = 0  # graftlint: guarded-by(none: monotonic hint, torn reads tolerated)
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def _run(self):
        while True:
            with self._lock:
                self.total += 1
            self.hint += 1

    def bump(self, n):
        with self._lock:
            self.total += n


# graftlint: guarded-by(none: per-request object, single-thread by construction)
class Scratch:
    def __init__(self):
        self.items = []

    def add(self, x):
        self.items.append(x)


def main():
    c = Counter()
    c.bump(3)
    s = Scratch()
    s.add(c.total)
    return s.items
