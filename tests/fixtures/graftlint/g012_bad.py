"""G012 positive fixture: writes to durable roots without the atomic
idioms (tmp+fsync+replace, O_EXCL create, fsync'd append)."""

import json
import os


def save_status(run_dir, doc):
    path = os.path.join(run_dir, "status", "job.json")
    with open(path, "w", encoding="utf-8") as f:   # bare overwrite
        json.dump(doc, f)


def append_journal(run_dir, line):
    path = os.path.join(run_dir, "journal.wal")
    with open(path, "a", encoding="utf-8") as f:   # append, never fsync'd
        f.write(line)


def _write_doc(path, doc):
    # helper that writes whatever path it is handed, non-atomically
    with open(path, "w", encoding="utf-8") as f:
        f.write(doc)


def publish_checkpoint(root, doc):
    _write_doc(os.path.join(root, "checkpoint", "latest.json"), doc)
