"""Sweep service (ISSUE 9): coalesced multi-tenant batching + compile
cache.

The load-bearing claims, each tested here:
- ``ExperimentConfig.fingerprint()`` moves with kernel-relevant statics
  and ONLY those (tag fields never move it);
- two tenants coalesced into one device batch produce per-tenant
  streams byte-identical to their solo runs (board/lowered_bits AND
  general paths — chains are independent because per-chain PRNG keys
  live in the state);
- a second submission with an identical lowering signature+shape emits
  zero ``compile`` / ``compile_cache_miss`` events (amortization);
- failures follow the supervisor taxonomy (solo retry, quarantine) and
  heartbeats are namespaced per job with a probeable merged summary;
- the simulation mode sustains the ISSUE's tenant-efficiency floor.
"""

import json
import os
import subprocess

import numpy as np
import pytest

from flipcomplexityempirical_tpu import obs
from flipcomplexityempirical_tpu.experiments import driver as drv
from flipcomplexityempirical_tpu.experiments.config import ExperimentConfig
from flipcomplexityempirical_tpu.lower.dispatch import lowering_signature
from flipcomplexityempirical_tpu.resilience.supervisor import RetryPolicy
from flipcomplexityempirical_tpu.service import (CompileCache, SweepService)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_leaked_faults():
    from flipcomplexityempirical_tpu.resilience import faults as rfaults
    rfaults.install_plan(None)
    yield
    rfaults.install_plan(None)

FRANK = dict(family="frank", base=0.3, pop_tol=0.1, total_steps=120,
             n_chains=2, backend="jax")
HEX = dict(family="hex", base=0.3, pop_tol=0.1, total_steps=120,
           n_chains=2, backend="jax", lattice_m=4, lattice_n=6)


def _cfg(**kw):
    merged = {**FRANK, **kw}
    merged.setdefault("alignment", 2)
    return ExperimentConfig(**merged)


def _solo(cfg):
    g, plan, _ = drv.build_graph_and_plan(cfg)
    return drv._run_jax(cfg, g, plan, None)


# ---------------------------------------------------------------------------
# fingerprint
# ---------------------------------------------------------------------------

def test_fingerprint_ignores_tag_fields():
    """alignment/base/pop_tol define the tag; none of them changes the
    compiled kernel, so none may move the fingerprint."""
    ref = _cfg().fingerprint()
    assert _cfg(alignment=0).fingerprint() == ref
    assert _cfg(base=2.5).fingerprint() == ref
    assert _cfg(pop_tol=0.9).fingerprint() == ref
    assert _cfg(seed=99).fingerprint() == ref
    assert _cfg(n_chains=64).fingerprint() == ref
    assert _cfg(checkpoint_every=50).fingerprint() == ref
    # distinct tags, equal fingerprints: the coalescing precondition
    assert _cfg().tag != _cfg(alignment=0).tag


def test_fingerprint_moves_with_kernel_statics():
    ref = _cfg().fingerprint()
    assert _cfg(family="sec11").fingerprint() != ref
    assert _cfg(total_steps=121).fingerprint() != ref
    assert _cfg(record_every=2).fingerprint() != ref
    assert _cfg(contiguity="exact").fingerprint() != ref
    assert _cfg(accept="corrected").fingerprint() != ref
    assert _cfg(propose_parallel=4).fingerprint() != ref
    assert _cfg(backend="python").fingerprint() != ref


def test_fingerprint_dual_seed_is_kernel_relevant():
    """The dual family's geometry generation consumes the seed, so equal
    seeds are required to share a graph there — and only there."""
    mk = lambda s: ExperimentConfig(family="dual", alignment=0, base=2.6,
                                    pop_tol=0.25, seed=s)
    assert mk(1).fingerprint() != mk(2).fingerprint()


def test_lowering_signature_stable_and_discriminating():
    cfg = _cfg()
    g, _, _ = drv.build_graph_and_plan(cfg)
    spec = drv.spec_for(cfg)
    assert lowering_signature(g, spec) == lowering_signature(g, spec)
    g2, _, _ = drv.build_graph_and_plan(_cfg(family="sec11"))
    assert lowering_signature(g2, drv.spec_for(_cfg(family="sec11"))) \
        != lowering_signature(g, spec)


# ---------------------------------------------------------------------------
# batched == solo, bit for bit
# ---------------------------------------------------------------------------

def _assert_tenant_matches_solo(job, cfg):
    ref = _solo(cfg)
    got = job.result
    for k in ("end_signed", "cut_times", "num_flips", "waits_all"):
        np.testing.assert_array_equal(np.asarray(got[k]),
                                      np.asarray(ref[k]), err_msg=k)
    assert set(got["history"]) == set(ref["history"])
    for k in ref["history"]:
        np.testing.assert_array_equal(np.asarray(got["history"][k]),
                                      np.asarray(ref["history"][k]),
                                      err_msg=f"history[{k}]")
    np.testing.assert_array_equal(np.asarray(got["assignments"]),
                                  np.asarray(ref["assignments"]))


@pytest.mark.parametrize("base_kw,alignments,expect_path", [
    (FRANK, (2, 1), "lowered_bits"),
    (HEX, (0, 1), "general_dense"),
], ids=["board-lowered_bits", "general_dense"])
def test_coalesced_batch_bit_identical_to_solo(tmp_path, base_kw,
                                               alignments, expect_path):
    """Two tenants with equal fingerprints run as ONE batch; each
    tenant's sliced rows must be byte-identical to its solo run on both
    the bit-packed board path and the general family's dense rung
    (hex resolves general_dense since ISSUE 15)."""
    cfgs = [ExperimentConfig(alignment=al, seed=3 + 4 * i, **base_kw)
            for i, al in enumerate(alignments)]
    svc = SweepService(outdir=str(tmp_path))
    jobs = [svc.submit(c) for c in cfgs]
    svc.run_until_idle()
    assert [j.status for j in jobs] == ["done", "done"], \
        [(j.tag, j.error) for j in jobs]
    assert len(svc.batch_stats) == 1
    stat = svc.batch_stats[0]
    assert stat.kernel_path == expect_path
    assert stat.chains == sum(c.n_chains for c in cfgs)
    assert jobs[0].batch == jobs[1].batch
    for job, cfg in zip(jobs, cfgs):
        _assert_tenant_matches_solo(job, cfg)


def test_batched_run_checkpoints_per_tenant(tmp_path):
    """A coalesced batch writes each tenant its OWN checkpoint (sliced
    chain rows), so a preempted service resumes per job — and a job
    with an existing checkpoint is never coalesced again."""
    ck = tmp_path / "ckpt"
    cfgs = [ExperimentConfig(alignment=al, seed=3 + al, checkpoint_every=60,
                             **HEX) for al in (0, 1)]
    svc = SweepService(outdir=str(tmp_path), checkpoint_dir=str(ck))
    jobs = [svc.submit(c) for c in cfgs]
    svc.run_until_idle()
    assert [j.status for j in jobs] == ["done", "done"]
    for cfg in cfgs:
        assert (ck / f"{cfg.tag}.npz").exists()
    # with checkpoints on disk, a resubmission runs solo (fresh service)
    svc2 = SweepService(outdir=str(tmp_path), checkpoint_dir=str(ck))
    j2 = [svc2.submit(c) for c in cfgs]
    svc2.run_until_idle()
    assert [j.status for j in j2] == ["done", "done"]
    assert len(svc2.batch_stats) == 2  # two solo singletons, no coalescing


# ---------------------------------------------------------------------------
# compile amortization
# ---------------------------------------------------------------------------

def test_second_identical_submission_compiles_nothing(tmp_path):
    """The event-stream proof (ISSUE 9 acceptance): after a first batch
    compiles, a later tenant with the same lowering signature and batch
    shape produces ZERO compile and ZERO compile_cache_miss events."""
    ev = tmp_path / "events.jsonl"
    rec = obs.Recorder(str(ev))
    svc = SweepService(outdir=str(tmp_path), recorder=rec)
    first = svc.submit(ExperimentConfig(alignment=0, seed=3, **HEX))
    svc.run_until_idle()
    n_before = len(ev.read_text().splitlines())
    second = svc.submit(ExperimentConfig(alignment=1, seed=9, **HEX))
    svc.run_until_idle()
    rec.close()
    assert first.status == "done" and second.status == "done"
    tail = [json.loads(line)
            for line in ev.read_text().splitlines()[n_before:]]
    kinds = [e["event"] for e in tail]
    assert "compile_cache_hit" in kinds
    assert "compile_cache_miss" not in kinds
    assert "compile" not in kinds


def test_compile_cache_index_survives_restart(tmp_path):
    cache_dir = tmp_path / "cache"
    c1 = CompileCache(str(cache_dir))
    key = CompileCache.key("abc123", 8, 100, 50)
    assert c1.check(key, kernel_path="lowered_bits") is False
    assert c1.check(key, kernel_path="lowered_bits") is True
    # a fresh process (new instance) loads the persisted index
    c2 = CompileCache(str(cache_dir))
    assert c2.check(key, kernel_path="lowered_bits") is True
    assert len(c2) == 1
    # in-memory-only caches forget across instances
    c3 = CompileCache()
    assert c3.check(key, kernel_path="lowered_bits") is False


# ---------------------------------------------------------------------------
# failure taxonomy + heartbeats
# ---------------------------------------------------------------------------

def test_poison_job_quarantined_batch_unharmed(tmp_path):
    ev = tmp_path / "events.jsonl"
    rec = obs.Recorder(str(ev))
    svc = SweepService(outdir=str(tmp_path), recorder=rec,
                       heartbeat=str(tmp_path / "heartbeat.json"),
                       policy=RetryPolicy(backoff_base_s=0.01))
    good = [svc.submit(ExperimentConfig(alignment=al, seed=3 + al, **HEX))
            for al in (0, 1)]
    # base=0.5 keeps the poison tag distinct from good[0]'s
    poison = svc.submit(ExperimentConfig(
        alignment=0, **{**HEX, "base": 0.5, "backend": "python"}))
    svc.run_until_idle()
    rec.close()
    assert [j.status for j in good] == ["done", "done"]
    assert poison.status == "quarantined"
    assert poison.solo  # retried in isolation, not inside a batch
    assert svc.exit_code != 0
    kinds = [json.loads(line)["event"]
             for line in ev.read_text().splitlines()]
    assert kinds.count("compile_cache_miss") == 1
    assert kinds.count("config_quarantined") == 1
    assert kinds.count("retry") == 1


def test_transient_fault_retries_solo_and_completes(tmp_path):
    """An injected transient fault fails the batch attempt; both members
    retry SOLO (isolation first) and complete."""
    from flipcomplexityempirical_tpu.resilience import faults as rfaults

    rfaults.install_from_spec("segment.step:once")
    try:
        svc = SweepService(outdir=str(tmp_path),
                           policy=RetryPolicy(backoff_base_s=0.01))
        jobs = [svc.submit(ExperimentConfig(alignment=al, seed=3 + al,
                                            **HEX))
                for al in (0, 1)]
        svc.run_until_idle()
    finally:
        rfaults.install_plan(None)
    assert [j.status for j in jobs] == ["done", "done"], \
        [(j.tag, j.error) for j in jobs]
    assert all(j.attempts == 2 and j.solo for j in jobs)
    # the solo reruns are still bit-identical to clean solo runs
    for job in jobs:
        _assert_tenant_matches_solo(job, job.config)


def test_namespaced_heartbeats_and_merged_summary(tmp_path):
    hb = tmp_path / "heartbeat.json"
    svc = SweepService(outdir=str(tmp_path), heartbeat=str(hb))
    jobs = [svc.submit(ExperimentConfig(alignment=al, seed=3 + al, **HEX))
            for al in (0, 1)]
    svc.run_until_idle()
    merged = json.loads(hb.read_text())
    assert merged["status"] == "complete"
    assert set(merged["jobs"]) == {j.tag for j in jobs}
    for j in jobs:
        entry = merged["jobs"][j.tag]
        assert entry["status"] == "done"
        assert entry["batch"] == j.batch
        per_job = tmp_path / f"heartbeat.{j.tag}.json"
        assert per_job.exists()
        assert json.loads(per_job.read_text())["status"] == "done"


def test_obs_report_probes_namespaced_heartbeats(tmp_path):
    """The extended check_heartbeat follows a service summary's running
    jobs into their per-batch files and applies the staleness rule
    there."""
    import sys
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        from obs_report import check_heartbeat
    finally:
        sys.path.pop(0)
    base = tmp_path / "heartbeat.json"
    batch = tmp_path / "heartbeat.b0000.json"
    base.write_text(json.dumps({
        "status": "running",
        "jobs": {"2B30P10": {"status": "running", "batch": "b0000"},
                 "1B30P10": {"status": "done"}}}))
    batch.write_text(json.dumps({"status": "running"}))
    assert check_heartbeat(str(base), 300.0) is None
    old = (os.path.getmtime(batch) - 10_000,) * 2
    os.utime(batch, old)
    err = check_heartbeat(str(base), 300.0)
    assert err and "2B30P10" in err and "stale" in err
    # completed summaries never probe (a finished service stops
    # refreshing by design)
    base.write_text(json.dumps({"status": "complete_with_failures",
                                "jobs": {}}))
    assert check_heartbeat(str(base), 300.0) is None


# ---------------------------------------------------------------------------
# simulation mode + CI gate
# ---------------------------------------------------------------------------

def test_simulation_tenant_efficiency_floor(tmp_path):
    """The ISSUE 9 acceptance floor: 4 tenants sharing one device via
    coalescing sustain >= 80% of a solo tenant's end-to-end throughput
    (one compile serves the whole batch). Runs the CLI in a fresh
    process: the efficiency prices each round's own compile, so the
    pytest process's warm jit cache must not leak into the solo leg."""
    import sys

    r = subprocess.run(
        [sys.executable, "-m", "flipcomplexityempirical_tpu.service",
         "--simulate", "--out", str(tmp_path), "--steps", "120"],
        capture_output=True, text=True, cwd=REPO, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr
    record = json.loads(r.stdout.strip().splitlines()[-1])
    assert record["metric"] == "tenant_efficiency"
    assert record["tenants"] == 4
    assert record["value"] >= 0.8, record
    # the record is bench_compare-gateable as-is
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        from bench_compare import extract_metrics
    finally:
        sys.path.pop(0)
    metrics = extract_metrics(record)
    assert metrics == {"tenant_efficiency[tenants=4]": record["value"]}


def test_service_check_gate_passes():
    """make service-check: the coalescing + quarantine + event-stream
    smoke as one script, tier-1 so the service contract gates every
    commit."""
    r = subprocess.run(
        ["bash", os.path.join(REPO, "tools", "service_check.sh")],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "service-check: OK" in r.stdout
