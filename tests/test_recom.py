"""ReCom proposal tests: host oracle invariants, then the batched JAX
kernel validated against the same invariants (tree is spanning, split is
balanced, both sides connected, untouched districts untouched)."""

import numpy as np
import networkx as nx
import jax
import jax.numpy as jnp
import pytest

import flipcomplexityempirical_tpu as fce
from flipcomplexityempirical_tpu import compat
from flipcomplexityempirical_tpu.sampling import recom as jrecom
from flipcomplexityempirical_tpu.state import derive


def nx_graph(lat):
    return nx.Graph(list(map(tuple, lat.edges)))


# ---------------------------------------------------------------------------
# host oracle
# ---------------------------------------------------------------------------

def test_random_spanning_tree_is_spanning():
    rng = np.random.default_rng(0)
    lat = fce.graphs.square_grid(6, 6)
    nodes = np.arange(lat.n_nodes)
    tree = compat.random_spanning_tree(lat, nodes, rng)
    assert len(tree) == lat.n_nodes - 1
    t = nx.Graph(tree)
    assert t.number_of_nodes() == lat.n_nodes
    assert nx.is_tree(t)


def test_bipartition_tree_balance_and_connectivity():
    rng = np.random.default_rng(1)
    lat = fce.graphs.square_grid(8, 8)
    nodes = np.arange(lat.n_nodes)
    pop = np.asarray(lat.pop, dtype=np.float64)
    target = pop.sum() / 2
    for seed in range(5):
        side = compat.bipartition_tree(lat, nodes, pop, target, 0.1,
                                       np.random.default_rng(seed))
        assert side is not None
        s = pop[side].sum()
        assert target * 0.9 <= s <= target * 1.1
        g = nx_graph(lat)
        other = np.setdiff1d(nodes, side)
        assert nx.is_connected(g.subgraph(side.tolist()))
        assert nx.is_connected(g.subgraph(other.tolist()))


def test_host_recom_chain_preserves_invariants():
    rng = np.random.default_rng(2)
    lat = fce.graphs.square_grid(8, 8)
    plan = fce.graphs.stripes_plan(lat, 4)
    updaters = {"population": compat.Tally("population"),
                "cut_edges": compat.cut_edges,
                "step_num": compat.step_num}
    part = compat.Partition(lat, plan, updaters)
    ideal = lat.n_nodes / 4
    proposal = compat.make_recom(rng, pop_target=ideal, epsilon=0.25,
                                 node_repeats=2)
    g = nx_graph(lat)
    moved = 0
    for _ in range(15):
        child = proposal(part)
        if child.flips:
            moved += 1
        a = child.assignment_array
        # exactly 4 districts, all connected, all within pop bounds
        assert set(np.unique(a)) == set(np.unique(plan))
        for d in np.unique(a):
            members = np.nonzero(a == d)[0].tolist()
            assert nx.is_connected(g.subgraph(members))
            assert ideal * 0.75 - 1e-9 <= len(members) <= ideal * 1.25 + 1e-9
        part = child
    assert moved >= 10  # recom on a loose tolerance should mostly succeed


def test_host_recom_only_touches_merged_pair():
    rng = np.random.default_rng(3)
    lat = fce.graphs.square_grid(8, 8)
    plan = fce.graphs.stripes_plan(lat, 4)
    part = compat.Partition(
        lat, plan, {"population": compat.Tally("population"),
                    "cut_edges": compat.cut_edges})
    proposal = compat.make_recom(rng, pop_target=lat.n_nodes / 4,
                                 epsilon=0.25, node_repeats=2)
    for _ in range(10):
        child = proposal(part)
        if not child.flips:
            continue
        changed_from = {int(part.assignment_array[lat.index[lab]])
                        for lab in child.flips}
        changed_to = {int(v) for v in child.flips.values()}
        assert len(changed_from | changed_to) <= 2


# ---------------------------------------------------------------------------
# batched JAX kernel
# ---------------------------------------------------------------------------

def setup_jax(n=8, k=2, chains=8, seed=0):
    g = fce.graphs.square_grid(n, n)
    plan = fce.graphs.stripes_plan(g, k)
    spec = fce.Spec(n_districts=k, proposal="pair" if k > 2 else "bi")
    dg, states, params = fce.init_batch(
        g, plan, n_chains=chains, seed=seed, spec=spec, base=1.0,
        pop_tol=0.5)
    return g, dg, spec, states


def test_jax_spanning_forest_is_spanning_tree():
    g, dg, spec, states = setup_jax()
    n = dg.n_nodes
    member = jnp.ones(n, bool)
    in_tree = jrecom.spanning_forest(dg, member, jax.random.PRNGKey(0))
    in_tree = np.asarray(in_tree)
    assert in_tree.sum() == n - 1
    t = nx.Graph([tuple(e) for e in np.asarray(dg.edges)[in_tree]])
    assert t.number_of_nodes() == n and nx.is_tree(t)


def test_jax_spanning_forest_respects_membership():
    g, dg, spec, states = setup_jax()
    a = np.asarray(states.assignment)[0]
    member = jnp.asarray(a == a[0])
    in_tree = np.asarray(
        jrecom.spanning_forest(dg, member, jax.random.PRNGKey(1)))
    edges = np.asarray(dg.edges)
    m = np.asarray(member)
    assert (m[edges[in_tree][:, 0]] & m[edges[in_tree][:, 1]]).all()
    assert in_tree.sum() == m.sum() - 1


def test_jax_tree_structure_and_subtree_pops():
    g, dg, spec, states = setup_jax(n=6)
    n = dg.n_nodes
    member = jnp.ones(n, bool)
    key = jax.random.PRNGKey(2)
    in_tree = jrecom.spanning_forest(dg, member, key)
    parent, depth = jrecom.tree_structure(dg, in_tree, member, jnp.int32(0))
    parent, depth = np.asarray(parent), np.asarray(depth)
    assert depth[0] == 0 and parent[0] == 0
    assert (depth >= 0).all()
    # parent depth is one less
    nz = np.arange(n) != 0
    assert (depth[parent[nz]] == depth[nz] - 1).all()
    sub = np.asarray(jrecom.subtree_populations(
        dg, jnp.asarray(parent), jnp.asarray(depth)))
    assert sub[0] == n  # root subtree = everything (unit pops)
    # oracle: per-node subtree sums via networkx descendants
    t = nx.DiGraph([(int(parent[i]), i) for i in range(n) if i != 0])
    for v in [3, 7, n - 1]:
        expect = 1 + len(nx.descendants(t, v)) if v in t else 1
        assert sub[v] == expect


@pytest.mark.parametrize("k", [2, 4])
def test_jax_recom_move_invariants(k):
    g, dg, spec, states = setup_jax(n=8, k=k, chains=8, seed=3)
    move = jax.jit(jax.vmap(
        lambda s: jrecom.recom_move(dg, spec, s, epsilon=0.4),
        in_axes=0), static_argnums=())
    gx = nx_graph(g)
    s = states
    for it in range(3):
        s = move(s)
    a_all = np.asarray(s.assignment)
    found = np.asarray(s.accept_count)
    assert (found > 0).any()  # at least some chains executed real moves
    for c in range(a_all.shape[0]):
        a = a_all[c]
        assert set(np.unique(a)) == set(range(k))
        for d in range(k):
            members = np.nonzero(a == d)[0].tolist()
            assert nx.is_connected(gx.subgraph(members))
    # derived fields consistent (b_count in the spec's move-set units)
    cut, cdeg, dpop, cc, bc = jax.vmap(
        lambda a: derive(dg, a, k, spec.proposal))(jnp.asarray(a_all))
    assert (np.asarray(cut) == np.asarray(s.cut)).all()
    assert (np.asarray(dpop) == np.asarray(s.dist_pop)).all()
    assert (np.asarray(bc) == np.asarray(s.b_count)).all()


def test_jax_recom_balance():
    # epsilon bounds hold for every executed move
    g, dg, spec, states = setup_jax(n=10, k=2, chains=16, seed=4)
    eps = 0.1
    move = jax.jit(jax.vmap(
        lambda s: jrecom.recom_move(dg, spec, s, epsilon=eps)))
    s2 = move(states)
    a = np.asarray(s2.assignment)
    moved = np.asarray(s2.accept_count) > 0
    assert moved.any()
    target = g.n_nodes / 2
    for c in np.nonzero(moved)[0]:
        pops = np.bincount(a[c], minlength=2)
        assert (np.abs(pops - target) <= eps * target + 1e-6).all()


def test_jax_recom_pop_target_k4():
    # global ideal target honored for a k=4 merged pair
    g, dg, spec, states = setup_jax(n=8, k=4, chains=16, seed=5)
    ideal = g.n_nodes / 4
    eps = 0.25
    move = jax.jit(jax.vmap(
        lambda s: jrecom.recom_move(dg, spec, s, epsilon=eps,
                                    pop_target=ideal)))
    s2 = move(states)
    a = np.asarray(s2.assignment)
    moved = np.asarray(s2.accept_count) > 0
    assert moved.any()
    for c in np.nonzero(moved)[0]:
        pops = np.bincount(a[c], minlength=4)
        assert (np.abs(pops - ideal) <= eps * ideal + 1e-6).all()


def test_spanning_forest_always_tree_many_keys():
    g, dg, spec, states = setup_jax(n=7)
    n = dg.n_nodes
    member = jnp.ones(n, bool)
    sf = jax.jit(lambda k: jrecom.spanning_forest(dg, member, k))
    for seed in range(20):
        in_tree = np.asarray(sf(jax.random.PRNGKey(seed)))
        assert in_tree.sum() == n - 1
        t = nx.Graph([tuple(e) for e in np.asarray(dg.edges)[in_tree]])
        assert t.number_of_nodes() == n and nx.is_tree(t)


def test_move_clock_survives_telemetry_reset():
    # the anneal clock must not reset when telemetry counters are zeroed
    # (the bench warmup pattern)
    spec = fce.Spec(anneal="linear")
    g = fce.graphs.square_grid(8, 8)
    plan = fce.graphs.stripes_plan(g, 2)
    dg, states, params = fce.init_batch(
        g, plan, n_chains=4, seed=6, spec=spec, base=0.5, pop_tol=0.5)
    res = fce.run_chains(dg, spec, params, states, n_steps=100)
    s = res.state
    clock1 = np.asarray(s.move_clock).copy()
    assert (clock1 == np.asarray(s.accept_count)).all()
    s = s.replace(accept_count=jnp.zeros_like(s.accept_count))
    res2 = fce.run_chains(dg, spec, params, s, n_steps=100)
    s2 = res2.state
    assert (np.asarray(s2.move_clock)
            >= clock1 + np.asarray(s2.accept_count)).all()
    assert (np.asarray(s2.move_clock) > np.asarray(s2.accept_count)).all()


def test_jax_recom_settles_parity_clocks():
    g, dg, spec, states = setup_jax(n=8, k=2, chains=8, seed=7)
    lv = jnp.asarray([1, -1], jnp.int32)
    res = fce.run_chains(dg, spec,
                         fce.kernel.step.make_params(
                             1.0, 0.0, g.n_nodes, lv, n_chains=8),
                         states, n_steps=50)
    s = res.state
    a_before = np.asarray(s.assignment).copy()
    nf_before = np.asarray(s.num_flips).copy()
    lf_before = np.asarray(s.last_flipped).copy()
    move = jax.jit(jax.vmap(
        lambda st: jrecom.recom_move(dg, spec, st, epsilon=0.3,
                                     label_values=lv)))
    s2 = move(s)
    a_after = np.asarray(s2.assignment)
    t_now = np.asarray(s.t_yield)
    for c in range(8):
        changed = a_before[c] != a_after[c]
        assert (np.asarray(s2.num_flips)[c][changed]
                == nf_before[c][changed] + 1).all()
        assert (np.asarray(s2.last_flipped)[c][changed] == t_now[c]).all()
        un = ~changed
        assert (np.asarray(s2.num_flips)[c][un] == nf_before[c][un]).all()
        assert (np.asarray(s2.last_flipped)[c][un] == lf_before[c][un]).all()


def test_host_bipartition_infeasible_total_fast_none():
    lat = fce.graphs.square_grid(6, 6)
    nodes = np.arange(lat.n_nodes)
    pop = np.asarray(lat.pop, dtype=np.float64)
    # target far from total/2: infeasible, must return None immediately
    side = compat.bipartition_tree(lat, nodes, pop, pop.sum(), 0.05,
                                   np.random.default_rng(0),
                                   max_attempts=10**9)
    assert side is None


def test_cross_backend_stationary_statistics():
    """VERDICT item: host-oracle vs batched recom chains on the same tiny
    graph must agree on stationary trajectory statistics (cut-count
    distribution and balance occupancy), catching distribution divergence
    between the unbounded host retry and the bounded in-kernel retry."""
    from test_parity import ks_stat

    lat = fce.graphs.square_grid(6, 6)
    plan = fce.graphs.stripes_plan(lat, 2)
    eps, steps, burn = 0.34, 400, 50

    # host oracle chain (recom proposal, always accept)
    rng = np.random.default_rng(11)
    part = compat.Partition(
        lat, plan, {"population": compat.Tally("population"),
                    "cut_edges": compat.cut_edges})
    proposal = compat.make_recom(rng, pop_target=lat.n_nodes / 2,
                                 epsilon=eps, node_repeats=3)
    host_cuts, host_p0 = [], []
    for _ in range(steps):
        part = proposal(part)
        host_cuts.append(int(part.cut_edge_mask().sum()))
        host_p0.append(int((part.assignment_array == 0).sum()))

    # batched kernel chains
    chains = 12
    spec = fce.Spec(n_districts=2)
    dg, st, params = fce.init_batch(lat, plan, n_chains=chains, seed=5,
                                    spec=spec, base=1.0, pop_tol=eps)
    move = jax.jit(jax.vmap(
        lambda s: jrecom.recom_move(dg, spec, s, epsilon=eps,
                                    pop_target=lat.n_nodes / 2)))
    jcuts, jp0 = [], []
    for _ in range(steps // 4):
        st = move(st)
        jcuts.append(np.asarray(st.cut_count))
        jp0.append(np.asarray(st.dist_pop)[:, 0])
    jcuts = np.stack(jcuts)[burn // 4:].ravel()
    jp0 = np.stack(jp0)[burn // 4:].ravel()
    host_cuts = np.asarray(host_cuts[burn:], float)
    host_p0 = np.asarray(host_p0[burn:], float)

    ks_c = ks_stat(host_cuts, jcuts.astype(float))
    ks_p = ks_stat(host_p0, jp0.astype(float))
    assert ks_c < 0.12, f"cut-count KS {ks_c:.3f}"
    assert ks_p < 0.12, f"district-0 size KS {ks_p:.3f}"
    assert abs(host_cuts.mean() - jcuts.mean()) / host_cuts.mean() < 0.05


@pytest.mark.slow
def test_tree_retries_recover_tight_epsilon():
    """At a tight tolerance a single tree often has no balanced edge; the
    bounded in-move retry must lift the per-move success rate well above
    the single-attempt baseline."""
    lat = fce.graphs.square_grid(6, 6)
    plan = fce.graphs.stripes_plan(lat, 2)
    spec = fce.Spec(n_districts=2)
    eps = 0.06
    rates = {}
    for retries in (1, 6):
        dg, st, params = fce.init_batch(lat, plan, n_chains=64, seed=9,
                                        spec=spec, base=1.0, pop_tol=eps)
        move = jax.jit(jax.vmap(
            lambda s: jrecom.recom_move(dg, spec, s, epsilon=eps,
                                        pop_target=lat.n_nodes / 2,
                                        tree_retries=retries)))
        for _ in range(6):
            st = move(st)
        rates[retries] = float(np.asarray(st.accept_count).mean()) / 6
    assert rates[6] > rates[1], rates
    assert rates[6] > 0.7, rates
