"""Per-rule fixture tests for tools.graftlint (tier-1, host-only: no
JAX work — the linter is pure stdlib ast)."""

import json
import os
import subprocess
import sys

import pytest

from tools.graftlint import LintConfig, RULES, lint_file
from tools.graftlint.baseline import load_baseline, partition, write_baseline
from tools.graftlint.engine import Pragmas, run_lint
from tools.graftlint.findings import Finding

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "graftlint")

RULE_IDS = [r.RULE_ID for r in RULES]


def _lint_fixture(name, rule):
    cfg = LintConfig(root=REPO, rules=frozenset({rule}))
    return lint_file(os.path.join(FIXTURES, name), cfg)


# ---- one positive + one negative fixture per rule ----------------------

@pytest.mark.parametrize("rule", RULE_IDS)
def test_rule_positive_fixture(rule):
    findings = _lint_fixture(f"{rule.lower()}_bad.py", rule)
    assert findings, f"{rule} found nothing in its positive fixture"
    assert all(f.rule == rule for f in findings)


@pytest.mark.parametrize("rule", RULE_IDS)
def test_rule_negative_fixture(rule):
    findings = _lint_fixture(f"{rule.lower()}_ok.py", rule)
    assert findings == [], [f.render() for f in findings]


# ---- specific findings the fixtures encode -----------------------------

def test_g001_catches_each_hazard_kind():
    msgs = "\n".join(f.message for f in _lint_fixture("g001_bad.py", "G001"))
    for needle in ("`if`", "`while`", "float()", ".item()", "np.asarray"):
        assert needle in msgs, f"missing hazard {needle!r}:\n{msgs}"


def test_g002_catches_loop_and_straightline_reuse():
    lines = sorted(f.line for f in _lint_fixture("g002_bad.py", "G002"))
    assert len(lines) == 2  # one straight-line, one cross-iteration


def test_g003_catches_each_contract_breach():
    msgs = "\n".join(f.message for f in _lint_fixture("g003_bad.py", "G003"))
    assert "must be annotated Optional" in msgs
    assert "must default to None" in msgs
    assert "must be trailing" in msgs


def test_g004_catches_unknown_missing_and_dynamic():
    msgs = "\n".join(f.message for f in _lint_fixture("g004_bad.py", "G004"))
    assert "unknown event type 'not_an_event'" in msgs
    assert "missing core field" in msgs
    assert "string literal" in msgs


def test_g004_covers_span_event_types():
    # the registry extension for the tracing layer: span_begin/span_end/
    # metrics_snapshot emit sites are checked like any other event
    ok = _lint_fixture("g004_span_ok.py", "G004")
    assert ok == [], [f.render() for f in ok]
    msgs = "\n".join(f.message
                     for f in _lint_fixture("g004_span_bad.py", "G004"))
    assert "unknown event type 'span_instant'" in msgs
    assert "missing core field" in msgs
    assert "dur_s" in msgs


def test_g005_covers_span_and_metrics_calls(tmp_path):
    # an unguarded span .begin() (or metrics .notify) in a dispatching
    # runner function is a G005 finding; the same code under `if rec:`
    # is clean
    body = ("def run(rec, state):\n"
            "    sp = obs.span(rec, 'chunk')\n"
            "    sp.begin()\n"
            "    state = _run_chunk(state)\n"
            "    sp.end()\n"
            "    met.notify(rec)\n"
            "    return state\n")
    d = tmp_path / "sampling"
    d.mkdir()
    p = d / "mod.py"
    p.write_text(body)
    cfg = LintConfig(root=str(tmp_path), rules=frozenset({"G005"}))
    findings = lint_file(str(p), cfg)
    assert {f.message.split("(")[0] for f in findings} == \
        {".span", ".begin", ".end", ".notify"}
    guarded = ("def run(rec, state):\n"
               "    if rec:\n"
               "        sp = obs.span(rec, 'chunk')\n"
               "        sp.begin()\n"
               "    state = _run_chunk(state)\n"
               "    if rec:\n"
               "        sp.end()\n"
               "        met.notify(rec)\n"
               "    return state\n")
    p2 = d / "mod2.py"
    p2.write_text(guarded)
    assert lint_file(str(p2), cfg) == []


def test_g007_catches_each_hazard_kind():
    msgs = "\n".join(f.message for f in _lint_fixture("g007_bad.py", "G007"))
    assert "swallowed broad exception" in msgs
    assert "time.time()" in msgs
    assert "random.uniform" in msgs


def test_g008_catches_each_impurity_kind():
    msgs = "\n".join(f.message for f in _lint_fixture("g008_bad.py", "G008"))
    assert "time.time() reads a clock" in msgs
    assert "time.monotonic() reads a clock" in msgs
    assert "random.random() draws randomness" in msgs
    assert "np.random.uniform() draws randomness" in msgs
    assert "emit() from inside a Policy" in msgs
    assert "journal.append() from inside a Policy" in msgs


def test_g008_control_package_is_clean():
    # the shipped control/ package must satisfy its own purity gate
    import glob
    cfg = LintConfig(root=REPO, rules=frozenset({"G008"}))
    pkg = os.path.join(REPO, "flipcomplexityempirical_tpu", "control")
    for path in sorted(glob.glob(os.path.join(pkg, "*.py"))):
        assert lint_file(path, cfg) == [], path


def test_g006_threshold_is_configurable():
    cfg = LintConfig(root=REPO, rules=frozenset({"G006"}),
                     max_test_steps=100000)
    loosened = lint_file(os.path.join(FIXTURES, "g006_bad.py"), cfg)
    # only the device loop survives a loosened step threshold
    assert [("devices" in f.message) for f in loosened] == [True]


# ---- pragmas -----------------------------------------------------------

def test_disable_pragma_suppresses_same_line(tmp_path):
    src = ("import jax\n\n"
           "@jax.jit\n"
           "def f(state):\n"
           "    return float(state)  # graftlint: disable=G001(host probe)\n")
    p = tmp_path / "mod.py"
    p.write_text(src)
    cfg = LintConfig(root=str(tmp_path), rules=frozenset({"G001"}))
    assert lint_file(str(p), cfg) == []


def test_disable_pragma_on_preceding_comment_line(tmp_path):
    src = ("import jax\n\n"
           "@jax.jit\n"
           "def f(state):\n"
           "    # graftlint: disable=G001(intentional sync)\n"
           "    return float(state)\n")
    p = tmp_path / "mod.py"
    p.write_text(src)
    cfg = LintConfig(root=str(tmp_path), rules=frozenset({"G001"}))
    assert lint_file(str(p), cfg) == []


def test_pragma_does_not_leak_to_other_rules_or_lines(tmp_path):
    src = ("import jax\n\n"
           "@jax.jit\n"
           "def f(state):\n"
           "    x = float(state)  # graftlint: disable=G002(wrong rule)\n"
           "    return x\n")
    p = tmp_path / "mod.py"
    p.write_text(src)
    cfg = LintConfig(root=str(tmp_path), rules=frozenset({"G001"}))
    assert len(lint_file(str(p), cfg)) == 1


def test_traced_pragma_marks_cross_module_kernels(tmp_path):
    body = ("def kernel(state):\n"
            "    return float(state)\n")
    p = tmp_path / "mod.py"
    p.write_text("# graftlint: traced\n" + body)
    cfg = LintConfig(root=str(tmp_path), rules=frozenset({"G001"}))
    assert len(lint_file(str(p), cfg)) == 1
    # without the marker the same function is host code: clean
    p2 = tmp_path / "mod2.py"
    p2.write_text(body)
    assert lint_file(str(p2), cfg) == []


def test_pragma_reasons_are_recorded():
    pr = Pragmas(["x = 1  # graftlint: disable=G001(why not)"])
    assert pr.suppressed("G001", 1)
    assert not pr.suppressed("G002", 1)
    assert pr.reasons[(1, "G001")] == "why not"


# ---- baseline workflow -------------------------------------------------

def test_baseline_roundtrip_and_partition(tmp_path):
    f1 = Finding("G001", "a.py", 3, 0, "msg one", snippet="x = float(y)")
    f2 = Finding("G002", "b.py", 9, 4, "msg two", snippet="u(key)")
    path = tmp_path / "base.json"
    write_baseline(str(path), [f1])
    fps = load_baseline(str(path))
    assert fps == {f1.fingerprint}
    new, old = partition([f1, f2], fps)
    assert new == [f2] and old == [f1]


def test_fingerprint_stable_across_line_shift():
    a = Finding("G001", "a.py", 3, 0, "m", snippet="x = float(y)")
    b = Finding("G001", "a.py", 300, 7, "m", snippet="x = float(y)")
    assert a.fingerprint == b.fingerprint


def test_fixture_dirs_excluded_from_walks():
    findings = run_lint([os.path.join(REPO, "tests")],
                        LintConfig(root=REPO))
    assert not any("fixtures/graftlint" in f.path for f in findings)


# ---- CLI ---------------------------------------------------------------

def _cli(args, cwd=REPO):
    return subprocess.run([sys.executable, "-m", "tools.graftlint", *args],
                          cwd=cwd, capture_output=True, text=True)


def test_cli_nonzero_on_fixture_violation():
    res = _cli([os.path.join(FIXTURES, "g003_bad.py")])
    assert res.returncode == 1, res.stdout + res.stderr
    assert "G003" in res.stdout


def test_cli_json_format():
    res = _cli(["--format", "json", os.path.join(FIXTURES, "g003_bad.py")])
    assert res.returncode == 1
    doc = json.loads(res.stdout)
    assert doc["counts"]["new"] >= 1
    assert all(f["rule"] == "G003" for f in doc["new"])


def test_cli_baseline_grandfathers(tmp_path):
    fixture = os.path.join(FIXTURES, "g003_bad.py")
    base = tmp_path / "base.json"
    res = _cli(["--baseline", str(base), "--write-baseline", fixture])
    assert res.returncode == 0, res.stdout + res.stderr
    res = _cli(["--baseline", str(base), fixture])
    assert res.returncode == 0, res.stdout + res.stderr
    assert "baselined" in res.stdout
