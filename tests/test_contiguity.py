"""Contiguity checker tests against the networkx ground truth.

- exact_connected must EQUAL the oracle on every tested flip.
- patch_connected must be SOUND (True => flip keeps district connected) and
  must agree with exact on simply-connected districts (measured on real
  chain trajectories; the reference lattices stay simply connected in
  practice).
"""

import numpy as np
import networkx as nx
import jax
import jax.numpy as jnp
import pytest

from flipcomplexityempirical_tpu import graphs, compat
from flipcomplexityempirical_tpu.kernel import contiguity


def nx_connected_after_flip(lat, a, v, d_origin):
    """Oracle: is the origin district still connected after removing v?"""
    members = [i for i in range(lat.n_nodes)
               if a[i] == d_origin and i != v]
    if len(members) <= 1:
        return True
    g = nx.Graph()
    g.add_nodes_from(members)
    ms = set(members)
    for (x, y) in lat.edges:
        if x in ms and y in ms:
            g.add_edge(int(x), int(y))
    return nx.is_connected(g)


def trajectory_states(lat, steps=300, seed=0, eps=0.5, base=1.0):
    """Valid partition states visited by the oracle chain."""
    rng = np.random.default_rng(seed)
    plan = graphs.stripes_plan(lat, 2)
    signed = {lab: 1 - 2 * int(plan[i]) for i, lab in enumerate(lat.labels)}
    updaters = {"population": compat.Tally("population"),
                "cut_edges": compat.cut_edges,
                "b_nodes": compat.b_nodes_bi,
                "base": lambda p: base}
    part = compat.Partition(lat, signed, updaters)
    popbound = compat.within_percent_of_ideal_population(part, eps)
    chain = compat.MarkovChain(
        compat.make_reversible_propose_bi(rng),
        compat.Validator([compat.single_flip_contiguous, popbound]),
        compat.make_cut_accept(rng), part, steps)
    seen = []
    for t, p in enumerate(chain):
        if t % 10 == 0:
            # map +1/-1 to internal 0/1
            seen.append((np.asarray(p.assignment_array) == -1).astype(np.int8))
    return seen


@pytest.mark.parametrize("make", [
    lambda: graphs.square_grid(7, 7),
    lambda: graphs.grid_sec11(),
    lambda: graphs.frankengraph(),
    lambda: graphs.triangular_lattice(5, 8),
    lambda: graphs.hex_lattice(3, 3),
])
def test_checkers_on_trajectories(make):
    lat = make()
    dg = lat.device()
    steps = 120 if lat.n_nodes > 500 else 300
    states = trajectory_states(lat, steps=steps)
    exact_f = jax.jit(lambda a, v, d: contiguity.exact_connected(dg, a, v, d))
    patch_f = jax.jit(lambda a, v, d: contiguity.patch_connected(dg, a, v, d))
    rng = np.random.default_rng(1)
    patch_disagree = 0
    checked = 0
    for a in states:
        aj = jnp.asarray(a)
        # candidate flips: boundary nodes (where the chain actually proposes)
        cut = a[lat.edges[:, 0]] != a[lat.edges[:, 1]]
        bnodes = np.unique(lat.edges[cut].ravel())
        for v in rng.choice(bnodes, size=min(8, len(bnodes)), replace=False):
            d = int(a[v])
            want = nx_connected_after_flip(lat, a, int(v), d)
            got_exact = bool(exact_f(aj, jnp.int32(v), jnp.int32(d)))
            got_patch = bool(patch_f(aj, jnp.int32(v), jnp.int32(d)))
            assert got_exact == want, "exact checker diverged from networkx"
            if got_patch:
                assert want, "patch checker unsound (said safe, was not)"
            elif want:
                patch_disagree += 1
            checked += 1
    # patch must agree almost always on these simply-connected trajectories
    assert checked > 50
    assert patch_disagree / checked < 0.02, (
        f"patch check too conservative: {patch_disagree}/{checked}")


def test_singleton_district_vacuous_true():
    lat = graphs.square_grid(4, 4)
    dg = lat.device()
    a = np.zeros(16, np.int8)
    a[0] = 1  # corner singleton district
    v = 0
    got_e = bool(contiguity.exact_connected(dg, jnp.asarray(a),
                                            jnp.int32(v), jnp.int32(1)))
    got_p = bool(contiguity.patch_connected(dg, jnp.asarray(a),
                                            jnp.int32(v), jnp.int32(1)))
    # matches oracle semantics (compat.single_flip_contiguous: <=1 neighbor)
    assert got_e and got_p


def test_known_disconnection():
    # path graph, district 0 = {0,1,2}: flipping the middle node must be
    # detected as a disconnection by both checkers.
    from flipcomplexityempirical_tpu.graphs import build_lattice
    lat = build_lattice({0: [1], 1: [0, 2], 2: [1, 3], 3: [2, 4], 4: [3]})
    dg = lat.device()
    a = np.array([0, 0, 0, 1, 1], np.int8)
    # flipping node 1 leaves {0, 2}: 0-2 not adjacent -> disconnected
    assert not bool(contiguity.exact_connected(
        dg, jnp.asarray(a), jnp.int32(1), jnp.int32(0)))
    assert not bool(contiguity.patch_connected(
        dg, jnp.asarray(a), jnp.int32(1), jnp.int32(0)))
    # flipping node 2 leaves {0,1}: connected
    assert bool(contiguity.exact_connected(
        dg, jnp.asarray(a), jnp.int32(2), jnp.int32(0)))
    assert bool(contiguity.patch_connected(
        dg, jnp.asarray(a), jnp.int32(2), jnp.int32(0)))
