"""Telemetry subsystem (obs/): schema round-trip, the null recorder's
no-op contract, chunk-event accounting against the runners' chunking
math, driver sweep events + heartbeat, and the obs_report.py --check
gate over a real stream."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import flipcomplexityempirical_tpu as fce
from flipcomplexityempirical_tpu import obs
from flipcomplexityempirical_tpu import experiments as ex

REPORT = os.path.join(os.path.dirname(__file__), os.pardir,
                      "tools", "obs_report.py")


def read_events(path):
    with open(path, encoding="utf-8") as f:
        return [json.loads(line) for line in f if line.strip()]


def assert_stream_valid(events):
    for e in events:
        err = obs.validate_event(e)
        assert err is None, (err, e)


# ---------------------------------------------------------------- schema


def test_recorder_jsonl_roundtrip(tmp_path):
    """One of each event type through Recorder -> file -> parse ->
    validate: the writer and the schema agree on every type."""
    path = str(tmp_path / "ev.jsonl")
    with obs.Recorder(path=path) as rec:
        rec.emit("run_start", runner="general", chains=4, n_steps=101,
                 chunk=25)
        rec.emit("chunk", runner="general", steps=25, chains=4, flips=100,
                 wall_s=0.01, flips_per_s=1e4, accept_rate=0.5,
                 transfer_bytes=800, hbm_history_bytes=0, done=25,
                 total=100)
        rec.emit("compile", fn="runner._run_chunk", cache_size=1)
        rec.emit("transfer", what="initial_record", bytes=96)
        rec.emit("run_end", runner="general", n_yields=101, wall_s=0.04,
                 flips_per_s=1e4)
        rec.emit("sweep_config", tag="2B30P10", family="sec11",
                 status="start")
        rec.emit("error", message="boom")
        assert rec.n_emitted == 7
    events = read_events(path)
    assert [e["event"] for e in events] == [
        "run_start", "chunk", "compile", "transfer", "run_end",
        "sweep_config", "error"]
    assert_stream_valid(events)
    assert all(e["v"] == obs.SCHEMA_VERSION for e in events)
    # ts is monotone-ish wall time, numeric on every event
    assert all(isinstance(e["ts"], float) for e in events)


def test_recorder_rejects_unknown_event(tmp_path):
    """A typo'd emitter fails at its own call site, not downstream."""
    rec = obs.Recorder(path=str(tmp_path / "e.jsonl"))
    with pytest.raises(ValueError, match="unknown event type"):
        rec.emit("chunkk", runner="general")
    rec.close()


def test_validate_event_rejections():
    ok = {"v": 1, "ts": 0.0, "event": "error", "message": "x"}
    assert obs.validate_event(ok) is None
    assert "missing" in obs.validate_event(
        {"v": 1, "ts": 0.0, "event": "error"})
    assert obs.validate_event(
        {"v": 99, "ts": 0.0, "event": "error", "message": "x"})
    assert obs.validate_event(
        {"v": 1, "ts": 0.0, "event": "nope"})
    assert obs.validate_event(
        {"v": 1, "ts": "later", "event": "error", "message": "x"})
    assert obs.validate_event(
        {"v": 1, "ts": 0.0, "event": "sweep_config", "tag": "t",
         "family": "f", "status": "resting"})
    # forward compatibility: extra fields pass
    assert obs.validate_event(dict(ok, extra_field=123)) is None
    # numpy payloads serialize (the runners emit numpy scalars)
    rec_line = json.dumps(
        {"v": 1, "ts": 0.0, "event": "error", "message": "x"})
    assert obs.validate_line(rec_line) is None
    assert obs.validate_line("not json {") is not None
    assert obs.validate_line("   \n") is None  # blank lines pass


def test_from_spec_routing(tmp_path, capsys):
    assert obs.from_spec(None) is obs.NULL
    assert obs.from_spec("") is obs.NULL
    stderr_rec = obs.from_spec("-")
    assert stderr_rec.enabled and stderr_rec.path is None
    p = str(tmp_path / "f.jsonl")
    with obs.from_spec(p) as rec:
        assert rec.path == p
        rec.emit("error", message="hi")
    assert len(read_events(p)) == 1


def test_null_recorder_noop():
    """bool(NULL) is False (call sites gate metric readbacks on it),
    emit/close/context-manager are inert."""
    assert not obs.NULL
    assert obs.NULL.emit("chunk", anything="goes") is None
    with obs.NULL as rec:
        assert rec is obs.NULL
    assert obs.resolve_recorder(None) is obs.NULL
    prev = obs.set_default_recorder(obs.NULL)
    try:
        assert obs.resolve_recorder(None) is obs.NULL
    finally:
        obs.set_default_recorder(prev)


# ------------------------------------------------- runner chunk accounting


def _grid_setup(n=8):
    g = fce.graphs.square_grid(n, n)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(contiguity="patch")
    return g, plan, spec


def test_run_chains_chunk_events(tmp_path):
    """The acceptance contract: one run_start, exactly one chunk event
    per executed chunk (ceil((n_steps-1)/chunk) on the general path),
    one run_end — with flips/s, accept rate, and transfer bytes
    populated — and the stream passes the schema gate. The instrumented
    run's history is identical to the un-instrumented one (telemetry
    reads, never perturbs)."""
    g, plan, spec = _grid_setup()
    path = str(tmp_path / "run.jsonl")
    runs = {}
    for rec_on in (False, True):
        dg, st, params = fce.init_batch(g, plan, n_chains=4, seed=0,
                                        spec=spec, base=1.3, pop_tol=0.4)
        rec = obs.Recorder(path=path) if rec_on else None
        res = fce.run_chains(dg, spec, params, st, n_steps=101, chunk=25,
                             recorder=rec)
        if rec:
            rec.close()
        runs[rec_on] = res
    events = read_events(path)
    assert_stream_valid(events)
    kinds = [e["event"] for e in events]
    assert kinds.count("run_start") == 1
    assert kinds.count("run_end") == 1
    chunks = [e for e in events if e["event"] == "chunk"]
    assert len(chunks) == 4  # (101 - 1 initial yield) / 25
    assert sum(c["steps"] for c in chunks) == 100
    # done counts yields (the initial record is yield 1 of 101)
    assert chunks[-1]["done"] == chunks[-1]["total"] == 101
    start = next(e for e in events if e["event"] == "run_start")
    assert start["runner"] == "general"
    assert start["chains"] == 4 and start["n_steps"] == 101
    for c in chunks:
        assert c["flips"] == 4 * c["steps"]
        assert c["wall_s"] > 0 and c["flips_per_s"] > 0
        assert 0.0 <= c["accept_rate"] <= 1.0
        assert c["transfer_bytes"] > 0  # host history path copies back
        assert c["hbm_history_bytes"] == 0
    end = next(e for e in events if e["event"] == "run_end")
    assert end["n_yields"] == 101 and end["flips_per_s"] > 0
    # accept_rate deltas integrate to a plausible overall rate
    assert 0.0 <= end["accept_rate"] <= 1.0
    # telemetry must not change the walk
    for k in runs[False].history:
        np.testing.assert_array_equal(runs[True].history[k],
                                      runs[False].history[k])


def test_run_chains_chunk_events_device_history(tmp_path):
    """history_device=True: transfer_bytes drops to 0 (nothing crosses
    to host per chunk) while hbm_history_bytes grows monotonically."""
    g, plan, spec = _grid_setup(6)
    dg, st, params = fce.init_batch(g, plan, n_chains=4, seed=0,
                                    spec=spec, base=1.3, pop_tol=0.4)
    path = str(tmp_path / "dev.jsonl")
    with obs.Recorder(path=path) as rec:
        fce.run_chains(dg, spec, params, st, n_steps=76, chunk=25,
                       history_device=True, recorder=rec)
    chunks = [e for e in read_events(path) if e["event"] == "chunk"]
    assert len(chunks) == 3
    hbm = [c["hbm_history_bytes"] for c in chunks]
    assert all(c["transfer_bytes"] == 0 for c in chunks)
    assert hbm[0] > 0 and hbm == sorted(hbm)


def test_run_board_chunk_events(tmp_path):
    """Board fast path: same event contract, accept readbacks deferred
    to the run-end sync (chunk events are back-stamped, so their ts
    precedes run_end's)."""
    g, plan, spec = _grid_setup()
    bg, st, params = fce.sampling.init_board(
        g, plan, n_chains=4, seed=0, spec=spec, base=1.3, pop_tol=0.4)
    path = str(tmp_path / "board.jsonl")
    with obs.Recorder(path=path) as rec:
        fce.sampling.run_board(bg, spec, params, st, n_steps=101,
                               chunk=25, recorder=rec)
    events = read_events(path)
    assert_stream_valid(events)
    kinds = [e["event"] for e in events]
    assert kinds.count("run_start") == 1 and kinds.count("run_end") == 1
    chunks = [e for e in events if e["event"] == "chunk"]
    assert len(chunks) == 4
    assert all(c["runner"] == "board" for c in chunks)
    assert sum(c["steps"] for c in chunks) == 100
    for c in chunks:
        assert 0.0 <= c["accept_rate"] <= 1.0
    end = next(e for e in events if e["event"] == "run_end")
    assert all(c["ts"] <= end["ts"] for c in chunks)
    # the board segment covers n_steps - 1 = 100 transitions; the final
    # yield comes from finalize_board_run (its host copy is the trailing
    # transfer event)
    assert end["n_yields"] == 100
    assert chunks[-1]["done"] == chunks[-1]["total"] == 100


def test_run_tempered_round_events(tmp_path):
    """Tempered runner: chunk events carry round/parity, one per swap
    round, and run_end reports the swap totals."""
    g, plan, spec = _grid_setup(6)
    handle, st, params = fce.sampling.init_tempered(
        g, plan, betas=(1.0, 0.5), n_ladders=2, seed=0, spec=spec,
        base=1.3, pop_tol=0.4)
    path = str(tmp_path / "temper.jsonl")
    with obs.Recorder(path=path) as rec:
        fce.sampling.run_tempered(handle, spec, params, st, n_steps=41,
                                  betas=(1.0, 0.5), n_ladders=2,
                                  swap_every=10, recorder=rec)
    events = read_events(path)
    assert_stream_valid(events)
    start = next(e for e in events if e["event"] == "run_start")
    assert start["runner"] == "tempered"
    chunks = [e for e in events if e["event"] == "chunk"]
    assert len(chunks) == 4  # 40 transitions / swap_every=10
    assert [c["round"] for c in chunks] == [0, 1, 2, 3]
    assert all(c["parity"] in (0, 1) for c in chunks)
    end = next(e for e in events if e["event"] == "run_end")
    assert end["n_yields"] == 41
    assert end["swap_attempts"] >= 0 and end["n_rounds"] == 4


# --------------------------------------------------- driver sweep events


def test_run_sweep_skip_events_and_heartbeat(tmp_path):
    """A completed config (all manifest artifacts on disk) emits exactly
    one sweep_config skip event — no start/done — and the heartbeat file
    lands atomically with the final 'complete' status."""
    out = str(tmp_path / "plots")
    os.makedirs(out)
    cfg = ex.ExperimentConfig(family="frank", alignment=0, base=0.3,
                              pop_tol=0.5, total_steps=200, n_chains=2)
    for kind in ex.ARTIFACT_KINDS:
        with open(os.path.join(out, cfg.tag + kind), "w") as f:
            f.write("x")
    assert ex.is_done(cfg, out)
    path = str(tmp_path / "sweep.jsonl")
    hb = str(tmp_path / "hb" / "heartbeat.json")
    with obs.Recorder(path=path) as rec:
        results = ex.run_sweep([cfg], out, verbose=False, recorder=rec,
                               heartbeat=hb)
    assert results == []
    events = read_events(path)
    assert_stream_valid(events)
    sweep = [e for e in events if e["event"] == "sweep_config"]
    assert [e["status"] for e in sweep] == ["skip"]
    assert sweep[0]["tag"] == cfg.tag
    assert sweep[0]["family"] == "frank"
    assert sweep[0]["artifacts"] == len(ex.ARTIFACT_KINDS)
    with open(hb) as f:
        beat = json.load(f)
    assert beat["status"] == "complete"
    assert beat["n_skipped"] == 1 and beat["n_done"] == 0
    assert beat["ts"] > 0
    assert not os.path.exists(hb + ".tmp")  # atomic replace, no residue


def test_write_heartbeat_atomic(tmp_path):
    from flipcomplexityempirical_tpu.experiments import driver as drv
    hb = str(tmp_path / "nested" / "hb.json")
    drv.write_heartbeat(hb, status="running", current="X")
    with open(hb) as f:
        d = json.load(f)
    assert d["status"] == "running" and d["current"] == "X"
    drv.write_heartbeat(None)  # disabled path is a no-op


# ------------------------------------------------------ obs_report gate


def test_obs_report_check_passes_real_stream(tmp_path):
    """The acceptance gate: a stream from an actual run_chains call
    passes ``tools/obs_report.py --check`` (exit 0), and the report
    mode renders its run table."""
    g, plan, spec = _grid_setup(6)
    dg, st, params = fce.init_batch(g, plan, n_chains=4, seed=0,
                                    spec=spec, base=1.3, pop_tol=0.4)
    path = str(tmp_path / "real.jsonl")
    with obs.Recorder(path=path) as rec:
        fce.run_chains(dg, spec, params, st, n_steps=51, chunk=25,
                       recorder=rec)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, REPORT, "--check", path],
                       capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr
    assert "ok (" in r.stdout
    r = subprocess.run([sys.executable, REPORT, path],
                       capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr
    assert "general" in r.stdout and "## Runs" in r.stdout


def test_obs_report_check_fails_bad_stream(tmp_path):
    """Unknown/malformed events exit nonzero, each with a line-numbered
    diagnostic."""
    path = str(tmp_path / "bad.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"v": 1, "ts": 1.0, "event": "bogus"}) + "\n")
        f.write("not json {\n")
        f.write(json.dumps({"v": 1, "ts": 2.0, "event": "error",
                            "message": "fine"}) + "\n")
    r = subprocess.run([sys.executable, REPORT, "--check", path],
                       capture_output=True, text=True)
    assert r.returncode == 1
    assert f"{path}:1:" in r.stderr and f"{path}:2:" in r.stderr
    assert "2/3 events failed" in r.stderr


# --------------------------------------- chain health monitor (ISSUE 3)


class _Cap:
    """Minimal truthy recorder: captures emitted events in memory."""

    def __init__(self):
        self.events = []

    def __bool__(self):
        return True

    def emit(self, event, ts=None, **fields):
        e = {"event": event, **fields}
        self.events.append(e)
        return e


def test_chain_monitor_matches_offline_oracles():
    """While the thinning buffer is below cap (stride 1) the streaming
    R-hat/ESS are EXACTLY the stats.diagnostics oracles applied to the
    concatenated history, and the Welford mean matches numpy."""
    from flipcomplexityempirical_tpu.stats import diagnostics as dx
    rng = np.random.default_rng(0)
    # 6 chains with slightly offset means so R-hat is > 1 but finite
    blocks = [rng.normal(size=(50, 6)) + 0.05 * np.arange(6)
              for _ in range(4)]
    rec = _Cap()
    mon = obs.ChainMonitor(rec, observable="cut_count", total=200,
                           path="general", runner="general")
    for i, b in enumerate(blocks):
        mon.observe_chunk(outs={"cut_count": b}, wall_s=0.5,
                          done=(i + 1) * 50)
    full = np.concatenate(blocks, axis=0).T  # (C, T)
    diags = [e for e in rec.events if e["event"] == "diag"]
    assert len(diags) == 4
    d = diags[-1]
    assert d["samples"] == 200 and d["chunks"] == 4
    assert d["rhat"] == pytest.approx(dx.gelman_rubin(full), rel=1e-12)
    assert d["ess"] == pytest.approx(dx.ess(full)[1], rel=1e-12)
    assert d["ess_per_s"] == pytest.approx(d["ess"] / 2.0, rel=1e-12)
    assert d["mean"] == pytest.approx(full.mean(), rel=1e-12)
    assert not [e for e in rec.events if e["event"] == "anomaly"]


def test_chain_monitor_thinning_stays_bounded():
    """Past buffer_cap the keep-stride doubles and memory stays bounded;
    diagnostics remain finite and ESS is scaled back to raw samples."""
    rng = np.random.default_rng(1)
    rec = _Cap()
    mon = obs.ChainMonitor(rec, buffer_cap=64)
    for _ in range(10):
        mon.observe_chunk(outs={"cut_count": rng.normal(size=(100, 4))})
    assert mon._stride > 1
    assert mon._buf.shape[1] <= 64
    assert mon._n == 1000  # Welford still saw every sample
    # observe_chunk now runs under a "diag" span, so the tail of the
    # stream is its span_end — pick the last diag event explicitly
    d = [e for e in rec.events if e["event"] == "diag"][-1]
    assert d["rhat"] is not None
    # white noise: ESS scaled by stride lands near the raw sample count
    assert d["ess"] > 64


def test_chain_monitor_anomaly_thresholds_fire_and_rearm():
    """Synthetic feeds trip each detector: a chain that stops accepting
    goes frozen after freeze_chunks, acceptance EWMA below the floor
    collapses after warmup, and a pop-saturated reject breakdown fires
    immediately; a recovery re-arms the edge-triggered events."""
    rec = _Cap()
    mon = obs.ChainMonitor(rec, freeze_chunks=2, warmup_chunks=1,
                           collapse_rate=0.2, pop_sat_frac=0.9)

    def feed(acc_per_chain, rate, pop_frac):
        # cumulative accepts series: chain c gains acc_per_chain[c]
        base = feed.cum.copy()
        feed.cum = feed.cum + np.asarray(acc_per_chain, float)
        accepts = np.linspace(base, feed.cum, 10)  # (T, C)
        prop = 100
        rej = {"nonboundary": 0, "pop": int(pop_frac * prop),
               "disconnect": 0, "metropolis": 0,
               "accepted": int(rate * prop), "proposals": prop}
        rej["nonboundary"] = prop - rej["pop"] - rej["accepted"]
        mon.observe_chunk(outs={"accepts": accepts},
                          accept_rate=rate, reject=rej)

    feed.cum = np.zeros(3)
    feed([5, 5, 5], 0.5, 0.1)           # healthy
    feed([5, 0, 5], 0.5, 0.1)           # chain 1 stalls (streak 1)
    feed([5, 0, 5], 0.01, 0.95)         # streak 2 -> frozen; pop sat
    kinds = [e["kind"] for e in rec.events if e["event"] == "anomaly"]
    assert "frozen_chain" in kinds and "pop_bound_saturation" in kinds
    frozen = next(e for e in rec.events if e["event"] == "anomaly"
                  and e["kind"] == "frozen_chain")
    assert frozen["detail"]["new_chains"] == [1]
    feed([5, 0, 5], 0.01, 0.95)         # EWMA sinks below collapse_rate
    feed([5, 0, 5], 0.01, 0.95)
    kinds = [e["kind"] for e in rec.events if e["event"] == "anomaly"]
    assert "acceptance_collapse" in kinds
    n_before = len(kinds)
    feed([5, 0, 5], 0.01, 0.95)         # still sick: no re-fire
    kinds = [e["kind"] for e in rec.events if e["event"] == "anomaly"]
    assert len(kinds) == n_before
    feed([5, 5, 5], 0.9, 0.1)           # recovery re-arms everything
    feed([5, 0, 5], 0.5, 0.95)
    feed([5, 0, 5], 0.5, 0.95)          # second frozen episode
    kinds = [e["kind"] for e in rec.events if e["event"] == "anomaly"]
    assert kinds.count("frozen_chain") == 2
    assert kinds.count("pop_bound_saturation") == 2


# ------------------------------------- reject-reason taxonomy (ISSUE 3)


def test_reject_breakdown_general_path(tmp_path):
    """run_chains chunk events carry a reject breakdown whose reasons +
    accepted sum exactly to the proposals drawn that chunk, and the
    counter plumbing never leaks into the returned state."""
    g, plan, spec = _grid_setup(6)
    # n_chains=3 is unique in this file: the chunk body really compiles
    # here (not a jit-cache hit from an earlier test), so the compile
    # event with its AOT cost analysis must appear
    dg, st, params = fce.init_batch(g, plan, n_chains=3, seed=0,
                                    spec=spec, base=1.3, pop_tol=0.4)
    path = str(tmp_path / "rej.jsonl")
    with obs.Recorder(path=path) as rec:
        res = fce.run_chains(dg, spec, params, st, n_steps=76, chunk=25,
                             recorder=rec)
    assert res.state.reject_count is None  # stripped before return
    events = read_events(path)
    assert_stream_valid(events)
    chunks = [e for e in events if e["event"] == "chunk"]
    assert len(chunks) == 3
    for c in chunks:
        r = c["reject"]
        parts = (r["nonboundary"] + r["pop"] + r["disconnect"]
                 + r["metropolis"] + r["accepted"])
        assert parts == r["proposals"] > 0
        assert all(v >= 0 for v in r.values())
    diags = [e for e in events if e["event"] == "diag"]
    assert len(diags) == len(chunks)
    assert all(d["observable"] == "cut_count" for d in diags)
    # compile events carry the AOT cost analysis when XLA provides it
    comp = [e for e in events if e["event"] == "compile"]
    assert comp and any("flops" in e or "cost_error" in e for e in comp)


def test_reject_breakdown_lowered_path(tmp_path):
    """The queen-adjacency grid takes the surgical-stencil lowered
    family (bit-packed since round 8); its reject counters obey the same
    sum-to-proposals invariant (board proposals = chains * steps, one
    draw per step)."""
    from flipcomplexityempirical_tpu.kernel import board as kboard
    g = fce.graphs.square_grid(8, 8, queen=True)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(contiguity="patch")
    bg, st, params = fce.sampling.init_board(
        g, plan, n_chains=4, seed=0, spec=spec, base=1.3, pop_tol=0.4)
    assert kboard.body_for(bg, spec) == "lowered_bits"
    path = str(tmp_path / "low.jsonl")
    # bits=False: the reject stream is body-independent (the packed body
    # is bit-identical, gated by tests/test_bitboard_lowered.py) and the
    # int8 body compiles well inside the fast-tier budget
    with obs.Recorder(path=path) as rec:
        res = fce.sampling.run_board(bg, spec, params, st, n_steps=61,
                                     chunk=20, bits=False, recorder=rec)
    assert res.state.reject_count is None
    events = read_events(path)
    assert_stream_valid(events)
    chunks = [e for e in events if e["event"] == "chunk"]
    assert len(chunks) == 3
    for c in chunks:
        r = c["reject"]
        assert r["proposals"] == c["flips"] == 4 * c["steps"]
        parts = (r["nonboundary"] + r["pop"] + r["disconnect"]
                 + r["metropolis"] + r["accepted"])
        assert parts == r["proposals"]
        assert r["accepted"] == round(c["accept_rate"] * c["flips"])


def test_frozen_board_run_emits_anomalies_and_strict_gate(tmp_path):
    """pop_tol=0 rejects every proposal on the population bound: the
    stream must carry pop_bound_saturation, frozen_chain, and
    acceptance_collapse anomalies, pass --check, and fail --strict."""
    g, plan, spec = _grid_setup()
    bg, st, params = fce.sampling.init_board(
        g, plan, n_chains=4, seed=0, spec=spec, base=1.3, pop_tol=0.0)
    path = str(tmp_path / "frozen.jsonl")
    with obs.Recorder(path=path) as rec:
        fce.sampling.run_board(bg, spec, params, st, n_steps=121,
                               chunk=15, recorder=rec)
    events = read_events(path)
    assert_stream_valid(events)
    kinds = {e["kind"] for e in events if e["event"] == "anomaly"}
    assert {"pop_bound_saturation", "frozen_chain",
            "acceptance_collapse"} <= kinds
    for c in (e for e in events if e["event"] == "chunk"):
        assert c["reject"]["pop"] == c["reject"]["proposals"]
        assert c["reject"]["accepted"] == 0
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, REPORT, "--check", path],
                       capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr
    r = subprocess.run([sys.executable, REPORT, "--strict", path],
                       capture_output=True, text=True, env=env)
    assert r.returncode == 2
    assert "anomal" in r.stderr
    assert "## Health" in r.stdout and "pop_bound_saturation" in r.stdout


def test_obs_report_synthesizes_partial_run(tmp_path):
    """A stream that ends mid-run (no run_end: crash or in flight) still
    reports the run, marked partial, with totals from its chunks."""
    g, plan, spec = _grid_setup(6)
    dg, st, params = fce.init_batch(g, plan, n_chains=4, seed=0,
                                    spec=spec, base=1.3, pop_tol=0.4)
    path = str(tmp_path / "part.jsonl")
    with obs.Recorder(path=path) as rec:
        fce.run_chains(dg, spec, params, st, n_steps=51, chunk=25,
                       recorder=rec)
    lines = [ln for ln in open(path, encoding="utf-8")
             if '"run_end"' not in ln]
    with open(path, "w", encoding="utf-8") as f:
        f.writelines(lines)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, REPORT, path],
                       capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr
    assert "general*" in r.stdout
    assert "synthesized" in r.stdout


# ----------------------------------------- recorder durability (ISSUE 3)


def test_recorder_fsyncs_on_error_event(tmp_path, monkeypatch):
    """The error event an aborting sweep emits must hit the disk before
    the process dies: emit('error') flushes AND fsyncs the stream."""
    from flipcomplexityempirical_tpu.obs import recorder as rmod
    synced = []
    monkeypatch.setattr(rmod.os, "fsync", lambda fd: synced.append(fd))
    cfg = ex.ExperimentConfig(family="dual", dual_source="bogus",
                              alignment=0, base=0.3, pop_tol=0.5,
                              total_steps=50, n_chains=2)
    path = str(tmp_path / "err.jsonl")
    with obs.Recorder(path=path) as rec:
        with pytest.raises(ValueError, match="dual_source"):
            ex.run_sweep([cfg], str(tmp_path / "out"), verbose=False,
                         recorder=rec)
        assert synced  # fsync happened at emit time, not at close
    events = read_events(path)
    errs = [e for e in events if e["event"] == "error"]
    assert len(errs) == 1 and "dual_source" in errs[0]["message"]
    assert errs[0]["tag"] == cfg.tag


def test_heartbeat_embeds_latest_diag(tmp_path, monkeypatch):
    """While a config runs, each runner diag snapshot refreshes the
    sweep heartbeat under the active config's tag; the hook is cleared
    once the config finishes."""
    from flipcomplexityempirical_tpu.experiments import driver as drv
    seen = []
    real = drv.write_heartbeat

    def spy(hb_path, **payload):
        if "diag" in payload:
            seen.append(payload)
        return real(hb_path, **payload)

    monkeypatch.setattr(drv, "write_heartbeat", spy)
    cfg = ex.ExperimentConfig(family="frank", alignment=0, base=0.3,
                              pop_tol=0.5, total_steps=120, n_chains=2)
    out = str(tmp_path / "plots")
    os.makedirs(out)
    path = str(tmp_path / "sw.jsonl")
    hb = str(tmp_path / "hb.json")
    with obs.Recorder(path=path) as rec:
        ex.run_sweep([cfg], out, verbose=False, recorder=rec,
                     heartbeat=hb)
        assert getattr(rec, "diag_hook", "unset") is None
    assert seen, "no diag-bearing heartbeat refresh while running"
    snap = seen[-1]["diag"][cfg.tag]
    assert snap["event"] == "diag" and snap["samples"] > 0
    assert seen[-1]["status"] == "running"
    assert seen[-1]["current"] == cfg.tag


def test_heartbeat_carries_anomaly_and_metrics(tmp_path, monkeypatch):
    """While a monitor anomaly is active, the heartbeat JSON carries
    BOTH the per-kind anomaly tally and the latest metrics snapshot
    (ISSUE 5 satellite): a sweep watcher sees 'sick + how slow' in one
    read. The pop-saturation threshold is dropped below zero so the
    first chunk's reject breakdown trips it deterministically."""
    from flipcomplexityempirical_tpu.experiments import driver as drv
    from flipcomplexityempirical_tpu.obs import monitor as mon_mod

    orig_init = mon_mod.ChainMonitor.__init__

    def tight_init(self, *a, **kw):
        orig_init(self, *a, **kw)
        self.pop_sat_frac = -1.0  # any pop fraction (even 0.0) trips

    monkeypatch.setattr(mon_mod.ChainMonitor, "__init__", tight_init)
    seen = []
    real = drv.write_heartbeat

    def spy(hb_path, **payload):
        seen.append(payload)
        return real(hb_path, **payload)

    monkeypatch.setattr(drv, "write_heartbeat", spy)
    cfg = ex.ExperimentConfig(family="frank", alignment=0, base=0.3,
                              pop_tol=0.5, total_steps=120, n_chains=2)
    out = str(tmp_path / "plots")
    os.makedirs(out)
    hb = str(tmp_path / "hb.json")
    with obs.Recorder(path=str(tmp_path / "sw.jsonl")) as rec:
        ex.run_sweep([cfg], out, verbose=False, recorder=rec,
                     heartbeat=hb)
        assert rec.anomaly_hook is None and rec.metrics_hook is None
    both = [p for p in seen if "anomalies" in p and "metrics" in p]
    assert both, "no heartbeat refresh carried anomalies + metrics"
    payload = both[-1]
    tally = payload["anomalies"][cfg.tag]
    assert tally.get("pop_bound_saturation", 0) >= 1
    met = payload["metrics"][cfg.tag]
    assert met["histograms"]["chunk_wall_s"]["count"] >= 1
    assert met["counters"]["chunks"] >= 1
    # the anomaly itself also landed in the event stream
    events = read_events(str(tmp_path / "sw.jsonl"))
    assert any(e["event"] == "anomaly"
               and e["kind"] == "pop_bound_saturation" for e in events)
