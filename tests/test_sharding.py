"""Sharding tests on the 8-virtual-device CPU mesh (conftest sets
xla_force_host_platform_device_count=8): the TPU-native analogue of
multi-node testing without a cluster (SURVEY.md section 4.5).

Key property: sharding the chains axis over 1 vs 8 devices is
bit-identical — per-chain PRNG keys make the batch embarrassingly parallel.
"""

import json
import types

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
import pytest

import flipcomplexityempirical_tpu as fce

from conftest import assert_grid_districts_connected
from flipcomplexityempirical_tpu import distribute, obs
from flipcomplexityempirical_tpu.distribute import sharded as dsh
from flipcomplexityempirical_tpu.sampling import tempering


def setup_batch(chains=16, seed=0, spec=None, base=0.8):
    g = fce.graphs.square_grid(6, 6)
    spec = spec or fce.Spec()
    plan = fce.graphs.stripes_plan(g, 2)
    dg, states, params = fce.init_batch(
        g, plan, n_chains=chains, seed=seed, spec=spec, base=base,
        pop_tol=0.3)
    return g, dg, states, params, spec


def test_eight_devices_available():
    assert len(jax.devices()) == 8


@pytest.mark.slow
def test_sharded_run_bit_identical():
    g, dg, states, params, spec = setup_batch()
    res1 = fce.run_chains(dg, spec, params, states, n_steps=100)

    mesh = distribute.make_mesh(8)
    g2, dg2, states2, params2, _ = setup_batch()
    states2 = distribute.shard_chain_batch(mesh, states2)
    params2 = distribute.shard_chain_batch(mesh, params2)
    res2 = fce.run_chains(dg2, spec, params2, states2, n_steps=100)

    s1, s2 = res1.host_state(), res2.host_state()
    assert (np.asarray(s1.assignment) == np.asarray(s2.assignment)).all()
    assert (res1.history["cut_count"] == res2.history["cut_count"]).all()
    assert (res1.history["wait"] == res2.history["wait"]).all()


def test_cross_device_swaps_pair_adjacent_ranks():
    """At base=1 every valid swap accepts, so one train step (a parity-0
    round then a parity-1 round) must apply the deterministic rank
    brickwork to each slot's ladder — rank-paired, NOT device-paired:
    after the parity-0 exchange the betas sit permuted across devices,
    and the parity-1 round must still pair the adjacent TEMPERATURES."""
    mesh = distribute.make_mesh(8)
    g, dg, states, params, spec = setup_batch(chains=8, base=1.0)
    betas = np.linspace(2.0, 0.25, 8).astype(np.float32)  # descending
    params = params.replace(beta=jnp.asarray(betas))
    states = distribute.shard_chain_batch(mesh, states)
    params = distribute.shard_chain_batch(mesh, params)
    step = distribute.make_train_step(dg, spec, mesh, inner_steps=3)
    params2, _, info = step(jax.random.PRNGKey(1), params, states)
    # expected: pos_of_rank starts [0..7]; parity-0 swaps rank pairs
    # (0,1)(2,3)(4,5)(6,7); parity-1 swaps (1,2)(3,4)(5,6)
    pos_of_rank = np.arange(8)
    for parity in (0, 1):
        for r in range(7):
            if r % 2 == parity:
                pos_of_rank[[r, r + 1]] = pos_of_rank[[r + 1, r]]
    expect = np.empty(8, np.float32)
    expect[pos_of_rank] = betas
    np.testing.assert_array_equal(np.asarray(params2.beta), expect)
    assert int(info["swaps"]) == 2 * (4 + 3)  # both partners count


def test_train_step_with_cross_device_exchange():
    mesh = distribute.make_mesh(8)
    g, dg, states, params, spec = setup_batch(chains=16)
    # ladder along the device axis: betas vary per device
    betas = np.repeat(np.linspace(0.2, 2.0, 8), 2).astype(np.float32)
    params = params.replace(beta=jnp.asarray(betas))
    states = distribute.shard_chain_batch(mesh, states)
    params = distribute.shard_chain_batch(mesh, params)

    step = distribute.make_train_step(dg, spec, mesh, inner_steps=20)
    key = jax.random.PRNGKey(7)
    params2, states2, info = step(key, params, states)
    assert int(info["accepts"]) > 0
    s2 = jax.tree.map(np.asarray, states2)
    assert int(np.asarray(s2.t_yield).sum()) == 16 * 20
    # betas remain a permutation of the original ladder within each pair set
    b = np.sort(np.asarray(params2.beta))
    assert np.allclose(b, np.sort(betas))


def test_within_batch_tempering_swaps():
    g, dg, states, params, spec = setup_batch(chains=16)
    params = tempering.make_ladder_params(
        params, betas=np.linspace(0.2, 2.0, 4), n_ladders=4)
    res = fce.run_chains(dg, spec, params, states, n_steps=60)
    key = jax.random.PRNGKey(0)
    p2, accept = tempering.swap_within_batch(
        key, res.state, params, n_rungs=4, parity=0, spec=spec)
    accept = np.asarray(accept)
    b0 = np.asarray(params.beta).reshape(4, 4)
    b2 = np.asarray(p2.beta).reshape(4, 4)
    # swaps only exchange betas within ladders: multiset per ladder preserved
    assert np.allclose(np.sort(b2, axis=1), np.sort(b0, axis=1))
    # parity-0 round only touches pairs (0,1) and (2,3)
    changed = (b0 != b2)
    assert not changed[:, [0, 1]].any() or True  # pairs may or may not swap
    # accepted pairs actually exchanged
    for lad in range(4):
        for r in (0, 2):
            i = lad * 4 + r
            if accept[i]:
                assert b2[lad, r] == b0[lad, r + 1]
                assert b2[lad, r + 1] == b0[lad, r]


def test_within_batch_tempering_board_path():
    """swap_within_batch reads only cut_count + batch size, so the board
    fast path tempers in-batch too: alternate board chunks with swap
    rounds, check ladder-multiset preservation and the physical ordering
    (hot rungs sit at longer boundaries)."""
    g = fce.graphs.square_grid(6, 32)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(contiguity="patch", geom_waits=False,
                    parity_metrics=False)
    n_rungs, n_ladders = 4, 8
    betas = np.linspace(0.2, 2.0, n_rungs)
    bg, states, params = fce.sampling.init_board(
        g, plan, n_chains=n_rungs * n_ladders, seed=9, spec=spec,
        base=2.0, pop_tol=0.4)
    params = tempering.make_ladder_params(params, betas=betas,
                                          n_ladders=n_ladders)
    key = jax.random.PRNGKey(3)
    accepts = 0
    for r in range(30):
        res = fce.sampling.run_board(bg, spec, params, states, n_steps=41,
                                     record_history=False)
        states = res.state
        key, ks = jax.random.split(key)
        params, acc = tempering.swap_within_batch(
            ks, states, params, n_rungs=n_rungs, parity=r % 2, spec=spec)
        accepts += int(np.asarray(acc).sum())
    assert accepts > 0
    b = np.asarray(params.beta).reshape(n_ladders, n_rungs)
    assert np.allclose(np.sort(b, axis=1), betas)
    # physical sanity: base > 1 with high beta favors SHORT boundaries,
    # so mean cut at the hottest rung (lowest beta) exceeds the coldest
    cuts = np.asarray(states.cut_count).astype(float)
    beta_flat = np.asarray(params.beta)
    b32 = betas.astype(np.float32)
    hot = cuts[beta_flat == b32[0]].mean()
    cold = cuts[beta_flat == b32[-1]].mean()
    assert hot > cold, (hot, cold)


def test_board_sharded_pair_train_step():
    """The k-district pair walk composes with the sharded board train
    step: chunks auto-dispatch (pair bit body on this 32-aligned grid)
    and the exchange ladder reads the carried cut_count."""
    k = 4
    g = fce.graphs.square_grid(4, 32)
    plan = fce.graphs.stripes_plan(g, k)
    spec = fce.Spec(n_districts=k, proposal="pair", contiguity="patch")
    bg, states, params = fce.sampling.init_board(
        g, plan, n_chains=16, seed=0, spec=spec, base=1.3, pop_tol=0.6)
    mesh = distribute.make_mesh(8)
    betas = np.repeat(np.linspace(0.25, 2.0, 8), 2).astype(np.float32)
    params = params.replace(beta=jnp.asarray(betas))
    states = distribute.shard_chain_batch(mesh, states)
    params = distribute.shard_chain_batch(mesh, params)
    from flipcomplexityempirical_tpu.kernel import bitboard as bb
    assert bb.supported_pair(bg, spec)   # the documented dispatch claim
    step = distribute.make_board_train_step(bg, spec, mesh, inner_steps=5,
                                            exchange=True)
    params, states, info = step(jax.random.PRNGKey(2), params, states)
    t = np.asarray(jax.device_get(states.t_yield))
    assert int(t.sum()) == 16 * 5, t
    assert int(info["accepts"]) > 0
    b = np.asarray(jax.device_get(states.board)).reshape(-1, 4, 32)
    assert_grid_districts_connected(b, k)


@pytest.mark.slow
def test_board_sharded_run_bit_identical():
    """The board fast path shards the chains axis transparently: 1 vs 8
    devices produce bit-identical histories and state."""
    g = fce.graphs.square_grid(8, 8)
    spec = fce.Spec(contiguity="patch")
    plan = fce.graphs.stripes_plan(g, 2)

    def setup():
        return fce.sampling.init_board(g, plan, n_chains=16, seed=3,
                                       spec=spec, base=1.3, pop_tol=0.3)

    bg, st, params = setup()
    res1 = fce.sampling.run_board(bg, spec, params, st, n_steps=100)

    mesh = distribute.make_mesh(8)
    bg2, st2, params2 = setup()
    st2 = distribute.shard_chain_batch(mesh, st2)
    params2 = distribute.shard_chain_batch(mesh, params2)
    res2 = fce.sampling.run_board(bg2, spec, params2, st2, n_steps=100)

    for k in res1.history:
        np.testing.assert_array_equal(res1.history[k], res2.history[k],
                                      err_msg=k)
    s1, s2 = res1.host_state(), res2.host_state()
    for fld in ("board", "part_sum", "num_flips", "cut_times_e"):
        np.testing.assert_array_equal(np.asarray(getattr(s1, fld)),
                                      np.asarray(getattr(s2, fld)),
                                      err_msg=fld)


def test_board_train_step_cross_device_exchange():
    """shard_map'd board kernel + rank-paired beta ladder: the multi-chip
    form of the benchmark workload."""
    from flipcomplexityempirical_tpu.kernel import board as kboard

    mesh = distribute.make_mesh(8)
    g = fce.graphs.square_grid(8, 8)
    spec = fce.Spec(contiguity="patch")
    plan = fce.graphs.stripes_plan(g, 2)
    bg, st, params = fce.sampling.init_board(g, plan, n_chains=16, seed=1,
                                             spec=spec, base=1.3,
                                             pop_tol=0.3)
    betas = np.repeat(np.linspace(0.2, 2.0, 8), 2).astype(np.float32)
    params = params.replace(beta=jnp.asarray(betas))
    st = distribute.shard_chain_batch(mesh, st)
    params = distribute.shard_chain_batch(mesh, params)

    step = distribute.make_board_train_step(bg, spec, mesh, inner_steps=20)
    params2, st2, info = step(jax.random.PRNGKey(7), params, st)
    assert int(info["accepts"]) > 0
    s2 = jax.tree.map(np.asarray, st2)
    assert int(np.asarray(s2.t_yield).sum()) == 16 * 20
    assert np.allclose(np.sort(np.asarray(params2.beta)), np.sort(betas))


# ---------------------------------------------------------------------------
# divisibility contract (shard_chain_batch)
# ---------------------------------------------------------------------------

def test_shard_chain_batch_rejects_indivisible_chains(mesh8):
    """A chain axis that does not divide by the mesh size must raise, not
    silently replicate: replication hands every device the FULL batch (8x
    the work, identical results per device)."""
    g, dg, states, params, spec = setup_batch(chains=12)
    with pytest.raises(ValueError, match="does not divide"):
        distribute.shard_chain_batch(mesh8, states)
    with pytest.raises(ValueError, match="does not divide"):
        distribute.shard_chain_batch(mesh8, params)


def test_shard_chain_batch_replicates_small_leaves(mesh8):
    """Leaves whose leading dim is smaller than the chain count (e.g. the
    (k,) label_values) replicate even when their own dim divides the mesh
    — only the chain axis shards."""
    g, dg, states, params, spec = setup_batch(chains=16)
    assert params.label_values.shape == (2,)
    placed = distribute.shard_chain_batch(mesh8, params)
    assert placed.label_values.sharding.is_fully_replicated
    assert not placed.beta.sharding.is_fully_replicated


# ---------------------------------------------------------------------------
# replica-exchange parity vs the in-batch oracle
# ---------------------------------------------------------------------------

def _swap_harness(mesh, parity, n_dev):
    pspec = dsh._params_spec(sharded=True)

    def body(key, params, cuts):
        return dsh._swap_round(key, params, cuts, parity, n_dev)

    return jax.jit(dsh._shard_map(
        body, mesh,
        in_specs=(P(), pspec, P(distribute.CHAINS_AXIS)),
        out_specs=(pspec, P(distribute.CHAINS_AXIS))))


@pytest.mark.parametrize("parity", [0, 1])
def test_cross_device_swap_round_matches_in_batch_oracle(mesh8, parity):
    """The device-axis swap round and the single-device in-batch oracle
    (tempering.swap_within_batch) produce the IDENTICAL (beta, chain)
    pairing on the same energies.

    Construction forces every valid pair to accept regardless of the two
    implementations' differing uniform draws: with log_base > 0 and cuts
    strictly increasing with beta, log_a = (b1-b2)(e1-e2) > 0 on every
    valid pair, so the decision is deterministic. Layout mapping: sharded
    global chain g = d*L + i (device d, local slot i) forms slot i's
    ladder along the device axis; the oracle's ladder-major index for the
    same chain is i*n_dev + d.
    """
    n_dev, n_local = 8, 2
    n_chains = n_dev * n_local
    g, dg, states, params, spec = setup_batch(chains=n_chains)

    # slot i's ladder along the device axis, distinct betas per slot
    ladders = np.stack([np.linspace(0.2, 2.0, n_dev),
                        np.linspace(0.3, 2.4, n_dev)]).astype(np.float32)
    beta_sh = np.empty(n_chains, np.float32)
    cut_sh = np.empty(n_chains, np.int32)
    for d in range(n_dev):
        for i in range(n_local):
            beta_sh[d * n_local + i] = ladders[i, d]
            cut_sh[d * n_local + i] = int(round(ladders[i, d] * 10))

    params_sh = params.replace(
        beta=jnp.asarray(beta_sh),
        log_base=jnp.ones(n_chains, jnp.float32))
    params_sh = distribute.shard_chain_batch(mesh8, params_sh)
    cuts_dev = distribute.shard_chain_batch(mesh8, jnp.asarray(cut_sh))

    key = jax.random.PRNGKey(42)
    p2, accept = _swap_harness(mesh8, parity, n_dev)(
        key, params_sh, cuts_dev)
    beta_out_sh = np.asarray(jax.device_get(p2.beta))
    accept_sh = np.asarray(jax.device_get(accept))

    # oracle layout: chain (d, i) at index i*n_dev + d
    to_oracle = np.array([i * n_dev + d
                          for d in range(n_dev) for i in range(n_local)])
    beta_or = np.empty(n_chains, np.float32)
    cut_or = np.empty(n_chains, np.int32)
    beta_or[to_oracle] = beta_sh
    cut_or[to_oracle] = cut_sh
    params_or = params.replace(
        beta=jnp.asarray(beta_or),
        log_base=jnp.ones(n_chains, jnp.float32))
    oracle_states = types.SimpleNamespace(cut_count=jnp.asarray(cut_or))
    p2_or, acc_or = tempering.swap_within_batch(
        jax.random.PRNGKey(7), oracle_states, params_or,
        n_rungs=n_dev, parity=parity, spec=spec)
    beta_out_or = np.asarray(p2_or.beta)
    accept_or = np.asarray(acc_or)

    assert accept_sh.sum() > 0, "forced-accept construction swapped nothing"
    np.testing.assert_array_equal(accept_sh, accept_or[to_oracle])
    np.testing.assert_array_equal(beta_out_sh, beta_out_or[to_oracle])


# ---------------------------------------------------------------------------
# fast-path dispatch inside the sharded step
# ---------------------------------------------------------------------------

def test_sharded_board_step_dispatches_bitboard(mesh8):
    """A plain 32-aligned grid must reach the BIT-BOARD body through the
    sharded step, not fall back to int8/general (the pre-rework gap)."""
    g = fce.graphs.square_grid(4, 32)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(contiguity="patch", geom_waits=False,
                    parity_metrics=False)
    bg, st, params = fce.sampling.init_board(
        g, plan, n_chains=16, seed=0, spec=spec, base=1.3, pop_tol=0.3)
    st = distribute.shard_chain_batch(mesh8, st)
    params = distribute.shard_chain_batch(mesh8, params)
    step = distribute.make_board_train_step(bg, spec, mesh8, inner_steps=4)
    assert step.kernel_path == "bitboard"
    _, st2, info = step(jax.random.PRNGKey(0), params, st)
    assert int(np.asarray(jax.device_get(st2.t_yield)).sum()) == 16 * 4
    assert int(info["accepts"]) > 0


def test_sharded_board_step_dispatches_lowered(mesh8):
    """The queen-adjacency (surgical) grid takes the PACKED lowered
    stencil body through the sharded step — the treedef with
    cut_times_se/sw leaves that a fixed placeholder in_specs struct
    used to reject, now on the lowered_bits rung (ISSUE 8). bits=True
    builds first-class; bits=False opts down to the int8 lowered body;
    a workload the packed gate rejects still refuses bits=True loudly."""
    g = fce.graphs.square_grid(8, 8, queen=True)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(contiguity="patch")
    bg, st, params = fce.sampling.init_board(
        g, plan, n_chains=8, seed=0, spec=spec, base=1.3, pop_tol=0.4)
    st = distribute.shard_chain_batch(mesh8, st)
    params = distribute.shard_chain_batch(mesh8, params)
    step = distribute.make_board_train_step(bg, spec, mesh8, inner_steps=3)
    assert step.kernel_path == "lowered_bits"
    # b2_disp-ambiguous miniature: the packed gate rejects bits=True
    # loudly (before any compile)
    g4 = fce.graphs.square_grid(3, 4, remove_nodes=[(0, 0)],
                                extra_edges=[((0, 1), (1, 0))])
    plan4 = fce.graphs.stripes_plan(g4, 2)
    bg4, _, _ = fce.sampling.init_board(
        g4, plan4, n_chains=8, seed=0, spec=spec, base=1.3, pop_tol=0.5)
    with pytest.raises(ValueError, match="supported_lowered"):
        distribute.make_board_train_step(bg4, spec, mesh8, inner_steps=3,
                                         bits=True)
    _, st2, info = step(jax.random.PRNGKey(0), params, st)
    assert int(np.asarray(jax.device_get(st2.t_yield)).sum()) == 8 * 3
    assert int(info["accepts"]) > 0


@pytest.mark.slow
def test_sharded_board_step_lowered_bits_forcing(mesh8):
    """Explicit bits= on the lowered family through the sharded step:
    True builds the packed body first-class, False opts down to the
    int8 lowered body, and both advance the same BoardState."""
    g = fce.graphs.square_grid(8, 8, queen=True)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(contiguity="patch")
    bg, st, params = fce.sampling.init_board(
        g, plan, n_chains=8, seed=0, spec=spec, base=1.3, pop_tol=0.4)
    st = distribute.shard_chain_batch(mesh8, st)
    params = distribute.shard_chain_batch(mesh8, params)
    for bits, want in ((True, "lowered_bits"), (False, "lowered")):
        step = distribute.make_board_train_step(
            bg, spec, mesh8, inner_steps=3, bits=bits)
        assert step.kernel_path == want
        _, st2, info = step(jax.random.PRNGKey(0), params, st)
        assert int(np.asarray(jax.device_get(st2.t_yield)).sum()) == 8 * 3
        assert int(info["accepts"]) > 0


# ---------------------------------------------------------------------------
# run_sharded: instrumented multi-round driver
# ---------------------------------------------------------------------------

def test_run_sharded_event_stream(mesh8, tmp_path):
    g = fce.graphs.square_grid(4, 32)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(contiguity="patch", geom_waits=False,
                    parity_metrics=False)
    bg, st, params = fce.sampling.init_board(
        g, plan, n_chains=16, seed=0, spec=spec, base=1.3, pop_tol=0.3)
    betas = np.repeat(np.linspace(0.25, 2.0, 8), 2).astype(np.float32)
    params = params.replace(beta=jnp.asarray(betas))
    st = distribute.shard_chain_batch(mesh8, st)
    params = distribute.shard_chain_batch(mesh8, params)
    step = distribute.make_board_train_step(bg, spec, mesh8, inner_steps=5)
    path = str(tmp_path / "events.jsonl")
    with obs.Recorder(path=path) as rec:
        params, st, info = distribute.run_sharded(
            step, params, st, rounds=3, inner_steps=5,
            key=jax.random.PRNGKey(1), recorder=rec)

    assert info["devices"] == 8
    assert info["kernel_path"] == "bitboard"
    assert info["flips"] == 16 * 15
    assert info["flips_per_s"] > 0
    assert info["flips_per_s_per_chip"] == pytest.approx(
        info["flips_per_s"] / 8)

    with open(path, encoding="utf-8") as f:
        lines = [ln for ln in f if ln.strip()]
    events = []
    for ln in lines:
        assert obs.validate_line(ln) is None, ln
        events.append(json.loads(ln))
    assert obs.validate_spans(events) == []
    names = [e["event"] for e in events]
    assert names.count("run_start") == names.count("run_end") == 1
    chunks = [e for e in events if e["event"] == "chunk"]
    assert len(chunks) == 3
    assert all(c["path"] == "bitboard" and c["devices"] == 8
               for c in chunks)
    span_names = [e["name"] for e in events if e["event"] == "span_begin"]
    assert span_names.count("swap_round") == 3
    assert span_names.count("chunk") == 3
    run_end = [e for e in events if e["event"] == "run_end"][0]
    assert run_end["flips_per_s_per_chip"] == pytest.approx(
        run_end["flips_per_s"] / 8)
