"""Sharding tests on the 8-virtual-device CPU mesh (conftest sets
xla_force_host_platform_device_count=8): the TPU-native analogue of
multi-node testing without a cluster (SURVEY.md section 4.5).

Key property: sharding the chains axis over 1 vs 8 devices is
bit-identical — per-chain PRNG keys make the batch embarrassingly parallel.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import flipcomplexityempirical_tpu as fce

from conftest import assert_grid_districts_connected
from flipcomplexityempirical_tpu import distribute
from flipcomplexityempirical_tpu.sampling import tempering


def setup_batch(chains=16, seed=0, spec=None, base=0.8):
    g = fce.graphs.square_grid(6, 6)
    spec = spec or fce.Spec()
    plan = fce.graphs.stripes_plan(g, 2)
    dg, states, params = fce.init_batch(
        g, plan, n_chains=chains, seed=seed, spec=spec, base=base,
        pop_tol=0.3)
    return g, dg, states, params, spec


def test_eight_devices_available():
    assert len(jax.devices()) == 8


@pytest.mark.slow
def test_sharded_run_bit_identical():
    g, dg, states, params, spec = setup_batch()
    res1 = fce.run_chains(dg, spec, params, states, n_steps=100)

    mesh = distribute.make_mesh(8)
    g2, dg2, states2, params2, _ = setup_batch()
    states2 = distribute.shard_chain_batch(mesh, states2)
    params2 = distribute.shard_chain_batch(mesh, params2)
    res2 = fce.run_chains(dg2, spec, params2, states2, n_steps=100)

    s1, s2 = res1.host_state(), res2.host_state()
    assert (np.asarray(s1.assignment) == np.asarray(s2.assignment)).all()
    assert (res1.history["cut_count"] == res2.history["cut_count"]).all()
    assert (res1.history["wait"] == res2.history["wait"]).all()


def test_cross_device_swaps_pair_adjacent_ranks():
    """At base=1 every valid swap accepts, so one train step (a parity-0
    round then a parity-1 round) must apply the deterministic rank
    brickwork to each slot's ladder — rank-paired, NOT device-paired:
    after the parity-0 exchange the betas sit permuted across devices,
    and the parity-1 round must still pair the adjacent TEMPERATURES."""
    mesh = distribute.make_mesh(8)
    g, dg, states, params, spec = setup_batch(chains=8, base=1.0)
    betas = np.linspace(2.0, 0.25, 8).astype(np.float32)  # descending
    params = params.replace(beta=jnp.asarray(betas))
    states = distribute.shard_chain_batch(mesh, states)
    params = distribute.shard_chain_batch(mesh, params)
    step = distribute.make_train_step(dg, spec, mesh, inner_steps=3)
    params2, _, info = step(jax.random.PRNGKey(1), params, states)
    # expected: pos_of_rank starts [0..7]; parity-0 swaps rank pairs
    # (0,1)(2,3)(4,5)(6,7); parity-1 swaps (1,2)(3,4)(5,6)
    pos_of_rank = np.arange(8)
    for parity in (0, 1):
        for r in range(7):
            if r % 2 == parity:
                pos_of_rank[[r, r + 1]] = pos_of_rank[[r + 1, r]]
    expect = np.empty(8, np.float32)
    expect[pos_of_rank] = betas
    np.testing.assert_array_equal(np.asarray(params2.beta), expect)
    assert int(info["swaps"]) == 2 * (4 + 3)  # both partners count


def test_train_step_with_cross_device_exchange():
    mesh = distribute.make_mesh(8)
    g, dg, states, params, spec = setup_batch(chains=16)
    # ladder along the device axis: betas vary per device
    betas = np.repeat(np.linspace(0.2, 2.0, 8), 2).astype(np.float32)
    params = params.replace(beta=jnp.asarray(betas))
    states = distribute.shard_chain_batch(mesh, states)
    params = distribute.shard_chain_batch(mesh, params)

    step = distribute.make_train_step(dg, spec, mesh, inner_steps=20)
    key = jax.random.PRNGKey(7)
    params2, states2, info = step(key, params, states)
    assert int(info["accepts"]) > 0
    s2 = jax.tree.map(np.asarray, states2)
    assert int(np.asarray(s2.t_yield).sum()) == 16 * 20
    # betas remain a permutation of the original ladder within each pair set
    b = np.sort(np.asarray(params2.beta))
    assert np.allclose(b, np.sort(betas))


def test_within_batch_tempering_swaps():
    g, dg, states, params, spec = setup_batch(chains=16)
    params = tempering.make_ladder_params(
        params, betas=np.linspace(0.2, 2.0, 4), n_ladders=4)
    res = fce.run_chains(dg, spec, params, states, n_steps=60)
    key = jax.random.PRNGKey(0)
    p2, accept = tempering.swap_within_batch(
        key, res.state, params, n_rungs=4, parity=0, spec=spec)
    accept = np.asarray(accept)
    b0 = np.asarray(params.beta).reshape(4, 4)
    b2 = np.asarray(p2.beta).reshape(4, 4)
    # swaps only exchange betas within ladders: multiset per ladder preserved
    assert np.allclose(np.sort(b2, axis=1), np.sort(b0, axis=1))
    # parity-0 round only touches pairs (0,1) and (2,3)
    changed = (b0 != b2)
    assert not changed[:, [0, 1]].any() or True  # pairs may or may not swap
    # accepted pairs actually exchanged
    for lad in range(4):
        for r in (0, 2):
            i = lad * 4 + r
            if accept[i]:
                assert b2[lad, r] == b0[lad, r + 1]
                assert b2[lad, r + 1] == b0[lad, r]


def test_within_batch_tempering_board_path():
    """swap_within_batch reads only cut_count + batch size, so the board
    fast path tempers in-batch too: alternate board chunks with swap
    rounds, check ladder-multiset preservation and the physical ordering
    (hot rungs sit at longer boundaries)."""
    g = fce.graphs.square_grid(6, 32)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(contiguity="patch", geom_waits=False,
                    parity_metrics=False)
    n_rungs, n_ladders = 4, 8
    betas = np.linspace(0.2, 2.0, n_rungs)
    bg, states, params = fce.sampling.init_board(
        g, plan, n_chains=n_rungs * n_ladders, seed=9, spec=spec,
        base=2.0, pop_tol=0.4)
    params = tempering.make_ladder_params(params, betas=betas,
                                          n_ladders=n_ladders)
    key = jax.random.PRNGKey(3)
    accepts = 0
    for r in range(30):
        res = fce.sampling.run_board(bg, spec, params, states, n_steps=41,
                                     record_history=False)
        states = res.state
        key, ks = jax.random.split(key)
        params, acc = tempering.swap_within_batch(
            ks, states, params, n_rungs=n_rungs, parity=r % 2, spec=spec)
        accepts += int(np.asarray(acc).sum())
    assert accepts > 0
    b = np.asarray(params.beta).reshape(n_ladders, n_rungs)
    assert np.allclose(np.sort(b, axis=1), betas)
    # physical sanity: base > 1 with high beta favors SHORT boundaries,
    # so mean cut at the hottest rung (lowest beta) exceeds the coldest
    cuts = np.asarray(states.cut_count).astype(float)
    beta_flat = np.asarray(params.beta)
    b32 = betas.astype(np.float32)
    hot = cuts[beta_flat == b32[0]].mean()
    cold = cuts[beta_flat == b32[-1]].mean()
    assert hot > cold, (hot, cold)


def test_board_sharded_pair_train_step():
    """The k-district pair walk composes with the sharded board train
    step: chunks auto-dispatch (pair bit body on this 32-aligned grid)
    and the exchange ladder reads the carried cut_count."""
    k = 4
    g = fce.graphs.square_grid(4, 32)
    plan = fce.graphs.stripes_plan(g, k)
    spec = fce.Spec(n_districts=k, proposal="pair", contiguity="patch")
    bg, states, params = fce.sampling.init_board(
        g, plan, n_chains=16, seed=0, spec=spec, base=1.3, pop_tol=0.6)
    mesh = distribute.make_mesh(8)
    betas = np.repeat(np.linspace(0.25, 2.0, 8), 2).astype(np.float32)
    params = params.replace(beta=jnp.asarray(betas))
    states = distribute.shard_chain_batch(mesh, states)
    params = distribute.shard_chain_batch(mesh, params)
    from flipcomplexityempirical_tpu.kernel import bitboard as bb
    assert bb.supported_pair(bg, spec)   # the documented dispatch claim
    step = distribute.make_board_train_step(bg, spec, mesh, inner_steps=5,
                                            exchange=True)
    params, states, info = step(jax.random.PRNGKey(2), params, states)
    t = np.asarray(jax.device_get(states.t_yield))
    assert int(t.sum()) == 16 * 5, t
    assert int(info["accepts"]) > 0
    b = np.asarray(jax.device_get(states.board)).reshape(-1, 4, 32)
    assert_grid_districts_connected(b, k)


@pytest.mark.slow
def test_board_sharded_run_bit_identical():
    """The board fast path shards the chains axis transparently: 1 vs 8
    devices produce bit-identical histories and state."""
    g = fce.graphs.square_grid(8, 8)
    spec = fce.Spec(contiguity="patch")
    plan = fce.graphs.stripes_plan(g, 2)

    def setup():
        return fce.sampling.init_board(g, plan, n_chains=16, seed=3,
                                       spec=spec, base=1.3, pop_tol=0.3)

    bg, st, params = setup()
    res1 = fce.sampling.run_board(bg, spec, params, st, n_steps=100)

    mesh = distribute.make_mesh(8)
    bg2, st2, params2 = setup()
    st2 = distribute.shard_chain_batch(mesh, st2)
    params2 = distribute.shard_chain_batch(mesh, params2)
    res2 = fce.sampling.run_board(bg2, spec, params2, st2, n_steps=100)

    for k in res1.history:
        np.testing.assert_array_equal(res1.history[k], res2.history[k],
                                      err_msg=k)
    s1, s2 = res1.host_state(), res2.host_state()
    for fld in ("board", "part_sum", "num_flips", "cut_times_e"):
        np.testing.assert_array_equal(np.asarray(getattr(s1, fld)),
                                      np.asarray(getattr(s2, fld)),
                                      err_msg=fld)


def test_board_train_step_cross_device_exchange():
    """shard_map'd board kernel + rank-paired beta ladder: the multi-chip
    form of the benchmark workload."""
    from flipcomplexityempirical_tpu.kernel import board as kboard

    mesh = distribute.make_mesh(8)
    g = fce.graphs.square_grid(8, 8)
    spec = fce.Spec(contiguity="patch")
    plan = fce.graphs.stripes_plan(g, 2)
    bg, st, params = fce.sampling.init_board(g, plan, n_chains=16, seed=1,
                                             spec=spec, base=1.3,
                                             pop_tol=0.3)
    betas = np.repeat(np.linspace(0.2, 2.0, 8), 2).astype(np.float32)
    params = params.replace(beta=jnp.asarray(betas))
    st = distribute.shard_chain_batch(mesh, st)
    params = distribute.shard_chain_batch(mesh, params)

    step = distribute.make_board_train_step(bg, spec, mesh, inner_steps=20)
    params2, st2, info = step(jax.random.PRNGKey(7), params, st)
    assert int(info["accepts"]) > 0
    s2 = jax.tree.map(np.asarray, st2)
    assert int(np.asarray(s2.t_yield).sum()) == 16 * 20
    assert np.allclose(np.sort(np.asarray(params2.beta)), np.sort(betas))
