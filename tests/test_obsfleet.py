"""Fleet observability plane (ISSUE 18).

The load-bearing claims, each tested here:
- ``FleetCollector`` tails every stream incrementally — file-offset
  checkpoints survive a collector restart without double-counting, a
  torn tail (the SIGKILL mid-line write) waits un-consumed until the
  writer finishes it, and a truncated stream re-reads from zero;
- ``prometheus_text()`` is well-formed text exposition (HELP/TYPE
  pairs, label syntax, fleet rollups that sum per-stream snapshots);
- the trace the front door mints at submit survives the WAL record,
  spool doc, and lease file: the worker's run spans join it, and
  ``trace_export --fleet``'s end-to-end parenting gate passes;
- ``/v1/metrics`` and ``/v1/fleet`` serve live collector state, and
  ``POST /v1/profile/<job>`` drops the atomic marker the worker honors
  at its next segment boundary;
- ``obs/slo.py`` burn-rate math: storms trip, clean timelines pass,
  thin populations pass vacuously;
- the fleet-layout heartbeat probe names the stale worker.

The committed fixture under tests/fixtures/obs/fleet/ is a fully
terminal 2-worker run (journal + server stream + 2 worker streams +
status docs) generated with the real Recorder on a deterministic
clock. The cross-process gate (real server + 2 worker processes,
mid-run scrape, SLO breach injection) is tools/obsfleet_check.sh
(`make obsfleet-check`), wrapped here as a slow-tier test.
"""

import json
import os
import shutil
import subprocess
import sys
import time
import urllib.request

import pytest

from flipcomplexityempirical_tpu import obs
from flipcomplexityempirical_tpu.obs import slo
from flipcomplexityempirical_tpu.obs.aggregate import FleetCollector
from flipcomplexityempirical_tpu.resilience import faults as rfaults
from flipcomplexityempirical_tpu.service import (
    FleetServer, ServiceClient, Worker, clear_drain)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures", "obs", "fleet")

OVERRIDES = {"total_steps": 60, "n_chains": 2, "checkpoint_every": 20}


@pytest.fixture(autouse=True)
def _clean_process_state():
    rfaults.install_plan(None)
    clear_drain()
    yield
    rfaults.install_plan(None)
    clear_drain()


def _tools(name):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


def _fixture_copy(tmp_path) -> str:
    root = os.path.join(str(tmp_path), "fleet")
    shutil.copytree(FIXTURE, root)
    return root


# ---------------------------------------------------------------------------
# FleetCollector: incremental tailing, checkpoints, torn tails
# ---------------------------------------------------------------------------

def test_collector_folds_fixture_and_is_idempotent(tmp_path):
    root = _fixture_copy(tmp_path)
    c = FleetCollector(root)
    first = c.poll()
    assert first == {"events": 30, "streams": 3}
    # folded topology: both jobs seen running (terminal stages are the
    # server's status files, merged in /v1/fleet), both workers exited
    jobs = c.state["jobs"]
    assert sorted(jobs) == ["j0000", "j0001"]
    assert jobs["j0000"]["trace_id"] == "job:j0000"
    assert jobs["j0000"]["worker"] == "w1"
    assert jobs["j0000"]["profiled_segments"] == 2
    assert all(w["exited"] for w in c.state["workers"].values())
    # ident stamped at the Recorder layer, recovered from the stream
    srv = c.state["streams"]["server.jsonl"]
    assert srv["ident"] == {"pid": 101, "worker_name": "server"}
    # nothing new: the second poll reads zero bytes
    assert c.poll() == {"events": 0, "streams": 3}


def test_collector_checkpoint_survives_restart(tmp_path):
    root = _fixture_copy(tmp_path)
    FleetCollector(root).poll()
    assert os.path.exists(os.path.join(root, "events",
                                       ".collector.json"))
    # a RESTARTED collector (fresh instance, same root) resumes from
    # the checkpoint: no event is counted twice
    c2 = FleetCollector(root)
    assert c2.poll()["events"] == 0
    assert c2.state["streams"]["w1.jsonl"]["events"][
        "worker_started"] == 1
    # new events past the checkpointed offset are picked up
    with open(os.path.join(root, "events", "w1.jsonl"), "a") as f:
        f.write(json.dumps({"v": 1, "ts": 2000.0,
                            "event": "worker_started",
                            "worker": "w1"}) + "\n")
    assert FleetCollector(root).poll()["events"] == 1


def test_collector_waits_for_torn_tail(tmp_path):
    root = _fixture_copy(tmp_path)
    c = FleetCollector(root)
    c.poll()
    path = os.path.join(root, "events", "w2.jsonl")
    line = json.dumps({"v": 1, "ts": 2000.0, "event": "worker_exited",
                       "worker": "w2", "reason": "idle"}) + "\n"
    # a half-written line (no newline yet) must not be consumed...
    with open(path, "a") as f:
        f.write(line[:20])
    assert c.poll()["events"] == 0
    # ...and is read whole once the writer finishes it
    with open(path, "a") as f:
        f.write(line[20:])
    assert c.poll()["events"] == 1
    assert c.state["streams"]["w2.jsonl"]["malformed"] == 0


def test_collector_counts_malformed_and_resets_on_truncation(tmp_path):
    root = _fixture_copy(tmp_path)
    c = FleetCollector(root)
    c.poll()
    path = os.path.join(root, "events", "w1.jsonl")
    with open(path, "a") as f:
        f.write("{not json}\n")
    assert c.poll()["events"] == 0
    assert c.state["streams"]["w1.jsonl"]["malformed"] == 1
    # a stream that SHRANK (rotation) re-reads from offset zero
    with open(path, "w") as f:
        f.write(json.dumps({"v": 1, "ts": 3000.0,
                            "event": "worker_started",
                            "worker": "w1"}) + "\n")
    assert c.poll()["events"] == 1
    assert c.state["streams"]["w1.jsonl"]["offset"] == \
        os.path.getsize(path)


def test_prometheus_exposition_format(tmp_path):
    root = _fixture_copy(tmp_path)
    c = FleetCollector(root, checkpoint=False)
    c.poll()
    text = c.prometheus_text()
    assert text.endswith("\n")
    lines = text.splitlines()
    # every metric family announces itself: HELP then TYPE
    helps = [i for i, ln in enumerate(lines)
             if ln.startswith("# HELP")]
    for i in helps:
        assert lines[i + 1].startswith("# TYPE"), lines[i:i + 2]
    # samples are `name{label="v",...} value` or `name value`
    for ln in lines:
        if ln.startswith("#"):
            continue
        metric, _, value = ln.rpartition(" ")
        float(value)            # parses as a number
        assert metric and (metric.endswith("}") or "{" not in metric)
    # fleet rollups sum the per-stream snapshots (w1: 4096, w2: 8192)
    assert 'graft_fleet_counter{name="flips"} 12288' in lines
    assert 'graft_fleet_workers{state="exited"} 2' in lines
    assert 'graft_events_total{event="lease_acquired",' \
           'stream="w1"} 1' in lines
    # histogram digests surface count/sum/percentiles per stream
    assert any(ln.startswith('graft_histogram{name="segment_wall_s"')
               and '"p99"' in ln for ln in lines)
    # checkpoint=False never dirtied the fixture copy
    assert not os.path.exists(os.path.join(root, "events",
                                           ".collector.json"))


def test_fixture_passes_fleet_trace_gate():
    trace_export = _tools("trace_export")
    schema = trace_export._load_schema()
    assert trace_export.validate_fleet(FIXTURE, schema) == 0
    doc = trace_export.export(trace_export.fleet_streams(FIXTURE),
                              schema, fleet=True)
    evs = doc["traceEvents"]
    names = {e["args"]["name"] for e in evs
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert names == {"server", "w1", "w2"}
    # one flow (s->f pair) per adopted top-level span: queue_wait +
    # job span, per job
    assert sum(1 for e in evs if e.get("cat") == "fleet"
               and e["ph"] == "s") == 4


# ---------------------------------------------------------------------------
# SLO burn-rate math
# ---------------------------------------------------------------------------

def _fleet_events(n_jobs=6, wait_s=1.0, tail_s=None):
    evs = []
    for i in range(n_jobs):
        jid = f"j{i:04d}"
        sub = 1000.0 + i
        evs.append({"event": "job_submitted", "ts": sub, "job_id": jid})
        wait = (tail_s if tail_s is not None and i == n_jobs - 1
                else wait_s)
        evs.append({"event": "lease_acquired", "ts": sub + wait,
                    "job_id": jid, "worker": "w1"})
    return evs


def test_slo_clean_timeline_passes():
    rows = slo.evaluate(_fleet_events())
    assert all(r["ok"] for r in rows)
    by = {r["name"]: r for r in rows}
    assert by["queue_to_start_tail"]["value"] == 1.0
    assert by["lease_expiry_rate"]["burn"] == 0.0


def test_slo_queue_tail_trips_on_a_straggler():
    rows = slo.evaluate(_fleet_events(n_jobs=8, wait_s=1.0,
                                      tail_s=20.0))
    r = {x["name"]: x for x in rows}["queue_to_start_tail"]
    assert r["value"] == 20.0 and r["burn"] == pytest.approx(2.5)
    assert not r["ok"]


def test_slo_lease_expiry_storm_burns_by_worst_window():
    # 5 expirations inside one 60s window: 5/min vs target 2/min
    evs = _fleet_events() + [
        {"event": "lease_expired", "ts": 1100.0 + 10 * k,
         "job_id": "j0000", "worker": "w9"} for k in range(5)]
    r = {x["name"]: x for x in slo.evaluate(evs)}["lease_expiry_rate"]
    assert r["value"] == pytest.approx(5.0)
    assert r["burn"] == pytest.approx(2.5) and not r["ok"]
    # spread the same 5 at 50s apart: the worst 60s window holds only
    # 2 -> exactly at target, ok
    evs = _fleet_events() + [
        {"event": "lease_expired", "ts": 1100.0 + 50 * k,
         "job_id": "j0000", "worker": "w9"} for k in range(5)]
    r = {x["name"]: x for x in slo.evaluate(evs)}["lease_expiry_rate"]
    assert r["value"] == pytest.approx(2.0) and r["ok"]


def test_slo_vacuous_below_min_count():
    # 2 queue pairs < min_count 4: passes vacuously, burn 0, even with
    # a catastrophic tail
    rows = slo.evaluate(_fleet_events(n_jobs=2, tail_s=10_000.0))
    r = {x["name"]: x for x in rows}["queue_to_start_tail"]
    assert r["ok"] and r["burn"] == 0.0 and "vacuous" in r["detail"]


def test_slo_floor_and_cache_burn_directions():
    evs = _fleet_events()
    # first board run is warmup (jit compile) and must be excluded;
    # the straggler among the steady-state runs sets the floor
    evs += [{"event": "run_end", "ts": 2000.0 + i,
             "kernel_path": "board", "flips_per_s": fps}
            for i, fps in enumerate((1.0, 100.0, 10.0))]
    # k1's first miss is compulsory (cold); the 4 repeat probes (1 hit,
    # 3 misses) are what the cache is judged on
    evs += [{"event": "compile_cache_miss", "ts": 2010.0, "key": "k1"}]
    evs += [{"event": "compile_cache_hit", "ts": 2011.0, "key": "k1"}]
    evs += [{"event": "compile_cache_miss", "ts": 2012.0 + k,
             "key": "k1"} for k in range(3)]
    by = {r["name"]: r for r in slo.evaluate(evs)}
    # floor objectives burn as target/value: 0.2 / 0.1 = 2.0
    floor = by["throughput_floor"]
    assert floor["value"] == pytest.approx(0.1)
    assert floor["burn"] == pytest.approx(2.0) and not floor["ok"]
    assert "1 warmup(s) excluded" in floor["detail"]
    # hit-ratio burns the consumed error budget: exactly at target
    # (0.25 hits) the budget is fully but not over-spent -> burn 1.0, ok
    cache = by["compile_cache_hit_ratio"]
    assert cache["value"] == pytest.approx(0.25)
    assert cache["burn"] == pytest.approx(1.0) and cache["ok"]


def test_slo_cold_start_is_not_a_breach():
    """A cold fleet's compulsory work never burns budget: warmup-only
    runs and first-seen-key misses leave both objectives vacuous."""
    evs = _fleet_events()
    # one run per shape group: all warmup, nothing steady-state
    evs += [{"event": "run_end", "ts": 2000.0, "kernel_path": "board",
             "flips_per_s": 1.0, "worker_name": "w1"},
            {"event": "run_end", "ts": 2001.0, "kernel_path": "board",
             "flips_per_s": 500.0, "worker_name": "w2"}]
    # every probe is a distinct key's first miss
    evs += [{"event": "compile_cache_miss", "ts": 2010.0 + k,
             "key": f"k{k}"} for k in range(6)]
    by = {r["name"]: r for r in slo.evaluate(evs)}
    assert by["throughput_floor"]["ok"]
    assert by["throughput_floor"]["count"] == 0
    assert by["compile_cache_hit_ratio"]["ok"]
    assert "cold" in by["compile_cache_hit_ratio"]["detail"]
    # a first-seen HIT (persistent index pre-warm) still counts
    evs += [{"event": "compile_cache_hit", "ts": 2020.0, "key": "p0"}]
    r = {x["name"]: x for x in slo.evaluate(evs)}[
        "compile_cache_hit_ratio"]
    assert r["value"] == pytest.approx(1.0) and r["count"] == 1


# ---------------------------------------------------------------------------
# fleet heartbeat probe (obs_report --heartbeat DIRECTORY mode)
# ---------------------------------------------------------------------------

def test_fleet_heartbeat_probe_names_the_stale_worker(tmp_path):
    obs_report = _tools("obs_report")
    d = os.path.join(str(tmp_path), "workers")
    os.makedirs(d)

    def doc(name, status, hb_s=2.0, age_s=0.0):
        path = os.path.join(d, f"{name}.json")
        with open(path, "w") as f:
            json.dump({"worker": name, "pid": 1, "ts": 0.0,
                       "status": status, "job_id": None,
                       "hb_s": hb_s}, f)
        t = time.time() - age_s
        os.utime(path, (t, t))

    doc("w1", "running", hb_s=2.0, age_s=0.5)      # fresh
    doc("w2", "running", hb_s=2.0, age_s=60.0)     # stale
    doc("w3", "exited", hb_s=2.0, age_s=600.0)     # exempt by design
    err = obs_report.check_fleet_heartbeats(str(tmp_path), 2.0)
    assert err is not None and "worker w2" in err
    assert "w1" not in err and "w3" not in err
    # every worker fresh (or exited): no error
    doc("w2", "running", hb_s=2.0, age_s=1.0)
    assert obs_report.check_fleet_heartbeats(str(tmp_path), 2.0) is None
    # an empty fleet has no liveness story: that's an error, not a pass
    empty = os.path.join(str(tmp_path), "empty")
    os.makedirs(os.path.join(empty, "workers"))
    assert "no worker heartbeat docs" in \
        obs_report.check_fleet_heartbeats(empty, 2.0)


# ---------------------------------------------------------------------------
# live endpoints: /v1/metrics, /v1/fleet, /v1/profile
# ---------------------------------------------------------------------------

def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return (resp.status, resp.headers.get("Content-Type", ""),
                resp.read().decode("utf-8"))


def test_metrics_fleet_and_profile_endpoints(tmp_path):
    with FleetServer(str(tmp_path)) as srv:
        client = ServiceClient(srv.url, tenant="acme")
        job_id = client.submit(workload="frank",
                               overrides=OVERRIDES)["job_id"]
        status, ctype, body = _get(srv.url + "/v1/metrics")
        assert status == 200 and ctype.startswith("text/plain")
        assert "# TYPE graft_fleet_jobs gauge" in body
        status, _, body = _get(srv.url + "/v1/fleet")
        doc = json.loads(body)
        assert doc["stages"] in ({"pending": 1}, {"queued": 1})
        assert "queue_depth" in doc and doc["draining"] is False
        # profile request: 404 unknown job, then marker drop + readback
        req = urllib.request.Request(
            srv.url + "/v1/profile/j9999", data=b"{}", method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 404
        req = urllib.request.Request(
            srv.url + f"/v1/profile/{job_id}",
            data=json.dumps({"segments": 2}).encode(), method="POST")
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert json.loads(resp.read()) == {
                "job_id": job_id, "segments": 2,
                "profiling": "requested"}
        marker = os.path.join(str(tmp_path), "profile",
                              f"{job_id}.json")
        assert json.load(open(marker))["segments"] == 2
        _, _, body = _get(srv.url + f"/v1/profile/{job_id}")
        doc = json.loads(body)
        assert doc["requested"]["segments"] == 2
        assert doc["captured"] is None


# ---------------------------------------------------------------------------
# end-to-end: submit trace adopted by the worker, profile captured
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_trace_propagates_submit_to_worker_spans(tmp_path):
    """The tentpole invariant in-process: the trace minted at submit is
    the one the worker's spans carry, linked via ctx_parent_id to the
    submit span, with the queue wait back-stamped — and the --fleet
    gate agrees."""
    root = str(tmp_path)
    events = os.path.join(root, "events")
    with obs.recorder.Recorder(
            path=os.path.join(events, "server.jsonl"),
            ident={"pid": os.getpid(), "worker_name": "server"}) as rec:
        with FleetServer(root, recorder=rec) as srv:
            client = ServiceClient(srv.url, tenant="acme")
            job_id = client.submit(workload="frank",
                                   overrides=OVERRIDES)["job_id"]
            # profile marker BEFORE the run: the worker captures at
            # its segment boundaries mid-job
            req = urllib.request.Request(
                srv.url + f"/v1/profile/{job_id}",
                data=json.dumps({"segments": 1}).encode(),
                method="POST")
            urllib.request.urlopen(req, timeout=10).close()
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and not os.path.exists(
                    os.path.join(root, "jobs", f"{job_id}.json")):
                time.sleep(0.02)
            with obs.recorder.Recorder(
                    path=os.path.join(events, "w1.jsonl"),
                    ident={"pid": os.getpid(),
                           "worker_name": "w1"}) as wrec:
                w = Worker(root, worker="w1", ttl_s=30.0,
                           recorder=wrec)
                assert w.run_once() == 1
            assert client.status(job_id)["status"] == "done"

    trace_id = f"job:{job_id}"
    server_evs = [json.loads(ln) for ln in
                  open(os.path.join(events, "server.jsonl"))]
    worker_evs = [json.loads(ln) for ln in
                  open(os.path.join(events, "w1.jsonl"))]
    submit = [e for e in server_evs if e["event"] == "span_begin"
              and e["name"] == "submit"]
    assert len(submit) == 1 and submit[0]["trace_id"] == trace_id
    # the server's job_submitted/http_request carry the trace too
    assert any(e["event"] == "job_submitted"
               and e.get("trace_id") == trace_id for e in server_evs)
    wspans = [e for e in worker_evs if e["event"] == "span_begin"]
    adopted = [e for e in wspans
               if e.get("ctx_parent_id") == submit[0]["span_id"]]
    assert adopted and all(e["trace_id"] == trace_id for e in adopted)
    # queue wait back-stamped from the spool doc's submitted_ts
    assert any(e["name"] == "queue_wait" for e in adopted)
    job_span = [e for e in adopted if e["name"] == "job"]
    assert len(job_span) == 1
    # the run actually happened UNDER the adopted span (local child)
    assert any(e.get("parent_id") == job_span[0]["span_id"]
               for e in wspans)
    # every worker event is pid/name-stamped at the Recorder layer
    assert all(e.get("worker_name") == "w1" for e in worker_evs)

    # the on-demand profile was honored at a segment boundary
    capture = json.load(open(os.path.join(
        root, "artifacts", f"{job_id}.profile.json")))
    assert capture["ok"] is True and capture["segments"] >= 1
    assert not os.path.exists(os.path.join(root, "profile",
                                           f"{job_id}.json"))
    assert any(e["event"] == "profile_captured" for e in worker_evs)

    # the external gate sees the same story
    trace_export = _tools("trace_export")
    assert trace_export.validate_fleet(
        root, trace_export._load_schema()) == 0


# ---------------------------------------------------------------------------
# the cross-process gate script (slow tier, like fleet-check)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_obsfleet_check_script(tmp_path):
    """`make obsfleet-check` end to end: real server + 2 worker
    processes, mid-run /v1/metrics scrape, --fleet trace gate, SLO
    section + --strict breach injection, collector bench."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        ["bash", os.path.join(REPO, "tools", "obsfleet_check.sh")],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
        timeout=420)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout
