"""The examples/ scripts run end-to-end and print their summaries.

Each example is exercised as a real subprocess (its own interpreter, CPU
backend via the script's own --cpu flag — the conftest's in-process CPU
forcing does not reach subprocesses) at reduced sizes. Slow tier: each
run pays a fresh jax import + compile.
"""

import os
import subprocess
import sys

import pytest

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples")

CASES = [
    ("01_quickstart.py",
     ["--cpu", "--grid", "16", "--chains", "8", "--steps", "501"],
     "board fast path"),
    ("02_replica_exchange.py",
     ["--cpu", "--steps", "501", "--ladders", "2"],
     "swap accept rates"),
    ("03_dual_geometry.py",
     ["--cpu", "--precincts", "36", "--chains", "4", "--steps", "501"],
     "Polsby-Popper"),
    ("04_diagnostics.py",
     ["--cpu", "--chains", "4", "--steps", "501", "--burn", "100"],
     "bottleneck ratio"),
    ("05_multi_device.py",
     ["--devices", "2", "--inner-steps", "10", "--rounds", "1"],
     "cross-device beta swaps"),
    ("06_recom.py",
     ["--cpu", "--grid", "12", "--chains", "4", "--moves", "5"],
     "executed moves/chain"),
]


@pytest.mark.slow
@pytest.mark.parametrize("script,args,needle",
                         CASES, ids=[c[0] for c in CASES])
def test_example_runs(script, args, needle):
    env = dict(os.environ)
    # the scripts force CPU themselves (--cpu / virtual devices); drop the
    # conftest's 8-virtual-device flag so each example controls its own
    # backend exactly as a user invocation would
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, script)] + args,
        capture_output=True, text=True, timeout=540, env=env)
    assert r.returncode == 0, (script, r.stdout[-2000:], r.stderr[-2000:])
    assert needle in r.stdout, (script, needle, r.stdout[-2000:])
