"""Preemption-proof serving (ISSUE 11): durable journal, graceful
drain, hung-dispatch watchdog, elastic mesh recovery.

The load-bearing claims, each tested here:
- the write-ahead journal detects a torn tail (SHA-256 + seq) and
  recovery replays the longest intact prefix;
- a drain (injected sigterm or a real SIGTERM) requeues in-flight jobs
  without burning a retry and a recovered service finishes them
  BIT-IDENTICALLY to uninterrupted runs — across a crash-point x
  tail-state matrix;
- the watchdog fires on a hung dispatch, journals poison-suspect, and
  recovery retries those jobs solo;
- interleaved supervision deadlines no longer clobber each other
  (per-scope state, not a module global);
- a 4-device mesh that loses devices resumes on the surviving
  power-of-two sub-mesh, and the record is marked so bench_compare
  refuses to gate it.
"""

import json
import os
import signal
import subprocess
import threading
import time

import numpy as np
import pytest

from flipcomplexityempirical_tpu import obs
from flipcomplexityempirical_tpu.control import ControlLoop, EarlyStopPolicy
from flipcomplexityempirical_tpu.experiments import driver as drv
from flipcomplexityempirical_tpu.experiments.config import ExperimentConfig
from flipcomplexityempirical_tpu.obs.metrics import MetricsRegistry
from flipcomplexityempirical_tpu.resilience import faults as rfaults
from flipcomplexityempirical_tpu.resilience import supervisor as sup
from flipcomplexityempirical_tpu.resilience.degrade import is_device_loss
from flipcomplexityempirical_tpu.service import (
    DispatchWatchdog, DrainController, DrainRequested, EXIT_DRAINED,
    Journal, LeaseManager, SweepService, Worker, check_drain,
    clear_drain, drain_requested, request_drain)
from flipcomplexityempirical_tpu.service import journal as jnl

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_process_state():
    rfaults.install_plan(None)
    clear_drain()
    yield
    rfaults.install_plan(None)
    clear_drain()


# same segmenting as test_resilience's checkpoint configs (60 steps in
# 20-step segments, 2 chains): recovery runs here then reuse the jit
# specializations those tests already compiled, keeping the scenario
# fixture inside the fast-tier budget
FRANK = dict(family="frank", base=0.3, pop_tol=0.1, total_steps=60,
             n_chains=2, backend="jax", checkpoint_every=20)


def _cfg(alignment=2, seed=3, **kw):
    return ExperimentConfig(alignment=alignment, seed=seed,
                            **{**FRANK, **kw})


def _solo(cfg):
    g, plan, _ = drv.build_graph_and_plan(cfg)
    return drv._run_jax(cfg, g, plan, None)


def _assert_result_matches(got, ref):
    for k in ("end_signed", "cut_times", "num_flips", "waits_all"):
        np.testing.assert_array_equal(np.asarray(got[k]),
                                      np.asarray(ref[k]), err_msg=k)
    for k in ref["history"]:
        np.testing.assert_array_equal(np.asarray(got["history"][k]),
                                      np.asarray(ref["history"][k]),
                                      err_msg=f"history[{k}]")


# ---------------------------------------------------------------------------
# journal: integrity, torn tails, fault site
# ---------------------------------------------------------------------------

def test_journal_round_trip(tmp_path):
    p = str(tmp_path / "journal.jsonl")
    j = Journal(p)
    j.append("job_submitted", job_id="j0000", config={"x": 1})
    j.append("batch_started", batch_id="b0000", jobs=["j0000"])
    records, truncated = Journal.read(p)
    assert not truncated
    assert [r["kind"] for r in records] == ["job_submitted",
                                           "batch_started"]
    assert [r["seq"] for r in records] == [0, 1]
    # reopening continues the sequence and keeps the prefix
    j2 = Journal(p)
    assert j2.dropped == 0
    assert len(j2.recovered_records) == 2
    j2.append("job_done", job_id="j0000")
    records, truncated = Journal.read(p)
    assert not truncated and [r["seq"] for r in records] == [0, 1, 2]


def test_journal_detects_and_repairs_torn_tail(tmp_path):
    p = str(tmp_path / "journal.jsonl")
    j = Journal(p)
    j.append("job_submitted", job_id="j0000", config={})
    j.append("job_done", job_id="j0000")
    with open(p, "ab") as f:  # the write the preemption interrupted
        f.write(b'{"seq": 2, "kind": "job_fail')
    records, truncated = Journal.read(p)
    assert truncated and len(records) == 2
    # opening repairs the file on disk and reports the drop
    j2 = Journal(p)
    assert j2.dropped == 1
    records, truncated = Journal.read(p)
    assert not truncated and len(records) == 2
    # appends continue from the repaired tail
    j2.append("job_requeued", job_id="j0000")
    records, truncated = Journal.read(p)
    assert not truncated and records[-1]["seq"] == 2


def test_journal_sha_break_invalidates_suffix(tmp_path):
    """A bit-rotted record in the MIDDLE invalidates itself and every
    later record: the journal is append-only, so an intact suffix
    behind a broken record cannot be trusted to belong to this run."""
    p = str(tmp_path / "journal.jsonl")
    j = Journal(p)
    for i in range(3):
        j.append("job_submitted", job_id=f"j{i:04d}", config={})
    lines = open(p).read().splitlines()
    lines[1] = lines[1].replace('"j0001"', '"j9999"')  # sha now wrong
    open(p, "w").write("\n".join(lines) + "\n")
    records, truncated = Journal.read(p)
    assert truncated and len(records) == 1
    assert records[0]["job_id"] == "j0000"


def test_journal_append_fault_site(tmp_path):
    p = str(tmp_path / "journal.jsonl")
    j = Journal(p)
    rfaults.install_from_spec("journal.append:once")
    with pytest.raises(rfaults.InjectedFault):
        j.append("job_submitted", job_id="j0000", config={})
    # the fault fired BEFORE the write: nothing reached the file
    assert Journal.read(p) == ([], False)
    j.append("job_submitted", job_id="j0000", config={})
    assert len(Journal.read(p)[0]) == 1


def test_journal_truncate_rule_tears_after_write(tmp_path):
    """``journal.append:truncate`` models dying DURING the journal
    write: the record lands torn, and the next open repairs it."""
    p = str(tmp_path / "journal.jsonl")
    j = Journal(p)
    # arm before the journal's first corrupt_file consultation: truncate
    # rules count their own hit stream, so @2 addresses the 2nd append.
    # Pad the torn record so the half-file tear lands inside IT rather
    # than clipping the intact first record.
    rfaults.install_from_spec("journal.append:truncate@2")
    j.append("job_submitted", job_id="j0000", config={})
    j.append("job_done", job_id="j0000", note="x" * 512)
    rfaults.install_plan(None)
    records, truncated = Journal.read(p)
    assert truncated and len(records) == 1
    assert records[0]["kind"] == "job_submitted"
    assert Journal(p).dropped >= 1


def test_replay_folds_transitions():
    cfg_doc = {"family": "frank"}
    records = []
    jrn = []

    def rec(kind, **fields):
        r = {"seq": len(records), "ts": 0.0, "kind": kind, **fields}
        records.append(r)

    rec("job_submitted", job_id="j0000", config=cfg_doc)
    rec("job_submitted", job_id="j0001", config=cfg_doc)
    rec("batch_started", batch_id="b0000", jobs=["j0000", "j0001"])
    rec("job_done", job_id="j0000")
    rec("batch_poison_suspect", batch_id="b0000")
    state = jnl.replay(records)
    assert state["j0000"]["status"] == "done"
    assert state["j0001"]["status"] == "running"
    assert state["j0001"]["attempts"] == 1
    # poison-suspect marks surviving members solo
    assert state["j0001"]["solo"] is True


def test_config_doc_round_trip():
    cfg = _cfg(betas=(0.5, 1.0, 2.0))
    doc = json.loads(json.dumps(jnl.config_to_doc(cfg)))
    assert jnl.config_from_doc(doc) == cfg


# ---------------------------------------------------------------------------
# drain: flag, fault site, real signals
# ---------------------------------------------------------------------------

def test_check_drain_raises_after_request():
    check_drain("t")  # no-op while the flag is down
    request_drain("test")
    with pytest.raises(DrainRequested) as ei:
        check_drain("t")
    assert ei.value.reason == "test"
    clear_drain()
    check_drain("t")


def test_sigterm_fault_site_requests_drain():
    rfaults.install_from_spec("sigterm:once@2")
    check_drain("t")  # hit 1: no fire
    with pytest.raises(DrainRequested) as ei:
        check_drain("t")  # hit 2 fires and converts to a drain
    assert "injected-sigterm@2" in ei.value.reason


def test_drain_controller_handles_real_sigterm():
    with DrainController():
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 5.0
        while drain_requested() is None and time.monotonic() < deadline:
            time.sleep(0.01)
        assert drain_requested() == "SIGTERM"
        with pytest.raises(DrainRequested):
            check_drain("t")
    # handlers restored; flag cleared by the autouse fixture


# ---------------------------------------------------------------------------
# the crash-point x tail-state recovery matrix
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def drained_scenario(tmp_path_factory):
    """One drained-then-recovered service run, shared by the matrix:
    returns (scenario_dir, configs, solo_refs, journal_records). The
    scenario journal holds the FULL story — submits, the interrupted
    batch, drain requeues, service_draining, and the recovered run's
    solo batches and job_done records — so every crash point is a
    prefix of it."""
    td = str(tmp_path_factory.mktemp("preempt-scenario"))
    cfgs = [_cfg(alignment=2, seed=3), _cfg(alignment=1, seed=4)]
    rfaults.install_from_spec("sigterm:once@2")
    # max_batch_chains=2 keeps every dispatch on the solo 2-chain
    # shapes (no 4-chain coalesce compile); the coalesced drain story
    # runs in tools/preempt_check.sh
    svc = SweepService(outdir=td, max_batch_chains=2)
    for c in cfgs:
        svc.submit(c)
    svc.run_until_idle()
    rfaults.install_plan(None)
    clear_drain()
    assert svc.drained and svc.exit_code == EXIT_DRAINED
    svc2 = SweepService.recover(td, max_batch_chains=2)
    svc2.run_until_idle()
    assert svc2.exit_code == 0
    refs = {c.tag: _solo(c) for c in cfgs}
    records, truncated = Journal.read(jnl.journal_path_for(td))
    assert not truncated
    return td, cfgs, refs, records


def _cut_index(records, crash_point):
    """Journal prefix length for each simulated crash point."""
    kinds = [r["kind"] for r in records]
    if crash_point == "after_submit":
        return max(i for i, k in enumerate(kinds)
                   if k == "job_submitted") + 1
    if crash_point == "mid_batch":
        return kinds.index("batch_started") + 1
    if crash_point == "during_drain":
        return kinds.index("service_draining") + 1
    if crash_point == "after_sliceout":
        return kinds.index("job_done") + 1
    raise AssertionError(crash_point)


@pytest.mark.parametrize("tail", ["clean", "torn"])
@pytest.mark.parametrize("crash_point", ["after_submit", "mid_batch",
                                         "during_drain",
                                         "after_sliceout"])
def test_recovery_matrix(drained_scenario, tmp_path, crash_point, tail):
    src, cfgs, refs, records = drained_scenario
    td = str(tmp_path)
    # the crash leaves the journal prefix + (torn case) a partial write
    cut = _cut_index(records, crash_point)
    with open(jnl.journal_path_for(td), "w") as f:
        for r in records[:cut]:
            f.write(json.dumps(r, **jnl._CANONICAL) + "\n")
        if tail == "torn":
            f.write(json.dumps(records[cut], **jnl._CANONICAL)[:25])
    # checkpoints survive the crash alongside the journal
    for fn in os.listdir(src):
        if fn.startswith("ckpt") or fn.endswith(".npz"):
            data = open(os.path.join(src, fn), "rb").read()
            open(os.path.join(td, fn), "wb").write(data)

    ev = str(tmp_path / "events.jsonl")
    with obs.Recorder(ev) as rec:
        svc = SweepService.recover(td, recorder=rec, max_batch_chains=2)
        assert svc.journal.dropped == (1 if tail == "torn" else 0)
        svc.run_until_idle()
    assert svc.exit_code == 0
    done = {j.tag: j for j in svc.queue.jobs()}
    assert len(done) == 2
    for c in cfgs:
        assert done[c.tag].status == "done", (crash_point, tail,
                                              done[c.tag].error)
        if done[c.tag].result is not None:
            _assert_result_matches(done[c.tag].result, refs[c.tag])
    evs = [json.loads(l) for l in open(ev)]
    names = [e["event"] for e in evs]
    assert names.count("service_recovered") == 1
    assert (names.count("journal_truncated") == 1) == (tail == "torn")


def test_recovery_preserves_done_verdicts(drained_scenario, tmp_path):
    """Recovering the COMPLETED journal re-runs nothing: both jobs come
    back done (results live in artifacts, not the journal) and the
    service is immediately idle."""
    src, cfgs, refs, records = drained_scenario
    td = str(tmp_path)
    with open(jnl.journal_path_for(td), "w") as f:
        for r in records:
            f.write(json.dumps(r, **jnl._CANONICAL) + "\n")
    svc = SweepService.recover(td)
    jobs = {j.tag: j for j in svc.queue.jobs()}
    assert all(j.status == "done" for j in jobs.values())
    svc.run_until_idle()
    assert svc.exit_code == 0


def test_drain_requeue_does_not_burn_attempts(drained_scenario):
    """A drain is not a failure: the requeue record must not have cost
    the job a retry (attempts counts batch entries; the drain decrement
    cancels the interrupted batch's increment)."""
    _, _, _, records = drained_scenario
    state = jnl.replay(records[:_cut_index(records, "during_drain")])
    assert all(st["status"] == "queued" and st["attempts"] <= 1
               for st in state.values()), state


# ---------------------------------------------------------------------------
# adaptive control across a drain: the recovered service REPLAYS the
# journaled decisions, bit-identically
# ---------------------------------------------------------------------------

# loose enough that the 60-step frank histories pass at the FIRST
# 20-step boundary (split R-hat ~1.8-2.1, total ESS ~14-15 there), so
# every job's story is: one segment, one journaled stop
_LOOSE_STOP = dict(rhat_target=5.0, ess_target=4.0, patience=1,
                   min_columns=4)


def _control_action_key(r):
    return (r["action"], r["tag"], r["step"], r["policy"],
            json.dumps(r["detail"], sort_keys=True))


def test_drain_recover_replays_identical_control_actions(tmp_path):
    """SIGTERM-drain a controlled sweep mid-run, recover it, and demand
    the journal's control_action sequence — and the artifacts — come out
    identical to an uninterrupted run of the same submissions. The
    recovered loop ADOPTS the journaled stop (it does not re-derive or
    re-journal it), and jobs resumed at/past their stop boundary close
    immediately."""
    cfgs = [_cfg(alignment=2, seed=3), _cfg(alignment=1, seed=4)]

    # reference: same submissions, no interruption
    ref_dir = str(tmp_path / "ref")
    ref_loop = ControlLoop(policies=[EarlyStopPolicy(**_LOOSE_STOP)])
    ref_svc = SweepService(outdir=ref_dir, max_batch_chains=2,
                           control=ref_loop)
    ref_jobs = [ref_svc.submit(c) for c in cfgs]
    ref_svc.run_until_idle()
    assert [j.status for j in ref_jobs] == ["done", "done"]
    assert all(j.result["early_stopped"] == 20 for j in ref_jobs)
    ref_records, _ = Journal.read(jnl.journal_path_for(ref_dir))
    ref_ctl = [_control_action_key(r) for r in ref_records
               if r["kind"] == "control_action"]
    assert [(k[0], k[2]) for k in ref_ctl] == [("stop", 20)] * 2

    # drained run: job 1's stop consumes sigterm hit 1 (the stop breaks
    # the segment loop), job 2's first boundary takes hit 2 -> drain
    td = str(tmp_path / "drained")
    rfaults.install_from_spec("sigterm:once@2")
    loop = ControlLoop(policies=[EarlyStopPolicy(**_LOOSE_STOP)])
    svc = SweepService(outdir=td, max_batch_chains=2, control=loop)
    jobs = [svc.submit(c) for c in cfgs]
    svc.run_until_idle()
    rfaults.install_plan(None)
    clear_drain()
    assert svc.drained and svc.exit_code == EXIT_DRAINED

    # recovery: a FRESH loop adopts the journaled decisions
    loop2 = ControlLoop(policies=[EarlyStopPolicy(**_LOOSE_STOP)])
    svc2 = SweepService.recover(td, max_batch_chains=2, control=loop2)
    mid_records, _ = Journal.read(jnl.journal_path_for(td))
    adopted = sum(r["kind"] == "control_action" for r in mid_records)
    assert loop2.taken(cfgs[0].tag).get("stop", 0) + \
        loop2.taken(cfgs[1].tag).get("stop", 0) == adopted >= 1
    svc2.run_until_idle()
    assert svc2.exit_code == 0
    done = {j.tag: j for j in svc2.queue.jobs()}
    assert all(done[c.tag].status == "done" for c in cfgs)

    # the FULL journal (drained prefix + recovery) tells the identical
    # control story, decision for decision, detail byte for byte
    records, truncated = Journal.read(jnl.journal_path_for(td))
    assert not truncated
    ctl = [_control_action_key(r) for r in records
           if r["kind"] == "control_action"]
    assert ctl == ref_ctl

    # and the artifacts match the uninterrupted run's
    for c, ref_job in zip(cfgs, ref_jobs):
        got = done[c.tag].result
        if got is not None and ref_job.result is not None:
            _assert_result_matches(got, ref_job.result)


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------

def test_watchdog_effective_timeout():
    wd = DispatchWatchdog(timeout_s=7.5)
    assert wd.effective_timeout() == 7.5
    assert DispatchWatchdog().effective_timeout() is None
    met = MetricsRegistry()
    wd2 = DispatchWatchdog(metrics=met)
    assert wd2.effective_timeout() is None  # no latency prior yet
    for v in (1.0, 2.0, 100.0):
        met.observe("segment_wall_s", v)
    t = wd2.effective_timeout()
    hist = met.histogram("segment_wall_s")
    assert t == max(30.0, 10.0 * hist.percentile(0.95))


def test_watchdog_fires_and_journals(tmp_path):
    ev = str(tmp_path / "events.jsonl")
    journal = Journal(str(tmp_path / "journal.jsonl"))
    with obs.Recorder(ev) as rec:
        wd = DispatchWatchdog(recorder=rec, journal=journal,
                              timeout_s=0.1, poll_s=0.01)
        with wd.watch("b0000", ["j0000", "j0001"]):
            deadline = time.monotonic() + 5.0
            while not wd.fired_for("b0000") \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
        wd.stop()
    assert wd.stalled == ["b0000"]
    evs = [json.loads(l) for l in open(ev)]
    stalls = [e for e in evs if e["event"] == "dispatch_stalled"]
    assert len(stalls) == 1 and stalls[0]["batch_id"] == "b0000"
    assert stalls[0]["waited_s"] >= stalls[0]["timeout_s"] == 0.1
    records, _ = Journal.read(journal.path)
    assert [r["kind"] for r in records] == ["batch_poison_suspect"]
    assert records[0]["jobs"] == ["j0000", "j0001"]


def test_watchdog_unarmed_without_timeout():
    wd = DispatchWatchdog(timeout_s=None)  # no metrics either
    with wd.watch("b0000", ["j0000"]):
        time.sleep(0.05)
    wd.stop()
    assert wd.stalled == []


def test_service_stall_marks_poison_and_recovery_goes_solo(tmp_path):
    """End to end: an injected dispatch stall fires the watchdog inside
    a live service (dispatch_stalled + journaled poison-suspect), the
    stalled dispatch's error retries under the supervisor taxonomy, and
    a service recovered from that journal forces the batch's jobs
    SOLO."""
    td = str(tmp_path)
    ev = str(tmp_path / "events.jsonl")
    rfaults.install_from_spec("dispatch.stall:once")
    with obs.Recorder(ev) as rec:
        svc = SweepService(outdir=td, recorder=rec,
                           dispatch_timeout=0.1)
        svc.watchdog.poll_s = 0.01
        job = svc.submit(_cfg())
        svc.run_until_idle()
    rfaults.install_plan(None)
    assert job.status == "done", job.error
    evs = [json.loads(l) for l in open(ev)]
    # the stalled batch fires exactly once; the watchdog is advisory, so
    # the 0.1 s test timeout may ALSO flag the legitimate (successful)
    # solo retry, whose cold compile takes longer than that — count per
    # batch, not globally
    stalls = [e for e in evs if e["event"] == "dispatch_stalled"]
    assert len(stalls) >= 1
    assert sum(e["batch_id"] == stalls[0]["batch_id"]
               for e in stalls) == 1
    records, _ = Journal.read(jnl.journal_path_for(td))
    kinds = [r["kind"] for r in records]
    assert "batch_poison_suspect" in kinds

    # recovery from a journal cut right after the poison marker: the
    # job was mid-batch at the "kill", so it requeues forced-solo
    cut = kinds.index("batch_poison_suspect") + 1
    td2 = str(tmp_path / "restart")
    os.makedirs(td2)
    with open(jnl.journal_path_for(td2), "w") as f:
        for r in records[:cut]:
            f.write(json.dumps(r, **jnl._CANONICAL) + "\n")
    svc2 = SweepService.recover(td2)
    (job2,) = svc2.queue.jobs()
    assert job2.status == "queued" and job2.solo is True


# ---------------------------------------------------------------------------
# supervisor: interleaved deadlines (regression for the module global)
# ---------------------------------------------------------------------------

def test_interleaved_deadline_scopes_do_not_clobber():
    """The old module-level ``_deadline`` meant a second supervision
    (service thread, nested sweep) silently disarmed or hijacked the
    first. Scopes are now tracked per instance: ending one leaves the
    other armed, and expiry names the scope that expired."""
    outer = sup.DeadlineScope(60.0, "outer").begin()
    inner = sup.DeadlineScope(1e-4, "inner").begin()
    try:
        time.sleep(0.002)
        with pytest.raises(sup.ConfigDeadlineExceeded) as ei:
            sup.check_deadline()
        assert "inner" in str(ei.value)
        inner.end()
        sup.check_deadline()  # outer is still armed, not expired
        # the regression: ending an UNRELATED scope must not disarm a
        # live one (the old global could only track one deadline)
        third = sup.DeadlineScope(1e-4, "third").begin()
        outer.end()
        time.sleep(0.002)
        with pytest.raises(sup.ConfigDeadlineExceeded):
            sup.check_deadline()
        third.end()
        sup.check_deadline()
    finally:
        for s in (outer, inner):
            s.end()  # idempotent


def test_legacy_set_clear_deadline_is_lifo():
    sup.set_deadline(60.0, "a")
    sup.set_deadline(1e-4, "b")
    time.sleep(0.002)
    with pytest.raises(sup.ConfigDeadlineExceeded):
        sup.check_deadline()
    sup.clear_deadline()  # pops b
    sup.check_deadline()
    sup.clear_deadline()  # pops a
    sup.clear_deadline()  # extra clear is a no-op, not someone's scope
    sup.check_deadline()


def test_unarmed_scope_never_expires():
    s = sup.DeadlineScope(None, "x").begin()
    sup.check_deadline()
    s.end()


# ---------------------------------------------------------------------------
# elastic mesh recovery (conftest forces 8 virtual CPU devices)
# ---------------------------------------------------------------------------

def _mesh_setup(chains=8):
    import flipcomplexityempirical_tpu as fce
    from flipcomplexityempirical_tpu import distribute

    g = fce.graphs.square_grid(6, 6)
    spec = fce.Spec()
    plan = fce.graphs.stripes_plan(g, 2)
    dg, states, params = fce.init_batch(g, plan, n_chains=chains, seed=0,
                                        spec=spec, base=0.8, pop_tol=0.3)
    mesh = distribute.make_mesh(4)
    states = distribute.shard_chain_batch(mesh, states)
    params = distribute.shard_chain_batch(mesh, params)
    return dg, spec, mesh, states, params


def test_largest_pow2():
    from flipcomplexityempirical_tpu.distribute.sharded import largest_pow2
    assert [largest_pow2(n) for n in (1, 2, 3, 4, 5, 7, 8)] == \
        [1, 2, 2, 4, 4, 4, 8]
    with pytest.raises(ValueError):
        largest_pow2(0)


def test_is_device_loss_markers():
    assert is_device_loss(RuntimeError("UNAVAILABLE: socket closed"))
    assert is_device_loss(RuntimeError("FAILED PRECONDITION: device"))
    assert not is_device_loss(RuntimeError("shape mismatch"))
    # injected compile faults stand in for device loss in chaos tests
    rfaults.install_from_spec("compile:once")
    with pytest.raises(rfaults.InjectedFault) as ei:
        rfaults.fault_point("compile")
    assert is_device_loss(ei.value)


def test_reshard_down_moves_to_pow2_submesh():
    import jax
    from flipcomplexityempirical_tpu.distribute import sharded as dsh

    dg, spec, mesh, states, params = _mesh_setup()
    new_mesh, placed = dsh.reshard_down(states, mesh, lost=1)
    assert dsh._mesh_size(new_mesh) == 2
    np.testing.assert_array_equal(
        np.asarray(placed.accept_count),
        np.asarray(states.accept_count))
    with pytest.raises(ValueError):
        dsh.reshard_down(states, dsh.make_mesh(1), lost=1)


def test_elastic_run_survives_device_loss(tmp_path):
    import jax
    from flipcomplexityempirical_tpu.distribute import sharded as dsh
    from tools import bench_compare

    dg, spec, mesh, states, params = _mesh_setup()
    # dense=False forces the legacy general step: since ISSUE 15 the
    # default resolves general_dense, whose in-family fallback would
    # CONSUME the injected compile fault as a kernel degradation
    # (covered in test_elastic_dense_fault_degrades_not_resharded) —
    # the legacy body has no fallback, so the fault escapes run_sharded
    # as a device loss mid-run (segment 1)
    make_step = lambda m: dsh.make_train_step(dg, spec, m,
                                              inner_steps=5, dense=False)
    rfaults.install_from_spec("compile:once@3")
    ev = str(tmp_path / "events.jsonl")
    with obs.Recorder(ev) as rec:
        p2, s2, info = dsh.run_sharded_elastic(
            make_step, mesh, params, states, rounds=4, inner_steps=5,
            key=jax.random.PRNGKey(3), recorder=rec, segment_rounds=2)
    rfaults.install_plan(None)
    assert info["devices"] == 2 and info["degraded"] is True
    assert info["flips"] == 8 * 4 * 5  # no rounds lost to the reshard
    (deg,) = info["mesh_degradations"]
    assert (deg["from_devices"], deg["to_devices"]) == (4, 2)
    evs = [json.loads(l) for l in open(ev)]
    md = [e for e in evs if e["event"] == "mesh_degraded"]
    assert len(md) == 1 and md[0]["to_devices"] == 2
    # degraded records must not gate
    assert bench_compare.record_degraded(info)


def test_elastic_dense_fault_degrades_not_resharded(tmp_path):
    """The default sharded step resolves general_dense (ISSUE 15), which
    HAS an in-family fallback: an injected compile fault degrades the
    kernel general_dense -> general inside run_sharded — same segment,
    same key, shared ChainState with the conn plane stripped — so the
    fault never escapes as a device loss and the mesh stays whole."""
    import jax
    from flipcomplexityempirical_tpu.distribute import sharded as dsh
    from flipcomplexityempirical_tpu.resilience import degrade as rdegrade

    dg, spec, mesh, states, params = _mesh_setup()
    make_step = lambda m: dsh.make_train_step(dg, spec, m, inner_steps=5)
    assert make_step(mesh).kernel_path == "general_dense"
    mark = rdegrade.snapshot()
    rfaults.install_from_spec("compile:once@3")
    ev = str(tmp_path / "events.jsonl")
    with obs.Recorder(ev) as rec:
        _, _, info = dsh.run_sharded_elastic(
            make_step, mesh, params, states, rounds=4, inner_steps=5,
            key=jax.random.PRNGKey(3), recorder=rec, segment_rounds=2)
    rfaults.install_plan(None)
    assert info["devices"] == 4 and "degraded" not in info  # mesh whole
    assert info["flips"] == 8 * 4 * 5
    assert info["kernel_path"] == "general"  # finished on the fallback
    falls = [(d["from_path"], d["to_path"]) for d in rdegrade.since(mark)]
    assert falls == [("general_dense", "general")]
    evs = [json.loads(l) for l in open(ev)]
    assert not any(e["event"] == "mesh_degraded" for e in evs)
    kd = [e for e in evs if e["event"] == "kernel_path_degraded"]
    assert len(kd) == 1 and kd[0]["to_path"] == "general"


def test_elastic_run_clean_is_unmarked():
    import jax
    from flipcomplexityempirical_tpu.distribute import sharded as dsh
    from tools import bench_compare

    dg, spec, mesh, states, params = _mesh_setup()
    make_step = lambda m: dsh.make_train_step(dg, spec, m,
                                              inner_steps=5)
    _, _, info = dsh.run_sharded_elastic(
        make_step, mesh, params, states, rounds=2, inner_steps=5,
        key=jax.random.PRNGKey(3))
    assert info["devices"] == 4
    assert "degraded" not in info
    assert not bench_compare.record_degraded(info)


# ---------------------------------------------------------------------------
# fleet lease protocol (ISSUE 17): claim races, expiry, reclaim,
# SIGKILL-resume bit-identity
# ---------------------------------------------------------------------------

def _events(path):
    return [json.loads(line) for line in open(path)
            if line.strip()]


def test_lease_claim_race_exactly_one_winner(tmp_path):
    """Two workers racing one job: the O_EXCL create arbitrates, so
    exactly one claim lands per job no matter how the race times out."""
    root = str(tmp_path)
    m1 = LeaseManager(root, "w1")
    m2 = LeaseManager(root, "w2")
    for i in range(20):
        job_id = f"j{i:04d}"
        barrier = threading.Barrier(2)
        wins = []

        def race(mgr):
            barrier.wait()
            lease = mgr.claim(job_id)
            if lease is not None:
                wins.append(lease)

        ts = [threading.Thread(target=race, args=(m,))
              for m in (m1, m2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert len(wins) == 1, f"{len(wins)} winners on {job_id}"
        # the loser is blocked while the winner is live
        loser = m1 if wins[0]._mgr is m2 else m2
        assert loser.claim(job_id) is None
        wins[0].release()


def test_lease_release_then_fresh_claim(tmp_path):
    m1 = LeaseManager(root := str(tmp_path), "w1")
    m2 = LeaseManager(root, "w2")
    lease = m1.claim("j0000")
    assert lease is not None
    lease.release()
    lease.release()      # idempotent
    again = m2.claim("j0000")
    assert again is not None and m2.holder("j0000")["worker"] == "w2"


def test_expired_lease_reclaimed_with_events(tmp_path):
    ev = tmp_path / "events.jsonl"
    rec = obs.Recorder(str(ev))
    root = str(tmp_path)
    m1 = LeaseManager(root, "w1", ttl_s=2.0, recorder=rec)
    m2 = LeaseManager(root, "w2", ttl_s=2.0, recorder=rec)
    assert m1.claim("j0000") is not None
    assert m2.claim("j0000") is None          # live: blocked
    # w1 dies silently; its heartbeat stops and the lease ages out
    path = m1.path("j0000")
    old = time.time() - 10.0
    os.utime(path, (old, old))
    assert not m2.live("j0000")
    lease = m2.claim("j0000")
    assert lease is not None
    assert m2.holder("j0000")["worker"] == "w2"
    # the broken lease is a tombstone, not a deletion — forensics keep
    # who held it and who broke it
    tombs = [n for n in os.listdir(os.path.join(root, "leases"))
             if ".expired." in n]
    assert len(tombs) == 1 and ".expired.w2." in tombs[0]
    rec.close()
    events = _events(ev)
    expired = [e for e in events if e["event"] == "lease_expired"]
    assert len(expired) == 1
    assert expired[0]["worker"] == "w1" and expired[0]["by"] == "w2"
    assert expired[0]["age_s"] >= 2.0
    acquired = [e for e in events if e["event"] == "lease_acquired"]
    assert [(e["worker"], e["reclaim"]) for e in acquired] == \
        [("w1", False), ("w2", True)]


def test_heartbeat_refresh_keeps_lease_live(tmp_path):
    m1 = LeaseManager(root := str(tmp_path), "w1", ttl_s=2.0)
    m2 = LeaseManager(root, "w2", ttl_s=2.0)
    lease = m1.claim("j0000")
    old = time.time() - 10.0
    os.utime(lease.path, (old, old))
    assert not m1.live("j0000")
    lease.refresh()                    # the beat that saves it
    assert m1.live("j0000")
    assert m2.claim("j0000") is None


def test_torn_lease_blocks_while_fresh_reclaims_when_aged(tmp_path):
    """A lease torn mid-write (SIGKILL between create and payload, or
    an injected truncate) must not wedge the job forever — but a FRESH
    torn lease still blocks: the writer may be alive and about to
    heartbeat. Liveness is mtime-only, never payload-parseability."""
    rfaults.install_from_spec("lease.write:truncate")
    m1 = LeaseManager(root := str(tmp_path), "w1", ttl_s=2.0)
    m2 = LeaseManager(root, "w2", ttl_s=2.0)
    assert m1.claim("j0000") is not None
    assert m1.holder("j0000") is None          # payload torn
    assert m2.claim("j0000") is None           # fresh: still blocked
    path = m1.path("j0000")
    old = time.time() - 10.0
    os.utime(path, (old, old))
    ev = tmp_path / "events.jsonl"
    rec = obs.Recorder(str(ev))
    m3 = LeaseManager(root, "w3", ttl_s=2.0, recorder=rec)
    assert m3.claim("j0000") is not None
    rec.close()
    expired = [e for e in _events(ev) if e["event"] == "lease_expired"]
    assert expired[0]["worker"] == "unknown"   # torn payload: honest


def test_lease_write_fault_fails_claim_then_recovers(tmp_path):
    rfaults.install_from_spec("lease.write:once")
    m1 = LeaseManager(str(tmp_path), "w1")
    with pytest.raises(rfaults.InjectedFault):
        m1.claim("j0000")
    # the fault fired BEFORE the create: nothing on disk, retry lands
    assert m1.holder("j0000") is None
    assert m1.claim("j0000") is not None


def _spool_job(root, job_id, cfg, tenant="t0"):
    """Hand-spool one admitted job doc the way the front door does."""
    path = os.path.join(root, "jobs", f"{job_id}.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"job_id": job_id, "tenant": tenant, "admit_seq": 0,
                   "submitted_ts": time.time(),
                   "config": jnl.config_to_doc(cfg)}, f)


@pytest.mark.slow
def test_sigkill_resume_bit_identical(tmp_path):
    """The crash-interchangeability contract: worker A is killed
    mid-batch (emulated via the injected-sigterm drain at a segment
    boundary — same on-disk state as a SIGKILL after the checkpoint
    write), worker B reclaims and resumes from the sliced checkpoint,
    and the artifact digest equals an uninterrupted run's exactly.
    Slow tier (three full service runs — ~22 s cold); the real
    cross-process SIGKILL (exit 137) leg runs in tools/fleet_check.sh
    and the fast-tier lease matrix above stays tier-1."""
    cfg = _cfg(seed=17)
    # reference: one uninterrupted worker on its own root
    ref_root = str(tmp_path / "ref")
    ref_w = Worker(ref_root, worker="ref")
    _spool_job(ref_root, "j0000", cfg)
    assert ref_w.run_once() == 1
    ref_art = json.load(open(
        os.path.join(ref_root, "artifacts", "j0000.json")))
    assert ref_art["result_sha256"]

    # chaos root: worker A drains at the second segment boundary
    root = str(tmp_path / "fleet")
    wa = Worker(root, worker="wa")
    _spool_job(root, "j0000", cfg)
    rfaults.install_from_spec("sigterm:once@2")
    assert wa.run_once() == 0        # no verdict: drained mid-job
    assert wa.executed == []
    assert os.path.exists(
        jnl.journal_path_for(os.path.join(root, "run", "j0000")))
    assert os.listdir(os.path.join(root, "ckpt", "j0000"))
    # no terminal verdict published, lease released for the successor
    assert wa.terminal("j0000") is None
    assert wa.leases.holder("j0000") is None

    # worker B (a different process in production) recovers and lands
    # the SAME bits
    rfaults.install_plan(None)
    clear_drain()
    wb = Worker(root, worker="wb")
    assert wb.run_once() == 1
    status = wb.terminal("j0000")
    assert status["status"] == "done" and status["worker"] == "wb"
    art = json.load(open(
        os.path.join(root, "artifacts", "j0000.json")))
    assert art["result_sha256"] == ref_art["result_sha256"]


def test_worker_skips_published_verdicts(tmp_path):
    """Re-scanning a root whose jobs are all terminal must not claim,
    re-execute, or touch leases — the regression that once spun the
    fleet at hundreds of claim cycles per second."""
    root = str(tmp_path)
    w = Worker(root, worker="w1")
    _spool_job(root, "j0000", _cfg(seed=17))
    assert w.run_once() == 1
    w2 = Worker(root, worker="w2")
    assert w2.run_once() == 0
    assert w2.executed == []
    assert os.listdir(os.path.join(root, "leases")) == []
    assert w2.all_terminal()


# ---------------------------------------------------------------------------
# graftlint G007 covers service/ clock injection
# ---------------------------------------------------------------------------

def test_g007_flags_bare_time_time_in_service(tmp_path):
    from tools.graftlint import LintConfig, lint_file

    d = tmp_path / "service"
    d.mkdir()
    bad = d / "mod.py"
    bad.write_text("import time\n\n"
                   "def submit(job):\n"
                   "    job.ts = time.time()\n")
    cfg = LintConfig(root=str(tmp_path), rules=frozenset({"G007"}))
    findings = lint_file(str(bad), cfg)
    assert len(findings) == 1
    assert "injects clocks" in findings[0].message
    # passing time.time AS the clock (a reference) is the sanctioned
    # spelling; calling the injected clock is clean too
    ok = d / "mod2.py"
    ok.write_text("import time\n\n"
                  "def make_queue(clock=time.time):\n"
                  "    return clock()\n")
    assert lint_file(str(ok), cfg) == []
    # outside service/, timestamps stay legal (only durations flag)
    other = tmp_path / "resilience"
    other.mkdir()
    ts = other / "mod.py"
    ts.write_text("import time\n\n"
                  "def stamp(rec):\n"
                  "    rec.ts = time.time()\n")
    assert lint_file(str(ts), cfg) == []


# ---------------------------------------------------------------------------
# the tier-1 gate itself
# ---------------------------------------------------------------------------

def test_preempt_check_gate():
    """The frank-only subset keeps this inside the fast-tier budget
    (one cold XLA compile); `make preempt-check` runs both families."""
    proc = subprocess.run(
        [os.path.join(REPO, "tools", "preempt_check.sh")],
        cwd=REPO, capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PREEMPT_FAMILIES": "frank"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "preempt-check: OK" in proc.stdout
