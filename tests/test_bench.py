"""End-to-end smoke of bench.py, the scoreboard entry point.

The driver records BENCH_r{N} by running ``python bench.py`` and parsing
its single stdout JSON line, so a schema or CLI regression here costs a
round's record (round-3 post-mortem: ``parsed: null``). This drives the
real script in a subprocess at a tiny shape with explicit ``--cpu``
(NOT the cpu-fallback path — that one is probe-driven and frozen) and
pins the contract: exactly one JSON object on stdout, the documented
fields, an explicit chain count honored verbatim, and per-run detail on
stderr.

Slow tier: the subprocess pays a fresh JAX import + compile (~40 s).
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


@pytest.mark.slow
def test_bench_cpu_record_schema_and_explicit_chains():
    proc = subprocess.run(
        [sys.executable, BENCH, "--cpu", "--chains", "8", "--steps", "21",
         "--warmup", "11", "--chunk", "10"],
        capture_output=True, text=True, timeout=600, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"stdout must be ONE JSON line: {lines}"
    rec = json.loads(lines[0])
    assert rec["metric"] == "flips_per_sec_per_chip_64x64"
    assert rec["unit"] == "flips/s"
    assert rec["value"] > 0
    # explicit --cpu is a local verification run, not the probe-driven
    # fallback: no cpu_fallback tag, and the ratio stays numeric
    assert "cpu_fallback" not in rec
    # mirror bench.py's emission exactly (round to 4 decimals) — a
    # relative tolerance is tighter than the rounding grid at this shape
    assert rec["vs_baseline"] == round(rec["value"] / 1.25e6, 4)
    assert rec["repeat_policy"] == "best"
    detail = [json.loads(ln) for ln in proc.stderr.splitlines()
              if ln.startswith("{")]
    assert detail, "per-run detail JSON expected on stderr"
    assert detail[-1]["chains"] == 8, "explicit --chains must win"


@pytest.mark.slow
def test_bench_mesh_record_schema():
    """--mesh N: the MULTICHIP record contract. Two forced-host CPU
    devices, a 2-rung scaling ladder, a fast-path body (bitboard on the
    plain 32-grid — NOT the int8/general fallback), per-chip flips/s for
    cross-device-count gating, and still exactly one stdout JSON line."""
    proc = subprocess.run(
        [sys.executable, BENCH, "--mesh", "2", "--cpu", "--grid", "32",
         "--chains", "4", "--steps", "41", "--warmup", "21",
         "--chunk", "20"],
        capture_output=True, text=True, timeout=600, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"stdout must be ONE JSON line: {lines}"
    rec = json.loads(lines[0])
    assert rec["metric"] == "flips_per_sec_multichip_32x32"
    assert rec["devices"] == 2
    assert rec["device"].endswith(" x2")
    assert rec["body"] in ("bitboard", "lowered"), \
        "plain grid must win a fast-path body, not int8/general"
    assert rec["kernel_path"] == rec["body"]
    assert rec["value"] > 0
    assert rec["flips_per_s_per_chip"] > 0
    # --chains is PER CHIP in mesh mode (weak scaling)
    assert rec["chains_per_chip"] == 4
    assert rec["chains"] == 8
    ladder = rec["scaling"]
    assert [row["devices"] for row in ladder] == [1, 2]
    for row in ladder:
        assert row["flips_per_s_per_chip"] > 0
        assert row["flips_per_s"] == pytest.approx(
            row["flips_per_s_per_chip"] * row["devices"], rel=1e-3)
    assert rec["repeat_policy"] == "best"
