"""Packed lowered-stencil backend verification (kernel/bitboard.py's
lowered family, ISSUE 8).

Same promise as the rook bit-board: BIT-IDENTICAL trajectories to the
int8 lowered body — same PRNG stream, same m-th-valid selection, same
acceptance arithmetic, same cut_times planes and interface metrics — so
the primary tests run the same chunk through both bodies and assert
every state field and history row equal, on the paper's own workloads
(sec11, queen, Frankengraph). Because the int8 lowered body is already
proven against the general kernel and the compat/ (gerrychain-
semantics) oracle (tests/test_lower.py), bit-identity transfers every
one of those guarantees to the packed body; the packed body also gets
its own exact-enumeration chi2 bar in test_lower.py. Plus unit tests of
the canvas packing/shift primitives and the supported_lowered gate, and
the `make bitpack-check` CI wrapper.
"""

import os
import subprocess

import numpy as np
import pytest

import jax.numpy as jnp

import flipcomplexityempirical_tpu as fce
from flipcomplexityempirical_tpu.kernel import bitboard as bb
from flipcomplexityempirical_tpu.kernel import board as kb

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def surgical_grid(h=5, w=7):
    """test_lower.py's surgery menu: two holes, two diagonal bypasses."""
    return fce.graphs.square_grid(
        h, w, remove_nodes=[(0, 0), (2, 3)],
        extra_edges=[((0, 1), (1, 0)), ((3, 4), (4, 5))])


DEFAULT_KW = dict(n_districts=2, proposal="bi", contiguity="patch",
                  invalid="repropose", accept="cut",
                  parity_metrics=True, geom_waits=True)


def make_spec(**overrides):
    kw = dict(DEFAULT_KW)
    kw.update(overrides)
    return fce.Spec(**kw)


# ---------------------------------------------------------------------------
# canvas packing primitives
# ---------------------------------------------------------------------------

def test_pack_canvas_roundtrip(rng):
    for h, w in ((5, 7), (3, 32), (4, 40), (2, 33)):
        plane = rng.integers(0, 2, size=(3, h * w)).astype(np.int8)
        words = bb.pack_canvas(jnp.asarray(plane), h, w)
        assert words.shape == (3, h * bb.canvas_words(w))
        back = bb.unpack_canvas(words, h, w)
        np.testing.assert_array_equal(np.asarray(back), plane,
                                      err_msg=f"{h}x{w}")


def test_canvas_bit_index():
    for h, w in ((5, 7), (4, 40)):
        wpr32 = bb.canvas_words(w) * 32
        for flat in (0, 1, w - 1, w, h * w - 1):
            r, c = divmod(flat, w)
            got = int(bb.canvas_bit_index(jnp.asarray(flat), w))
            assert got == r * wpr32 + c, (h, w, flat)


def test_shift_canvas_matches_numpy(rng):
    """shift_canvas(dr, dc) reads the (r+dr, c+dc) neighbor wherever the
    destination cell's masked read is in-frame; out-of-frame garbage is
    the caller's to mask, so the check ANDs with the in-frame window
    exactly as the kernel ANDs with adj/b2_in planes."""
    h, w = 6, 40
    plane = rng.integers(0, 2, size=(2, h * w)).astype(np.int8)
    arr = plane.reshape(2, h, w)
    words = bb.pack_canvas(jnp.asarray(plane), h, w)
    for dr, dc in ((0, 1), (1, 1), (1, 0), (1, -1), (0, -1), (-1, -1),
                   (-1, 0), (-1, 1), (2, -2), (-2, 2)):
        got = np.asarray(bb.unpack_canvas(
            bb.shift_canvas(words, dr, dc, w), h, w)).reshape(2, h, w)
        want = np.zeros_like(arr)
        rs = slice(max(0, -dr), min(h, h - dr))
        cs = slice(max(0, -dc), min(w, w - dc))
        want[:, rs, cs] = arr[:, slice(max(0, dr), min(h, h + dr)),
                              slice(max(0, dc), min(w, w + dc))]
        inframe = np.zeros((h, w), dtype=bool)
        inframe[rs, cs] = True
        np.testing.assert_array_equal(got * inframe, want * inframe,
                                      err_msg=f"({dr},{dc})")


def test_counter_fold_canvas(rng):
    h, w, c, t = 4, 40, 3, 37
    planes = rng.integers(0, 2, size=(t, c, h * w)).astype(np.int8)
    npw = h * bb.canvas_words(w)
    slices = bb.counter_init(c, npw, t.bit_length())
    for r in range(t):
        slices = bb.counter_add(
            slices, bb.pack_canvas(jnp.asarray(planes[r]), h, w))
    got = bb.counter_fold_canvas(slices, h, w)
    np.testing.assert_array_equal(np.asarray(got), planes.sum(0))


# ---------------------------------------------------------------------------
# the supported_lowered gate
# ---------------------------------------------------------------------------

def test_supported_lowered_gate():
    g = surgical_grid()
    bg = kb.make_board_graph(g)
    assert bb.supported_lowered(bg, make_spec())
    assert bb.supported_lowered(bg, make_spec(contiguity="none"))
    assert bb.supported_lowered(bg, make_spec(accept="always"))
    # off-menu spec axes stand down to the int8 body
    assert not bb.supported_lowered(bg, make_spec(accept="corrected"))
    assert not bb.supported_lowered(
        bg, make_spec(n_districts=3, proposal="pair"))
    # w=4: one flat B2 offset realized by two (dr, dc) pairs => b2_disp
    # None => patch contiguity cannot run packed (but "none" still can)
    g4 = fce.graphs.square_grid(3, 4, remove_nodes=[(0, 0)],
                                extra_edges=[((0, 1), (1, 0))])
    bg4 = kb.make_board_graph(g4)
    assert bg4.b2_disp is None
    assert not bb.supported_lowered(bg4, make_spec())
    assert bb.supported_lowered(bg4, make_spec(contiguity="none"))
    assert kb.body_for(bg4, make_spec()) == "lowered"


def test_bits_true_on_unsupported_lowered_raises():
    """The stale 'no bit-board backend' rejection is gone: bits=True on
    a SUPPORTED lowered workload dispatches (the parity tests), and on
    an unsupported one the refusal names the gate and the opt-out."""
    g4 = fce.graphs.square_grid(3, 4, remove_nodes=[(0, 0)],
                                extra_edges=[((0, 1), (1, 0))])
    spec = make_spec()
    plan = fce.graphs.stripes_plan(g4, 2)
    bg, st, params = fce.sampling.init_board(
        g4, plan, n_chains=2, seed=0, spec=spec, base=1.3, pop_tol=0.5)
    with pytest.raises(ValueError, match="supported_lowered"):
        kb.run_board_chunk(bg, spec, params, st, 5, bits=True)
    # the explicit opt-out says which body it picked
    bgs = kb.make_board_graph(surgical_grid())
    assert kb.body_for(bgs, spec, bits=False) == "lowered"
    assert kb.body_for(bgs, spec, bits=True) == "lowered_bits"
    assert kb.body_for(bgs, spec) == "lowered_bits"


# ---------------------------------------------------------------------------
# packed B2 contiguity == int8 _stencil_patch_ok
# ---------------------------------------------------------------------------

def test_patch_ok_bits_matches_int8(rng):
    for g in (surgical_grid(), fce.graphs.square_grid(6, queen=True)):
        bg = kb.make_board_graph(g)
        cell = np.asarray(bg.cell_of_node)
        for _ in range(8):
            a = rng.integers(0, 2, g.n_nodes).astype(np.int8)
            board = np.full(bg.n, -1, np.int8)
            board[cell] = a
            want = np.asarray(kb._stencil_patch_ok(
                bg, jnp.asarray(board[None])))
            bw = bb.pack_canvas(
                jnp.asarray((board[None] == 1).astype(np.int8)),
                bg.h, bg.w)
            got = np.asarray(bb.unpack_canvas(
                bb._patch_ok_bits(bg, bw), bg.h, bg.w)).astype(bool)
            # int8 planes are only meaningful on real cells; the packed
            # verdict is consumed under the same adj masks
            mask = np.asarray(bg.node_mask).astype(bool)
            np.testing.assert_array_equal(got[:, mask], want[:, mask],
                                          err_msg=g.name)


# ---------------------------------------------------------------------------
# bit-identity: lowered_bits vs the int8 lowered body
# ---------------------------------------------------------------------------

def assert_chunks_equal(got, want):
    """Field-for-field equality of two (state, outs) chunk results,
    including None bookkeeping fields."""
    got_state, got_outs = got
    want_state, want_outs = want
    assert set(got_outs) == set(want_outs)
    for key in want_outs:
        np.testing.assert_array_equal(np.asarray(got_outs[key]),
                                      np.asarray(want_outs[key]),
                                      err_msg=key)
    for f in want_state.__dataclass_fields__:
        a, b = getattr(got_state, f), getattr(want_state, f)
        if b is None:
            assert a is None, f
            continue
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=f)


def _parity(g, plan, spec, chains=6, steps=75, seed=3):
    bg, st, params = fce.sampling.init_board(
        g, plan, n_chains=chains, seed=seed, spec=spec, base=1.3,
        pop_tol=0.3)
    assert bb.supported_lowered(bg, spec)
    assert kb.body_for(bg, spec) == "lowered_bits"
    assert_chunks_equal(
        kb.run_board_chunk(bg, spec, params, st, steps),
        kb.run_board_chunk(bg, spec, params, st, steps, bits=False))


@pytest.mark.parametrize("spec_kw", [
    {},
    dict(accept="always"),
    dict(contiguity="none"),
])
def test_bit_identity_surgical(spec_kw):
    """Tier-1 parity on the small surgical grid (holes + diagonals):
    the auto-dispatched packed body equals the int8 body forced via
    bits=False, field for field."""
    g = surgical_grid()
    _parity(g, fce.graphs.stripes_plan(g, 2), make_spec(**spec_kw))


def test_bit_identity_assignment_bits():
    """record_assignment_bits (n_real <= 32): the per-yield packed
    assignments must match exactly too."""
    g = fce.graphs.square_grid(4, 7, remove_nodes=[(0, 0), (2, 3)],
                               extra_edges=[((0, 1), (1, 0)),
                                            ((2, 4), (3, 5))])
    spec = make_spec(record_assignment_bits=True, parity_metrics=False,
                     geom_waits=False)
    _parity(g, fce.graphs.stripes_plan(g, 2), spec)


@pytest.mark.parametrize("record_interface", [False, True])
@pytest.mark.slow
def test_bit_identity_sec11(record_interface):
    """The paper's corner-surgery grid, both record_interface settings:
    trajectories, cut_times planes, and the keyed min-reduce interface
    metrics all bit-identical."""
    g = fce.graphs.grid_sec11()
    plan = fce.graphs.sec11_plan(g, alignment=0)
    _parity(g, plan, make_spec(record_interface=record_interface),
            chains=4, steps=40)


@pytest.mark.slow
def test_bit_identity_queen():
    g = fce.graphs.square_grid(8, queen=True)
    _parity(g, fce.graphs.stripes_plan(g, 2), make_spec())


@pytest.mark.slow
def test_bit_identity_frankengraph():
    g = fce.graphs.frankengraph()
    _parity(g, fce.graphs.frank_plan(g, alignment=0), make_spec(),
            chains=4, steps=40)


@pytest.mark.slow
def test_bit_identity_full_run_sec11():
    """Multi-chunk run through the runner (scan boundaries, history
    assembly, cut_times accumulation across chunks): edge_cut_times and
    every history row agree."""
    g = fce.graphs.grid_sec11()
    plan = fce.graphs.sec11_plan(g, alignment=0)
    spec = make_spec()
    bg, st, params = fce.sampling.init_board(
        g, plan, n_chains=4, seed=7, spec=spec, base=1.4, pop_tol=0.3)
    res_p = fce.sampling.run_board(bg, spec, params, st, n_steps=121,
                                   chunk=40)
    res_i = fce.sampling.run_board(bg, spec, params, st, n_steps=121,
                                   chunk=40, bits=False)
    for key in res_i.history:
        np.testing.assert_array_equal(
            np.asarray(res_p.history[key]), np.asarray(res_i.history[key]),
            err_msg=key)
    np.testing.assert_array_equal(kb.edge_cut_times(g, res_p.state),
                                  kb.edge_cut_times(g, res_i.state))


# ---------------------------------------------------------------------------
# the CI gate wrapper
# ---------------------------------------------------------------------------

def test_bitpack_check_gate_passes():
    """make bitpack-check: the fast lowered_bits-vs-lowered parity smoke
    as one script, tier-1 so the bit-identity contract gates every
    commit."""
    r = subprocess.run(
        ["bash", os.path.join(REPO, "tools", "bitpack_check.sh")],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "bitpack-check: OK" in r.stdout
