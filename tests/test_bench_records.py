"""Integrity of the committed benchmark-record artifacts.

`bench_runs/` is the evidence directory behind every performance claim
in README/PROFILE (one JSON record per capture, committed the moment it
lands — the round-5 capture discipline). This guards it against silent
rot: every committed `.json` record must be a single parseable JSON
object, throughput records must carry the documented fields with a
self-consistent `vs_baseline`, and anything named `tpu_*`/captured by
the TPU scripts must actually claim TPU silicon — a cpu-fallback record
under an on-chip name is exactly the mixup `tools/bench_lib.sh`
quarantines, and this test makes the quarantine's invariant durable.
"""

import glob
import json
import os
import subprocess

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RUNS = os.path.join(REPO, "bench_runs")


def _committed_records():
    tracked = subprocess.run(
        ["git", "ls-files", "bench_runs"], cwd=REPO,
        capture_output=True, text=True).stdout.split()
    return [os.path.join(REPO, p) for p in tracked
            if p.endswith(".json") and "cpu_scaling" not in p]


def test_committed_bench_records_parse_and_claim_silicon():
    records = _committed_records()
    assert records, "no committed bench_runs records found"
    for path in records:
        with open(path) as f:
            text = f.read().strip()
        assert text, (f"{path}: empty record — a zero-byte capture "
                      "(like the round-4 C16384 OOM artifact) must be "
                      "dropped, not committed")
        rec = json.loads(text)
        name = os.path.basename(path)
        if "check" in rec:
            # pallas exactness/bring-up evidence: either a verdict or a
            # preserved error, never both absent — and a VERDICT must
            # come from silicon (same quarantine invariant as below;
            # error records legitimately predate device claim)
            assert "exact" in rec or "error" in rec, (name, rec)
            if "exact" in rec:
                assert not rec.get("cpu_fallback"), (name, rec)
                assert "TPU" in rec.get("device", ""), (name, rec)
            continue
        assert rec.get("metric", "").startswith(
            "flips_per_sec_per_chip"), (name, rec)
        assert rec["unit"] == "flips/s", (name, rec)
        assert rec["value"] > 0, (name, rec)
        assert not rec.get("cpu_fallback"), (
            f"{name}: cpu-fallback output under a committed on-chip "
            "record name (bench_lib.sh quarantine invariant)")
        assert "TPU" in rec["device"], (name, rec["device"])
        # bench.py derives vs_baseline from the UNROUNDED fps while
        # value is rounded to 0.1, so recomputing from value can land
        # one 1e-4 grid point away near a boundary: allow the grid
        assert abs(rec["vs_baseline"] - rec["value"] / 1.25e6) < 1e-4, (
            name, rec)
