"""The TPU-capture helpers (tools/bench_lib.sh) hold the round's
benchmark-record integrity: every failure shape must be quarantined by
rename so record globs and the tail watchdog's completion gates only
ever see real committed records, and capture commits must be
pathspec'd so they never sweep up unrelated staged work. Driven here
against a stubbed bench.py in a throwaway git repo — exactly the
scenario matrix the round-5 reviews demanded (failed / cpu_fallback /
below-floor / commit-race / success)."""

import os
import shutil
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

STUB = """\
import json, os, sys
mode = os.environ.get("STUB", "ok")
if mode == "fail":
    sys.exit(1)
if mode == "fallback":
    print(json.dumps({"value": 1, "vs_baseline": None,
                      "cpu_fallback": True})); sys.exit(0)
if mode == "low":
    print(json.dumps({"value": 1, "vs_baseline": 0.72})); sys.exit(0)
print(json.dumps({"value": 1, "vs_baseline": 16.3}))
"""


@pytest.fixture()
def sandbox(tmp_path):
    for tool in ("git", "bash", "timeout", "python"):
        if shutil.which(tool) is None:
            pytest.skip(f"{tool} unavailable (bench_lib.sh hardcodes it)")
    (tmp_path / "bench_runs").mkdir()
    (tmp_path / "bench.py").write_text(STUB)
    shutil.copy(os.path.join(REPO, "tools", "bench_lib.sh"),
                tmp_path / "bench_lib.sh")
    run = lambda *cmd: subprocess.run(cmd, cwd=tmp_path, check=True,
                                      capture_output=True)
    run("git", "init", "-q", ".")
    run("git", "config", "user.email", "t@t")
    run("git", "config", "user.name", "t")
    (tmp_path / "README").write_text("x")
    run("git", "add", "README")
    run("git", "commit", "-q", "-m", "init")
    return tmp_path


def drive(sandbox, stub_mode, cmd):
    """Source bench_lib.sh and run one helper invocation under the stub."""
    env = dict(os.environ, STUB=stub_mode, TS="TEST")
    return subprocess.run(
        ["bash", "-c", f". ./bench_lib.sh; {cmd}"],
        cwd=sandbox, env=env, capture_output=True, text=True)


def bench_files(sandbox):
    return sorted(os.listdir(sandbox / "bench_runs"))


@pytest.mark.slow
def test_failure_shapes_are_quarantined(sandbox):
    """Three full run_bench round-trips (~14s: each sources the helper,
    spawns the stub under `timeout`, and drives a git quarantine rename)
    — over the fast tier's 12s per-test budget, so slow tier."""
    r = drive(sandbox, "fail", "run_bench t1 60")
    assert r.returncode == 1
    assert "TEST_t1.json.failed" in bench_files(sandbox)
    assert "TEST_t1.json" not in bench_files(sandbox)

    r = drive(sandbox, "fallback", "run_bench t2 60")
    assert r.returncode == 1
    assert "TEST_t2.json.fallback" in bench_files(sandbox)

    r = drive(sandbox, "low", "run_bench_min 2.0 t3 60")
    assert r.returncode == 1
    assert "TEST_t3.json.suspect" in bench_files(sandbox)

    # no quarantined shape satisfies a record glob
    import glob
    assert glob.glob(str(sandbox / "bench_runs" / "*_t1.json")) == []


def test_success_commits_only_the_record(sandbox):
    # unrelated staged work must survive a capture commit untouched
    (sandbox / "unrelated.txt").write_text("wip")
    subprocess.run(["git", "add", "unrelated.txt"], cwd=sandbox, check=True)
    r = drive(sandbox, "ok", "run_bench_min 2.0 t4 60")
    assert r.returncode == 0, r.stderr
    assert "TEST_t4.json" in bench_files(sandbox)
    show = subprocess.run(
        ["git", "show", "--stat", "--format=%s", "HEAD"],
        cwd=sandbox, capture_output=True, text=True).stdout
    # the commit SUBJECT also contains the basename, so assert the
    # stat PATH — a pathspec regression must not hide behind it
    assert "bench_runs/TEST_t4.json" in show
    assert "unrelated" not in show
    status = subprocess.run(["git", "status", "--short"], cwd=sandbox,
                            capture_output=True, text=True).stdout
    assert "A  unrelated.txt" in status


def test_floor_only_applies_when_set(sandbox):
    r = drive(sandbox, "low", "run_bench t5 60")
    assert r.returncode == 0, r.stderr   # bare run_bench has no floor
    assert "TEST_t5.json" in bench_files(sandbox)


def test_commit_race_quarantines_uncommitted(sandbox):
    """commit_retry exhaustion (here: a held index.lock) must rename
    the valid record to *.uncommitted so the watchdog gates retry it
    next window instead of counting an uncommitted file as done."""
    (sandbox / ".git" / "index.lock").write_text("")
    # shim sleep so the 5 retry backoffs are instant
    r = drive(sandbox, "ok", "sleep(){ :; }; run_bench t6 60")
    assert r.returncode == 1
    assert "TEST_t6.json.uncommitted" in bench_files(sandbox)
    assert "TEST_t6.json" not in bench_files(sandbox)


def test_pick_health_record_quarantine_shapes(sandbox):
    """The tail watchdog's window-health selection: a committed record
    wins, the .uncommitted quarantine is an acceptable stand-in (a lost
    commit race is still a true reading), and the .failed/.fallback
    shapes yield NOTHING — the caller must classify the window unhealthy
    explicitly, not via vsb_at_least's missing-file fallthrough."""
    runs = sandbox / "bench_runs"
    base = "bench_runs/h.json"

    r = drive(sandbox, "ok", f"pick_health_record {base}")
    assert r.returncode == 1 and r.stdout == ""

    (runs / "h.json.failed").write_text('{"value": 0}')
    (runs / "h.json.fallback").write_text(
        '{"value": 1, "cpu_fallback": true}')
    (runs / "h.json.suspect").write_text('{"vs_baseline": 0.7}')
    r = drive(sandbox, "ok", f"pick_health_record {base}")
    assert r.returncode == 1 and r.stdout == ""

    (runs / "h.json.uncommitted").write_text('{"vs_baseline": 16.3}')
    r = drive(sandbox, "ok", f"pick_health_record {base}")
    assert r.returncode == 0
    assert r.stdout.strip() == f"{base}.uncommitted"

    (runs / "h.json").write_text('{"vs_baseline": 16.3}')
    r = drive(sandbox, "ok", f"pick_health_record {base}")
    assert r.returncode == 0
    assert r.stdout.strip() == base


def test_vsb_at_least_gate(sandbox):
    f = sandbox / "bench_runs" / "x.json"
    for content, floor, expect in (
            ('{"vs_baseline": 16.4}', "15", 0),
            ('{"vs_baseline": 13.9}', "15", 1),
            ('{"vs_baseline": null}', "1", 1),
            ("", "1", 1)):
        f.write_text(content)
        r = drive(sandbox, "ok", f"vsb_at_least bench_runs/x.json {floor}")
        assert r.returncode == expect, (content, floor, r.returncode)


# --------------------------------------------------------------------
# tools/bench_compare.py: diffing two bench records

import json
import sys

BENCH_COMPARE = os.path.join(REPO, "tools", "bench_compare.py")


def _bench_record(path, value, seconds=2.0, metric="flips_per_sec_total"):
    """Write a BENCH_r*-shaped record: a parsed block plus a captured
    tail holding a metric line and a config line."""
    tail = (json.dumps({"metric": metric, "value": value,
                        "unit": "flips/s"}) + "\n"
            + "some non-json log line\n"
            + json.dumps({"path": "board", "body": "bitboard", "grid": 64,
                          "chains": 8, "steps": 101,
                          "seconds": seconds}) + "\n")
    doc = {"n": 1, "rc": 0, "tail": tail,
           "parsed": {"metric": metric + "_parsed", "value": value}}
    path.write_text(json.dumps(doc))
    return path


def _compare(a, b, *extra):
    return subprocess.run(
        [sys.executable, BENCH_COMPARE, str(a), str(b), *extra],
        capture_output=True, text=True)


def test_bench_compare_improvement_passes(tmp_path):
    a = _bench_record(tmp_path / "a.json", 1000.0)
    b = _bench_record(tmp_path / "b.json", 1100.0, seconds=1.8)
    r = _compare(a, b)
    assert r.returncode == 0, r.stderr
    assert "flips_per_sec_total" in r.stdout
    # the derived per-config throughput is in the table too
    assert "config[" in r.stdout and ".flips_per_s" in r.stdout
    assert "REGRESSED" not in r.stdout


def test_bench_compare_regression_gates(tmp_path):
    a = _bench_record(tmp_path / "a.json", 1000.0)
    b = _bench_record(tmp_path / "b.json", 800.0)  # -20%
    r = _compare(a, b)
    assert r.returncode == 1
    assert "REGRESSED" in r.stdout
    assert "flips_per_sec_total" in r.stderr
    # a loose enough tolerance lets the same pair through
    r = _compare(a, b, "--tolerance", "0.25")
    assert r.returncode == 0, r.stderr
    assert "REGRESSED" not in r.stdout


def test_bench_compare_disjoint_metrics_warns(tmp_path):
    a = _bench_record(tmp_path / "a.json", 1000.0, metric="m_old")
    b = tmp_path / "b.json"
    b.write_text(json.dumps({"parsed": {"metric": "m_new", "value": 1.0}}))
    r = _compare(a, b)
    assert r.returncode == 0
    assert "nothing to gate on" in r.stderr


def _device_record(path, value, **extra):
    doc = {"parsed": {"metric": "flips_per_sec_total", "value": value},
           **extra}
    path.write_text(json.dumps(doc))
    return path


def test_bench_compare_refuses_cross_device_gate(tmp_path):
    """A TPU record vs a CPU-fallback record: the -92% 'regression' is a
    setup difference, so the tolerance gate is refused (exit 0) with an
    explicit incomparable-devices note; the delta table still prints."""
    a = _device_record(tmp_path / "a.json", 1000.0, device="tpu-v4")
    b = _device_record(tmp_path / "b.json", 80.0, device="cpu",
                       cpu_fallback=True)
    r = _compare(a, b)
    assert r.returncode == 0, r.stderr
    assert "incomparable devices" in r.stderr
    assert "flips_per_sec_total" in r.stdout  # table still rendered
    # same tags on both sides: the gate applies again
    a2 = _device_record(tmp_path / "a2.json", 1000.0, device="tpu-v4")
    b2 = _device_record(tmp_path / "b2.json", 80.0, device="tpu-v4")
    r = _compare(a2, b2)
    assert r.returncode == 1
    assert "incomparable" not in r.stderr


def _mesh_record(path, per_chip, devices, *, cpu_fallback=False,
                 silicon="TFRT_CPU_0", rung1=52_000.0):
    """A bench --mesh headline: aggregate + per-chip throughput, a
    device tag carrying the device count, and a scaling ladder (whose
    single-device rung is steady by default — only the full-mesh
    per-chip figure varies across records)."""
    doc = {"metric": "flips_per_sec_multichip_32x32",
           "value": per_chip * devices, "unit": "flips/s",
           "device": f"{silicon} x{devices}", "devices": devices,
           "flips_per_s_per_chip": per_chip,
           "cpu_fallback": cpu_fallback,
           "scaling": [
               {"devices": 1, "flips_per_s": rung1,
                "flips_per_s_per_chip": rung1},
               {"devices": devices, "flips_per_s": per_chip * devices,
                "flips_per_s_per_chip": per_chip},
           ]}
    path.write_text(json.dumps(doc))
    return path


def test_bench_compare_gates_per_chip_across_device_counts(tmp_path):
    """Mesh records of the SAME silicon at different device counts are
    comparable per chip: aggregate flips/s legitimately scales with the
    count (no flag), but a per-chip drop past tolerance gates."""
    a = _mesh_record(tmp_path / "a.json", 50_000.0, 2)
    b = _mesh_record(tmp_path / "b.json", 30_000.0, 8)  # -40% per chip
    r = _compare(a, b)
    assert r.returncode == 1, r.stderr
    assert "silicon matches" in r.stderr
    assert "per-chip" in r.stderr
    # aggregate moved +140% and the matching devices=1 rung is steady:
    # neither may be what flags
    assert "flips_per_sec_multichip_32x32.per_chip" in r.stderr
    assert "mesh[devices=1]" not in r.stderr

    # a healthy per-chip figure passes despite the differing counts
    b2 = _mesh_record(tmp_path / "b2.json", 49_500.0, 8)
    r = _compare(a, b2)
    assert r.returncode == 0, r.stderr
    assert "silicon matches" in r.stderr
    assert "REGRESSED" not in r.stdout


def test_bench_compare_mesh_scaling_rows_extracted(tmp_path):
    """The scaling ladder contributes per-rung metrics: rungs present in
    both records (devices=1 here) land in the delta table by name."""
    a = _mesh_record(tmp_path / "a.json", 50_000.0, 2)
    b = _mesh_record(tmp_path / "b.json", 48_000.0, 8)
    r = _compare(a, b)
    assert "mesh[devices=1].flips_per_s_per_chip" in r.stdout
    assert "mesh[devices=2].flips_per_s" in r.stdout  # only-in-A row


def test_bench_compare_mesh_still_refuses_fallback_mismatch(tmp_path):
    """Same silicon string but only one side fell back to CPU: that is a
    setup difference, not a per-chip regression — refusal stands."""
    a = _mesh_record(tmp_path / "a.json", 50_000.0, 2)
    b = _mesh_record(tmp_path / "b.json", 30_000.0, 8, cpu_fallback=True)
    r = _compare(a, b)
    assert r.returncode == 0, r.stderr
    assert "incomparable devices" in r.stderr


def test_bench_compare_general_paths_never_cross_gate(tmp_path):
    """Two records whose bench config lines differ ONLY in kernel_path
    (general_dense vs legacy general) must not gate against each other:
    the dense body is distribution-equivalent but a different kernel, so
    its 3x throughput must never read as a legacy-path 'regression' (or
    vice versa). _config_name keys on kernel_path — pin that here."""
    def rec(path, kernel_path, seconds):
        cfg = {"path": "general", "kernel_path": kernel_path,
               "graph": "hex", "grid": 32, "k": 2, "chains": 256,
               "steps": 201, "seconds": seconds, "device": "TFRT_CPU_0"}
        # a shared, unchanged metric keeps the gate armed: the refusal
        # we are pinning is per-key, not a record-level incomparability
        tail = (json.dumps({"metric": "flips_per_sec_total",
                            "value": 1000.0, "unit": "flips/s",
                            "device": "TFRT_CPU_0"}) + "\n"
                + json.dumps(cfg) + "\n")
        path.write_text(json.dumps({"n": 1, "rc": 0, "tail": tail}))
        return path

    a = rec(tmp_path / "a.json", "general_dense", seconds=0.8)
    b = rec(tmp_path / "b.json", "general", seconds=2.4)  # 3x slower body
    r = _compare(a, b)
    assert r.returncode == 0, r.stderr
    assert "REGRESSED" not in r.stdout
    # the two config throughputs landed under distinct keys, one per side
    assert "kernel_path=general_dense" in r.stdout
    assert "kernel_path=general," in r.stdout
    assert "only in A" in r.stdout and "only in B" in r.stdout
