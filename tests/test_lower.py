"""Stencil-lowering subsystem verification (flipcomplexityempirical_tpu/lower).

Five layers:

1. Lowering shapes: ``lower_to_stencil`` embeds the paper's two surgical
   graphs (grid_sec11, frankengraph) and queen grids exactly — canvas
   dims, hole masks, per-direction adjacency planes, edge mapping — and
   refuses what it cannot embed (tiny canvases, non-king edges).
2. Dispatch: ``kernel_path_for`` routes each workload to the body the
   runners actually select (lowered / board / general).
3. Local equivalence of the lowered primitives against the general
   kernel's: the offset-keyed B2 bitset propagation vs
   ``contiguity.patch_connected`` per node on random boards, and the
   keyed min-reduce interface metrics vs ``step.interface_metrics``
   bit-for-bit on sec11.
4. Exact per-run invariants of the lowered body (cut recount, district
   populations, hole cells, edge_cut_times tie-out) plus the checkpoint
   field-mismatch guard.
5. Distributional parity (slow): lowered vs general trajectories on the
   real sec11/frank workloads, and — the exact-enumeration bar — the
   lowered path vs the power-iterated stationary distribution of the
   literal transition matrix on a small surgically-modified grid, with
   a chi-square occupancy gate and the compat/ oracle as referee.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import flipcomplexityempirical_tpu as fce
from flipcomplexityempirical_tpu import compat, lower
from flipcomplexityempirical_tpu.kernel import board as kb
from flipcomplexityempirical_tpu.kernel import contiguity
from flipcomplexityempirical_tpu.kernel import step as kstep

from test_parity import ks_stat


def surgical_grid(h=5, w=7):
    """A small grid with the full surgery menu: two holes (one corner,
    one interior) and two diagonal bypass edges."""
    return fce.graphs.square_grid(
        h, w, remove_nodes=[(0, 0), (2, 3)],
        extra_edges=[((0, 1), (1, 0)), ((3, 4), (4, 5))])


# ---------------------------------------------------------------------------
# 1. lowering shapes
# ---------------------------------------------------------------------------

def _check_embedding(g, st):
    """Structural consistency of a StencilSpec against its graph."""
    cell = np.asarray(st.cell_of_node)
    mask = np.asarray(st.node_mask)
    assert st.n == st.h * st.w
    assert st.n_real == g.n_nodes
    assert mask.sum() == g.n_nodes
    assert np.unique(cell).size == g.n_nodes and mask[cell].all()
    # holes carry zero degree/pop; real cells carry the graph's
    deg = np.zeros(g.n_nodes, np.int64)
    np.add.at(deg, g.edges.ravel(), 1)
    assert (np.asarray(st.deg)[cell] == deg).all()
    assert (np.asarray(st.deg)[~mask] == 0).all()
    assert (np.asarray(st.pop)[~mask] == 0).all()
    # every edge appears in exactly one forward plane at its smaller
    # endpoint's cell, and the adjacency planes are symmetric: the total
    # plane population double-counts each edge once per endpoint
    assert np.asarray(st.adj).sum() == 2 * len(g.edges)
    assert len(st.edge_plane) == len(g.edges)
    assert ((np.asarray(st.edge_plane) >= 0)
            & (np.asarray(st.edge_plane) < 4)).all()
    adj = np.asarray(st.adj)
    assert adj[np.asarray(st.edge_plane), np.asarray(st.edge_cell)].all()


def test_lower_sec11():
    g = fce.graphs.grid_sec11()
    st = lower.lower_to_stencil(g)
    assert st is not None
    assert (st.h, st.w) == (40, 40)
    assert st.n_real == 1596 and st.surgical and not st.plain
    assert st.patch_exact and st.iface_ok
    _check_embedding(g, st)
    # the 4 corner cells plus nothing else are holes
    assert (~np.asarray(st.node_mask)).sum() == 4


def test_lower_frankengraph():
    g = fce.graphs.frankengraph()
    st = lower.lower_to_stencil(g)
    assert st is not None
    assert st.n_real == 800 and st.surgical
    assert st.h * st.w == 800          # seam canvas has no holes
    assert st.patch_exact and st.iface_ok
    _check_embedding(g, st)


def test_lower_queen_and_plain():
    q = fce.graphs.square_grid(6, queen=True)
    st = lower.lower_to_stencil(q)
    assert st is not None and st.surgical and st.patch_exact
    _check_embedding(q, st)

    p = fce.graphs.square_grid(6, 6)
    st = lower.lower_to_stencil(p)
    assert st is not None and st.plain and not st.surgical
    _check_embedding(p, st)


def test_queen_builder_counts():
    """Satellite: the queen option of square_grid — n^2 nodes,
    2n(n-1) rook + 2(n-1)^2 diagonal edges (the reference's commented
    queen block, grid_chain_sec11.py:241-249)."""
    for n in (3, 6, 8):
        g = fce.graphs.square_grid(n, queen=True)
        assert g.name == f"queen{n}x{n}"
        assert g.n_nodes == n * n
        assert len(g.edges) == 2 * n * (n - 1) + 2 * (n - 1) ** 2
    # rook default unchanged
    g = fce.graphs.square_grid(4, 5)
    assert g.name == "grid4x5" and len(g.edges) == 4 * 4 + 3 * 5


def test_lower_rejections():
    # canvases thinner than the ring's aliasing bound
    assert lower.lower_to_stencil(fce.graphs.square_grid(2, 5)) is None
    # a non-king extra edge cannot be a stencil plane
    far = fce.graphs.square_grid(5, 5, extra_edges=[((0, 0), (0, 4))])
    assert lower.lower_to_stencil(far) is None
    # hex lowers structurally but its radius-3 patch tables don't match
    # the radius-2 B2 windows => never patch_exact
    st = lower.lower_to_stencil(fce.graphs.hex_lattice(4, 4))
    assert st is None or not st.patch_exact


# ---------------------------------------------------------------------------
# 2. dispatch
# ---------------------------------------------------------------------------

def test_kernel_path_routing():
    spec = fce.Spec(contiguity="patch")
    assert lower.kernel_path_for(fce.graphs.grid_sec11(),
                                 spec) == "lowered_bits"
    assert lower.kernel_path_for(fce.graphs.frankengraph(),
                                 spec) == "lowered_bits"
    assert lower.kernel_path_for(
        fce.graphs.square_grid(6, queen=True), spec) == "lowered_bits"
    assert lower.kernel_path_for(fce.graphs.square_grid(6, 6), spec) == "board"
    # hex rejects lowering (radius-3 patches) and lands on the
    # rejection-free dense rung (ISSUE 15), not the legacy kernel
    assert lower.kernel_path_for(fce.graphs.hex_lattice(4, 4),
                                 spec) == "general_dense"
    # a w=4 canvas realizes one flat B2 offset by two distinct (dr, dc)
    # pairs => b2_disp is None and the packed body stands down to the
    # int8 lowered body (bitboard.supported_lowered)
    g4 = fce.graphs.square_grid(3, 4, remove_nodes=[(0, 0)],
                                extra_edges=[((0, 1), (1, 0))])
    assert lower.kernel_path_for(g4, spec) == "lowered"
    # record_interface: lowered where wall planes encode, the general
    # family (dense rung first — interface recording lives in the
    # shared commit tail) where the graph has no walls at all
    ispec = fce.Spec(record_interface=True)
    assert lower.kernel_path_for(fce.graphs.grid_sec11(),
                                 ispec) == "lowered_bits"
    assert lower.kernel_path_for(fce.graphs.square_grid(6, 6),
                                 ispec) == "general_dense"
    # dispatch agrees with the body the runner will build
    for g in (fce.graphs.grid_sec11(), fce.graphs.square_grid(6, 6)):
        bg = kb.make_board_graph(g)
        assert lower.kernel_path_for(g, spec) == kb.body_for(bg, spec)


# ---------------------------------------------------------------------------
# 3. lowered primitives == general primitives
# ---------------------------------------------------------------------------

def test_b2_contiguity_matches_patch_connected(rng):
    """The offset-keyed bitset propagation is patch_connected, exactly:
    every node of every random board agrees, on a grid with holes and
    diagonals and on a queen grid."""
    for g, trials in ((surgical_grid(), 12),
                      (fce.graphs.square_grid(6, queen=True), 8)):
        bg = kb.make_board_graph(g)
        dg = g.device()
        cell = np.asarray(bg.cell_of_node)
        pc = jax.vmap(contiguity.patch_connected, in_axes=(None, None, 0, 0))
        vs = jnp.arange(g.n_nodes)
        for _ in range(trials):
            a = rng.integers(0, 2, g.n_nodes).astype(np.int8)
            board = np.full(bg.n, -1, np.int8)
            board[cell] = a
            ok = np.asarray(kb._stencil_patch_ok(bg, jnp.asarray(board[None])))
            av = jnp.asarray(a)
            ref = np.asarray(pc(dg, av, vs, av[vs].astype(jnp.int32)))
            np.testing.assert_array_equal(ok[0, cell], ref)


def test_interface_planes_match_general(rng):
    """Keyed min-reduce slope/angle == step.interface_metrics bit-for-bit
    on sec11 (same two smallest-index wall-cut edges, same f32 math)."""
    g = fce.graphs.grid_sec11()
    bg = kb.make_board_graph(g)
    dg = g.device()
    cell = np.asarray(bg.cell_of_node)
    for _ in range(6):
        a = rng.integers(0, 2, g.n_nodes).astype(np.int8)
        board = np.full(bg.n, -1, np.int8)
        board[cell] = a
        bj = jnp.asarray(board[None])
        same = kb._same_planes_stencil(bg, bj)
        cuts = [bg.adj[d][None] & ~same[d] for d in range(4)]
        slope_l, angle_l = kb._interface_stencil(bg, cuts)
        cut_e = (a[g.edges[:, 0]] != a[g.edges[:, 1]]).astype(np.int32)
        slope_g, angle_g = kstep.interface_metrics(dg, jnp.asarray(cut_e))
        for lo, go in ((slope_l[0], slope_g), (angle_l[0], angle_g)):
            lo, go = float(lo), float(go)
            assert (np.isnan(lo) and np.isnan(go)) or lo == go, (lo, go)


# ---------------------------------------------------------------------------
# 4. lowered-body run invariants + checkpoint guard
# ---------------------------------------------------------------------------

def test_lowered_run_invariants():
    g = surgical_grid()
    spec = fce.Spec(n_districts=2, proposal="bi", contiguity="patch",
                    invalid="repropose", accept="cut",
                    parity_metrics=True, geom_waits=True)
    assert kb.supports(g, spec)
    plan = fce.graphs.stripes_plan(g, 2)
    bg, st, params = fce.sampling.init_board(
        g, plan, n_chains=8, seed=9, spec=spec, base=1.3, pop_tol=0.3)
    assert kb.body_for(bg, spec) == "lowered_bits"
    res = fce.sampling.run_board(bg, spec, params, st, n_steps=301, chunk=100)
    s = res.host_state()
    board = np.asarray(s.board)

    # hole cells never change district
    mask = np.asarray(bg.node_mask)
    assert (board[:, ~mask] == -1).all()

    # derived fields are pure functions of the board
    cut = np.asarray(kb.recount_cuts(bg, jnp.asarray(board)))
    np.testing.assert_array_equal(np.asarray(s.cut_count), cut)
    a = kb.node_view(bg, board)
    pop0 = (a == 0).sum(axis=1)
    np.testing.assert_array_equal(np.asarray(s.dist_pop)[:, 0], pop0)
    np.testing.assert_array_equal(np.asarray(s.dist_pop)[:, 1],
                                  g.n_nodes - pop0)

    # both districts stay connected under the graph's real adjacency
    import networkx as nx
    gx = nx.Graph(list(map(tuple, g.edges)))
    for row in a:
        for d in (0, 1):
            sub = gx.subgraph(np.nonzero(row == d)[0].tolist())
            assert sub.number_of_nodes() and nx.is_connected(sub)

    # diagonal cut_times planes exist and the per-edge accumulators tie
    # out against the recorded per-yield cut counts
    assert s.cut_times_se is not None and s.cut_times_sw is not None
    ct = kb.edge_cut_times(g, res.state)
    assert ct.shape == (8, len(g.edges))
    np.testing.assert_array_equal(ct.sum(axis=1),
                                  res.history["cut_count"].sum(axis=1))


def test_checkpoint_field_mismatch_restarts():
    """A checkpoint written by a different kernel path (missing state
    fields the current path carries) must raise CheckpointIdentityError
    out of _state_from_arrays — the _load_resume guard that refuses to
    silently mix two walks (the supervisor classifies it deterministic,
    and only the kernel-degradation rerun downgrades it to a fresh
    start)."""
    from flipcomplexityempirical_tpu.experiments.driver import \
        _state_from_arrays
    from flipcomplexityempirical_tpu.resilience.errors import \
        CheckpointIdentityError

    g = surgical_grid()
    spec = fce.Spec(contiguity="patch")
    plan = fce.graphs.stripes_plan(g, 2)
    _, st, _ = fce.sampling.init_board(
        g, plan, n_chains=2, seed=0, spec=spec, base=1.2, pop_tol=0.3)
    full = {f"state_{f}": np.asarray(v)
            for f in st.__dataclass_fields__
            if (v := getattr(st, f)) is not None}

    # round-trip: every field restored, None fields stay None
    back = _state_from_arrays(st, full)
    for f in st.__dataclass_fields__:
        v = getattr(st, f)
        if v is None:
            assert getattr(back, f) is None
        else:
            np.testing.assert_array_equal(np.asarray(getattr(back, f)),
                                          np.asarray(v))

    # drop a field the lowered path requires => loud identity refusal
    partial = {k: v for k, v in full.items() if k != "state_cut_times_se"}
    with pytest.raises(CheckpointIdentityError) as ei:
        _state_from_arrays(st, partial)
    assert "cut_times_se" in str(ei.value)


# ---------------------------------------------------------------------------
# 5. distributional parity (slow)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("graph", ["sec11", "frank"])
@pytest.mark.slow
def test_lowered_matches_general_trajectory(graph):
    """The paper's two workloads, lowered vs general (independent RNG
    streams): same cut/b trajectory distributions, accept rates, and
    cut-edge heat profiles — the sec11/frank analogue of
    test_board_matches_general_path."""
    if graph == "sec11":
        g = fce.graphs.grid_sec11()
        plan = fce.graphs.sec11_plan(g, alignment=0)
    else:
        g = fce.graphs.frankengraph()
        plan = fce.graphs.frank_plan(g, alignment=0)
    chains, steps, burn = 24, 4001, 800
    base, tol = 1.4, 0.3
    spec = fce.Spec(n_districts=2, proposal="bi", contiguity="patch",
                    invalid="repropose", accept="cut",
                    parity_metrics=True, geom_waits=True)

    dg, st_g, par_g = fce.init_batch(g, plan, n_chains=chains, seed=11,
                                     spec=spec, base=base, pop_tol=tol)
    res_g = fce.run_chains(dg, spec, par_g, st_g, n_steps=steps)

    bg, st_b, par_b = fce.sampling.init_board(
        g, plan, n_chains=chains, seed=17, spec=spec, base=base, pop_tol=tol)
    assert kb.body_for(bg, spec) == "lowered_bits"
    res_b = fce.sampling.run_board(bg, spec, par_b, st_b, n_steps=steps)

    sub = slice(burn, None, 20)
    for key, tol_ks in (("cut_count", 0.08), ("b_count", 0.08)):
        a = res_g.history[key][:, sub]
        b = res_b.history[key][:, sub]
        ks = ks_stat(a.ravel(), b.ravel())
        assert ks < tol_ks, f"{graph} {key} KS {ks:.4f}"
        # means via a between-chain z-test: per-chain means are the
        # independent unit (within-chain samples are heavily
        # autocorrelated at this run length, so a fixed relative
        # tolerance would mis-calibrate across graphs)
        ma, mb = a.mean(axis=1), b.mean(axis=1)
        se = np.sqrt(ma.var(ddof=1) / chains + mb.var(ddof=1) / chains)
        z = abs(ma.mean() - mb.mean()) / se
        assert z < 4.0, (f"{graph} {key} means {ma.mean():.2f} vs "
                         f"{mb.mean():.2f} (z={z:.2f})")

    aa = np.asarray(res_g.state.accept_count).mean()
    ab = np.asarray(res_b.state.accept_count).mean()
    assert abs(aa - ab) / aa < 0.06, f"accepts {aa:.1f} vs {ab:.1f}"

    ct_g = np.asarray(res_g.state.cut_times).mean(axis=0)
    ct_b = kb.edge_cut_times(g, res_b.state).mean(axis=0)
    corr = np.corrcoef(ct_g, ct_b)[0, 1]
    assert corr > 0.95, f"cut_times profile corr {corr:.3f}"


# --- exact enumeration on a surgically-modified grid -----------------------

CHI_EPS = 0.5


def _nbr_bitmasks(g):
    nbrmask = [0] * g.n_nodes
    for u, v in g.edges:
        nbrmask[u] |= 1 << int(v)
        nbrmask[v] |= 1 << int(u)
    return nbrmask


def _connected(mask, nbrmask):
    if mask == 0:
        return False
    reach = mask & (-mask)
    while True:
        grow, m = reach, reach
        while m:
            b = m & (-m)
            grow |= nbrmask[b.bit_length() - 1]
            m ^= b
        grow &= mask
        if grow == reach:
            return reach == mask
        reach = grow


def _enumerate_states(g, nbrmask):
    n = g.n_nodes
    full = (1 << n) - 1
    ideal = n / 2
    lo, hi = (1 - CHI_EPS) * ideal, (1 + CHI_EPS) * ideal
    states = []
    for m in range(1, full):
        p1 = bin(m).count("1")
        if not (lo <= p1 <= hi and lo <= n - p1 <= hi):
            continue
        if _connected(m, nbrmask) and _connected(full ^ m, nbrmask):
            states.append(m)
    return states


def _build_transition(states, g, base):
    """Row-stochastic matrix of the re-propose chain with literal accept,
    over the graph's OWN edge list (test_enumeration's build_transition
    is rook-grid-specific; this one takes any LatticeGraph)."""
    n = g.n_nodes
    index = {m: i for i, m in enumerate(states)}
    edges = g.edges

    def cut_of(m):
        a = np.array([(m >> i) & 1 for i in range(n)])
        return int((a[edges[:, 0]] != a[edges[:, 1]]).sum())

    cuts = np.array([cut_of(m) for m in states])
    P = np.zeros((len(states), len(states)))
    for i, m in enumerate(states):
        a = np.array([(m >> v) & 1 for v in range(n)])
        cut = a[edges[:, 0]] != a[edges[:, 1]]
        bnodes = np.unique(edges[cut].ravel())
        moves = [index[m ^ (1 << int(v))] for v in bnodes
                 if (m ^ (1 << int(v))) in index]
        V = len(moves)
        assert V > 0
        stay = 0.0
        for j in moves:
            acc = min(1.0, base ** (cuts[i] - cuts[j]))
            P[i, j] += acc / V
            stay += (1 - acc) / V
        P[i, i] += stay
    assert np.allclose(P.sum(axis=1), 1.0)
    return P, cuts


def _stationary(P):
    pi = np.full(P.shape[0], 1.0 / P.shape[0])
    for _ in range(20000):
        nxt = pi @ P
        if np.abs(nxt - pi).max() < 1e-13:
            break
        pi = nxt
    return pi / pi.sum()


def _occupancy_checks(masks, states, pi, cuts, label, tv_tol=0.06,
                      cut_tol=0.02, chi2_tol=None):
    """TV + E[cut] (the repo's standard gates) plus, when requested, a
    chi-square occupancy statistic over thinned samples."""
    index = {m: i for i, m in enumerate(states)}
    idx = np.array([index[int(m)] for m in masks])   # KeyError => invalid
    emp = np.bincount(idx, minlength=len(states)).astype(float)
    tot = emp.sum()
    tv = 0.5 * np.abs(emp / tot - pi).sum()
    assert tv < tv_tol, f"{label}: TV {tv:.4f} (|S|={len(states)})"
    e_exact = float((pi * cuts).sum())
    e_emp = float((emp / tot * cuts).sum())
    assert abs(e_emp - e_exact) / e_exact < cut_tol, \
        f"{label}: E[cut] {e_emp:.3f} vs {e_exact:.3f}"
    if chi2_tol is not None:
        exp = pi * tot
        chi2 = float((((emp - exp) ** 2) / exp).sum())
        dof = len(states) - 1
        assert chi2 < chi2_tol * dof, \
            f"{label}: chi2/dof {chi2 / dof:.2f} (dof={dof})"


@pytest.mark.slow
def test_lowered_matches_exact_stationary_chi2():
    """Satellite: the exact-enumeration bar for the SURGICAL fast path.
    A 3x4 grid with one corner removed and one diagonal bypass edge (the
    sec11 surgery in miniature) routes through the lowered body; its
    empirical occupancy must match the power-iterated stationary
    distribution of the literal transition matrix — chi-square over
    thinned samples plus the TV/E[cut] gates — and agree with the
    general kernel and the compat/ (gerrychain-semantics) oracle."""
    base = 1.5
    g = fce.graphs.square_grid(3, 4, remove_nodes=[(0, 0)],
                               extra_edges=[((0, 1), (1, 0))])
    assert g.n_nodes == 11 and len(g.edges) == 16
    nbrmask = _nbr_bitmasks(g)
    states = _enumerate_states(g, nbrmask)
    P, cuts = _build_transition(states, g, base)
    pi = _stationary(P)

    spec = fce.Spec(contiguity="patch", record_assignment_bits=True,
                    geom_waits=False, parity_metrics=False)
    # w=4: b2_disp is ambiguous, so this stays on the int8 lowered body
    # (the packed rerun is test_lowered_bits_matches_exact_stationary_chi2)
    assert lower.kernel_path_for(g, spec) == "lowered"
    plan = fce.graphs.stripes_plan(g, 2)
    chains, steps, burn, stride = 48, 12000, 2000, 25

    # lowered board path: decode the node-rank abits packing (bit p of a
    # record is the node at canvas-cell rank p)
    bg, st, params = fce.sampling.init_board(
        g, plan, n_chains=chains, seed=13, spec=spec, base=base,
        pop_tol=CHI_EPS)
    assert kb.body_for(bg, spec) == "lowered"
    res_b = fce.sampling.run_board(bg, spec, params, st, n_steps=steps)
    rank = np.cumsum(np.asarray(bg.node_mask)) - 1
    rank_of_node = rank[np.asarray(bg.cell_of_node)]
    abits = np.asarray(res_b.history["abits"][:, burn::stride])
    per_node = (abits[..., None] >> rank_of_node) & 1
    masks_b = (per_node << np.arange(g.n_nodes)).sum(axis=-1).ravel()
    _occupancy_checks(masks_b, states, pi, cuts, "lowered", chi2_tol=2.0)

    # general kernel, node-index packing
    dg, st_g, par_g = fce.init_batch(g, plan, n_chains=chains, seed=29,
                                     spec=spec, base=base, pop_tol=CHI_EPS)
    res_g = fce.run_chains(dg, spec, par_g, st_g, n_steps=steps)
    masks_g = np.asarray(res_g.history["abits"][:, burn::stride]).ravel()
    _occupancy_checks(masks_g, states, pi, cuts, "general", chi2_tol=2.0)

    # compat oracle (single sequential chain => looser gates)
    rng = np.random.default_rng(5)
    signed = {lab: 1 - 2 * int(plan[i]) for i, lab in enumerate(g.labels)}
    part = compat.Partition(g, signed, {
        "population": compat.Tally("population"),
        "cut_edges": compat.cut_edges,
        "b_nodes": compat.b_nodes_bi,
        "base": lambda p: base,
        "step_num": compat.step_num,
    })
    popbound = compat.within_percent_of_ideal_population(part, CHI_EPS)
    chain = compat.MarkovChain(
        compat.make_reversible_propose_bi(rng),
        compat.Validator([compat.single_flip_contiguous, popbound]),
        compat.make_cut_accept(rng), part, 8000)
    masks_c = []
    for t, p in enumerate(chain):
        if t >= 1000 and t % 5 == 0:
            a = p.assignment_array
            masks_c.append(int(((a == -1).astype(np.uint32)
                                << np.arange(g.n_nodes)).sum()))
    _occupancy_checks(np.array(masks_c), states, pi, cuts, "oracle",
                      tv_tol=0.15, cut_tol=0.05)


@pytest.mark.slow
def test_lowered_bits_matches_exact_stationary_chi2():
    """The exact-enumeration bar rerun on the PACKED lowered body
    (ISSUE 8 satellite): the 3x4 miniature widened to 3x5 so b2_disp is
    unambiguous and dispatch takes the lowered_bits rung. Same gates —
    chi-square occupancy over thinned samples plus TV/E[cut] against
    the power-iterated stationary distribution. (Bit-identity against
    the int8 body is tests/test_bitboard_lowered.py; this proves the
    packed body is ALSO exactly right in distribution on its own.)"""
    base = 1.5
    g = fce.graphs.square_grid(3, 5, remove_nodes=[(0, 0)],
                               extra_edges=[((0, 1), (1, 0))])
    nbrmask = _nbr_bitmasks(g)
    states = _enumerate_states(g, nbrmask)
    P, cuts = _build_transition(states, g, base)
    pi = _stationary(P)

    spec = fce.Spec(contiguity="patch", record_assignment_bits=True,
                    geom_waits=False, parity_metrics=False)
    assert lower.kernel_path_for(g, spec) == "lowered_bits"
    plan = fce.graphs.stripes_plan(g, 2)
    chains, steps, burn, stride = 48, 12000, 2000, 25

    bg, st, params = fce.sampling.init_board(
        g, plan, n_chains=chains, seed=13, spec=spec, base=base,
        pop_tol=CHI_EPS)
    assert kb.body_for(bg, spec) == "lowered_bits"
    res_b = fce.sampling.run_board(bg, spec, params, st, n_steps=steps)
    rank = np.cumsum(np.asarray(bg.node_mask)) - 1
    rank_of_node = rank[np.asarray(bg.cell_of_node)]
    abits = np.asarray(res_b.history["abits"][:, burn::stride])
    per_node = (abits[..., None] >> rank_of_node) & 1
    masks_b = (per_node << np.arange(g.n_nodes)).sum(axis=-1).ravel()
    _occupancy_checks(masks_b, states, pi, cuts, "lowered_bits",
                      chi2_tol=2.0)
