"""Reference-scale replication check (SURVEY.md section 4.4): run one
100,000-step FRANK config end-to-end through the sweep driver and compare
the wait.txt scalar against the reference's shipped ground truth
(plots/FRANK/*wait.txt; tables in BASELINE.md / REPLICATION.md).

The B30 cells are the tight regime: the reference's 12 cells all fall in
[8.255e7, 8.451e7] (2.4% spread), so a single run is a sharp test. The
asserted band is that spread widened by ~2% on each side for single-run
sampling noise.
"""

import os

import numpy as np
import pytest

from flipcomplexityempirical_tpu import experiments as ex

# full-scale replication cells: slow tier as a module
pytestmark = pytest.mark.slow


def test_frank_b30_full_scale_wait_sum(tmp_path):
    cfg = ex.ExperimentConfig(family="frank", alignment=2, base=0.3,
                              pop_tol=0.5, total_steps=100_000, n_chains=2)
    out = str(tmp_path / "rep")
    data = ex.run_config(cfg, out)
    wait = float(open(os.path.join(out, cfg.tag + "wait.txt")).read())
    assert 8.0e7 < wait < 8.7e7, wait
    # every chain in the batch lands in the same band
    assert np.all(data["waits_all"] > 8.0e7)
    assert np.all(data["waits_all"] < 8.7e7)
    # yields accounted exactly: 100k cut-count records per chain
    assert data["history"]["cut_count"].shape == (2, 100_000)


@pytest.mark.parametrize("family", ["sec11", "frank"])
def test_multiseed_slow_base_consistent_with_reference_spread(family):
    """The committed 15-seed records for the slow bases (sec11 B263 = mu,
    B695 = mu^2, B1000; frank B333 — the bimodal regime) must remain
    statistically exchangeable with the reference's own per-base
    wait.txt spread (two-sample KS on the chain-0 seeds — VERDICT r4:
    replace 'inside the spread' with a quantitative statement).
    Regenerate with `python replication/multiseed.py run [--family ...]`
    after kernel changes."""
    import importlib.util
    import pathlib

    path = (pathlib.Path(__file__).resolve().parent.parent
            / "replication" / "multiseed.py")
    mspec = importlib.util.spec_from_file_location("multiseed", path)
    mod = importlib.util.module_from_spec(mspec)
    mspec.loader.exec_module(mod)
    fam = mod.FAMILIES[family]
    if not os.path.exists(fam["record"]):
        pytest.skip("multiseed record not generated yet")
    if not os.path.isdir(fam["ref_dir"]):
        pytest.skip("reference corpus unavailable")
    res = mod.analyze(fam["record"], family=family)
    assert set(res) == set(fam["cells"])
    for name, cell in res.items():
        assert cell["ref_cells"] == fam["ref_cells"], (
            name, cell["ref_cells"])
        # the gate itself lives in multiseed.cell_consistent so the CLI
        # verdict and this test can never drift apart
        assert mod.cell_consistent(cell, fam["gates"].get(name)), (
            name, cell)
