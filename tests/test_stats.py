"""stats/ diagnostics: calibrated against processes with known answers
(AR(1) autocorrelation time, two-state metastable conductance, hand-computed
partisan tallies, exact square-district geometry), plus an integration pass
over real kernel histories."""

import numpy as np
import pytest

import flipcomplexityempirical_tpu as fce
from flipcomplexityempirical_tpu import stats


def ar1(rng, c, t, rho):
    x = np.zeros((c, t))
    eps = rng.standard_normal((c, t))
    for i in range(1, t):
        x[:, i] = rho * x[:, i - 1] + np.sqrt(1 - rho ** 2) * eps[:, i]
    return x


def test_autocorrelation_ar1(rng):
    rho = 0.8
    x = ar1(rng, 4, 20000, rho)
    acf = stats.autocorrelation(x, max_lag=20).mean(axis=0)
    lags = np.arange(21)
    assert np.allclose(acf, rho ** lags, atol=0.05)
    assert acf[0] == 1.0


def test_tau_and_ess_ar1(rng):
    rho = 0.9  # tau = (1+rho)/(1-rho) = 19
    x = ar1(rng, 8, 50000, rho)
    tau = stats.integrated_autocorr_time(x)
    assert np.allclose(tau.mean(), 19.0, rtol=0.2)
    per, total = stats.ess(x)
    assert np.allclose(per.mean(), 50000 / 19.0, rtol=0.25)
    assert np.allclose(total, (50000 / tau).sum(), rtol=1e-6)


def test_iid_is_white(rng):
    x = rng.standard_normal((4, 8000))
    assert stats.integrated_autocorr_time(x).mean() < 1.5
    assert abs(stats.gelman_rubin(x) - 1.0) < 0.02
    assert stats.autocorr_mixing_time(x) == 1.0


def test_gelman_rubin_flags_divergence(rng):
    x = rng.standard_normal((4, 1000))
    x += np.arange(4)[:, None] * 5.0  # chains stuck in different modes
    assert stats.gelman_rubin(x) > 1.5


def test_frozen_observable_degenerate():
    x = np.ones((3, 100))
    assert np.all(stats.integrated_autocorr_time(x) >= 1.0)
    assert stats.gelman_rubin(x) == 1.0
    phi, r = stats.bottleneck_ratio(x)
    assert np.isnan(phi)


def test_bottleneck_two_state_metastable(rng):
    # Two wells {0, 1} with P(switch) = p: the only nontrivial level set has
    # Q(S, S^c) = pi(S) * p, so Phi = p exactly.
    p = 0.02
    t, c = 40000, 4
    switches = rng.random((c, t)) < p
    x = (np.cumsum(switches, axis=1) % 2).astype(float)
    phi, r = stats.bottleneck_ratio(x)
    assert np.isclose(phi, p, rtol=0.3)
    assert r == 0.0


def test_conductance_profile_shape(rng):
    x = rng.integers(0, 5, size=(2, 5000)).astype(float)
    thr, phi = stats.conductance_profile(x)
    assert thr.shape == phi.shape
    assert np.isnan(phi[-1])  # full-space level set has no complement


def test_partisan_hand_example():
    # 2 districts: d0 = 60/40, d1 = 30/70 => shares (.6, .3)
    tallies = np.array([[[60.0, 40.0], [30.0, 70.0]]])
    assert np.allclose(stats.mean_median(tallies), 0.45 - 0.45)  # K=2: 0
    assert stats.seats_won(tallies)[0] == 1
    # wasted: d0 w0=60-50=10, w1=40; d1 w0=30, w1=70-50=20
    # eg = ((40+20) - (10+30)) / 200 = 0.1
    assert np.allclose(stats.efficiency_gap(tallies), 0.1)


def test_partisan_tallies_batched(rng):
    n, c, k = 50, 3, 2
    votes = rng.random((n, 2))
    a = rng.integers(0, k, size=(c, n))
    tal = stats.district_vote_tallies(a, votes, k)
    for ci in range(c):
        for d in range(k):
            assert np.allclose(tal[ci, d], votes[a[ci] == d].sum(axis=0))


def test_compactness_square_district():
    # 4x4 grid split into two 2x4 halves: with unit cells each district is a
    # 2x4 rectangle (area 8, perimeter 12) => PP = 4*pi*8/144
    g = fce.graphs.square_grid(4, 4)
    a = np.array([0 if x < 2 else 1 for (x, y) in g.labels], np.int8)
    sp = np.ones(g.n_edges)  # unit shared edge lengths
    area = np.ones(g.n_nodes)
    # exterior perimeter: each node's sides not shared with any neighbor
    deg = np.zeros(g.n_nodes)
    for e in g.edges:
        deg[e[0]] += 1
        deg[e[1]] += 1
    ext = 4.0 - deg
    pp = stats.polsby_popper(a, 2, edges=g.edges, shared_perim=sp,
                             node_area=area, node_exterior_perim=ext)
    assert pp.shape == (1, 2)
    assert np.allclose(pp, 4 * np.pi * 8 / 144)
    assert stats.cut_edge_count(a, g.edges)[0] == 4


def test_kernel_history_integration():
    g = fce.graphs.square_grid(8, 8)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec()
    dg, st, params = fce.init_batch(g, plan, n_chains=8, seed=3, spec=spec,
                                    base=1.0, pop_tol=0.5)
    res = fce.run_chains(dg, spec, params, st, n_steps=2000)
    cuts = res.history["cut_count"].astype(float)
    tau = stats.integrated_autocorr_time(cuts)
    assert np.all(tau >= 1.0) and np.all(np.isfinite(tau))
    per, total = stats.ess(cuts)
    assert total > 8  # mixes at least somewhat
    phi, r = stats.bottleneck_ratio(cuts)
    assert 0 < phi <= 1.0
    assert stats.gelman_rubin(cuts) < 1.5


def test_seed_votes_reference_semantics():
    """grid_chain_sec11.py:223-228: Bernoulli(1/2), exactly one of
    pink/purple per node, deterministic under the seed."""
    g = fce.graphs.square_grid(10, 10)
    v1 = fce.graphs.seed_votes(g, seed=4)
    v2 = fce.graphs.seed_votes(g, seed=4)
    np.testing.assert_array_equal(v1, v2)
    assert v1.shape == (100, 2)
    assert (v1.sum(axis=1) == 1).all()
    assert 20 < v1[:, 0].sum() < 80  # p=1/2, not degenerate
    assert (fce.graphs.seed_votes(g, seed=5) != v1).any()


def test_election_updater_through_chain_matches_batched_stats(rng):
    """The compat Election updater (incremental) agrees with the batched
    stats.partisan scoring on every yielded plan — the vote subsystem is
    reachable end-to-end from a chain."""
    from flipcomplexityempirical_tpu import compat

    g = fce.graphs.square_grid(6, 6)
    votes = fce.graphs.seed_votes(g, seed=7)
    plan = fce.graphs.stripes_plan(g, 2)
    nprng = np.random.default_rng(0)
    elect = compat.Election(
        "Pink-Purple", {"Pink": "pink", "Purple": "purple"},
        columns={"pink": votes[:, 0], "purple": votes[:, 1]})
    updaters = {"population": compat.Tally("population"),
                "cut_edges": compat.cut_edges,
                "b_nodes": compat.b_nodes_bi,
                "base": lambda p: 1.0,
                "Pink-Purple": elect}
    part = compat.Partition(g, {lab: int(plan[i])
                                for i, lab in enumerate(g.labels)}, updaters)
    popbound = compat.within_percent_of_ideal_population(part, 0.5)
    chain = compat.MarkovChain(
        compat.make_reversible_propose_bi(nprng),
        compat.Validator([compat.single_flip_contiguous, popbound]),
        compat.make_cut_accept(nprng), part, 120)

    assigns, mms, egs, wins = [], [], [], []
    for p in chain:
        r = p["Pink-Purple"]
        # incremental tallies == recompute from scratch
        fresh = compat.Election(
            "X", {"Pink": "pink", "Purple": "purple"},
            columns={"pink": votes[:, 0], "purple": votes[:, 1]})(
                compat.Partition(g, p.assignment_array.copy(),
                                 {}))
        np.testing.assert_array_equal(r.tallies, fresh.tallies)
        assigns.append(p.assignment_array.copy())
        mms.append(compat.mean_median(r))
        egs.append(compat.efficiency_gap(r))
        wins.append(r.wins("Pink"))

    tallies = stats.district_vote_tallies(np.stack(assigns), votes, k=2)
    np.testing.assert_allclose(stats.mean_median(tallies), mms)
    np.testing.assert_allclose(stats.efficiency_gap(tallies), egs)
    np.testing.assert_array_equal(stats.seats_won(tallies), wins)


def test_driver_emits_partisan_summary(tmp_path):
    from flipcomplexityempirical_tpu import experiments as ex

    cfg = ex.ExperimentConfig(family="frank", alignment=0, base=0.3,
                              pop_tol=0.5, total_steps=120, n_chains=3)
    data = ex.run_config(cfg, str(tmp_path / "p"))
    ps = data["partisan"]
    assert ps["mean_median"].shape == (3,)
    assert ps["efficiency_gap"].shape == (3,)
    assert set(np.asarray(ps["seats_pink"]).tolist()) <= {0, 1, 2}


def test_election_with_signed_labels():
    """The reference loop assigns districts +1/-1, not 0/1
    (grid_chain_sec11.py:194-214): Election must tally those correctly
    rather than aliasing label -1 onto a row index."""
    from flipcomplexityempirical_tpu import compat

    g = fce.graphs.square_grid(4, 4)
    votes = fce.graphs.seed_votes(g, seed=3)
    signed = np.where(np.arange(16) < 8, 1, -1)
    el = compat.Election(
        "PP", {"Pink": "pink", "Purple": "purple"},
        columns={"pink": votes[:, 0], "purple": votes[:, 1]})
    r = el(compat.Partition(g, signed, {}))
    assert r.districts == (-1, 1)
    np.testing.assert_array_equal(
        r.tallies[0], votes[8:].sum(axis=0))
    np.testing.assert_array_equal(
        r.tallies[1], votes[:8].sum(axis=0))
    # incremental path preserves the label->row map
    part = compat.Partition(g, signed, {"PP": el})
    part["PP"]
    child = part.flip({g.labels[0]: -1})
    r2 = el(child)
    fresh = compat.Election(
        "F", {"Pink": "pink", "Purple": "purple"},
        columns={"pink": votes[:, 0], "purple": votes[:, 1]})(
            compat.Partition(g, child.assignment_array.copy(), {}))
    np.testing.assert_array_equal(r2.tallies, fresh.tallies)


def _ar1(rng, c, t, phi):
    e = rng.standard_normal((c, t))
    x = np.zeros((c, t))
    for i in range(1, t):
        x[:, i] = phi * x[:, i - 1] + e[:, i]
    return x * 30 + 700          # cut-count-like scale/offset


@pytest.mark.parametrize("phi", [0.0, 0.7, 0.95])
def test_ess_device_matches_host(rng, phi):
    """stats.ess_device (f32, on-device FFT + masked Sokal window) agrees
    with the host f64 estimator to <1% on bench-scale trajectories —
    the tolerance bench.py's ess_host_check field monitors on silicon."""
    x = _ar1(rng, 32, 1500, phi)
    per_h, tot_h = stats.ess(x)
    per_d, tot_d = stats.ess_device(x)
    assert abs(float(tot_d) - tot_h) / tot_h < 0.01
    np.testing.assert_allclose(np.asarray(per_d), per_h, rtol=0.02)


def test_run_board_history_device_identity():
    """history_device=True returns the SAME history as the host path —
    device arrays instead of numpy, values identical."""
    import jax
    g = fce.graphs.square_grid(8, 8)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(contiguity="patch")
    runs = {}
    for dev in (False, True):
        bg, st, params = fce.sampling.init_board(
            g, plan, n_chains=4, seed=0, spec=spec, base=1.3, pop_tol=0.4)
        res = fce.sampling.run_board(bg, spec, params, st, n_steps=101,
                                     chunk=25, history_device=dev)
        runs[dev] = res.history
    assert all(isinstance(v, jax.Array) for v in runs[True].values())
    for k in runs[False]:
        np.testing.assert_array_equal(np.asarray(runs[True][k]),
                                      runs[False][k])


def test_run_chains_history_device_identity():
    """The general runner's history_device=True returns the SAME history
    as the host path (device arrays, values identical), across chunk
    boundaries, the initial record, and record_every thinning — the
    device-diagnostics input for the graphs the big sweeps run on
    (sec11/frank/dual are not board-eligible)."""
    import jax
    g = fce.graphs.square_grid(6, 6)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(contiguity="patch")
    for every in (1, 5):
        runs = {}
        for dev in (False, True):
            dg, st, params = fce.init_batch(
                g, plan, n_chains=4, seed=0, spec=spec, base=1.3,
                pop_tol=0.4)
            res = fce.run_chains(dg, spec, params, st, n_steps=101,
                                 chunk=25, record_every=every,
                                 history_device=dev)
            runs[dev] = res.history
        assert all(isinstance(v, jax.Array) for v in runs[True].values())
        assert set(runs[True]) == set(runs[False])
        for k in runs[False]:
            np.testing.assert_array_equal(np.asarray(runs[True][k]),
                                          runs[False][k])


def test_bottleneck_device_matches_host():
    """conductance_profile_device / bottleneck_ratio_device agree with the
    host f64 estimators on shared explicit thresholds: the counts are
    exact integer arithmetic on both sides, so only the final f32 divide
    differs. Covers a metastable two-well walk, a frozen observable
    (NaN contract), and 1-D input promotion."""
    import jax.numpy as jnp
    rng = np.random.default_rng(7)
    # two-well walk: values cluster near 10 and 30 with rare crossings
    c, t = 6, 400
    wells = rng.integers(0, 2, size=(c, 1)) * 20 + 10
    x = wells + rng.integers(-3, 4, size=(c, t))
    flips = rng.random((c, t)) < 0.01
    x = np.where(np.cumsum(flips, axis=1) % 2 == 1, 40 - x, x).astype(
        np.float64)
    thr = np.arange(x.min(), x.max() + 1, dtype=np.float64)

    th_h, phi_h = stats.conductance_profile(x, thr)
    th_d, phi_d = stats.conductance_profile_device(jnp.asarray(x), thr)
    np.testing.assert_array_equal(np.asarray(th_d), th_h)
    np.testing.assert_array_equal(np.isnan(np.asarray(phi_d)),
                                  np.isnan(phi_h))
    m = ~np.isnan(phi_h)
    np.testing.assert_allclose(np.asarray(phi_d)[m], phi_h[m], rtol=1e-5)

    ph_h, r_h = stats.bottleneck_ratio(x, thr)
    ph_d, r_d = stats.bottleneck_ratio_device(jnp.asarray(x), thr)
    assert float(r_d) == r_h
    np.testing.assert_allclose(float(ph_d), ph_h, rtol=1e-5)

    # frozen observable: every level set one-sided -> (nan, nan)
    frozen = np.full((3, 50), 7.0)
    ph_d, r_d = stats.bottleneck_ratio_device(jnp.asarray(frozen),
                                              np.array([7.0]))
    assert np.isnan(float(ph_d)) and np.isnan(float(r_d))

    # 1-D promotion matches host
    ph_h, r_h = stats.bottleneck_ratio(x[0], thr)
    ph_d, r_d = stats.bottleneck_ratio_device(jnp.asarray(x[0]), thr)
    np.testing.assert_allclose(float(ph_d), ph_h, rtol=1e-5)
    assert float(r_d) == r_h


def test_bottleneck_device_unsorted_thresholds():
    """An unsorted threshold grid must match the host estimator, which
    sorts unconditionally — searchsorted on an unsorted grid silently
    bins wrong (ADVICE r5), so the device twin now sorts at trace time."""
    import jax.numpy as jnp
    rng = np.random.default_rng(13)
    x = rng.integers(0, 12, size=(5, 300)).astype(np.float64)
    thr = np.arange(x.min(), x.max() + 1, dtype=np.float64)
    shuffled = rng.permutation(thr)
    assert not np.all(shuffled[:-1] <= shuffled[1:])  # genuinely unsorted

    th_h, phi_h = stats.conductance_profile(x, shuffled)
    th_d, phi_d = stats.conductance_profile_device(jnp.asarray(x), shuffled)
    np.testing.assert_array_equal(np.asarray(th_d), th_h)
    np.testing.assert_array_equal(np.isnan(np.asarray(phi_d)),
                                  np.isnan(phi_h))
    m = ~np.isnan(phi_h)
    np.testing.assert_allclose(np.asarray(phi_d)[m], phi_h[m], rtol=1e-5)


def test_bottleneck_device_rejects_single_yield():
    """T=1 raises at trace time (host parity), rather than returning the
    frozen-observable (nan, nan) verdict for a mis-sliced history."""
    import jax.numpy as jnp
    with pytest.raises(ValueError, match="T >= 2"):
        stats.conductance_profile_device(jnp.zeros((3, 1)),
                                         np.array([0.0]))


def test_gelman_rubin_device_matches_host():
    """Split R-hat device twin: f32 parity with the host f64 estimator
    plus both frozen contracts (agreeing constants -> 1.0, disagreeing
    constants -> inf)."""
    import jax.numpy as jnp
    rng = np.random.default_rng(11)
    # metastable: chains offset by wells -> R-hat far from 1
    x = (rng.integers(0, 2, size=(6, 1)) * 20
         + rng.normal(0, 2, size=(6, 200))).astype(np.float64)
    np.testing.assert_allclose(
        float(stats.gelman_rubin_device(jnp.asarray(x))),
        stats.gelman_rubin(x), rtol=1e-5)
    # well-mixed: close to 1 on both
    y = rng.normal(0, 1, size=(6, 500))
    np.testing.assert_allclose(
        float(stats.gelman_rubin_device(jnp.asarray(y))),
        stats.gelman_rubin(y), rtol=1e-5)
    frozen_agree = np.full((4, 50), 3.0)
    assert float(stats.gelman_rubin_device(jnp.asarray(frozen_agree))) == 1.0
    # f32-inexact constant: the fused-variance residue must not bypass
    # the frozen contract through a tiny nonzero w
    frozen_tenth = np.full((4, 50), 0.1)
    assert float(stats.gelman_rubin_device(jnp.asarray(frozen_tenth))) == 1.0
    frozen_disagree = np.repeat([[1.0], [2.0]], 50, axis=1)
    assert np.isinf(float(stats.gelman_rubin_device(
        jnp.asarray(frozen_disagree))))
    with pytest.raises(ValueError, match="T >= 4"):
        stats.gelman_rubin_device(jnp.zeros((2, 3)))


def test_gelman_rubin_device_large_offset():
    """A genuinely mixing observable sitting at a large offset (std
    ~0.02% of magnitude) must match the host, not trip the frozen floor:
    R-hat is shift-invariant, so the device twin centers on the grand
    mean BEFORE halving and judges frozenness against the centered
    variance (ADVICE r5 — the old raw-scale 1e-6 floor swallowed this
    case). The large-offset frozen contracts must survive the tighter
    floor too."""
    import jax.numpy as jnp
    rng = np.random.default_rng(17)
    x = (4000.0 + rng.normal(0, 1, size=(6, 400))).astype(np.float64)
    r_d = float(stats.gelman_rubin_device(jnp.asarray(x)))
    assert np.isfinite(r_d)
    np.testing.assert_allclose(r_d, stats.gelman_rubin(x), rtol=1e-3)
    # frozen contracts at the same offset scale
    assert float(stats.gelman_rubin_device(
        jnp.full((4, 50), 4000.0))) == 1.0
    assert np.isinf(float(stats.gelman_rubin_device(
        jnp.asarray(np.repeat([[4000.0], [4001.0]], 50, axis=1)))))


def test_integer_thresholds_grid():
    """The shared threshold builder spans the observed range inclusively
    on integer bounds (concrete values, jit-shapeable length)."""
    import jax.numpy as jnp
    thr = stats.integer_thresholds(jnp.asarray([[2.0, 5.0], [3.0, 4.0]]))
    np.testing.assert_array_equal(np.asarray(thr), [2.0, 3.0, 4.0, 5.0])
