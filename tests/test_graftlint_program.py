"""Whole-program stage tests: thread-entry seeding, lock-dominance
through the call graph, guarded-by pragmas, the durability resolver,
fault-plan scanning in shell files, and the result cache / --jobs
dispatch (tier-1, host-only: pure stdlib ast)."""

import json
import os
import subprocess
import sys

from tools.graftlint import LintConfig
from tools.graftlint.engine import run_lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "graftlint")


def _lint_tree(tmp_path, files, rules):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    cfg = LintConfig(root=str(tmp_path), rules=frozenset(rules),
                     cache=False)
    return run_lint([str(tmp_path)], cfg)


# ---- G011: thread-entry seeding ---------------------------------------

THREAD_SUBCLASS = """\
import threading


class Worker(threading.Thread):
    def __init__(self):
        super().__init__(daemon=True)
        self.count = 0

    def run(self):
        self.count += 1

    def bump(self):
        self.count += 1


def main():
    w = Worker()
    w.start()
    w.bump()
"""


def test_thread_subclass_run_is_an_entry(tmp_path):
    findings = _lint_tree(tmp_path, {"mod.py": THREAD_SUBCLASS},
                          {"G011"})
    assert len(findings) == 2, [f.render() for f in findings]
    assert all("Worker.count" in f.message for f in findings)
    assert any("thread:Worker" in f.message for f in findings)


def test_handler_root_alone_counts_as_concurrent(tmp_path):
    # one do_* method is enough: ThreadingHTTPServer runs it on many
    # threads at once (weight 2), while a never-called method on a
    # plain class stays single-threaded (weight 1)
    src = ("class Handler:\n"
           "    def do_GET(self):\n"
           "        self.hits = self.hits + 1\n"
           "\n\n"
           "class Single:\n"
           "    def poke(self):\n"
           "        self.n = 1\n")
    findings = _lint_tree(tmp_path, {"mod.py": src}, {"G011"})
    assert [f for f in findings if "Handler.hits" in f.message]
    assert not [f for f in findings if "Single.n" in f.message]


def test_signal_handler_is_an_entry(tmp_path):
    src = ("import signal\n\n\n"
           "class App:\n"
           "    def __init__(self):\n"
           "        self.stopping = False\n"
           "        signal.signal(signal.SIGTERM, self._on_term)\n"
           "\n"
           "    def _on_term(self, signum, frame):\n"
           "        self.stopping = True\n"
           "\n"
           "    def poll(self):\n"
           "        return self.stopping\n"
           "\n\n"
           "def main():\n"
           "    a = App()\n"
           "    return a.poll()\n")
    findings = _lint_tree(tmp_path, {"mod.py": src}, {"G011"})
    assert len(findings) == 1, [f.render() for f in findings]
    assert "App.stopping" in findings[0].message


# ---- G011: lock dominance through the call graph ----------------------

LOCKED_HELPER = """\
import threading


class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = []
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def _append(self, x):
        self.items.append(x)

    def _loop(self):
        while True:
            with self._lock:
                self._append(1)

    def add(self, x):
        with self._lock:
            self._append(x)


def main():
    b = Box()
    b.add(2)
"""


def test_lock_inherited_through_helper_is_clean(tmp_path):
    # _append never takes the lock lexically; every resolved caller
    # holds it, so the mutation is dominated
    findings = _lint_tree(tmp_path, {"mod.py": LOCKED_HELPER}, {"G011"})
    assert findings == [], [f.render() for f in findings]


def test_one_unlocked_caller_breaks_dominance(tmp_path):
    src = LOCKED_HELPER + (
        "\n\n"
        "def sneak(b):\n"
        "    b.sneaky(3)\n")
    src = src.replace(
        "    def add(self, x):",
        "    def sneaky(self, x):\n"
        "        self._append(x)\n"
        "\n"
        "    def add(self, x):")
    findings = _lint_tree(tmp_path, {"mod.py": src}, {"G011"})
    assert len(findings) == 1, [f.render() for f in findings]
    assert "Box.items" in findings[0].message


def test_init_only_helpers_are_construction_time(tmp_path):
    # _recover mutates without the lock but is reachable only from
    # __init__: no other thread holds the object yet
    src = LOCKED_HELPER.replace(
        "        self._t = threading.Thread",
        "        self._recover()\n"
        "        self._t = threading.Thread").replace(
        "    def _append(self, x):",
        "    def _recover(self):\n"
        "        self.items.append(0)\n"
        "\n"
        "    def _append(self, x):")
    findings = _lint_tree(tmp_path, {"mod.py": src}, {"G011"})
    assert findings == [], [f.render() for f in findings]


# ---- G011: guarded-by pragmas -----------------------------------------

def test_guarded_by_on_store_line_suppresses(tmp_path):
    src = THREAD_SUBCLASS.replace(
        "    def run(self):\n"
        "        self.count += 1\n",
        "    def run(self):\n"
        "        self.count += 1"
        "  # graftlint: guarded-by(none: approximate counter)\n")
    src = src.replace(
        "    def bump(self):\n"
        "        self.count += 1\n",
        "    def bump(self):\n"
        "        # graftlint: guarded-by(none: approximate counter)\n"
        "        self.count += 1\n")
    findings = _lint_tree(tmp_path, {"mod.py": src}, {"G011"})
    assert findings == [], [f.render() for f in findings]


def test_guarded_by_on_class_line_exempts_all_attrs(tmp_path):
    src = ("# graftlint: guarded-by(none: single-thread by construction)\n"
           + THREAD_SUBCLASS.replace("import threading\n\n\n", ""))
    src = "import threading\n\n\n" + src
    findings = _lint_tree(tmp_path, {"mod.py": src}, {"G011"})
    assert findings == [], [f.render() for f in findings]


def test_guarded_by_does_not_leak_to_other_attrs(tmp_path):
    # pragma on one attribute's definition must not blanket the class
    src = THREAD_SUBCLASS.replace(
        "        self.count = 0\n",
        "        self.count = 0\n"
        "        self.other = []"
        "  # graftlint: guarded-by(none: write-once)\n").replace(
        "    def run(self):\n"
        "        self.count += 1\n",
        "    def run(self):\n"
        "        self.count += 1\n"
        "        self.other.append(1)\n")
    findings = _lint_tree(tmp_path, {"mod.py": src}, {"G011"})
    assert findings and all("Worker.count" in f.message
                            for f in findings), \
        [f.render() for f in findings]


# ---- G013: shell plan scanning ----------------------------------------

REGISTRY = ('FAULT_SITES = {\n'
            '    "worker.sigkill": "x",\n'
            '    "http.accept": "y",\n'
            '}\n')


def test_shell_fault_plans_are_checked(tmp_path):
    files = {
        "resilience/faults.py": REGISTRY,
        "tools/gate.sh": (
            "#!/usr/bin/env bash\n"
            "python -m svc --faults worker.sigkil:once@3\n"
            "GRAFT_FAULTS=http.accep:always python -m svc\n"
            "python -m svc --faults \"$PLAN\"\n"),
    }
    findings = _lint_tree(tmp_path, files, {"G013"})
    msgs = sorted(f.message for f in findings)
    assert len(findings) == 2, [f.render() for f in findings]
    assert all(f.path == "tools/gate.sh" for f in findings)
    assert "did you mean 'worker.sigkill'?" in msgs[1]
    assert "did you mean 'http.accept'?" in msgs[0]


def test_shell_pragma_suppresses_g013(tmp_path):
    files = {
        "resilience/faults.py": REGISTRY,
        "tools/gate.sh": (
            "#!/usr/bin/env bash\n"
            "python -m svc --faults bogus.site:once"
            "  # graftlint: disable=G013(negative-path probe)\n"),
    }
    findings = _lint_tree(tmp_path, files, {"G013"})
    assert findings == [], [f.render() for f in findings]


def test_g013_inert_without_registry(tmp_path):
    files = {"tools/gate.sh": "run --faults anything.goes:once\n"}
    findings = _lint_tree(tmp_path, files, {"G013"})
    assert findings == []


# ---- cache + --jobs ---------------------------------------------------

def _cli(args, cwd=REPO):
    return subprocess.run([sys.executable, "-m", "tools.graftlint",
                           *args], cwd=cwd, capture_output=True,
                          text=True)


def _seed_pkg(tmp_path):
    """A small lintable tree with one real finding (G001 in kernel/)."""
    import shutil
    pkg = tmp_path / "pkg"
    (pkg / "kernel").mkdir(parents=True)
    (pkg / "obs").mkdir()
    shutil.copy(os.path.join(REPO, "flipcomplexityempirical_tpu",
                             "obs", "events.py"),
                pkg / "obs" / "events.py")
    shutil.copy(os.path.join(FIXTURES, "g001_bad.py"),
                pkg / "kernel" / "hot.py")
    return pkg


def test_cache_written_hit_and_invalidated(tmp_path):
    pkg = _seed_pkg(tmp_path)
    cache = tmp_path / ".graftlint_cache.json"

    first = _cli(["--root", str(tmp_path), "--format", "json", str(pkg)])
    assert cache.exists()
    doc = json.loads(cache.read_text())
    assert doc["v"] == 2 and doc["files"]

    second = _cli(["--root", str(tmp_path), "--format", "json",
                   str(pkg)])
    assert (json.loads(first.stdout)["counts"]
            == json.loads(second.stdout)["counts"])

    # edit a file: its entry (and the program stage) must re-lint
    hot = pkg / "kernel" / "hot.py"
    hot.write_text("def clean(x):\n    return x\n")
    third = _cli(["--root", str(tmp_path), str(pkg)])
    assert third.returncode == 0, third.stdout + third.stderr


def test_no_cache_flag_leaves_no_file(tmp_path):
    pkg = _seed_pkg(tmp_path)
    _cli(["--root", str(tmp_path), "--no-cache", str(pkg)])
    assert not (tmp_path / ".graftlint_cache.json").exists()


def test_jobs_dispatch_matches_serial(tmp_path):
    pkg = _seed_pkg(tmp_path)
    serial = _cli(["--root", str(tmp_path), "--no-cache",
                   "--format", "json", str(pkg)])
    para = _cli(["--root", str(tmp_path), "--no-cache", "--jobs", "2",
                 "--format", "json", str(pkg)])
    a, b = json.loads(serial.stdout), json.loads(para.stdout)
    assert a["counts"] == b["counts"]
    assert a["new"] == b["new"]
    assert serial.returncode == para.returncode == 1
