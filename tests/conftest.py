# Force CPU with 8 virtual devices BEFORE any computation: sharding tests
# exercise multi-chip code paths without TPU hardware (SURVEY.md section 4.5).
#
# Note: this environment's sitecustomize registers the TPU PJRT plugin at
# interpreter startup and pins JAX_PLATFORMS, so plain env vars are not
# enough — the jax config must be updated before backend initialization.
import os
import re

_flags = os.environ.get("XLA_FLAGS", "")
_m = re.search(r"--xla_force_host_platform_device_count=(\d+)", _flags)
if _m is None:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
elif int(_m.group(1)) < 8:
    # an inherited flag with a smaller count would quietly drop the
    # sharding suites to fewer devices than they assert on
    os.environ["XLA_FLAGS"] = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "--xla_force_host_platform_device_count=8", _flags)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="also run tests marked slow (the full matrix: the 100k-step "
             "replication cell, 8-device ladder suites, exhaustive "
             "enumerations). The default selection is the fast tier.")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test, skipped unless --runslow")


# The slow tier is declared AT DEFINITION SITE with @pytest.mark.slow
# (VERDICT r4: a name-substring table here silently mis-tiered renamed or
# new slow tests). Criterion for marking a test slow: >= ~9 s on the
# 1-core build box (full-scale replication, exhaustive enumerations, long
# bit-identity matrices, 8-device suites, heavyweight end-to-end cells).
# The default selection is the fast iteration tier; CI-style runs pass
# --runslow for the full matrix. pytest_terminal_summary below polices the
# boundary: any unmarked test that runs long is flagged at the end of a
# fast-tier run, so the tier cannot silently drift (the wall-clock load on
# this box varies 2-3x, hence a loud report rather than a hard failure).

FAST_TIER_PER_TEST_BUDGET_S = 12.0


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    skip = pytest.mark.skip(reason="slow tier: pass --runslow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    if config.getoption("--runslow"):
        return
    over = [
        (rep.duration, rep.nodeid)
        for rep in terminalreporter.stats.get("passed", ())
        if rep.when == "call" and rep.duration > FAST_TIER_PER_TEST_BUDGET_S
    ]
    if over:
        terminalreporter.section("fast-tier budget")
        for dur, nodeid in sorted(over, reverse=True):
            terminalreporter.write_line(
                f"{dur:6.1f}s  {nodeid}  — exceeds the "
                f"{FAST_TIER_PER_TEST_BUDGET_S:.0f}s fast-tier budget; "
                "mark it @pytest.mark.slow")


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)


@pytest.fixture(scope="session")
def mesh8():
    """8-device chains mesh on the forced-host CPU backend. Skips (rather
    than fails) when the backend didn't come up with 8 devices — e.g. a
    run on real silicon with fewer chips — so the multi-chip suites stay
    tier-1 on any box via the XLA_FLAGS forcing above."""
    if jax.default_backend() != "cpu" or len(jax.devices()) < 8:
        pytest.skip("needs 8 forced-host CPU devices")
    from flipcomplexityempirical_tpu import distribute
    return distribute.make_mesh(8)


def assert_grid_districts_connected(boards, k):
    """Every district of every (C, H, W) board is nonempty and
    rook-connected (scipy 4-connectivity labeling)."""
    from scipy.ndimage import label as cc_label

    for c in range(boards.shape[0]):
        for d in range(k):
            member = boards[c] == d
            assert member.any(), f"chain {c} district {d} vanished"
            _, ncomp = cc_label(member)
            assert ncomp == 1, f"chain {c} district {d}: {ncomp} components"
