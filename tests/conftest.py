# Force CPU with 8 virtual devices BEFORE any computation: sharding tests
# exercise multi-chip code paths without TPU hardware (SURVEY.md section 4.5).
#
# Note: this environment's sitecustomize registers the TPU PJRT plugin at
# interpreter startup and pins JAX_PLATFORMS, so plain env vars are not
# enough — the jax config must be updated before backend initialization.
import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="also run tests marked slow (the full matrix: the 100k-step "
             "replication cell, 8-device ladder suites, exhaustive "
             "enumerations). The default selection is the fast tier.")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test, skipped unless --runslow")


# The slow tier, maintained here in one place from pytest --durations runs
# (everything >= ~9 s on the 1-core build box): full-scale replication,
# exhaustive enumerations, long bit-identity matrices, 8-device suites,
# heavyweight end-to-end cells. The default selection (< ~3 min) is for
# iteration; CI-style runs pass --runslow for the full matrix.
SLOW_TEST_SUBSTRINGS = (
    "test_replication.py",
    "test_pair_walk_matches_exact_stationary",
    "test_pair_walk_k2_equals_bi_walk",
    "test_kernel_matches_exact_stationary",
    "test_board_path_matches_exact_stationary",
    "test_corrected_accept_matches_reversible_target",
    "test_bit_identity_vs_int8_body",
    "test_pair_bit_identity_vs_int8_body",
    "test_mid_config_resume_is_bit_identical",
    "test_run_config_artifacts_and_resume",
    "test_checkpoint_mismatch_and_stale_formats_ignored",
    "test_checkpoint_roundtrip",
    "test_apply_flip_log_chunked_composition",
    "test_board_chunking_is_invisible",
    "test_record_every_is_a_stride",
    "test_board_matches_general_path",
    "test_board_invariants",
    "test_tree_retries_recover_tight_epsilon",
    "test_simulator_matches_xla_board_distribution",
    "test_pair_board_matches_general_path",
    "test_sharded_run_bit_identical",
    "test_board_sharded_run_bit_identical",
    "test_temper_family_end_to_end",
    "test_kpair_family_end_to_end",
    "test_single_rung_matches_plain_runner",
    "test_base1_deterministic_swaps_and_rung_reconstruction",
    "test_pair_kernel_matches_oracle_distributions",
    "test_kernel_matches_oracle_distributions",
    "test_invariants_pair_k8",
    "test_anneal_linear_beta_ramps_to_max",
    "test_select_flat_picks_mth_valid",
)


def pytest_collection_modifyitems(config, items):
    for item in items:
        if any(s in item.nodeid for s in SLOW_TEST_SUBSTRINGS):
            item.add_marker(pytest.mark.slow)
    if config.getoption("--runslow"):
        return
    skip = pytest.mark.skip(reason="slow tier: pass --runslow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)


def assert_grid_districts_connected(boards, k):
    """Every district of every (C, H, W) board is nonempty and
    rook-connected (scipy 4-connectivity labeling)."""
    from scipy.ndimage import label as cc_label

    for c in range(boards.shape[0]):
        for d in range(k):
            member = boards[c] == d
            assert member.any(), f"chain {c} district {d} vanished"
            _, ncomp = cc_label(member)
            assert ncomp == 1, f"chain {c} district {d}: {ncomp} components"
