# Force CPU with 8 virtual devices BEFORE any computation: sharding tests
# exercise multi-chip code paths without TPU hardware (SURVEY.md section 4.5).
#
# Note: this environment's sitecustomize registers the TPU PJRT plugin at
# interpreter startup and pins JAX_PLATFORMS, so plain env vars are not
# enough — the jax config must be updated before backend initialization.
import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)


def assert_grid_districts_connected(boards, k):
    """Every district of every (C, H, W) board is nonempty and
    rook-connected (scipy 4-connectivity labeling)."""
    from scipy.ndimage import label as cc_label

    for c in range(boards.shape[0]):
        for d in range(k):
            member = boards[c] == d
            assert member.any(), f"chain {c} district {d} vanished"
            _, ncomp = cc_label(member)
            assert ncomp == 1, f"chain {c} district {d}: {ncomp} components"
