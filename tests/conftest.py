# Force CPU with 8 virtual devices BEFORE jax initializes: sharding tests
# exercise multi-chip code paths without TPU hardware (SURVEY.md section 4.5).
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
