"""End-to-end graftlint gate (tier-1, `not slow`): the real package must
lint clean against the committed baseline, and the gate must actually
bite when a violation is introduced. Mirrors the ROADMAP verify flow —
this is the test that makes the contracts in PROFILE.md enforceable."""

import json
import os
import shutil
import subprocess
import sys

from tools.graftlint import LintConfig
from tools.graftlint.engine import run_lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "graftlint")
PKG = os.path.join(REPO, "flipcomplexityempirical_tpu")


def _cli(args, cwd=REPO):
    return subprocess.run([sys.executable, "-m", "tools.graftlint", *args],
                          cwd=cwd, capture_output=True, text=True)


def test_repo_lints_clean():
    """The acceptance-criteria invocation: zero non-baselined findings
    over the shipped package + tools."""
    res = _cli(["flipcomplexityempirical_tpu", "tools"])
    assert res.returncode == 0, res.stdout + res.stderr


def test_committed_baseline_is_empty():
    """Violations are fixed or pragma'd, never grandfathered: the
    committed baseline must stay empty (obs_report --check prints this
    count so drift is visible)."""
    with open(os.path.join(REPO, "graftlint_baseline.json"),
              encoding="utf-8") as f:
        doc = json.load(f)
    assert doc["findings"] == []


def test_gate_bites_on_injected_violation(tmp_path):
    """Copy the package skeleton, inject one single-rule fixture
    violation into kernel/, and the same invocation must exit nonzero."""
    pkg = tmp_path / "flipcomplexityempirical_tpu"
    (pkg / "kernel").mkdir(parents=True)
    obs_dir = pkg / "obs"
    obs_dir.mkdir()
    shutil.copy(os.path.join(REPO, "flipcomplexityempirical_tpu", "obs",
                             "events.py"), obs_dir / "events.py")
    shutil.copy(os.path.join(FIXTURES, "g001_bad.py"),
                pkg / "kernel" / "hot.py")
    res = _cli(["--root", str(tmp_path), str(pkg)])
    assert res.returncode == 1, res.stdout + res.stderr
    assert "G001" in res.stdout


def test_obs_report_check_surfaces_baseline_count(tmp_path):
    stream = tmp_path / "events.jsonl"
    stream.write_text('{"v": 1, "ts": 1.0, "event": "error", '
                      '"message": "x"}\n')
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obs_report.py"),
         "--check", str(stream)],
        cwd=REPO, capture_output=True, text=True)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "graftlint baseline: 0 grandfathered" in res.stdout


# ---- seeded-defect proofs (the acceptance criteria) -------------------
#
# Each test copies real shipped sources, re-introduces one historical
# defect class, and asserts the matching program rule trips — while
# test_repo_lints_clean above pins the unmutated tree to zero findings.

def _lint_copy(tmp_path, files, rule, mutate=None):
    """Copy repo files into tmp (dest-relative paths), optionally
    mutate one, and run the single program rule over the copy."""
    for src, dst in files.items():
        d = tmp_path / dst
        d.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(os.path.join(REPO, src), d)
    if mutate is not None:
        dst, old, new = mutate
        p = tmp_path / dst
        text = p.read_text()
        assert old in text, f"mutation anchor missing from {dst}"
        p.write_text(text.replace(old, new, 1))
    cfg = LintConfig(root=str(tmp_path), rules=frozenset({rule}),
                     cache=False)
    return run_lint([str(tmp_path)], cfg)


def test_deleting_a_lock_trips_g011(tmp_path):
    files = {"flipcomplexityempirical_tpu/service/server.py":
             "svc/server.py",
             "flipcomplexityempirical_tpu/service/journal.py":
             "svc/journal.py"}
    clean = _lint_copy(tmp_path, files, "G011")
    assert clean == [], [f.render() for f in clean]
    seeded = _lint_copy(tmp_path, files, "G011",
                        mutate=("svc/server.py",
                                "with self._buckets_lock:", "if True:"))
    assert len(seeded) == 1, [f.render() for f in seeded]
    assert "FrontDoor._buckets" in seeded[0].message


def test_bare_durable_write_trips_g012(tmp_path):
    files = {"flipcomplexityempirical_tpu/service/worker.py":
             "svc/worker.py"}
    clean = _lint_copy(tmp_path, files, "G012")
    assert clean == [], [f.render() for f in clean]
    atomic = ('    tmp = f"{path}.tmp.{os.getpid()}"\n'
              '    with open(tmp, "w", encoding="utf-8") as f:\n'
              '        json.dump(doc, f, sort_keys=True)\n'
              '        f.flush()\n'
              '        os.fsync(f.fileno())\n'
              '    os.replace(tmp, path)\n')
    bare = ('    with open(path, "w", encoding="utf-8") as f:\n'
            '        json.dump(doc, f, sort_keys=True)\n')
    seeded = _lint_copy(tmp_path, files, "G012",
                        mutate=("svc/worker.py", atomic, bare))
    assert seeded, "bare overwrite of durable docs went unflagged"
    assert all(f.rule == "G012" for f in seeded)
    roots = "\n".join(f.message for f in seeded)
    assert "_write_json_atomic" in roots


def test_misspelled_fault_site_in_gate_script_trips_g013(tmp_path):
    files = {"flipcomplexityempirical_tpu/resilience/faults.py":
             "resilience/faults.py",
             "tools/fleet_check.sh": "tools/fleet_check.sh"}
    clean = _lint_copy(tmp_path, files, "G013")
    assert clean == [], [f.render() for f in clean]
    seeded = _lint_copy(tmp_path, files, "G013",
                        mutate=("tools/fleet_check.sh",
                                "worker.sigkill:once",
                                "worker.sigkil:once"))
    assert len(seeded) == 1, [f.render() for f in seeded]
    assert "worker.sigkil" in seeded[0].message
    assert "did you mean 'worker.sigkill'?" in seeded[0].message
