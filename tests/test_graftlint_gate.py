"""End-to-end graftlint gate (tier-1, `not slow`): the real package must
lint clean against the committed baseline, and the gate must actually
bite when a violation is introduced. Mirrors the ROADMAP verify flow —
this is the test that makes the contracts in PROFILE.md enforceable."""

import json
import os
import shutil
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "graftlint")


def _cli(args, cwd=REPO):
    return subprocess.run([sys.executable, "-m", "tools.graftlint", *args],
                          cwd=cwd, capture_output=True, text=True)


def test_repo_lints_clean():
    """The acceptance-criteria invocation: zero non-baselined findings
    over the shipped package + tools."""
    res = _cli(["flipcomplexityempirical_tpu", "tools"])
    assert res.returncode == 0, res.stdout + res.stderr


def test_committed_baseline_is_empty():
    """Violations are fixed or pragma'd, never grandfathered: the
    committed baseline must stay empty (obs_report --check prints this
    count so drift is visible)."""
    with open(os.path.join(REPO, "graftlint_baseline.json"),
              encoding="utf-8") as f:
        doc = json.load(f)
    assert doc["findings"] == []


def test_gate_bites_on_injected_violation(tmp_path):
    """Copy the package skeleton, inject one single-rule fixture
    violation into kernel/, and the same invocation must exit nonzero."""
    pkg = tmp_path / "flipcomplexityempirical_tpu"
    (pkg / "kernel").mkdir(parents=True)
    obs_dir = pkg / "obs"
    obs_dir.mkdir()
    shutil.copy(os.path.join(REPO, "flipcomplexityempirical_tpu", "obs",
                             "events.py"), obs_dir / "events.py")
    shutil.copy(os.path.join(FIXTURES, "g001_bad.py"),
                pkg / "kernel" / "hot.py")
    res = _cli(["--root", str(tmp_path), str(pkg)])
    assert res.returncode == 1, res.stdout + res.stderr
    assert "G001" in res.stdout


def test_obs_report_check_surfaces_baseline_count(tmp_path):
    stream = tmp_path / "events.jsonl"
    stream.write_text('{"v": 1, "ts": 1.0, "event": "error", '
                      '"message": "x"}\n')
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obs_report.py"),
         "--check", str(stream)],
        cwd=REPO, capture_output=True, text=True)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "graftlint baseline: 0 grandfathered" in res.stdout
